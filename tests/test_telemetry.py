"""Telemetry registry unit suite (round 11, libs/telemetry.py):
counter/gauge/histogram semantics, label cardinality bound, concurrent
increments, legacy flat-dict rendering, and Prometheus 0.0.4 format
validation (the golden-format contract GET /metrics serves)."""

from __future__ import annotations

import math
import re
import threading

import pytest

from tendermint_tpu.libs import telemetry
from tendermint_tpu.libs.telemetry import (
    Registry,
    log_buckets,
)


@pytest.fixture()
def reg():
    return Registry()


# -- instruments ---------------------------------------------------------------


class TestCounter:
    def test_inc_and_value(self, reg):
        c = reg.counter("c_total", "help")
        c.inc()
        c.inc(5)
        assert c.value == 6

    def test_negative_inc_rejected(self, reg):
        c = reg.counter("c_total")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_create_or_get_same_instance(self, reg):
        assert reg.counter("c_total") is reg.counter("c_total")

    def test_type_conflict_fails_loudly(self, reg):
        reg.counter("x")
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x")

    def test_concurrent_increments_lose_nothing(self, reg):
        c = reg.counter("c_total")
        n_threads, n_incs = 8, 2000

        def work():
            for _ in range(n_incs):
                c.inc()

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == n_threads * n_incs


class TestGauge:
    def test_set_inc_dec(self, reg):
        g = reg.gauge("g")
        g.set(10)
        g.inc(2.5)
        g.dec()
        assert g.value == 11.5

    def test_callback_gauge(self, reg):
        box = {"v": 3}
        g = reg.gauge("g_fn", fn=lambda: box["v"])
        assert g.value == 3
        box["v"] = 7
        assert g.value == 7

    def test_callback_gauge_cannot_be_labeled(self, reg):
        with pytest.raises(ValueError):
            reg.gauge("g_bad", labelnames=("a",), fn=lambda: 1)


class TestHistogram:
    def test_log_buckets_shape(self):
        b = log_buckets(0.001, 1.0, 1)
        assert b == (0.001, 0.01, 0.1, 1.0)
        b4 = log_buckets(1e-4, 30.0, 4)
        assert b4[0] == 1e-4 and b4[-1] >= 30.0
        assert list(b4) == sorted(b4)

    def test_bad_bucket_spec_rejected(self):
        with pytest.raises(ValueError):
            log_buckets(0, 1, 4)
        with pytest.raises(ValueError):
            log_buckets(1, 1, 4)

    def test_observe_lands_in_bucket(self, reg):
        h = reg.histogram("h_seconds", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        child = h._own()
        assert child.counts == [1, 2, 1, 1]  # last = +Inf bucket
        assert child.count == 5
        assert child.sum == pytest.approx(56.05)

    def test_boundary_value_counts_in_its_le_bucket(self, reg):
        # Prometheus le is INCLUSIVE: observe(0.1) must count under
        # le="0.1"
        h = reg.histogram("h_edge", buckets=(0.1, 1.0))
        h.observe(0.1)
        assert h._own().counts == [1, 0, 0]

    def test_quantile_approximation(self, reg):
        h = reg.histogram("h_q", buckets=(1.0, 2.0, 4.0, 8.0))
        for v in [0.5] * 50 + [3.0] * 49 + [7.0]:
            h.observe(v)
        assert h.quantile(0.5) == 1.0
        assert h.quantile(0.99) == 4.0

    def test_env_tunable_default_buckets(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_TELEMETRY_HIST_MIN_S", "0.01")
        monkeypatch.setenv("TENDERMINT_TELEMETRY_HIST_MAX_S", "1.0")
        monkeypatch.setenv("TENDERMINT_TELEMETRY_HIST_PER_DECADE", "1")
        assert telemetry.default_latency_buckets() == (0.01, 0.1, 1.0)
        # a typo'd knob warns and keeps the default (envknob contract)
        monkeypatch.setenv("TENDERMINT_TELEMETRY_HIST_MIN_S", "oops")
        b = telemetry.default_latency_buckets()
        assert b[0] == 1e-4

    def test_disable_knob_makes_observe_noop(self, reg):
        h = reg.histogram("h_off", buckets=(1.0,))
        c = reg.counter("c_off")
        telemetry.set_enabled(False)
        try:
            h.observe(0.5)
            c.inc()
            # API validation must not depend on the runtime knob: a
            # caller bug crashes identically either way
            with pytest.raises(ValueError):
                c.inc(-1)
        finally:
            telemetry.set_enabled(True)
        assert h.count == 0 and c.value == 0
        h.observe(0.5)
        assert h.count == 1


class TestLabels:
    def test_labeled_series_are_independent(self, reg):
        c = reg.counter("ops_total", labelnames=("op",))
        c.labels(op="verify").inc(3)
        c.labels(op="hash").inc(1)
        assert c.labels(op="verify").value == 3
        assert c.labels(op="hash").value == 1

    def test_wrong_label_names_fail_loudly(self, reg):
        c = reg.counter("ops_total", labelnames=("op",))
        with pytest.raises(KeyError):
            c.labels(kind="verify")
        with pytest.raises(KeyError):
            c.inc()  # labeled family has no unlabeled series

    def test_cardinality_bound_collapses_to_overflow(self, reg):
        c = reg.counter("wide_total", labelnames=("k",), max_series=4)
        for i in range(10):
            c.labels(k=f"v{i}").inc()
        assert c.series_count() <= 5  # 4 + the shared overflow series
        assert c.dropped_series == 6
        # totals survive the collapse
        total = sum(child.value for _k, child in c._items())
        assert total == 10
        assert c.labels(k=telemetry.OVERFLOW_LABEL).value == 6

    def test_cardinality_bound_holds_for_labeled_histograms(self, reg):
        """Round 15: the _other collapse must bound labeled HISTOGRAMS
        too — a per-peer latency histogram under 100-peer churn stays at
        the series cap, every observation lands somewhere, and the
        overflow child is a real histogram (buckets, sum, count)."""
        h = reg.histogram("peer_lat_seconds", labelnames=("peer",),
                          buckets=(0.1, 1.0), max_series=8)
        for i in range(100):  # 100-peer churn
            h.labels(peer=f"peer{i}").observe(0.5)
        assert h.series_count() <= 9  # 8 + the shared overflow series
        assert h.dropped_series == 100 - 8
        total = sum(child.count for _k, child in h._items())
        assert total == 100, "observations must survive the collapse"
        over = h.labels(peer=telemetry.OVERFLOW_LABEL)
        assert over.count == 92
        counts, total_sum, count = over.snapshot()
        assert counts[1] == 92 and count == 92  # 0.5 -> le=1.0 bucket
        assert total_sum == pytest.approx(92 * 0.5)
        # ... and the rendered exposition stays parseable and bounded
        text = reg.render_prometheus()
        bucket_lines = [l for l in text.splitlines()
                        if l.startswith("peer_lat_seconds_bucket")]
        assert len(bucket_lines) == h.series_count() * 3  # 2 bounds + +Inf

    def test_remove_labels_drops_series_and_frees_slot(self, reg):
        """Round 15: staleness cleanup — a removed child vanishes from
        the exposition and its slot counts against the cardinality
        bound again (churned-out peers must disappear, not freeze)."""
        g = reg.gauge("peer_age", labelnames=("peer",), max_series=2)
        g.labels(peer="a").set(1)
        g.labels(peer="b").set(2)
        g.labels(peer="c").set(3)  # over the bound -> _other
        assert g.labels(peer="c") is g.labels(peer=telemetry.OVERFLOW_LABEL)
        g.remove_labels(peer="a")
        assert 'peer="a"' not in reg.render_prometheus()
        # freed slots admit a new real series instead of overflowing
        # (the retained _other series occupies one slot itself)
        g.remove_labels(peer="b")
        g.labels(peer="d").set(4)
        assert g.labels(peer="d") is not g.labels(
            peer=telemetry.OVERFLOW_LABEL
        )
        g.remove_labels(peer="missing")  # no-op
        with pytest.raises(KeyError):
            g.remove_labels(wrong="a")

    def test_per_family_max_series_env_override(self, monkeypatch):
        """TENDERMINT_TELEMETRY_MAX_SERIES_<FAMILY> (round 15) overrides
        the global bound for one family; a typo'd value keeps the
        default (envknob contract)."""
        monkeypatch.setenv("TENDERMINT_TELEMETRY_MAX_SERIES", "16")
        monkeypatch.setenv(
            "TENDERMINT_TELEMETRY_MAX_SERIES_NARROW_TOTAL", "2"
        )
        reg = Registry()
        narrow = reg.counter("narrow_total", labelnames=("k",))
        wide = reg.counter("other_total", labelnames=("k",))
        for i in range(10):
            narrow.labels(k=f"v{i}").inc()
            wide.labels(k=f"v{i}").inc()
        assert narrow.series_count() <= 3  # 2 + overflow
        assert wide.series_count() == 10   # global 16 still governs
        assert telemetry.family_max_series("narrow_total") == 2
        monkeypatch.setenv(
            "TENDERMINT_TELEMETRY_MAX_SERIES_NARROW_TOTAL", "oops"
        )
        assert telemetry.family_max_series("narrow_total") == 16


# -- registry rendering --------------------------------------------------------


PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"              # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\""    # first label
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})?"
    r" [0-9.eE+-]+$|^.* \+Inf$"
)


class TestRegistry:
    def _sample_registry(self):
        reg = Registry()
        reg.counter("reqs_total", "requests").inc(3)
        g = reg.gauge("depth", "queue depth")
        g.set(2)
        h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0),
                          labelnames=("op",))
        h.labels(op="a").observe(0.05)
        h.labels(op="a").observe(0.5)
        reg.register_producer("plane", lambda: {"x": 1, "y": 2.5})
        reg.register_producer("scrapeonly", lambda: {"z": 9}, legacy=False)
        return reg

    def test_flatten_is_legacy_producers_only(self):
        reg = self._sample_registry()
        flat = reg.flatten()
        assert flat == {"plane_x": 1, "plane_y": 2.5}

    def test_producer_replacement_and_unregister(self, reg):
        reg.register_producer("p", lambda: {"a": 1})
        reg.register_producer("p", lambda: {"b": 2})
        assert reg.flatten() == {"p_b": 2}
        reg.unregister_producer("p")
        assert reg.flatten() == {}

    def test_failing_producer_fails_loudly(self, reg):
        """The PR-4 loud-wiring convention: a renamed attribute (any
        producer exception) surfaces as an RPC error / a 500 scrape —
        never a silently missing plane behind a healthy-looking 200."""
        def boom():
            raise AttributeError("renamed_field")

        reg.register_producer("bad", boom)
        with pytest.raises(AttributeError, match="renamed_field"):
            reg.flatten()
        with pytest.raises(AttributeError, match="renamed_field"):
            reg.render_prometheus()

    def test_failing_callback_gauge_fails_loudly(self, reg):
        reg.gauge("g_bad", fn=lambda: (_ for _ in ()).throw(
            AttributeError("renamed")
        ))
        with pytest.raises(AttributeError):
            reg.render_prometheus()

    def test_prometheus_format_golden(self):
        """A sample scrape parses: HELP/TYPE per family, every sample
        line matches the 0.0.4 grammar, histogram series are cumulative
        and agree with _count."""
        text = self._sample_registry().render_prometheus()
        lines = text.strip().splitlines()
        assert text.endswith("\n")
        fams = {}
        for line in lines:
            if line.startswith("# HELP "):
                continue
            if line.startswith("# TYPE "):
                _, _, name, kind = line.split()
                fams[name] = kind
                continue
            assert PROM_SAMPLE.match(line), line
        assert fams["reqs_total"] == "counter"
        assert fams["depth"] == "gauge"
        assert fams["lat_seconds"] == "histogram"
        assert fams["plane_x"] == "gauge"
        assert fams["scrapeonly_z"] == "gauge"  # scrape-only still scrapes
        # every family has a HELP line preceding its TYPE line
        for name in fams:
            assert any(l.startswith(f"# HELP {name} ") for l in lines), name
        # histogram contract: cumulative buckets, +Inf == count
        buckets = [l for l in lines if l.startswith("lat_seconds_bucket")]
        counts = [float(l.rsplit(" ", 1)[1]) for l in buckets]
        assert counts == sorted(counts)
        inf = next(l for l in buckets if 'le="+Inf"' in l)
        cnt = next(l for l in lines if l.startswith("lat_seconds_count"))
        assert inf.rsplit(" ", 1)[1] == cnt.rsplit(" ", 1)[1] == "2"
        sm = next(l for l in lines if l.startswith("lat_seconds_sum"))
        assert math.isclose(float(sm.rsplit(" ", 1)[1]), 0.55)

    def test_parent_chain_renders_but_does_not_flatten(self):
        parent = Registry()
        parent.counter("proc_total").inc(1)
        parent.register_producer("procplane", lambda: {"v": 7})
        child = Registry(parent=parent)
        child.register_producer("nodeplane", lambda: {"w": 8})
        assert child.flatten() == {"nodeplane_w": 8}
        names = {f.name for f in child.collect()}
        assert {"proc_total", "procplane_v", "nodeplane_w"} <= names

    def test_name_dedup_first_wins(self):
        parent = Registry()
        parent.gauge("dup", fn=lambda: 1)
        child = Registry(parent=parent)
        child.gauge("dup", fn=lambda: 2)
        fams = [f for f in child.collect() if f.name == "dup"]
        assert len(fams) == 1
        assert fams[0].samples[0][2] == 2  # child's own wins

    def test_default_registry_reset_reruns_install_hooks(self):
        calls = []
        telemetry.on_default_registry(
            lambda r: calls.append(r) or r.register_producer(
                "hooked", lambda: {"v": 1}, legacy=False
            )
        )
        assert calls[-1] is telemetry.default_registry()
        fresh = telemetry.reset_default_registry()
        try:
            assert calls[-1] is fresh
            names = {f.name for f in fresh.collect()}
            assert "hooked_v" in names
            # module hooks re-registered too (ops/faults imports in this
            # process via other tests; tolerate either)
        finally:
            telemetry.reset_default_registry()

    def test_sanitize_bad_metric_chars(self):
        reg = Registry()
        reg.register_producer("weird", lambda: {"a-b.c": 1})
        text = reg.render_prometheus()
        assert "weird_a_b_c 1" in text

    def test_on_collect_hook_refreshes_before_instruments_render(self):
        """Round 15: a pre-collect hook runs before instruments are
        gathered, so a point-in-time labeled gauge (per-peer last-recv
        age) is fresh in the SAME scrape — not one scrape stale."""
        reg = Registry()
        g = reg.gauge("age_seconds", labelnames=("peer",))
        box = {"v": 1.0}
        reg.on_collect(lambda: g.labels(peer="a").set(box["v"]))
        assert 'age_seconds{peer="a"} 1.0' in reg.render_prometheus()
        box["v"] = 2.5
        assert 'age_seconds{peer="a"} 2.5' in reg.render_prometheus()


class TestTraceRecorder:
    """consensus/trace.py: the segment clock partitions wall time."""

    def test_segments_partition_wall_clock(self):
        from tendermint_tpu.consensus.trace import TraceRecorder

        rec = TraceRecorder(device_probe=None, ring=4)
        rec.begin(5, now=100.0)
        rec.mark("propose", now=100.5)
        rec.mark("prevote", now=100.75)
        rec.mark("commit", now=101.0)
        rec.note("part_hash_s", 0.2)
        tr = rec.finish(5, wall_s=1.5, now=101.5)
        assert tr.segments == {
            "new_height": 0.5, "propose": 0.25, "prevote": 0.25,
            "commit": 0.5,
        }
        assert tr.total_s == pytest.approx(1.5)
        assert tr.wall_s == 1.5
        assert tr.aux == {"part_hash_s": 0.2}
        assert rec.last(1)[0] is tr

    def test_ring_bound_and_order(self):
        from tendermint_tpu.consensus.trace import TraceRecorder

        rec = TraceRecorder(ring=3)
        for h in range(6):
            rec.begin(h, now=float(h))
            rec.finish(h, wall_s=1.0, now=float(h) + 1)
        got = [t.height for t in rec.last(10)]
        assert got == [5, 4, 3]  # newest first, ring-bounded

    def test_device_probe_deltas_and_state(self):
        from tendermint_tpu.consensus.trace import TraceRecorder

        probes = iter([
            {"verify_cpu_sigs": 3, "breaker_state": 0},   # constructor
            {"verify_cpu_sigs": 10, "breaker_state": 0},  # begin()
            {"verify_cpu_sigs": 17, "breaker_state": 2},  # finish()
        ])
        rec = TraceRecorder(device_probe=lambda: next(probes), ring=2)
        rec.begin(1, now=0.0)
        tr = rec.finish(1, wall_s=1.0, now=1.0)
        assert tr.device["verify_cpu_sigs"] == 7
        assert tr.device["breaker_state_start"] == 0
        assert tr.device["breaker_state_end"] == 2

    def test_failing_probe_never_raises(self):
        from tendermint_tpu.consensus.trace import TraceRecorder

        def boom():
            raise RuntimeError("probe died")

        rec = TraceRecorder(device_probe=boom, ring=2)
        rec.begin(1, now=0.0)
        tr = rec.finish(1, wall_s=1.0, now=1.0)
        assert tr.device == {}
