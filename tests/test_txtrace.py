"""Tx-lifecycle tracing tests (round 17, libs/txtrace.py + the
tx_trace RPC + ops/txtrace cross-node join).

Contracts under test: the sampling knobs (first-K-per-height + 1-in-N),
keep-first stamp semantics, span TELESCOPING (stamped spans through
block_commit sum exactly to the commit latency), the bounded
active/ring tables (eviction seals, never drops silently), the kill
switch, the per-stage histograms, the mempool stamp sites, the
cross-node join, and the consensus vote-duplicate counters that ride
this round."""

from __future__ import annotations

import threading
import time

import pytest

from tendermint_tpu.libs import telemetry
from tendermint_tpu.libs.txtrace import STAGES, TxTraceRecorder, txtrace_hists


def _tx(i: int) -> bytes:
    return b"txtrace-%04d=v" % i


class TestSampling:
    def test_first_k_per_height_plus_one_in_n(self):
        rec = TxTraceRecorder(first_k=2, sample_n=10)
        decisions = [rec.maybe_trace(_tx(i)) for i in range(25)]
        # first 2 sampled (the K window), then the countdown samples
        # every 10th submission after the burst
        assert decisions[0] and decisions[1]
        assert decisions[2:11] == [False] * 9
        assert decisions[11] is True  # the 1-in-10 countdown fired
        assert decisions[12:21] == [False] * 9
        assert decisions[21] is True
        assert rec.sampled == sum(decisions)

    def test_commit_resets_the_first_k_window(self):
        rec = TxTraceRecorder(first_k=1, sample_n=0)
        assert rec.maybe_trace(_tx(0))
        assert not rec.maybe_trace(_tx(1))
        rec.commit([_tx(0)], height=5)
        assert rec.maybe_trace(_tx(2)), "commit must re-arm first-K"

    def test_sample_n_zero_disables_the_modulo_arm(self):
        rec = TxTraceRecorder(first_k=0, sample_n=0)
        assert not any(rec.maybe_trace(_tx(i)) for i in range(50))
        assert rec.stats()["active"] == 0

    def test_kill_switch(self):
        rec = TxTraceRecorder(first_k=8, sample_n=1)
        rec.set_enabled(False)
        assert not rec.maybe_trace(_tx(0))
        rec.stamp(_tx(0), "mempool_admit")
        assert rec.stats() == {
            "sampled": 0, "completed": 0, "rejected": 0, "evicted": 0,
            "active": 0,
        }


class TestSpans:
    def test_spans_telescope_to_the_end_to_end_latencies(self):
        """The acceptance-bar arithmetic: stamped spans through
        block_commit sum EXACTLY to the commit latency (a bench asserts
        within 10% against the live node to guard the stamp sites)."""
        rec = TxTraceRecorder(first_k=1, sample_n=0)
        t0 = 1000.0
        assert rec.maybe_trace(_tx(0), at=t0)
        rec.stamp(_tx(0), "sig_gate", at=t0 + 0.010)
        rec.stamp(_tx(0), "mempool_admit", at=t0 + 0.015)
        rec.stamp(_tx(0), "p2p_broadcast", at=t0 + 0.020)
        rec.stamp_present([_tx(0)], "proposal", at=t0 + 0.100)
        rec.commit([_tx(0)], height=7, at=t0 + 0.200)
        rec.stamp_present([_tx(0)], "apply", at=t0 + 0.250)
        rec.delivered([_tx(0)], at=t0 + 0.260)

        [t] = rec.last(5)
        assert t["outcome"] == "committed" and t["height"] == 7
        assert t["commit_latency_s"] == pytest.approx(0.200)
        assert t["visible_latency_s"] == pytest.approx(0.260)
        commit_spans = sum(
            t["spans"][s] for s in STAGES
            if s in t["spans"] and STAGES.index(s) <= STAGES.index(
                "block_commit")
        )
        assert commit_spans == pytest.approx(t["commit_latency_s"], rel=1e-9)
        assert sum(t["spans"].values()) == pytest.approx(
            t["visible_latency_s"], rel=1e-9
        )
        # stage order in the record follows the canonical order
        stamped = [s for s in STAGES if s in t["stages"]]
        instants = [t["stages"][s] for s in stamped]
        assert instants == sorted(instants)

    def test_stamps_are_keep_first(self):
        rec = TxTraceRecorder(first_k=1, sample_n=0)
        rec.maybe_trace(_tx(0), at=10.0)
        rec.stamp(_tx(0), "proposal", at=11.0)
        rec.stamp(_tx(0), "proposal", at=99.0)  # re-proposed round
        rec.commit([_tx(0)], height=1, at=12.0)
        rec.delivered([_tx(0)], at=13.0)
        assert rec.last(1)[0]["stages"]["proposal"] == 11.0

    def test_untraced_stamps_are_no_ops(self):
        rec = TxTraceRecorder(first_k=1, sample_n=0)
        rec.stamp(_tx(5), "mempool_admit")      # nothing in flight
        rec.maybe_trace(_tx(0))
        rec.stamp(_tx(5), "mempool_admit")      # in flight, wrong tx
        assert rec.stats()["active"] == 1
        assert rec.last(5) == []


class TestBounds:
    def test_active_bound_evicts_oldest_as_sealed(self):
        rec = TxTraceRecorder(first_k=100, sample_n=0, max_active=3)
        for i in range(5):
            assert rec.maybe_trace(_tx(i))
        assert rec.stats()["active"] == 3
        assert rec.evicted == 2
        evicted = [t for t in rec.last(10) if t["outcome"] == "evicted"]
        assert {t["hash"] for t in evicted} == {
            rec._ring[0].hash.hex().upper(), rec._ring[1].hash.hex().upper()
        }

    def test_ring_keeps_newest(self):
        rec = TxTraceRecorder(first_k=100, sample_n=0, ring=4)
        for i in range(8):
            rec.maybe_trace(_tx(i), at=float(i))
            rec.commit([_tx(i)], height=i + 1, at=float(i) + 0.5)
            rec.delivered([_tx(i)], at=float(i) + 0.6)
        got = rec.last(10)
        assert len(got) == 4
        assert [t["height"] for t in got] == [8, 7, 6, 5]  # newest first

    def test_reject_seals_with_outcome(self):
        rec = TxTraceRecorder(first_k=1, sample_n=0)
        rec.maybe_trace(_tx(0))
        rec.reject(_tx(0), "bad_sig")
        assert rec.stats()["active"] == 0 and rec.rejected == 1
        assert rec.last(1)[0]["outcome"] == "bad_sig"


class TestMetrics:
    def test_seal_feeds_the_histograms(self):
        reg = telemetry.Registry()
        rec = TxTraceRecorder(first_k=1, sample_n=0)
        rec.metrics_registry = reg
        rec.maybe_trace(_tx(0), at=0.0)
        rec.stamp(_tx(0), "mempool_admit", at=0.010)
        rec.commit([_tx(0)], height=1, at=0.050)
        rec.delivered([_tx(0)], at=0.060)
        hists = txtrace_hists(reg)
        child = hists["stage"].labels(stage="mempool_admit")
        assert child.count == 1
        assert child.sum == pytest.approx(0.010)
        assert hists["commit"].count == 1
        assert hists["commit"].sum == pytest.approx(0.050)
        assert hists["visible"].sum == pytest.approx(0.060)

    def test_concurrent_stamps_never_corrupt(self):
        rec = TxTraceRecorder(first_k=1000, sample_n=0, max_active=1000)
        txs = [_tx(i) for i in range(64)]
        for t in txs:
            rec.maybe_trace(t)

        def worker(stage):
            for t in txs:
                rec.stamp(t, stage)

        threads = [
            threading.Thread(target=worker, args=(s,))
            for s in ("sig_gate", "mempool_admit", "p2p_broadcast")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        rec.commit(txs, height=1)
        rec.delivered(txs)
        assert rec.completed == 64
        for tr in rec.last(64):
            assert set(tr["stages"]) >= {
                "rpc_ingress", "sig_gate", "mempool_admit", "p2p_broadcast",
                "block_commit", "event_delivery",
            }


class TestMempoolIntegration:
    def _mempool(self):
        from tendermint_tpu.abci.apps.kvstore import KVStoreApp
        from tendermint_tpu.abci.client import LocalClient
        from tendermint_tpu.config import test_config
        from tendermint_tpu.mempool import Mempool
        from tendermint_tpu.proxy.app_conn import AppConnMempool

        mp = Mempool(
            test_config().mempool,
            AppConnMempool(LocalClient(KVStoreApp(), threading.RLock())),
        )
        mp.txtrace = TxTraceRecorder(first_k=4, sample_n=0)
        return mp

    def test_check_tx_stamps_ingress_and_admit(self):
        mp = self._mempool()
        tx = b"k1=v1"
        mp.check_tx(tx)
        deadline = time.monotonic() + 10
        while mp.size() < 1 and time.monotonic() < deadline:
            mp.flush_app_conn()
            time.sleep(0.005)
        assert mp.size() == 1
        [active] = mp.txtrace.active()
        assert active["source"] == "rpc"
        assert "rpc_ingress" in active["stages"]
        assert "mempool_admit" in active["stages"]

    def test_peer_source_tags_the_trace(self):
        mp = self._mempool()
        mp.check_tx(b"k2=v2", source="peer")
        [active] = mp.txtrace.active()
        assert active["source"] == "peer"


class TestRPCAndCLI:
    def _snapshot(self):
        """Two fabricated node scrapes: the tx was submitted on A
        (source=rpc), gossiped to B (source=peer) which proposed and
        committed it; a second tx sits parked on A."""
        h = "AB" * 10
        parked = "CD" * 10
        t0 = 1000.0
        return {
            "a:46657": {
                "traces": [{
                    "hash": h, "source": "rpc", "height": 9,
                    "outcome": "committed",
                    "stages": {"rpc_ingress": t0, "mempool_admit": t0 + 0.01,
                               "p2p_broadcast": t0 + 0.02,
                               "block_commit": t0 + 0.30,
                               "event_delivery": t0 + 0.31},
                    "spans": {}, "commit_latency_s": 0.30,
                    "visible_latency_s": 0.31, "completed_at": t0 + 0.31,
                }],
                "active": [{
                    "hash": parked, "source": "rpc", "height": 0,
                    "outcome": None,
                    "stages": {"rpc_ingress": t0 + 5.0,
                               "mempool_admit": t0 + 5.01},
                    "spans": {}, "commit_latency_s": None,
                    "visible_latency_s": None, "completed_at": None,
                }],
            },
            "b:46657": {
                "traces": [{
                    "hash": h, "source": "peer", "height": 9,
                    "outcome": "committed",
                    "stages": {"rpc_ingress": t0 + 0.03,
                               "mempool_admit": t0 + 0.04,
                               "proposal": t0 + 0.20,
                               "block_commit": t0 + 0.29,
                               "event_delivery": t0 + 0.30},
                    "spans": {}, "commit_latency_s": 0.26,
                    "visible_latency_s": 0.27, "completed_at": t0 + 0.30,
                }],
                "active": [],
            },
            "c:46657": {"error": "ConnectionRefusedError: down"},
        }

    def test_join_builds_cross_node_rows(self):
        from tendermint_tpu.ops.txtrace import join_tx_timelines

        rows = join_tx_timelines(self._snapshot())
        assert len(rows) == 2
        parked = next(r for r in rows if not r["committed"])
        done = next(r for r in rows if r["committed"])
        # the committed tx: submitted on A, proposed on B, cross-node
        assert done["submitted_on"] == "a:46657"
        assert done["proposed_on"] == "b:46657"
        assert done["height"] == 9
        assert done["nodes_reporting"] == 2
        assert done["commit_latency_s"] == pytest.approx(0.26)
        # the parked tx never reached proposal — the wedge-triage read
        assert parked["last_stage"] == "mempool_admit"
        assert parked["nodes_reporting"] == 1

    def test_render_names_the_parked_stage(self):
        import io

        from tendermint_tpu.ops.txtrace import join_tx_timelines, render

        rows = join_tx_timelines(self._snapshot())
        buf = io.StringIO()
        render(rows, out=buf)
        out = buf.getvalue()
        assert "PARKED at mempool_admit" in out
        assert "committed @h=9" in out
        assert "submitted on a:46657" in out

    def test_tx_trace_rpc_handler_filters_by_hash(self):
        from tendermint_tpu.rpc.core.handlers import tx_trace

        rec = TxTraceRecorder(first_k=4, sample_n=0)
        rec.maybe_trace(_tx(0), at=1.0)
        rec.maybe_trace(_tx(1), at=2.0)
        rec.commit([_tx(0)], height=3, at=4.0)
        rec.delivered([_tx(0)], at=5.0)

        class _Node:
            txtrace = rec

        class _Ctx:
            node = _Node()

        res = tx_trace(_Ctx())
        assert len(res["traces"]) == 1 and len(res["active"]) == 1
        want = res["traces"][0]["hash"]
        res2 = tx_trace(_Ctx(), hash=want.lower())
        assert [t["hash"] for t in res2["traces"]] == [want]
        assert res2["active"] == []
        # a context without a node answers empty, never raises
        class _Bare:
            node = None

        assert tx_trace(_Bare()) == {"traces": [], "active": []}


class TestVoteDuplicateCounters:
    def test_peer_duplicate_counted_flat_and_per_peer(self):
        """Round-17 satellite: a gossiped vote begin_add screens as
        already-seen counts on consensus_vote_duplicates AND the
        sender's p2p_peer_vote_duplicates_total series — the 2NxN
        redundancy before-number. Our own re-delivered votes do not
        count (empty peer_id)."""
        from tendermint_tpu.p2p.telemetry import peer_metrics
        from tests.consensus_common import TEST_CHAIN_ID, make_cs_and_stubs
        from tendermint_tpu.types import BlockID
        from tendermint_tpu.types.vote import VOTE_TYPE_PREVOTE

        cs, stubs, prop_idx = make_cs_and_stubs(4)
        reg = telemetry.Registry()
        cs.trace.metrics_registry = reg
        bid = BlockID(b"\x11" * 20)
        voter = next(s for s in stubs if s.index != prop_idx)
        vote = voter.sign_vote(VOTE_TYPE_PREVOTE, TEST_CHAIN_ID, bid)
        assert cs.add_vote(vote, "peer-A") is True
        assert cs.vote_duplicates == 0
        # the same vote from two peers: each re-delivery counts against
        # its sender
        assert cs.add_vote(vote, "peer-A") is False
        assert cs.add_vote(vote, "peer-B") is False
        assert cs.vote_duplicates == 2
        fams = peer_metrics(reg)
        assert fams["vote_duplicates"].labels(peer="peer-A").value == 1
        assert fams["vote_duplicates"].labels(peer="peer-B").value == 1
        # our own duplicate (internal redelivery) is not gossip waste
        assert cs.add_vote(vote, "") is False
        assert cs.vote_duplicates == 2


class TestGatedMempoolEdges:
    """Post-review hardening: every early exit from the lifecycle on a
    GATED mempool seals or stamps the trace — saturation refusals seal
    (never a false PARKED), gate-bypassing txs still get their admit
    stamp, and the ring serves under concurrent stamping."""

    def _gated_mempool(self, max_backlog=8192, parse=None):
        from tendermint_tpu.abci.apps.kvstore import KVStoreApp
        from tendermint_tpu.abci.client import LocalClient
        from tendermint_tpu.config import test_config
        from tendermint_tpu.mempool import Mempool
        from tendermint_tpu.mempool.mempool import SigBatcher
        from tendermint_tpu.ops.gateway import Verifier

        from tendermint_tpu.proxy.app_conn import AppConnMempool

        batcher = SigBatcher(
            Verifier(min_tpu_batch=1 << 30),
            parse if parse is not None else (lambda tx: None),
            max_backlog=max_backlog,
        )
        mp = Mempool(
            test_config().mempool,
            AppConnMempool(LocalClient(KVStoreApp(), threading.RLock())),
            sig_batcher=batcher,
        )
        mp.txtrace = TxTraceRecorder(first_k=8, sample_n=0)
        return mp

    def test_gate_saturation_seals_the_trace(self):
        # max_backlog=0: every parseable tx is refused at submit
        mp = self._gated_mempool(
            max_backlog=0,
            parse=lambda tx: (b"\x00" * 32, tx, b"\x00" * 64),
        )
        mp.check_tx(b"sat=1")
        rec = mp.txtrace
        assert rec.stats()["active"] == 0, "refused tx left in flight"
        [t] = rec.last(5)
        assert t["outcome"] == "gate_saturated"
        assert rec.rejected == 1

    def test_gate_bypassing_tx_still_gets_admit_stamp(self):
        # parse -> None: the tx bypasses the gate to the app directly;
        # the batch-granular admit stamp never covers it, so its own
        # response callback must
        mp = self._gated_mempool(parse=lambda tx: None)
        tx = b"bypass=v"
        mp.check_tx(tx)
        deadline = time.monotonic() + 10
        while mp.size() < 1 and time.monotonic() < deadline:
            mp.flush_app_conn()
            time.sleep(0.005)
        assert mp.size() == 1
        [active] = mp.txtrace.active()
        assert "mempool_admit" in active["stages"], active


class TestUnwantedRoundNotCounted:
    def test_catchup_budget_drop_is_not_a_duplicate(self):
        """Post-review hardening: a vote dropped because its round is
        beyond the peer's catchup budget was never SEEN — it must not
        inflate the 2NxN redundancy counters."""
        from tests.consensus_common import TEST_CHAIN_ID, make_cs_and_stubs
        from tendermint_tpu.types import BlockID
        from tendermint_tpu.types.vote import VOTE_TYPE_PREVOTE

        cs, stubs, prop_idx = make_cs_and_stubs(4)
        cs.trace.metrics_registry = telemetry.Registry()
        bid = BlockID(b"\x22" * 20)
        voter = next(s for s in stubs if s.index != prop_idx)

        # sign each round ONCE, ascending (the privval's double-sign
        # guard refuses re-signing a lower round); re-deliveries reuse
        # the signed vote object like real gossip does
        def vote_at(round_):
            from tendermint_tpu.types.vote import Vote

            v = Vote(
                validator_address=voter.pv.get_address(),
                validator_index=voter.index,
                height=cs.rs.height,
                round_=round_,
                type_=VOTE_TYPE_PREVOTE,
                block_id=bid,
            )
            return voter.pv.sign_vote(TEST_CHAIN_ID, v)

        v10, v20, v30 = vote_at(10), vote_at(20), vote_at(30)
        # two catchup rounds fit the per-peer budget
        assert cs.add_vote(v10, "peer-C") is True
        assert cs.add_vote(v20, "peer-C") is True
        dup0 = cs.vote_duplicates
        # third distinct round: catchup budget spent -> dropped
        # (HeightVoteSet UNWANTED_ROUND), NOT counted as a duplicate
        assert cs.add_vote(v30, "peer-C") is False
        assert cs.vote_duplicates == dup0
        # a genuine re-delivery still counts
        assert cs.add_vote(v10, "peer-C") is False
        assert cs.vote_duplicates == dup0 + 1
