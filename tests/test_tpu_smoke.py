"""Honest TPU smoke tier (VERDICT r3 #8): every default suite run
exercises the live accelerator when it is reachable, and a dead tunnel
shows up as a SKIP with a reason in CI output — not only in bench JSON.

The probe goes through the device daemon (tendermint_tpu/devd.py) at its
PRODUCTION socket, so this test process never initializes jax against
the tunnel (tests pin jax to CPU precisely because a wedged tunnel hangs
any in-process dial). When the daemon holds the chip, the 64-lane batch
below runs the production f32p kernel on real hardware — the coverage
the hardware-gated parity test (tests/test_ops_f32.py) can't give CI.
"""

from __future__ import annotations

import pytest

from tendermint_tpu import devd
from tendermint_tpu.crypto import ed25519 as ed


def _serving_daemon() -> tuple[devd.DevdClient, dict]:
    client = devd.DevdClient(devd.DEFAULT_SOCK, connect_timeout=2.0, io_timeout=60.0)
    try:
        rep = client.ping(timeout=3.0)
    except Exception as exc:  # noqa: BLE001 — reason goes in the skip
        pytest.skip(
            f"TPU smoke: no device daemon serving on {devd.DEFAULT_SOCK} "
            f"({type(exc).__name__}) — start one with `python -m "
            f"tendermint_tpu.devd`; tunnel state unknown"
        )
    if not rep.get("held"):
        pytest.skip(
            f"TPU smoke: daemon up (pid {rep.get('pid')}) but device not "
            f"held — status {rep.get('status')!r} (tunnel down or still "
            f"warming); uptime {rep.get('uptime_s')}s"
        )
    if rep.get("platform") not in ("tpu", "axon"):
        pytest.skip(
            f"TPU smoke: daemon serving platform {rep.get('platform')!r}, "
            f"not real accelerator hardware"
        )
    return client, rep


def test_live_accelerator_parity_64_lanes():
    client, rep = _serving_daemon()
    seed = b"\x2a" * 32
    pub = ed.public_key(seed)
    items = [
        (pub, b"tpu-smoke-%d" % i, ed.sign(seed, b"tpu-smoke-%d" % i))
        for i in range(64)
    ]
    items[7] = (items[7][0], items[7][1], b"\x66" * 64)  # forged
    items[23] = (items[23][0], items[23][1] + b"!", items[23][2])  # tampered
    before = rep["stats"].get("tpu_sigs", 0)
    got = client.verify_batch(items)
    want = [ed.verify(p, m, s) for p, m, s in items]
    assert got == want, "device/CPU verdict mismatch on live hardware"
    after = client.stats().get("tpu_sigs", 0)
    assert after - before >= 64, "batch did not ride the device kernel"
    client.close()
