"""Tests for the runtime primitives (tmlibs equivalents)."""

import os
import threading
import time

import pytest

from tendermint_tpu.libs.autofile import Group
from tendermint_tpu.libs.bitarray import BitArray
from tendermint_tpu.libs.clist import CList
from tendermint_tpu.libs.db import FileDB, MemDB
from tendermint_tpu.libs.events import EventCache, EventSwitch
from tendermint_tpu.libs.service import BaseService


class TestBaseService:
    def test_start_stop_idempotent(self):
        events = []

        class Svc(BaseService):
            def on_start(self):
                events.append("start")

            def on_stop(self):
                events.append("stop")

        s = Svc()
        assert s.start() is True
        assert s.start() is False
        assert s.is_running()
        assert s.stop() is True
        assert s.stop() is False
        assert not s.is_running()
        assert events == ["start", "stop"]

    def test_wait_unblocks_on_stop(self):
        s = BaseService()
        s.start()
        t = threading.Thread(target=lambda: (time.sleep(0.05), s.stop()))
        t.start()
        assert s.wait(timeout=2.0)
        t.join()

    def test_no_restart(self):
        s = BaseService()
        s.start()
        s.stop()
        with pytest.raises(RuntimeError):
            s.start()


class TestBitArray:
    def test_basics(self):
        ba = BitArray(10)
        assert not ba.get_index(3)
        assert ba.set_index(3, True)
        assert ba.get_index(3)
        assert not ba.set_index(10, True)  # out of range
        assert ba.num_true_bits() == 1

    def test_algebra(self):
        a = BitArray.from_indices(8, [0, 1, 2])
        b = BitArray.from_indices(8, [1, 2, 3])
        assert a.or_(b).indices() == [0, 1, 2, 3]
        assert a.and_(b).indices() == [1, 2]
        assert a.sub(b).indices() == [0]
        assert a.not_().indices() == [3, 4, 5, 6, 7]

    def test_full_empty(self):
        assert BitArray(0).is_empty()
        full = BitArray.from_indices(3, [0, 1, 2])
        assert full.is_full()
        assert not BitArray.from_indices(3, [0]).is_full()

    def test_pick_random(self):
        ba = BitArray.from_indices(64, [5, 17])
        seen = set()
        for _ in range(100):
            i, ok = ba.pick_random()
            assert ok
            seen.add(i)
        assert seen == {5, 17}
        _, ok = BitArray(4).pick_random()
        assert not ok

    def test_json_roundtrip(self):
        ba = BitArray.from_indices(12, [0, 7, 11])
        assert BitArray.from_json(ba.to_json()) == ba


class TestCList:
    def test_push_iterate(self):
        cl = CList()
        els = [cl.push_back(i) for i in range(5)]
        assert [e.value for e in cl] == [0, 1, 2, 3, 4]
        assert len(cl) == 5
        cl.remove(els[2])
        assert [e.value for e in cl] == [0, 1, 3, 4]
        # removed element still navigates forward
        assert els[2].next().value == 3

    def test_front_wait_blocks_until_push(self):
        cl = CList()
        got = []

        def consumer():
            el = cl.front_wait(timeout=2.0)
            got.append(el.value if el else None)

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.05)
        cl.push_back("tx")
        t.join()
        assert got == ["tx"]

    def test_next_wait(self):
        cl = CList()
        el = cl.push_back(1)
        t = threading.Thread(target=lambda: (time.sleep(0.05), cl.push_back(2)))
        t.start()
        nxt = el.next_wait(timeout=2.0)
        t.join()
        assert nxt.value == 2


class TestEvents:
    def test_fire_and_remove(self):
        sw = EventSwitch()
        got = []
        sw.add_listener_for_event("l1", "ev", lambda d: got.append(("l1", d)))
        sw.add_listener_for_event("l2", "ev", lambda d: got.append(("l2", d)))
        sw.fire_event("ev", 1)
        assert sorted(got) == [("l1", 1), ("l2", 1)]
        sw.remove_listener("l1")
        got.clear()
        sw.fire_event("ev", 2)
        assert got == [("l2", 2)]

    def test_cache_flush_order(self):
        sw = EventSwitch()
        got = []
        sw.add_listener_for_event("l", "a", lambda d: got.append(("a", d)))
        sw.add_listener_for_event("l", "b", lambda d: got.append(("b", d)))
        cache = EventCache(sw)
        cache.fire_event("a", 1)
        cache.fire_event("b", 2)
        assert got == []
        cache.flush()
        assert got == [("a", 1), ("b", 2)]
        cache.flush()
        assert got == [("a", 1), ("b", 2)]


class TestDB:
    def test_memdb(self):
        db = MemDB()
        db.set(b"k1", b"v1")
        db.set(b"k2", b"v2")
        assert db.get(b"k1") == b"v1"
        assert db.get(b"missing") is None
        db.delete(b"k1")
        assert not db.has(b"k1")
        assert list(db.iterate_prefix(b"k")) == [(b"k2", b"v2")]

    def test_filedb_persistence(self, tmp_path):
        path = str(tmp_path / "test.db")
        db = FileDB(path)
        db.set(b"a", b"1")
        db.set_sync(b"b", b"2")
        db.delete(b"a")
        db.close()
        db2 = FileDB(path)
        assert db2.get(b"a") is None
        assert db2.get(b"b") == b"2"
        db2.close()

    def test_filedb_torn_tail(self, tmp_path):
        path = str(tmp_path / "torn.db")
        db = FileDB(path)
        db.set_sync(b"good", b"val")
        db.close()
        with open(path, "ab") as f:
            f.write(b"\x01\x05\x00\x00")  # truncated record
        db2 = FileDB(path)
        assert db2.get(b"good") == b"val"
        # writes after torn-tail recovery must survive ANOTHER restart
        db2.set_sync(b"newkey", b"newval")
        db2.close()
        db3 = FileDB(path)
        assert db3.get(b"newkey") == b"newval"
        assert db3.get(b"good") == b"val"
        assert len(db3._index) == 2
        db3.close()

    def test_filedb_compaction(self, tmp_path):
        path = str(tmp_path / "compact.db")
        db = FileDB(path, compact_threshold=2000)
        for i in range(100):
            db.set(b"key", str(i).encode() * 10)
        db.close()
        assert os.path.getsize(path) < 2000
        db2 = FileDB(path)
        assert db2.get(b"key") == b"99" * 10
        db2.close()

    def test_filedb_reads_after_compaction_and_deletes(self, tmp_path):
        """The disk-resident value design (key -> offset index): offsets
        must survive compaction rewriting the journal, deletes must
        persist, and gets must read through live appends."""
        path = str(tmp_path / "offsets.db")
        db = FileDB(path, compact_threshold=1500)
        for i in range(60):
            db.set(b"k%03d" % i, b"v%03d" % i * 9)
        for i in range(0, 60, 3):
            db.delete(b"k%03d" % i)
        db.set(b"k001", b"rewritten")  # overwrite post-delete-phase
        # every surviving key reads its latest value (compactions have
        # happened along the way at this threshold)
        assert db.get(b"k001") == b"rewritten"
        for i in range(60):
            if i % 3 == 0:
                want = None  # deleted
            elif i == 1:
                want = b"rewritten"
            else:
                want = b"v%03d" % i * 9
            assert db.get(b"k%03d" % i) == want, i
        # the reads above went through LIVE post-compaction offsets —
        # prove compaction actually happened (a new_index offset bug
        # would otherwise pass the suite and corrupt a running node)
        assert db._compactions > 0
        # iteration reads values through the index too
        items = dict(db.iterate_prefix(b"k"))
        assert items[b"k001"] == b"rewritten" and b"k000" not in items
        db.close()
        # and the whole state survives a restart
        db2 = FileDB(path)
        assert db2.get(b"k001") == b"rewritten"
        assert db2.get(b"k003") is None
        assert db2.get(b"k002") == b"v002" * 9
        db2.close()

    def test_filedb_memory_is_index_only(self, tmp_path):
        """The in-memory footprint must be the key index, not the values
        (a block store retaining ~9KB RAM per block grows without bound —
        caught by the round-4 soak)."""
        db = FileDB(str(tmp_path / "big.db"))
        big = os.urandom(64 * 1024)
        for i in range(16):
            db.set(b"blk%05d" % i, big)
        import sys as _sys

        index_bytes = _sys.getsizeof(db._index) + sum(
            _sys.getsizeof(k) + _sys.getsizeof(v) for k, v in db._index.items()
        )
        assert index_bytes < 16 * 1024  # 1MB of values, ~2KB of index
        assert db.get(b"blk00007") == big
        db.close()


class TestAutofile:
    def test_write_and_search(self, tmp_path):
        g = Group(str(tmp_path / "wal"))
        g.write_line("msg1")
        g.write_line("#ENDHEIGHT: 1")
        g.write_line("msg2")
        g.write_line("msg3")
        g.flush()
        assert g.search_lines_after_marker("#ENDHEIGHT: 1") == ["msg2", "msg3"]
        assert g.search_lines_after_marker("#ENDHEIGHT: 99") is None
        g.close()

    def test_rotation(self, tmp_path):
        g = Group(str(tmp_path / "wal"), chunk_size=100)
        for i in range(50):
            g.write_line(f"line-{i:04d}")
            g.flush()
        assert g.read_all_lines() == [f"line-{i:04d}" for i in range(50)]
        # marker search spans chunks
        g.write_line("#M")
        g.write_line("after")
        g.flush()
        assert g.search_lines_after_marker("#M") == ["after"]
        g.close()

    def test_reopen_appends(self, tmp_path):
        path = str(tmp_path / "wal")
        g = Group(path)
        g.write_line("first")
        g.close()
        g2 = Group(path)
        g2.write_line("second")
        g2.flush()
        assert g2.read_all_lines() == ["first", "second"]
        g2.close()

    def test_marker_search_parity_with_full_scan(self, tmp_path):
        """The newest-first early-stop search must agree with a naive
        front-to-back scan over every chunk, for every marker position
        across rotated multi-chunk groups (the round-9 satellite's parity
        oracle: replay only ever wants the LAST #ENDHEIGHT, but the
        answer must be identical to the exhaustive scan's)."""

        def full_scan(g: Group, marker: str):
            lines = g.read_all_lines()
            best = None
            for i, ln in enumerate(lines):
                if ln == marker:
                    best = i
            return None if best is None else lines[best + 1 :]

        import random

        rng = random.Random(9)
        for case in range(6):
            g = Group(str(tmp_path / f"w{case}"), chunk_size=64)
            markers = [f"#ENDHEIGHT: {h}" for h in range(4)]
            for i in range(rng.randrange(5, 60)):
                if rng.random() < 0.3:
                    g.write_line(markers[rng.randrange(4)])
                else:
                    g.write_line(f"case{case}-line-{i}")
                g.flush()
            for marker in markers + ["#ENDHEIGHT: 99"]:
                assert g.search_lines_after_marker(marker) == full_scan(g, marker), (
                    case, marker,
                )
            g.close()

    def test_marker_search_stops_at_newest_chunk(self, tmp_path):
        """The early-stop claim itself: a marker in the newest chunk means
        older chunks are never opened (node-start cost on long WALs)."""
        import builtins

        g = Group(str(tmp_path / "wal"), chunk_size=64)
        for i in range(30):
            g.write_line(f"old-{i}")
            g.flush()
        g.write_line("#M")
        g.write_line("after")
        g.flush()
        chunks = g.chunk_paths()
        assert len(chunks) > 2
        opened = []
        real_open = builtins.open

        def spy(path, *a, **kw):
            opened.append(str(path))
            return real_open(path, *a, **kw)

        builtins.open = spy
        try:
            assert g.search_lines_after_marker("#M") == ["after"]
        finally:
            builtins.open = real_open
        # the head may have just rotated (empty head + marker in the last
        # numbered chunk): the scan may touch the newest chunks until the
        # marker hit, but must never read the older ones
        read_chunks = set(p for p in opened if p in chunks)
        assert read_chunks <= set(chunks[-2:]), "older chunks were scanned"

    def test_synced_flush_never_blocks_concurrent_appends(self, tmp_path):
        """flush(sync=True) must run the fsync OUTSIDE the append lock —
        the WAL flusher's group commit must never stall a save() on the
        consensus receive hot path behind a disk round trip."""
        import threading as th
        from unittest import mock

        g = Group(str(tmp_path / "wal"))
        g.write_line("seed")
        entered, release, done = th.Event(), th.Event(), th.Event()
        real_fsync = os.fsync

        def slow_fsync(fd):
            entered.set()
            assert release.wait(5)
            return real_fsync(fd)

        with mock.patch("tendermint_tpu.libs.autofile.os.fsync", slow_fsync):
            syncer = th.Thread(target=g.flush, kwargs={"sync": True})
            syncer.start()
            assert entered.wait(5)

            def append():
                g.write_line("hot-path")
                g.flush()
                done.set()

            appender = th.Thread(target=append)
            appender.start()
            stalled = not done.wait(2)
            release.set()
            syncer.join(5)
            appender.join(5)
        g.close()
        assert not stalled, "append stalled behind the synced flush's fsync"

    def test_sync_journals_directory_after_creation_and_rotation(self, tmp_path):
        """Directory entries (fresh head, rotation's os.replace) are durable
        only once the directory itself is fsynced; the next synced flush
        must do that — and idle synced flushes must not re-pay it."""
        import stat
        from unittest import mock

        synced_dirs = []
        real_fsync = os.fsync

        def spy(fd):
            if stat.S_ISDIR(os.fstat(fd).st_mode):
                synced_dirs.append(fd)
            return real_fsync(fd)

        with mock.patch("tendermint_tpu.libs.autofile.os.fsync", spy):
            g = Group(str(tmp_path / "wal"), chunk_size=32)
            g.write_line("a")
            g.flush(sync=True)
            assert synced_dirs, "head creation never journaled the directory"
            synced_dirs.clear()
            g.flush(sync=True)
            assert not synced_dirs, "clean sync re-paid the directory fsync"
            for i in range(6):
                g.write_line(f"row-{i}")
                g.flush()  # rotates (chunk_size=32)
            assert len(g.chunk_paths()) > 1
            g.flush(sync=True)
            assert synced_dirs, "rotation never journaled the directory"
            g.close()

    def test_write_bytes_and_chunk_header(self, tmp_path):
        """Raw byte appends (the framed WAL path) + the per-chunk header:
        every chunk — head at creation AND each post-rotation head —
        starts with the magic."""
        path = str(tmp_path / "wal")
        g = Group(path, chunk_size=16, header=b"HDR!")
        for i in range(10):
            g.write_bytes(b"payload-%02d" % i)
            g.flush()
        g.close()
        chunks = Group.list_chunks(path)
        assert len(chunks) > 2
        for p in chunks:
            with open(p, "rb") as f:
                assert f.read(4) == b"HDR!", p


def test_reqres_done_and_timeout_path():
    """ReqRes after the lazy-Event rewrite: done() is the public probe
    (code-review r3: SocketClient's timeout path uses it), wait() before
    and after completion, and callback-after-done fires immediately."""
    from tendermint_tpu.abci.client import ReqRes

    rr = ReqRes("echo")
    assert not rr.done()
    assert rr.wait(timeout=0.01) is None  # timeout: not done, no crash
    assert not rr.done()
    rr.complete({"ok": True})
    assert rr.done()
    assert rr.wait() == {"ok": True}
    got = []
    rr.set_callback(got.append)  # already done -> fires inline
    assert got == [{"ok": True}]


class TestSqliteDB:
    """SqliteDB: the bounded-RAM persistent backend (libs/db.py — the
    round-5 soak found FileDB's in-memory key index grows with chain
    length forever; sqlite keeps the index on disk behind a fixed page
    cache)."""

    def _mk(self, tmp_path, name="test.sqlite"):
        from tendermint_tpu.libs.db import SqliteDB

        return SqliteDB(str(tmp_path / name))

    def test_basic_ops(self, tmp_path):
        db = self._mk(tmp_path)
        db.set(b"k1", b"v1")
        db.set(b"k2", b"v2")
        assert db.get(b"k1") == b"v1"
        assert db.get(b"missing") is None
        db.delete(b"k1")
        assert not db.has(b"k1")
        assert list(db.iterate_prefix(b"k")) == [(b"k2", b"v2")]
        db.close()

    def test_persistence_and_set_sync(self, tmp_path):
        from tendermint_tpu.libs.db import SqliteDB

        path = str(tmp_path / "p.sqlite")
        db = SqliteDB(path)
        db.set(b"a", b"1")
        db.set_sync(b"b", b"2")
        db.delete(b"a")
        db.close()
        db2 = SqliteDB(path)
        assert db2.get(b"a") is None
        assert db2.get(b"b") == b"2"
        db2.close()

    def test_overwrite_keeps_latest(self, tmp_path):
        db = self._mk(tmp_path)
        for i in range(50):
            db.set(b"key", b"%d" % i)
        assert db.get(b"key") == b"49"
        db.close()

    def test_iterate_prefix_range_bounds(self, tmp_path):
        # keys beyond a naive fixed-width upper bound must still match:
        # the exclusive-upper-bound trick, not prefix+0xff padding
        db = self._mk(tmp_path)
        db.set(b"p\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff", b"deep")
        db.set(b"p1", b"v1")
        db.set(b"q1", b"other")
        got = dict(db.iterate_prefix(b"p"))
        assert got == {
            b"p\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff": b"deep",
            b"p1": b"v1",
        }
        # all-0xff prefix: no upper bound, still correct
        db.set(b"\xff\xffx", b"last")
        assert dict(db.iterate_prefix(b"\xff\xff")) == {b"\xff\xffx": b"last"}
        db.close()

    def test_provider_selects_sqlite(self, tmp_path):
        from tendermint_tpu.libs.db import SqliteDB, db_provider

        db = db_provider("blockstore", "sqlite", str(tmp_path))
        assert isinstance(db, SqliteDB)
        db.set(b"x", b"y")
        assert db.get(b"x") == b"y"
        db.close()

    def test_concurrent_readers_and_writers(self, tmp_path):
        import threading as th

        db = self._mk(tmp_path)
        errs = []

        def writer(base):
            try:
                for i in range(200):
                    db.set(b"w%d-%d" % (base, i), b"v%d" % i)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        def reader():
            try:
                for _ in range(200):
                    db.get(b"w0-5")
                    list(db.iterate_prefix(b"w1-19"))
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [th.Thread(target=writer, args=(i,)) for i in range(2)] + [
            th.Thread(target=reader)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, errs
        assert db.get(b"w0-199") == b"v199"
        db.close()
