"""Real-TCP chaos-net harness (round 12, docs/secure-p2p.md scenario
matrix): N full in-process Nodes — every subsystem wired exactly as
production (`node/node.py`: consensus, mempool, fast sync, statesync,
RPC, telemetry) — peered over REAL TCP listeners through per-link
`ops/netfaults.LinkProxy` relays, with the in-repo SecretConnection
(X25519 + ChaCha20-Poly1305) encrypting every byte. No loopback fabric
anywhere: what the scenario matrix breaks is an actual network.

Topology: nodes boot in index order; node i dials every earlier node j
through the fabric's directed link (i, j), as a PERSISTENT seed — so a
severed link keeps retrying through an outage and heals without test
intervention (switch reconnect cadence is env-tuned tight for tests).
Inbound/outbound dedup never races: only i dials j, never both.

Shared by tests/test_netchaos.py (the scenario matrix) and
benches/bench_netchaos.py (BENCH_r12: partition-heal recovery time,
committed-tx/s under churn), which is why it lives in a _common module
like tests/consensus_common.py.
"""

from __future__ import annotations

import os
import shutil
import socket
import time

from tendermint_tpu.config.config import test_config
from tendermint_tpu.config.toml import ensure_root
from tendermint_tpu.node.node import Node, default_new_node
from tendermint_tpu.ops.netfaults import NetFabric
from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivValidatorFS

CHAIN_ID = "netchaos"

# tight reconnect cadence: a healed partition must re-peer in ~a second,
# not the production 3 s x 30 default (libs/envknob-parsed, so a typo'd
# override never kills a node)
os.environ.setdefault("TENDERMINT_P2P_RECONNECT_INTERVAL_S", "0.25")
os.environ.setdefault("TENDERMINT_P2P_RECONNECT_ATTEMPTS", "400")


def wait_until(cond, timeout=60.0, tick=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(tick)
    return cond()


class ChaosNet:
    """N-validator kvstore net over real TCP through fault proxies."""

    def __init__(self, n: int, root: str, app: str = "kvstore",
                 snapshot_interval: int = 0):
        self.n = n
        self.root = root
        self.app = app
        self.snapshot_interval = snapshot_interval
        self.fabric = NetFabric(name=f"chaosnet-{os.path.basename(root)}")
        self.nodes: list[Node] = []
        self.pvs: list[PrivValidatorFS] = []
        os.makedirs(root, exist_ok=True)

        # one genesis, n validators (sorted by address like make_genesis)
        pvs = []
        for i in range(n):
            pv = PrivValidatorFS(
                gen_priv_key_ed25519(f"{CHAIN_ID}-val-{i}".encode()), None
            )
            pvs.append(pv)
        pvs.sort(key=lambda pv: pv.get_address())
        self.pvs = pvs
        self.genesis = GenesisDoc(
            genesis_time_ns=time.time_ns(),
            chain_id=CHAIN_ID,
            validators=[
                GenesisValidator(pv.get_pub_key(), 10, f"v{i}")
                for i, pv in enumerate(pvs)
            ],
        )

    # -- boot ---------------------------------------------------------------

    def _make_config(self, idx: int, statesync_from: list[int] | None = None):
        cfg = test_config()
        home = os.path.join(self.root, f"node{idx}")
        ensure_root(home, cfg)
        cfg.base.proxy_app = self.app
        cfg.base.moniker = f"chaos-{idx}"
        cfg.base.chain_id = CHAIN_ID
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.statesync.snapshot_interval = self.snapshot_interval
        if statesync_from:
            cfg.base.fast_sync = True
            cfg.statesync.enable = True
            cfg.statesync.rpc_servers = ",".join(
                f"127.0.0.1:{self.nodes[j].rpc_port()}" for j in statesync_from
            )
        self.genesis.save_as(cfg.base.genesis_file())
        return cfg

    def _listener_port(self, j: int) -> int:
        return self.nodes[j].listener.internal_address().port

    def _seed_links(self, i: int, targets: list[int]) -> str:
        seeds = []
        for j in targets:
            link = self.fabric.add_link(
                i, j, ("127.0.0.1", self._listener_port(j))
            )
            seeds.append(link.laddr)
        return ",".join(seeds)

    def start_node(self, idx: int, pv: PrivValidatorFS | None,
                   statesync_from: list[int] | None = None,
                   dial: list[int] | None = None) -> Node:
        cfg = self._make_config(idx, statesync_from=statesync_from)
        if pv is not None:
            pv.file_path = cfg.base.priv_validator_file()
            pv.save()
        node = default_new_node(cfg)
        node.start()
        # dial earlier nodes through per-link proxies AFTER start (the
        # listener port exists once started; seeds at config time would
        # race the boot order anyway)
        targets = dial if dial is not None else list(range(len(self.nodes)))
        if targets:
            node.sw.dial_seeds(self._seed_links(idx, targets).split(","))
        self.nodes.append(node)
        return node

    def start(self) -> "ChaosNet":
        for i in range(self.n):
            self.start_node(i, self.pvs[i])
        return self

    # -- chaos verbs --------------------------------------------------------

    def partition(self, group_a) -> None:
        self.fabric.partition_groups(set(group_a))

    def heal(self) -> None:
        self.fabric.heal_all()

    def delay_node(self, idx: int, one_way_s: float,
                   asymmetric: bool = True) -> None:
        """Slow every link touching `idx`: inbound-direction traffic
        toward the node delayed, return path fast (asymmetric=True) or
        both ways (False)."""
        for (i, j), link in self.fabric.links().items():
            if idx not in (i, j):
                continue
            toward_j = one_way_s if j == idx else (0 if asymmetric else one_way_s)
            toward_i = one_way_s if i == idx else (0 if asymmetric else one_way_s)
            link.set_delay(c2s_s=toward_j, s2c_s=toward_i)

    def clear_delays(self) -> None:
        for link in self.fabric.links().values():
            link.set_delay(0, 0)

    def churn_listener(self, idx: int, down_s: float = 0.5) -> None:
        """The peer-churn arm: kill node idx's listener, reset every
        connection it has (both directions via its links), then restart
        the listener on the SAME port and let persistent dials re-peer."""
        node = self.nodes[idx]
        port = node.listener.internal_address().port
        node.listener.stop()
        for link in self.fabric.links_of(idx):
            link.drop_all()
        for peer in node.sw.peers.list():
            node.sw.stop_peer_for_error(peer, "chaos: listener churn")
        time.sleep(down_s)
        from tendermint_tpu.p2p.listener import Listener

        # the dead listener's port re-binds (SO_REUSEADDR) so the
        # fabric's links keep pointing at it and healing is automatic —
        # but lingering accepted-socket teardown can hold the addr for a
        # beat, so retry the bind briefly
        lst = None
        for _ in range(100):
            try:
                lst = Listener(f"127.0.0.1:{port}")
                break
            except OSError:
                time.sleep(0.1)
        if lst is None:
            raise OSError(f"could not re-bind churned listener port {port}")
        node.listener = lst
        node.sw.start_listener(lst)

    # -- convergence assertions ---------------------------------------------

    def heights(self) -> list[int]:
        return [n.block_store.height() for n in self.nodes]

    def wait_height(self, h: int, timeout: float = 120.0,
                    nodes: list[int] | None = None) -> bool:
        idxs = nodes if nodes is not None else range(len(self.nodes))
        return wait_until(
            lambda: all(self.nodes[i].block_store.height() >= h for i in idxs),
            timeout=timeout,
            tick=0.1,
        )

    def fingerprints(self, upto: int, node_idx: int) -> list[tuple]:
        """(height, block hash, part-set root, app hash) per height —
        the byte-identity surface the soaks assert on."""
        node = self.nodes[node_idx]
        out = []
        for h in range(1, upto + 1):
            meta = node.block_store.load_block_meta(h)
            block = node.block_store.load_block(h)
            out.append(
                (
                    h,
                    meta.block_id.hash.hex(),
                    meta.block_id.parts_header.hash.hex(),
                    block.header.app_hash.hex(),
                    block.header.evidence_hash.hex(),
                )
            )
        return out

    def assert_converged(self, upto: int, nodes: list[int] | None = None) -> None:
        idxs = list(nodes if nodes is not None else range(len(self.nodes)))
        want = self.fingerprints(upto, idxs[0])
        for i in idxs[1:]:
            got = self.fingerprints(upto, i)
            assert got == want, (
                f"node {i} diverges from node {idxs[0]} in heights 1..{upto}:"
                f"\n{set(want) ^ set(got)}"
            )

    def broadcast_tx(self, tx: bytes, via: int = 0) -> None:
        self.nodes[via].mempool.check_tx(tx)

    def stop(self) -> None:
        for node in self.nodes:
            try:
                node.stop()
            except Exception:  # noqa: BLE001 — teardown best effort
                pass
        self.fabric.stop()
        shutil.rmtree(self.root, ignore_errors=True)


# -- the hostile-but-fluent peer: byzantine vote injection --------------------


class VoteInjector:
    """Dials a node over the REAL encrypted transport (TCP ->
    SecretConnection -> NodeInfo handshake -> MConnection) and pushes
    crafted consensus votes — the double-signer of the byzantine
    scenario. It speaks enough protocol to be admitted as a peer; it
    never runs a consensus state of its own."""

    def __init__(self, target_host: str, target_port: int, chain_id: str):
        from tendermint_tpu.blockchain.reactor import BLOCKCHAIN_CHANNEL
        from tendermint_tpu.consensus.reactor import (
            DATA_CHANNEL,
            STATE_CHANNEL,
            VOTE_CHANNEL,
            VOTE_SET_BITS_CHANNEL,
        )
        from tendermint_tpu.mempool.reactor import MEMPOOL_CHANNEL
        from tendermint_tpu.p2p.conn import ChannelDescriptor, MConnection
        from tendermint_tpu.p2p.node_info import NodeInfo, default_version
        from tendermint_tpu.p2p.peer import exchange_node_info
        from tendermint_tpu.p2p.secret_connection import SecretConnection
        from tendermint_tpu.p2p.stream import SocketStream
        from tendermint_tpu.statesync.reactor import STATESYNC_CHANNEL
        from tendermint_tpu.version import VERSION

        self.vote_channel = VOTE_CHANNEL
        # every channel the node's reactors gossip on: an unknown inbound
        # channel is a fatal mconn error, and the consensus/mempool
        # reactors start pushing to a fresh peer immediately
        channels = (
            STATE_CHANNEL, DATA_CHANNEL, VOTE_CHANNEL, VOTE_SET_BITS_CHANNEL,
            MEMPOOL_CHANNEL, BLOCKCHAIN_CHANNEL, STATESYNC_CHANNEL,
        )
        sock = socket.create_connection((target_host, target_port), timeout=10)
        self._key = gen_priv_key_ed25519()
        self.conn = SecretConnection(SocketStream(sock), self._key)
        info = NodeInfo(
            pub_key=self._key.pub_key(),
            moniker="byz-injector",
            network=chain_id,
            version=default_version(VERSION),
        )
        info.channels = bytes(channels)
        self.remote_info = exchange_node_info(self.conn, info, timeout=10)
        self._err: list = []
        self.mconn = MConnection(
            self.conn,
            [ChannelDescriptor(id=c, priority=5) for c in channels],
            on_receive=lambda ch, msg: None,
            on_error=self._err.append,
        )
        self.mconn.start()

    def send_vote(self, vote) -> bool:
        from tendermint_tpu.consensus import messages as msgs
        from tendermint_tpu.consensus.reactor import _enc

        return self.mconn.send(self.vote_channel, _enc(msgs.VoteMessage(vote)))

    def close(self) -> None:
        try:
            self.mconn.stop()
        except Exception:  # noqa: BLE001 — teardown best effort
            pass
        self.conn.close()


def make_conflicting_votes(pv, validators, height: int, round_: int,
                           chain_id: str):
    """Two signed prevotes by `pv` for the same (height, round) naming
    different blocks — the raw material of DuplicateVoteEvidence (the
    signer bypasses the privval double-sign guard exactly like
    test_byzantine.ByzantinePrivValidator: a real byzantine key holder
    is not running our guard)."""
    from tendermint_tpu.types import BlockID, PartSetHeader
    from tendermint_tpu.types.vote import VOTE_TYPE_PREVOTE, Vote

    idx, _ = validators.get_by_address(pv.get_address())
    votes = []
    for fill in (0xAA, 0xCC):
        vote = Vote(
            validator_address=pv.get_address(),
            validator_index=idx,
            height=height,
            round_=round_,
            type_=VOTE_TYPE_PREVOTE,
            block_id=BlockID(
                bytes([fill]) * 20, PartSetHeader(1, bytes([fill ^ 0xFF]) * 20)
            ),
        )
        votes.append(
            vote.with_signature(pv.priv_key.sign(vote.sign_bytes(chain_id)))
        )
    return votes
