"""Real-TCP chaos-net harness (round 12, docs/secure-p2p.md scenario
matrix): N full in-process Nodes — every subsystem wired exactly as
production (`node/node.py`: consensus, mempool, fast sync, statesync,
RPC, telemetry) — peered over REAL TCP listeners through per-link
`ops/netfaults.LinkProxy` relays, with the in-repo SecretConnection
(X25519 + ChaCha20-Poly1305) encrypting every byte. No loopback fabric
anywhere: what the scenario matrix breaks is an actual network.

Topology: nodes boot in index order; node i dials every earlier node j
through the fabric's directed link (i, j), as a PERSISTENT seed — so a
severed link keeps retrying through an outage and heals without test
intervention (switch reconnect cadence is env-tuned tight for tests).
Inbound/outbound dedup never races: only i dials j, never both.

Round 18 grows this into the ADVERSARIAL network tier
(docs/netchaos.md): ChaosNet gains WAN-profile / geo-cluster verbs
(seeded latency distributions over the same link proxies), a rolling
restart arm (stop -> retarget links -> statesync re-join), per-node
genesis commit_format overrides (mixed-version nets), and soak
instrumentation (RSS / disk / flight-recorder quietness); the
VoteInjector generalizes into a HostilePeer family — mempool flooder,
oversized-frame peer, slow-loris, eclipse identities, frame corruptor —
every one speaking the real encrypted protocol.

Shared by tests/test_netchaos.py (the scenario matrix) and
benches/bench_netchaos.py + benches/bench_wan.py (BENCH_r12/r18),
which is why it lives in a _common module like
tests/consensus_common.py.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import socket
import time

from tendermint_tpu.config.config import test_config
from tendermint_tpu.config.toml import ensure_root
from tendermint_tpu.node.node import Node, default_new_node
from tendermint_tpu.ops.netfaults import NetFabric, geo_clusters
from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivValidatorFS

CHAIN_ID = "netchaos"

# tight reconnect cadence: a healed partition must re-peer in ~a second,
# not the production 3 s x 30 default (libs/envknob-parsed, so a typo'd
# override never kills a node)
os.environ.setdefault("TENDERMINT_P2P_RECONNECT_INTERVAL_S", "0.25")
os.environ.setdefault("TENDERMINT_P2P_RECONNECT_ATTEMPTS", "400")


def wait_until(cond, timeout=60.0, tick=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(tick)
    return cond()


class ChaosNet:
    """N-validator kvstore net over real TCP through fault proxies."""

    def __init__(self, n: int, root: str, app: str = "kvstore",
                 snapshot_interval: int = 0,
                 commit_format_of: dict[int, str] | None = None,
                 db_backend: str | None = None,
                 retain_blocks: int = 0,
                 prune_interval: int = 0,
                 snapshot_chunk_size: int | None = None,
                 snapshot_full_every: int | None = None,
                 snapshot_keep: int | None = None,
                 height_throttle_s: float | None = None,
                 gossip_dedup: bool | None = None):
        self.n = n
        self.root = root
        self.app = app
        self.snapshot_interval = snapshot_interval
        # bounded-retention lifecycle (round 19): arm [pruning] on every
        # node; db_backend="sqlite" puts the block store on real disk so
        # the retention soaks measure actual bytes (the test preset's
        # memdb keeps only WAL + snapshots on disk)
        self.db_backend = db_backend
        self.retain_blocks = retain_blocks
        self.prune_interval = prune_interval
        self.snapshot_chunk_size = snapshot_chunk_size
        self.snapshot_full_every = snapshot_full_every
        # snapshot LIFETIME engineering for the retention scenarios: at
        # the test preset's cadence a node commits 5-20 heights/s, so
        # the default keep_recent=2 rotates a snapshot out in a couple
        # of seconds — any restore loses the race and the pruner chases
        # past the height being fetched (real deployments snapshot
        # hourly; lifetime >> restore time). snapshot_keep widens the
        # window; height_throttle_s slows the commit cadence itself
        # (a real timeout_commit instead of the preset's skipped one).
        self.snapshot_keep = snapshot_keep
        self.height_throttle_s = height_throttle_s
        # round 20: None = config default (dedup on); False boots the
        # whole net with the pre-round-20 gossip, the A/B baseline the
        # duplicate-ratio assertions compare against
        self.gossip_dedup = gossip_dedup
        # mixed-version nets (round 18): per-node genesis commit_format
        # override — {idx: "aggregate"} boots node idx under the other
        # flag; NodeInfo.compatible_with refuses the peering loudly
        self.commit_format_of = commit_format_of or {}
        self.fabric = NetFabric(name=f"chaosnet-{os.path.basename(root)}")
        self.nodes: list[Node] = []
        self.pvs: list[PrivValidatorFS] = []
        os.makedirs(root, exist_ok=True)

        # one genesis, n validators (sorted by address like make_genesis)
        pvs = []
        for i in range(n):
            pv = PrivValidatorFS(
                gen_priv_key_ed25519(f"{CHAIN_ID}-val-{i}".encode()), None
            )
            pvs.append(pv)
        pvs.sort(key=lambda pv: pv.get_address())
        self.pvs = pvs
        self.genesis = GenesisDoc(
            genesis_time_ns=time.time_ns(),
            chain_id=CHAIN_ID,
            validators=[
                GenesisValidator(pv.get_pub_key(), 10, f"v{i}")
                for i, pv in enumerate(pvs)
            ],
        )

    # -- boot ---------------------------------------------------------------

    def _make_config(self, idx: int, statesync_from: list[int] | None = None,
                     statesync_enable: bool = True):
        cfg = test_config()
        home = os.path.join(self.root, f"node{idx}")
        ensure_root(home, cfg)
        cfg.base.proxy_app = self.app
        cfg.base.moniker = f"chaos-{idx}"
        cfg.base.chain_id = CHAIN_ID
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.statesync.snapshot_interval = self.snapshot_interval
        if self.db_backend is not None:
            cfg.base.db_backend = self.db_backend
        if self.retain_blocks:
            cfg.pruning.retain_blocks = self.retain_blocks
            cfg.pruning.interval_heights = max(self.prune_interval, 1)
        if self.snapshot_chunk_size is not None:
            cfg.statesync.chunk_size = self.snapshot_chunk_size
        if self.snapshot_full_every is not None:
            cfg.statesync.snapshot_full_every = self.snapshot_full_every
        if self.snapshot_keep is not None:
            cfg.statesync.snapshot_keep_recent = self.snapshot_keep
        if self.height_throttle_s is not None:
            # production semantics: wait timeout_commit after each
            # commit before the next height (the preset skips it)
            cfg.consensus.timeout_commit = self.height_throttle_s
            cfg.consensus.skip_timeout_commit = False
        if self.gossip_dedup is not None:
            cfg.consensus.gossip_dedup = self.gossip_dedup
        if statesync_from:
            # statesync_enable=False configures the light-client
            # endpoints WITHOUT arming a boot-time restore — the
            # below-horizon runtime fallback (round 19) is what arms it
            cfg.base.fast_sync = True
            cfg.statesync.enable = statesync_enable
            cfg.statesync.rpc_servers = ",".join(
                f"127.0.0.1:{self.nodes[j].rpc_port()}" for j in statesync_from
            )
        gen = self.genesis
        fmt = self.commit_format_of.get(idx)
        if fmt is not None:
            gen = dataclasses.replace(gen, commit_format=fmt)
        gen.save_as(cfg.base.genesis_file())
        return cfg

    def _listener_port(self, j: int) -> int:
        return self.nodes[j].listener.internal_address().port

    def _seed_links(self, i: int, targets: list[int]) -> str:
        seeds = []
        for j in targets:
            link = self.fabric.add_link(
                i, j, ("127.0.0.1", self._listener_port(j))
            )
            seeds.append(link.laddr)
        return ",".join(seeds)

    def start_node(self, idx: int, pv: PrivValidatorFS | None,
                   statesync_from: list[int] | None = None,
                   dial: list[int] | None = None,
                   statesync_enable: bool = True) -> Node:
        cfg = self._make_config(
            idx, statesync_from=statesync_from,
            statesync_enable=statesync_enable,
        )
        if pv is not None:
            pv.file_path = cfg.base.priv_validator_file()
            pv.save()
        node = default_new_node(cfg)
        node.start()
        # dial earlier nodes through per-link proxies AFTER start (the
        # listener port exists once started; seeds at config time would
        # race the boot order anyway)
        targets = dial if dial is not None else list(range(len(self.nodes)))
        if targets:
            node.sw.dial_seeds(self._seed_links(idx, targets).split(","))
        self.nodes.append(node)
        return node

    def start(self) -> "ChaosNet":
        for i in range(self.n):
            self.start_node(i, self.pvs[i])
        return self

    # -- chaos verbs --------------------------------------------------------

    def partition(self, group_a) -> None:
        self.fabric.partition_groups(set(group_a))

    def heal(self) -> None:
        self.fabric.heal_all()

    def delay_node(self, idx: int, one_way_s: float,
                   asymmetric: bool = True) -> None:
        """Slow every link touching `idx`: inbound-direction traffic
        toward the node delayed, return path fast (asymmetric=True) or
        both ways (False)."""
        for (i, j), link in self.fabric.links().items():
            if idx not in (i, j):
                continue
            toward_j = one_way_s if j == idx else (0 if asymmetric else one_way_s)
            toward_i = one_way_s if i == idx else (0 if asymmetric else one_way_s)
            link.set_delay(c2s_s=toward_j, s2c_s=toward_i)

    def clear_delays(self) -> None:
        for link in self.fabric.links().values():
            link.set_delay(0, 0)

    # -- WAN tier (round 18) -------------------------------------------------

    # the test preset's 10x-shortened consensus timeouts (100 ms propose)
    # can NEVER cover an intercontinental link (40-90 ms per relayed
    # chunk): proposals always miss the window and rounds churn forever
    # with 1 ms deltas. Real WAN operators provision timeouts for RTT
    # (the production schedule is 3 s propose); applying a heavy profile
    # therefore also raises the live nodes' timeout schedule to a
    # WAN-shaped floor, and clear_wan restores the test preset. The
    # schedule is read per round from the shared config object, so the
    # mutation takes effect at the next round.
    _WAN_TIMEOUT_FLOOR = {
        "timeout_propose": 1.0, "timeout_propose_delta": 0.25,
        "timeout_prevote": 0.4, "timeout_prevote_delta": 0.2,
        "timeout_precommit": 0.4, "timeout_precommit_delta": 0.2,
    }

    def _wan_timeouts(self, on: bool) -> None:
        for node in self.nodes:
            ccfg = node.config.consensus
            if on:
                if not hasattr(ccfg, "_pre_wan_timeouts"):
                    ccfg._pre_wan_timeouts = {
                        k: getattr(ccfg, k) for k in self._WAN_TIMEOUT_FLOOR
                    }
                for k, floor in self._WAN_TIMEOUT_FLOOR.items():
                    setattr(ccfg, k, max(getattr(ccfg, k), floor))
            else:
                pre = getattr(ccfg, "_pre_wan_timeouts", None)
                if pre is not None:
                    for k, v in pre.items():
                        setattr(ccfg, k, v)

    @staticmethod
    def _is_heavy(profile) -> bool:
        from tendermint_tpu.ops.netfaults import wan_profile

        return profile is not None and wan_profile(profile).name != "lan"

    def apply_wan(self, profile, seed: int = 0) -> None:
        """One named WAN profile (ops/netfaults.WAN_PROFILES) across
        every link; per-link latencies still differ (seeded sample).
        Heavy profiles also raise the consensus timeout schedule to the
        WAN floor (see _WAN_TIMEOUT_FLOOR)."""
        self.fabric.apply_wan(profile, seed=seed)
        self._wan_timeouts(self._is_heavy(profile))

    def apply_geo_clusters(self, clusters=None, k: int = 2,
                           intra="lan", inter="intercontinental",
                           seed: int = 0) -> list[list[int]]:
        """Geo-cluster topology declared as data: "k clusters x m
        nodes" — low intra-cluster latency, high inter-cluster. Returns
        the cluster lists actually applied."""
        if clusters is None:
            clusters = geo_clusters(self.n, k)
        self.fabric.apply_geo(clusters, intra=intra, inter=inter, seed=seed)
        self._wan_timeouts(self._is_heavy(inter) or self._is_heavy(intra))
        return clusters

    def clear_wan(self) -> None:
        self.fabric.clear_wan()
        self._wan_timeouts(False)

    # -- rolling restart (round 18) ------------------------------------------

    def restart_node(self, idx: int, statesync_from: list[int] | None = None,
                     wipe: bool = False) -> Node:
        """Stop node idx and boot it again — same home (a plain restart)
        or wiped + statesync (the rolling-upgrade cold-replace arm). The
        fabric's inbound links retarget to the fresh listener port so
        the other nodes' persistent reconnect loops re-peer on their
        own; the restarted node re-dials its earlier peers through the
        SAME links (WAN profiles / delays riding them stay armed)."""
        old = self.nodes[idx]
        try:
            old.stop()
        except Exception:  # noqa: BLE001 — teardown best effort
            pass
        for link in self.fabric.links_of(idx):
            link.drop_all()
        if wipe:
            shutil.rmtree(os.path.join(self.root, f"node{idx}"),
                          ignore_errors=True)
        cfg = self._make_config(idx, statesync_from=statesync_from)
        pv = self.pvs[idx] if idx < len(self.pvs) else None
        if pv is not None:
            pv.file_path = cfg.base.priv_validator_file()
            pv.save()
        node = default_new_node(cfg)
        node.start()
        self.nodes[idx] = node
        if any(
            (link.wan_profile_name() or "lan") != "lan"
            for link in self.fabric.links().values()
        ):
            # the replacement boots with the test preset's tight
            # timeouts; if the net is WAN-shaped it needs the floor too
            self._wan_timeouts(True)
        port = self._listener_port(idx)
        seeds = []
        for (i, j), link in self.fabric.links().items():
            if j == idx:
                link.retarget(("127.0.0.1", port))
            elif i == idx:
                seeds.append(link.laddr)
        if seeds:
            node.sw.dial_seeds(seeds)
        return node

    # -- soak instrumentation (round 18) -------------------------------------

    @staticmethod
    def rss_kb() -> int:
        """This process's resident set (VmRSS), in KiB."""
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
        raise RuntimeError("no VmRSS in /proc/self/status")

    def disk_bytes(self) -> int:
        """Total bytes under every node home (WALs, stores, snapshots)."""
        total = 0
        for dirpath, _dirs, files in os.walk(self.root):
            for fn in files:
                try:
                    total += os.path.getsize(os.path.join(dirpath, fn))
                except OSError:
                    pass
        return total

    def flight_dump_counts(self) -> list[int]:
        """Per-node flight-recorder auto-dump episode counts — the
        healthy-soak quietness assert (round 17's recorder)."""
        return [n.flightrec.stats()["dumps"] for n in self.nodes]

    def churn_listener(self, idx: int, down_s: float = 0.5) -> None:
        """The peer-churn arm: kill node idx's listener, reset every
        connection it has (both directions via its links), then restart
        the listener on the SAME port and let persistent dials re-peer."""
        node = self.nodes[idx]
        port = node.listener.internal_address().port
        node.listener.stop()
        for link in self.fabric.links_of(idx):
            link.drop_all()
        for peer in node.sw.peers.list():
            node.sw.stop_peer_for_error(peer, "chaos: listener churn")
        time.sleep(down_s)
        from tendermint_tpu.p2p.listener import Listener

        # the dead listener's port re-binds (SO_REUSEADDR) so the
        # fabric's links keep pointing at it and healing is automatic —
        # but lingering accepted-socket teardown can hold the addr for a
        # beat, so retry the bind briefly
        lst = None
        for _ in range(100):
            try:
                lst = Listener(f"127.0.0.1:{port}")
                break
            except OSError:
                time.sleep(0.1)
        if lst is None:
            raise OSError(f"could not re-bind churned listener port {port}")
        node.listener = lst
        node.sw.start_listener(lst)

    # -- convergence assertions ---------------------------------------------

    def heights(self) -> list[int]:
        return [n.block_store.height() for n in self.nodes]

    def wait_height(self, h: int, timeout: float = 120.0,
                    nodes: list[int] | None = None) -> bool:
        idxs = nodes if nodes is not None else range(len(self.nodes))
        return wait_until(
            lambda: all(self.nodes[i].block_store.height() >= h for i in idxs),
            timeout=timeout,
            tick=0.1,
        )

    def fingerprints(self, upto: int, node_idx: int,
                     from_height: int = 1) -> list[tuple]:
        """(height, block hash, part-set root, app hash) per height —
        the byte-identity surface the soaks assert on. `from_height`
        starts above 1 on pruned/restored stores (round 19), where
        heights below base() are legitimately absent."""
        node = self.nodes[node_idx]
        out = []
        for h in range(from_height, upto + 1):
            meta = node.block_store.load_block_meta(h)
            block = node.block_store.load_block(h)
            out.append(
                (
                    h,
                    meta.block_id.hash.hex(),
                    meta.block_id.parts_header.hash.hex(),
                    block.header.app_hash.hex(),
                    block.header.evidence_hash.hex(),
                )
            )
        return out

    def assert_converged(self, upto: int, nodes: list[int] | None = None,
                         from_height: int | None = None) -> None:
        """Byte-identity across `nodes` for heights [from_height, upto].
        from_height=None compares from the HIGHEST base among the nodes
        (round 19: pruned/restored stores legitimately hold different
        prefixes; what they share must still be byte-identical)."""
        idxs = list(nodes if nodes is not None else range(len(self.nodes)))
        if from_height is None:
            from_height = max(
                max(self.nodes[i].block_store.base(), 1) for i in idxs
            )
        want = self.fingerprints(upto, idxs[0], from_height=from_height)
        for i in idxs[1:]:
            got = self.fingerprints(upto, i, from_height=from_height)
            assert got == want, (
                f"node {i} diverges from node {idxs[0]} in heights "
                f"{from_height}..{upto}:\n{set(want) ^ set(got)}"
            )

    def broadcast_tx(self, tx: bytes, via: int = 0) -> None:
        self.nodes[via].mempool.check_tx(tx)

    def stop(self) -> None:
        for node in self.nodes:
            try:
                node.stop()
            except Exception:  # noqa: BLE001 — teardown best effort
                pass
        self.fabric.stop()
        shutil.rmtree(self.root, ignore_errors=True)


# -- the hostile-but-fluent peer family (round 18 adversary catalog) ----------


class HostilePeer:
    """Protocol-fluent adversary base: dials a node over the REAL
    encrypted transport (TCP -> SecretConnection -> NodeInfo handshake
    -> MConnection) and is admitted as an ordinary peer; it never runs
    a consensus state of its own. Subclasses are the adversary catalog
    (docs/netchaos.md): vote injection, mempool flooding, oversized
    frames, eclipse identities, frame corruption.

    `corrupt_prob` wires the p2p/fuzz.py FuzzedStream UNDER the
    SecretConnection — the frame-corruption peer: a seeded fraction of
    this adversary's encrypted frames arrive tampered, which the
    target's AEAD must flag loudly (auth failure + peer dropped)."""

    moniker = "hostile"

    def __init__(self, target_host: str, target_port: int, chain_id: str,
                 corrupt_prob: float = 0.0, corrupt_seed: int = 7,
                 commit_format: str = "full", key=None):
        from tendermint_tpu.blockchain.reactor import BLOCKCHAIN_CHANNEL
        from tendermint_tpu.consensus.reactor import (
            DATA_CHANNEL,
            STATE_CHANNEL,
            VOTE_CHANNEL,
            VOTE_SET_BITS_CHANNEL,
        )
        from tendermint_tpu.mempool.reactor import MEMPOOL_CHANNEL
        from tendermint_tpu.p2p.conn import ChannelDescriptor, MConnection
        from tendermint_tpu.p2p.fuzz import FuzzedStream
        from tendermint_tpu.p2p.node_info import NodeInfo, default_version
        from tendermint_tpu.p2p.peer import exchange_node_info
        from tendermint_tpu.p2p.secret_connection import SecretConnection
        from tendermint_tpu.p2p.stream import SocketStream
        from tendermint_tpu.statesync.reactor import STATESYNC_CHANNEL
        from tendermint_tpu.version import VERSION

        self.vote_channel = VOTE_CHANNEL
        self.mempool_channel = MEMPOOL_CHANNEL
        # every channel the node's reactors gossip on: an unknown inbound
        # channel is a fatal mconn error, and the consensus/mempool
        # reactors start pushing to a fresh peer immediately
        channels = (
            STATE_CHANNEL, DATA_CHANNEL, VOTE_CHANNEL, VOTE_SET_BITS_CHANNEL,
            MEMPOOL_CHANNEL, BLOCKCHAIN_CHANNEL, STATESYNC_CHANNEL,
        )
        sock = socket.create_connection((target_host, target_port), timeout=10)
        self._key = key if key is not None else gen_priv_key_ed25519()
        stream = SocketStream(sock)
        self.fuzz = None
        if corrupt_prob > 0:
            # handshake CLEAN (a corrupted key exchange would just fail
            # admission), then arm corruption once the mconn runs — the
            # adversary is a fluent peer whose frames tamper in flight
            stream = FuzzedStream(stream, prob_corrupt=0.0,
                                  seed=corrupt_seed)
            self.fuzz = stream
        self.conn = SecretConnection(stream, self._key)
        info = NodeInfo(
            pub_key=self._key.pub_key(),
            moniker=self.moniker,
            network=chain_id,
            version=default_version(VERSION),
            other=[f"commit_format={commit_format}"],
        )
        info.channels = bytes(channels)
        self.remote_info = exchange_node_info(self.conn, info, timeout=10)
        self._err: list = []
        self.mconn = MConnection(
            self.conn,
            [ChannelDescriptor(id=c, priority=5) for c in channels],
            # round 19: subclasses that TALK BACK (the adversarial
            # statesync offerers) override _on_receive; the base peer
            # stays deaf like before
            on_receive=self._on_receive,
            on_error=self._err.append,
        )
        self.mconn.start()
        if self.fuzz is not None:
            self.fuzz.prob_corrupt = corrupt_prob

    def _on_receive(self, ch_id: int, msg_bytes: bytes) -> None:
        """Inbound messages from the target; base adversaries ignore
        them (runs on the mconn recv thread — overrides must not block)."""

    def send_msg(self, ch_id: int, payload: bytes) -> bool:
        return self.mconn.send(ch_id, payload)

    def errors(self) -> list:
        return list(self._err)

    def dropped(self) -> bool:
        """Did the target (or the wire) kill this adversary's link?"""
        return bool(self._err) or not self.mconn.is_running()

    def close(self) -> None:
        try:
            self.mconn.stop()
        except Exception:  # noqa: BLE001 — teardown best effort
            pass
        try:
            self.conn.close()
        except Exception:  # noqa: BLE001
            pass


class VoteInjector(HostilePeer):
    """Pushes crafted consensus votes — the double-signer of the
    byzantine scenario."""

    moniker = "byz-injector"

    def send_vote(self, vote) -> bool:
        from tendermint_tpu.consensus import messages as msgs
        from tendermint_tpu.consensus.reactor import _enc

        return self.send_msg(self.vote_channel, _enc(msgs.VoteMessage(vote)))


class MempoolFlooder(HostilePeer):
    """Floods the target's mempool over the gossip channel: garbage
    signed-shaped txs (structurally parseable, signatures junk — shed
    at the batched sig gate without ever reaching the app) and
    valid-but-duplicate txs (shed at the dedup cache). The scenario
    asserts consensus liveness stays flat while the flood is shed and
    visible in p2p_adversary_flood_txs_rejected."""

    moniker = "mempool-flooder"

    @staticmethod
    def _encode_tx(tx: bytes) -> bytes:
        # the REAL gossip envelope: the flood must exercise the sig
        # gate, not the unknown-message reject path
        from tendermint_tpu.mempool.reactor import _encode_tx

        return _encode_tx(tx)

    def flood_garbage(self, n: int, payload_size: int = 24,
                      seed: int = 1) -> int:
        """n unique garbage txs shaped like signedkv envelopes
        (32B pubkey + 64B sig + payload) whose signatures are noise;
        returns how many were handed to the wire."""
        import random as _random

        rng = _random.Random(seed)
        sent = 0
        for i in range(n):
            tx = rng.randbytes(96) + b"flood-%d-" % i + rng.randbytes(
                payload_size
            )
            if self.send_msg(self.mempool_channel, self._encode_tx(tx)):
                sent += 1
        return sent

    def flood_duplicates(self, tx: bytes, n: int) -> int:
        """The same VALID tx n times — every copy past the first is
        dedup-cache shed on the target."""
        sent = 0
        for _ in range(n):
            if self.send_msg(self.mempool_channel, self._encode_tx(tx)):
                sent += 1
        return sent


class OversizedFramePeer(HostilePeer):
    """Streams one message past a channel's recv ceiling: the target
    must error the reassembly at the right-sized bound (round-18 caps)
    and drop this peer for cause."""

    moniker = "oversized-framer"

    def send_oversized(self, total_bytes: int = 200_000) -> bool:
        # the mconn send side chops any length; the TARGET's vote
        # channel caps reassembly at 64 KiB and must kill the link
        return self.send_msg(self.vote_channel, b"\x00" * total_bytes)


class HostileOfferer(HostilePeer):
    """Adversarial statesync offerer (round 19 adversary catalog):
    answers the target's snapshot discovery with an offer and then
    attacks the restore path per `mode`:

      "forged"  — serves a manifest whose header/app hashes contradict
                  the light-verified chain (internally consistent, so it
                  passes decode; the binding check proves the lie);
      "corrupt" — offers a REAL snapshot but serves chunks whose bytes
                  are flipped (the digest batch proves it);
      "stall"   — answers discovery and the manifest, serves
                  `stall_after` chunks, then goes silent mid-transfer.

    The target must ban each kind (statesync_offerer_bans_* counters)
    and complete its restore from the honest offerers. Construction:
    attack state is set BEFORE super().__init__ because the mconn recv
    thread (which drives _on_receive) starts inside it."""

    moniker = "hostile-offerer"

    def __init__(self, target_host: str, target_port: int, chain_id: str,
                 manifest_json: dict, chunks: list[bytes] | None = None,
                 mode: str = "forged", stall_after: int = 1, **kw):
        assert mode in ("forged", "corrupt", "stall")
        self.manifest_json = manifest_json
        self.chunks = list(chunks or [])
        self.mode = mode
        self.stall_after = stall_after
        self.chunks_answered = 0
        self.requests_seen: list[str] = []
        super().__init__(target_host, target_port, chain_id, **kw)

    def _send_statesync(self, obj: dict) -> None:
        import json as _json

        from tendermint_tpu.statesync.reactor import STATESYNC_CHANNEL

        self.send_msg(
            STATESYNC_CHANNEL, _json.dumps(obj, sort_keys=True).encode()
        )

    def _lite(self) -> dict:
        m = self.manifest_json
        lite = {
            "format": m["format"], "height": m["height"],
            "chain_id": m["chain_id"], "chunks": m["chunks"],
            "total_bytes": m["total_bytes"], "root": m["root"],
            "header_hash": m["header_hash"],
            "kind": m.get("kind", "full"),
        }
        if lite["kind"] == "delta":
            lite["base_height"] = m["base_height"]
        return lite

    def _on_receive(self, ch_id: int, msg_bytes: bytes) -> None:
        import json as _json

        from tendermint_tpu.statesync.reactor import STATESYNC_CHANNEL

        if ch_id != STATESYNC_CHANNEL:
            return
        try:
            msg = _json.loads(msg_bytes.decode())
            mtype = msg.get("type")
        except (ValueError, UnicodeDecodeError):
            return
        self.requests_seen.append(str(mtype))
        if mtype == "snapshots_request":
            self._send_statesync(
                {"type": "snapshots_response", "snapshots": [self._lite()]}
            )
        elif mtype == "manifest_request":
            if msg.get("height") == self.manifest_json["height"]:
                self._send_statesync(
                    {"type": "manifest_response",
                     "manifest": self.manifest_json}
                )
        elif mtype == "chunk_request":
            if msg.get("height") != self.manifest_json["height"]:
                return
            if self.mode == "stall" and self.chunks_answered >= self.stall_after:
                return  # mid-transfer silence — the attack
            i = msg.get("index", 0)
            if not isinstance(i, int) or not 0 <= i < len(self.chunks):
                return
            payload = self.chunks[i]
            if self.mode == "corrupt" and payload:
                payload = bytes([payload[0] ^ 0xFF]) + payload[1:]
            self.chunks_answered += 1
            self._send_statesync({
                "type": "chunk_response",
                "height": self.manifest_json["height"],
                "index": i,
                "chunk": payload.hex().upper(),
            })


def forged_manifest_json(honest_manifest, height: int, seed: int = 5) -> dict:
    """A DECODE-VALID manifest at `height` that contradicts the verified
    chain: chunk digests and root are internally consistent (one garbage
    chunk), but header/app hashes are noise — the light-client binding
    check is the only gate that can catch it, by proving the server
    lied. Returns (manifest_json); pair it with HostileOfferer(mode=
    "forged")."""
    import random as _random

    from tendermint_tpu.statesync.snapshot import Manifest, chunk_digest

    rng = _random.Random(seed)
    garbage = rng.randbytes(512)
    m = Manifest(
        height=height,
        chain_id=honest_manifest.chain_id,
        chunk_size=honest_manifest.chunk_size,
        total_bytes=len(garbage),
        chunk_digests=[chunk_digest(garbage)],
        header_hash=rng.randbytes(20),
        app_hash=rng.randbytes(20),
        seen_commit=honest_manifest.seen_commit,
    )
    return m.to_json()


def hostile_offerer_matrix(target_host: str, target_port: int,
                           chain_id: str, honest_manifest,
                           chunks: list[bytes],
                           stall_after: int = 0) -> dict[str, HostileOfferer]:
    """The full three-kind adversarial offerer burst against one
    target: a FORGED manifest one height above the honest snapshot
    (the picker takes max, so it is exercised first and its light walk
    succeeds while the binding check proves the lie), a CORRUPT-chunk
    offerer and a STALLING offerer both pinned at the honest height.
    Shared by the netchaos scenario and benches/bench_retention.py —
    callers also arm the TENDERMINT_STATESYNC_{WINDOW,CHUNK_TIMEOUT_S,
    STALL_BAN,DISCOVERY_S} knobs for their timing budget, and must
    close() every offerer."""
    return {
        "forged": HostileOfferer(
            target_host, target_port, chain_id,
            forged_manifest_json(honest_manifest,
                                 honest_manifest.height + 1),
        ),
        "corrupt": HostileOfferer(
            target_host, target_port, chain_id, honest_manifest.to_json(),
            chunks=chunks, mode="corrupt",
        ),
        "stall": HostileOfferer(
            target_host, target_port, chain_id, honest_manifest.to_json(),
            chunks=chunks, mode="stall", stall_after=stall_after,
        ),
    }


def slow_loris_handshake(target_host: str, target_port: int,
                         byte_interval_s: float = 0.4,
                         max_s: float = 60.0) -> float | None:
    """The slow-loris adversary: connect and dribble one random byte at
    a time into the secret-connection handshake, never completing it.
    Returns seconds until the TARGET closed the socket (its handshake
    deadline firing), or None if it tolerated the loris for max_s —
    the failure the scenario asserts against."""
    import random as _random

    rng = _random.Random(11)  # deterministic dribble
    sock = socket.create_connection((target_host, target_port), timeout=10)
    sock.settimeout(byte_interval_s)
    t0 = time.monotonic()
    try:
        while time.monotonic() - t0 < max_s:
            try:
                sock.sendall(rng.randbytes(1))
            except OSError:
                return time.monotonic() - t0
            try:
                if sock.recv(4096) == b"":
                    return time.monotonic() - t0
            except socket.timeout:
                continue
            except OSError:
                return time.monotonic() - t0
        return None
    finally:
        try:
            sock.close()
        except OSError:
            pass


def eclipse_dials(target_host: str, target_port: int, chain_id: str,
                  n: int) -> tuple[list[HostilePeer], int]:
    """The eclipse adversary: n distinct identities (fresh Ed25519 keys)
    dialed from ONE address range (loopback — exactly the shape
    IPRangeCounter dampens). Returns (admitted peers, refused count);
    the caller closes the admitted ones."""
    admitted: list[HostilePeer] = []
    refused = 0
    for i in range(n):
        try:
            admitted.append(
                HostilePeer(target_host, target_port, chain_id,
                            key=gen_priv_key_ed25519(
                                f"{chain_id}-eclipse-{i}".encode()))
            )
        except Exception:  # noqa: BLE001 — refusal shapes vary (reset,
            # EOF mid-handshake, timeout): all count as the dial shed
            refused += 1
    return admitted, refused


def make_conflicting_votes(pv, validators, height: int, round_: int,
                           chain_id: str):
    """Two signed prevotes by `pv` for the same (height, round) naming
    different blocks — the raw material of DuplicateVoteEvidence (the
    signer bypasses the privval double-sign guard exactly like
    test_byzantine.ByzantinePrivValidator: a real byzantine key holder
    is not running our guard)."""
    from tendermint_tpu.types import BlockID, PartSetHeader
    from tendermint_tpu.types.vote import VOTE_TYPE_PREVOTE, Vote

    idx, _ = validators.get_by_address(pv.get_address())
    votes = []
    for fill in (0xAA, 0xCC):
        vote = Vote(
            validator_address=pv.get_address(),
            validator_index=idx,
            height=height,
            round_=round_,
            type_=VOTE_TYPE_PREVOTE,
            block_id=BlockID(
                bytes([fill]) * 20, PartSetHeader(1, bytes([fill ^ 0xFF]) * 20)
            ),
        )
        votes.append(
            vote.with_signature(pv.priv_key.sign(vote.sign_bytes(chain_id)))
        )
    return votes
