"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths
compile and execute without TPU hardware. Must run before jax is imported
anywhere in the test process.
"""

import os

# Tests must be hermetic: never route the default Verifier through a
# production device daemon that happens to be serving on this box
# (tendermint_tpu/devd.py) — unconditionally, since the operator may have
# TENDERMINT_DEVD_SOCK exported. test_devd.py points at its own socket
# per-test with monkeypatch.
os.environ["TENDERMINT_DEVD_SOCK"] = "/nonexistent/devd.sock"
# Bounded platform resolution (ops/gateway.resolve_platform): tests are
# CPU-only, so pin the answer rather than paying a 45s subprocess probe
# per test process (the env override is consulted first).
os.environ["TENDERMINT_TPU_PLATFORM"] = "cpu"

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment's TPU-tunnel plugin re-forces jax_platforms="axon,cpu" at
# interpreter startup, overriding the JAX_PLATFORMS env var — which makes
# every jax.devices() call dial the TPU even in CPU-only tests (and hang
# hard if the tunnel is unavailable). Win the override war: the config
# update below happens before any backend is initialized.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# The ed25519 ladder takes ~45s/bucket to compile on the CPU backend;
# persist compiled artifacts across test runs.
from tendermint_tpu.jitcache import enable as _enable_jit_cache  # noqa: E402

_enable_jit_cache()


# Round 12 closed the `cryptography` dependency hole: every transport/
# key primitive is in-repo (crypto/x25519, crypto/chacha20poly1305, pure
# secp256k1), so NO test may ever again skip — or fail collection —
# because a crypto backend is missing. The only sanctioned mentions are
# the explicitly-labeled parity-oracle skips (cross-checks that NEED the
# optional package to have something to compare against).
_ILLEGAL_CRYPTO_SKIPS: list = []


def pytest_runtest_logreport(report):
    if not report.skipped:
        return
    reason = (
        report.longrepr[2]
        if isinstance(report.longrepr, tuple)
        else str(report.longrepr)
    )
    low = reason.lower()
    if ("cryptography" in low or "libcrypto" in low) and \
            "parity oracle" not in low and "oracle" not in low:
        _ILLEGAL_CRYPTO_SKIPS.append((report.nodeid, reason))


def pytest_sessionfinish(session, exitstatus):
    if _ILLEGAL_CRYPTO_SKIPS:
        import pytest as _pytest

        raise _pytest.UsageError(
            "tests skipped for a missing crypto backend — the round-12 "
            "in-repo transport contract forbids this (mark genuine "
            "cross-check skips with 'parity oracle' in the reason): "
            + "; ".join(f"{nid}: {r}" for nid, r in _ILLEGAL_CRYPTO_SKIPS)
        )


def pytest_collection_modifyitems(config, items):
    """Deselect slow-marked tests on whole-suite runs (keeps the default
    `pytest tests/` under a minute), but honor an explicit -m expression
    or a test named by node id — unlike an addopts `-m "not slow"`, which
    would silently deselect even a directly requested slow test."""
    if config.option.markexpr:
        return
    if any("::" in a for a in config.args):
        return
    slow = [i for i in items if "slow" in i.keywords]
    if slow:
        config.hook.pytest_deselected(items=slow)
        items[:] = [i for i in items if "slow" not in i.keywords]
