"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths
compile and execute without TPU hardware. Must run before jax is imported
anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The ed25519 ladder programs take minutes to compile on the CPU backend;
# persist compiled artifacts across test runs.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(os.path.dirname(__file__), "..", ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")

# The environment's TPU-tunnel plugin re-forces jax_platforms="axon,cpu" at
# interpreter startup, overriding the JAX_PLATFORMS env var — which makes
# every jax.devices() call dial the TPU even in CPU-only tests (and hang
# hard if the tunnel is unavailable). Win the override war: the config
# update below happens before any backend is initialized.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    # CPU-backend persistent caching needs the XLA-level caches enabled too
    jax.config.update("jax_persistent_cache_enable_xla_caches", "all")
except Exception:
    pass
