"""p2p layer tests (reference test models: p2p/switch_test.go,
connection_test.go, secret_connection_test.go, addrbook_test.go,
pex_reactor_test.go)."""

import threading
import time

import pytest

from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
from tendermint_tpu.p2p import (
    ChannelDescriptor,
    MConnection,
    NetAddress,
    NodeInfo,
    Reactor,
    Switch,
    connect2_switches,
    make_connected_switches,
)
from tendermint_tpu.p2p.addrbook import AddrBook
from tendermint_tpu.p2p.node_info import default_version
from tendermint_tpu.p2p.secret_connection import SecretConnection
from tendermint_tpu.p2p.stream import pipe_pair


def wait_until(cond, timeout=5.0, tick=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(tick)
    return cond()


# -- netaddress ---------------------------------------------------------------


def test_netaddress_parse_and_classify():
    a = NetAddress.from_string("127.0.0.1:26656")
    assert a.ip == "127.0.0.1" and a.port == 26656
    assert a.valid() and a.local() and not a.routable()
    assert NetAddress("8.8.8.8", 53).routable()
    assert not NetAddress("10.0.0.1", 80).routable()
    assert not NetAddress("notanip", 80).valid()
    with pytest.raises(ValueError):
        NetAddress.from_string("nocolon")
    assert NetAddress("8.8.8.8", 53).same_network(NetAddress("8.8.4.4", 99))


# -- secret connection --------------------------------------------------------


def test_secret_connection_roundtrip():
    a, b = pipe_pair()
    ka, kb = gen_priv_key_ed25519(), gen_priv_key_ed25519()
    out = {}

    def srv():
        out["conn"] = SecretConnection(b, kb)

    t = threading.Thread(target=srv, daemon=True)
    t.start()
    ca = SecretConnection(a, ka)
    t.join(5)
    cb = out["conn"]
    assert ca.remote_pubkey().raw == kb.pub_key().raw
    assert cb.remote_pubkey().raw == ka.pub_key().raw

    # large payload crosses frame boundaries
    payload = bytes(range(256)) * 20  # 5120 bytes > 1024 frame
    ca.write(payload)
    got = bytearray()
    while len(got) < len(payload):
        got += cb.read(4096)
    assert bytes(got) == payload
    # and the other direction
    cb.write(b"pong")
    assert ca.read(10) == b"pong"
    ca.close()


def test_secret_connection_tampering_detected():
    a, b = pipe_pair()
    ka, kb = gen_priv_key_ed25519(), gen_priv_key_ed25519()
    out = {}
    t = threading.Thread(
        target=lambda: out.update(conn=SecretConnection(b, kb)), daemon=True
    )
    t.start()
    ca = SecretConnection(a, ka)
    t.join(5)
    # corrupt a ciphertext frame on the raw stream underneath: tampering
    # must RAISE (round 12) — the old b"" return read as a graceful peer
    # hangup, hiding an active attack as EOF
    from tendermint_tpu.p2p.secret_connection import SecretConnectionError

    ca.stream.write(b"\x00\x20" + b"\x00" * 32)
    with pytest.raises(SecretConnectionError):
        out["conn"].read(10)
    # and the connection stays poisoned: every later read raises too
    with pytest.raises(SecretConnectionError):
        out["conn"].read(1)
    ca.close()


# -- mconnection --------------------------------------------------------------


def _mconn_pair(descs=None, **cfg_kw):
    from tendermint_tpu.p2p.conn import MConnConfig

    descs = descs or [ChannelDescriptor(id=0x01, priority=1)]
    a, b = pipe_pair()
    recv_a, recv_b = [], []
    err = []
    cfg = MConnConfig(**cfg_kw)
    ma = MConnection(a, descs, lambda ch, m: recv_a.append((ch, m)), lambda e: err.append(e), cfg)
    mb = MConnection(b, descs, lambda ch, m: recv_b.append((ch, m)), lambda e: err.append(e), cfg)
    ma.start()
    mb.start()
    return ma, mb, recv_a, recv_b, err


def test_mconnection_send_recv_multipacket():
    ma, mb, recv_a, recv_b, _ = _mconn_pair()
    msg = b"x" * 5000  # > 4 packets
    assert ma.send(0x01, msg)
    assert wait_until(lambda: recv_b and recv_b[0] == (0x01, msg))
    assert mb.send(0x01, b"reply")
    assert wait_until(lambda: recv_a and recv_a[0] == (0x01, b"reply"))
    ma.stop()
    mb.stop()


def test_mconnection_unknown_channel_refused():
    ma, mb, *_ = _mconn_pair()
    assert not ma.send(0x99, b"nope")
    assert not ma.try_send(0x99, b"nope")
    ma.stop()
    mb.stop()


def test_mconnection_ping_pong_keeps_alive():
    ma, mb, _, recv_b, err = _mconn_pair(ping_interval=0.05, pong_timeout=1.0)
    time.sleep(0.4)  # several ping cycles
    assert not err
    assert ma.send(0x01, b"still here")
    assert wait_until(lambda: recv_b)
    ma.stop()
    mb.stop()


def test_mconnection_peer_close_fires_on_error():
    ma, mb, _, _, err = _mconn_pair()
    mb.stream.close()
    assert wait_until(lambda: err)
    ma.stop()
    mb.stop()


def test_mconnection_priority_fairness():
    """High-priority channel data is not starved by a bulk channel."""
    descs = [
        ChannelDescriptor(id=0x01, priority=1, send_queue_capacity=100),
        ChannelDescriptor(id=0x02, priority=10, send_queue_capacity=100),
    ]
    ma, mb, _, recv_b, _ = _mconn_pair(descs)
    for _ in range(50):
        ma.try_send(0x01, b"bulk" * 256)
    ma.try_send(0x02, b"urgent")
    assert wait_until(
        lambda: any(ch == 0x02 for ch, _ in recv_b), timeout=10
    )
    ma.stop()
    mb.stop()


# -- switch -------------------------------------------------------------------


class EchoReactor(Reactor):
    """Records messages; replies on the same channel when asked."""

    def __init__(self, ch_id=0x05):
        self.ch_id = ch_id
        self.received = []
        self.peers = []

    def start(self):
        pass

    def stop(self):
        pass

    def get_channels(self):
        return [ChannelDescriptor(id=self.ch_id, priority=1, send_queue_capacity=32)]

    def add_peer(self, peer):
        self.peers.append(peer)

    def remove_peer(self, peer, reason):
        if peer in self.peers:
            self.peers.remove(peer)

    def receive(self, ch_id, peer, msg):
        self.received.append((peer.id(), msg))


def _make_net(n):
    reactors = []

    def init(i, sw):
        r = EchoReactor()
        reactors.append(r)
        sw.add_reactor("echo", r)
        return sw

    return make_connected_switches(n, init), reactors


def test_switch_broadcast_reaches_all_peers():
    sws, reactors = _make_net(3)
    try:
        sws[0].broadcast(0x05, b"fan-out")
        assert wait_until(lambda: len(reactors[1].received) == 1)
        assert wait_until(lambda: len(reactors[2].received) == 1)
        assert reactors[1].received[0][1] == b"fan-out"
    finally:
        for sw in sws:
            sw.stop()


def test_switch_refuses_self_and_duplicate_connections():
    sws, _ = _make_net(2)
    try:
        with pytest.raises(ConnectionError):
            connect2_switches(sws, 0, 1)  # duplicate peering
    finally:
        for sw in sws:
            sw.stop()


def test_switch_incompatible_network_rejected():
    def init_a(i, sw):
        sw.add_reactor("echo", EchoReactor())
        return sw

    sw_a, sw_b = Switch(), Switch()
    sw_a.add_reactor("echo", EchoReactor())
    sw_b.add_reactor("echo", EchoReactor())
    for sw, net in ((sw_a, "chain-A"), (sw_b, "chain-B")):
        sw.set_node_info(
            NodeInfo(
                pub_key=sw.node_priv_key.pub_key(),
                moniker="m",
                network=net,
                version=default_version("0.1.0"),
            )
        )
        sw.start()
    try:
        with pytest.raises(ConnectionError, match="network mismatch"):
            connect2_switches([sw_a, sw_b], 0, 1)
        assert sw_a.peers.size() == 0 and sw_b.peers.size() == 0
    finally:
        sw_a.stop()
        sw_b.stop()


def test_switch_stop_peer_for_error_removes_from_reactors():
    sws, reactors = _make_net(2)
    try:
        peer = sws[0].peers.list()[0]
        sws[0].stop_peer_for_error(peer, "test")
        assert sws[0].peers.size() == 0
        assert peer not in reactors[0].peers
        # remote side notices the close too
        assert wait_until(lambda: sws[1].peers.size() == 0)
    finally:
        for sw in sws:
            sw.stop()


def test_switch_tcp_listener_end_to_end():
    from tendermint_tpu.p2p.listener import Listener

    sw_a, sw_b = Switch(), Switch()
    ra, rb = EchoReactor(), EchoReactor()
    sw_a.add_reactor("echo", ra)
    sw_b.add_reactor("echo", rb)
    lst = Listener("127.0.0.1:0")
    sw_a.add_listener(lst)
    sw_a.start()
    sw_b.start()
    try:
        addr = lst.internal_address()
        peer = sw_b.dial_peer_with_address(NetAddress("127.0.0.1", addr.port))
        assert wait_until(lambda: sw_a.peers.size() == 1)
        peer.send(0x05, b"over tcp")
        assert wait_until(lambda: ra.received and ra.received[0][1] == b"over tcp")
    finally:
        sw_a.stop()
        sw_b.stop()


def test_inbound_ip_range_count_released_on_peer_removal():
    """Regression (round 12, caught by the real-TCP chaos tier): the
    inbound IP-range count is taken on the RAW socket stream, which peer
    admission wraps in a SecretConnection — removal must UNcount through
    the wrapper chain, or 16 inbound churn cycles from one /24 (any
    loopback testnet) permanently exhaust the accept budget."""
    from tendermint_tpu.p2p.listener import Listener

    sw_a, sw_b = Switch(), Switch()
    sw_a.add_reactor("echo", EchoReactor())
    sw_b.add_reactor("echo", EchoReactor())
    lst = Listener("127.0.0.1:0")
    sw_a.add_listener(lst)
    sw_a.start()
    sw_b.start()
    try:
        port = lst.internal_address().port
        for _ in range(3):
            sw_b.dial_peer_with_address(NetAddress("127.0.0.1", port))
            assert wait_until(lambda: sw_a.peers.size() == 1)
            assert sw_a.ip_ranges.count("127") == 1
            sw_a.stop_peer_for_error(sw_a.peers.list()[0], "churn")
            assert wait_until(lambda: sw_b.peers.size() == 0)
            # the count must drop with the peer — this leaked pre-round-12
            assert wait_until(lambda: sw_a.ip_ranges.count("127") == 0)
    finally:
        sw_a.stop()
        sw_b.stop()


# -- addrbook -----------------------------------------------------------------


def test_addrbook_add_pick_good(tmp_path):
    book = AddrBook(str(tmp_path / "addrbook.json"))
    src = NetAddress("1.2.3.4", 26656)
    for i in range(50):
        assert book.add_address(NetAddress(f"5.6.{i}.1", 26656), src) or True
    assert book.size() > 0
    picked = book.pick_address()
    assert picked is not None
    book.mark_good(picked)
    # non-routable rejected in strict mode
    assert not book.add_address(NetAddress("192.168.1.1", 26656), src)
    book.save()

    book2 = AddrBook(str(tmp_path / "addrbook.json"))
    assert book2.size() == book.size()
    assert any(str(picked) == str(ka.addr) and ka.is_old()
               for ka in book2._addrs.values())


def test_addrbook_selection_and_removal():
    book = AddrBook("", routability_strict=False)
    src = NetAddress("127.0.0.1", 1)
    for i in range(20):
        book.add_address(NetAddress("127.0.0.1", 1000 + i), src)
    sel = book.get_selection()
    assert 0 < len(sel) <= 20
    victim = sel[0]
    book.remove_address(victim)
    assert str(victim) not in book._addrs


# -- pex ----------------------------------------------------------------------


def test_pex_reactor_exchanges_addresses():
    from tendermint_tpu.p2p.pex import PEXReactor

    books = [AddrBook("", routability_strict=False) for _ in range(2)]
    books[0].add_address(NetAddress("127.0.0.1", 7771), NetAddress("127.0.0.1", 1))

    def init(i, sw):
        sw.add_reactor("pex", PEXReactor(books[i], ensure_peers_period=3600))
        sw.set_node_info(
            NodeInfo(
                pub_key=sw.node_priv_key.pub_key(),
                moniker=f"n{i}",
                network="test",
                version=default_version("0.1.0"),
                listen_addr=f"127.0.0.1:{7000 + i}",
            )
        )
        return sw

    sws = make_connected_switches(2, init)
    try:
        # node1's inbound peer (node0... whichever side is inbound) requests
        # addrs; eventually node1 learns node0's known address
        assert wait_until(
            lambda: books[0].size() + books[1].size() >= 3, timeout=5
        )
    finally:
        for sw in sws:
            sw.stop()


# -- fuzz ---------------------------------------------------------------------


def test_fuzzed_stream_delays_but_delivers():
    from tendermint_tpu.p2p.fuzz import FuzzedStream

    a, b = pipe_pair()
    fa = FuzzedStream(a, prob_sleep=0.5, max_delay=0.01, seed=7)
    fa.write(b"through the fuzz")
    assert b.read(100) == b"through the fuzz"
    fa.close()


def test_switch_inbound_peer_cap():
    """Beyond max_num_peers, inbound connections are closed at accept
    (switch.go:462-467) — outbound/dialed peers are not affected."""
    import socket as _socket

    from tendermint_tpu.config.config import P2PConfig
    from tendermint_tpu.p2p.switch import Switch

    sw = Switch(config=P2PConfig(max_num_peers=1))

    class _FakePeer:
        def id(self):
            return "aa" * 20

        def key(self):
            return self.id()

    assert sw.peers.add(_FakePeer())  # at the cap
    a, b = _socket.socketpair()
    try:
        sw._accept_peer(a)
        b.settimeout(2)
        assert b.recv(1) == b""  # remote end sees an immediate close
    finally:
        for s in (a, b):
            try:
                s.close()
            except OSError:
                pass


def test_switch_ip_range_cap():
    """Inbound peers beyond the per-IP-range limit are closed at accept
    (ip_range_counter wiring)."""
    import socket as _socket

    from tendermint_tpu.p2p.ip_range_counter import IPRangeCounter
    from tendermint_tpu.p2p.switch import Switch

    sw = Switch()
    sw.ip_ranges = IPRangeCounter(limits=(1, 1, 1))
    assert sw.ip_ranges.try_add("127.0.0.1")  # range now full

    lst = _socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(1)
    cli = _socket.create_connection(lst.getsockname())
    srv, _ = lst.accept()
    try:
        sw._accept_peer(srv)
        cli.settimeout(2)
        assert cli.recv(1) == b""  # closed without handshake
    finally:
        for s in (cli, srv, lst):
            try:
                s.close()
            except OSError:
                pass


def test_addrbook_is_bad_and_eviction():
    import time as _time

    from tendermint_tpu.p2p import addrbook as ab

    book = AddrBook("", routability_strict=False)
    src = NetAddress("127.0.0.1", 1)
    addr = NetAddress("127.0.0.1", 2000)
    book.add_address(addr, src)
    ka = book._addrs[str(addr)]

    # fresh address: not bad
    assert not ka.is_bad()
    # repeated failures without a success -> bad (once past the
    # recent-attempt grace window)
    for _ in range(ab.MAX_FAILURES):
        book.mark_attempt(addr)
    assert not ka.is_bad()  # just tried: within RECENT_ATTEMPT grace
    ka.last_attempt -= ab.RECENT_ATTEMPT + 1
    assert ka.is_bad()
    # a success clears badness; old addresses are never bad
    book.mark_good(addr)
    assert ka.is_old() and not ka.is_bad()
    # staleness: not heard from in STALE_AFTER
    ka2 = ab.KnownAddress(NetAddress("127.0.0.1", 2001), src)
    ka2.added = _time.time() - ab.STALE_AFTER - 1
    assert ka2.is_bad()

    # mark_bad removes outright (ref MarkBad)
    book.mark_bad(addr)
    assert str(addr) not in book._addrs


def test_addrbook_pick_skips_bad():
    from tendermint_tpu.p2p import addrbook as ab

    book = AddrBook("", routability_strict=False)
    src = NetAddress("127.0.0.1", 1)
    good = NetAddress("127.0.0.1", 3000)
    bad = NetAddress("127.0.0.1", 3001)
    book.add_address(good, src)
    book.add_address(bad, src)
    kb = book._addrs[str(bad)]
    kb.attempts = ab.MAX_FAILURES
    kb.last_attempt = 1.0  # long ago, never succeeded -> bad
    for _ in range(50):
        picked = book.pick_address(new_bias_pct=100)
        assert str(picked) == str(good)


def test_addrbook_need_more_addrs():
    from tendermint_tpu.p2p import addrbook as ab

    book = AddrBook("", routability_strict=False)
    assert book.need_more_addrs()
    assert ab.NEED_ADDRESS_THRESHOLD == 1000


def test_addrbook_pick_recovers_when_all_bad():
    """After an outage burns attempts on every address, pick_address must
    fall back to retrying them, never strand the node (code-review r3)."""
    from tendermint_tpu.p2p import addrbook as ab

    book = AddrBook("", routability_strict=False)
    src = NetAddress("127.0.0.1", 1)
    for port in (4000, 4001):
        a = NetAddress("127.0.0.1", port)
        book.add_address(a, src)
        ka = book._addrs[str(a)]
        ka.attempts = ab.MAX_FAILURES
        ka.last_attempt = 1.0  # never succeeded, long ago -> is_bad
    assert book.pick_address() is not None


def test_pex_flood_eviction_requires_ip_match():
    """A flooder claiming a victim's listen_addr must not evict it from
    the book; only an address matching the socket IP is marked bad."""
    from tendermint_tpu.p2p.pex import PEXReactor

    book = AddrBook("", routability_strict=False)
    victim = NetAddress("127.0.0.1", 5555)
    book.add_address(victim, victim)

    class FakeStream:
        @staticmethod
        def remote_addr():
            return "10.9.9.9:1234"  # attacker's real socket IP

    class FakePeer:
        node_info = type(
            "NI", (), {"listen_addr": "127.0.0.1:5555"}
        )()  # claims the victim's address
        stream = FakeStream()

        @staticmethod
        def id():
            return "attacker"

    class FakeSwitch:
        stopped = []

        def stop_peer_for_error(self, peer, reason):
            self.stopped.append((peer, reason))

    pex = PEXReactor(book, ensure_peers_period=3600)
    pex.switch = FakeSwitch()
    pex._msg_counts["attacker"] = [time.monotonic()] * 1001  # over limit
    pex.receive(0x00, FakePeer(), b"{}")
    assert str(victim) in book._addrs  # victim survives
    assert pex.switch.stopped  # flooder still disconnected


def test_recv_routine_never_inherits_the_admission_timeout():
    """Round-17 regression for the full-suite fast-sync flake ("stream
    closed" on both sides, B stuck at 0): Switch.add_peer_from_stream
    arms a handshake timeout on the RAW socket and only restores
    blocking mode AFTER add_peer returns — but peer.start() (inside
    add_peer) launches the mconn recv routine first, and CPython fixes
    a recv's deadline at call entry, so the first blocking read
    inherited the armed timeout. A link quiet past that budget (mconn
    pings only every 40 s; under full-suite load the remote's first
    sends can be arbitrarily late) then tripped the timeout, which
    SocketStream.read reports as EOF — the connection died as
    ConnectionError("stream closed") with nothing wrong on the wire.

    The deterministic interleaving: arm a short admission timeout, let
    the peer start (recv enters with it armed), restore blocking mode a
    beat later exactly as the switch does, stay SILENT past the armed
    budget, then speak. Pre-fix the message is lost and on_error fires
    "stream closed"; post-fix (Peer.on_start clears the raw socket's
    timeout before the recv routine launches) the peer survives."""
    import socket as _socket
    import struct as _struct

    from tendermint_tpu.p2p.peer import Peer, PeerConfig
    from tendermint_tpu.p2p.stream import SocketStream

    a, b = _socket.socketpair()
    a.settimeout(0.4)  # the switch's admission arming
    got, errs = [], []
    peer = Peer(
        SocketStream(a),
        outbound=False,
        channel_descs=[ChannelDescriptor(id=0x20)],
        on_receive=lambda p, ch, msg: got.append((ch, msg)),
        on_error=lambda p, exc: errs.append(exc),
        config=PeerConfig(auth_enc=False),
        node_priv_key=gen_priv_key_ed25519(),
    )
    peer.start()           # recv routine enters its first blocking read
    time.sleep(0.05)
    a.settimeout(None)     # the finally in add_peer_from_stream — which
    # pre-fix was too late for the already-parked recv call
    try:
        time.sleep(1.0)    # silent link, well past the armed 0.4 s
        payload = b"hello-after-quiet"
        b.sendall(
            _struct.pack(">BBBH", 0x02, 0x20, 1, len(payload)) + payload
        )
        assert wait_until(lambda: got, timeout=5), (
            f"message lost; connection errors: {errs}"
        )
        assert got[0] == (0x20, payload)
        assert not errs, f"connection fataled on a healthy quiet link: {errs}"
    finally:
        peer.stop()
        b.close()


# -- round-18 adversarial-tier hardening regressions --------------------------
#
# Each hole below was exposed by the hostile-peer family in
# tests/netchaos_common.py (slow-loris, oversized-frame, eclipse); per
# the issue discipline every fix gets a deterministic UNIT regression
# here, not just a scenario.


def test_node_info_dribble_hits_absolute_deadline():
    """Slow-loris against the NodeInfo phase: the admission timeout used
    to bound each socket READ, so a peer feeding one byte per
    just-under-the-budget interval could hold the admission thread for
    MAX_NODE_INFO_SIZE reads. exchange_node_info's deadline is now
    ABSOLUTE — a dribbler whose every byte lands comfortably within the
    per-read budget still trips it at the total budget."""
    import socket as _socket
    import struct as _struct

    from tendermint_tpu.p2p.peer import exchange_node_info
    from tendermint_tpu.p2p.stream import SocketStream

    a, b = _socket.socketpair()
    info = NodeInfo(
        pub_key=gen_priv_key_ed25519().pub_key(),
        moniker="m", network="n", version=default_version("t"),
    )
    stop = threading.Event()

    def dribble():
        try:
            b.recv(65536)  # drain the honest side's own info
            b.sendall(_struct.pack(">I", 512))  # plausible length claim
            while not stop.is_set():
                b.sendall(b"x")  # one byte per beat: every READ succeeds
                stop.wait(0.15)
        except OSError:
            pass

    t = threading.Thread(target=dribble, daemon=True)
    t.start()
    t0 = time.monotonic()
    try:
        with pytest.raises(ConnectionError, match="timed out"):
            exchange_node_info(SocketStream(a), info, timeout=0.8)
        took = time.monotonic() - t0
        # absolute, not per-read: the per-read budget alone would NEVER
        # fire here (each byte arrives within 0.15 s)
        assert took < 5.0, f"deadline not absolute: took {took:.1f}s"
    finally:
        stop.set()
        for s in (a, b):
            try:
                s.close()
            except OSError:
                pass


def test_secretconn_oversized_frame_claim_refused_before_buffering():
    """Oversized-frame adversary: a frame length claim beyond the legal
    maximum (DATA_MAX_SIZE + 16-byte tag) is refused the moment the
    claim is read. The old path tried to BUFFER the claimed payload
    first — an attacker claiming 64 KiB and sending nothing parked the
    reader forever, and one sending junk cost a 64 KiB buffer per frame
    just to fail the AEAD tag."""
    import struct as _struct

    from tendermint_tpu.libs import telemetry
    from tendermint_tpu.p2p.secret_connection import (
        DATA_MAX_SIZE,
        SecretConnectionError,
    )

    a, b = pipe_pair()
    ka, kb = gen_priv_key_ed25519(), gen_priv_key_ed25519()
    out = {}
    t = threading.Thread(
        target=lambda: out.update(conn=SecretConnection(b, kb)), daemon=True
    )
    t.start()
    ca = SecretConnection(a, ka)
    t.join(5)
    reg = telemetry.default_registry()
    over0 = reg.counter("p2p_secretconn_oversized_frames_total").value

    # an illegal claim with NO payload behind it: pre-fix this blocked
    # the reader; post-fix it raises immediately
    ca.stream.write(_struct.pack(">H", DATA_MAX_SIZE + 17))
    with pytest.raises(SecretConnectionError, match="oversized"):
        out["conn"].read(10)
    # poisoned forever, and counted
    with pytest.raises(SecretConnectionError):
        out["conn"].read(1)
    assert reg.counter(
        "p2p_secretconn_oversized_frames_total"
    ).value == over0 + 1
    ca.close()


def test_reactor_recv_ceilings_right_sized():
    """The per-channel reassembly ceilings are right-sized to each
    channel's largest LEGAL message (round 18): before, every channel
    inherited the 21 MiB block ceiling, so an oversized-frame peer
    could park ~147 MiB of never-delivered reassembly bytes across one
    connection's channels."""
    from tendermint_tpu.codec import jsonval as jv
    from tendermint_tpu.consensus.reactor import (
        ConsensusReactor,
        DATA_CHANNEL,
        STATE_CHANNEL,
        VOTE_CHANNEL,
        VOTE_SET_BITS_CHANNEL,
    )
    from tendermint_tpu.mempool.reactor import MempoolReactor
    from tendermint_tpu.p2p.pex import PEXReactor

    from tendermint_tpu.types.params import MAX_BLOCK_PART_SIZE_BYTES

    caps = {
        d.id: d.recv_message_capacity
        for d in ConsensusReactor.get_channels(None)
    }
    assert caps[VOTE_CHANNEL] == 1 << 16  # a vote is ~700 B
    assert caps[STATE_CHANNEL] == 1 << 16
    assert caps[VOTE_SET_BITS_CHANNEL] == 1 << 16
    # the DATA cap DERIVES from the params-validated part-size bound
    # (hex-doubled + envelope headroom) so a legal genesis can never
    # configure a part the channel refuses
    assert caps[DATA_CHANNEL] == 2 * MAX_BLOCK_PART_SIZE_BYTES + (1 << 16)
    assert caps[DATA_CHANNEL] < 1 << 20
    [mp] = MempoolReactor.get_channels(None)
    # ... but a MAX_TX_BYTES tx must still FIT (hex-doubled + envelope)
    assert mp.recv_message_capacity >= 2 * jv.MAX_TX_BYTES
    assert mp.recv_message_capacity < 10 * (1 << 20)
    [px] = PEXReactor.get_channels(None)
    assert px.recv_message_capacity == 1 << 16
    # ... and genesis validation refuses a part size the channel could
    # not carry (the binding that keeps cap and params consistent)
    from tendermint_tpu.types.params import ConsensusParams

    cp = ConsensusParams()
    cp.block_gossip.block_part_size_bytes = MAX_BLOCK_PART_SIZE_BYTES + 1
    err = cp.validate()
    assert err is not None and "recv ceiling" in err
    cp.block_gossip.block_part_size_bytes = MAX_BLOCK_PART_SIZE_BYTES
    assert cp.validate() is None


def test_vote_channel_reassembly_past_ceiling_drops_peer():
    """Behavioral half of the ceiling regression: streaming a message
    past the vote channel's 64 KiB bound errors the connection (the
    switch then drops the peer for cause) instead of buffering toward
    the old 21 MiB."""
    from tendermint_tpu.consensus.reactor import ConsensusReactor, VOTE_CHANNEL

    descs = ConsensusReactor.get_channels(None)
    ma, mb, recv_a, recv_b, err = _mconn_pair(descs=descs)
    try:
        assert ma.send(VOTE_CHANNEL, b"\x00" * (1 << 17))  # 128 KiB
        assert wait_until(lambda: err, timeout=5), "oversize never errored"
        assert any("exceeds" in str(e) for e in err), err
        assert not recv_b, "oversized message must never be delivered"
    finally:
        ma.stop()
        mb.stop()


def test_fuzzed_stream_corrupts_deterministically():
    """The frame-corruption wrapper (p2p/fuzz.py, round-18 audit): the
    broken-against-SecretConnection silent write-DROP mode is gone;
    prob_corrupt XORs one byte per write, seeded-deterministic."""
    from tendermint_tpu.p2p.fuzz import FuzzedStream

    outs = []
    for _ in range(2):
        a, b = pipe_pair()
        fa = FuzzedStream(a, prob_corrupt=1.0, seed=3)
        fa.write(b"AAAABBBB")
        got = b.read(100)
        outs.append(got)
        assert got != b"AAAABBBB" and len(got) == 8
        assert sum(x != y for x, y in zip(got, b"AAAABBBB")) == 1
        assert fa.corrupted_writes == 1
        fa.close()
        b.close()
    assert outs[0] == outs[1], "same seed must corrupt identically"
    # and the drop mode is really gone — the constructor refuses it
    a, b = pipe_pair()
    with pytest.raises(TypeError):
        FuzzedStream(a, prob_drop_rw=0.5)
    a.close()
    b.close()


def test_fuzz_corruption_is_loud_tamper_under_secretconn():
    """The frame-corruption peer end to end: a FuzzedStream UNDER the
    SecretConnection makes a corrupted write ciphertext tamper on the
    wire — the receiving AEAD must raise (never EOF) and count it."""
    from tendermint_tpu.libs import telemetry
    from tendermint_tpu.p2p.fuzz import FuzzedStream
    from tendermint_tpu.p2p.secret_connection import SecretConnectionError

    a, b = pipe_pair()
    fa = FuzzedStream(a, prob_corrupt=0.0, seed=5)  # clean handshake
    out = {}
    t = threading.Thread(
        target=lambda: out.update(
            conn=SecretConnection(b, gen_priv_key_ed25519())
        ),
        daemon=True,
    )
    t.start()
    ca = SecretConnection(fa, gen_priv_key_ed25519())
    t.join(5)
    reg = telemetry.default_registry()
    af0 = reg.counter("p2p_secretconn_auth_failures_total").value
    fa.prob_corrupt = 1.0  # every frame from now on arrives tampered
    ca.write(b"this frame will not verify")
    with pytest.raises(SecretConnectionError):
        out["conn"].read(10)
    assert fa.corrupted_writes >= 1
    assert reg.counter("p2p_secretconn_auth_failures_total").value > af0
    ca.close()


def test_ip_range_counter_boundary_and_churn_races():
    """Eclipse backing, unit level: the range counter at the limit
    boundary under add/remove churn — a slot freed by a leaving peer is
    immediately claimable, concurrent add/remove pairs never leak or
    steal counts, and the counter lands exactly at zero."""
    from tendermint_tpu.p2p.ip_range_counter import IPRangeCounter

    # boundary: at the limit, refuse; free one slot, admit exactly one
    c = IPRangeCounter(limits=(2, 2, 2))
    assert c.try_add("9.9.9.1")
    assert c.try_add("9.9.9.2")
    assert not c.try_add("9.9.9.3")  # /24 full
    c.remove("9.9.9.1")
    assert c.try_add("9.9.9.3")      # freed slot claimable
    assert not c.try_add("9.9.9.4")  # and only that one
    # a refused add must not have half-counted any depth
    assert c.count("9") == 2 and c.count("9.9") == 2 and c.count("9.9.9") == 2

    # churn: racing add/remove pairs across threads; paired ops must
    # cancel exactly (no leaked counts to starve later honest peers —
    # the round-12 leak's failure shape — and no negative underflow)
    c2 = IPRangeCounter(limits=(64, 32, 16))
    errs = []

    def churn(tid):
        try:
            for i in range(300):
                ip = f"10.0.{tid % 3}.{i % 7}"
                if c2.try_add(ip):
                    c2.remove(ip)
        except Exception as exc:  # noqa: BLE001
            errs.append(exc)

    threads = [
        threading.Thread(target=churn, args=(t,), daemon=True)
        for t in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errs
    for p in ("10", "10.0", "10.0.0", "10.0.1", "10.0.2"):
        assert c2.count(p) == 0, (p, c2.count(p))


def test_uncount_stream_releases_exactly_once_across_wrapper_chain():
    """The round-12 wrapper-chain uncount under churn: the count marker
    lives on the RAW stream under fuzz/secret wrappers; releasing twice
    (error path + removal path racing) must not steal a still-live
    peer's count from the same range."""
    import socket as _socket

    from tendermint_tpu.p2p.fuzz import FuzzedStream
    from tendermint_tpu.p2p.stream import SocketStream

    sw = Switch()
    assert sw.ip_ranges.try_add("10.1.2.3")  # peer A
    assert sw.ip_ranges.try_add("10.1.2.4")  # peer B, same /24

    s1, s2 = _socket.socketpair()
    raw = SocketStream(s1)
    raw.counted_ip = "10.1.2.3"

    class _Outer:  # a secret-connection-shaped wrapper
        def __init__(self, stream):
            self.stream = stream

    chain = _Outer(FuzzedStream(raw))
    sw._uncount_stream(chain)
    assert sw.ip_ranges.count("10.1.2") == 1  # A released
    # the double-release race: a second uncount finds the marker cleared
    sw._uncount_stream(chain)
    assert sw.ip_ranges.count("10.1.2") == 1, "double uncount stole B's count"
    for s in (s1, s2):
        s.close()


def test_addrbook_one_slash24_cannot_dominate_the_book():
    """Eclipse backing, addr-book level: hundreds of addresses from one
    /24 (one attacker subnet, one source) collapse into the few buckets
    their (group, source-group) hash allows, so they evict EACH OTHER —
    while a handful of diverse addresses stay present and pickable."""
    import random as _random

    book = AddrBook()
    book._rng = _random.Random(7)
    src = NetAddress("9.9.9.1", 26656)
    for i in range(500):
        book.add_address(NetAddress(f"9.9.9.{i % 250}", 10000 + i), src)
    diverse = []
    for i in range(20):
        a = NetAddress(f"{20 + i}.{i + 1}.0.1", 26656)
        diverse.append(a)
        book.add_address(a, a)

    doms = [k for k in book._addrs if k.startswith("9.9.9.")]
    # one (group, src-group) pair hashes to at most NEW_BUCKETS_PER_ADDRESS
    # buckets of BUCKET_SIZE — the 500 dials cannot occupy more
    from tendermint_tpu.p2p.addrbook import (
        BUCKET_SIZE,
        NEW_BUCKETS_PER_ADDRESS,
    )

    assert len(doms) <= NEW_BUCKETS_PER_ADDRESS * BUCKET_SIZE, len(doms)
    # every diverse address survived the flood
    for a in diverse:
        assert str(a) in book._addrs
    # and the picker still reaches them (seeded: deterministic)
    picked_diverse = sum(
        1 for _ in range(300)
        if not str(book.pick_address()).startswith("9.9.9.")
    )
    assert picked_diverse >= 10, picked_diverse
