"""Authenticated state tree tests (tendermint_tpu/statetree/, round 13,
docs/state-tree.md).

The load-bearing property is CANONICAL SHAPE: the tree's root must be a
pure function of its key/value set, independent of the operation history
that produced it — replay-from-genesis, restore-from-sorted-map, and
delta-chain application must all land on byte-identical roots. The
oracle here is a direct recursive statement of that definition (root =
max-priority key; partition; recurse), in the same spirit as
merkle/simple.py's recursive parity oracle for the flat builder.
"""

from __future__ import annotations

import json
import random

import pytest

from tendermint_tpu.merkle.statetree_proof import (
    EMPTY_HASH,
    TreeProof,
    key_priority,
    node_hash,
    value_hash,
)
from tendermint_tpu.statetree import VersionedTree
from tendermint_tpu.statetree.tree import TreeError


# -- the recursive oracle -----------------------------------------------------


def oracle_root(entries: dict[bytes, bytes]) -> bytes:
    """The canonical treap root, straight from the definition."""
    def build(keys: list[bytes]) -> bytes:
        if not keys:
            return EMPTY_HASH
        root_key = max(keys, key=key_priority)
        left = build([k for k in keys if k < root_key])
        right = build([k for k in keys if k > root_key])
        return node_hash(root_key, value_hash(entries[root_key]), left, right)

    return build(list(entries))


def _entries(n: int, seed: int = 0) -> dict[bytes, bytes]:
    rng = random.Random(seed)
    out = {}
    while len(out) < n:
        k = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 12)))
        out[k] = b"v:" + k + bytes([rng.randrange(256)])
    return out


def _tree_from(entries: dict, version: int = 1, **kw) -> VersionedTree:
    t = VersionedTree(**kw)
    for k, v in entries.items():
        t.set(k, v)
    t.commit(version)
    return t


# -- canonical shape ----------------------------------------------------------


class TestCanonicalShape:
    def test_oracle_parity_1_to_300_keys(self):
        """Root parity against the recursive oracle at every size 1..300
        (stepped above 64 for runtime), via incremental inserts in a
        shuffled order."""
        rng = random.Random(7)
        sizes = list(range(1, 65)) + list(range(65, 301, 7))
        for n in sizes:
            entries = _entries(n, seed=n)
            keys = list(entries)
            rng.shuffle(keys)
            t = VersionedTree()
            for k in keys:
                t.set(k, entries[k])
            assert t.commit(1) == oracle_root(entries), f"n={n}"

    def test_insertion_order_independent(self):
        entries = _entries(120, seed=3)
        roots = set()
        for seed in range(4):
            keys = list(entries)
            random.Random(seed).shuffle(keys)
            t = VersionedTree()
            for k in keys:
                t.set(k, entries[k])
            roots.add(t.commit(1))
        assert len(roots) == 1

    def test_bulk_load_matches_incremental(self):
        entries = _entries(200, seed=9)
        inc = _tree_from(entries)
        bulk = VersionedTree.from_entries(entries, version=1)
        assert bulk.root_hash() == inc.root_hash() == oracle_root(entries)
        assert bulk.entries() == sorted(entries.items())
        assert bulk.size == len(entries)

    def test_delete_reaches_the_smaller_sets_root(self):
        """Deleting keys must land exactly on the canonical root of the
        remaining set — shape history must not leak into the hash."""
        entries = _entries(80, seed=5)
        t = _tree_from(entries)
        gone = sorted(entries)[::3]
        survivors = {k: v for k, v in entries.items() if k not in set(gone)}
        for k in gone:
            assert t.delete(k)
        assert t.commit(2) == oracle_root(survivors)
        assert t.size == len(survivors)
        # and the older version is untouched (persistence)
        assert t.root_hash(1) == oracle_root(entries)
        assert t.get(gone[0], version=1) == entries[gone[0]]
        assert t.get(gone[0], version=2) is None

    def test_update_changes_only_value_binding(self):
        entries = _entries(50, seed=11)
        t = _tree_from(entries)
        k = sorted(entries)[25]
        t.set(k, b"updated")
        changed = {**entries, k: b"updated"}
        assert t.commit(2) == oracle_root(changed)

    def test_empty_tree_and_single_key(self):
        t = VersionedTree()
        assert t.commit(1) == EMPTY_HASH
        t.set(b"a", b"1")
        root = t.commit(2)
        assert root == oracle_root({b"a": b"1"})
        assert t.delete(b"a")
        assert t.commit(3) == EMPTY_HASH

    def test_delete_absent_is_a_noop(self):
        entries = _entries(20, seed=1)
        t = _tree_from(entries)
        assert not t.delete(b"\xff" * 20)
        assert t.commit(2) == t.root_hash(1)


# -- proofs -------------------------------------------------------------------


class TestProofs:
    def test_membership_and_absence_round_trip_1_to_300(self):
        """Golden-vector sweep: at every size, every present key proves
        membership and a fistful of absent keys prove absence — through
        a JSON round trip, against the oracle root."""
        for n in [1, 2, 3, 5, 9, 17, 33, 64, 127, 300]:
            entries = _entries(n, seed=100 + n)
            t = _tree_from(entries)
            root = t.root_hash()
            assert root == oracle_root(entries)
            keys = sorted(entries)
            probe = keys if n <= 33 else keys[:: max(1, n // 16)]
            for k in probe:
                p = TreeProof.from_json(
                    json.loads(json.dumps(t.prove(k).to_json()))
                )
                assert p.is_membership and p.value == entries[k]
                assert p.verify(root), (n, k)
            for absent in (b"", b"\x00", b"\xff" * 16, keys[0] + b"\x00"):
                if absent in entries:
                    continue
                p = TreeProof.from_json(
                    json.loads(json.dumps(t.prove(absent).to_json()))
                )
                assert not p.is_membership
                assert p.verify(root), (n, absent)

    def test_proof_binds_value(self):
        entries = _entries(40, seed=2)
        t = _tree_from(entries)
        root = t.root_hash()
        k = sorted(entries)[7]
        p = t.prove(k)
        assert p.verify(root)
        forged = TreeProof(k, b"forged-value", p.steps)
        assert not forged.verify(root)

    def test_proof_for_wrong_root_fails(self):
        a = _tree_from(_entries(30, seed=4))
        b = _tree_from(_entries(30, seed=6))
        k = sorted(_entries(30, seed=4))[0]
        assert a.prove(k).verify(a.root_hash())
        assert not a.prove(k).verify(b.root_hash())

    def test_absence_proof_cannot_claim_present_key(self):
        entries = _entries(40, seed=8)
        t = _tree_from(entries)
        root = t.root_hash()
        k = sorted(entries)[3]
        # strip the value off a membership proof: the terminal step's
        # key equals the query, which the absence rule rejects
        p = t.prove(k)
        assert not TreeProof(k, None, p.steps).verify(root)

    def test_membership_proof_cannot_claim_absent_key(self):
        entries = _entries(40, seed=12)
        t = _tree_from(entries)
        root = t.root_hash()
        absent = b"\xfe" * 9
        assert absent not in entries
        p = t.prove(absent)
        assert p.value is None and p.verify(root)
        assert not TreeProof(absent, b"anything", p.steps).verify(root)

    def test_tampered_steps_fail(self):
        entries = _entries(64, seed=13)
        t = _tree_from(entries)
        root = t.root_hash()
        k = sorted(entries)[31]
        base = t.prove(k)
        assert len(base.steps) >= 2
        # drop a step / swap two steps / flip a child hash bit
        assert not TreeProof(k, entries[k], base.steps[1:]).verify(root)
        swapped = [base.steps[1], base.steps[0]] + base.steps[2:]
        assert not TreeProof(k, entries[k], swapped).verify(root)
        obj = base.to_json()
        top = obj["steps"][-1]
        for slot in (2, 3):
            if top[slot]:
                bad = json.loads(json.dumps(obj))
                flipped = bytearray(bytes.fromhex(bad["steps"][-1][slot]))
                flipped[0] ^= 0x01
                bad["steps"][-1][slot] = flipped.hex().upper()
                assert not TreeProof.from_json(bad).verify(root)
                break

    def test_decode_hardening(self):
        good = _tree_from(_entries(5, seed=5)).prove(b"zz").to_json()
        for mutate in (
            lambda o: o.update(key=7),
            lambda o: o.update(steps="zz"),
            lambda o: o.update(steps=[["zz"]]),
            lambda o: o.update(steps=[["00", "11" * 20, "", ""]] * 600),
            lambda o: o.update(value=["no"]),
        ):
            obj = json.loads(json.dumps(good))
            mutate(obj)
            with pytest.raises(ValueError):
                TreeProof.from_json(obj)

    def test_empty_tree_absence(self):
        t = VersionedTree()
        t.commit(1)
        p = t.prove(b"anything")
        assert p.verify(EMPTY_HASH)
        assert not p.verify(b"\x11" * 20)
        assert not TreeProof(b"k", b"v", []).verify(EMPTY_HASH)


# -- versions, diff, journal --------------------------------------------------


class TestVersions:
    def test_diff_exact(self):
        t = VersionedTree()
        t.set(b"a", b"1")
        t.set(b"b", b"2")
        t.set(b"c", b"3")
        t.commit(10)
        t.set(b"b", b"2x")       # update
        t.set(b"d", b"4")        # insert
        t.delete(b"a")           # delete
        t.set(b"c", b"3")        # touched but unchanged -> not in diff
        t.commit(20)
        ups, dels = t.diff(10, 20)
        assert ups == {b"b": b"2x", b"d": b"4"}
        assert dels == [b"a"]

    def test_diff_folds_multiple_commits(self):
        t = VersionedTree()
        t.set(b"a", b"1")
        t.commit(1)
        t.set(b"x", b"1")
        t.commit(2)
        t.delete(b"x")
        t.set(b"y", b"2")
        t.commit(3)
        ups, dels = t.diff(1, 3)
        assert ups == {b"y": b"2"}  # x set then deleted: absent from both
        assert dels == []

    def test_diff_applied_to_base_reproduces_target(self):
        entries = _entries(90, seed=21)
        t = _tree_from(entries, version=1)
        rng = random.Random(22)
        cur = dict(entries)
        for v in (2, 3, 4):
            for k in rng.sample(sorted(cur), 10):
                if rng.random() < 0.3:
                    t.delete(k)
                    cur.pop(k)
                else:
                    t.set(k, b"v%d" % v + k)
                    cur[k] = b"v%d" % v + k
            nk = b"new-%d" % v
            t.set(nk, b"n")
            cur[nk] = b"n"
            t.commit(v)
        ups, dels = t.diff(1, 4)
        replay = dict(entries)
        for k in dels:
            replay.pop(k)
        replay.update(ups)
        assert replay == cur
        assert VersionedTree.from_entries(replay, 1).root_hash() == t.root_hash(4)

    def test_diff_pruned_raises(self):
        t = VersionedTree(keep_recent=2)
        for v in (1, 2, 3, 4):
            t.set(b"k%d" % v, b"v")
            t.commit(v)
        assert t.versions() == [3, 4]
        with pytest.raises(TreeError):
            t.diff(1, 4)
        ups, _dels = t.diff(3, 4)
        assert ups == {b"k4": b"v"}

    def test_commit_version_must_increase(self):
        t = VersionedTree()
        t.commit(5)
        with pytest.raises(TreeError):
            t.commit(5)
        with pytest.raises(TreeError):
            t.commit(4)

    def test_rollback_to(self):
        entries = _entries(30, seed=30)
        t = _tree_from(entries, version=1)
        root1 = t.root_hash(1)
        t.set(b"zz", b"staged")          # uncommitted staging
        t.rollback_to()
        assert t.get(b"zz") is None
        t.set(b"zz", b"v2")
        t.commit(2)
        t.rollback_to(1)                 # drop committed version 2
        assert t.versions() == [1]
        assert t.root_hash() == root1 and t.get(b"zz") is None
        assert t.size == len(entries)
        # and the tree keeps working after a rollback
        t.set(b"zz", b"v3")
        assert t.commit(3) == oracle_root({**entries, b"zz": b"v3"})

    def test_retention_prunes_oldest(self):
        t = VersionedTree(keep_recent=3)
        for v in range(1, 8):
            t.set(b"k%d" % v, b"v")
            t.commit(v)
        assert t.versions() == [5, 6, 7]
        with pytest.raises(TreeError):
            t.root_hash(2)


# -- batched hashing ----------------------------------------------------------


class _CountingHasher:
    """Duck-types the one Hasher method the tree uses; CPU digests so
    parity with the unhashed path is byte-exact."""

    def __init__(self):
        self.batches = 0
        self.items = 0

    def part_leaf_hashes(self, chunks):
        from tendermint_tpu.crypto.hashing import ripemd160

        self.batches += 1
        self.items += len(chunks)
        return [ripemd160(c) for c in chunks]


class TestBatchedHashing:
    def test_gateway_batches_match_cpu(self):
        entries = _entries(400, seed=40)
        h = _CountingHasher()
        t = VersionedTree.from_entries(entries, version=1, hasher=h)
        assert t.root_hash() == oracle_root(entries)
        assert h.batches >= 1 and h.items >= 400
        assert t.stats()["gateway_nodes"] == h.items

    def test_incremental_commit_batches_waves(self):
        entries = _entries(600, seed=41)
        h = _CountingHasher()
        t = VersionedTree.from_entries(entries, version=1, hasher=h)
        h.batches = h.items = 0
        for i in range(40):
            t.set(b"upd-%03d" % i, b"x")
        t.commit(2)
        # a 40-key update dirties O(changed * log n) nodes; the wave
        # batching must stay far below one call per node
        assert t.stats()["last_commit_nodes"] > 40
        assert h.batches <= 40, "wave batching degenerated to per-node calls"
        assert t.root_hash() == oracle_root(
            {**entries, **{b"upd-%03d" % i: b"x" for i in range(40)}}
        )


# -- app / RPC / light-client integration -------------------------------------


class TestAppIntegration:
    def test_kvstore_app_hash_is_tree_root(self):
        from tendermint_tpu.abci.apps.kvstore import KVStoreApp

        app = KVStoreApp()
        app.deliver_tx(b"a=1")
        app.deliver_tx(b"b=2")
        res = app.commit()
        assert res.data == app.app_hash == oracle_root({b"a": b"1", b"b": b"2"})
        app.deliver_tx(b"a=9")
        app.commit()
        assert app.app_hash == oracle_root({b"a": b"9", b"b": b"2"})
        assert app.tree.root_hash(1) == oracle_root({b"a": b"1", b"b": b"2"})

    def test_kvstore_query_proofs(self):
        from tendermint_tpu.abci.apps.kvstore import KVStoreApp

        app = KVStoreApp()
        app.deliver_tx(b"a=1")
        app.commit()
        res = app.query(b"a", prove=True)
        assert res.code == 0 and res.value == b"1" and res.height == 1
        p = TreeProof.from_json(json.loads(res.proof))
        assert p.verify(app.app_hash) and p.value == b"1"
        absent = app.query(b"nope", prove=True)
        assert absent.code == 0 and absent.value == b""
        pa = TreeProof.from_json(json.loads(absent.proof))
        assert pa.value is None and pa.verify(app.app_hash)
        # a fresh app has no committed root to prove against
        fresh = KVStoreApp()
        assert fresh.query(b"a", prove=True).code != 0

    def test_counter_prove_clear_unsupported_error(self):
        from tendermint_tpu.abci.apps.counter import CounterApp
        from tendermint_tpu.abci.types import CODE_UNSUPPORTED, Application

        for app in (CounterApp(), Application()):
            res = app.query(b"hash", prove=True)
            assert res.code == CODE_UNSUPPORTED
            assert "proofs unsupported" in res.log
            assert res.proof == b""
            # and the non-proving path still serves
            assert app.query(b"hash").code == 0

    def test_persistent_app_reload_rebuilds_tree(self, tmp_path):
        from tendermint_tpu.abci.apps.kvstore import PersistentKVStoreApp

        app = PersistentKVStoreApp(str(tmp_path))
        app.deliver_tx(b"x=1")
        app.commit()
        app.deliver_tx(b"y=2")
        app.commit()
        reloaded = PersistentKVStoreApp(str(tmp_path))
        assert reloaded.app_hash == app.app_hash
        assert reloaded.height == 2
        p = TreeProof.from_json(json.loads(reloaded.query(b"x", prove=True).proof))
        assert p.verify(reloaded.app_hash)

    def test_restore_delta_contract(self):
        from tendermint_tpu.abci.apps.kvstore import KVStoreApp

        src = KVStoreApp()
        for h in range(1, 4):
            src.deliver_tx(b"k%d=v%d" % (h, h))
            if h == 2:
                src.deliver_tx(b"k1=updated")
            src.commit()
        # restore a replica at height 2, then delta it to height 3
        replica = KVStoreApp()
        snap2 = json.dumps({
            "height": 2,
            "app_hash": src.tree.root_hash(2).hex(),
            "state": {"k1": b"updated".hex(), "k2": b"v2".hex()},
        }, sort_keys=True).encode()
        replica.restore(snap2, height=2, app_hash=src.tree.root_hash(2))
        ups, dels = src.tree.diff(2, 3)
        replica.restore_delta(ups, dels, 3, src.app_hash)
        assert replica.app_hash == src.app_hash and replica.height == 3
        assert replica.state == src.state

    def test_restore_delta_refuses_wrong_hash_with_nothing_applied(self):
        from tendermint_tpu.abci.apps.kvstore import KVStoreApp

        app = KVStoreApp()
        snap = json.dumps({
            "height": 1, "app_hash": oracle_root({b"a": b"1"}).hex(),
            "state": {"a": b"1".hex()},
        }, sort_keys=True).encode()
        app.restore(snap, height=1, app_hash=oracle_root({b"a": b"1"}))
        before = (app.height, app.app_hash, dict(app.state))
        with pytest.raises(ValueError, match="verified app hash"):
            app.restore_delta({b"b": b"2"}, [], 2, b"\xee" * 20)
        assert (app.height, app.app_hash, app.state) == before
        assert app.tree.versions() == [1]
        with pytest.raises(ValueError, match="stale delta"):
            app.restore_delta({b"b": b"2"}, [], 1, oracle_root({b"a": b"1"}))
        with pytest.raises(ValueError, match="restored base"):
            KVStoreApp().restore_delta({b"b": b"2"}, [], 2, b"\x11" * 20)


class TestVerifiedQuery:
    def _chain(self, n=6):
        from tendermint_tpu.rpc.light import LightClient
        from tendermint_tpu.statesync.devchain import build_kvstore_chain

        chain = build_kvstore_chain(n)
        lc = LightClient(
            chain.rpc_stub(), chain.genesis_doc.chain_id,
            chain.state.load_validators(1), trusted_height=0,
        )
        return chain, lc

    def test_membership_and_absence(self):
        chain, lc = self._chain()
        head = chain.block_store.height()
        res = lc.verified_query(b"k5-0", height=head - 1)
        assert res["value"] == b"v5" and not res["absent"]
        assert res["height"] == head - 1
        gone = lc.verified_query(b"never-written", height=head - 1)
        assert gone["absent"] and gone["value"] is None

    def test_head_proof_needs_next_header(self):
        from tendermint_tpu.rpc.light import LightClientError

        chain, lc = self._chain()
        head = chain.block_store.height()
        with pytest.raises(LightClientError, match="header"):
            lc.verified_query(b"k5-0", height=head)
        chain.build(1)  # header head+1 now exists
        res = lc.verified_query(b"k5-0", height=head)
        assert res["value"] == b"v5"

    def test_header_memo_one_verification_per_burst(self):
        """Round-24 satellite: a 100-query burst at one height verifies
        that height's commit ONCE — repeat proofs ride the verified-
        header memo, so a replica's serve path costs no per-read commit
        verification (every /commit fetch implies a verification, so
        counting fetches counts verifications)."""
        chain, lc = self._chain()
        head = chain.block_store.height()
        real = chain.rpc_stub()
        calls = {"commit": 0}

        class Counting:
            def __getattr__(self, name):
                return getattr(real, name)

            def commit(self, **kw):
                calls["commit"] += 1
                return real.commit(**kw)

        lc.client = Counting()
        first = lc.verified_query(b"k5-0", height=head - 1)
        assert first["value"] == b"v5"
        walked = calls["commit"]
        assert walked >= 1
        for _ in range(100):
            res = lc.verified_query(b"k5-0", height=head - 1)
            assert res["value"] == b"v5"
        assert calls["commit"] == walked

    def test_lying_node_detected(self):
        from tendermint_tpu.rpc.light import LightClientError

        chain, lc = self._chain()
        head = chain.block_store.height()
        real = chain.rpc_stub()

        class Liar:
            def __getattr__(self, name):
                return getattr(real, name)

            def abci_query(self, **kw):
                out = real.abci_query(**kw)
                out["response"]["value"] = b"forged".hex().upper()
                return out

        lc.client = Liar()
        with pytest.raises(LightClientError, match="value"):
            lc.verified_query(b"k5-0", height=head - 1)

    def test_forged_proof_detected(self):
        from tendermint_tpu.rpc.light import LightClientError

        chain, lc = self._chain()
        head = chain.block_store.height()
        real = chain.rpc_stub()

        class ProofForger:
            def __getattr__(self, name):
                return getattr(real, name)

            def abci_query(self, **kw):
                out = real.abci_query(**kw)
                raw = json.loads(bytes.fromhex(out["response"]["proof"]))
                step = raw["steps"][-1]
                flip = bytearray(bytes.fromhex(step[1]))
                flip[0] ^= 0x01
                step[1] = flip.hex().upper()
                out["response"]["proof"] = (
                    json.dumps(raw).encode().hex().upper()
                )
                return out

        lc.client = ProofForger()
        with pytest.raises(LightClientError, match="proof"):
            lc.verified_query(b"k5-0", height=head - 1)

    def test_unsupported_app_refused_loudly(self):
        from tendermint_tpu.abci.apps.counter import CounterApp
        from tendermint_tpu.rpc.light import LightClient, LightClientError
        from tendermint_tpu.statesync.devchain import DevChain

        chain = DevChain(CounterApp())
        chain.build(3)
        lc = LightClient(
            chain.rpc_stub(), chain.genesis_doc.chain_id,
            chain.state.load_validators(1), trusted_height=0,
        )
        with pytest.raises(LightClientError, match="proofs unsupported"):
            lc.verified_query(b"hash", height=2)


# -- sizes & stats ------------------------------------------------------------


class TestBookkeeping:
    def test_size_and_entries(self):
        entries = _entries(70, seed=50)
        t = _tree_from(entries)
        assert t.size == 70
        assert t.entries() == sorted(entries.items())
        assert t.get(sorted(entries)[0]) == entries[sorted(entries)[0]]

    def test_stats_shape(self):
        t = _tree_from(_entries(10, seed=51))
        s = t.stats()
        for key in ("size", "commits", "nodes_created", "hashed_nodes",
                    "hash_waves", "gateway_nodes", "proofs",
                    "versions_retained", "latest_version"):
            assert key in s
        assert s["size"] == 10 and s["commits"] == 1
