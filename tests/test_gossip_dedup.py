"""Has-vote-aware gossip dedup (round 20, docs/localnet.md).

The 2NxN redundancy mechanism: every validator's vote reaches every
node ~2N times because the pick/send loops only learn what a peer
holds from votes WE sent it or full VoteSetBits exchanges — the cheap
HasVote announcements peers broadcast after every accepted vote were
mostly dropped on the floor (no tracking array ensured yet, or the
peer had just committed and its announcements were one height "behind"
the mirror). With `consensus.gossip_dedup` on (the default), the STATE
channel feeds all of them into the mirror and the part-set gossip
gains the same screen (HasBlockPartMessage).

These are the unit halves; the process-scale A/B lives in
benches/bench_localnet.py (dedup on-vs-off duplicate-vote ratio at
n=10 real processes, asserted directional)."""

from __future__ import annotations

import pytest

from tendermint_tpu.consensus import messages as msgs
from tendermint_tpu.consensus.reactor import (
    PEER_STATE_KEY,
    STATE_CHANNEL,
    ConsensusReactor,
    PeerState,
    _enc,
)
from tendermint_tpu.libs.bitarray import BitArray
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, VOTE_TYPE_PREVOTE


class _VoteSet:
    """Minimal vote_set: holds the given indices at (height, round, type)."""

    def __init__(self, height, round_, type_, indices, size=4):
        self.height, self.round_, self.type_ = height, round_, type_
        self._indices = list(indices)
        self._size = size

    def size(self):
        return self._size

    def bit_array(self):
        return BitArray.from_indices(self._size, self._indices)

    def get_by_index(self, index):
        assert index in self._indices
        return ("vote", index)


def test_has_vote_announcement_suppresses_pick():
    """The core dedup claim: a HasVote announcement alone (no vote
    round-trip) must stop the picker from pushing that vote to the
    announcing peer."""
    ps = PeerState(peer=None)
    ps.prs.height, ps.prs.round_ = 5, 0
    ps.ensure_vote_bit_arrays(5, 4)
    vs = _VoteSet(5, 0, VOTE_TYPE_PREVOTE, [1, 2])

    assert ps.apply_has_vote(msgs.HasVoteMessage(5, 0, VOTE_TYPE_PREVOTE, 1))
    # index 1 is now known-held: only index 2 remains pickable
    for _ in range(8):
        vote = ps.pick_vote_to_send(vs)
        assert vote == ("vote", 2)

    assert ps.apply_has_vote(msgs.HasVoteMessage(5, 0, VOTE_TYPE_PREVOTE, 2))
    assert ps.pick_vote_to_send(vs) is None


def test_has_vote_mid_pick_race_is_benign():
    """A HasVote landing BETWEEN pick and send is the unavoidable race
    (the peer's announcement and our push cross on the wire). The send
    still goes out — one harmless duplicate — but the bit the HasVote
    set must survive the send's own marking, even a FAILED send: a
    failed send leaves the bit as the HasVote left it (held), so the
    picker doesn't re-push a vote the peer itself told us it has."""

    class _Peer:
        def __init__(self, ok):
            self.ok = ok

        def send(self, ch, raw):
            return self.ok

    class _Vote:
        height, round_, type_, validator_index = 5, 0, VOTE_TYPE_PREVOTE, 1

        def to_json(self):
            return {"height": self.height}

    ps = PeerState(peer=None)
    ps.prs.height, ps.prs.round_ = 5, 0
    ps.ensure_vote_bit_arrays(5, 4)
    vs = _VoteSet(5, 0, VOTE_TYPE_PREVOTE, [1])

    picked = ps.pick_vote_to_send(vs)
    assert picked == ("vote", 1)
    # the race: the peer announces the same vote before our send lands
    assert ps.apply_has_vote(msgs.HasVoteMessage(5, 0, VOTE_TYPE_PREVOTE, 1))
    # failed send: pre-round-20 semantics would retry the vote forever
    # (bit only ever set on successful send) — the announcement must win
    assert not ConsensusReactor._send_vote(None, _Peer(ok=False), ps, _Vote())
    assert ps.pick_vote_to_send(vs) is None, (
        "a vote the peer announced must stay unpickable after a failed send"
    )
    # and a successful send re-marking the same bit is idempotent
    assert ConsensusReactor._send_vote(None, _Peer(ok=True), ps, _Vote())
    assert ps.pick_vote_to_send(vs) is None


def test_last_commit_has_vote_lands_only_with_dedup():
    """A node that just committed H keeps broadcasting HasVotes for its
    H-precommits while peers' mirrors already show it at H+1. The
    strict gate (pre-round-20) dropped ALL of them — so everyone kept
    re-pushing commit votes the node already held. With
    allow_last_commit the announcement routes into the last_commit
    tracking array."""
    ps = PeerState(peer=None)
    ps.prs.height, ps.prs.round_ = 6, 0
    ps.prs.last_commit_round = 0
    ps.ensure_vote_bit_arrays(5, 4)  # height+1 branch -> last_commit array
    announce = msgs.HasVoteMessage(5, 0, VOTE_TYPE_PRECOMMIT, 2)

    assert not ps.apply_has_vote(announce)  # strict gate: dropped
    assert ps.apply_has_vote(announce, allow_last_commit=True)

    # the last-commit picker now skips the announced vote
    last = _VoteSet(5, 0, VOTE_TYPE_PRECOMMIT, [2, 3])
    assert ps.pick_vote_to_send(last) == ("vote", 3)


def test_laggard_catchup_branch_unaffected_by_dedup():
    """The stored-commit catchup path (peer >= 2 heights behind) must
    keep working under dedup: HasVotes from the laggard for its OWN
    height route into the catchup-commit array (so we skip what it
    has), and announcements for coordinates no array tracks are
    DROPPED, never mis-filed into a same-index bit of another round."""
    ps = PeerState(peer=None)
    ps.prs.height, ps.prs.round_ = 5, 2  # laggard raced past commit round 0
    ps.ensure_vote_bit_arrays(5, 4)

    # an announcement for the untracked commit round is dropped...
    stray = msgs.HasVoteMessage(5, 0, VOTE_TYPE_PRECOMMIT, 1)
    assert not ps.apply_has_vote(stray, allow_last_commit=True)
    # ...and did not leak into the round-2 precommit array
    assert ps.prs.precommits.is_empty()

    # the catchup branch then ensures the commit-round array; the same
    # announcement now lands there and the commit picker skips it
    ps.ensure_catchup_commit_round(5, 0, 4)
    assert ps.apply_has_vote(stray, allow_last_commit=True)
    commit_votes = _VoteSet(5, 0, VOTE_TYPE_PRECOMMIT, [1, 3])
    assert ps.pick_vote_to_send(commit_votes) == ("vote", 3)


# -- the reactor's STATE-channel wiring ---------------------------------------


class _Validators:
    def __init__(self, n):
        self._n = n

    def size(self):
        return self._n


class _RoundState:
    def __init__(self, height, n=4):
        self.height = height
        self.validators = _Validators(n)
        self.last_commit = _Validators(n)  # only size() is consulted


class _ConState:
    def __init__(self, height=5, gossip_dedup=True):
        from types import SimpleNamespace

        self.config = SimpleNamespace(gossip_dedup=gossip_dedup)
        self._rs = _RoundState(height)
        self.vote_recv_mono = {}

    def get_round_state(self):
        return self._rs


class _StubPeer:
    def __init__(self):
        self._kv = {}

    def id(self):
        return "stub-peer-0000"

    def get(self, k):
        return self._kv.get(k)

    def set(self, k, v):
        self._kv[k] = v

    def send(self, ch, raw):
        return True

    def try_send(self, ch, raw):
        return True


def _reactor_with_peer(gossip_dedup: bool):
    r = ConsensusReactor(_ConState(height=5, gossip_dedup=gossip_dedup))
    r._started = True  # receive() guards on is_running()
    peer = _StubPeer()
    ps = PeerState(peer)
    ps.prs.height, ps.prs.round_ = 5, 0
    peer.set(PEER_STATE_KEY, ps)
    return r, peer, ps


def test_state_channel_has_vote_ensures_arrays_when_dedup_on():
    """The first-window drop: at a fresh height the mirror has NO bit
    arrays yet, so every early HasVote used to vanish into the
    set_has_vote no-op. With dedup on, receive() ensures the arrays
    (exactly like the VOTE channel does) before applying."""
    r, peer, ps = _reactor_with_peer(gossip_dedup=True)
    assert ps.prs.prevotes is None  # fresh mirror, nothing ensured
    raw = _enc(msgs.HasVoteMessage(5, 0, VOTE_TYPE_PREVOTE, 2))
    r.receive(STATE_CHANNEL, peer, raw)
    assert r.has_votes_applied == 1
    assert ps.pick_vote_to_send(_VoteSet(5, 0, VOTE_TYPE_PREVOTE, [2])) is None


def test_state_channel_has_vote_dropped_when_dedup_off():
    """gossip_dedup=false restores the pre-round-20 gossip exactly —
    the A/B baseline the bench compares against."""
    r, peer, ps = _reactor_with_peer(gossip_dedup=False)
    raw = _enc(msgs.HasVoteMessage(5, 0, VOTE_TYPE_PREVOTE, 2))
    r.receive(STATE_CHANNEL, peer, raw)
    assert r.has_votes_applied == 0
    assert ps.prs.prevotes is None  # no arrays ensured, announcement lost


def test_has_block_part_announcement_marks_mirror():
    """HasBlockPartMessage on the STATE channel marks the peer's
    part-set mirror so gossip_data stops pushing a part the peer
    already assembled — applied regardless of our own knob (free
    information, only ever reduces redundant sends)."""
    r, peer, ps = _reactor_with_peer(gossip_dedup=False)
    ps.set_has_proposal(
        type(
            "P",
            (),
            {
                "height": 5,
                "round_": 0,
                "block_parts_header": type(
                    "H", (), {"total": 4, "hash": b"x"}
                )(),
                "pol_round": -1,
            },
        )()
    )
    assert not ps.prs.proposal_block_parts.get_index(3)
    r.receive(STATE_CHANNEL, peer, _enc(msgs.HasBlockPartMessage(5, 0, 3)))
    assert r.part_announces_applied == 1
    assert ps.prs.proposal_block_parts.get_index(3)


def test_broadcast_has_part_gated_by_knob():
    """Local part adds only announce when the knob is on (the off arm
    of the A/B must not emit round-20 messages at all)."""
    from tendermint_tpu.types.events import EventDataBlockPart

    sent = []

    class _Switch:
        def broadcast(self, ch, raw):
            sent.append((ch, raw))

    data = EventDataBlockPart(height=5, round_=0, index=1)

    r_off = ConsensusReactor(_ConState(gossip_dedup=False))
    r_off.switch = _Switch()
    r_off._broadcast_has_part(data)
    assert not sent and r_off.part_announces_sent == 0

    r_on = ConsensusReactor(_ConState(gossip_dedup=True))
    r_on.switch = _Switch()
    r_on._broadcast_has_part(data)
    assert len(sent) == 1 and sent[0][0] == STATE_CHANNEL
    assert r_on.part_announces_sent == 1
    msg = msgs.msg_from_json(__import__("json").loads(sent[0][1].decode()))
    assert isinstance(msg, msgs.HasBlockPartMessage)
    assert (msg.height, msg.round_, msg.index) == (5, 0, 1)


def test_relay_screen_holds_fresh_votes_only():
    """The lazy-relay screen: a vote we received under VOTE_RELAY_DELAY
    ago is held (its origin is fanning it out and HasVotes are in
    flight); after the hold, or for unstamped votes (our own,
    store-backed catchup commits), relay is immediate. Off-knob nets
    never hold."""
    import time as _time

    from tendermint_tpu.consensus.reactor import VOTE_RELAY_DELAY

    class _V:
        height, round_, type_, validator_index = 5, 0, VOTE_TYPE_PREVOTE, 1

    r = ConsensusReactor(_ConState(gossip_dedup=True))
    assert r._relay_ready(_V())  # unstamped: our own vote

    key = (5, 0, VOTE_TYPE_PREVOTE, 1)
    r.con_s.vote_recv_mono[key] = _time.monotonic()
    assert not r._relay_ready(_V())  # just received: held
    r.con_s.vote_recv_mono[key] = _time.monotonic() - VOTE_RELAY_DELAY - 0.01
    assert r._relay_ready(_V())  # hold expired: genuinely needed

    r_off = ConsensusReactor(_ConState(gossip_dedup=False))
    r_off.con_s.vote_recv_mono[key] = _time.monotonic()
    assert r_off._relay_ready(_V())  # pre-round-20 gossip: no hold


def test_adaptive_relay_delay_clamp_and_fallback():
    """Round 21 satellite: the lazy-relay hold tracks 2x the smoothed
    peer RTT, clamped to [0.5x, 4x] of the constant; no samples keeps
    the constant exactly."""
    from tendermint_tpu.consensus.reactor import (
        VOTE_RELAY_DELAY,
        VOTE_RELAY_DELAY_MAX,
        VOTE_RELAY_DELAY_MIN,
        adaptive_relay_delay,
    )

    assert VOTE_RELAY_DELAY_MIN == pytest.approx(0.5 * VOTE_RELAY_DELAY)
    assert VOTE_RELAY_DELAY_MAX == pytest.approx(4.0 * VOTE_RELAY_DELAY)
    # no samples: the constant, byte-for-byte
    assert adaptive_relay_delay(None) == VOTE_RELAY_DELAY
    # fast LAN: clamps at the floor, never disables the hold
    assert adaptive_relay_delay(0.0005) == VOTE_RELAY_DELAY_MIN
    assert adaptive_relay_delay(0.0) == VOTE_RELAY_DELAY_MIN
    # mid-range: tracks 2x RTT
    assert adaptive_relay_delay(0.08) == pytest.approx(0.16)
    # slow WAN / garbage sample: clamps at the ceiling
    assert adaptive_relay_delay(1.5) == VOTE_RELAY_DELAY_MAX


def test_reactor_relay_delay_reads_rtt_ewma():
    """The reactor's hold: constant with no switch, no registry, or no
    samples; RTT-adaptive once the switch's registry carries ping
    samples (fed by PeerConnMetrics.pong_received)."""
    from tendermint_tpu.consensus.reactor import VOTE_RELAY_DELAY
    from tendermint_tpu.libs import telemetry
    from tendermint_tpu.p2p.telemetry import peer_metrics

    r = ConsensusReactor(_ConState(gossip_dedup=True))
    assert r._relay_delay() == VOTE_RELAY_DELAY  # no switch at all

    class _Switch:
        metrics_registry = None

    r.switch = _Switch()
    assert r._relay_delay() == VOTE_RELAY_DELAY  # switch, no registry

    reg = telemetry.Registry()  # fresh: no cross-test samples
    r.switch.metrics_registry = reg
    assert r._relay_delay() == VOTE_RELAY_DELAY  # registry, no samples

    peer_metrics(reg)["ping_rtt_ewma"].observe(0.08)
    assert r._relay_delay() == pytest.approx(0.16)
    # EWMA moves with new samples, and the clamp still rules
    for _ in range(64):
        peer_metrics(reg)["ping_rtt_ewma"].observe(5.0)
    assert r._relay_delay() == pytest.approx(4.0 * VOTE_RELAY_DELAY)


def test_rtt_ewma_smoothing():
    from tendermint_tpu.p2p.telemetry import RttEwma

    e = RttEwma()
    assert e.value() is None
    e.observe(0.1)
    assert e.value() == pytest.approx(0.1)  # first sample seeds exactly
    e.observe(0.2)
    assert e.value() == pytest.approx(0.1 + 0.2 * (0.2 - 0.1))


def test_vote_recv_stamp_is_bounded():
    """The stamp map self-prunes on overflow — entries only matter for
    one gossip tick, so unbounded growth would be a leak, not memory."""
    import time as _time

    from tendermint_tpu.consensus.state import ConsensusState

    class _S:
        vote_recv_mono: dict = {}

    stamp = ConsensusState._stamp_vote_recv
    s = _S()

    class _V:
        def __init__(self, h):
            self.height, self.round_ = h, 0
            self.type_, self.validator_index = VOTE_TYPE_PREVOTE, h % 100

    for h in range(4096):
        stamp(s, _V(h))
    assert len(s.vote_recv_mono) == 4096
    # age everything out, then one more stamp triggers the sweep
    for k in list(s.vote_recv_mono):
        s.vote_recv_mono[k] = _time.monotonic() - 10.0
    stamp(s, _V(5000))
    assert len(s.vote_recv_mono) == 1


# -- duplicate-ratio direction ------------------------------------------------


def test_announcements_reduce_redundant_sends_across_peer_fan_out():
    """The ratio direction, deterministically: one vote, three peers.
    Without announcements every peer gets a push (3 sends, 2 of which
    the receiving side would count as duplicates once the vote has
    propagated); with HasVotes applied from two peers, only the silent
    one is picked for — redundant sends drop 3 -> 1. This is the causal
    core of the duplicate-ratio drop the n=10 process A/B in
    benches/bench_localnet.py asserts wall-clock."""
    vs = _VoteSet(5, 0, VOTE_TYPE_PREVOTE, [1])

    def fresh_peer():
        ps = PeerState(peer=None)
        ps.prs.height, ps.prs.round_ = 5, 0
        ps.ensure_vote_bit_arrays(5, 4)
        return ps

    peers = [fresh_peer() for _ in range(3)]
    assert sum(ps.pick_vote_to_send(vs) is not None for ps in peers) == 3

    announce = msgs.HasVoteMessage(5, 0, VOTE_TYPE_PREVOTE, 1)
    assert peers[0].apply_has_vote(announce)
    assert peers[1].apply_has_vote(announce)
    picked = [ps.pick_vote_to_send(vs) is not None for ps in peers]
    assert picked == [False, False, True]


@pytest.mark.slow
def test_duplicate_ratio_counters_move_on_live_net(tmp_path):
    """The PR-17 counters and the round-20 dedup counters all move in
    their right directions on a live 4-node real-TCP net with dedup on:
    votes are accepted, the 2NxN redundancy registers as duplicates
    (never negative, never counted as accepts), the ratio is finite,
    and the dedup plumbing demonstrably engages (announcements applied,
    part screens sent AND applied). The wall-clock on-vs-off ratio drop
    is asserted at n=10 REAL PROCESSES in benches/bench_localnet.py —
    at 4 in-process nodes under one GIL the scheduler noise swamps the
    few-percent gain."""
    from tests.netchaos_common import ChaosNet

    net = ChaosNet(4, str(tmp_path / "dedup-on"), gossip_dedup=True)
    net.start()
    try:
        assert net.wait_height(6, timeout=150), net.heights()
        dups = sum(n.consensus_state.vote_duplicates for n in net.nodes)
        acc = sum(n.consensus_state.vote_accepted for n in net.nodes)
        applied = sum(n.consensus_reactor.has_votes_applied for n in net.nodes)
        part_sent = sum(
            n.consensus_reactor.part_announces_sent for n in net.nodes
        )
        part_applied = sum(
            n.consensus_reactor.part_announces_applied for n in net.nodes
        )
    finally:
        net.stop()
    # 4 validators x 2 vote types x >=5 heights x 4 nodes: accepts move
    assert acc >= 4 * 2 * 5 * 4
    # redundant pushes exist at all (the problem being engineered down)
    # and land on the duplicates counter, not the accepts
    assert dups > 0
    ratio = dups / acc
    assert 0 < ratio < 10, ratio
    # the dedup mechanisms engaged: announcements fed the mirrors and
    # part screens crossed the wire in both directions
    assert applied > 0
    assert part_sent > 0
    assert part_applied > 0


@pytest.mark.slow
def test_dedup_reduces_duplicate_ratio_on_live_net(tmp_path):
    """The directional claim on a live 4-node real-TCP net: dedup on
    (HasVote exploitation + lazy-relay hold) yields a strictly lower
    fleet duplicate-vote ratio than off, at real commit pacing (the
    hold needs a cadence where announcements can land; the unthrottled
    test preset commits heights faster than a gossip tick). The
    process-scale A/B at n=10 is asserted in benches/bench_localnet.py."""
    from tests.netchaos_common import ChaosNet

    def ratio(dedup: bool, sub: str) -> float:
        net = ChaosNet(
            4, str(tmp_path / sub), gossip_dedup=dedup,
            height_throttle_s=0.25,
        )
        net.start()
        try:
            assert net.wait_height(10, timeout=150), net.heights()
            dups = sum(n.consensus_state.vote_duplicates for n in net.nodes)
            acc = sum(n.consensus_state.vote_accepted for n in net.nodes)
        finally:
            net.stop()
        assert acc > 0
        return dups / acc

    on = ratio(True, "dedup-on")
    off = ratio(False, "dedup-off")
    assert on < off, f"dedup did not reduce duplicates: on={on:.3f} off={off:.3f}"
