"""WAL torture tier (round 9): crash a REAL node subprocess at chosen byte
offsets of its WAL append stream (and at chunk-rotation boundaries), then
prove restart recovers via repair + handshake + WAL replay with the
committed chain byte-identical and the node still committing.

The crash model is state/fail.py's torn-write hook: the WAL write crossing
cumulative offset B is cut at exactly B, fsynced, and the process dies —
the on-disk image is what a power failure at that instant leaves. The
companion in-process tier (tests/test_wal_repair.py) sweeps EVERY byte
offset of the repair logic cheaply; this tier samples offsets end-to-end
through a real node (full sweep: the slow-marked test).

Per cycle, the invariants of docs/crash-recovery.md:
- restart reaches a height past the pre-crash chain and commits a fresh tx;
- every height committed before the crash is BYTE-IDENTICAL after recovery
  (block hash, part-set root, app hash, txs) — repair/replay never forks
  or rewrites history;
- no height at or below the last synced #ENDHEIGHT is lost;
- the metrics RPC reports the v2 WAL (wal_format=2) and any repair.

Scaffolding shared with tests/test_persist.py via consensus_common.
"""

from __future__ import annotations

import os
import signal
import subprocess
import time

import pytest

from consensus_common import free_port, init_node_home, node_proc, rpc, wait_height

CYCLE_DEADLINE_S = 90


def _store_fingerprints(home: str) -> dict:
    """h -> (block hash, part-set root, app hash, txs) from the on-disk
    block store; byte-identity of these across a recovery is the
    no-fork/no-rewrite invariant."""
    from tendermint_tpu.blockchain.store import BlockStore
    from tendermint_tpu.libs.db import FileDB

    store = BlockStore(FileDB(os.path.join(home, "data", "blockstore.db")))
    out = {}
    for h in range(1, store.height() + 1):
        meta = store.load_block_meta(h)
        block = store.load_block(h)
        out[h] = (
            meta.block_id.hash.hex(),
            meta.block_id.parts_header.hash.hex(),
            block.header.app_hash.hex(),
            tuple(tx.hex() for tx in (block.data.txs or [])),
        )
    return out


def _wal_last_synced_endheight(home: str) -> int:
    """Last #ENDHEIGHT surviving in the crash image, via the READ-ONLY
    view: read_wal_lines stops at a damaged frame exactly where repair
    would cut, but never mutates the image — the restarting node must run
    its OWN repair-on-open, not inherit one this helper already did."""
    from tendermint_tpu.consensus.wal import decode_wal_line, read_wal_lines

    try:
        lines = read_wal_lines(os.path.join(home, "data", "cs.wal", "wal"))
    except FileNotFoundError:
        return -1  # crash landed before the WAL head existed
    last = -1
    for line in lines:
        try:
            decoded = decode_wal_line(line)
        except Exception:
            continue
        if decoded and decoded[0] == "endheight":
            last = decoded[1]
    return last


def _wait_exit(proc, deadline_s: float):
    deadline = time.time() + deadline_s
    while proc.poll() is None and time.time() < deadline:
        time.sleep(0.2)
    return proc.poll()


def _stop(proc) -> None:
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(15)
    except subprocess.TimeoutExpired:
        proc.kill()
    if proc.stdout:
        proc.stdout.close()


def torture_cycle(tmp_path, name: str, crash_env: dict) -> str:
    """One crash/recover cycle; returns a short outcome tag."""
    home = str(tmp_path / name)
    init_node_home(home, f"torture-{name}")

    port = free_port()
    proc = node_proc(home, port, extra_env=crash_env)
    rc = _wait_exit(proc, CYCLE_DEADLINE_S)
    if rc is None:
        # offset beyond what this run wrote before the cycle deadline —
        # a sampled-sweep cycle may legitimately not reach it
        _stop(proc)
        return "offset-not-reached"
    out = proc.stdout.read().decode(errors="replace")[-2000:]
    proc.stdout.close()
    assert rc == 99, f"{name}: expected torn-write crash exit 99, got {rc}:\n{out}"

    pre = _store_fingerprints(home)
    h_sync = _wal_last_synced_endheight(home)
    # durability floor: a height whose #ENDHEIGHT fsynced can never be lost
    assert max(pre, default=0) >= h_sync, (
        f"{name}: store lost height {h_sync} behind a synced ENDHEIGHT"
    )

    port = free_port()
    proc = node_proc(home, port)
    try:
        target = max(pre, default=0) + 1
        h = wait_height(port, target, CYCLE_DEADLINE_S)
        assert h >= target, (
            f"{name}: no recovery past pre-crash height {max(pre, default=0)}"
            f" (h={h})"
        )
        res = rpc(
            port, "broadcast_tx_commit", timeout=30,
            tx=f"torture-{name}=1".encode().hex(),
        )
        assert res["deliver_tx"]["code"] == 0, res
        m = rpc(port, "metrics", timeout=10)
        assert m.get("wal_format") == 2, m.get("wal_format")
    finally:
        _stop(proc)

    post = _store_fingerprints(home)
    for height, fp in pre.items():
        assert post[height] == fp, (
            f"{name}: height {height} rewritten by recovery:\n"
            f"pre={fp}\npost={post[height]}"
        )
    assert max(post, default=0) > max(pre, default=0)
    return "recovered"


# deterministic tier-1 sample: one offset inside height-1's record stream,
# so the cycle tears REAL records and restart runs a REAL repair (tier-1
# keeps exactly one end-to-end subprocess cycle for budget — the
# in-process tests/test_wal_repair.py sweep stays exhaustive per byte,
# and the dense subprocess sweep below rides the slow tier)
TIER1_OFFSETS = (180,)
SLOW_OFFSETS = tuple(range(0, 64)) + tuple(range(64, 1200, 11))


def test_torn_write_sampled_offsets(tmp_path):
    outcomes = {}
    for b in TIER1_OFFSETS:
        outcomes[b] = torture_cycle(
            tmp_path, f"b{b}",
            {"FAIL_TEST_MODE": "torn_write", "FAIL_TEST_WAL_BYTES": b},
        )
    # small offsets land within the first height's records: every tier-1
    # cycle must actually crash and recover, not skip
    assert set(outcomes.values()) == {"recovered"}, outcomes


# -- round 14: execution-pipeline stage boundaries ---------------------------
#
# The pipelined finalize (docs/execution-pipeline.md) writes the block +
# WAL #ENDHEIGHT SYNCHRONOUSLY, then defers apply/hook/events to the
# executor thread. The named pipeline_point() crash tier (state/fail.py)
# dies exactly at the new stage boundaries; restart must recover via the
# same WAL repair + handshake + replay path, with every pre-crash height
# byte-identical — the "marker precedes a crashed deferred apply" image
# is the handshake's store==state+1 case.


def test_pipeline_crash_before_deferred_apply(tmp_path):
    """Die on the executor thread AFTER block save + #ENDHEIGHT landed
    but BEFORE the deferred apply touched the app (the third pipelined
    height, so recovered history spans applied AND unapplied heights)."""
    tag = torture_cycle(
        tmp_path, "pipe-pre-apply",
        {
            "FAIL_TEST_MODE": "pipeline",
            "FAIL_TEST_PIPELINE_POINT": "pre_apply",
            "FAIL_TEST_PIPELINE_HITS": 2,
        },
    )
    assert tag == "recovered", tag


def test_pipeline_crash_mid_parallel_apply(tmp_path):
    """Die INSIDE the sharded kvstore apply — after the shard workers
    folded, before the deterministic merge mutates the app. Needs a
    multi-tx block, so this cycle injects a burst while the point is
    armed (the shared torture_cycle only waits for the crash)."""
    home = str(tmp_path / "pipe-mid")
    init_node_home(home, "torture-pipe-mid")
    port = free_port()
    proc = node_proc(home, port, extra_env={
        "FAIL_TEST_MODE": "pipeline",
        "FAIL_TEST_PIPELINE_POINT": "mid_parallel_apply",
        "TENDERMINT_KVSTORE_SHARDS": 2,
        "TENDERMINT_KVSTORE_SHARD_MIN": 2,
    })
    try:
        assert wait_height(port, 1, CYCLE_DEADLINE_S) >= 1
        # burst of async txs: the first block carrying >= 2 of them takes
        # the sharded path and dies at the armed point
        deadline = time.time() + CYCLE_DEADLINE_S
        i = 0
        while proc.poll() is None and time.time() < deadline:
            try:
                rpc(port, "broadcast_tx_async", timeout=2,
                    tx=f"burst{i}={i}".encode().hex())
            except Exception:
                pass  # the process may die mid-request — that's the point
            i += 1
            time.sleep(0.02)
        rc = proc.poll()
        assert rc == 99, f"expected mid-parallel-apply crash exit 99, got {rc}"
    finally:
        if proc.poll() is None:
            _stop(proc)
        elif proc.stdout:
            proc.stdout.close()

    pre = _store_fingerprints(home)
    assert pre, "crash landed before any committed height"
    h_sync = _wal_last_synced_endheight(home)
    assert max(pre, default=0) >= h_sync

    port = free_port()
    proc = node_proc(home, port)
    try:
        target = max(pre, default=0) + 1
        assert wait_height(port, target, CYCLE_DEADLINE_S) >= target
        res = rpc(port, "broadcast_tx_commit", timeout=30,
                  tx=b"post-crash=1".hex())
        assert res["deliver_tx"]["code"] == 0, res
    finally:
        _stop(proc)
    post = _store_fingerprints(home)
    for height, fp in pre.items():
        assert post[height] == fp, (
            f"height {height} rewritten after mid-parallel-apply recovery"
        )


def _rotation_cycle(tmp_path, phase: str) -> None:
    tag = torture_cycle(
        tmp_path, f"rot-{phase}",
        {
            "FAIL_TEST_MODE": "rotate_crash",
            "FAIL_TEST_ROTATE_INDEX": 0,
            "FAIL_TEST_ROTATE_PHASE": phase,
            "TENDERMINT_WAL_CHUNK_BYTES": 700,
        },
    )
    assert tag == "recovered", (phase, tag)


@pytest.mark.slow
def test_rotation_boundary_crash_post_replace(tmp_path):
    """Die right after the os.replace publishing a chunk — the nastiest
    boundary image (numbered chunks, NO head file): restart must serve
    the records across the boundary and keep committing. (Both subprocess
    rotation phases ride the slow tier for tier-1 budget; tier 1 covers
    the same disk images in-process via TestRotationBoundary, notably
    test_missing_head_after_rotation_crash.)"""
    _rotation_cycle(tmp_path, "post")


@pytest.mark.slow
def test_rotation_boundary_crash_pre_replace(tmp_path):
    _rotation_cycle(tmp_path, "pre")


@pytest.mark.slow
def test_legacy_wal_home_still_recovers(tmp_path):
    """A pre-round-9 node home (JSON-line WAL written by the old format)
    must handshake + replay + keep committing after upgrade."""
    home = str(tmp_path / "legacy")
    init_node_home(home, "torture-legacy")
    # run clean to height 2, stop, then rewrite the WAL as legacy lines
    port = free_port()
    proc = node_proc(home, port)
    try:
        assert wait_height(port, 2, CYCLE_DEADLINE_S) >= 2
    finally:
        _stop(proc)
    pre = _store_fingerprints(home)

    from tendermint_tpu.consensus.wal import WAL

    wal_path = os.path.join(home, "data", "cs.wal", "wal")
    wal = WAL(wal_path)
    lines = wal.read_all_lines()
    wal.group.close()
    assert lines, "recorded run produced no WAL records"
    from tendermint_tpu.libs.autofile import Group

    for p in Group.list_chunks(wal_path):
        os.unlink(p)
    with open(wal_path, "w") as f:
        f.write("".join(ln + "\n" for ln in lines))

    port = free_port()
    proc = node_proc(home, port)
    try:
        target = max(pre, default=0) + 1
        assert wait_height(port, target, CYCLE_DEADLINE_S) >= target
        m = rpc(port, "metrics", timeout=10)
        assert m.get("wal_format") == 1, "legacy WAL must stay legacy"
    finally:
        _stop(proc)
    post = _store_fingerprints(home)
    for height, fp in pre.items():
        assert post[height] == fp


@pytest.mark.slow
def test_torn_write_full_sweep(tmp_path):
    """Dense byte-offset sweep through a real node: step 1 across the
    deterministic head (magic + seeded ENDHEIGHT frame + first records),
    then strided through height 1-2's stream. Every reached offset must
    recover; the in-process tier already proves every-offset repair."""
    outcomes: dict[int, str] = {}
    for b in SLOW_OFFSETS:
        outcomes[b] = torture_cycle(
            tmp_path, f"s{b}",
            {"FAIL_TEST_MODE": "torn_write", "FAIL_TEST_WAL_BYTES": b},
        )
    recovered = sum(1 for v in outcomes.values() if v == "recovered")
    not_reached = [b for b, v in outcomes.items() if v == "offset-not-reached"]
    assert recovered >= len(SLOW_OFFSETS) * 0.9, (
        f"only {recovered}/{len(SLOW_OFFSETS)} offsets recovered; "
        f"unreached: {not_reached}"
    )
    assert all(v in ("recovered", "offset-not-reached") for v in outcomes.values())
