"""Full-node + JSON-RPC tests (reference test models: rpc/client/rpc_test.go,
rpc/test/helpers.go — start a real node in-process, drive it over RPC)."""

from __future__ import annotations

import json
import re
import tempfile
import time
import urllib.request

import pytest

from tendermint_tpu.config import reset_test_root
from tendermint_tpu.node import default_new_node
from tendermint_tpu.rpc.client import HTTPClient, RPCClientError, WSClient


def wait_until(cond, timeout=30.0, tick=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(tick)
    return cond()


@pytest.fixture(scope="module")
def node():
    tmp = tempfile.mkdtemp(prefix="node-test-")
    cfg = reset_test_root(tmp)
    cfg.base.proxy_app = "kvstore"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    n = default_new_node(cfg)
    n.start()
    assert wait_until(lambda: n.block_store.height() >= 1, timeout=30)
    yield n
    n.stop()


@pytest.fixture(scope="module")
def client(node):
    return HTTPClient(f"127.0.0.1:{node.rpc_port()}")


def test_status(node, client):
    res = client.status()
    assert res["latest_block_height"] >= 1
    assert res["node_info"]["moniker"] == node.config.base.moniker
    assert len(res["latest_app_hash"]) >= 0


def test_abci_info_and_query(node, client):
    res = client.abci_info()
    assert res["response"]["last_block_height"] >= 0


def test_broadcast_tx_commit_and_lookup(node, client):
    tx = b"rpc-key=rpc-value"
    res = client.broadcast_tx_commit(tx=tx.hex())
    assert res["check_tx"]["code"] == 0
    assert res["deliver_tx"]["code"] == 0
    assert res["height"] >= 1
    # abci_query sees the committed value
    q = client.abci_query(data=b"rpc-key".hex())
    assert bytes.fromhex(q["response"]["value"]) == b"rpc-value"
    # tx indexer lookup with merkle proof
    got = client.tx(hash=res["hash"], prove=True)
    assert bytes.fromhex(got["tx"]) == tx
    assert got["height"] == res["height"]
    assert got["proof"] is not None


def test_broadcast_tx_sync_and_unconfirmed(node, client):
    res = client.broadcast_tx_sync(tx=b"sync-key=sync-val".hex())
    assert res["code"] == 0
    res2 = client.num_unconfirmed_txs()
    assert res2["n_txs"] >= 0  # may already be reaped


def test_block_and_blockchain_and_commit(node, client):
    assert wait_until(lambda: node.block_store.height() >= 2)
    res = client.block(height=1)
    assert res["block"]["header"]["height"] == 1
    info = client.blockchain(min_height=1, max_height=2)
    assert info["last_height"] >= 2
    assert len(info["block_metas"]) == 2
    cmt = client.commit(height=1)
    assert cmt["canonical_commit"] is True
    assert cmt["commit"] is not None


def test_validators_and_genesis_and_net_info(node, client):
    vals = client.validators()
    assert len(vals["validators"]["validators"]) == 1
    # historical form: the set that signed height 1 (light-client pairing
    # with /commit — docs/specification/light-client-protocol.md)
    assert wait_until(lambda: node.block_store.height() >= 1)
    hist = client.validators(height=1)
    assert hist["block_height"] == 1
    assert len(hist["validators"]["validators"]) == 1
    import pytest as _pytest

    with _pytest.raises(Exception):
        client.validators(height=10_000)
    gen = client.genesis()
    assert gen["genesis"]["chain_id"] == node.genesis_doc.chain_id
    ni = client.net_info()
    assert ni["listening"] is True


def test_dump_consensus_state(node, client):
    res = client.dump_consensus_state()
    assert res["round_state"]["height"] >= 1


def test_uri_transport(node, client):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{node.rpc_port()}/status", timeout=10
    ) as resp:
        body = json.loads(resp.read().decode())
    assert body["result"]["latest_block_height"] >= 1


def test_unknown_method_and_bad_params(node, client):
    with pytest.raises(RPCClientError, match="unknown RPC method"):
        client.call("no_such_method")
    with pytest.raises(RPCClientError, match="unknown parameter"):
        client.call("block", bogus=1)
    with pytest.raises(RPCClientError):
        client.block(height=10**9)


def test_websocket_subscription(node, client):
    ws = WSClient(f"127.0.0.1:{node.rpc_port()}")
    try:
        ws.subscribe("NewBlock")
        ev = ws.next_event(timeout=30)
        assert ev["event"] == "NewBlock"
        assert ev["data"]["block"]["header"]["height"] >= 1
        # RPC over the same websocket
        res = ws.call("status")
        assert res["latest_block_height"] >= 1
        ws.unsubscribe("NewBlock")
    finally:
        ws.close()


def test_unsafe_routes_gated(node, client):
    with pytest.raises(RPCClientError, match="unknown RPC method"):
        client.unsafe_flush_mempool()


def test_commit_missing_meta_is_rpc_error():
    """A height inside the valid range whose meta is missing (pruned /
    mid-write) must surface as RPCError, not AttributeError."""
    import pytest as _pytest

    from tendermint_tpu.rpc.core.handlers import RPCError, commit

    class _Store:
        def height(self):
            return 5

        def base(self):
            return 1

        def load_block_meta(self, h):
            return None

    class _Ctx:
        block_store = _Store()

    with _pytest.raises(RPCError):
        commit(_Ctx(), 3)


def test_light_client_verifies_headers_and_txs(node, client):
    """rpc/light.py against a live node: bootstrap trust from genesis,
    advance through real heights, verify a header + tx inclusion proof,
    and reject tampering (docs/specification/light-client-protocol.md)."""
    from tendermint_tpu.rpc.light import LightClient, LightClientError
    from tendermint_tpu.types.tx import tx_hash

    # commit a tx so there's something to prove
    tx = b"light-key=light-value"
    res = client.broadcast_tx_commit(tx=tx.hex())
    tx_height = res["height"]
    assert wait_until(lambda: node.block_store.height() >= tx_height + 1)

    lc = LightClient.from_genesis(client)
    lc.advance(tx_height)
    assert lc.height == tx_height
    header = lc.verify_header(tx_height)
    assert header.height == tx_height

    # the tx's inclusion proof checks out against the verified header
    verified = lc.verify_tx(tx_hash(tx), header)
    assert bytes.fromhex(verified["tx"]) == tx

    # tampering: a wrong chain id must fail
    bad = LightClient.from_genesis(client)
    bad.chain_id = "not-the-chain"
    with pytest.raises(LightClientError):
        bad.verify_header(1)

    # tampering: a forged validator set must fail
    from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
    from tendermint_tpu.types.validator import Validator
    from tendermint_tpu.types.validator_set import ValidatorSet

    forged = LightClient.from_genesis(client)
    forged.validators = ValidatorSet(
        [Validator.new(gen_priv_key_ed25519().pub_key(), 1)]
    )
    with pytest.raises(LightClientError):
        forged.verify_header(1)


def test_metrics_endpoint(node, client):
    m = client.metrics()
    assert m["consensus_height"] >= 1
    assert m["blockstore_height"] >= 1
    assert m["mempool_size"] >= 0
    assert "p2p_peers_outbound" in m and "p2p_peers_inbound" in m
    assert "gateway_verify_tpu_sigs" in m
    assert m["consensus_peer_msg_drops"] == 0  # healthy node drops nothing
    assert "gateway_hash_cpu_leaves" in m
    # the Hasher's streamed-transport gauges must surface through the
    # metrics RPC unconditionally (zeros off the devd route) — the PR-1
    # Verifier stream gauges only had client-side coverage, which let a
    # stats()-shape regression hide from the RPC surface
    for gauge in ("gateway_hash_stream_lanes", "gateway_hash_stream_batches",
                  "gateway_hash_stream_bytes_out", "gateway_hash_stream_trees",
                  "gateway_hash_stream_reconnects",
                  "gateway_hash_tx_root_cache_hits"):
        assert gauge in m, gauge
    assert all(isinstance(v, (int, float)) for v in m.values()), m


# round 11: the metrics RPC renders from the telemetry registry
# (node/telemetry.py). This is the COMPLETENESS contract — every
# subsystem's gauges present under their canonical <plane>_<name> on a
# real node — so a future wiring/rename regression fails here, loudly.
METRICS_REQUIRED_KEYS = (
    # consensus plane
    "consensus_height", "consensus_round", "consensus_step",
    "consensus_height_seconds_last", "consensus_height_seconds_max",
    "consensus_peer_msg_drops",
    # pipelined execution plane (round 14)
    "consensus_pipeline_applies",
    "consensus_pipeline_join_wait_seconds",
    "consensus_pipeline_overlap_seconds",
    # big-committee vote plane (round 16)
    "consensus_vote_batches", "consensus_vote_batched_sigs",
    "consensus_vote_singletons",
    # block store
    "blockstore_height", "blockstore_base",
    # WAL durability plane (present once consensus started)
    "wal_format", "wal_records", "wal_fsyncs", "wal_pending",
    "wal_group_size", "wal_repairs", "wal_sync_age_s",
    # evidence + mempool
    "evidence_count", "mempool_size",
    # p2p (round 15 adds the flat aggregates over the labeled
    # p2p_peer_* gossip families — the wedge signal on the legacy dict)
    "p2p_peers_outbound", "p2p_peers_inbound", "p2p_peers_dialing",
    "p2p_peer_send_failures", "p2p_peer_vote_gossip_picks",
    "p2p_peer_vote_gossip_sends", "p2p_peer_vote_gossip_send_failures",
    "p2p_peer_catchup_commits",
    # health plane (round 15): the /health verdict as flat gauges
    "node_health_status", "node_health_height_age_s",
    "node_health_checks_degraded", "node_health_checks_failing",
    # fast sync
    "fastsync_active", "fastsync_blocks_synced",
    "fastsync_rate_blocks_per_sec", "fastsync_apply_s",
    # statesync (reactor serves unconditionally)
    "statesync_restore_active", "statesync_snapshots",
    "statesync_chunks_served", "statesync_chunk_failures",
    "statesync_peers_banned", "statesync_load_failures",
    # gateway verify plane
    "gateway_verify_tpu_batches", "gateway_verify_tpu_sigs",
    "gateway_verify_cpu_sigs",
    # gateway hash plane
    "gateway_hash_tpu_part_batches", "gateway_hash_tpu_leaves",
    "gateway_hash_cpu_leaves", "gateway_hash_tx_root_cache_hits",
    "gateway_hash_batch_bytes", "gateway_hash_stream_batches",
)


def test_metrics_completeness_every_plane_present(node, client):
    m = client.metrics()
    missing = [k for k in METRICS_REQUIRED_KEYS if k not in m]
    assert not missing, f"metrics RPC lost gauges: {missing}"


PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'
    r" (\+Inf|-Inf|[0-9.eE+-]+)$"
)


def test_prometheus_exposition_endpoint(node):
    """GET /metrics serves valid text exposition 0.0.4: >= 40 families
    spanning every plane, HELP/TYPE per family, every sample line
    parseable, histogram families present."""
    with urllib.request.urlopen(
        f"http://127.0.0.1:{node.rpc_port()}/metrics", timeout=10
    ) as resp:
        assert resp.headers["Content-Type"].startswith(
            "text/plain; version=0.0.4"
        )
        text = resp.read().decode()
    families: dict[str, str] = {}
    helps = set()
    for line in text.strip().splitlines():
        if line.startswith("# HELP "):
            helps.add(line.split()[2])
        elif line.startswith("# TYPE "):
            _h, _t, name, kind = line.split()
            families[name] = kind
        else:
            assert PROM_LINE.match(line), line
    assert len(families) >= 40, f"only {len(families)} families"
    assert set(families) <= helps, "family missing its HELP line"
    # one family per plane the acceptance bar names (statetree_*: the
    # kvstore app carries the round-13 authenticated tree, scrape-only)
    for fam in ("consensus_height", "wal_format", "gateway_verify_tpu_sigs",
                # round 16: the big-committee vote-plane counters
                "consensus_vote_batches", "consensus_vote_singletons",
                "gateway_hash_tpu_leaves", "gateway_breaker_state",
                "mempool_size", "statesync_snapshots", "fastsync_active",
                "p2p_peers_outbound", "statetree_size", "statetree_commits",
                # round 15: health verdict + the per-peer queue gauges
                "node_health_status", "node_health_height_age_s",
                "p2p_peer_send_queue", "p2p_peer_send_queue_high_water",
                "p2p_peer_last_recv_age_seconds"):
        assert fam in families, fam
        assert families[fam] == "gauge"
    # round 15: the labeled per-peer gossip families are present (and
    # typed) from the first scrape even with zero peers — family
    # materialization is what makes churned series collapse instead of
    # appearing late
    for fam in ("p2p_peer_send_bytes_total", "p2p_peer_recv_bytes_total",
                "p2p_peer_send_msgs_total", "p2p_peer_recv_msgs_total",
                "p2p_peer_send_failures_total",
                "p2p_peer_vote_gossip_picks_total",
                "p2p_peer_vote_gossip_sends_total",
                "p2p_peer_vote_gossip_send_failures_total",
                "p2p_peer_catchup_commits_total"):
        assert families.get(fam) == "counter", fam
    # the latency-distribution instruments render as real histograms
    for fam in ("devd_stream_chunk_seconds", "devd_single_shot_seconds",
                "wal_fsync_seconds", "wal_group_records",
                "gateway_hash_batch_seconds",
                # round 14: the execution-pipeline distributions
                "consensus_height_seconds", "pipeline_join_wait_seconds",
                "pipeline_overlap_seconds",
                # round 16: the vote micro-batch distribution
                "consensus_vote_verify_batch_seconds",
                # round 15: gossip-arrival distributions + per-peer RTT
                "consensus_quorum_seconds", "consensus_first_part_seconds",
                "p2p_peer_ping_rtt_seconds"):
        assert families.get(fam) == "histogram", fam
    # a live node has fsynced (group commit): the histogram has samples
    count = next(
        l for l in text.splitlines() if l.startswith("wal_fsync_seconds_count")
    )
    assert float(count.rsplit(" ", 1)[1]) >= 1


def test_consensus_trace_rpc_segments_sum_to_wall(node, client):
    """consensus_trace reconstructs a committed height's wall time into
    named segments that sum to within 5% of the height's wall clock,
    with device-vs-CPU attribution attached."""
    assert wait_until(lambda: node.block_store.height() >= 2)
    traces = client.consensus_trace(last=5)["traces"]
    assert traces, "no completed heights traced"
    heights = [t["height"] for t in traces]
    assert heights == sorted(heights, reverse=True), "newest first"
    for t in traces:
        assert t["segments"], t
        total = sum(t["segments"].values())
        tol = max(0.05 * t["wall_s"], 0.005)  # floor for sub-ms heights
        assert abs(total - t["wall_s"]) <= tol, (total, t["wall_s"])
        # the commit machinery segments exist on every committed height.
        # Round 14: with the pipelined execution plane (the default) the
        # apply runs on the executor and is attributed to the height it
        # OVERLAPS as the overlap_apply_s aux note — the lowest traced
        # height carries neither (its apply credited to its successor)
        for seg in ("commit", "block_save"):
            assert seg in t["segments"], t["segments"]
        if t["height"] > min(heights):
            assert (
                "apply" in t["segments"] or "overlap_apply_s" in t["aux"]
            ), t
        dev = t["device"]
        for k in ("verify_tpu_sigs", "verify_cpu_sigs",
                  "hash_tpu_leaves", "hash_cpu_leaves"):
            assert k in dev, dev
        # CPU-route node: breaker not engaged, work attributed to CPU
        assert dev["breaker_state_end"] == -1
    # a single-validator CPU node verifies its own precommits on CPU
    assert any(
        t["device"]["verify_cpu_sigs"] > 0 or t["device"]["hash_cpu_leaves"] > 0
        for t in traces
    )
    # the operator CLI renders the same traces without raising
    import io

    from tendermint_tpu.ops.trace import render

    buf = io.StringIO()
    render(traces, out=buf)
    assert f"height {heights[0]}" in buf.getvalue()


def test_consensus_trace_carries_gossip_arrivals(node, client):
    """Round 15: every committed height's trace carries wall-clock
    gossip arrival marks in causal order — the raw material the fleet
    aggregator joins across nodes."""
    assert wait_until(lambda: node.block_store.height() >= 2)
    traces = client.consensus_trace(last=3)["traces"]
    assert traces
    for t in traces:
        arr = t["arrivals"]
        # a sole validator self-delivers its proposal: every mark exists
        for key in ("proposal", "first_block_part", "prevote_quorum",
                    "precommit_quorum", "commit"):
            assert key in arr, (key, arr)
        assert t["started_at"] <= arr["first_block_part"] + 1e-6
        assert arr["first_block_part"] <= arr["prevote_quorum"] + 1e-6
        assert arr["prevote_quorum"] <= arr["precommit_quorum"] + 1e-6
        assert arr["precommit_quorum"] <= arr["commit"] + 1e-6
        assert arr["commit"] <= t["completed_at"] + 1e-6


def test_health_endpoint_contract(node, client):
    """GET /health (round 15, node/health.py): a live committing node is
    ok with every check reported machine-readably, and the same verdict
    rides the flat node_health_* gauges."""
    assert wait_until(lambda: node.block_store.height() >= 1)
    with urllib.request.urlopen(
        f"http://127.0.0.1:{node.rpc_port()}/health", timeout=10
    ) as resp:
        assert resp.status == 200
        body = json.loads(resp.read().decode())
    assert body["status"] == "ok" and body["code"] == 0
    for check in ("height_age", "peers", "breaker", "wal", "pipeline",
                  "mempool"):
        assert check in body["checks"], body["checks"]
        assert body["checks"][check]["status"] in ("ok", "degraded")
    assert body["checks"]["height_age"]["age_s"] >= 0
    assert body["checks"]["wal"]["open"] is True
    assert body["checks"]["pipeline"]["poisoned"] is False
    m = client.metrics()
    assert m["node_health_status"] == 0
    assert m["node_health_checks_failing"] == 0


def test_health_thresholds_flip_degraded(node, client, monkeypatch):
    """The env-knob thresholds govern the verdict live (the netchaos
    tier tightens them the same way): an impossible height-age budget
    flips the report to degraded, then failing — and the flat gauge
    follows."""
    from tendermint_tpu.node.health import health_report

    monkeypatch.setenv("TENDERMINT_HEALTH_HEIGHT_AGE_DEGRADED_S", "0")
    monkeypatch.setenv("TENDERMINT_HEALTH_HEIGHT_AGE_FAILING_S", "1e9")
    report = health_report(node)
    assert report["status"] == "degraded"
    assert report["checks"]["height_age"]["status"] == "degraded"
    assert client.metrics()["node_health_status"] == 1
    monkeypatch.setenv("TENDERMINT_HEALTH_HEIGHT_AGE_FAILING_S", "0")
    report = health_report(node)
    assert report["status"] == "failing"
    # ... and the endpoint answers 503 so k8s-style probes see it
    import urllib.error

    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{node.rpc_port()}/health", timeout=10
        )
        raise AssertionError("failing health must answer 503")
    except urllib.error.HTTPError as exc:
        assert exc.code == 503
        assert json.loads(exc.read().decode())["status"] == "failing"


def test_fleet_scrapes_single_node(node):
    """ops/fleet against a live (single) node: the aggregator
    reconstructs the per-height timeline purely from GET /metrics +
    consensus_trace + GET /health scrapes."""
    import io

    from tendermint_tpu.ops import fleet

    assert wait_until(lambda: node.block_store.height() >= 2)
    url = f"127.0.0.1:{node.rpc_port()}"
    snapshot = fleet.collect([url], last=5)
    assert "error" not in snapshot[url], snapshot[url].get("error")
    assert snapshot[url]["health"]["status"] in ("ok", "degraded")
    rows = fleet.build_timeline(
        {u: e["traces"] for u, e in snapshot.items()}, last=5
    )
    assert rows and rows[0]["height"] >= rows[-1]["height"]
    for r in rows:
        assert r["nodes_reporting"] == 1
        assert r["precommit_quorum_s_max"] is not None
        assert r["precommit_quorum_s_max"] >= 0
        # one reporter: no cross-node spreads
        assert r["commit_skew_s"] is None
    summary = fleet.fleet_summary(snapshot)
    assert summary[url]["height"] >= 2
    buf = io.StringIO()
    fleet.render(snapshot, rows, out=buf)
    assert "health ok" in buf.getvalue() or "health degraded" in buf.getvalue()
