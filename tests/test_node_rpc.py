"""Full-node + JSON-RPC tests (reference test models: rpc/client/rpc_test.go,
rpc/test/helpers.go — start a real node in-process, drive it over RPC)."""

from __future__ import annotations

import json
import re
import tempfile
import time
import urllib.request

import pytest

from tendermint_tpu.config import reset_test_root
from tendermint_tpu.node import default_new_node
from tendermint_tpu.rpc.client import HTTPClient, RPCClientError, WSClient


def wait_until(cond, timeout=30.0, tick=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(tick)
    return cond()


@pytest.fixture(scope="module")
def node():
    tmp = tempfile.mkdtemp(prefix="node-test-")
    cfg = reset_test_root(tmp)
    cfg.base.proxy_app = "kvstore"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    n = default_new_node(cfg)
    n.start()
    assert wait_until(lambda: n.block_store.height() >= 1, timeout=30)
    yield n
    n.stop()


@pytest.fixture(scope="module")
def client(node):
    return HTTPClient(f"127.0.0.1:{node.rpc_port()}")


def test_status(node, client):
    res = client.status()
    assert res["latest_block_height"] >= 1
    assert res["node_info"]["moniker"] == node.config.base.moniker
    assert len(res["latest_app_hash"]) >= 0


def test_abci_info_and_query(node, client):
    res = client.abci_info()
    assert res["response"]["last_block_height"] >= 0


def test_broadcast_tx_commit_and_lookup(node, client):
    tx = b"rpc-key=rpc-value"
    res = client.broadcast_tx_commit(tx=tx.hex())
    assert res["check_tx"]["code"] == 0
    assert res["deliver_tx"]["code"] == 0
    assert res["height"] >= 1
    # abci_query sees the committed value
    q = client.abci_query(data=b"rpc-key".hex())
    assert bytes.fromhex(q["response"]["value"]) == b"rpc-value"
    # tx indexer lookup with merkle proof
    got = client.tx(hash=res["hash"], prove=True)
    assert bytes.fromhex(got["tx"]) == tx
    assert got["height"] == res["height"]
    assert got["proof"] is not None


def test_broadcast_tx_sync_and_unconfirmed(node, client):
    res = client.broadcast_tx_sync(tx=b"sync-key=sync-val".hex())
    assert res["code"] == 0
    res2 = client.num_unconfirmed_txs()
    assert res2["n_txs"] >= 0  # may already be reaped


def test_block_and_blockchain_and_commit(node, client):
    assert wait_until(lambda: node.block_store.height() >= 2)
    res = client.block(height=1)
    assert res["block"]["header"]["height"] == 1
    info = client.blockchain(min_height=1, max_height=2)
    assert info["last_height"] >= 2
    assert len(info["block_metas"]) == 2
    cmt = client.commit(height=1)
    assert cmt["canonical_commit"] is True
    assert cmt["commit"] is not None


def test_validators_and_genesis_and_net_info(node, client):
    vals = client.validators()
    assert len(vals["validators"]["validators"]) == 1
    # historical form: the set that signed height 1 (light-client pairing
    # with /commit — docs/specification/light-client-protocol.md)
    assert wait_until(lambda: node.block_store.height() >= 1)
    hist = client.validators(height=1)
    assert hist["block_height"] == 1
    assert len(hist["validators"]["validators"]) == 1
    import pytest as _pytest

    with _pytest.raises(Exception):
        client.validators(height=10_000)
    gen = client.genesis()
    assert gen["genesis"]["chain_id"] == node.genesis_doc.chain_id
    ni = client.net_info()
    assert ni["listening"] is True


def test_dump_consensus_state(node, client):
    res = client.dump_consensus_state()
    assert res["round_state"]["height"] >= 1


def test_uri_transport(node, client):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{node.rpc_port()}/status", timeout=10
    ) as resp:
        body = json.loads(resp.read().decode())
    assert body["result"]["latest_block_height"] >= 1


def test_unknown_method_and_bad_params(node, client):
    with pytest.raises(RPCClientError, match="unknown RPC method"):
        client.call("no_such_method")
    with pytest.raises(RPCClientError, match="unknown parameter"):
        client.call("block", bogus=1)
    with pytest.raises(RPCClientError):
        client.block(height=10**9)


def test_websocket_subscription(node, client):
    ws = WSClient(f"127.0.0.1:{node.rpc_port()}")
    try:
        ws.subscribe("NewBlock")
        ev = ws.next_event(timeout=30)
        assert ev["event"] == "NewBlock"
        assert ev["data"]["block"]["header"]["height"] >= 1
        # RPC over the same websocket
        res = ws.call("status")
        assert res["latest_block_height"] >= 1
        ws.unsubscribe("NewBlock")
    finally:
        ws.close()


def test_unsafe_routes_gated(node, client):
    with pytest.raises(RPCClientError, match="unknown RPC method"):
        client.unsafe_flush_mempool()


def test_commit_missing_meta_is_rpc_error():
    """A height inside the valid range whose meta is missing (pruned /
    mid-write) must surface as RPCError, not AttributeError."""
    import pytest as _pytest

    from tendermint_tpu.rpc.core.handlers import RPCError, commit

    class _Store:
        def height(self):
            return 5

        def base(self):
            return 1

        def load_block_meta(self, h):
            return None

    class _Ctx:
        block_store = _Store()

    with _pytest.raises(RPCError):
        commit(_Ctx(), 3)


def test_light_client_verifies_headers_and_txs(node, client):
    """rpc/light.py against a live node: bootstrap trust from genesis,
    advance through real heights, verify a header + tx inclusion proof,
    and reject tampering (docs/specification/light-client-protocol.md)."""
    from tendermint_tpu.rpc.light import LightClient, LightClientError
    from tendermint_tpu.types.tx import tx_hash

    # commit a tx so there's something to prove
    tx = b"light-key=light-value"
    res = client.broadcast_tx_commit(tx=tx.hex())
    tx_height = res["height"]
    assert wait_until(lambda: node.block_store.height() >= tx_height + 1)

    lc = LightClient.from_genesis(client)
    lc.advance(tx_height)
    assert lc.height == tx_height
    header = lc.verify_header(tx_height)
    assert header.height == tx_height

    # the tx's inclusion proof checks out against the verified header
    verified = lc.verify_tx(tx_hash(tx), header)
    assert bytes.fromhex(verified["tx"]) == tx

    # tampering: a wrong chain id must fail
    bad = LightClient.from_genesis(client)
    bad.chain_id = "not-the-chain"
    with pytest.raises(LightClientError):
        bad.verify_header(1)

    # tampering: a forged validator set must fail
    from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
    from tendermint_tpu.types.validator import Validator
    from tendermint_tpu.types.validator_set import ValidatorSet

    forged = LightClient.from_genesis(client)
    forged.validators = ValidatorSet(
        [Validator.new(gen_priv_key_ed25519().pub_key(), 1)]
    )
    with pytest.raises(LightClientError):
        forged.verify_header(1)


def test_metrics_endpoint(node, client):
    m = client.metrics()
    assert m["consensus_height"] >= 1
    assert m["blockstore_height"] >= 1
    assert m["mempool_size"] >= 0
    assert "p2p_peers_outbound" in m and "p2p_peers_inbound" in m
    assert "gateway_verify_tpu_sigs" in m
    assert m["consensus_peer_msg_drops"] == 0  # healthy node drops nothing
    assert "gateway_hash_cpu_leaves" in m
    # the Hasher's streamed-transport gauges must surface through the
    # metrics RPC unconditionally (zeros off the devd route) — the PR-1
    # Verifier stream gauges only had client-side coverage, which let a
    # stats()-shape regression hide from the RPC surface
    for gauge in ("gateway_hash_stream_lanes", "gateway_hash_stream_batches",
                  "gateway_hash_stream_bytes_out", "gateway_hash_stream_trees",
                  "gateway_hash_stream_reconnects",
                  "gateway_hash_tx_root_cache_hits"):
        assert gauge in m, gauge
    assert all(isinstance(v, (int, float)) for v in m.values()), m


# round 11: the metrics RPC renders from the telemetry registry
# (node/telemetry.py). This is the COMPLETENESS contract — every
# subsystem's gauges present under their canonical <plane>_<name> on a
# real node — so a future wiring/rename regression fails here, loudly.
METRICS_REQUIRED_KEYS = (
    # consensus plane
    "consensus_height", "consensus_round", "consensus_step",
    "consensus_height_seconds_last", "consensus_height_seconds_max",
    "consensus_peer_msg_drops",
    # pipelined execution plane (round 14)
    "consensus_pipeline_applies",
    "consensus_pipeline_join_wait_seconds",
    "consensus_pipeline_overlap_seconds",
    # big-committee vote plane (round 16)
    "consensus_vote_batches", "consensus_vote_batched_sigs",
    "consensus_vote_singletons",
    # vote-gossip redundancy (round 17): the 2NxN before-number
    "consensus_vote_duplicates",
    # block store (+ round-19 prune accounting)
    "blockstore_height", "blockstore_base",
    "blockstore_pruned_heights_total", "blockstore_prune_runs",
    # retention coordinator (round 19): enabled/target/runs, per-plane
    # floors, per-plane disk gauges — stable whether or not [pruning]
    # is armed
    "pruning_enabled", "pruning_retain_blocks", "pruning_runs",
    "pruning_pruned_heights", "pruning_wal_chunks_pruned",
    "pruning_last_retain_height", "pruning_floor_operator",
    "pruning_disk_blockstore_bytes", "pruning_disk_wal_bytes",
    "pruning_disk_snapshots_bytes", "pruning_disk_total_bytes",
    # WAL durability plane (present once consensus started)
    "wal_format", "wal_records", "wal_fsyncs", "wal_pending",
    "wal_group_size", "wal_repairs", "wal_sync_age_s",
    # evidence + mempool (cache_dups: round-18 dup-flood shed counter)
    "evidence_count", "mempool_size", "mempool_cache_dups",
    # overload-control plane (round 23): lane depths + intake shed
    # accounting on the mempool, admission counters on the RPC edge,
    # and the load-shed ladder's level/score
    "mempool_lane_priority_size", "mempool_lane_default_size",
    "mempool_lane_bulk_size", "mempool_lane_full_rejects",
    "mempool_pool_full_rejects", "mempool_source_limit_rejects",
    "mempool_shed_writes_rejects", "mempool_sources",
    "rpc_inflight", "rpc_connections", "rpc_sheds",
    "rpc_deadline_rejects", "rpc_ws_clients", "rpc_ws_evictions",
    "rpc_ws_dropped_events",
    "node_overload_level", "node_overload_score",
    "node_overload_transitions",
    # p2p (round 15 adds the flat aggregates over the labeled
    # p2p_peer_* gossip families — the wedge signal on the legacy dict)
    "p2p_peers_outbound", "p2p_peers_inbound", "p2p_peers_dialing",
    "p2p_peer_send_failures", "p2p_peer_vote_gossip_picks",
    "p2p_peer_vote_gossip_sends", "p2p_peer_vote_gossip_send_failures",
    "p2p_peer_catchup_commits", "p2p_peer_vote_duplicates",
    # adversarial-tier defense accounting (round 18): hostile pressure
    # shed at the eclipse gates / admission handshake / framing
    # contract / mempool flood path
    "p2p_adversary_eclipse_dials_refused",
    "p2p_adversary_handshake_rejects",
    "p2p_adversary_frame_violations",
    "p2p_adversary_flood_txs_rejected",
    # tx-lifecycle tracing + flight recorder (round 17)
    "txtrace_sampled", "txtrace_completed", "txtrace_active",
    "flightrec_events", "flightrec_dumps",
    # health plane (round 15): the /health verdict as flat gauges
    "node_health_status", "node_health_height_age_s",
    "node_health_checks_degraded", "node_health_checks_failing",
    # fast sync
    "fastsync_active", "fastsync_blocks_synced",
    "fastsync_rate_blocks_per_sec", "fastsync_apply_s",
    # statesync (reactor serves unconditionally; round 19 adds the
    # adversarial-offerer ban counters by proven kind)
    "statesync_restore_active", "statesync_snapshots",
    "statesync_chunks_served", "statesync_chunk_failures",
    "statesync_peers_banned", "statesync_load_failures",
    "statesync_offerers_banned", "statesync_offerer_bans_forged",
    "statesync_offerer_bans_corrupt", "statesync_offerer_bans_stall",
    # horizon-aware catchup (round 19)
    "fastsync_below_horizon_fallbacks",
    # gateway verify plane
    "gateway_verify_tpu_batches", "gateway_verify_tpu_sigs",
    "gateway_verify_cpu_sigs",
    # gateway hash plane
    "gateway_hash_tpu_part_batches", "gateway_hash_tpu_leaves",
    "gateway_hash_cpu_leaves", "gateway_hash_tx_root_cache_hits",
    "gateway_hash_batch_bytes", "gateway_hash_stream_batches",
    # sharded device plane (round 21): the flat aggregates over the
    # labeled gateway_endpoint_* families — stable in single-socket
    # mode (count=1) so the contract holds without a fleet
    "gateway_endpoints_count", "gateway_endpoints_healthy",
    "gateway_endpoints_dispatched_slices", "gateway_endpoints_stolen_slices",
    "gateway_endpoints_redispatches", "gateway_endpoints_outstanding",
)


def test_metrics_completeness_every_plane_present(node, client):
    m = client.metrics()
    missing = [k for k in METRICS_REQUIRED_KEYS if k not in m]
    assert not missing, f"metrics RPC lost gauges: {missing}"


PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'
    r" (\+Inf|-Inf|[0-9.eE+-]+)$"
)


def test_prometheus_exposition_endpoint(node):
    """GET /metrics serves valid text exposition 0.0.4: >= 40 families
    spanning every plane, HELP/TYPE per family, every sample line
    parseable, histogram families present."""
    with urllib.request.urlopen(
        f"http://127.0.0.1:{node.rpc_port()}/metrics", timeout=10
    ) as resp:
        assert resp.headers["Content-Type"].startswith(
            "text/plain; version=0.0.4"
        )
        text = resp.read().decode()
    families: dict[str, str] = {}
    helps = set()
    for line in text.strip().splitlines():
        if line.startswith("# HELP "):
            helps.add(line.split()[2])
        elif line.startswith("# TYPE "):
            _h, _t, name, kind = line.split()
            families[name] = kind
        else:
            assert PROM_LINE.match(line), line
    assert len(families) >= 40, f"only {len(families)} families"
    assert set(families) <= helps, "family missing its HELP line"
    # one family per plane the acceptance bar names (statetree_*: the
    # kvstore app carries the round-13 authenticated tree, scrape-only)
    for fam in ("consensus_height", "wal_format", "gateway_verify_tpu_sigs",
                # round 16: the big-committee vote-plane counters
                "consensus_vote_batches", "consensus_vote_singletons",
                "gateway_hash_tpu_leaves", "gateway_breaker_state",
                "mempool_size", "statesync_snapshots", "fastsync_active",
                "p2p_peers_outbound", "statetree_size", "statetree_commits",
                # round 15: health verdict + the per-peer queue gauges
                "node_health_status", "node_health_height_age_s",
                "p2p_peer_send_queue", "p2p_peer_send_queue_high_water",
                "p2p_peer_last_recv_age_seconds",
                # round 17: tx-lifecycle sampling + flight recorder +
                # the vote-gossip redundancy number
                "txtrace_sampled", "flightrec_events",
                "consensus_vote_duplicates",
                # round 18: adversary-defense accounting on the node +
                # the WAN-shaping counters on the chaos fabric (all-zero
                # outside a chaos harness but the family set is stable)
                "p2p_adversary_eclipse_dials_refused",
                "p2p_adversary_handshake_rejects",
                "p2p_adversary_frame_violations",
                "p2p_adversary_flood_txs_rejected",
                "netfaults_wan_delays_applied", "netfaults_wan_loss_stalls",
                "netfaults_wan_bytes_shaped", "netfaults_wan_resets",
                "netfaults_links",
                # round 19: bounded-retention lifecycle + adversarial
                # statesync offerer accounting + horizon-aware catchup
                "blockstore_pruned_heights_total", "pruning_enabled",
                "pruning_retain_blocks", "pruning_disk_total_bytes",
                "pruning_floor_operator",
                "statesync_offerers_banned",
                "statesync_offerer_bans_forged",
                "statesync_offerer_bans_corrupt",
                "statesync_offerer_bans_stall",
                "fastsync_below_horizon_fallbacks",
                # round 21: per-endpoint device-plane gauges (labeled by
                # endpoint socket; one child per configured endpoint even
                # in single-socket mode)
                "gateway_endpoint_outstanding",
                "gateway_endpoint_breaker_state",
                "gateway_endpoint_sigs_per_s",
                # round 23: overload-control plane — RPC admission,
                # per-lane mempool depth, and the load-shed ladder
                "rpc_inflight", "rpc_ws_clients",
                "node_overload_level", "node_overload_score",
                "mempool_lane_depth", "mempool_lane_bytes"):
        assert fam in families, fam
        assert families[fam] == "gauge"
    # round 18: the secret-connection transport counters, incl. the
    # oversized-frame refusal the adversarial tier asserts on
    for fam in ("p2p_secretconn_handshakes_total",
                "p2p_secretconn_handshake_timeouts_total",
                "p2p_secretconn_auth_failures_total",
                "p2p_secretconn_oversized_frames_total"):
        assert families.get(fam) == "counter", fam
    # round 15: the labeled per-peer gossip families are present (and
    # typed) from the first scrape even with zero peers — family
    # materialization is what makes churned series collapse instead of
    # appearing late
    for fam in ("p2p_peer_send_bytes_total", "p2p_peer_recv_bytes_total",
                "p2p_peer_send_msgs_total", "p2p_peer_recv_msgs_total",
                "p2p_peer_send_failures_total",
                "p2p_peer_vote_gossip_picks_total",
                "p2p_peer_vote_gossip_sends_total",
                "p2p_peer_vote_gossip_send_failures_total",
                "p2p_peer_catchup_commits_total",
                "p2p_peer_vote_duplicates_total",
                # round 21: per-endpoint dispatch accounting on the
                # sharded device plane
                "gateway_endpoint_dispatched_slices_total",
                "gateway_endpoint_stolen_slices_total",
                "gateway_endpoint_redispatches_total",
                # round 23: shed accounting by reason/lane + slow-WS
                # eviction counters
                "rpc_shed_total", "ws_evictions_total",
                "ws_dropped_events_total", "mempool_lane_full_total"):
        assert families.get(fam) == "counter", fam
    # the latency-distribution instruments render as real histograms
    for fam in ("devd_stream_chunk_seconds", "devd_single_shot_seconds",
                "wal_fsync_seconds", "wal_group_records",
                "gateway_hash_batch_seconds",
                # round 14: the execution-pipeline distributions
                "consensus_height_seconds", "pipeline_join_wait_seconds",
                "pipeline_overlap_seconds",
                # round 16: the vote micro-batch distribution
                "consensus_vote_verify_batch_seconds",
                # round 15: gossip-arrival distributions + per-peer RTT
                "consensus_quorum_seconds", "consensus_first_part_seconds",
                "p2p_peer_ping_rtt_seconds",
                # round 17: the tx-lifecycle distributions
                "tx_stage_seconds", "tx_commit_latency_seconds",
                "tx_visible_latency_seconds"):
        assert families.get(fam) == "histogram", fam
    # a live node has fsynced (group commit): the histogram has samples
    count = next(
        l for l in text.splitlines() if l.startswith("wal_fsync_seconds_count")
    )
    assert float(count.rsplit(" ", 1)[1]) >= 1


def test_consensus_trace_rpc_segments_sum_to_wall(node, client):
    """consensus_trace reconstructs a committed height's wall time into
    named segments that sum to within 5% of the height's wall clock,
    with device-vs-CPU attribution attached."""
    assert wait_until(lambda: node.block_store.height() >= 2)
    traces = client.consensus_trace(last=5)["traces"]
    assert traces, "no completed heights traced"
    heights = [t["height"] for t in traces]
    assert heights == sorted(heights, reverse=True), "newest first"
    for t in traces:
        assert t["segments"], t
        total = sum(t["segments"].values())
        tol = max(0.05 * t["wall_s"], 0.005)  # floor for sub-ms heights
        assert abs(total - t["wall_s"]) <= tol, (total, t["wall_s"])
        # the commit machinery segments exist on every committed height.
        # Round 14: with the pipelined execution plane (the default) the
        # apply runs on the executor and is attributed to the height it
        # OVERLAPS as the overlap_apply_s aux note — the lowest traced
        # height carries neither (its apply credited to its successor)
        for seg in ("commit", "block_save"):
            assert seg in t["segments"], t["segments"]
        if t["height"] > min(heights):
            assert (
                "apply" in t["segments"] or "overlap_apply_s" in t["aux"]
            ), t
        dev = t["device"]
        for k in ("verify_tpu_sigs", "verify_cpu_sigs",
                  "hash_tpu_leaves", "hash_cpu_leaves"):
            assert k in dev, dev
        # CPU-route node: breaker not engaged, work attributed to CPU
        assert dev["breaker_state_end"] == -1
    # a single-validator CPU node verifies its own precommits on CPU
    assert any(
        t["device"]["verify_cpu_sigs"] > 0 or t["device"]["hash_cpu_leaves"] > 0
        for t in traces
    )
    # the operator CLI renders the same traces without raising
    import io

    from tendermint_tpu.ops.trace import render

    buf = io.StringIO()
    render(traces, out=buf)
    assert f"height {heights[0]}" in buf.getvalue()


def test_consensus_trace_carries_gossip_arrivals(node, client):
    """Round 15: every committed height's trace carries wall-clock
    gossip arrival marks in causal order — the raw material the fleet
    aggregator joins across nodes."""
    assert wait_until(lambda: node.block_store.height() >= 2)
    traces = client.consensus_trace(last=3)["traces"]
    assert traces
    for t in traces:
        arr = t["arrivals"]
        # a sole validator self-delivers its proposal: every mark exists
        for key in ("proposal", "first_block_part", "prevote_quorum",
                    "precommit_quorum", "commit"):
            assert key in arr, (key, arr)
        assert t["started_at"] <= arr["first_block_part"] + 1e-6
        assert arr["first_block_part"] <= arr["prevote_quorum"] + 1e-6
        assert arr["prevote_quorum"] <= arr["precommit_quorum"] + 1e-6
        assert arr["precommit_quorum"] <= arr["commit"] + 1e-6
        assert arr["commit"] <= t["completed_at"] + 1e-6


def test_health_endpoint_contract(node, client):
    """GET /health (round 15, node/health.py): a live committing node is
    ok with every check reported machine-readably, and the same verdict
    rides the flat node_health_* gauges."""
    assert wait_until(lambda: node.block_store.height() >= 1)
    with urllib.request.urlopen(
        f"http://127.0.0.1:{node.rpc_port()}/health", timeout=10
    ) as resp:
        assert resp.status == 200
        body = json.loads(resp.read().decode())
    assert body["status"] == "ok" and body["code"] == 0
    for check in ("height_age", "peers", "breaker", "wal", "pipeline",
                  "mempool"):
        assert check in body["checks"], body["checks"]
        assert body["checks"][check]["status"] in ("ok", "degraded")
    assert body["checks"]["height_age"]["age_s"] >= 0
    assert body["checks"]["wal"]["open"] is True
    assert body["checks"]["pipeline"]["poisoned"] is False
    m = client.metrics()
    assert m["node_health_status"] == 0
    assert m["node_health_checks_failing"] == 0


def test_health_thresholds_flip_degraded(node, client, monkeypatch):
    """The env-knob thresholds govern the verdict live (the netchaos
    tier tightens them the same way): an impossible height-age budget
    flips the report to degraded, then failing — and the flat gauge
    follows."""
    from tendermint_tpu.node.health import health_report

    monkeypatch.setenv("TENDERMINT_HEALTH_HEIGHT_AGE_DEGRADED_S", "0")
    monkeypatch.setenv("TENDERMINT_HEALTH_HEIGHT_AGE_FAILING_S", "1e9")
    report = health_report(node)
    assert report["status"] == "degraded"
    assert report["checks"]["height_age"]["status"] == "degraded"
    assert client.metrics()["node_health_status"] == 1
    monkeypatch.setenv("TENDERMINT_HEALTH_HEIGHT_AGE_FAILING_S", "0")
    report = health_report(node)
    assert report["status"] == "failing"
    # ... and the endpoint answers 503 so k8s-style probes see it
    import urllib.error

    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{node.rpc_port()}/health", timeout=10
        )
        raise AssertionError("failing health must answer 503")
    except urllib.error.HTTPError as exc:
        assert exc.code == 503
        assert json.loads(exc.read().decode())["status"] == "failing"


def test_fleet_scrapes_single_node(node):
    """ops/fleet against a live (single) node: the aggregator
    reconstructs the per-height timeline purely from GET /metrics +
    consensus_trace + GET /health scrapes."""
    import io

    from tendermint_tpu.ops import fleet

    assert wait_until(lambda: node.block_store.height() >= 2)
    url = f"127.0.0.1:{node.rpc_port()}"
    snapshot = fleet.collect([url], last=5)
    assert "error" not in snapshot[url], snapshot[url].get("error")
    assert snapshot[url]["health"]["status"] in ("ok", "degraded")
    rows = fleet.build_timeline(
        {u: e["traces"] for u, e in snapshot.items()}, last=5
    )
    assert rows and rows[0]["height"] >= rows[-1]["height"]
    for r in rows:
        assert r["nodes_reporting"] == 1
        assert r["precommit_quorum_s_max"] is not None
        assert r["precommit_quorum_s_max"] >= 0
        # one reporter: no cross-node spreads
        assert r["commit_skew_s"] is None
    summary = fleet.fleet_summary(snapshot)
    assert summary[url]["height"] >= 2
    buf = io.StringIO()
    fleet.render(snapshot, rows, out=buf)
    assert "health ok" in buf.getvalue() or "health degraded" in buf.getvalue()


def test_tx_trace_rpc_spans_sum_to_commit_latency(node, client):
    """Round 17: a committed tx's lifecycle trace is served by the
    tx_trace RPC with its per-stage spans summing (within 10%, the
    acceptance bar — they telescope, so this guards the stamp sites) to
    the measured end-to-end commit latency, and the cross-node CLI
    renders it."""
    tx = b"txtrace-rpc-key=txtrace-rpc-val"
    res = client.broadcast_tx_commit(tx=tx.hex())
    assert res["deliver_tx"]["code"] == 0
    want_hash = res["hash"]

    def traced():
        return [
            t for t in client.tx_trace(last=50)["traces"]
            if t["hash"] == want_hash
        ]

    assert wait_until(lambda: traced(), timeout=30), (
        client.tx_trace(last=50)
    )
    [t] = traced()
    assert t["outcome"] == "committed"
    assert t["height"] == res["height"]
    assert t["source"] == "rpc"
    # the lifecycle stages a sole-validator commit must cross
    for stage in ("rpc_ingress", "mempool_admit", "proposal",
                  "block_commit", "apply", "event_delivery"):
        assert stage in t["stages"], (stage, t["stages"])
    # stamped instants are causally ordered
    from tendermint_tpu.libs.txtrace import STAGES

    stamped = [t["stages"][s] for s in STAGES if s in t["stages"]]
    assert stamped == sorted(stamped)
    # spans through block_commit sum to the commit latency within 10%
    assert t["commit_latency_s"] is not None and t["commit_latency_s"] > 0
    commit_idx = STAGES.index("block_commit")
    span_sum = sum(
        v for k, v in t["spans"].items() if STAGES.index(k) <= commit_idx
    )
    assert abs(span_sum - t["commit_latency_s"]) <= max(
        0.10 * t["commit_latency_s"], 1e-4
    ), (span_sum, t["commit_latency_s"])
    assert t["visible_latency_s"] >= t["commit_latency_s"]
    # hash filter returns exactly this tx
    only = client.tx_trace(hash=want_hash, last=50)
    assert [x["hash"] for x in only["traces"]] == [want_hash]
    # the cross-node joiner + renderer work against the live scrape
    import io

    from tendermint_tpu.ops import txtrace as ops_txtrace

    url = f"127.0.0.1:{node.rpc_port()}"
    snapshot = ops_txtrace.collect_txtraces([url], tx_hash=want_hash)
    rows = ops_txtrace.join_tx_timelines(snapshot)
    assert len(rows) == 1 and rows[0]["committed"]
    assert rows[0]["submitted_on"] == url
    buf = io.StringIO()
    ops_txtrace.render(rows, out=buf)
    assert f"committed @h={res['height']}" in buf.getvalue()


def test_debug_flight_endpoint(node, client):
    """GET /debug/flight serves the live event ring: step transitions
    and WAL endheight marks from real commits, newest events carrying
    the current chain position."""
    assert wait_until(lambda: node.block_store.height() >= 2)
    with urllib.request.urlopen(
        f"http://127.0.0.1:{node.rpc_port()}/debug/flight", timeout=10
    ) as resp:
        body = json.loads(resp.read().decode())
    assert body["enabled"] is True
    assert body["recorded_total"] >= len(body["events"]) >= 1
    kinds = {e["kind"] for e in body["events"]}
    assert "step" in kinds and "wal_endheight" in kinds
    ts = [e["t"] for e in body["events"]]
    assert ts == sorted(ts)
    steps = [e for e in body["events"] if e["kind"] == "step"]
    assert steps[-1]["height"] >= node.block_store.height() - 1


def test_debug_stacks_endpoint(node):
    """GET /debug/stacks: every live thread with a readable stack — the
    consensus receive routine must be among them (the wedge-triage
    read)."""
    with urllib.request.urlopen(
        f"http://127.0.0.1:{node.rpc_port()}/debug/stacks", timeout=10
    ) as resp:
        body = json.loads(resp.read().decode())
    assert body["count"] >= 3
    names = {t["name"] for t in body["threads"]}
    assert any(n.startswith("cs.receiveRoutine") for n in names), names
    for t in body["threads"]:
        assert isinstance(t["stack"], list) and t["stack"]


def test_debug_queues_endpoint(node):
    """GET /debug/queues: the backlog view — consensus input queues,
    pipeline executor, mempool, vote batcher — every section present
    and numeric on a live node."""
    import urllib.error

    with urllib.request.urlopen(
        f"http://127.0.0.1:{node.rpc_port()}/debug/queues", timeout=10
    ) as resp:
        body = json.loads(resp.read().decode())
    for section in ("consensus", "pipeline", "vote_batcher", "mempool",
                    "p2p"):
        assert section in body, body.keys()
        assert "error" not in body[section], body[section]
    assert body["consensus"]["height"] >= 1
    assert body["consensus"]["inputs"] >= 0
    assert body["pipeline"]["poisoned"] is False
    assert body["mempool"]["size"] >= 0
    # unknown debug endpoints 404, not 500
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        urllib.request.urlopen(
            f"http://127.0.0.1:{node.rpc_port()}/debug/nope", timeout=10
        )
    assert exc_info.value.code == 404
