"""Tests for the doubling-free comb Ed25519 kernel (ops/ed25519_comb.py)
— per-validator device-resident comb tables + fixed-base MXU comb.

Same coverage discipline as test_ops_f32.py (the kernel contract is
identical: strict cofactorless RFC 8032, lane-for-lane parity with
crypto/ed25519.verify), plus the pool mechanics that are new here:
slot reuse across batches, LRU eviction, capacity growth, and the
PoolExhausted -> ladder fallback.

Reference hot paths: types/vote_set.go:175,
types/validator_set.go:247-250, blockchain/reactor.go:235.
"""

from __future__ import annotations

import numpy as np
import pytest

from tendermint_tpu.crypto import ed25519 as ed
from tendermint_tpu.ops import ed25519_comb as comb


@pytest.fixture(autouse=True)
def _fresh_pool(monkeypatch):
    # build tables on first sight so every test below exercises the comb
    # path; the second-sight production default gets its own test
    monkeypatch.setenv("TENDERMINT_TPU_COMB_MIN_SIGHT", "1")
    comb.reset_default_pool()
    yield
    comb.reset_default_pool()


def _keypair(rng):
    sk = rng.bytes(32)
    return sk, ed.public_key(sk)


def _signed(rng, sk, pk, n=1, msg_len=40):
    out = []
    for _ in range(n):
        m = rng.bytes(msg_len)
        out.append((pk, m, ed.sign(sk, m)))
    return out


class TestVerifyParity:
    def test_rfc8032_vectors(self):
        # RFC 8032 section 7.1 test vectors 1-3
        vecs = [
            (
                "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
                b"",
            ),
            (
                "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
                bytes([0x72]),
            ),
            (
                "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
                bytes([0xAF, 0x82]),
            ),
        ]
        items = []
        for sk_hex, msg in vecs:
            sk = bytes.fromhex(sk_hex)
            pk = ed.public_key(sk)
            items.append((pk, msg, ed.sign(sk, msg)))
        assert list(comb.verify_batch(items)) == [True, True, True]

    def test_parity_with_cpu_reference_mixed_batch(self):
        """Random valid sigs from several keys, tampered sig/msg/pub,
        non-canonical s, bad-length rows — lane-for-lane identical to
        crypto/ed25519.verify."""
        rng = np.random.default_rng(11)
        pairs = [_keypair(rng) for _ in range(4)]
        items = []
        for i in range(24):
            sk, pk = pairs[i % 4]
            m = rng.bytes(32 + i)
            sig = ed.sign(sk, m)
            if i % 6 == 1:  # tamper sig
                b = bytearray(sig)
                b[10] ^= 0x40
                sig = bytes(b)
            elif i % 6 == 2:  # tamper msg
                m = m[:-1] + bytes([m[-1] ^ 1])
            elif i % 6 == 3:  # wrong pubkey
                pk = pairs[(i + 1) % 4][1]
            elif i % 6 == 4:  # non-canonical s (s + L)
                s_int = int.from_bytes(sig[32:], "little") + ed.L
                sig = sig[:32] + s_int.to_bytes(32, "little")
            items.append((pk, m, sig))
        items.append((b"\x00" * 31, b"m", b"\x00" * 64))  # bad pub length
        items.append((pairs[0][1], b"m", b"\x00" * 63))  # bad sig length
        expect = [ed.verify(p, m, s) for p, m, s in items]
        assert list(comb.verify_batch(items)) == expect

    def test_empty_and_single(self):
        rng = np.random.default_rng(3)
        sk, pk = _keypair(rng)
        assert list(comb.verify_batch([])) == []
        (it,) = _signed(rng, sk, pk)
        assert list(comb.verify_batch([it])) == [True]

    def test_agrees_with_f32_kernel(self):
        from tendermint_tpu.ops import ed25519_f32 as f32

        rng = np.random.default_rng(7)
        pairs = [_keypair(rng) for _ in range(3)]
        items = []
        for i in range(12):
            sk, pk = pairs[i % 3]
            m = rng.bytes(20)
            sig = ed.sign(sk, m)
            if i % 4 == 3:
                sig = sig[:63] + bytes([sig[63] ^ 2])
            items.append((pk, m, sig))
        assert list(comb.verify_batch(items)) == list(f32.verify_batch(items))


class TestPool:
    def test_slot_reuse_across_batches(self):
        rng = np.random.default_rng(5)
        sk, pk = _keypair(rng)
        comb.verify_batch(_signed(rng, sk, pk, 3))
        pool = comb.default_pool()
        assert pool.stats["build_keys"] == 1
        comb.verify_batch(_signed(rng, sk, pk, 3))
        assert pool.stats["build_keys"] == 1  # no rebuild on reuse

    def test_growth_and_eviction(self):
        pool = comb.CombPool(capacity=2, max_capacity=4)
        comb.set_default_pool(pool)
        rng = np.random.default_rng(9)
        pairs = [_keypair(rng) for _ in range(5)]
        assert pool.capacity == 2  # starts small
        for sk, pk in pairs[:3]:
            assert list(comb.verify_batch(_signed(rng, sk, pk))) == [True]
        assert pool.capacity == pool.cap == 4  # grew (slot 0 reserved)
        assert pool.stats["grows"] == 1
        # 2 more distinct keys -> evictions, results still correct
        for sk, pk in pairs[3:]:
            assert list(comb.verify_batch(_signed(rng, sk, pk))) == [True]
        assert pool.stats["evictions"] >= 1
        # the evicted first key still verifies correctly after re-lease
        sk, pk = pairs[0]
        assert list(comb.verify_batch(_signed(rng, sk, pk))) == [True]

    def test_second_sight_policy(self, monkeypatch):
        """Production default: a key's table is built only on its second
        batch appearance — first sight rides the ladder (one-shot mempool
        keys never pay the ~13-verify build; validator keys, which sign
        every block, are all-comb from block two)."""
        monkeypatch.setenv("TENDERMINT_TPU_COMB_MIN_SIGHT", "2")
        comb.reset_default_pool()
        rng = np.random.default_rng(21)
        sk, pk = _keypair(rng)
        pool = comb.default_pool()
        assert list(comb.verify_batch(_signed(rng, sk, pk))) == [True]
        assert pool.stats["build_keys"] == 0  # first sight: ladder
        assert list(comb.verify_batch(_signed(rng, sk, pk))) == [True]
        assert pool.stats["build_keys"] == 1  # second sight: built
        assert list(comb.verify_batch(_signed(rng, sk, pk))) == [True]
        assert pool.stats["build_keys"] == 1  # reused thereafter

    def test_pool_exhausted_falls_back_to_ladder(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_TPU_COMB_CAP", "2")
        comb.reset_default_pool()
        rng = np.random.default_rng(13)
        pairs = [_keypair(rng) for _ in range(3)]
        items = []
        for sk, pk in pairs:  # 3 distinct keys > 1 usable slot (cap=2)
            items.extend(_signed(rng, sk, pk))
        out = comb.verify_batch(items)  # must not raise
        assert list(out) == [True, True, True]
        # round-5 review regression: the aborted lease must be rolled
        # back — a follow-up batch with one of those keys must not ride a
        # never-built (garbage) slot table and reject a valid signature
        for sk, pk in pairs:
            assert list(comb.verify_batch(_signed(rng, sk, pk))) == [True]

    def test_eviction_never_steals_from_current_batch(self, monkeypatch):
        """Round-5 design bug guard: assigning slots for one batch must
        not evict a slot already leased to an earlier lane of the SAME
        batch (the earlier lane would verify against the wrong table)."""
        monkeypatch.setenv("TENDERMINT_TPU_COMB_CAP", "4")
        comb.reset_default_pool()
        rng = np.random.default_rng(17)
        # 3 distinct keys fill the 3 usable slots in one batch; then a
        # 4th-key batch triggers eviction of an out-of-batch slot only
        pairs = [_keypair(rng) for _ in range(4)]
        items = []
        for sk, pk in pairs[:3]:
            items.extend(_signed(rng, sk, pk, 2))
        assert all(comb.verify_batch(items))
        items2 = []
        for sk, pk in pairs[1:]:  # keys 1,2 pinned + new key 3
            items2.extend(_signed(rng, sk, pk, 2))
        assert all(comb.verify_batch(items2))


class TestBTable:
    def test_b_table_first_window_matches_reference(self):
        tab = comb.b_table()
        # entry [0][1] is 1*B: niels rows of the base point
        bx, by = ed.B[0], ed.B[1]
        want = comb._niels_rows_np(bx, by)
        assert np.array_equal(tab[0, 1], want)
        # entry [p][0] is the identity in niels form
        ident = np.zeros(96, dtype=np.float32)
        ident[0] = 1.0
        ident[32] = 1.0
        assert np.array_equal(tab[5, 0], ident)

    def test_b_table_window_weights(self):
        tab = comb.b_table()
        # entry [1][1] must be 16*B
        acc = ed.B
        for _ in range(4):
            acc = ed.point_double(acc)
        x, y = comb.base._affine(acc)
        assert np.array_equal(tab[1, 1], comb._niels_rows_np(x, y))
