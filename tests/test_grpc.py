"""gRPC transports: the ABCI gRPC client/server pair (ref types.proto
ABCIApplication service, proxy/client.go:40-58) and the BroadcastAPI
(ref rpc/grpc/api.go:14)."""

from __future__ import annotations

import tempfile
import time

import pytest

pytest.importorskip("grpc")

from tendermint_tpu.abci.apps.kvstore import KVStoreApp
from tendermint_tpu.abci.grpc import GRPCClient, GRPCServer
from tendermint_tpu.config import reset_test_root
from tendermint_tpu.node import default_new_node
from tendermint_tpu.proxy.client_creator import RemoteClientCreator, default_client_creator


def wait_until(cond, timeout=30.0, tick=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(tick)
    return cond()


class TestABCIGRPC:
    @pytest.fixture()
    def pair(self):
        app = KVStoreApp()
        server = GRPCServer(app, "127.0.0.1:0")
        server.start()
        client = GRPCClient(server.addr)
        client.start()
        yield app, client
        client.stop()
        server.stop()

    def test_sync_roundtrip(self, pair):
        _app, c = pair
        assert c.echo_sync("hello") == "hello"
        info = c.info_sync()
        assert info.last_block_height == 0
        assert c.check_tx_sync(b"k=v").code == 0
        assert c.deliver_tx_sync(b"k=v").code == 0
        commit = c.commit_sync()
        assert commit.code == 0 and commit.data
        q = c.query_sync(b"k")
        assert q.value == b"v"

    def test_async_ordering_and_callback(self, pair):
        _app, c = pair
        seen = []
        c.set_response_callback(lambda t, tx, res: seen.append((t, tx)))
        rrs = [c.deliver_tx_async(b"key%d=v%d" % (i, i)) for i in range(10)]
        c.flush_sync()
        assert all(rr.wait(5) is not None for rr in rrs)
        # the ordering contract: callbacks in request order
        assert [tx for t, tx in seen] == [b"key%d=v%d" % (i, i) for i in range(10)]

    def test_creator_dispatch(self):
        c = default_client_creator("127.0.0.1:1", transport="grpc")
        assert isinstance(c, RemoteClientCreator) and c.transport == "grpc"
        assert type(c.new_abci_client()).__name__ == "GRPCClient"


class TestBroadcastAPI:
    @pytest.fixture(scope="class")
    def node(self):
        tmp = tempfile.mkdtemp(prefix="grpc-node-test-")
        cfg = reset_test_root(tmp)
        cfg.base.proxy_app = "kvstore"
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.rpc.grpc_laddr = "tcp://127.0.0.1:0"
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        n = default_new_node(cfg)
        n.start()
        assert wait_until(lambda: n.block_store.height() >= 1, timeout=30)
        yield n
        n.stop()

    def test_ping_and_broadcast_tx(self, node):
        from tendermint_tpu.rpc.grpc import GRPCBroadcastClient

        c = GRPCBroadcastClient(node.grpc_server.addr)
        try:
            assert c.ping() == {}
            res = c.broadcast_tx(b"gk=gv")
            assert res["check_tx"]["code"] == 0
            assert res["deliver_tx"]["code"] == 0
            assert res["height"] > 0
        finally:
            c.close()


def test_node_commits_blocks_over_grpc_abci():
    """The `abci: grpc` config path end-to-end: a real node drives its
    app through the gRPC transport for all three connections and still
    makes blocks (proxy/client.go:40-58)."""
    app = KVStoreApp()
    server = GRPCServer(app, "127.0.0.1:0")
    server.start()
    tmp = tempfile.mkdtemp(prefix="grpc-abci-node-")
    cfg = reset_test_root(tmp)
    cfg.base.proxy_app = server.addr
    cfg.base.abci = "grpc"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    n = default_new_node(cfg)
    n.start()
    try:
        assert wait_until(lambda: n.block_store.height() >= 2, timeout=30)
        n.mempool.check_tx(b"gx=gy")
        assert wait_until(lambda: app.query(b"gx").value == b"gy", timeout=30)
    finally:
        n.stop()
        server.stop()
