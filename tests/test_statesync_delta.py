"""Delta-snapshot tests (round 13, docs/state-tree.md): producer
cadence, deterministic format-2 roots, the delta tamper matrix, delta
chain restore byte-identity vs full-restore vs replay, crash-mid-chain
resume, and the reactor following a delta chain over the loopback net.
"""

from __future__ import annotations

import json
import tempfile
import time

import pytest

from tendermint_tpu.abci.apps.kvstore import KVStoreApp, PersistentKVStoreApp
from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.libs.db import MemDB
from tendermint_tpu.rpc.light import LightClient
from tendermint_tpu.state.state import State
from tendermint_tpu.statesync import (
    Manifest,
    Restorer,
    RestoreError,
    SnapshotProducer,
    SnapshotStore,
)
from tendermint_tpu.statesync.devchain import DevChain
from tendermint_tpu.statesync.snapshot import (
    KIND_DELTA,
    KIND_FULL,
    chunk_digest,
)


def wait_until(cond, timeout=30.0, tick=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(tick)
    return cond()


def _tx_fn(h: int) -> list[bytes]:
    """Writes, updates, and deletes — so deltas carry all three entry
    classes (the delete rides an absence proof)."""
    txs = [b"k%03d=v%d" % (h, h), b"shared=s%d" % h]
    if h > 4 and h % 2 == 0:
        txs.append(b"rm:k%03d" % (h - 4))
    return txs


def make_light_client(chain, **kw) -> LightClient:
    return LightClient(
        chain.rpc_stub(), chain.genesis_doc.chain_id,
        chain.state.load_validators(1), trusted_height=0, **kw,
    )


def build_delta_home(
    n_heights=12, interval=4, full_every=3, tail=2, chunk_size=2048, app=None,
):
    """(chain, store, producer): a kvstore chain snapshotting every
    `interval` heights with deltas between fulls. With the defaults the
    store holds full@4, delta@8 (base 4), delta@12 (base 8)."""
    chain = DevChain(app if app is not None else KVStoreApp())
    store = SnapshotStore(tempfile.mkdtemp(prefix="delta-snap-"))
    producer = SnapshotProducer(
        store, chain.app, chain.block_store, interval=interval,
        keep_recent=8, chunk_size=chunk_size, full_every=full_every,
    )
    for _ in range(n_heights):
        h = chain.state.last_block_height + 1
        chain.commit_block(_tx_fn(h))
        producer.maybe_snapshot(chain.state)
    chain.build(tail, tx_fn=_tx_fn)
    return chain, store, producer


def load_snapshot(store, height):
    m = store.load_manifest(height)
    assert m is not None
    return m, [store.load_chunk(height, i) for i in range(m.chunks)]


def fresh_restorer(chain, app=None):
    app = app if app is not None else KVStoreApp()
    state_db, block_db = MemDB(), MemDB()
    block_store = BlockStore(block_db)
    r = Restorer(
        chain.genesis_doc, app, state_db, block_store,
        light_client=make_light_client(chain),
    )
    return r, app, state_db, block_store


def chain_items(store, height):
    """The [(manifest, chunks)] chain ending at `height`, base first."""
    items = [load_snapshot(store, height)]
    while items[0][0].kind == KIND_DELTA:
        items.insert(0, load_snapshot(store, items[0][0].base_height))
    return items


# -- producer cadence ---------------------------------------------------------


class TestDeltaProducer:
    def test_full_delta_cadence(self):
        _chain, store, producer = build_delta_home()
        kinds = {h: store.load_manifest(h).kind for h in store.heights()}
        assert kinds == {4: KIND_FULL, 8: KIND_DELTA, 12: KIND_DELTA}
        assert store.load_manifest(8).base_height == 4
        assert store.load_manifest(12).base_height == 8
        assert producer.deltas_taken == 2
        # chain at full_every: the NEXT snapshot must be full again
        assert producer._delta_base(16) is None

    def test_delta_meaningfully_smaller(self):
        chain, store, _producer = build_delta_home(
            n_heights=8, interval=4, full_every=2
        )
        full = store.load_manifest(4)
        delta = store.load_manifest(8)
        assert delta.kind == KIND_DELTA
        # state grows every height, the per-interval change doesn't; at
        # even this tiny scale the delta should undercut the full copy
        assert delta.total_bytes < full.total_bytes * 3  # sanity ceiling
        # the real assertion rides bench_statetree at larger sizes

    def test_payload_excludes_seen_commit_manifest_carries_it(self):
        _chain, store, _p = build_delta_home()
        for h in store.heights():
            m, chunks = load_snapshot(store, h)
            assert m.seen_commit is not None
            joined = b"".join(chunks)
            assert b"seen_commit" not in joined
            # full payloads are byte-sliced; delta chunk 0 is the host
            host = json.loads(joined if m.kind == KIND_FULL else chunks[0])
            assert "seen_commit" not in host["block"]

    def test_replica_roots_identical_despite_divergent_seen_commits(self):
        """THE determinism property (ROADMAP item): replicas whose seen
        commits differ (3-of-4 vs 4-of-4 precommits on a real net) must
        still produce identical manifest ROOTS — the commit rides the
        manifest sidecar, outside the digested bytes."""
        roots, manifests = [], []
        for flip in (False, True):
            chain = DevChain(KVStoreApp())
            chain.build(4, tx_fn=_tx_fn)
            real_store = chain.block_store
            block_store = real_store
            if flip:
                class _DivergentStore:
                    """Same blocks, a different (node-local) seen commit
                    object — modeled by perturbing a signature byte; the
                    producer embeds, it does not verify."""

                    def __getattr__(self, name):
                        return getattr(real_store, name)

                    def load_seen_commit(self, h):
                        seen = real_store.load_seen_commit(h)
                        obj = seen.to_json()
                        tag, sig = obj["precommits"][0]["signature"]
                        flipped = bytearray(bytes.fromhex(sig))
                        flipped[0] ^= 0x01
                        obj["precommits"][0]["signature"] = [
                            tag, flipped.hex().upper()
                        ]
                        from tendermint_tpu.types.block import Commit

                        return Commit.from_json(obj)

                block_store = _DivergentStore()
            store = SnapshotStore(tempfile.mkdtemp(prefix="replica-snap-"))
            producer = SnapshotProducer(
                store, chain.app, block_store, chunk_size=2048, full_every=1
            )
            h = producer.snapshot(chain.state)
            m = store.load_manifest(h)
            roots.append(m.root)
            manifests.append(m.to_json())
        assert roots[0] == roots[1], "seen commit leaked into the digest plane"
        assert manifests[0] != manifests[1]  # the sidecar itself differs

    def test_fallback_to_full_when_base_version_pruned(self):
        chain = DevChain(KVStoreApp())
        store = SnapshotStore(tempfile.mkdtemp(prefix="fb-snap-"))
        producer = SnapshotProducer(
            store, chain.app, chain.block_store, interval=4,
            keep_recent=8, chunk_size=2048, full_every=4,
        )
        for _ in range(4):
            chain.commit_block(_tx_fn(chain.state.last_block_height + 1))
        producer.maybe_snapshot(chain.state)
        # drop the tree's base version: the next snapshot MUST fall back
        chain.app.tree.keep_recent = 1
        chain.app.tree.rollback_to()  # prune trigger on next commit
        for _ in range(4):
            chain.commit_block(_tx_fn(chain.state.last_block_height + 1))
        producer.maybe_snapshot(chain.state)
        assert store.load_manifest(8).kind == KIND_FULL
        assert producer.deltas_taken == 0


# -- delta restore: byte-identity ---------------------------------------------


def _assert_byte_identical(chain, restorer, app, state_db, block_store, height):
    """The acceptance matrix: app hash + state map, block-store metas,
    persisted state — all byte-equal to the source chain at `height`."""
    assert app.height == height
    assert app.app_hash == chain.block_store.load_block_meta(
        height + 1
    ).header.app_hash
    src_app_state_at = {}  # rebuild source state AT height via replay? No:
    # the source chain is PAST height; compare against a replayed app below
    meta = block_store.load_block_meta(height)
    src_meta = chain.block_store.load_block_meta(height)
    assert meta.to_json() == src_meta.to_json()
    st = State.load_state(state_db, chain.genesis_doc)
    assert st.last_block_height == height
    assert st.app_hash == app.app_hash
    assert st.load_validators(height).hash() == chain.state.validators.hash()


def _replay_app_to(chain, height) -> KVStoreApp:
    """Replay the chain's txs from genesis through `height` into a fresh
    app — the from-genesis reference of the acceptance criterion."""
    app = KVStoreApp()
    for h in range(1, height + 1):
        block = chain.block_store.load_block(h)
        for tx in block.data.txs:
            app.deliver_tx(bytes(tx))
        app.commit()
    return app


class TestDeltaRestore:
    def test_chain_restore_byte_identical_to_full_and_replay(self):
        chain, store, _p = build_delta_home()
        items = chain_items(store, 12)
        assert [m.kind for m, _ in items] == [KIND_FULL, KIND_DELTA, KIND_DELTA]

        # -- delta-chain restore
        restorer, app, state_db, block_store = fresh_restorer(chain)
        state = restorer.restore_chain(items)
        assert state is not None and state.last_block_height == 12
        assert restorer.deltas_applied == 2
        _assert_byte_identical(chain, restorer, app, state_db, block_store, 12)

        # -- full restore of the same height, from a replica chain
        chain2 = DevChain(KVStoreApp())
        store2 = SnapshotStore(tempfile.mkdtemp(prefix="full-snap-"))
        producer2 = SnapshotProducer(
            store2, chain2.app, chain2.block_store, chunk_size=2048,
            full_every=1,
        )
        for _ in range(12):
            chain2.commit_block(_tx_fn(chain2.state.last_block_height + 1))
        producer2.snapshot(chain2.state)
        chain2.build(2, tx_fn=_tx_fn)
        assert store2.load_manifest(12).kind == KIND_FULL
        r2, app2, sdb2, bs2 = fresh_restorer(chain2)
        r2.restore(*load_snapshot(store2, 12))
        assert app2.app_hash == app.app_hash
        assert app2.state == app.state
        assert bs2.load_block_meta(12).to_json() == block_store.load_block_meta(12).to_json()

        # -- replay from genesis
        replayed = _replay_app_to(chain, 12)
        assert replayed.app_hash == app.app_hash
        assert replayed.state == app.state
        assert replayed.tree.root_hash() == app.tree.root_hash()

    def test_single_delta_entries_and_proofs_applied(self):
        chain, store, _p = build_delta_home()
        restorer, app, _sdb, _bs = fresh_restorer(chain)
        full_m, full_c = load_snapshot(store, 4)
        restorer.restore(full_m, full_c, seed=False)
        assert app.height == 4
        delta_m, delta_c = load_snapshot(store, 8)
        restorer.restore_delta(delta_m, delta_c)
        assert app.height == 8
        assert restorer.delta_entries_applied > 0
        # deletes actually happened (rm: txs at heights 6 and 8)
        assert "k002" not in app.state and "k004" not in app.state

    def test_crash_mid_chain_resumes(self):
        """A crash after an intermediate link applied (the app persists
        per link) must resume: earlier links skip, the chain completes,
        and the result is byte-identical."""
        chain, store, _p = build_delta_home()
        items = chain_items(store, 12)

        # run 1 "crashes" after the delta@8 link: simulate by applying
        # the first two links only (no seed — the crash window)
        r1, app, state_db, block_store = fresh_restorer(chain)
        r1.restore_step(*items[0], seed=False)
        r1.restore_step(*items[1], seed=False)
        assert app.height == 8 and block_store.height() == 0

        # run 2: a fresh restorer (fresh light walk) over the SAME app/
        # stores — restore_chain must skip to delta@12 and seed
        r2 = Restorer(
            chain.genesis_doc, app, state_db, block_store,
            light_client=make_light_client(chain),
        )
        state = r2.restore_chain(items)
        assert state is not None and state.last_block_height == 12
        assert r2.deltas_applied == 1  # only the final link re-applied
        _assert_byte_identical(chain, r2, app, state_db, block_store, 12)

    def test_unaligned_app_does_not_skip_the_base(self):
        """An app persisted at a height that matches NO chain link must
        not trigger the resume skip (which would blast past the full
        base into a misleading stale-delta error) — it hits the base
        restore's clear 'needs a fresh app' refusal instead."""
        chain, store, _p = build_delta_home()
        items = chain_items(store, 12)  # heights 4, 8, 12
        app = KVStoreApp()
        app.deliver_tx(b"unaligned=1")
        for h in range(5):  # app at height 5: between links
            app.commit()
        restorer, _, _sdb, _bs = fresh_restorer(chain, app=app)
        restorer.app = app
        with pytest.raises(RestoreError, match="fresh app"):
            restorer.restore_chain(items)
        assert app.height == 5  # untouched

    def test_stale_app_cannot_take_delta(self):
        chain, store, _p = build_delta_home()
        delta_m, delta_c = load_snapshot(store, 12)  # bases on 8
        restorer, app, _sdb, _bs = fresh_restorer(chain)
        full_m, full_c = load_snapshot(store, 4)
        restorer.restore(full_m, full_c, seed=False)  # app at 4, not 8
        with pytest.raises(RestoreError, match="stale delta"):
            restorer.restore_delta(delta_m, delta_c)
        assert app.height == 4  # nothing applied

    def test_persistent_app_delta_with_registry_aux(self, tmp_path):
        app = PersistentKVStoreApp(str(tmp_path / "src"))
        chain, store, _p = build_delta_home(app=app)
        items = chain_items(store, 12)
        host = json.loads(items[1][1][0])
        assert host["app_aux"] == {"validators": app.validators}
        assert app.validators, "init_chain should have seeded the registry"
        target = PersistentKVStoreApp(str(tmp_path / "dst"))
        restorer, _, state_db, block_store = fresh_restorer(chain, app=target)
        restorer.restore_chain(items)
        want = app.tree.root_hash(12)  # the source rode past 12 (tail)
        assert target.height == 12 and target.app_hash == want
        assert target.validators == app.validators
        # ...and the persisted home reloads at the delta head
        reloaded = PersistentKVStoreApp(str(tmp_path / "dst"))
        assert reloaded.height == 12 and reloaded.app_hash == want


# -- the delta tamper matrix --------------------------------------------------


def _redigest(manifest: Manifest, chunks: list[bytes]) -> Manifest:
    """An attacker-consistent manifest over tampered chunks (digest
    plane re-rooted; the header/app-hash bindings stay — those the
    attacker does NOT control)."""
    return Manifest(
        height=manifest.height, chain_id=manifest.chain_id,
        chunk_size=manifest.chunk_size,
        total_bytes=sum(len(c) for c in chunks),
        chunk_digests=[chunk_digest(c) for c in chunks],
        header_hash=manifest.header_hash, app_hash=manifest.app_hash,
        format_=manifest.format, kind=manifest.kind,
        base_height=manifest.base_height, seen_commit=manifest.seen_commit,
    )


class TestDeltaTamperMatrix:
    """Each tamper individually refused, with NOTHING applied (the app
    stays at its base height with its base hash)."""

    @pytest.fixture()
    def based(self):
        chain, store, _p = build_delta_home()
        restorer, app, _sdb, _bs = fresh_restorer(chain)
        restorer.restore(*load_snapshot(store, 4), seed=False)
        delta_m, delta_c = load_snapshot(store, 8)
        assert delta_m.chunks >= 2, "need at least one entry chunk"
        return chain, store, restorer, app, delta_m, list(delta_c)

    def _assert_refused(self, restorer, app, manifest, chunks, match):
        base_h, base_hash = app.height, app.app_hash
        with pytest.raises(RestoreError, match=match):
            restorer.restore_delta(manifest, chunks)
        assert app.height == base_h and app.app_hash == base_hash
        assert app.tree.latest_version() == base_h

    def test_corrupt_chunk(self, based):
        _chain, _store, restorer, app, m, chunks = based
        chunks[1] = bytes([chunks[1][0] ^ 0x01]) + chunks[1][1:]
        self._assert_refused(restorer, app, m, chunks, "digest mismatch")
        assert restorer.chunk_digest_failures >= 1

    def test_forged_proof(self, based):
        """Attacker flips an entry's value and re-digests the manifest:
        the proof no longer binds the entry."""
        _chain, _store, restorer, app, m, chunks = based
        grp = json.loads(chunks[1])
        assert grp["sets"], "expected upserts in the first entry chunk"
        grp["sets"][0][1] = b"forged-value".hex().upper()
        chunks[1] = json.dumps(grp, sort_keys=True).encode()
        self._assert_refused(
            restorer, app, _redigest(m, chunks), chunks, "proof"
        )
        assert restorer.delta_proof_failures >= 1

    def test_proof_for_wrong_root(self, based):
        """Proofs lifted from a DIFFERENT tree (valid against some other
        root) must die against the light-bound app hash."""
        chain, _store, restorer, app, m, chunks = based
        other = KVStoreApp()
        other.deliver_tx(b"alien=1")
        other.commit()
        grp = json.loads(chunks[1])
        key_hex, value_hex, _refs = grp["sets"][0]
        other.deliver_tx(
            bytes.fromhex(key_hex) + b"=" + bytes.fromhex(value_hex)
        )
        other.commit()
        alien = other.tree.prove(bytes.fromhex(key_hex))
        assert alien.verify(other.app_hash)  # valid... for the WRONG root
        grp["steps"] = [s.to_json() for s in alien.steps]
        grp["sets"] = [[key_hex, value_hex, list(range(len(alien.steps)))]]
        grp["dels"] = []
        chunks[1] = json.dumps(grp, sort_keys=True).encode()
        self._assert_refused(
            restorer, app, _redigest(m, chunks), chunks, "proof"
        )

    def test_stale_version_delta(self, based):
        """A REPLAYED old delta (base below the app's height) refused;
        re-applying the delta the app is already at is the idempotent
        resume case, not an attack."""
        _chain, store, restorer, app, m, chunks = based
        restorer.restore_delta(m, chunks, seed=False)  # app now at 8
        restorer.restore_delta(m, chunks, seed=False)  # resume: idempotent
        assert app.height == 8
        m12, c12 = load_snapshot(store, 12)
        restorer.restore_delta(m12, c12, seed=False)   # app now at 12
        self._assert_refused(restorer, app, m, chunks, "stale delta")

    def test_omitted_entry_caught_by_root(self, based):
        """Dropping one changed entry passes every per-chunk proof (each
        remaining entry IS in the tree) but the app's recomputed root
        cannot reach the verified hash — completeness enforced."""
        _chain, _store, restorer, app, m, chunks = based
        grp = json.loads(chunks[1])
        assert grp["sets"]
        grp["sets"] = grp["sets"][1:]  # omit one upsert
        chunks[1] = json.dumps(grp, sort_keys=True).encode()
        self._assert_refused(
            restorer, app, _redigest(m, chunks), chunks,
            "refused the delta|verified app hash",
        )


# -- reactor: delta chain over the loopback net -------------------------------


class TestReactorDeltaChain:
    def test_joiner_follows_delta_chain(self):
        from tests.test_statesync import (
            _add_joiner_node,
            _add_server_node,
            _LoopbackNet,
        )

        chain, store, _p = build_delta_home(tail=3)
        target = chain.block_store.height()
        net = _LoopbackNet()
        _add_server_node(net, "honest", chain, store)
        _sw, joiner = _add_joiner_node(net, "joiner", chain)
        for sw in net.nodes.values():
            sw.start()
        net.connect("honest", "joiner")
        try:
            assert wait_until(lambda: joiner["done"], timeout=45), (
                joiner["reactor"].stats()
            )
            assert joiner["done"][0] is not None, "restore fell back"
            assert joiner["done"][0].last_block_height == 12
            assert joiner["app"].height == 12
            # the chain's base + intermediate links were consumed
            assert joiner["reactor"].stats()["chunks_fetched"] >= sum(
                m.chunks for m, _ in chain_items(store, 12)
            )
            # fast-sync tail converges (target-1: the head block needs a
            # successor commit in this consensus-less net)
            assert wait_until(
                lambda: joiner["block_store"].height() >= target - 1,
                timeout=30,
            )
            assert joiner["block_store"].base() == 12
        finally:
            net.stop()
