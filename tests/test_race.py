"""Race-discipline tier (SURVEY §5: the reference runs `go test -race` in
CI, Makefile:31-34). Python's GIL masks word-tearing, so the detector
targets what actually deadlocks a threaded BFT node: lock-order inversions
and non-reentrant re-entry, recorded process-wide by libs/racecheck.

Two layers: unit tests of the detector itself, then stress runs of the
real consensus/p2p stack under instrumentation with a shrunken GIL switch
interval — the whole multi-reactor net must come out cycle-free."""

from __future__ import annotations

import sys
import threading
import time

import pytest

from tendermint_tpu.libs import racecheck


@pytest.fixture
def mon():
    m = racecheck.install()
    try:
        yield m
    finally:
        racecheck.uninstall()


class TestDetector:
    def test_consistent_order_is_clean(self, mon):
        a, b = threading.Lock(), threading.Lock()

        def use():
            for _ in range(5):
                with a:
                    with b:
                        pass

        ts = [threading.Thread(target=use) for _ in range(4)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        mon.check()  # no cycles

    def test_inversion_is_a_cycle(self, mon):
        # two sites acquired in opposite orders by different code paths;
        # sites are construction call-sites, so build on distinct lines
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        assert mon.cycles(repo_only=False)
        with pytest.raises(racecheck.LockOrderError, match="cycle"):
            mon.check(repo_only=False)

    def test_self_deadlock_raises_instead_of_hanging(self, mon):
        lk = threading.Lock()
        lk.acquire()
        with pytest.raises(racecheck.LockOrderError, match="self-deadlock"):
            lk.acquire()
        lk.release()

    def test_rlock_reentry_is_fine(self, mon):
        lk = threading.RLock()
        with lk:
            with lk:
                pass
        mon.check()

    def test_try_acquire_adds_no_edges(self, mon):
        a, b = threading.Lock(), threading.Lock()
        with a:
            assert b.acquire(False)
            b.release()
        with b:
            assert a.acquire(False)
            a.release()
        mon.check(repo_only=False)  # try-locks can't deadlock

    def test_condition_and_queue_survive_instrumentation(self, mon):
        import queue

        q = queue.Queue()
        got = []

        def worker():
            got.append(q.get(timeout=5))

        t = threading.Thread(target=worker)
        t.start()
        q.put("x")
        t.join()
        assert got == ["x"]

        cond = threading.Condition()
        flag = []

        def waiter():
            with cond:
                while not flag:
                    cond.wait(timeout=5)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cond:
            flag.append(1)
            cond.notify_all()
        t.join()
        mon.check()

    def test_thread_affinity_assert(self):
        racecheck.reset_affinity()
        obj = object()
        racecheck.assert_owner(obj, "round_state")
        racecheck.assert_owner(obj, "round_state")  # same thread: fine
        err = []

        def other():
            try:
                racecheck.assert_owner(obj, "round_state")
            except racecheck.LockOrderError as e:
                err.append(e)

        t = threading.Thread(target=other, name="intruder")
        t.start()
        t.join()
        assert err and "intruder" in str(err[0])
        racecheck.reset_affinity()


class TestStackDiscipline:
    """The real stack, instrumented."""

    def test_pex_net_is_cycle_free(self):
        from tendermint_tpu.p2p import make_connected_switches
        from tendermint_tpu.p2p.addrbook import AddrBook
        from tendermint_tpu.p2p.netaddress import NetAddress
        from tendermint_tpu.p2p.node_info import NodeInfo, default_version
        from tendermint_tpu.p2p.pex import PEXReactor

        old_interval = sys.getswitchinterval()
        mon = racecheck.install()
        try:
            sys.setswitchinterval(1e-5)
            books = [AddrBook("", routability_strict=False) for _ in range(3)]
            books[0].add_address(
                NetAddress("127.0.0.1", 7991), NetAddress("127.0.0.1", 1)
            )

            def init(i, sw):
                sw.add_reactor("pex", PEXReactor(books[i], ensure_peers_period=0.05))
                sw.set_node_info(
                    NodeInfo(
                        pub_key=sw.node_priv_key.pub_key(),
                        moniker=f"r{i}",
                        network="race_test",
                        version=default_version("0.1.0"),
                        listen_addr=f"127.0.0.1:{7700 + i}",
                    )
                )
                return sw

            sws = make_connected_switches(3, init)
            time.sleep(1.0)
            for sw in sws:
                sw.stop()
        finally:
            sys.setswitchinterval(old_interval)
            racecheck.uninstall()
        mon.check()

    @pytest.mark.slow
    def test_consensus_net_is_cycle_free(self):
        """3 validators committing real blocks under instrumentation +
        aggressive thread preemption: no lock-order cycles anywhere in
        the consensus/mempool/p2p stack."""
        from tests.test_reactors import start_consensus_net, stop_net, wait_until

        old_interval = sys.getswitchinterval()
        mon = racecheck.install()
        try:
            sys.setswitchinterval(1e-4)
            nodes, switches = start_consensus_net(3)
            try:
                assert wait_until(
                    lambda: all(len(n.blocks) >= 2 for n in nodes), timeout=90
                ), [len(n.blocks) for n in nodes]
            finally:
                stop_net(nodes, switches)
        finally:
            sys.setswitchinterval(old_interval)
            racecheck.uninstall()
        mon.check()
        # the net did real work under instrumentation
        assert mon.edges, "expected lock-order edges from the live stack"


class TestRLockReentry:
    def test_reentry_under_sublock_is_not_a_cycle(self):
        """`with r: with b: with r:` is deadlock-free (RLock re-entry
        never blocks) and must not report a phantom cycle (code-review r3)."""
        mon = racecheck.install()
        try:
            r = threading.RLock()
            b = threading.Lock()
            with r:
                with b:
                    with r:
                        pass
        finally:
            racecheck.uninstall()
        mon.check(repo_only=False)
