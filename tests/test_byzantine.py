"""Byzantine fault tolerance test (reference: consensus/byzantine_test.go).

4 validators, 1 byzantine. The byzantine proposer signs TWO conflicting
proposals and sends each to a different subset of peers (bypassing the
double-sign guard, byzantine_test.go:162-220 + ByzantinePrivValidator
268). The three honest validators must still converge: the chain advances
and every honest node commits identical blocks.
"""

from __future__ import annotations

import time

import pytest

from tendermint_tpu.consensus import messages as msgs
from tendermint_tpu.consensus.reactor import DATA_CHANNEL, ConsensusReactor, _enc
from tendermint_tpu.consensus.state import MsgInfo
from tendermint_tpu.mempool.reactor import MempoolReactor
from tendermint_tpu.p2p import make_connected_switches
from tendermint_tpu.p2p.node_info import NodeInfo, default_version
from tendermint_tpu.types import BlockID, Proposal
from tendermint_tpu.types.priv_validator import PrivValidatorFS
from tests.test_reactors import TEST_CHAIN_ID, make_genesis, make_node, wait_until
from tendermint_tpu.config import test_config as _test_config


class ByzantinePrivValidator:
    """Signs anything: no last-height/round/step regression guard
    (byzantine_test.go:268-305)."""

    def __init__(self, inner: PrivValidatorFS):
        self.inner = inner

    def get_address(self) -> bytes:
        return self.inner.get_address()

    def get_pub_key(self):
        return self.inner.get_pub_key()

    def sign_vote(self, chain_id: str, vote):
        vote.signature = self.inner.priv_key.sign(vote.sign_bytes(chain_id))
        return vote

    def sign_proposal(self, chain_id: str, proposal):
        proposal.signature = self.inner.priv_key.sign(proposal.sign_bytes(chain_id))
        return proposal

    def sign_heartbeat(self, chain_id: str, hb):
        hb.signature = self.inner.priv_key.sign(hb.sign_bytes(chain_id))
        return hb


def make_byzantine_decide_proposal(cs, sw):
    """Replace default_decide_proposal: two conflicting blocks, one per
    peer partition (byzantine_test.go:162-220)."""

    def byz_decide(height: int, round_: int) -> None:
        rs = cs.rs
        # two different blocks: created from different mempool views — we
        # fake divergence by tweaking nothing vs injecting a tx
        block_a, parts_a = cs.create_proposal_block()
        cs.mempool.check_tx(b"byz-extra-tx=1")
        block_b, parts_b = cs.create_proposal_block()
        if block_a is None or block_b is None:
            return
        peers = sw.peers.list()
        half = len(peers) // 2
        for block, parts, targets in (
            (block_a, parts_a, peers[:half]),
            (block_b, parts_b, peers[half:]),
        ):
            pol_round, pol_block_id = rs.votes.pol_info()
            proposal = Proposal(
                height=height,
                round_=round_,
                block_parts_header=parts.header(),
                pol_round=pol_round,
                pol_block_id=pol_block_id or BlockID(),
            )
            cs.priv_validator.sign_proposal(cs.state.chain_id, proposal)
            for peer in targets:
                peer.send(DATA_CHANNEL, _enc(msgs.ProposalMessage(proposal)))
                for i in range(parts.total):
                    peer.send(
                        DATA_CHANNEL,
                        _enc(msgs.BlockPartMessage(height, round_, parts.get_part(i))),
                    )
        # the byzantine node itself adopts block_a so it keeps voting
        cs.send_internal_message(MsgInfo(msgs.ProposalMessage(
            cs.priv_validator.sign_proposal(
                cs.state.chain_id,
                Proposal(
                    height=height, round_=round_,
                    block_parts_header=parts_a.header(),
                    pol_round=-1, pol_block_id=BlockID(),
                ),
            )
        )))
        for i in range(parts_a.total):
            cs.send_internal_message(
                MsgInfo(msgs.BlockPartMessage(height, round_, parts_a.get_part(i)))
            )

    return byz_decide


@pytest.mark.slow
def test_byzantine_proposer_cannot_halt_chain():
    doc, pvs = make_genesis(4)
    nodes = [make_node(doc, pvs[i]) for i in range(4)]
    for n in nodes:
        n.subscribe_blocks()
    # find which node is the height-1 proposer; make THAT one byzantine
    proposer_addr = nodes[0].state.validators.get_proposer().address
    byz_idx = next(
        i for i, pv in enumerate(pvs) if pv.get_address() == proposer_addr
    )
    byz_node = nodes[byz_idx]
    byz_node.cs.set_priv_validator(ByzantinePrivValidator(pvs[byz_idx]))

    reactors = []

    def init(i, sw):
        node = nodes[i]
        con_r = ConsensusReactor(node.cs, fast_sync=False)
        con_r.set_event_switch(node.evsw)
        sw.add_reactor("CONSENSUS", con_r)
        sw.add_reactor("MEMPOOL", MempoolReactor(_test_config().mempool, node.mempool))
        sw.set_node_info(
            NodeInfo(
                pub_key=sw.node_priv_key.pub_key(),
                moniker=f"byz{i}",
                network=TEST_CHAIN_ID,
                version=default_version("test"),
            )
        )
        reactors.append(con_r)
        if i == byz_idx:
            node.cs.decide_proposal = make_byzantine_decide_proposal(node.cs, sw)
        return sw

    switches = make_connected_switches(4, init)
    honest = [n for i, n in enumerate(nodes) if i != byz_idx]
    try:
        # the chain must advance despite conflicting proposals
        assert wait_until(
            lambda: all(n.store.height() >= 2 for n in honest), timeout=60
        ), [n.store.height() for n in honest]
        # and all honest nodes agree byte-for-byte
        for h in (1, 2):
            hashes = {n.store.load_block(h).hash() for n in honest}
            assert len(hashes) == 1, f"honest divergence at height {h}"
    finally:
        for sw in switches:
            sw.stop()
        for n in nodes:
            n.evsw.stop()
