"""Byzantine fault tolerance test (reference: consensus/byzantine_test.go).

4 validators, 1 byzantine. The byzantine proposer signs TWO conflicting
proposals and sends each to a different subset of peers (bypassing the
double-sign guard, byzantine_test.go:162-220 + ByzantinePrivValidator
268). The three honest validators must still converge: the chain advances
and every honest node commits identical blocks.
"""

from __future__ import annotations

import threading
import time

import pytest

from tendermint_tpu.consensus import messages as msgs
from tendermint_tpu.consensus.reactor import DATA_CHANNEL, ConsensusReactor, _enc
from tendermint_tpu.consensus.state import MsgInfo
from tendermint_tpu.mempool.reactor import MempoolReactor
from tendermint_tpu.p2p import make_connected_switches
from tendermint_tpu.p2p.node_info import NodeInfo, default_version
from tendermint_tpu.types import BlockID, Proposal
from tendermint_tpu.types.priv_validator import PrivValidatorFS
from tests.test_reactors import TEST_CHAIN_ID, make_genesis, make_node, wait_until
from tendermint_tpu.config import test_config as _test_config


class ByzantinePrivValidator:
    """Signs anything: no last-height/round/step regression guard
    (byzantine_test.go:268-305)."""

    def __init__(self, inner: PrivValidatorFS):
        self.inner = inner

    def get_address(self) -> bytes:
        return self.inner.get_address()

    def get_pub_key(self):
        return self.inner.get_pub_key()

    def sign_vote(self, chain_id: str, vote):
        vote.signature = self.inner.priv_key.sign(vote.sign_bytes(chain_id))
        return vote

    def sign_proposal(self, chain_id: str, proposal):
        proposal.signature = self.inner.priv_key.sign(proposal.sign_bytes(chain_id))
        return proposal

    def sign_heartbeat(self, chain_id: str, hb):
        hb.signature = self.inner.priv_key.sign(hb.sign_bytes(chain_id))
        return hb


def make_byzantine_decide_proposal(cs, sw):
    """Replace default_decide_proposal: two conflicting blocks, one per
    peer partition (byzantine_test.go:162-220)."""

    def byz_decide(height: int, round_: int) -> None:
        rs = cs.rs
        # two different blocks: created from different mempool views — we
        # fake divergence by tweaking nothing vs injecting a tx
        block_a, parts_a = cs.create_proposal_block()
        cs.mempool.check_tx(b"byz-extra-tx=1")
        block_b, parts_b = cs.create_proposal_block()
        if block_a is None or block_b is None:
            return
        peers = sw.peers.list()
        half = len(peers) // 2
        for block, parts, targets in (
            (block_a, parts_a, peers[:half]),
            (block_b, parts_b, peers[half:]),
        ):
            pol_round, pol_block_id = rs.votes.pol_info()
            proposal = Proposal(
                height=height,
                round_=round_,
                block_parts_header=parts.header(),
                pol_round=pol_round,
                pol_block_id=pol_block_id or BlockID(),
            )
            cs.priv_validator.sign_proposal(cs.state.chain_id, proposal)
            for peer in targets:
                peer.send(DATA_CHANNEL, _enc(msgs.ProposalMessage(proposal)))
                for i in range(parts.total):
                    peer.send(
                        DATA_CHANNEL,
                        _enc(msgs.BlockPartMessage(height, round_, parts.get_part(i))),
                    )
        # the byzantine node itself adopts block_a so it keeps voting
        cs.send_internal_message(MsgInfo(msgs.ProposalMessage(
            cs.priv_validator.sign_proposal(
                cs.state.chain_id,
                Proposal(
                    height=height, round_=round_,
                    block_parts_header=parts_a.header(),
                    pol_round=-1, pol_block_id=BlockID(),
                ),
            )
        )))
        for i in range(parts_a.total):
            cs.send_internal_message(
                MsgInfo(msgs.BlockPartMessage(height, round_, parts_a.get_part(i)))
            )

    return byz_decide


@pytest.mark.slow
def test_byzantine_proposer_cannot_halt_chain():
    doc, pvs = make_genesis(4)
    nodes = [make_node(doc, pvs[i]) for i in range(4)]
    for n in nodes:
        n.subscribe_blocks()
    # find which node is the height-1 proposer; make THAT one byzantine
    proposer_addr = nodes[0].state.validators.get_proposer().address
    byz_idx = next(
        i for i, pv in enumerate(pvs) if pv.get_address() == proposer_addr
    )
    byz_node = nodes[byz_idx]
    byz_node.cs.set_priv_validator(ByzantinePrivValidator(pvs[byz_idx]))

    reactors = []

    def init(i, sw):
        node = nodes[i]
        con_r = ConsensusReactor(node.cs, fast_sync=False)
        con_r.set_event_switch(node.evsw)
        sw.add_reactor("CONSENSUS", con_r)
        sw.add_reactor("MEMPOOL", MempoolReactor(_test_config().mempool, node.mempool))
        sw.set_node_info(
            NodeInfo(
                pub_key=sw.node_priv_key.pub_key(),
                moniker=f"byz{i}",
                network=TEST_CHAIN_ID,
                version=default_version("test"),
            )
        )
        reactors.append(con_r)
        if i == byz_idx:
            node.cs.decide_proposal = make_byzantine_decide_proposal(node.cs, sw)
        return sw

    switches = make_connected_switches(4, init)
    honest = [n for i, n in enumerate(nodes) if i != byz_idx]
    try:
        # the chain must advance despite conflicting proposals
        assert wait_until(
            lambda: all(n.store.height() >= 2 for n in honest), timeout=60
        ), [n.store.height() for n in honest]
        # and all honest nodes agree byte-for-byte
        for h in (1, 2):
            hashes = {n.store.load_block(h).hash() for n in honest}
            assert len(hashes) == 1, f"honest divergence at height {h}"
    finally:
        for sw in switches:
            sw.stop()
        for n in nodes:
            n.evsw.stop()


def test_flooding_peer_cannot_halt_chain():
    """Adversarial liveness: a peer that floods decodable consensus
    messages (valid-shape votes from a non-validator key) at wire rate
    must not stall the honest validators — the bounded peer-message
    enqueue drops excess instead of wedging recv routines
    (consensus/state._enqueue_peer_msg; the pre-fix behavior froze the
    whole multiplexed connection)."""
    from tendermint_tpu.consensus.reactor import (
        DATA_CHANNEL as _DC,
        STATE_CHANNEL,
        VOTE_CHANNEL,
        VOTE_SET_BITS_CHANNEL,
    )
    from tendermint_tpu.p2p import Switch, connect2_switches
    from tendermint_tpu.p2p.conn import ChannelDescriptor
    from tendermint_tpu.p2p.node_info import NodeInfo, default_version
    from tendermint_tpu.p2p.switch import Reactor
    from tendermint_tpu.types import Vote
    from tendermint_tpu.types.vote import VOTE_TYPE_PREVOTE
    from tests.test_reactors import start_consensus_net, stop_net, wait_until

    nodes, switches = start_consensus_net(4)

    from tendermint_tpu.libs.service import BaseService

    class FloodSender(Reactor, BaseService):
        """Speaks the consensus channels but only to inject traffic."""

        def __init__(self):
            BaseService.__init__(self, name="flood")

        def get_channels(self):
            # all four consensus channels: the victim gossips on
            # STATE/DATA too, and an unknown channel drops the peer
            return [
                ChannelDescriptor(id=ch, priority=5, send_queue_capacity=1000)
                for ch in (STATE_CHANNEL, _DC, VOTE_CHANNEL, VOTE_SET_BITS_CHANNEL)
            ]

        def add_peer(self, peer):
            pass

        def remove_peer(self, peer, reason):
            pass

        def receive(self, ch_id, peer, msg_bytes):
            pass

    flood_sw = Switch()
    flood_sw.add_reactor("FLOOD", FloodSender())
    flood_sw.set_node_info(
        NodeInfo(
            pub_key=flood_sw.node_priv_key.pub_key(),
            moniker="flooder",
            network=nodes[0].state.chain_id,
            version=default_version("test"),
        )
    )
    flood_sw.start()
    try:
        assert wait_until(lambda: all(len(n.blocks) >= 1 for n in nodes),
                          timeout=60)
        # CALIBRATE to the box's current headroom (round-3 flake: this
        # test fails at the tail of a 5-minute suite run on a 1-core box
        # but passes alone — wall-clock deadlines don't transfer across
        # load). Time an UNflooded 2-block stretch now, with whatever
        # leftover suite threads are churning, and scale both the flood
        # pacing and the flooded deadline from it.
        calib_start = min(len(n.blocks) for n in nodes)
        t0 = time.time()
        assert wait_until(
            lambda: all(len(n.blocks) >= calib_start + 2 for n in nodes),
            timeout=180,
        ), "calibration: chain not advancing even without flood"
        t_two_blocks = max(time.time() - t0, 1.0)

        connect2_switches(switches + [flood_sw], 0, 4)
        victim_peer = next(iter(flood_sw.peers.list()), None)
        assert victim_peer is not None

        # flood: shape-valid votes signed by a NON-validator, pinned to
        # the height at flood start (stale as the chain advances — still
        # decodable, still enqueued, still rejected by processing)
        from tendermint_tpu.crypto.keys import gen_priv_key_ed25519

        atk = PrivValidatorFS(gen_priv_key_ed25519(), None)
        flood_height = nodes[0].cs.get_round_state().height  # pin once:
        # the live RoundState mutates under us from the consensus thread
        stop_flood = threading.Event()
        stats = {"sent": 0}

        # pace inversely to headroom: ~200 msg/s on an idle box, scaled
        # down when the calibration says the box is already saturated (an
        # unthrottled python sign+send loop starves the validators of the
        # GIL and stalls consensus by resource exhaustion — which is not
        # the property under test; the bounded enqueue keeping recv
        # routines un-wedged is)
        pace = 0.005 * max(1.0, t_two_blocks / 10.0)

        def flood():
            i = 0
            while not stop_flood.is_set():
                v = Vote(
                    validator_address=atk.get_address(),
                    validator_index=i % 4,
                    height=flood_height,
                    round_=0,
                    type_=VOTE_TYPE_PREVOTE,
                    block_id=BlockID(),
                )
                v = atk.sign_vote(nodes[0].state.chain_id, v)  # returns the
                # signed copy; Vote is not mutated in place
                if victim_peer.try_send(VOTE_CHANNEL, _enc(msgs.VoteMessage(v))):
                    stats["sent"] += 1
                i += 1
                time.sleep(pace)

        flooder = threading.Thread(target=flood, daemon=True)
        flooder.start()

        # the chain must keep committing WHILE being flooded; the deadline
        # scales with the measured unflooded rate (8x headroom: flood
        # processing + drops legitimately slow the chain, they must not
        # STOP it)
        start = min(len(n.blocks) for n in nodes)
        deadline = min(300.0, max(60.0, 8.0 * t_two_blocks))
        ok = wait_until(
            lambda: all(len(n.blocks) >= start + 2 for n in nodes),
            timeout=deadline,
        )
        stop_flood.set()
        flooder.join(5)
        drops = [n.cs._peer_msg_drops for n in nodes]
        assert stats["sent"] > 20, f"flood only delivered {stats['sent']}"
        assert ok, (
            f"chain stalled under flood: blocks={[len(n.blocks) for n in nodes]} "
            f"start={start} deadline={deadline:.0f}s (unflooded 2 blocks took "
            f"{t_two_blocks:.1f}s) flood_sent={stats['sent']} "
            f"ingress_drops={drops} (drops>0 means the bound worked and the "
            f"stall is resource starvation, not a wedged recv routine)"
        )
        # and the victim still has its honest peers
        assert switches[0].peers.size() >= 3
    finally:
        flood_sw.stop()
        stop_net(nodes, switches)
