"""Core type tests, modeled on the reference's types/*_test.go suite:
vote_set_test.go (quorum math, conflicts), validator_set_test.go (proposer
rotation), part_set_test.go, priv_validator_test.go (double-sign guard),
tx_test.go (merkle proofs), genesis_test.go."""

import json

import pytest

from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
from tendermint_tpu.types import (
    Block,
    BlockID,
    Commit,
    ConsensusParams,
    GenesisDoc,
    GenesisValidator,
    PartSet,
    PartSetHeader,
    Proposal,
    Validator,
    ValidatorSet,
    Vote,
    VoteSet,
    VOTE_TYPE_PRECOMMIT,
    VOTE_TYPE_PREVOTE,
    txs_hash,
    txs_proof,
)
from tendermint_tpu.types.block import empty_commit
from tendermint_tpu.types.heartbeat import Heartbeat
from tendermint_tpu.types.part_set import InvalidProofError, UnexpectedIndexError
from tendermint_tpu.types.priv_validator import (
    DoubleSignError,
    PrivValidatorFS,
    STEP_PREVOTE,
)
from tendermint_tpu.types.validator_set import CommitError
from tendermint_tpu.types.vote import (
    ConflictingVotesError,
    InvalidSignatureError,
    UnexpectedStepError,
)


def make_val_set(n, power=10):
    """n validators with equal power; returns (ValidatorSet, [PrivValidatorFS])."""
    privs = [PrivValidatorFS(gen_priv_key_ed25519(f"val-{i}".encode()), None) for i in range(n)]
    vals = [Validator.new(p.get_pub_key(), power) for p in privs]
    vs = ValidatorSet(vals)
    # sort privs to match the set's address order
    privs.sort(key=lambda p: p.get_address())
    return vs, privs


def signed_vote(priv, vs, height, round_, type_, block_id, chain_id="test-chain"):
    idx, _ = vs.get_by_address(priv.get_address())
    vote = Vote(
        validator_address=priv.get_address(),
        validator_index=idx,
        height=height,
        round_=round_,
        type_=type_,
        block_id=block_id,
    )
    return priv.sign_vote(chain_id, vote)


BLOCK_ID = BlockID(b"\xaa" * 20, PartSetHeader(2, b"\xbb" * 20))
NIL_BLOCK = BlockID()


class TestVoteSet:
    def test_quorum_progression(self):
        vs, privs = make_val_set(10, power=1)
        voteset = VoteSet("test-chain", 1, 0, VOTE_TYPE_PREVOTE, vs)
        # 6 votes: no 2/3 (need 7 of 10)
        for p in privs[:6]:
            assert voteset.add_vote(signed_vote(p, vs, 1, 0, VOTE_TYPE_PREVOTE, BLOCK_ID))
        assert not voteset.has_two_thirds_majority()
        assert not voteset.has_two_thirds_any()
        # 7th: quorum
        assert voteset.add_vote(signed_vote(privs[6], vs, 1, 0, VOTE_TYPE_PREVOTE, BLOCK_ID))
        assert voteset.has_two_thirds_majority()
        assert voteset.two_thirds_majority() == BLOCK_ID

    def test_nil_votes_count_toward_any_not_block(self):
        vs, privs = make_val_set(9, power=1)
        voteset = VoteSet("test-chain", 1, 0, VOTE_TYPE_PREVOTE, vs)
        for p in privs[:4]:
            voteset.add_vote(signed_vote(p, vs, 1, 0, VOTE_TYPE_PREVOTE, BLOCK_ID))
        for p in privs[4:7]:
            voteset.add_vote(signed_vote(p, vs, 1, 0, VOTE_TYPE_PREVOTE, NIL_BLOCK))
        assert voteset.has_two_thirds_any()
        assert not voteset.has_two_thirds_majority()

    def test_duplicate_returns_false(self):
        vs, privs = make_val_set(4)
        voteset = VoteSet("test-chain", 1, 0, VOTE_TYPE_PREVOTE, vs)
        v = signed_vote(privs[0], vs, 1, 0, VOTE_TYPE_PREVOTE, BLOCK_ID)
        assert voteset.add_vote(v)
        assert voteset.add_vote(v) is False

    def test_wrong_step_rejected(self):
        vs, privs = make_val_set(4)
        voteset = VoteSet("test-chain", 1, 0, VOTE_TYPE_PREVOTE, vs)
        with pytest.raises(UnexpectedStepError):
            voteset.add_vote(signed_vote(privs[0], vs, 2, 0, VOTE_TYPE_PREVOTE, BLOCK_ID))
        with pytest.raises(UnexpectedStepError):
            voteset.add_vote(signed_vote(privs[1], vs, 1, 1, VOTE_TYPE_PREVOTE, BLOCK_ID))

    def test_bad_signature_rejected(self):
        vs, privs = make_val_set(4)
        voteset = VoteSet("test-chain", 1, 0, VOTE_TYPE_PREVOTE, vs)
        good = signed_vote(privs[0], vs, 1, 0, VOTE_TYPE_PREVOTE, BLOCK_ID)
        # re-sign under a different chain id -> signature invalid here
        bad = signed_vote(privs[1], vs, 1, 0, VOTE_TYPE_PREVOTE, BLOCK_ID, chain_id="other")
        assert voteset.add_vote(good)
        with pytest.raises(InvalidSignatureError):
            voteset.add_vote(bad)

    def test_conflicting_votes(self):
        vs, privs = make_val_set(4, power=1)
        voteset = VoteSet("test-chain", 1, 0, VOTE_TYPE_PREVOTE, vs)
        v1 = signed_vote(privs[0], vs, 1, 0, VOTE_TYPE_PREVOTE, BLOCK_ID)
        assert voteset.add_vote(v1)
        other = BlockID(b"\xcc" * 20, PartSetHeader(1, b"\xdd" * 20))
        # conflicting vote (same signer, different block) — not tracked: rejected
        # (note: signing would hit the double-sign guard, so craft directly)
        idx, _ = vs.get_by_address(privs[0].get_address())
        v2 = Vote(privs[0].get_address(), idx, 1, 0, VOTE_TYPE_PREVOTE, other)
        v2 = v2.with_signature(privs[0].priv_key.sign(v2.sign_bytes("test-chain")))
        with pytest.raises(ConflictingVotesError):
            voteset.add_vote(v2)
        # canonical vote unchanged
        assert voteset.get_by_index(idx).block_id == BLOCK_ID

    def test_peer_maj23_tracks_conflicts(self):
        vs, privs = make_val_set(4, power=1)
        voteset = VoteSet("test-chain", 1, 0, VOTE_TYPE_PREVOTE, vs)
        other = BlockID(b"\xcc" * 20, PartSetHeader(1, b"\xdd" * 20))
        voteset.set_peer_maj23("peer1", other)
        v1 = signed_vote(privs[0], vs, 1, 0, VOTE_TYPE_PREVOTE, BLOCK_ID)
        assert voteset.add_vote(v1)
        idx, _ = vs.get_by_address(privs[0].get_address())
        v2 = Vote(privs[0].get_address(), idx, 1, 0, VOTE_TYPE_PREVOTE, other)
        v2 = v2.with_signature(privs[0].priv_key.sign(v2.sign_bytes("test-chain")))
        # conflicting but tracked via peer claim: added=True, still raises conflict
        with pytest.raises(ConflictingVotesError):
            voteset.add_vote(v2)
        assert voteset.bit_array_by_block_id(other).num_true_bits() == 1

    def test_make_commit(self):
        vs, privs = make_val_set(4, power=1)
        voteset = VoteSet("test-chain", 1, 0, VOTE_TYPE_PRECOMMIT, vs)
        for p in privs[:3]:
            voteset.add_vote(signed_vote(p, vs, 1, 0, VOTE_TYPE_PRECOMMIT, BLOCK_ID))
        assert voteset.is_commit()
        commit = voteset.make_commit()
        assert commit.block_id == BLOCK_ID
        assert commit.size() == 4
        assert sum(1 for p in commit.precommits if p) == 3
        assert commit.validate_basic() is None

    def test_weighted_quorum(self):
        """One validator with 2/3+ of the power reaches quorum alone... but
        not quite: quorum needs strictly more than 2/3."""
        privs = [PrivValidatorFS(gen_priv_key_ed25519(f"w-{i}".encode()), None) for i in range(3)]
        vals = [
            Validator.new(privs[0].get_pub_key(), 67),
            Validator.new(privs[1].get_pub_key(), 23),
            Validator.new(privs[2].get_pub_key(), 10),
        ]
        vs = ValidatorSet(vals)
        voteset = VoteSet("test-chain", 1, 0, VOTE_TYPE_PREVOTE, vs)
        big = next(p for p in privs if Validator.new(p.get_pub_key(), 0).address == vals[0].address)
        voteset.add_vote(signed_vote(big, vs, 1, 0, VOTE_TYPE_PREVOTE, BLOCK_ID))
        # 67 of 100: needs > 66.67 i.e. >= 67... quorum = 100*2//3+1 = 67 -> reached
        assert voteset.has_two_thirds_majority()


class TestValidatorSet:
    def test_sorted_by_address(self):
        vs, _ = make_val_set(10)
        addrs = [v.address for v in vs.validators]
        assert addrs == sorted(addrs)

    def test_proposer_rotation_equal_power(self):
        """With equal powers, each validator proposes once per n rounds."""
        vs, _ = make_val_set(5, power=1)
        seen = []
        for _ in range(5):
            seen.append(vs.get_proposer().address)
            vs.increment_accum(1)
        assert sorted(seen) == sorted(v.address for v in vs.validators)
        assert len(set(seen)) == 5

    def test_proposer_rotation_weighted(self):
        """Proposer frequency tracks voting power over many rounds."""
        privs = [PrivValidatorFS(gen_priv_key_ed25519(f"rw-{i}".encode()), None) for i in range(3)]
        powers = {0: 1, 1: 2, 2: 7}
        vals = [Validator.new(p.get_pub_key(), powers[i]) for i, p in enumerate(privs)]
        by_addr = {v.address: powers[i] for i, v in enumerate(vals)}
        vs = ValidatorSet(vals)
        counts = {}
        for _ in range(1000):
            addr = vs.get_proposer().address
            counts[addr] = counts.get(addr, 0) + 1
            vs.increment_accum(1)
        for addr, count in counts.items():
            assert abs(count - 100 * by_addr[addr]) <= 1

    def test_increment_accum_times_matches_repeated(self):
        vs1, _ = make_val_set(5, power=3)
        vs2 = vs1.copy()
        vs1.increment_accum(5)
        for _ in range(5):
            vs2.increment_accum(1)
        assert vs1.get_proposer().address == vs2.get_proposer().address
        assert [v.accum for v in vs1.validators] == [v.accum for v in vs2.validators]

    def test_add_update_remove(self):
        vs, _ = make_val_set(3)
        new_priv = PrivValidatorFS(gen_priv_key_ed25519(b"new-val"), None)
        new_val = Validator.new(new_priv.get_pub_key(), 5)
        assert vs.add(new_val)
        assert not vs.add(new_val)  # dup
        assert vs.size() == 4
        assert vs.has_address(new_val.address)
        updated = Validator.new(new_priv.get_pub_key(), 15)
        assert vs.update(updated)
        _, got = vs.get_by_address(new_val.address)
        assert got.voting_power == 15
        removed, ok = vs.remove(new_val.address)
        assert ok and removed.voting_power == 15
        assert vs.size() == 3
        _, missing = vs.get_by_address(new_val.address)
        assert missing is None

    def test_hash_changes_with_membership(self):
        vs, _ = make_val_set(3)
        h1 = vs.hash()
        assert len(h1) == 20
        vs.add(Validator.new(PrivValidatorFS(gen_priv_key_ed25519(b"x"), None).get_pub_key(), 1))
        assert vs.hash() != h1

    def test_json_roundtrip(self):
        vs, _ = make_val_set(4)
        vs2 = ValidatorSet.from_json(vs.to_json())
        assert vs2.hash() == vs.hash()
        assert vs2.get_proposer().address == vs.get_proposer().address


class TestVerifyCommit:
    def _make_commit(self, vs, privs, height=1, block_id=BLOCK_ID, n_sign=None):
        voteset = VoteSet("test-chain", height, 0, VOTE_TYPE_PRECOMMIT, vs)
        for p in privs[: n_sign if n_sign is not None else len(privs)]:
            voteset.add_vote(signed_vote(p, vs, height, 0, VOTE_TYPE_PRECOMMIT, block_id))
        return voteset.make_commit()

    def test_valid_commit(self):
        vs, privs = make_val_set(4, power=1)
        commit = self._make_commit(vs, privs, n_sign=3)
        vs.verify_commit("test-chain", BLOCK_ID, 1, commit)  # no raise

    def test_insufficient_power(self):
        vs, privs = make_val_set(4, power=1)
        commit = self._make_commit(vs, privs, n_sign=3)
        # drop one signature -> only 2 of 4
        commit.precommits[[i for i, p in enumerate(commit.precommits) if p][0]] = None
        with pytest.raises(CommitError, match="voting power"):
            vs.verify_commit("test-chain", BLOCK_ID, 1, commit)

    def test_wrong_height(self):
        vs, privs = make_val_set(4, power=1)
        commit = self._make_commit(vs, privs, n_sign=3)
        with pytest.raises(CommitError, match="height"):
            vs.verify_commit("test-chain", BLOCK_ID, 2, commit)

    def test_tampered_signature(self):
        vs, privs = make_val_set(4, power=1)
        commit = self._make_commit(vs, privs, n_sign=3)
        i = next(i for i, p in enumerate(commit.precommits) if p)
        v = commit.precommits[i]
        from tendermint_tpu.crypto.keys import SignatureEd25519

        bad = bytearray(v.signature.raw)
        bad[0] ^= 1
        commit.precommits[i] = v.with_signature(SignatureEd25519(bytes(bad)))
        with pytest.raises(CommitError, match="signature"):
            vs.verify_commit("test-chain", BLOCK_ID, 1, commit)

    def test_batch_verifier_hook(self):
        """A batch verifier sees all signature items at once and its verdicts
        drive the same accept/reject logic."""
        vs, privs = make_val_set(4, power=1)
        commit = self._make_commit(vs, privs, n_sign=3)
        seen = []

        def batch(items):
            seen.extend(items)
            from tendermint_tpu.crypto import ed25519

            return [ed25519.verify(pk, msg, sig) for pk, msg, sig in items]

        vs.verify_commit("test-chain", BLOCK_ID, 1, commit, batch_verifier=batch)
        assert len(seen) == 3

        with pytest.raises(CommitError, match="signature"):
            vs.verify_commit(
                "test-chain", BLOCK_ID, 1, commit,
                batch_verifier=lambda items: [False] * len(items),
            )


class TestPartSet:
    def test_roundtrip(self):
        data = bytes(range(256)) * 500  # 128000 bytes
        ps = PartSet.from_data(data, 4096)
        assert ps.total == (len(data) + 4095) // 4096
        assert ps.is_complete()
        assert ps.get_data() == data

        # rebuild from header by adding parts in reverse order
        ps2 = PartSet.from_header(ps.header())
        assert not ps2.is_complete()
        for i in reversed(range(ps.total)):
            assert ps2.add_part(ps.get_part(i))
        assert ps2.is_complete()
        assert ps2.get_data() == data
        assert ps2.header() == ps.header()

    def test_duplicate_part(self):
        ps = PartSet.from_data(b"x" * 10000, 4096)
        ps2 = PartSet.from_header(ps.header())
        assert ps2.add_part(ps.get_part(0))
        assert ps2.add_part(ps.get_part(0)) is False

    def test_bad_index_and_proof(self):
        ps = PartSet.from_data(b"y" * 10000, 4096)
        ps2 = PartSet.from_header(ps.header())
        from tendermint_tpu.types.part_set import Part

        with pytest.raises(UnexpectedIndexError):
            ps2.add_part(Part(index=99, bytes_=b"z"))
        evil = ps.get_part(1)
        with pytest.raises(InvalidProofError):
            ps2.add_part(Part(index=1, bytes_=b"tampered", proof=evil.proof))

    def test_empty_data_single_part(self):
        ps = PartSet.from_data(b"", 4096)
        assert ps.total == 1
        assert ps.get_data() == b""


class TestBlock:
    def _make(self, txs=(b"tx1", b"tx2"), height=2):
        vs, privs = make_val_set(4, power=1)
        voteset = VoteSet("test-chain", height - 1, 0, VOTE_TYPE_PRECOMMIT, vs)
        prev_bid = BlockID(b"\x11" * 20, PartSetHeader(1, b"\x22" * 20))
        for p in privs[:3]:
            voteset.add_vote(signed_vote(p, vs, height - 1, 0, VOTE_TYPE_PRECOMMIT, prev_bid))
        commit = voteset.make_commit()
        block, ps = Block.make_block(
            height, "test-chain", list(txs), commit, prev_bid, vs.hash(), b"apphash", 4096
        )
        return block, ps, vs, prev_bid

    def test_hash_and_validate(self):
        block, ps, vs, prev_bid = self._make()
        assert len(block.hash()) == 20
        assert block.validate_basic("test-chain", 1, prev_bid, b"apphash") is None
        assert block.validate_basic("other", 1, prev_bid, b"apphash") is not None
        assert block.validate_basic("test-chain", 5, prev_bid, b"apphash") is not None
        assert block.validate_basic("test-chain", 1, BlockID(), b"apphash") is not None
        assert block.validate_basic("test-chain", 1, prev_bid, b"wrong") is not None

    def test_binary_roundtrip_preserves_hash(self):
        block, ps, _, _ = self._make()
        block2 = Block.from_bytes(block.to_bytes())
        assert block2.hash() == block.hash()
        assert block2.header.height == block.header.height
        assert block2.data.txs == block.data.txs
        assert block2.last_commit.hash() == block.last_commit.hash()

    def test_part_set_reassembles_block(self):
        block, ps, _, _ = self._make(txs=[b"tx-%d" % i for i in range(100)])
        data = ps.get_data()
        assert Block.from_bytes(data).hash() == block.hash()

    def test_json_roundtrip(self):
        block, _, _, _ = self._make()
        block2 = Block.from_json(json.loads(json.dumps(block.to_json())))
        assert block2.hash() == block.hash()

    def test_empty_commit_height1(self):
        vs, _ = make_val_set(1)
        block, ps = Block.make_block(
            1, "test-chain", [], empty_commit(), BlockID(), vs.hash(), b"", 4096
        )
        assert len(block.hash()) == 20
        assert block.validate_basic("test-chain", 0, BlockID(), b"") is None


class TestTxs:
    def test_merkle_proofs(self):
        txs = [b"tx-%d" % i for i in range(7)]
        root = txs_hash(txs)
        for i in range(7):
            proof = txs_proof(txs, i)
            assert proof.root_hash == root
            assert proof.validate(root) is None
            assert proof.validate(b"\x00" * 20) is not None


class TestPrivValidator:
    def test_sign_and_persist(self, tmp_path):
        path = str(tmp_path / "priv_validator.json")
        pv = PrivValidatorFS.load_or_generate(path)
        vote = Vote(pv.get_address(), 0, 5, 0, VOTE_TYPE_PREVOTE, BLOCK_ID)
        signed = pv.sign_vote("c", vote)
        assert pv.get_pub_key().verify_bytes(vote.sign_bytes("c"), signed.signature)
        # reload: last-sign state survives
        pv2 = PrivValidatorFS.load(path)
        assert pv2.last_height == 5
        assert pv2.last_step == STEP_PREVOTE
        assert pv2.get_address() == pv.get_address()

    def test_double_sign_prevention(self, tmp_path):
        pv = PrivValidatorFS.generate(str(tmp_path / "pv.json"))
        v1 = Vote(pv.get_address(), 0, 5, 1, VOTE_TYPE_PREVOTE, BLOCK_ID)
        pv.sign_vote("c", v1)
        # conflicting payload at same HRS
        other = Vote(pv.get_address(), 0, 5, 1, VOTE_TYPE_PREVOTE, NIL_BLOCK)
        with pytest.raises(DoubleSignError):
            pv.sign_vote("c", other)
        # height regression
        with pytest.raises(DoubleSignError):
            pv.sign_vote("c", Vote(pv.get_address(), 0, 4, 0, VOTE_TYPE_PREVOTE, BLOCK_ID))
        # round regression
        with pytest.raises(DoubleSignError):
            pv.sign_vote("c", Vote(pv.get_address(), 0, 5, 0, VOTE_TYPE_PREVOTE, BLOCK_ID))
        # step regression (precommit then prevote same round)
        pv.sign_vote("c", Vote(pv.get_address(), 0, 5, 1, VOTE_TYPE_PRECOMMIT, BLOCK_ID))
        with pytest.raises(DoubleSignError):
            pv.sign_vote("c", Vote(pv.get_address(), 0, 5, 1, VOTE_TYPE_PREVOTE, BLOCK_ID))

    def test_same_payload_replay_returns_same_sig(self, tmp_path):
        pv = PrivValidatorFS.generate(str(tmp_path / "pv.json"))
        v = Vote(pv.get_address(), 0, 5, 1, VOTE_TYPE_PREVOTE, BLOCK_ID)
        s1 = pv.sign_vote("c", v)
        s2 = pv.sign_vote("c", v)
        assert s1.signature == s2.signature

    def test_proposal_signing(self, tmp_path):
        pv = PrivValidatorFS.generate(str(tmp_path / "pv.json"))
        prop = Proposal(3, 0, PartSetHeader(2, b"\xee" * 20))
        signed = pv.sign_proposal("c", prop)
        assert pv.get_pub_key().verify_bytes(prop.sign_bytes("c"), signed.signature)
        # vote at same height/round is a LATER step: allowed
        pv.sign_vote("c", Vote(pv.get_address(), 0, 3, 0, VOTE_TYPE_PREVOTE, BLOCK_ID))
        # but another proposal at same HR is a step regression
        with pytest.raises(DoubleSignError):
            pv.sign_proposal("c", Proposal(3, 0, PartSetHeader(9, b"\xdd" * 20)))

    def test_heartbeat_no_hrs_tracking(self, tmp_path):
        pv = PrivValidatorFS.generate(str(tmp_path / "pv.json"))
        hb = Heartbeat(pv.get_address(), 0, 100, 0, 1)
        signed = pv.sign_heartbeat("c", hb)
        assert pv.get_pub_key().verify_bytes(hb.sign_bytes("c"), signed.signature)
        assert pv.last_height == 0  # untouched


class TestGenesis:
    def test_roundtrip_and_validation(self, tmp_path):
        privs = [PrivValidatorFS(gen_priv_key_ed25519(f"g-{i}".encode()), None) for i in range(3)]
        doc = GenesisDoc(
            genesis_time_ns=1_500_000_000 * 10**9,
            chain_id="test-chain",
            validators=[GenesisValidator(p.get_pub_key(), 10, f"v{i}") for i, p in enumerate(privs)],
        )
        doc.validate_and_complete()
        path = str(tmp_path / "genesis.json")
        doc.save_as(path)
        doc2 = GenesisDoc.from_file(path)
        assert doc2.chain_id == "test-chain"
        assert doc2.validator_hash() == doc.validator_hash()
        assert doc2.consensus_params.block_gossip.block_part_size_bytes == 65536

    def test_invalid_docs(self):
        with pytest.raises(ValueError):
            GenesisDoc(0, "", []).validate_and_complete()
        with pytest.raises(ValueError):
            GenesisDoc(0, "c", []).validate_and_complete()
        priv = PrivValidatorFS(gen_priv_key_ed25519(b"z"), None)
        with pytest.raises(ValueError):
            GenesisDoc(0, "c", [GenesisValidator(priv.get_pub_key(), 0)]).validate_and_complete()


class TestSignBytesFormat:
    def test_vote_sign_bytes_layout(self):
        v = Vote(b"\x01" * 20, 0, 1234, 1, VOTE_TYPE_PRECOMMIT, BLOCK_ID)
        sb = v.sign_bytes("my_chain")
        obj = json.loads(sb)
        assert list(obj.keys()) == sorted(obj.keys())
        assert obj["chain_id"] == "my_chain"
        assert obj["vote"]["height"] == 1234
        assert obj["vote"]["type"] == 2
        assert obj["vote"]["block_id"]["hash"] == "AA" * 20

    def test_nil_vote_omits_hash(self):
        v = Vote(b"\x01" * 20, 0, 1, 0, VOTE_TYPE_PREVOTE, NIL_BLOCK)
        obj = json.loads(v.sign_bytes("c"))
        assert "hash" not in obj["vote"]["block_id"]

    def test_proposal_sign_bytes_layout(self):
        p = Proposal(10, 2, PartSetHeader(3, b"\xab" * 20), -1, BlockID())
        obj = json.loads(p.sign_bytes("chain"))
        assert obj["proposal"]["pol_round"] == -1
        assert obj["proposal"]["round"] == 2
        assert "proposal" in obj and "chain_id" in obj

    def test_sign_bytes_stability(self):
        """Golden vector: any change to the canonical encoding breaks every
        signature in the chain — pin the exact bytes."""
        v = Vote(b"\x01" * 20, 0, 1, 0, VOTE_TYPE_PREVOTE, NIL_BLOCK)
        assert v.sign_bytes("test") == (
            b'{"chain_id":"test","vote":{"block_id":{"parts":{"hash":"","total":0}},'
            b'"height":1,"round":0,"type":1}}'
        )


class TestVerifyCommitsGrouped:
    def _mk(self, vs, privs, height, block_id, n_sign=None):
        voteset = VoteSet("test-chain", height, 0, VOTE_TYPE_PRECOMMIT, vs)
        for p in privs[: n_sign if n_sign is not None else len(privs)]:
            voteset.add_vote(
                signed_vote(p, vs, height, 0, VOTE_TYPE_PRECOMMIT, block_id)
            )
        return voteset.make_commit()

    def test_grouped_async_and_poisoned_entry(self):
        """verify_commits_async: one shared dispatch, per-entry finishers;
        a structurally bad commit raises from ITS finisher only."""
        from tendermint_tpu.ops.gateway import Verifier

        vs, privs = make_val_set(4, power=1)
        v = Verifier(min_tpu_batch=1, use_tpu=True)
        good1 = self._mk(vs, privs, 1, BLOCK_ID)
        bad = self._mk(vs, privs, 2, BLOCK_ID)  # wrong height vs entry
        good2 = self._mk(vs, privs, 3, BLOCK_ID)
        fins = vs.verify_commits_async(
            "test-chain",
            [(BLOCK_ID, 1, good1), (BLOCK_ID, 99, bad), (BLOCK_ID, 3, good2)],
            v.verify_batch_async,
        )
        assert len(fins) == 3
        fins[0]()  # no raise
        with pytest.raises(CommitError, match="height"):
            fins[1]()
        fins[2]()  # the bad entry did not poison this one
        assert v.stats()["tpu_sigs"] == 8  # both good commits, one batch set
