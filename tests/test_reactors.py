"""Reactor integration tests: in-process multi-node nets over pipe switches
(reference: consensus/reactor_test.go, mempool/reactor tests,
blockchain/reactor fast-sync behavior)."""

from __future__ import annotations

import tempfile
import threading
import time

import pytest

from tendermint_tpu.abci.apps.counter import CounterApp
from tendermint_tpu.abci.apps.kvstore import KVStoreApp
from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.blockchain.reactor import BlockchainReactor
from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.config import test_config as _test_config
from tendermint_tpu.consensus.reactor import ConsensusReactor
from tendermint_tpu.consensus.state import ConsensusState
from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
from tendermint_tpu.libs.db import MemDB
from tendermint_tpu.libs.events import EventSwitch
from tendermint_tpu.mempool import Mempool
from tendermint_tpu.mempool.reactor import MempoolReactor
from tendermint_tpu.p2p import make_connected_switches
from tendermint_tpu.proxy.app_conn import AppConnConsensus, AppConnMempool
from tendermint_tpu.state.state import State
from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivValidatorFS
from tendermint_tpu.types import events as tev

TEST_CHAIN_ID = "reactor_test_chain"


def wait_until(cond, timeout=30.0, tick=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(tick)
    return cond()


class Node:
    def __init__(self, cs: ConsensusState, evsw: EventSwitch, mempool: Mempool,
                 store: BlockStore, state: State):
        self.cs = cs
        self.evsw = evsw
        self.mempool = mempool
        self.store = store
        self.state = state
        self.blocks: list = []

    def subscribe_blocks(self) -> None:
        self.evsw.add_listener_for_event(
            "test", tev.EVENT_NEW_BLOCK, lambda d: self.blocks.append(d.block)
        )


def make_genesis(n: int):
    pvs = [PrivValidatorFS(gen_priv_key_ed25519(), None) for _ in range(n)]
    pvs.sort(key=lambda pv: pv.get_address())
    doc = GenesisDoc(
        genesis_time_ns=time.time_ns(),
        chain_id=TEST_CHAIN_ID,
        validators=[GenesisValidator(pv.get_pub_key(), 1, f"v{i}") for i, pv in enumerate(pvs)],
    )
    return doc, pvs


def make_node(doc: GenesisDoc, pv, app=None) -> Node:
    config = _test_config().consensus
    config.root_dir = tempfile.mkdtemp(prefix="reactor-test-")
    app = app if app is not None else CounterApp()
    mtx = threading.RLock()
    mempool = Mempool(_test_config().mempool, AppConnMempool(LocalClient(app, mtx)))
    store = BlockStore(MemDB())
    state = State.get_state(MemDB(), doc)
    evsw = EventSwitch()
    evsw.start()
    cs = ConsensusState(
        config, state, AppConnConsensus(LocalClient(app, mtx)), store, mempool
    )
    cs.set_event_switch(evsw)
    if pv is not None:
        cs.set_priv_validator(pv)
    return Node(cs, evsw, mempool, store, state)


def start_consensus_net(n: int, app_factory=None, switch_factory=None,
                        genesis=None):
    """genesis=(doc, pvs) overrides make_genesis(n) — e.g. a doc whose
    validator set covers only some of the n nodes (the rest run as full
    nodes until a val-tx adds them)."""
    doc, pvs = genesis if genesis is not None else make_genesis(n)
    nodes = [make_node(doc, pvs[i], app_factory() if app_factory else None)
             for i in range(n)]
    for node in nodes:
        node.subscribe_blocks()

    def init(i, sw):
        node = nodes[i]
        con_r = ConsensusReactor(node.cs, fast_sync=False)
        con_r.set_event_switch(node.evsw)
        sw.add_reactor("CONSENSUS", con_r)
        mem_r = MempoolReactor(_test_config().mempool, node.mempool)
        sw.add_reactor("MEMPOOL", mem_r)
        from tendermint_tpu.p2p.node_info import NodeInfo, default_version

        sw.set_node_info(
            NodeInfo(
                pub_key=sw.node_priv_key.pub_key(),
                moniker=f"node{i}",
                network=TEST_CHAIN_ID,
                version=default_version("test"),
            )
        )
        return sw

    switches = make_connected_switches(n, init, switch_factory=switch_factory)
    return nodes, switches


def stop_net(nodes, switches):
    for sw in switches:
        sw.stop()
    for node in nodes:
        node.evsw.stop()


# -- consensus reactor --------------------------------------------------------


@pytest.mark.slow
def test_reactor_net_makes_blocks():
    """4 validators over real reactors: every node commits blocks
    (consensus/reactor_test.go:24-79)."""
    nodes, switches = start_consensus_net(4)
    try:
        assert wait_until(
            lambda: all(len(n.blocks) >= 2 for n in nodes), timeout=60
        ), [len(n.blocks) for n in nodes]
        # all nodes agree on block 1's hash
        h1 = [n.store.load_block(1).hash() for n in nodes]
        assert len(set(h1)) == 1
    finally:
        stop_net(nodes, switches)


@pytest.mark.slow
def test_reactor_net_commits_txs():
    """A tx checked into one node's mempool gossips to the proposer and
    lands in a block everywhere (atomic-broadcast shape)."""
    nodes, switches = start_consensus_net(4, app_factory=KVStoreApp)
    try:
        tx = b"reactor-test-key=reactor-test-value"
        nodes[3].mempool.check_tx(tx)
        assert wait_until(
            lambda: all(
                any(tx in b.data.txs for b in n.blocks) for n in nodes
            ),
            timeout=60,
        ), [sum(len(b.data.txs) for b in n.blocks) for n in nodes]
    finally:
        stop_net(nodes, switches)


# -- fast sync ----------------------------------------------------------------


@pytest.mark.slow
def test_fast_sync_catches_up_and_switches():
    """Node B starts empty with fast_sync=True against node A's chain;
    it downloads+verifies+applies blocks, then switches to consensus
    (blockchain/reactor.go:174-262, 204-217)."""
    doc, pvs = make_genesis(1)
    # -- node A: sole validator, builds a chain by itself
    node_a = make_node(doc, pvs[0])
    # -- node B: non-validator, fast syncs
    node_b = make_node(doc, None)

    def init(i, sw):
        node = (node_a, node_b)[i]
        fast_sync = i == 1
        con_r = ConsensusReactor(node.cs, fast_sync=fast_sync)
        con_r.set_event_switch(node.evsw)
        sw.add_reactor("CONSENSUS", con_r)
        # the reactor owns its own state copy, like the reference's
        # node wiring (node.go:206-227 passes state.Copy() to each)
        bc_r = BlockchainReactor(
            node.state.copy(),
            node.cs.proxy_app_conn,
            node.store,
            fast_sync=fast_sync,
            event_cache=None,
            status_update_interval=0.5,  # test chains move fast
        )
        sw.add_reactor("BLOCKCHAIN", bc_r)
        from tendermint_tpu.p2p.node_info import NodeInfo, default_version

        sw.set_node_info(
            NodeInfo(
                pub_key=sw.node_priv_key.pub_key(),
                moniker=f"node{i}",
                network=TEST_CHAIN_ID,
                version=default_version("test"),
            )
        )
        return sw

    node_a.subscribe_blocks()
    node_b.subscribe_blocks()
    from tendermint_tpu.p2p import Switch, connect2_switches

    switches = [init(i, Switch()) for i in range(2)]
    for sw in switches:
        sw.start()
    try:
        # A builds its chain alone, then freezes — a fixed catch-up target
        assert wait_until(lambda: node_a.store.height() >= 8, timeout=60)
        node_a.cs.stop()
        target = node_a.store.height()
        connect2_switches(switches, 0, 1)
        assert wait_until(
            lambda: node_b.store.height() >= target, timeout=60
        ), f"B at {node_b.store.height()}, A at {target}"
        got = node_b.store.load_block(2)
        want = node_a.store.load_block(2)
        assert got is not None and got.hash() == want.hash()
        # and B switched over to consensus mode
        con_r_b = switches[1].reactor("CONSENSUS")
        assert wait_until(lambda: not con_r_b.fast_sync, timeout=30)
    finally:
        stop_net([node_a, node_b], switches)


@pytest.mark.slow
def test_reactor_net_commits_under_fuzzed_transport():
    """4 validators whose every p2p stream is wrapped in the chaos fuzz
    layer (random per-op delays, p2p/fuzz.py — the reference's
    FuzzedConnection): consensus must still commit and agree. Guards the
    timeout schedule and gossip against a slow, jittery transport."""
    from tendermint_tpu.p2p import Switch
    from tendermint_tpu.p2p.peer import PeerConfig

    def fuzzy_switch():
        return Switch(peer_config=PeerConfig(
            fuzz=True,
            fuzz_config={"prob_sleep": 0.2, "max_delay": 0.03, "seed": 7},
        ))

    nodes, switches = start_consensus_net(4, switch_factory=fuzzy_switch)
    try:
        assert wait_until(
            lambda: all(len(nd.blocks) >= 3 for nd in nodes), timeout=90
        ), [len(nd.blocks) for nd in nodes]
        h2 = [nd.store.load_block(2).hash() for nd in nodes]
        assert len(set(h2)) == 1
    finally:
        stop_net(nodes, switches)


@pytest.mark.slow
def test_validator_set_change_on_live_net():
    """reference consensus/reactor_test.go:82+ (TestValidatorSetChanges),
    end to end over real reactors: a val-tx through the persistent
    kvstore app adds a live full node to the validator set (EndBlock
    diff -> state.set_block_and_validators, effective next height); the
    new validator starts SIGNING (a later commit carries 3 precommits);
    a power-0 val-tx removes it again and the chain keeps going."""
    from tendermint_tpu.abci.apps.kvstore import PersistentKVStoreApp

    pvs = [PrivValidatorFS(gen_priv_key_ed25519(), None) for _ in range(3)]
    pvs.sort(key=lambda pv: pv.get_address())
    # nodes 0,1 validate (power 10 each); node 2 is a full node whose key
    # joins later with power 4 — quorum (>2/3 of 24 = >16) stays
    # reachable by the two genesis validators, so a lagging newcomer can
    # slow rounds but never halt the chain
    doc = GenesisDoc(
        genesis_time_ns=time.time_ns(),
        chain_id=TEST_CHAIN_ID,
        validators=[
            GenesisValidator(pvs[i].get_pub_key(), 10, f"v{i}") for i in range(2)
        ],
    )
    nodes, switches = start_consensus_net(
        3,
        app_factory=lambda: PersistentKVStoreApp(
            tempfile.mkdtemp(prefix="valchg-")
        ),
        genesis=(doc, pvs),
    )
    try:
        assert wait_until(lambda: nodes[0].store.height() >= 2, timeout=30)
        pub_hex = pvs[2].get_pub_key().raw.hex().upper()
        nodes[0].mempool.check_tx(b"val:" + pub_hex.encode() + b"/4")
        # the set grows to 3 on every node's state
        assert wait_until(
            lambda: all(n.cs.state.validators.size() == 3 for n in nodes),
            timeout=30,
        ), [n.cs.state.validators.size() for n in nodes]
        # ... and the newcomer actually signs: some later commit carries
        # all 3 precommits
        def newcomer_signed():
            h = nodes[0].store.height()
            for height in range(max(2, h - 5), h + 1):
                blk = nodes[0].store.load_block(height)
                if blk is not None and sum(
                    1 for pc in blk.last_commit.precommits if pc is not None
                ) == 3:
                    return True
            return False
        assert wait_until(newcomer_signed, timeout=60)
        # remove it again; the chain keeps committing with the original 2
        nodes[1].mempool.check_tx(b"val:" + pub_hex.encode() + b"/0")
        assert wait_until(
            lambda: all(n.cs.state.validators.size() == 2 for n in nodes),
            timeout=30,
        ), [n.cs.state.validators.size() for n in nodes]
        h_after = nodes[0].store.height()
        assert wait_until(lambda: nodes[0].store.height() >= h_after + 2, timeout=30)
    finally:
        stop_net(nodes, switches)


def test_consensus_catchup_of_behind_peer_on_live_chain():
    """A node far behind that is ALREADY in consensus mode (no fast
    sync) must catch up through the gossip catch-up branches — block
    parts from the peer's store (reactor.go:494-535) and stored-commit
    precommits (reactor.go:637-645) — while the chain KEEPS MOVING.
    This is the safety net under fast-sync's racy IsCaughtUp
    switchover: a restart that flips to consensus mode too early (seen
    in round-4 chaos soaks) must still converge, not stall."""
    doc, pvs = make_genesis(1)
    node_a = make_node(doc, pvs[0])
    node_b = make_node(doc, None)  # non-validator observer

    def init(i, sw):
        node = (node_a, node_b)[i]
        con_r = ConsensusReactor(node.cs, fast_sync=False)
        con_r.set_event_switch(node.evsw)
        sw.add_reactor("CONSENSUS", con_r)
        from tendermint_tpu.p2p.node_info import NodeInfo, default_version

        sw.set_node_info(
            NodeInfo(
                pub_key=sw.node_priv_key.pub_key(),
                moniker=f"node{i}",
                network=TEST_CHAIN_ID,
                version=default_version("test"),
            )
        )
        return sw

    node_a.subscribe_blocks()
    node_b.subscribe_blocks()
    from tendermint_tpu.p2p import Switch, connect2_switches

    switches = [init(i, Switch()) for i in range(2)]
    for sw in switches:
        sw.start()
    try:
        # A builds a head start alone — and KEEPS COMMITTING throughout
        assert wait_until(lambda: node_a.store.height() >= 6, timeout=60)
        connect2_switches(switches, 0, 1)
        # Phase 1 — live chain: B must make sustained catch-up progress
        # (the round-4 chaos stall was ZERO progress). A at test cadence
        # commits far faster than any real chain, so convergence isn't
        # asserted here — and no absolute height/deadline either (the
        # round-4 advisor flagged `>= 30 within 60s` as flaky on slow
        # machines): require monotonic progress across two samples.
        h0 = node_b.store.height()
        assert wait_until(
            lambda: node_b.store.height() > h0, timeout=90
        ), f"B made no progress from {h0}, A at {node_a.store.height()}"
        h1 = node_b.store.height()
        assert wait_until(
            lambda: node_b.store.height() > h1, timeout=90
        ), f"B stalled at {h1} after initial progress, A at {node_a.store.height()}"
        # Phase 2 — production pauses (real chains commit ~1/s; catch-up
        # is ~10x that): B must fully converge to A's tip.
        node_a.cs.stop()
        target = node_a.store.height()
        assert wait_until(
            lambda: node_b.store.height() >= target, timeout=120
        ), f"B stalled at {node_b.store.height()}, target {target}"
        got = node_b.store.load_block(3)
        want = node_a.store.load_block(3)
        assert got is not None and got.hash() == want.hash()
    finally:
        stop_net([node_a, node_b], switches)


def test_fast_sync_rides_the_tpu_gateway(monkeypatch):
    """Regression: fast sync with the gateway wired (as node/node.py wires
    it) must actually route commit signatures AND part hashing through the
    batched kernels — the stats counters move, and the synced chain is
    byte-identical to the builder's (blockchain/reactor.go:229-236)."""
    from tendermint_tpu.ops import gateway

    # close/fatal tracer for the intermittent both-peers-drop flake
    # ("stream closed" on both sides, full-suite-only): record who closes
    # streams and why connections die, dump on failure
    import traceback as _tb

    from tendermint_tpu.p2p import conn as _conn
    from tendermint_tpu.p2p import stream as _stream

    trace: list = []
    orig_close = _stream.SocketStream.close
    orig_fatal = _conn.MConnection._fatal

    def traced_close(self):
        trace.append(
            (time.monotonic(), "close", repr(self.sock),
             "".join(_tb.format_stack(limit=8)[:-1])[-600:])
        )
        return orig_close(self)

    def traced_fatal(self, exc):
        trace.append(
            (time.monotonic(), "fatal", f"{type(exc).__name__}: {exc}",
             "".join(_tb.format_stack(limit=8)[:-1])[-600:])
        )
        return orig_fatal(self, exc)

    monkeypatch.setattr(_stream.SocketStream, "close", traced_close)
    monkeypatch.setattr(_conn.MConnection, "_fatal", traced_fatal)

    verifier = gateway.Verifier(min_tpu_batch=1, use_tpu=True)
    hasher = gateway.Hasher(min_tpu_batch=1, use_tpu=True)

    doc, pvs = make_genesis(1)
    node_a = make_node(doc, pvs[0])
    node_b = make_node(doc, None)

    def init(i, sw):
        node = (node_a, node_b)[i]
        fast_sync = i == 1
        con_r = ConsensusReactor(node.cs, fast_sync=fast_sync)
        con_r.set_event_switch(node.evsw)
        sw.add_reactor("CONSENSUS", con_r)
        bc_r = BlockchainReactor(
            node.state.copy(),
            node.cs.proxy_app_conn,
            node.store,
            fast_sync=fast_sync,
            event_cache=None,
            batch_verifier=verifier.commit_batch_verifier() if fast_sync else None,
            async_batch_verifier=verifier.verify_batch_async if fast_sync else None,
            part_hasher=hasher.part_leaf_hashes if fast_sync else None,
            status_update_interval=0.5,
        )
        sw.add_reactor("BLOCKCHAIN", bc_r)
        from tendermint_tpu.p2p.node_info import NodeInfo, default_version

        sw.set_node_info(
            NodeInfo(
                pub_key=sw.node_priv_key.pub_key(),
                moniker=f"node{i}",
                network=TEST_CHAIN_ID,
                version=default_version("test"),
            )
        )
        return sw

    from tendermint_tpu.p2p import Switch, connect2_switches

    switches = [init(i, Switch()) for i in range(2)]
    for sw in switches:
        sw.start()
    try:
        assert wait_until(lambda: node_a.store.height() >= 4, timeout=120)
        node_a.cs.stop()
        target = node_a.store.height()
        connect2_switches(switches, 0, 1)
        if not wait_until(lambda: node_b.store.height() >= target, timeout=120):
            # stall diagnostics: the flake signature is B stuck at 0 under
            # heavy parallel load — record enough to tell "never connected"
            # from "connected but no requests" from "requests but no blocks"
            bc_b = switches[1].reactors.get("BLOCKCHAIN")
            from collections import Counter

            names = Counter(
                t.name.split("-")[0].split(".")[0] for t in threading.enumerate()
            )
            tr = "\n".join(
                f"  t={t:.3f} {kind} {what}\n{stack}" for t, kind, what, stack in trace
            )
            raise AssertionError(
                f"B at {node_b.store.height()}, A at {target}; "
                f"peers A={switches[0].peers.size()} B={switches[1].peers.size()}; "
                f"B pool height={bc_b.pool.height} "
                f"requesters={len(bc_b.pool.requesters)} "
                f"max_peer_height={bc_b.pool.max_peer_height}; "
                f"B synced={bc_b.blocks_synced}; "
                f"threads={threading.active_count()} {dict(names.most_common(8))}\n"
                f"close/fatal trace ({len(trace)} events):\n{tr}"
            )
        for h in range(1, target + 1):
            assert node_b.store.load_block(h).hash() == node_a.store.load_block(h).hash()
        vstats, hstats = verifier.stats(), hasher.stats()
        assert vstats["tpu_sigs"] > 0, vstats  # commit sigs rode the kernel
        assert vstats["tpu_batches"] > 0, vstats
        assert hstats["tpu_part_batches"] > 0, hstats  # part hashing did too
        assert hstats["tpu_leaves"] > 0, hstats
    finally:
        stop_net([node_a, node_b], switches)


# -- mempool reactor ----------------------------------------------------------


def test_mempool_reactor_gossips_txs():
    """Tx checked on one node appears in the other's mempool."""
    doc, _pvs = make_genesis(1)
    n1, n2 = make_node(doc, None, CounterApp()), make_node(doc, None, CounterApp())

    def init(i, sw):
        node = (n1, n2)[i]
        sw.add_reactor("MEMPOOL", MempoolReactor(_test_config().mempool, node.mempool))
        from tendermint_tpu.p2p.node_info import NodeInfo, default_version

        sw.set_node_info(
            NodeInfo(
                pub_key=sw.node_priv_key.pub_key(),
                moniker=f"m{i}",
                network=TEST_CHAIN_ID,
                version=default_version("test"),
            )
        )
        return sw

    switches = make_connected_switches(2, init)
    try:
        tx = (0).to_bytes(8, "big")  # counter app wants ordered u64 txs
        n1.mempool.check_tx(tx)
        assert wait_until(lambda: n2.mempool.size() == 1, timeout=10)
        assert n2.mempool.reap(10) == [tx]
    finally:
        stop_net([n1, n2], switches)


def test_speculative_group_spans_never_overshoot():
    """Grouping must stop BEFORE exceeding group_sig_target so dispatches
    stay in the intended power-of-two kernel bucket (code-review r3)."""
    from tendermint_tpu.blockchain.reactor import group_spans

    # 1000-validator commits, target 4096: groups of 4, never 5
    assert group_spans([1000] * 9, 4096) == [(0, 4), (4, 8), (8, 9)]
    # one commit larger than the target still goes alone
    assert group_spans([5000, 100, 100], 4096) == [(0, 1), (1, 3)]
    # small commits pack tightly up to the boundary
    assert group_spans([1024] * 4, 4096) == [(0, 4)]
    assert group_spans([1025] * 4, 4096) == [(0, 3), (3, 4)]
    assert group_spans([], 4096) == []


def test_fastsync_flag_clears_on_switchover():
    """/metrics fastsync_active must go 0 once the node switches to
    consensus (code-review r3: the constructor flag was never cleared)."""
    from tendermint_tpu.blockchain.reactor import BlockchainReactor

    doc, pvs = make_genesis(1)
    node = make_node(doc, pvs[0])
    bc = BlockchainReactor(
        node.state.copy(), node.cs.proxy_app_conn, node.store, fast_sync=True,
        status_update_interval=0.05,
    )

    class _FakePool:
        def is_running(self):
            return True

        def is_caught_up(self):
            return True

        def stop(self):
            pass

        def peek_blocks(self, n):
            return []

        def peek_two_blocks(self):
            return (None, None)

    class _FakeSwitch:
        def reactor(self, name):
            return None

        def broadcast(self, *a, **k):
            return []

    bc.pool = _FakePool()
    bc.switch = _FakeSwitch()
    bc._started = True  # the routine guards on is_running()
    assert bc.fast_sync is True
    bc._pool_routine()  # caught up immediately -> switchover path
    assert bc.fast_sync is False


def test_vote_gossip_marks_peer_only_on_successful_send():
    """pick_vote_to_send must NOT mark the peer as having the vote —
    the mark lands in _send_vote only AFTER peer.send succeeds
    (reactor.go PickSendVote's order). Marking at pick time meant a
    vote whose send failed on a full channel queue (exactly the
    burst-load moment) was skipped for that peer forever; with no other
    resend mechanism a 2-2 height split could wedge the whole net — the
    netchaos smoke's stall signature."""
    from tendermint_tpu.consensus.reactor import ConsensusReactor, PeerState
    from tendermint_tpu.libs.bitarray import BitArray
    from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT

    class _Vote:
        height, round_, type_, validator_index = 5, 0, VOTE_TYPE_PRECOMMIT, 1

        def to_json(self):
            return {"height": self.height}

    class _VoteSet:
        height, round_, type_ = 5, 0, VOTE_TYPE_PRECOMMIT

        def size(self):
            return 4

        def bit_array(self):
            ba = BitArray(4)
            ba.set_index(1, True)
            return ba

        def get_by_index(self, index):
            assert index == 1
            return _Vote()

    class _Peer:
        def __init__(self, ok):
            self.ok = ok
            self.sent = 0

        def send(self, ch, raw):
            self.sent += 1
            return self.ok

    ps = PeerState(peer=None)
    ps.prs.height, ps.prs.round_ = 5, 0
    ps.ensure_vote_bit_arrays(5, 4)
    vs = _VoteSet()
    picks0 = ps.m_vote_picks.value
    sends0 = ps.m_vote_sends.value
    fails0 = ps.m_vote_send_failures.value

    # pick alone must not mark: the same vote stays pickable
    assert ps.pick_vote_to_send(vs) is not None
    assert ps.pick_vote_to_send(vs) is not None
    assert ps.m_vote_picks.value == picks0  # picking alone never counts

    # failed send: bit stays clear, the vote is retried later — AND the
    # per-peer failure counter moves (round 15: the scrape-visible form
    # of the PR-13 wedge — picks outrunning sends)
    failing = _Peer(ok=False)
    assert not ConsensusReactor._send_vote(None, failing, ps, _Vote())
    assert failing.sent == 1
    assert ps.pick_vote_to_send(vs) is not None, (
        "a failed send must leave the vote pickable"
    )
    assert ps.m_vote_picks.value == picks0 + 1
    assert ps.m_vote_sends.value == sends0
    assert ps.m_vote_send_failures.value == fails0 + 1, (
        "a failed vote send must increment the per-peer failure counter"
    )

    # successful send: marked, never picked again
    assert ConsensusReactor._send_vote(None, _Peer(ok=True), ps, _Vote())
    assert ps.pick_vote_to_send(vs) is None
    assert ps.m_vote_picks.value == picks0 + 2
    assert ps.m_vote_sends.value == sends0 + 1
    assert ps.m_vote_send_failures.value == fails0 + 1


def test_last_commit_gossip_reaches_peer_in_a_later_round():
    """The 2-2 wedge mechanism (netchaos stall): a laggard one height
    behind whose ROUND raced past the commit round (it timed out
    waiting for exactly these votes) had no tracking bit array — the
    last-commit gossip branch silently sent nothing, and with the ahead
    nodes unable to advance (no quorum), the >= +2 stored-commit
    catchup never engaged. The gossip branch now ensures the
    catchup-commit array at the commit's round first."""
    from tendermint_tpu.consensus.reactor import PeerState
    from tendermint_tpu.libs.bitarray import BitArray
    from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT

    class _LastCommit:
        height, round_, type_ = 5, 0, VOTE_TYPE_PRECOMMIT

        def size(self):
            return 4

        def bit_array(self):
            ba = BitArray(4)
            for i in range(3):
                ba.set_index(i, True)
            return ba

        def get_by_index(self, index):
            return ("vote", index)

    ps = PeerState(peer=None)
    ps.prs.height, ps.prs.round_ = 5, 2  # raced past commit round 0
    ps.ensure_vote_bit_arrays(5, 4)     # tracks round 2, not round 0
    catchups0 = ps.m_catchup_commits.value
    # the hole: without a catchup array at round 0, nothing is pickable
    assert ps.pick_vote_to_send(_LastCommit()) is None
    # the fix: the height+1 gossip branch ensures the catchup round —
    # and the engagement is COUNTED per peer (round 15: the catchup
    # signal a fleet scrape alarms on instead of a frozen height vector)
    ps.ensure_catchup_commit_round(5, 0, 4)
    assert ps.m_catchup_commits.value == catchups0 + 1
    # re-ensuring the SAME round is a no-op, not a recount
    ps.ensure_catchup_commit_round(5, 0, 4)
    assert ps.m_catchup_commits.value == catchups0 + 1
    assert ps.pick_vote_to_send(_LastCommit()) is not None
    # and marking via set_has_vote lands in the SAME tracking array
    ps.set_has_vote(5, 0, VOTE_TYPE_PRECOMMIT, 0)
    ps.set_has_vote(5, 0, VOTE_TYPE_PRECOMMIT, 1)
    ps.set_has_vote(5, 0, VOTE_TYPE_PRECOMMIT, 2)
    assert ps.pick_vote_to_send(_LastCommit()) is None
