"""CLI tests (reference: cmd/tendermint/commands/*)."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from tendermint_tpu.cli import main


def test_init_and_show_validator(tmp_path, capsys):
    home = str(tmp_path / "node0")
    assert main(["--home", home, "init", "--chain-id", "cli-chain"]) == 0
    out = capsys.readouterr().out
    assert "Generated private validator" in out
    assert "Generated genesis file" in out
    assert os.path.exists(os.path.join(home, "genesis.json"))
    assert os.path.exists(os.path.join(home, "config.toml"))
    # idempotent
    assert main(["--home", home, "init"]) == 0
    assert "Found private validator" in capsys.readouterr().out

    assert main(["--home", home, "show_validator"]) == 0
    pub = json.loads(capsys.readouterr().out)
    assert pub[0] == 1 and len(pub[1]) == 64  # [type, hex32]


def test_gen_validator_and_version(capsys):
    assert main(["gen_validator"]) == 0
    pv = json.loads(capsys.readouterr().out)
    assert pv["pub_key"][0] == 1
    assert main(["version"]) == 0
    assert capsys.readouterr().out.strip().count(".") == 2


def test_testnet(tmp_path, capsys):
    d = str(tmp_path / "net")
    assert main(["testnet", "--n", "3", "--dir", d, "--chain-id", "net-chain"]) == 0
    docs = []
    for i in range(3):
        with open(os.path.join(d, f"mach{i}", "genesis.json")) as f:
            docs.append(json.load(f))
    assert all(doc["chain_id"] == "net-chain" for doc in docs)
    assert all(len(doc["validators"]) == 3 for doc in docs)
    assert docs[0]["validators"] == docs[1]["validators"] == docs[2]["validators"]


def test_reset_all(tmp_path, capsys):
    home = str(tmp_path / "node1")
    main(["--home", home, "init"])
    data = os.path.join(home, "data")
    os.makedirs(data, exist_ok=True)
    with open(os.path.join(data, "junk"), "w") as f:
        f.write("x")
    assert main(["--home", home, "reset_all"]) == 0
    assert not os.path.exists(os.path.join(data, "junk"))


@pytest.mark.slow
def test_cli_node_subprocess(tmp_path):
    """Boot a real node via the CLI, hit its RPC, shut it down cleanly
    (the reference's test/app/dummy_test.sh shape)."""
    home = str(tmp_path / "noderun")
    subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "init"],
        check=True, capture_output=True,
    )
    # pin an ephemeral-ish rpc port by picking a free one
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ, JAX_PLATFORMS="cpu", TENDERMINT_TPU_DISABLE="1")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "node",
            "--proxy_app", "kvstore",
            "--rpc.laddr", f"tcp://127.0.0.1:{port}",
            "--p2p.laddr", "tcp://127.0.0.1:0",
        ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        deadline = time.time() + 60
        status = None
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status", timeout=2
                ) as resp:
                    status = json.loads(resp.read().decode())
                if status["result"]["latest_block_height"] >= 1:
                    break
            except Exception:
                time.sleep(0.3)
        assert status is not None and status["result"]["latest_block_height"] >= 1
        # commit a tx through the running node
        tx = b"cli-key=cli-val".hex()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/",
            data=json.dumps(
                {"jsonrpc": "2.0", "id": 1, "method": "broadcast_tx_commit",
                 "params": {"tx": tx}}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            res = json.loads(resp.read().decode())
        assert res["result"]["deliver_tx"]["code"] == 0
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(15)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_replay_after_run(tmp_path, capsys):
    """Run a node in-process briefly, then `replay` its WAL."""
    from tendermint_tpu.config import load_config, ensure_root
    from tendermint_tpu.node import default_new_node

    home = str(tmp_path / "replaynode")
    main(["--home", home, "init"])
    capsys.readouterr()
    cfg = load_config(home)
    cfg.base.proxy_app = "kvstore"
    cfg.rpc.laddr = ""
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    # test-speed consensus
    cfg.consensus.timeout_commit = 0.05
    cfg.consensus.skip_timeout_commit = True
    cfg.consensus.timeout_propose = 0.2
    node = default_new_node(cfg)
    node.start()
    deadline = time.time() + 30
    while time.time() < deadline and node.block_store.height() < 3:
        time.sleep(0.05)
    assert node.block_store.height() >= 3
    node.stop()

    from tendermint_tpu.consensus.replay_file import run_replay_file

    replayed = run_replay_file(cfg, console=False)
    assert replayed > 0
    out = capsys.readouterr().out
    assert "replayed" in out
