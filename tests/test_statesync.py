"""State-sync snapshot subsystem tests (tendermint_tpu/statesync/, round
10, docs/state-sync.md).

Tiers:
- fast (tier 1): chunk framing + manifest decode hardening, the snapshot
  store's CRC/damage contracts, producer determinism + interval gating,
  the restore tamper matrix (every verification gate must individually
  refuse), BlockStore seed/prune + the RPC below-base error, and a small
  p2p net where a fresh node restores over the statesync reactor — with
  a corrupting peer banned mid-download and the chunk re-fetched from an
  honest one — then fast-syncs the tail via start_after_statesync.
- slow: the acceptance soak — a fresh node restores a >=1k-block
  signedkv home from a snapshot and ends byte-identical (app hash,
  block-store contents, every subsequent committed height) to a node
  that fast-synced the same chain from genesis.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time

import pytest

from tendermint_tpu.abci.apps.kvstore import KVStoreApp
from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.libs.db import MemDB
from tendermint_tpu.rpc.light import LightClient
from tendermint_tpu.state.state import State
from tendermint_tpu.statesync import (
    Manifest,
    Restorer,
    RestoreError,
    SnapshotError,
    SnapshotProducer,
    SnapshotStore,
)
from tendermint_tpu.statesync.devchain import (
    build_kvstore_chain,
    build_signedkv_chain,
)
from tendermint_tpu.statesync.snapshot import (
    CHUNK_MAGIC,
    chunk_digest,
    chunk_digests_root,
    chunk_payload,
    frame_chunk,
    unframe_chunk,
)


def wait_until(cond, timeout=30.0, tick=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(tick)
    return cond()


def make_light_client(chain, **kw) -> LightClient:
    """A light client anchored at the chain's genesis validator set,
    verifying against the DevChain's RPC stub."""
    return LightClient(
        chain.rpc_stub(), chain.genesis_doc.chain_id,
        chain.state.load_validators(1), trusted_height=0, **kw,
    )


_chain_cache: dict = {}


def snapshot_chain(n_blocks=20, tail=3, chunk_size=4096, builder=build_kvstore_chain):
    """A chain with a snapshot at height `n_blocks` and `tail` more
    blocks after it (the manifest binds to header H+1, so a snapshot is
    only restorable once the chain extends past it). Memoized per arg
    tuple — the many restore-tamper tests only READ the chain/store
    (they tamper payload copies and restore into fresh targets), and
    rebuilding a signed chain per test is the file's dominant cost."""
    key = (n_blocks, tail, chunk_size, builder)
    if key not in _chain_cache:
        chain = builder(n_blocks)
        store = SnapshotStore(tempfile.mkdtemp(prefix="snapstore-"))
        producer = SnapshotProducer(
            store, chain.app, chain.block_store, chunk_size=chunk_size
        )
        height = producer.snapshot(chain.state)
        chain.build(tail)
        _chain_cache[key] = (chain, store, producer, height)
    return _chain_cache[key]


def fresh_restorer(chain, app=None, **kw):
    """A Restorer over fresh app/state/store targets, light-verifying
    against `chain`. Returns (restorer, app, state_db, block_store)."""
    app = app if app is not None else KVStoreApp()
    state_db, block_db = MemDB(), MemDB()
    block_store = BlockStore(block_db)
    r = Restorer(
        chain.genesis_doc, app, state_db, block_store,
        light_client=kw.pop("light_client", make_light_client(chain)), **kw,
    )
    return r, app, state_db, block_store


def load_snapshot(store, height):
    m = store.load_manifest(height)
    assert m is not None
    return m, [store.load_chunk(height, i) for i in range(m.chunks)]


# -- chunk framing ------------------------------------------------------------


class TestChunkFraming:
    def test_round_trip(self):
        for payload in (b"", b"x", b"hello" * 1000):
            assert unframe_chunk(frame_chunk(payload)) == payload

    def test_bit_flip_detected(self):
        buf = bytearray(frame_chunk(b"payload-bytes" * 64))
        buf[len(buf) // 2] ^= 0x40
        with pytest.raises(SnapshotError, match="crc|length"):
            unframe_chunk(bytes(buf))

    def test_truncation_detected(self):
        buf = frame_chunk(b"payload-bytes" * 64)
        for cut in (1, len(buf) // 2, len(buf) - 1):
            with pytest.raises(SnapshotError):
                unframe_chunk(buf[:cut])

    def test_trailing_garbage_detected(self):
        with pytest.raises(SnapshotError, match="length"):
            unframe_chunk(frame_chunk(b"abc") + b"\x00")

    def test_bad_magic_detected(self):
        buf = frame_chunk(b"abc")
        with pytest.raises(SnapshotError, match="magic"):
            unframe_chunk(b"X" + buf[1:])
        assert buf.startswith(CHUNK_MAGIC)

    def test_chunk_payload_split(self):
        payload = bytes(range(256)) * 10
        chunks = chunk_payload(payload, 1000)
        assert b"".join(chunks) == payload
        assert all(len(c) == 1000 for c in chunks[:-1])
        assert chunk_payload(b"", 1024) == [b""]  # well-formed empty
        with pytest.raises(ValueError):
            chunk_payload(b"x", 0)


# -- manifest decode hardening ------------------------------------------------


class TestManifest:
    def _manifest(self, n_chunks=4) -> Manifest:
        digests = [chunk_digest(bytes([i]) * 100) for i in range(n_chunks)]
        return Manifest(
            height=20, chain_id="devchain", chunk_size=100,
            total_bytes=100 * n_chunks, chunk_digests=digests,
            header_hash=b"\x11" * 20, app_hash=b"\x22" * 20,
        )

    def test_json_round_trip(self):
        m = self._manifest()
        m2 = Manifest.from_json(json.loads(json.dumps(m.to_json())))
        assert m2.root == m.root == chunk_digests_root(m.chunk_digests)
        assert m2.chunk_digests == m.chunk_digests
        assert m2.to_json() == m.to_json()

    def test_root_digest_disagreement_rejected(self):
        obj = self._manifest().to_json()
        obj["root"] = chunk_digests_root([b"\x00" * 20]).hex().upper()
        with pytest.raises(ValueError, match="root"):
            Manifest.from_json(obj)

    def test_tampered_digest_rejected(self):
        # flipping one digest breaks the root binding — the lynchpin of
        # the whole per-chunk verification scheme
        obj = self._manifest().to_json()
        obj["chunk_digests"][0] = ("00" * 20).upper()
        with pytest.raises(ValueError, match="root"):
            Manifest.from_json(obj)

    @pytest.mark.parametrize("mutate", [
        lambda o: o.update(height=0),
        lambda o: o.update(chain_id=7),
        lambda o: o.update(chunk_size=0),
        lambda o: o.update(chunk_digests=[]),
        lambda o: o.update(chunk_digests="zz"),
        lambda o: o.update(chunk_digests=["zz"]),
        lambda o: o.update(header_hash="11"),  # not 20 bytes
        lambda o: o.pop("root"),
    ])
    def test_malformed_fields_rejected(self, mutate):
        obj = self._manifest().to_json()
        mutate(obj)
        with pytest.raises((ValueError, KeyError, TypeError)):
            Manifest.from_json(obj)

    def test_lite_is_discovery_subset(self):
        lite = self._manifest().lite()
        assert set(lite) == {
            "format", "height", "chain_id", "chunks", "total_bytes",
            "root", "header_hash", "kind",
        }


# -- the on-disk store --------------------------------------------------------


class TestSnapshotStore:
    def _store_with(self, heights, chunk_size=64) -> SnapshotStore:
        store = SnapshotStore(tempfile.mkdtemp(prefix="snapstore-"))
        for h in heights:
            payload = (b"%06d" % h) * 100
            chunks = chunk_payload(payload, chunk_size)
            m = Manifest(
                height=h, chain_id="t", chunk_size=chunk_size,
                total_bytes=len(payload),
                chunk_digests=[chunk_digest(c) for c in chunks],
                header_hash=b"\x11" * 20, app_hash=b"\x22" * 20,
            )
            store.save(m, chunks)
        return store

    def test_save_load_heights(self):
        store = self._store_with([10, 20, 30])
        assert store.heights() == [10, 20, 30]
        m = store.load_manifest(20)
        chunks = [store.load_chunk(20, i) for i in range(m.chunks)]
        assert b"".join(chunks) == (b"%06d" % 20) * 100
        assert store.load_manifest(15) is None
        assert store.load_chunk(20, m.chunks + 5) is None

    def test_prune_keeps_newest(self):
        store = self._store_with([10, 20, 30, 40])
        assert store.prune(2) == [10, 20]
        assert store.heights() == [30, 40]
        assert store.prune(0) == [30]  # floor of 1 kept

    def test_damaged_chunk_raises_damaged_manifest_none(self):
        store = self._store_with([10])
        d = os.path.join(store.base_dir, "0000000010")
        chunk0 = os.path.join(d, store.chunk_name(0))
        with open(chunk0, "r+b") as f:
            f.seek(len(CHUNK_MAGIC) + 8 + 3)
            b = f.read(1)
            f.seek(-1, 1)
            f.write(bytes([b[0] ^ 0x01]))
        with pytest.raises(SnapshotError):
            store.load_chunk(10, 0)
        with open(os.path.join(d, "manifest.json"), "w") as f:
            f.write("{not json")
        assert store.load_manifest(10) is None
        assert store.load_failures == 1

    def test_half_written_snapshot_not_listed(self):
        store = self._store_with([10])
        # a .tmp assembly dir (crash mid-save) and a dir without a
        # manifest must both be invisible
        os.makedirs(os.path.join(store.base_dir, "0000000099.tmp"))
        os.makedirs(os.path.join(store.base_dir, "0000000098"))
        assert store.heights() == [10]


# -- producer -----------------------------------------------------------------


class TestProducer:
    def test_interval_gating(self):
        chain = build_kvstore_chain(10)
        store = SnapshotStore(tempfile.mkdtemp(prefix="snapstore-"))
        producer = SnapshotProducer(
            store, chain.app, chain.block_store, interval=4, chunk_size=4096
        )
        assert producer.maybe_snapshot(chain.state) is None  # 10 % 4 != 0
        chain.build(2)  # height 12
        assert producer.maybe_snapshot(chain.state) == 12
        assert store.heights() == [12]
        assert producer.stats()["snapshots_taken"] == 1

    def test_retention(self):
        chain = build_kvstore_chain(2)
        store = SnapshotStore(tempfile.mkdtemp(prefix="snapstore-"))
        # full_every=1: all-full snapshots, so retention isn't clamped
        # up to protect a delta chain (that case: test_statesync_delta)
        producer = SnapshotProducer(
            store, chain.app, chain.block_store, interval=2,
            keep_recent=2, chunk_size=4096, full_every=1,
        )
        for _ in range(3):
            assert producer.maybe_snapshot(chain.state) is not None
            chain.build(2)
        assert store.heights() == [4, 6]  # 2 was pruned

    def test_deterministic_across_replicas(self):
        """Two replicas at the same height must serialize byte-identical
        snapshots — the manifest digests (and so the whole p2p protocol)
        depend on it."""
        manifests, chunk_sets = [], []
        for _ in range(2):
            chain = build_kvstore_chain(8)
            store = SnapshotStore(tempfile.mkdtemp(prefix="snapstore-"))
            h = SnapshotProducer(
                store, chain.app, chain.block_store, chunk_size=2048
            ).snapshot(chain.state)
            m, chunks = load_snapshot(store, h)
            manifests.append(m)
            chunk_sets.append(chunks)
        assert manifests[0].root == manifests[1].root
        assert manifests[0].to_json() == manifests[1].to_json()
        assert chunk_sets[0] == chunk_sets[1]

    def test_producer_failure_never_raises(self):
        """maybe_snapshot on a broken producer (app refuses) must count
        the failure and return None — it rides the consensus post-apply
        hook and a raise there would wedge block commit."""
        chain = build_kvstore_chain(4)

        class NoSnapApp:
            def snapshot(self):
                return None

        store = SnapshotStore(tempfile.mkdtemp(prefix="snapstore-"))
        producer = SnapshotProducer(
            store, NoSnapApp(), chain.block_store, interval=4
        )
        assert producer.maybe_snapshot(chain.state) is None
        assert producer.snapshot_failures == 1


# -- restore: the verification gates ------------------------------------------


class TestRestore:
    def test_happy_path_and_reload(self):
        chain, store, _producer, height = snapshot_chain()
        manifest, chunks = load_snapshot(store, height)
        restorer, app, state_db, block_store = fresh_restorer(chain)
        state = restorer.restore(manifest, chunks)

        assert state.last_block_height == height
        assert state.app_hash == manifest.app_hash
        assert app.height == height
        # block store is seeded with the REAL block H
        assert block_store.height() == block_store.base() == height
        meta = block_store.load_block_meta(height)
        assert meta.header.hash() == manifest.header_hash
        src_meta = chain.block_store.load_block_meta(height)
        assert meta.to_json() == src_meta.to_json()
        seen = block_store.load_seen_commit(height)
        assert seen.to_json() == chain.block_store.load_seen_commit(height).to_json()
        # the persisted state reloads and serves validator history at H
        st2 = State.load_state(state_db, chain.genesis_doc)
        assert st2 is not None and st2.equals(state)
        assert st2.load_validators(height).hash() == chain.state.validators.hash()
        assert restorer.stats()["restored_height"] == height
        assert restorer.stats()["chunk_digest_failures"] == 0

    def test_corrupt_chunk_rejected(self):
        chain, store, _p, height = snapshot_chain()
        manifest, chunks = load_snapshot(store, height)
        assert manifest.chunks >= 2, "need a multi-chunk snapshot"
        bad = bytearray(chunks[1])
        bad[0] ^= 0x01
        chunks[1] = bytes(bad)
        restorer, app, _sdb, block_store = fresh_restorer(chain)
        with pytest.raises(RestoreError, match=r"digest mismatch at \[1\]"):
            restorer.restore(manifest, chunks)
        # nothing was applied
        assert app.height == 0 and block_store.height() == 0
        assert restorer.stats()["chunk_digest_failures"] == 1

    def test_wrong_chunk_count_rejected(self):
        chain, store, _p, height = snapshot_chain()
        manifest, chunks = load_snapshot(store, height)
        restorer, *_ = fresh_restorer(chain)
        with pytest.raises(RestoreError, match="chunk"):
            restorer.restore(manifest, chunks[:-1])

    def test_forged_manifest_rejected_at_header_bind(self):
        """A manifest whose root/digests are self-consistent but whose
        header or app hash is forged must die at the light-client bind,
        BEFORE any chunk is even considered."""
        chain, store, _p, height = snapshot_chain()
        manifest, chunks = load_snapshot(store, height)
        for field, value in (("header_hash", b"\xee" * 20),
                             ("app_hash", b"\xee" * 20)):
            obj = manifest.to_json()
            obj[field] = value.hex().upper()
            forged = Manifest.from_json(obj)
            restorer, *_ = fresh_restorer(chain)
            with pytest.raises(RestoreError, match="header|app hash"):
                restorer.verify_manifest(forged)

    def test_unverifiable_height_rejected(self):
        """A manifest claiming a height past the served chain cannot be
        light-verified (header H+1 does not exist)."""
        chain, store, _p, height = snapshot_chain(tail=0)  # nothing past H
        manifest, chunks = load_snapshot(store, height)
        restorer, *_ = fresh_restorer(chain)
        with pytest.raises(RestoreError, match="light verification"):
            restorer.restore(manifest, chunks)

    def test_payload_state_tamper_rejected(self):
        """Re-chunk a payload whose embedded state was tampered: the
        manifest re-roots (attacker-controlled), so only the header
        cross-checks can catch it."""
        chain, store, _p, height = snapshot_chain()
        manifest, chunks = load_snapshot(store, height)
        obj = json.loads(b"".join(chunks))
        obj["state"]["app_hash"] = ("ee" * 20).upper()
        restorer, *_ = fresh_restorer(chain)
        with pytest.raises(RestoreError, match="app hash|state"):
            restorer.restore(*_rechunk(manifest, obj))

    def test_forged_validators_info_rejected(self):
        """A validators_info record carrying a set the verified headers
        never vouched for must be refused — it would become 'historical
        truth' served to RPC clients."""
        from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
        from tendermint_tpu.types.validator import Validator
        from tendermint_tpu.types.validator_set import ValidatorSet

        chain, store, _p, height = snapshot_chain()
        manifest, chunks = load_snapshot(store, height)
        obj = json.loads(b"".join(chunks))
        forged_set = ValidatorSet(
            [Validator.new(gen_priv_key_ed25519().pub_key(), 99)]
        )
        obj["validators_info"][str(height)] = {
            "last_height_changed": height,
            "validator_set": forged_set.to_json(),
        }
        restorer, *_ = fresh_restorer(chain)
        with pytest.raises(RestoreError, match="unverified set"):
            restorer.restore(*_rechunk(manifest, obj))

    def test_tampered_seen_commit_rejected(self):
        # format 2: the seen commit rides the MANIFEST sidecar (outside
        # the digested payload — deterministic roots); it must still be
        # signature-verified against the height-H validator set
        chain, store, _p, height = snapshot_chain()
        manifest, chunks = load_snapshot(store, height)
        mobj = json.loads(json.dumps(manifest.to_json()))
        tag, sig_hex = mobj["seen_commit"]["precommits"][0]["signature"]
        sig = bytearray(bytes.fromhex(sig_hex))
        sig[0] ^= 0x01
        mobj["seen_commit"]["precommits"][0]["signature"] = [tag, sig.hex().upper()]
        tampered = Manifest.from_json(mobj)
        restorer, *_ = fresh_restorer(chain)
        with pytest.raises(RestoreError, match="commit"):
            restorer.restore(tampered, chunks)

    def test_format2_manifest_without_seen_commit_refused(self):
        chain, store, _p, height = snapshot_chain()
        manifest, chunks = load_snapshot(store, height)
        mobj = manifest.to_json()
        mobj.pop("seen_commit")
        stripped = Manifest.from_json(mobj)
        restorer, *_ = fresh_restorer(chain)
        with pytest.raises(RestoreError, match="seen commit"):
            restorer.restore(stripped, chunks)

    def test_total_bytes_mismatch_rejected(self):
        chain, store, _p, height = snapshot_chain()
        manifest, chunks = load_snapshot(store, height)
        obj = manifest.to_json()
        obj["total_bytes"] = manifest.total_bytes + 1
        lying = Manifest.from_json(obj)
        restorer, *_ = fresh_restorer(chain)
        with pytest.raises(RestoreError, match="bytes"):
            # trust path objects to the SIZE claim even when digests match
            restorer._parse_payload(lying, b"".join(chunks))

    def test_used_app_rejected(self):
        chain, store, _p, height = snapshot_chain()
        manifest, chunks = load_snapshot(store, height)
        used = KVStoreApp()
        used.deliver_tx(b"a=b")
        used.commit()
        restorer, *_ = fresh_restorer(chain, app=used)
        with pytest.raises(RestoreError, match="fresh app"):
            restorer.restore(manifest, chunks)

    def test_poisoned_app_state_rejected_before_mutation(self):
        """An app_state whose CLAIMED app_hash matches the verified
        header but whose state map was poisoned must refuse inside the
        app's restore (it recomputes the hash from the map) with nothing
        mutated — the claimed hash alone proves nothing."""
        chain, store, _p, height = snapshot_chain(n_blocks=8, tail=2, chunk_size=2048)
        manifest, chunks = load_snapshot(store, height)
        obj = json.loads(b"".join(chunks))
        app_obj = json.loads(bytes.fromhex(obj["app_state"]))
        app_obj["state"]["poison"] = "ee" * 8
        obj["app_state"] = json.dumps(app_obj, sort_keys=True).encode().hex()
        restorer, app, _sdb, block_store = fresh_restorer(chain)
        with pytest.raises(RestoreError, match="refused"):
            restorer.restore(*_rechunk(manifest, obj))
        assert app.height == 0 and app.state == {}
        assert block_store.height() == 0

    def test_wrong_height_app_state_rejected_before_mutation(self):
        """A self-consistent app_state for the WRONG height must refuse
        before the app mutates (the old path applied first and gated on
        Info afterwards, leaving the app poisoned for later attempts)."""
        chain, store, _p, height = snapshot_chain(n_blocks=8, tail=2, chunk_size=2048)
        manifest, chunks = load_snapshot(store, height)
        obj = json.loads(b"".join(chunks))
        app_obj = json.loads(bytes.fromhex(obj["app_state"]))
        app_obj["height"] = height + 1
        obj["app_state"] = json.dumps(app_obj, sort_keys=True).encode().hex()
        restorer, app, *_ = fresh_restorer(chain)
        with pytest.raises(RestoreError, match="height"):
            restorer.restore(*_rechunk(manifest, obj))
        assert app.height == 0 and app.state == {}
        # the refusal left the app FRESH: the honest snapshot restores
        restorer2, app2, *_ = fresh_restorer(chain, app=app)
        assert restorer2.restore(manifest, chunks).last_block_height == height
        assert app2.height == height

    def test_failed_high_candidate_does_not_poison_lower_snapshots(self):
        """A forged offer above the chain head fails its light walk —
        and must NOT advance the restorer's trust: the honest snapshot
        at a lower height must still verify and restore afterwards (the
        walk rides a clone, adopted only when a manifest binds)."""
        chain, store, _p, height = snapshot_chain(n_blocks=8, tail=2, chunk_size=2048)
        manifest, chunks = load_snapshot(store, height)
        restorer, *_ = fresh_restorer(chain)
        obj = manifest.to_json()
        obj["height"] = height + 100
        forged = Manifest.from_json(obj)
        with pytest.raises(RestoreError, match="light verification"):
            restorer.verify_manifest(forged)
        state = restorer.restore(manifest, chunks)
        assert state.last_block_height == height

    def test_non_dict_app_state_refuses_cleanly(self):
        """app_state whose JSON shape is wrong (non-dict state map /
        non-dict top level) must come back as a RestoreError, not an
        AttributeError crashing the restore driver."""
        chain, store, _p, height = snapshot_chain(n_blocks=8, tail=2, chunk_size=2048)
        manifest, chunks = load_snapshot(store, height)
        base = json.loads(b"".join(chunks))
        app_obj = json.loads(bytes.fromhex(base["app_state"]))
        for poison in ({**app_obj, "state": "oops"}, [1, 2, 3]):
            obj = json.loads(json.dumps(base))
            obj["app_state"] = json.dumps(poison, sort_keys=True).encode().hex()
            restorer, app, *_ = fresh_restorer(chain)
            with pytest.raises(RestoreError, match="refused"):
                restorer.restore(*_rechunk(manifest, obj))
            assert app.height == 0 and app.state == {}

    def test_non_int_state_fields_refuse_cleanly(self):
        """Non-int last_height_validators_changed / block time in the
        embedded state must refuse as RestoreError — max()/time math on
        them used to raise TypeError past the driver's error alphabet."""
        chain, store, _p, height = snapshot_chain(n_blocks=8, tail=2, chunk_size=2048)
        manifest, chunks = load_snapshot(store, height)
        base = json.loads(b"".join(chunks))
        for field, match in (
            ("last_height_validators_changed", "last_height_validators_changed"),
            ("last_block_time", "block time"),
        ):
            obj = json.loads(json.dumps(base))
            obj["state"][field] = "x"
            restorer, app, *_ = fresh_restorer(chain)
            with pytest.raises(RestoreError, match=match):
                restorer.restore(*_rechunk(manifest, obj))
            assert app.height == 0

    def test_interrupted_seed_resumes(self):
        """Crash window: a prior restore persisted the app but died
        before the block store / state seeded. A new attempt with the
        SAME app (already at exactly the verified height/app hash) must
        resume idempotently, not wedge on 'needs a fresh app'."""
        chain, store, _p, height = snapshot_chain(
            n_blocks=8, tail=2, chunk_size=2048
        )
        manifest, chunks = load_snapshot(store, height)
        r1, app, *_ = fresh_restorer(chain)
        r1.restore(manifest, chunks)  # the app half of the crash image
        r2, _app2, state_db, block_store = fresh_restorer(chain, app=app)
        state = r2.restore(manifest, chunks)
        assert state.last_block_height == height
        assert block_store.height() == height
        assert State.load_state(state_db, chain.genesis_doc) is not None
        # resumption is exact-match only: an app at any OTHER height
        # still refuses (test_used_app_rejected covers the mismatch)
        app.height += 1
        r3, *_ = fresh_restorer(chain, app=app)
        try:
            with pytest.raises(RestoreError, match="fresh app"):
                r3.restore(manifest, chunks)
        finally:
            app.height -= 1

    def test_malformed_validators_info_rejected(self):
        """Junk heights, junk pointers, and pointer records that resolve
        to nothing must all refuse before anything applies — a
        non-numeric key used to crash seed_restored AFTER the app and
        block store had already been seeded."""
        chain, store, _p, height = snapshot_chain(n_blocks=8, tail=2, chunk_size=2048)
        manifest, chunks = load_snapshot(store, height)
        base = json.loads(b"".join(chunks))
        cases = [
            ("abc", {"last_height_changed": 1}),
            (str(height + 7), {"last_height_changed": 1}),
            (str(height), {"last_height_changed": "abc"}),
            (str(height), "not-a-dict"),
            # pointer past its own key
            (str(height), {"last_height_changed": height + 1}),
            # pointer-only record pointing at another pointer-only record
            (str(height), {"last_height_changed": height}),
        ]
        for key, rec in cases:
            obj = json.loads(json.dumps(base))
            obj["validators_info"] = {key: rec}
            restorer, app, _sdb, block_store = fresh_restorer(chain)
            with pytest.raises(RestoreError, match="validators_info"):
                restorer.restore(*_rechunk(manifest, obj))
            assert app.height == 0 and block_store.height() == 0, (key, rec)
        # presence too: stripped-empty (or missing H/H+1) validators_info
        # passes every per-record check but must refuse — the restored
        # node's load_validators would raise forever
        for vi in ({}, {str(height): base["validators_info"][str(height)]}):
            obj = json.loads(json.dumps(base))
            obj["validators_info"] = vi
            restorer, app, *_ = fresh_restorer(chain)
            with pytest.raises(RestoreError, match="validators_info"):
                restorer.restore(*_rechunk(manifest, obj))
            assert app.height == 0


def _rechunk(manifest: Manifest, obj: dict):
    """Re-encode a (tampered) payload object into chunks + a manifest
    whose digest plane is CONSISTENT with the bytes — modeling an
    attacker who controls the snapshot but not the header chain."""
    payload = json.dumps(obj, sort_keys=True).encode()
    chunks = chunk_payload(payload, manifest.chunk_size)
    m = Manifest(
        height=manifest.height, chain_id=manifest.chain_id,
        chunk_size=manifest.chunk_size, total_bytes=len(payload),
        chunk_digests=[chunk_digest(c) for c in chunks],
        header_hash=manifest.header_hash, app_hash=manifest.app_hash,
        format_=manifest.format, kind=manifest.kind,
        base_height=manifest.base_height, seen_commit=manifest.seen_commit,
    )
    return m, chunks


# -- BlockStore base/prune + RPC below-base errors ----------------------------


class TestBlockStorePrune:
    def test_prune_to_moves_base_and_deletes(self):
        chain = build_kvstore_chain(10)
        store, db = chain.block_store, chain.block_store_db
        assert store.base() == 1
        assert store.prune_to(6) == 5
        assert store.base() == 6
        assert store.load_block_meta(5) is None
        assert store.load_block(3) is None
        assert store.load_block_meta(6) is not None
        # idempotent + bounded
        assert store.prune_to(6) == 0
        with pytest.raises(ValueError, match="past head"):
            store.prune_to(store.height() + 1)
        # base survives a reopen
        assert BlockStore(db).base() == 6

    def test_save_block_continues_after_seed(self):
        """After seed_snapshot at H, fast sync must be able to append
        H+1 — and a second seed on the now non-empty store must refuse."""
        chain = build_kvstore_chain(5)
        src = chain.block_store
        meta = src.load_block_meta(3)
        parts = [src.load_block_part(3, i)
                 for i in range(meta.block_id.parts_header.total)]
        seen = src.load_seen_commit(3)

        store = BlockStore(MemDB())
        store.seed_snapshot(meta, parts, seen)
        assert (store.base(), store.height()) == (3, 3)
        blk4 = src.load_block(4)
        ps = blk4.make_part_set(
            chain.state.params().block_gossip.block_part_size_bytes
        )
        store.save_block(blk4, ps, src.load_seen_commit(4))
        assert (store.base(), store.height()) == (3, 4)
        with pytest.raises(ValueError, match="non-empty"):
            store.seed_snapshot(meta, parts, seen)

    def test_rpc_below_base_is_clear_error(self):
        from tendermint_tpu.rpc.core.handlers import (
            RPCError,
            block as rpc_block,
            blockchain_info,
            commit as rpc_commit,
        )

        chain = build_kvstore_chain(8)
        chain.block_store.prune_to(5)

        class _Ctx:
            block_store = chain.block_store

        with pytest.raises(RPCError, match="below the store's base"):
            rpc_block(_Ctx(), 3)
        with pytest.raises(RPCError, match="below the store's base"):
            rpc_commit(_Ctx(), 4)
        # in-range queries still serve
        assert rpc_block(_Ctx(), 6)["block"] is not None
        assert rpc_commit(_Ctx(), 6)["header"]["height"] == 6
        # blockchain_info clamps its default window to the base
        info = blockchain_info(_Ctx())
        got = {m["header"]["height"] for m in info["block_metas"]}
        assert min(got) == 5 and max(got) == 8


# -- p2p reactor: serve, restore, ban, hand off -------------------------------
#
# The real Switch rides the encrypted transport (p2p/secret_connection),
# whose `cryptography` dependency is absent on this image — the loopback
# fabric below exercises the REAL reactors (statesync + blockchain, their
# actual receive/serve/ban/handoff logic) over queue-per-node delivery
# threads, stubbing only the wire. The reactors use exactly the Switch
# surface the fabric provides: broadcast, peers.get/list/size,
# stop_peer_for_error, reactor(name), peer.try_send/id.


class _LoopbackPeer:
    def __init__(self, owner: "_LoopbackSwitch", remote: str):
        self._owner = owner
        self._remote = remote
        self.outbound = True

    def id(self) -> str:
        return self._remote

    def try_send(self, ch_id: int, msg: bytes) -> bool:
        remote = self._owner.net.nodes.get(self._remote)
        if remote is None:
            return False
        remote.enqueue(ch_id, self._owner.name, bytes(msg))
        return True


class _PeerSet:
    def __init__(self):
        self._peers: dict = {}

    def get(self, pid):
        return self._peers.get(pid)

    def list(self):
        return list(self._peers.values())

    def size(self) -> int:
        return len(self._peers)


class _LoopbackSwitch:
    def __init__(self, net: "_LoopbackNet", name: str):
        self.net = net
        self.name = name
        self.peers = _PeerSet()
        self._reactors: dict = {}
        self._by_channel: dict = {}
        import queue

        self._q: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._deliver_loop, daemon=True, name=f"loopback-{name}"
        )

    def add_reactor(self, name: str, reactor) -> None:
        reactor.set_switch(self)
        self._reactors[name] = reactor
        for ch in reactor.get_channels():
            self._by_channel[ch.id] = reactor

    def reactor(self, name: str):
        return self._reactors.get(name)

    def start(self) -> None:
        self._thread.start()
        for r in self._reactors.values():
            r.start()

    def stop(self) -> None:
        self._q.put(None)
        for r in self._reactors.values():
            r.stop()

    def enqueue(self, ch_id: int, src: str, msg: bytes) -> None:
        self._q.put((ch_id, src, msg))

    def _deliver_loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            ch_id, src, msg = item
            peer = self.peers.get(src)
            reactor = self._by_channel.get(ch_id)
            if peer is not None and reactor is not None:
                reactor.receive(ch_id, peer, msg)

    def broadcast(self, ch_id: int, msg: bytes) -> None:
        for peer in self.peers.list():
            peer.try_send(ch_id, msg)

    def stop_peer_for_error(self, peer, reason) -> None:
        self.net.disconnect(self.name, peer.id())

    def _attach(self, remote: str) -> None:
        peer = _LoopbackPeer(self, remote)
        self.peers._peers[remote] = peer
        for r in self._reactors.values():
            r.add_peer(peer)

    def _drop(self, remote: str, reason) -> None:
        peer = self.peers._peers.pop(remote, None)
        if peer is not None:
            for r in self._reactors.values():
                r.remove_peer(peer, reason)


class _LoopbackNet:
    def __init__(self):
        self.nodes: dict = {}

    def add_node(self, name: str) -> _LoopbackSwitch:
        sw = _LoopbackSwitch(self, name)
        self.nodes[name] = sw
        return sw

    def connect(self, a: str, b: str) -> None:
        self.nodes[a]._attach(b)
        self.nodes[b]._attach(a)

    def disconnect(self, a: str, b: str) -> None:
        self.nodes[a]._drop(b, "error")
        if b in self.nodes:
            self.nodes[b]._drop(a, "error")

    def stop(self) -> None:
        for sw in self.nodes.values():
            sw.stop()


def _make_corrupting_reactor_cls():
    from tendermint_tpu.statesync.reactor import STATESYNC_CHANNEL, StateSyncReactor

    class CorruptingReactor(StateSyncReactor):
        """Serves the manifest honestly but every chunk corrupted —
        the digest-mismatch → ban → refetch path's antagonist."""

        def _serve_chunk(self, peer, height, index):
            chunk = self.store.load_chunk(height, index)
            if chunk is None:
                return super()._serve_chunk(peer, height, index)
            evil_bytes = bytes([chunk[0] ^ 0x01]) + chunk[1:]
            peer.try_send(
                STATESYNC_CHANNEL,
                json.dumps({
                    "type": "chunk_response", "height": height,
                    "index": index, "chunk": evil_bytes.hex().upper(),
                }, sort_keys=True).encode(),
            )

    return CorruptingReactor


def _make_forging_reactor_cls():
    from tendermint_tpu.statesync.reactor import STATESYNC_CHANNEL, StateSyncReactor

    class ForgingReactor(StateSyncReactor):
        """Serves manifests whose digest plane is self-consistent but
        whose header_hash is forged — the manifest-binding antagonist."""

        def _serve_manifest(self, peer, height):
            m = self.store.load_manifest(height)
            if m is None:
                return super()._serve_manifest(peer, height)
            obj = m.to_json()
            obj["header_hash"] = ("ee" * 20).upper()
            peer.try_send(
                STATESYNC_CHANNEL,
                json.dumps(
                    {"type": "manifest_response", "manifest": obj},
                    sort_keys=True,
                ).encode(),
            )

    return ForgingReactor


def _add_server_node(net, name, chain, snap_store, reactor_cls=None):
    from tendermint_tpu.blockchain.reactor import BlockchainReactor
    from tendermint_tpu.statesync.reactor import StateSyncReactor

    sw = net.add_node(name)
    sw.add_reactor("STATESYNC", (reactor_cls or StateSyncReactor)(snap_store))
    sw.add_reactor("BLOCKCHAIN", BlockchainReactor(
        chain.state.copy(), chain._proxy, chain.block_store,
        fast_sync=False, event_cache=None, status_update_interval=0.5,
    ))
    return sw


def _add_joiner_node(net, name, chain, app=None, **reactor_kw):
    """A fresh node: statesync enabled, blockchain reactor deferred for
    the restore handoff. Returns (switch, dict of its moving parts)."""
    import threading as _threading

    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.blockchain.reactor import BlockchainReactor
    from tendermint_tpu.proxy.app_conn import AppConnConsensus
    from tendermint_tpu.statesync.reactor import StateSyncReactor

    app = app if app is not None else KVStoreApp()
    state_db, block_db = MemDB(), MemDB()
    block_store = BlockStore(block_db)
    state = State.get_state(state_db, chain.genesis_doc)
    proxy = AppConnConsensus(LocalClient(app, _threading.RLock()))
    bc_r = BlockchainReactor(
        state.copy(), proxy, block_store, fast_sync=True, event_cache=None,
        status_update_interval=0.5, defer_for_statesync=True,
    )
    restorer = Restorer(
        chain.genesis_doc, app, state_db, block_store,
        light_client=make_light_client(chain),
    )
    done: list = []

    def on_complete(restored_state):
        done.append(restored_state)
        bc_r.start_after_statesync(restored_state)

    reactor_kw.setdefault("chunk_window", 4)
    reactor_kw.setdefault("chunk_timeout_s", 5.0)
    reactor_kw.setdefault("discovery_s", 0.2)
    reactor_kw.setdefault("fallback_s", 30.0)
    ss_r = StateSyncReactor(
        SnapshotStore(tempfile.mkdtemp(prefix=f"{name}-snap-")),
        restorer=restorer, enabled=True, on_complete=on_complete, **reactor_kw,
    )
    sw = net.add_node(name)
    sw.add_reactor("STATESYNC", ss_r)
    sw.add_reactor("BLOCKCHAIN", bc_r)
    return sw, {
        "app": app, "block_store": block_store, "reactor": ss_r,
        "bc_reactor": bc_r, "done": done, "state_db": state_db,
    }


def _statesync_net(chain, snap_store, evil=False):
    """Loopback net: serving peer(s) with `chain`'s snapshot + block
    stores, and a joining node. Returns (net, joiner_dict)."""
    net = _LoopbackNet()
    if evil:
        _add_server_node(
            net, "evil", chain, snap_store,
            reactor_cls=_make_corrupting_reactor_cls(),
        )
    _add_server_node(net, "honest", chain, snap_store)
    _joiner_sw, joiner = _add_joiner_node(net, "joiner", chain)
    for sw in net.nodes.values():
        sw.start()
    for server in [n for n in net.nodes if n != "joiner"]:
        net.connect(server, "joiner")
    return net, joiner


class TestStateSyncReactor:
    def test_restore_over_p2p_then_fast_sync_tail(self):
        chain, snap_store, _p, height = snapshot_chain(
            n_blocks=12, tail=4, chunk_size=2048
        )
        target = chain.block_store.height()
        net, joiner = _statesync_net(chain, snap_store)
        try:
            assert wait_until(lambda: joiner["done"], timeout=30), (
                joiner["reactor"].stats()
            )
            assert joiner["done"][0] is not None, "restore fell back"
            assert joiner["done"][0].last_block_height == height
            # the fast-sync handoff pulls the tail; block `target` itself
            # needs a successor commit to verify, so fast sync (with no
            # consensus layer in this net) converges at target - 1
            synced_to = target - 1
            assert wait_until(
                lambda: joiner["block_store"].height() >= synced_to, timeout=30
            ), f"tail sync stalled at {joiner['block_store'].height()}"
            assert joiner["block_store"].base() == height
            # app hash after synced_to is committed in header(target)
            want_app_hash = chain.block_store.load_block_meta(
                target
            ).header.app_hash
            assert joiner["app"].app_hash == want_app_hash
            got = joiner["block_store"].load_block(synced_to)
            want = chain.block_store.load_block(synced_to)
            assert got is not None and got.hash() == want.hash()
            stats = joiner["reactor"].stats()
            assert stats["chunks_fetched"] >= 2
            assert stats["peers_banned"] == 0
            # scratch dir cleaned after a completed restore
            assert not os.path.isdir(
                joiner["reactor"]._scratch_dir(height)
            )
        finally:
            net.stop()

    def test_corrupt_chunk_bans_peer_and_refetches(self):
        """A peer serving digest-mismatching chunks is penalized
        (stop_peer_for_error) and every chunk re-fetches from the honest
        peer — the restore still completes."""
        chain, snap_store, _p, height = snapshot_chain(
            n_blocks=12, tail=2, chunk_size=1024
        )
        assert snap_store.load_manifest(height).chunks >= 4
        net, joiner = _statesync_net(chain, snap_store, evil=True)
        try:
            assert wait_until(lambda: joiner["done"], timeout=45), (
                joiner["reactor"].stats()
            )
            assert joiner["done"][0] is not None, "restore fell back"
            stats = joiner["reactor"].stats()
            assert stats["peers_banned"] >= 1, stats
            assert stats["chunk_failures"] >= 1, stats
            assert joiner["app"].height == height
            # the banned peer is disconnected from the joiner's switch
            assert wait_until(
                lambda: net.nodes["joiner"].peers.get("evil") is None,
                timeout=10,
            )
            # ...and the restored bytes all came digest-verified
            assert stats["chunks_fetched"] >= snap_store.load_manifest(
                height
            ).chunks
        finally:
            net.stop()

    def test_reactor_rejects_garbage_messages(self):
        """Every decode violation is a peer error — never an exception
        out of receive()."""
        from tendermint_tpu.statesync.reactor import StateSyncReactor

        banned = []

        class _Switch:
            peers = None

            def stop_peer_for_error(self, peer, err):
                banned.append((peer, err))

        class _Peer:
            def id(self):
                return "p1"

            def try_send(self, ch, msg):
                return True

        r = StateSyncReactor(SnapshotStore(tempfile.mkdtemp()))
        r.switch = _Switch()
        for msg in (
            b"\xff\xfe",  # not utf-8
            b"not json",
            b"[]",
            b'{"type": "warp"}',
            b'{"type": "chunk_response", "height": -1, "index": 0, "chunk": ""}',
            b'{"type": "manifest_response", "manifest": {"format": 1}}',
            b'{"type": "snapshots_response", "snapshots": 3}',
        ):
            r.receive(0x60, _Peer(), msg)
        assert len(banned) == 7

    def test_forged_manifest_bans_serving_peer(self):
        """A peer serving a manifest that contradicts the light-verified
        chain is banned (the forgery PROVES it lied); with no honest
        offerer left the joiner falls back to fast sync rather than
        wedging — the height is never poisoned by the forger."""
        chain, snap_store, _p, height = snapshot_chain(
            n_blocks=8, tail=2, chunk_size=2048
        )
        net = _LoopbackNet()
        _add_server_node(
            net, "forger", chain, snap_store,
            reactor_cls=_make_forging_reactor_cls(),
        )
        _joiner_sw, joiner = _add_joiner_node(
            net, "joiner", chain, fallback_s=1.2, chunk_timeout_s=2.0,
        )
        for sw in net.nodes.values():
            sw.start()
        net.connect("forger", "joiner")
        try:
            assert wait_until(lambda: joiner["done"], timeout=30), (
                joiner["reactor"].stats()
            )
            assert joiner["done"][0] is None, "forged manifest was accepted"
            assert joiner["reactor"].stats()["peers_banned"] >= 1
            assert net.nodes["joiner"].peers.get("forger") is None
            assert joiner["app"].height == 0
        finally:
            net.stop()

    def test_unsolicited_manifest_ignored(self):
        """A WELL-FORMED manifest_response from a peer we never asked
        must not enter the manifest inbox — a malicious peer could
        otherwise race a forged manifest in and poison the restore of a
        height an honest peer offered. It is not a peer error either
        (it may be a late reply to a prior request)."""
        from tendermint_tpu.statesync.reactor import StateSyncReactor

        _chain, store, _p, height = snapshot_chain()
        manifest = store.load_manifest(height)
        banned = []

        class _Switch:
            peers = None

            def stop_peer_for_error(self, peer, err):
                banned.append(peer)

        class _Peer:
            def __init__(self, pid):
                self._pid = pid

            def id(self):
                return self._pid

            def try_send(self, ch, msg):
                return True

        r = StateSyncReactor(SnapshotStore(tempfile.mkdtemp()))
        r.switch = _Switch()
        msg = json.dumps(
            {"type": "manifest_response", "manifest": manifest.to_json()}
        ).encode()
        # nothing awaited: ignored
        r.receive(0x60, _Peer("stranger"), msg)
        assert r._manifest_inbox == {}
        # awaiting another peer: still ignored
        r._manifest_expect = (height, "friend")
        r.receive(0x60, _Peer("stranger"), msg)
        assert r._manifest_inbox == {}
        # the peer actually asked: delivered
        r.receive(0x60, _Peer("friend"), msg)
        assert r._manifest_inbox[height].root == manifest.root
        assert banned == []

    def test_phantom_high_offer_does_not_starve_restore(self):
        """A peer offering a phantom max-height (and then never serving
        its manifest) must not starve the honest snapshot: after a
        bounded number of transient failures the phantom height is
        dropped and the real one restores — the picker always takes the
        highest offer, so an unbounded retry would burn the whole
        fallback window on the forgery."""
        from tendermint_tpu.statesync.reactor import (
            STATESYNC_CHANNEL,
            StateSyncReactor,
        )

        chain, snap_store, _p, height = snapshot_chain(
            n_blocks=8, tail=2, chunk_size=2048
        )

        class PhantomReactor(StateSyncReactor):
            def _serve_snapshots(self, peer):
                super()._serve_snapshots(peer)
                peer.try_send(STATESYNC_CHANNEL, json.dumps({
                    "type": "snapshots_response",
                    "snapshots": [{"height": 999999}],
                }, sort_keys=True).encode())

            def _serve_manifest(self, peer, h):
                if h == 999999:
                    return  # silence: the joiner must time out
                super()._serve_manifest(peer, h)

        net = _LoopbackNet()
        _add_server_node(
            net, "phantom", chain, snap_store, reactor_cls=PhantomReactor
        )
        _joiner_sw, joiner = _add_joiner_node(
            net, "joiner", chain, chunk_timeout_s=0.4, fallback_s=20.0,
        )
        for sw in net.nodes.values():
            sw.start()
        net.connect("phantom", "joiner")
        try:
            assert wait_until(lambda: joiner["done"], timeout=30), (
                joiner["reactor"].stats()
            )
            assert joiner["done"][0] is not None, (
                "restore fell back — starved by the phantom offer"
            )
            assert joiner["done"][0].last_block_height == height
        finally:
            net.stop()

    def test_stop_during_discovery_is_not_fallback(self):
        """A graceful stop mid-discovery must NOT fire the fast-sync
        fallback handoff or delete the resumable scratch dirs — that
        path is for the fallback deadline, not shutdown."""
        from tendermint_tpu.statesync.reactor import StateSyncReactor

        class _Switch:
            def broadcast(self, ch, msg):
                pass

        done: list = []
        store = SnapshotStore(tempfile.mkdtemp())
        scratch = os.path.join(store.base_dir, "restore-0000000005")
        os.makedirs(scratch)
        restorer = Restorer(
            None, KVStoreApp(), MemDB(), BlockStore(MemDB()),
            trust_manifest=True,
        )
        r = StateSyncReactor(
            store, restorer=restorer, enabled=True,
            on_complete=lambda s: done.append(s),
            discovery_s=0.2, fallback_s=30.0,
        )
        r.switch = _Switch()
        r.start()
        time.sleep(0.3)
        r.stop()
        assert wait_until(lambda: not r._thread.is_alive(), timeout=5)
        assert done == [], "stop fired the fallback handoff"
        assert os.path.isdir(scratch), "stop deleted resumable scratch"

    def test_unsolicited_chunks_ignored(self):
        """chunk_response/no_chunk for (height, index) pairs the driver
        is not currently fetching must not be stored — the inbox key
        space is attacker-chosen and each payload is up to 4 MiB, so
        unsolicited entries are a memory-exhaustion vector (serve-only
        nodes never pop them at all)."""
        from tendermint_tpu.statesync.reactor import StateSyncReactor

        class _Switch:
            def stop_peer_for_error(self, peer, err):
                raise AssertionError("unsolicited chunk is not a peer error")

        class _Peer:
            def id(self):
                return "p1"

        r = StateSyncReactor(SnapshotStore(tempfile.mkdtemp()))
        r.switch = _Switch()
        chunk_msg = json.dumps(
            {"type": "chunk_response", "height": 3, "index": 0, "chunk": "AB"}
        ).encode()
        r.receive(0x60, _Peer(), chunk_msg)
        r.receive(0x60, _Peer(), json.dumps(
            {"type": "no_chunk", "height": 3, "index": 1}
        ).encode())
        assert r._chunk_inbox == {}
        # the awaited window IS stored
        r._chunk_expect = {(3, 0)}
        r.receive(0x60, _Peer(), chunk_msg)
        assert r._chunk_inbox == {(3, 0): ("p1", b"\xab")}

    def test_offers_gated_on_restore_and_bounded_per_peer(self):
        """Offers are only collected mid-restore (serve-only nodes would
        accumulate them forever), and one peer can hold at most
        MAX_OFFERED_SNAPSHOTS heights — its lowest dropped first."""
        from tendermint_tpu.statesync.reactor import (
            MAX_OFFERED_SNAPSHOTS,
            StateSyncReactor,
        )

        class _Peer:
            def id(self):
                return "p1"

        r = StateSyncReactor(SnapshotStore(tempfile.mkdtemp()))
        r._note_offers(_Peer(), [{"height": 1}])
        assert r._offers == {}, "offer stored on a non-restoring node"
        r.restore_active = 1
        for h in range(1, 40):
            r._note_offers(_Peer(), [{"height": h}])
        assert len(r._offers) == MAX_OFFERED_SNAPSHOTS
        assert max(r._offers) == 39
        assert min(r._offers) == 40 - MAX_OFFERED_SNAPSHOTS

    def test_discovery_window_prefers_higher_late_offer(self):
        """_pick_snapshot collects offers for the FULL discovery window
        before choosing, so a higher snapshot offered moments after the
        first response wins (the old code returned on the first offer
        and clamped discovery_s to 1 s, making the knob dead)."""
        from tendermint_tpu.statesync.reactor import StateSyncReactor

        class _Switch:
            def broadcast(self, ch, msg):
                pass

        class _Peer:
            def __init__(self, pid):
                self._pid = pid

            def id(self):
                return self._pid

        r = StateSyncReactor(SnapshotStore(tempfile.mkdtemp()), discovery_s=0.5)
        r.switch = _Switch()
        r.start()
        r.restore_active = 1  # offers are only collected mid-restore
        try:
            results: list = []
            t = threading.Thread(
                target=lambda: results.append(
                    r._pick_snapshot(time.monotonic() + 10)
                )
            )
            t.start()
            r._note_offers(_Peer("a"), [{"height": 5}])
            time.sleep(0.2)
            r._note_offers(_Peer("b"), [{"height": 50}])
            t.join(timeout=5)
            assert not t.is_alive() and results == [50]
        finally:
            r.stop()


# -- node wiring: producer hook + RPC surface ---------------------------------


class TestNodeWiring:
    def test_node_produces_and_serves_snapshots(self):
        """A real node with snapshot_interval set produces snapshots on
        the consensus post-apply hook and serves them over the
        `snapshots` RPC route; statesync_* gauges ride /metrics."""
        from tendermint_tpu.config import reset_test_root
        from tendermint_tpu.node import default_new_node
        from tendermint_tpu.rpc.client import HTTPClient

        tmp = tempfile.mkdtemp(prefix="statesync-node-")
        cfg = reset_test_root(tmp)
        cfg.base.proxy_app = "kvstore"
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.statesync.snapshot_interval = 2
        cfg.statesync.snapshot_keep_recent = 2
        n = default_new_node(cfg)
        n.start()
        try:
            assert wait_until(
                lambda: n.snapshot_store.heights(), timeout=60
            ), f"no snapshot by height {n.block_store.height()}"
            client = HTTPClient(f"127.0.0.1:{n.rpc_port()}")
            offers = client.snapshots()["snapshots"]
            assert offers and offers[0]["height"] % 2 == 0
            assert offers[0]["chain_id"] == n.config.base.chain_id
            m = client.metrics()
            for gauge in ("statesync_restore_active", "statesync_snapshots",
                          "statesync_chunks_served", "statesync_peers_banned",
                          "statesync_snapshots_taken",
                          "statesync_last_snapshot_height"):
                assert gauge in m, gauge
            assert m["statesync_restore_active"] == 0
            assert m["statesync_snapshots_taken"] >= 1
            assert m["blockstore_base"] == 1
            # retention holds as the chain grows
            assert len(offers) <= 2
        finally:
            n.stop()


# -- the acceptance soak ------------------------------------------------------


@pytest.mark.slow
class TestStateSyncSoak:
    def test_1k_block_signedkv_restore_matches_fast_sync(self):
        """A fresh node restores a >=1k-block signedkv home from a
        snapshot + fast-syncs the tail; a second fresh node fast-syncs
        the whole chain from genesis. App hash, block-store contents,
        and every post-snapshot committed height must be byte-identical
        across the two — restore is a shortcut, never a fork."""
        import threading as _threading

        from tendermint_tpu.abci.apps.signedkv import SignedKVStoreApp
        from tendermint_tpu.abci.client import LocalClient
        from tendermint_tpu.blockchain.reactor import BlockchainReactor
        from tendermint_tpu.proxy.app_conn import AppConnConsensus

        chain = build_signedkv_chain(1000)
        snap_store = SnapshotStore(tempfile.mkdtemp(prefix="soak-snap-"))
        producer = SnapshotProducer(
            store=snap_store, app=chain.app, block_store=chain.block_store,
            chunk_size=16 * 1024,
        )
        snap_height = producer.snapshot(chain.state)
        assert snap_height == 1000
        chain.build(12)  # the tail both nodes must also commit
        target = chain.block_store.height()

        net = _LoopbackNet()
        _add_server_node(net, "source", chain, snap_store)
        # generous windows: this box's throughput swings >2x under host
        # load, and a transient timeout here burns one of the bounded
        # restore attempts — the soak proves byte-identity, not latency
        _sw_b, restored = _add_joiner_node(
            net, "restored", chain, app=SignedKVStoreApp(),
            chunk_window=8, chunk_timeout_s=20.0, fallback_s=180.0,
        )

        # the fast-sync-from-genesis comparison node
        app_c = SignedKVStoreApp()
        state_db_c, block_db_c = MemDB(), MemDB()
        store_c = BlockStore(block_db_c)
        state_c = State.get_state(state_db_c, chain.genesis_doc)
        proxy_c = AppConnConsensus(LocalClient(app_c, _threading.RLock()))
        sw_c = net.add_node("replayed")
        sw_c.add_reactor("BLOCKCHAIN", BlockchainReactor(
            state_c.copy(), proxy_c, store_c, fast_sync=True,
            event_cache=None, status_update_interval=0.5,
        ))
        replayed = {"app": app_c, "block_store": store_c,
                    "state_db": state_db_c}

        for sw in net.nodes.values():
            sw.start()
        net.connect("source", "restored")
        net.connect("source", "replayed")
        try:
            assert wait_until(lambda: restored["done"], timeout=200)
            assert restored["done"][0] is not None, "restore fell back"
            assert restored["done"][0].last_block_height == snap_height
            # block `target` needs a successor commit to verify, so fast
            # sync (with no consensus layer in this net) ends at target-1
            synced_to = target - 1
            assert wait_until(
                lambda: restored["block_store"].height() >= synced_to
                and replayed["block_store"].height() >= synced_to,
                timeout=240,
            ), (restored["block_store"].height(), replayed["block_store"].height())

            # -- byte-identity: app state --------------------------------
            assert restored["app"].app_hash == replayed["app"].app_hash
            # the app hash after synced_to is committed in header(target)
            assert restored["app"].app_hash == chain.block_store.load_block_meta(
                target
            ).header.app_hash
            assert restored["app"].snapshot() == replayed["app"].snapshot()

            # -- byte-identity: block-store contents over the shared
            # range (the restored store legitimately starts at base) ----
            assert restored["block_store"].base() == snap_height
            assert replayed["block_store"].base() == 1
            for h in range(snap_height, synced_to + 1):
                got = restored["block_store"].load_block_meta(h)
                want = replayed["block_store"].load_block_meta(h)
                assert got.to_json() == want.to_json(), f"meta diverges at {h}"
            # every subsequent committed height carries identical blocks
            for h in range(snap_height + 1, synced_to + 1):
                got_b = restored["block_store"].load_block(h)
                want_b = replayed["block_store"].load_block(h)
                assert got_b.hash() == want_b.hash(), f"block diverges at {h}"
                src_b = chain.block_store.load_block(h)
                assert got_b.hash() == src_b.hash()

            # -- the persisted states agree ------------------------------
            st_restored = State.load_state(
                restored["state_db"], chain.genesis_doc
            )
            st_replayed = State.load_state(
                replayed["state_db"], chain.genesis_doc
            )
            assert st_restored is not None and st_replayed is not None
            assert st_restored.equals(st_replayed)
            # validator history resolves at and after the snapshot height
            assert st_restored.load_validators(snap_height).hash() == \
                st_replayed.load_validators(snap_height).hash()
        finally:
            net.stop()
