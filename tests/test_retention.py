"""Bounded-retention lifecycle (round 19, docs/state-sync.md § Retention).

Covers the retention coordinator's safe-retain-height formula, the
block store's crash-safe prune (watermark-first + clean_base resume,
held with a REAL SIGKILL mid-delete in a subprocess), WAL chunk
retention, prune-vs-concurrent-reader races (RPC block reads and the
statesync producer racing an in-flight prune_to see base-consistent
results, never partial deletes), the RPC range clamping on pruned
stores, the fast-sync pool's below-base peer ineligibility, and the
below-horizon statesync fallback trigger.

The live multi-node tiers — the retention soak (disk bounded by
retention, wiped node re-joins via snapshot), the adversarial statesync
offerer matrix, and the laggard-below-horizon auto-switch — live in
tests/test_netchaos.py (slow-marked) and benches/bench_retention.py
(`make retention-smoke`, tier 1).
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

import pytest

from tendermint_tpu.blockchain.pool import BlockPool
from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.config.config import PruningConfig
from tendermint_tpu.libs.db import FileDB, MemDB
from tendermint_tpu.node.retention import (
    MIN_RETAIN_BLOCKS,
    RetentionCoordinator,
)
from tendermint_tpu.statesync.devchain import build_kvstore_chain

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- safe-retain-height formula ----------------------------------------------


class _FakeSnapStore:
    def __init__(self, heights):
        self._heights = list(heights)

    def heights(self):
        return sorted(self._heights)


class _FakeEvPool:
    def __init__(self, min_h):
        self._min = min_h

    def min_pending_height(self):
        return self._min


class _FakeTree:
    def __init__(self, versions):
        self._versions = list(versions)

    def versions(self):
        return sorted(self._versions)


class _FakeTreeApp:
    def __init__(self, versions):
        self.tree = _FakeTree(versions)


def _coord(retain=20, interval=5, **kw):
    cfg = PruningConfig(retain_blocks=retain, interval_heights=interval)
    return RetentionCoordinator(cfg, BlockStore(MemDB()), **kw)


class TestSafeRetainHeight:
    def test_operator_target_alone(self):
        c = _coord(retain=20)
        safe, floors = c.safe_retain_height(100)
        assert safe == 81 and floors == {"operator": 81}

    def test_never_below_one(self):
        safe, _ = _coord(retain=50).safe_retain_height(10)
        assert safe == 1

    def test_snapshot_floor_wins(self):
        c = _coord(retain=5, snapshot_store=_FakeSnapStore([60, 80]))
        safe, floors = c.safe_retain_height(100)
        # operator target 96, oldest published snapshot 60: the producer
        # must stay serviceable, so 60 wins
        assert safe == 60 and floors["snapshots"] == 60

    def test_evidence_floor_wins(self):
        c = _coord(retain=5, evidence_pool=_FakeEvPool(42))
        safe, floors = c.safe_retain_height(100)
        assert safe == 42 and floors["evidence"] == 42

    def test_statetree_floor_wins(self):
        c = _coord(retain=5, tree_app=_FakeTreeApp([70, 71, 72]))
        safe, floors = c.safe_retain_height(100)
        assert safe == 70 and floors["statetree"] == 70

    def test_min_of_all_planes(self):
        c = _coord(
            retain=10,
            snapshot_store=_FakeSnapStore([85]),
            evidence_pool=_FakeEvPool(88),
            tree_app=_FakeTreeApp([80, 90]),
        )
        safe, floors = c.safe_retain_height(100)
        assert floors == {
            "operator": 91, "snapshots": 85, "evidence": 88, "statetree": 80,
        }
        assert safe == 80

    def test_absent_planes_do_not_constrain(self):
        c = _coord(
            retain=10,
            snapshot_store=_FakeSnapStore([]),
            evidence_pool=_FakeEvPool(None),
            tree_app=_FakeTreeApp([]),
        )
        safe, floors = c.safe_retain_height(100)
        assert safe == 91 and set(floors) == {"operator"}

    def test_retain_clamped_to_min(self):
        c = _coord(retain=1)
        assert c.retain_blocks == MIN_RETAIN_BLOCKS

    def test_disabled_coordinator_is_inert(self):
        cfg = PruningConfig()  # retain_blocks=0 -> off
        chain = build_kvstore_chain(6)

        class _S:
            last_block_height = 6

        c = RetentionCoordinator(cfg, chain.block_store)
        assert c.maybe_prune(_S()) is None
        assert chain.block_store.base() == 1

    def test_maybe_prune_interval_and_never_raises(self):
        chain = build_kvstore_chain(20)
        cfg = PruningConfig(retain_blocks=5, interval_heights=10)
        c = RetentionCoordinator(cfg, chain.block_store)

        class _S:
            last_block_height = 7

        assert c.maybe_prune(_S()) is None  # off-interval: no pass
        _S.last_block_height = 20
        assert c.maybe_prune(_S()) == 15  # 1..15 pruned, 16..20 kept
        assert chain.block_store.base() == 16
        assert chain.block_store.height() == 20
        # a failing plane must not raise out of the hook (executor tail)
        c.block_store = None  # everything below explodes
        assert c.maybe_prune(_S()) is None
        assert c.prune_failures == 1

    def test_prune_pass_is_bounded_by_max_per_pass(self):
        """Enabling pruning on a deep archive drains the backlog across
        passes (max_per_pass heights each) instead of one unbounded
        delete inside the post-apply hook — which runs INLINE in
        consensus commit under the serial finalize."""
        chain = build_kvstore_chain(30)
        cfg = PruningConfig(retain_blocks=5, interval_heights=1)
        c = RetentionCoordinator(cfg, chain.block_store)
        c.max_per_pass = 8

        class _S:
            last_block_height = 30

        assert c.maybe_prune(_S()) == 8  # base 1 -> 9
        assert chain.block_store.base() == 9
        assert c.maybe_prune(_S()) == 8  # -> 17
        assert c.maybe_prune(_S()) == 8  # -> 25
        assert c.maybe_prune(_S()) == 1  # -> the operator target, 26
        assert chain.block_store.base() == 26
        assert c.maybe_prune(_S()) == 0  # caught up

    def test_stats_shape_numeric(self):
        c = _coord(retain=7, snapshot_store=_FakeSnapStore([3]))
        c.prune(head=0)
        s = c.stats()
        for k, v in s.items():
            assert isinstance(v, (int, float)), (k, v)
        for k in ("enabled", "retain_blocks", "runs", "pruned_heights",
                  "wal_chunks_pruned", "last_retain_height",
                  "floor_operator", "floor_snapshots", "disk_total_bytes"):
            assert k in s


# -- block store: crash-safe prune --------------------------------------------


class TestStorePruneCrashSafety:
    def test_prune_basic_and_counters(self):
        chain = build_kvstore_chain(12)
        store = chain.block_store
        assert store.prune_to(8) == 7
        assert (store.base(), store.height()) == (8, 12)
        assert store.pruned_heights == 7 and store.prune_runs == 1
        assert store.load_block(7) is None
        assert store.load_block_meta(3) is None
        assert store.load_block(8) is not None
        # idempotent / below-base no-ops
        assert store.prune_to(8) == 0
        with pytest.raises(ValueError, match="past head"):
            store.prune_to(99)

    def test_interrupted_prune_resumes_on_open(self):
        """Crash AFTER the watermark flush but MID-delete: the reopened
        store sees base=retain, clean_base=old — and finishes the
        deletes itself (no leftover keys below base, ever)."""
        chain = build_kvstore_chain(10)
        db = chain.block_store_db
        store = chain.block_store

        real_delete = db.delete
        calls = {"n": 0}

        class _Boom(RuntimeError):
            pass

        def hooked(key):
            real_delete(key)
            calls["n"] += 1
            if calls["n"] >= 3:
                raise _Boom("simulated crash mid-prune")

        db.delete = hooked
        with pytest.raises(_Boom):
            store.prune_to(6)
        db.delete = real_delete

        # readers on the crashed-in-memory store already see base 6
        assert store.base() == 6
        # a fresh open resumes the delete and marks clean
        store2 = BlockStore(db)
        assert (store2.base(), store2.height()) == (6, 10)
        wm = json.loads(db.get(b"blockStore"))
        assert wm["clean_base"] == 6
        leftovers = [
            k for k, _v in db.iterate_prefix(b"H:")
            if int(k.split(b":")[1]) < 6
        ]
        assert leftovers == []
        assert store2.load_block(6) is not None

    def test_sigkill_mid_prune_subprocess(self, tmp_path):
        """The real crash model: a subprocess SIGKILLs itself mid-delete
        (after the watermark flushed). The reopened store's base is the
        new retain height and the open-time resume clears every leftover
        key below it — the store.py watermark-first claim, held with an
        actual kill."""
        db_path = str(tmp_path / "blockstore.db")
        db = FileDB(db_path)
        build_kvstore_chain(10, block_store_db=db)
        db.close()

        child = f"""
import os, signal, sys
sys.path.insert(0, {REPO_ROOT!r})
from tendermint_tpu.libs.db import FileDB
from tendermint_tpu.blockchain.store import BlockStore
db = FileDB({db_path!r})
store = BlockStore(db)
real = db.delete
n = [0]
def hooked(key):
    real(key)
    n[0] += 1
    if n[0] >= 4:
        os.kill(os.getpid(), signal.SIGKILL)
db.delete = hooked
store.prune_to(7)
print("UNREACHABLE")
"""
        proc = subprocess.run(
            [sys.executable, "-c", child], capture_output=True, text=True,
            timeout=120, cwd=REPO_ROOT,
        )
        assert proc.returncode == -signal.SIGKILL, (
            proc.returncode, proc.stdout, proc.stderr,
        )
        assert "UNREACHABLE" not in proc.stdout

        db2 = FileDB(db_path)
        store2 = BlockStore(db2)
        assert (store2.base(), store2.height()) == (7, 10)
        wm = json.loads(db2.get(b"blockStore"))
        assert wm["base"] == 7 and wm["clean_base"] == 7
        for prefix in (b"H:", b"SC:", b"P:"):
            for k, _v in db2.iterate_prefix(prefix):
                h = int(k.split(b":")[1])
                assert h >= 7, f"leftover {k!r} below base after resume"
        # the store still serves its retained range
        assert store2.load_block(7) is not None
        assert store2.load_seen_commit(10) is not None
        db2.close()

    def test_pre_round19_watermark_still_loads(self):
        """A watermark without clean_base (older home) opens cleanly and
        treats base as clean."""
        chain = build_kvstore_chain(5)
        db = chain.block_store_db
        db.set_sync(b"blockStore", json.dumps({"height": 5, "base": 2}).encode())
        store = BlockStore(db)
        assert (store.base(), store.height()) == (2, 5)


# -- prune vs concurrent readers ----------------------------------------------


class TestPruneReaderRaces:
    def test_rpc_reads_and_producer_race_inflight_prune(self):
        """RPC block reads and the statesync producer's host_sections
        racing an in-flight prune_to must see base-consistent results:
        either a full, decodable answer or a clean below-base outcome —
        never a partial block or an unhandled decode error."""
        from tendermint_tpu.statesync.producer import host_sections

        chain = build_kvstore_chain(60, txs_per_block=3)
        store = chain.block_store
        errors: list = []
        stop = threading.Event()

        def reader():
            while not stop.is_set():
                for h in range(1, store.height() + 1):
                    base = store.base()
                    blk = store.load_block(h)
                    meta = store.load_block_meta(h)
                    if blk is not None:
                        # a served block is COMPLETE and decodable
                        if blk.header.height != h:
                            errors.append(("height", h))
                    elif h >= store.base() and h >= base:
                        # absent inside the CURRENT retained range and
                        # the range seen before the read: a real hole
                        errors.append(("hole", h, base, store.base()))
                    if meta is None and h >= store.base() and h >= base:
                        errors.append(("meta-hole", h))

        # the producer's state handle pinned at a height the pruner WILL
        # overtake mid-test: before that, full sections must build;
        # after, the clean ValueError fallback — never anything else
        pinned = chain.state.copy()
        pinned.last_block_height = 30
        saw_valueerror = []

        def producer_reader():
            # what the snapshot producer does between commit and prune:
            # a height pruned mid-read must surface as the producer's
            # clean ValueError (caught upstream), nothing else
            while not stop.is_set():
                try:
                    sections, _seen = host_sections(pinned, store)
                    assert sections["block"]["meta"] is not None
                except ValueError:
                    saw_valueerror.append(1)  # clean fallback path

        threads = [
            threading.Thread(target=reader, daemon=True),
            threading.Thread(target=reader, daemon=True),
            threading.Thread(target=producer_reader, daemon=True),
        ]
        for t in threads:
            t.start()
        try:
            for retain in range(5, 56, 5):
                store.prune_to(retain)
                time.sleep(0.01)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
        assert not errors, errors[:10]
        assert store.base() == 55
        # the pinned height crossed the base mid-test, so the producer
        # path exercised its clean fallback at least once
        assert saw_valueerror


# -- WAL chunk retention ------------------------------------------------------


class TestWalRetention:
    def _make_wal(self, root: str, chunk_size: int = 600):
        from tendermint_tpu.consensus.wal import WAL

        wal = WAL(
            os.path.join(root, "cs.wal", "wal"),
            flush_interval_s=0.02,
            chunk_size=chunk_size,
        )
        wal.start()
        return wal

    def _fill(self, wal, heights: int, per_height: int = 4):
        for h in range(1, heights + 1):
            for i in range(per_height):
                wal.save({"type": "msg_info", "peer_id": "",
                          "msg": {"pad": "x" * 64, "h": h, "i": i}})
            wal.write_end_height(h)

    def test_prune_drops_old_chunks_and_replay_survives(self, tmp_path):
        wal = self._make_wal(str(tmp_path))
        self._fill(wal, 30)
        paths_before = wal.group.chunk_paths()
        assert len(paths_before) > 4, "fixture must span several chunks"

        pruned = wal.prune_to(25)
        assert pruned > 0
        assert wal.stats()["chunks_pruned"] == pruned
        paths_after = wal.group.chunk_paths()
        assert len(paths_after) == len(paths_before) - pruned
        # everything replay can still be asked for survives: retention
        # keeps blocks >= 25, so markers >= 24 must all resolve
        for h in (24, 25, 28, 30):
            lines = wal.lines_after_height(h)
            assert lines is not None, f"marker {h} lost by prune"
        wal.stop()

        # a reopen (repair scan + clean watermark with a pruned PREFIX)
        # must come up clean and keep working
        wal2 = self._make_wal(str(tmp_path))
        assert wal2.lines_after_height(30) is not None
        self._fill_more(wal2, 31, 33)
        assert wal2.lines_after_height(33) == []
        wal2.stop()

    def _fill_more(self, wal, lo, hi):
        for h in range(lo, hi + 1):
            wal.save({"type": "msg_info", "peer_id": "",
                      "msg": {"pad": "y" * 64, "h": h}})
            wal.write_end_height(h)

    def test_prune_noop_cases(self, tmp_path):
        wal = self._make_wal(str(tmp_path), chunk_size=1 << 20)
        self._fill(wal, 10)
        # single head chunk: nothing rotated, nothing to prune
        assert wal.prune_to(9) == 0
        wal.stop()

    def test_prune_stops_at_first_unlink_failure(self, tmp_path,
                                                 monkeypatch):
        """A failed unlink must STOP the pass, not skip ahead: deleting
        newer chunks past a surviving older one punches a mid-log hole
        that permanently invalidates the clean watermark (its tolerance
        covers a LEADING pruned run only). The stuck chunk simply
        retries on the next pass."""
        import tendermint_tpu.consensus.wal as walmod

        wal = self._make_wal(str(tmp_path))
        self._fill(wal, 30)
        chunks_before = wal.group.chunk_paths()
        stuck = chunks_before[0]
        real_unlink = os.unlink

        def flaky(path, *a, **kw):
            if path == stuck:
                raise OSError("simulated EACCES")
            return real_unlink(path, *a, **kw)

        monkeypatch.setattr(walmod.os, "unlink", flaky)
        assert wal.prune_to(25) == 0  # stopped before deleting ANYTHING
        assert wal.group.chunk_paths() == chunks_before
        monkeypatch.setattr(walmod.os, "unlink", real_unlink)
        assert wal.prune_to(25) > 0  # next pass finishes the job
        wal.stop()

    def test_prune_keeps_boundary_chunk(self, tmp_path):
        """The anchor chunk (newest one holding a marker <= retain-1)
        must SURVIVE — deleting it would cut records between its marker
        and the next chunk's first marker."""
        wal = self._make_wal(str(tmp_path))
        self._fill(wal, 40)
        wal.prune_to(35)
        # every marker from retain-1 up must still be found
        for h in range(34, 41):
            assert wal.lines_after_height(h) is not None
        wal.stop()


# -- RPC range clamping on pruned stores --------------------------------------


class TestRpcClamping:
    def _ctx(self, chain):
        class _Ctx:
            block_store = chain.block_store
        return _Ctx()

    def test_blockchain_info_clamps_not_errors(self):
        from tendermint_tpu.rpc.core.handlers import RPCError, blockchain_info

        chain = build_kvstore_chain(20)
        chain.block_store.prune_to(10)
        ctx = self._ctx(chain)

        # explicit range straddling the base: clamps to [10, 15]
        info = blockchain_info(ctx, min_height=2, max_height=15)
        got = [m["header"]["height"] for m in info["block_metas"]]
        assert got == list(range(15, 9, -1))
        assert info["base"] == 10 and info["last_height"] == 20

        # range ENTIRELY below the base: empty, not an error
        info = blockchain_info(ctx, min_height=2, max_height=8)
        assert info["block_metas"] == [] and info["base"] == 10

        # a caller-inverted range is still the caller's error
        with pytest.raises(RPCError, match="min height"):
            blockchain_info(ctx, min_height=15, max_height=12)

        # default window on a deeply pruned store clamps to the base
        chain.block_store.prune_to(18)
        got = [
            m["header"]["height"]
            for m in blockchain_info(ctx)["block_metas"]
        ]
        assert got == [20, 19, 18]

    def test_status_reports_earliest_height(self):
        from tendermint_tpu.rpc.core.handlers import status

        chain = build_kvstore_chain(12)
        chain.block_store.prune_to(9)

        class _Ctx:
            block_store = chain.block_store
            switch = None
            priv_validator = None

        st = status(_Ctx())
        assert st["earliest_block_height"] == 9
        assert st["latest_block_height"] == 12

    def test_tx_proof_below_base_is_clear_error(self):
        from tendermint_tpu.rpc.core.handlers import RPCError, tx as rpc_tx
        from tendermint_tpu.types.tx import tx_hash

        class _Res:
            height, index = 2, 0

            class result:
                code, data, log = 0, b"", ""

            tx = b"k2-0=v2"

        class _Indexer:
            def get(self, h):
                return _Res()

        chain = build_kvstore_chain(10)
        chain.block_store.prune_to(6)

        class _Ctx:
            block_store = chain.block_store
            tx_indexer = _Indexer()

        # without proof: the indexed result still serves
        out = rpc_tx(_Ctx(), tx_hash(b"k2-0=v2").hex(), prove=False)
        assert out["height"] == 2
        # with proof: the block is gone — clear error, not a crash
        with pytest.raises(RPCError, match="below the store's base"):
            rpc_tx(_Ctx(), tx_hash(b"k2-0=v2").hex(), prove=True)


# -- fast-sync pool: bases + horizon ------------------------------------------


class TestPoolHorizon:
    def _pool(self, start=1):
        sent = []
        pool = BlockPool(
            start, request_fn=lambda h, p: sent.append((h, p)),
            timeout_fn=lambda p, r: None,
        )
        return pool, sent

    def test_below_base_peer_ineligible_without_round_trip(self):
        """A peer whose base is above the wanted height is never asked —
        the old behavior burned a block_request/no_block_response round
        trip per retry (round-19 efficiency satellite)."""
        pool, sent = self._pool(start=1)
        pool.set_peer_height("pruned", 100, base=50)
        pool._started_at = time.monotonic()
        pool._spawn_and_retry()
        # heights the peer retains are fair game; nothing below its base
        assert sent, "the peer must still serve its retained range"
        assert all(h >= 50 for h, _p in sent), sent[:5]
        # an archive peer arrives: the below-base heights flow to IT
        sent.clear()
        pool.set_peer_height("archive", 100, base=1)
        pool._spawn_and_retry()
        low = [(h, p) for h, p in sent if h < 50]
        assert low and all(p == "archive" for _h, p in low)

    def test_base_zero_means_serves_everything(self):
        pool, sent = self._pool(start=1)
        pool.set_peer_height("old-proto", 100)  # no base reported
        pool._started_at = time.monotonic()
        pool._spawn_and_retry()
        assert sent and all(p == "old-proto" for _h, p in sent)

    def test_below_horizon_detection(self):
        pool, _ = self._pool(start=1)
        assert pool.below_horizon() is None  # no peers: undecidable
        pool.set_peer_height("a", 100, base=40)
        pool.set_peer_height("b", 90, base=35)
        assert pool.below_horizon() == 35
        # one peer that can serve height 1 clears the verdict
        pool.set_peer_height("c", 95, base=1)
        assert pool.below_horizon() is None
        pool.remove_peer("c")
        assert pool.below_horizon() == 35

    def test_peers_behind_us_do_not_count(self):
        pool, _ = self._pool(start=50)
        pool.set_peer_height("laggard", 10, base=1)
        assert pool.below_horizon() is None


class TestReactorHorizonFallback:
    def _reactor(self):
        from tests.test_reactors import make_genesis, make_node
        from tendermint_tpu.blockchain.reactor import BlockchainReactor

        doc, pvs = make_genesis(1)
        node = make_node(doc, pvs[0])
        bc = BlockchainReactor(
            node.state.copy(), node.cs.proxy_app_conn, node.store,
            fast_sync=True,
        )

        class _FakeSwitch:
            def reactor(self, name):
                return None

            def broadcast(self, *a, **k):
                return []

        bc.switch = _FakeSwitch()
        bc._started = True
        return bc

    def test_two_strikes_then_fallback(self):
        bc = self._reactor()
        calls = []

        class _Pool:
            below = 40
            stopped = False

            def below_horizon(self):
                return self.below

            def stop(self):
                self.stopped = True

        bc.pool = _Pool()
        bc.horizon_fallback = lambda h: calls.append(h) or True
        assert bc._check_horizon() is False  # strike 1: no trigger yet
        assert calls == []
        assert bc._check_horizon() is True  # strike 2: statesync armed
        assert calls == [40]
        assert bc.pool.stopped and bc._deferred
        assert bc.below_horizon_fallbacks == 1

    def test_recovering_horizon_resets_strikes(self):
        bc = self._reactor()

        class _Pool:
            below = 40

            def below_horizon(self):
                return self.below

        bc.pool = _Pool()
        bc.horizon_fallback = lambda h: True
        assert bc._check_horizon() is False
        bc.pool.below = None  # an archive peer showed up
        assert bc._check_horizon() is False
        bc.pool.below = 40
        assert bc._check_horizon() is False  # strikes restarted

    def test_failed_fallback_keeps_fast_sync(self):
        bc = self._reactor()

        class _Pool:
            stopped = False

            def below_horizon(self):
                return 40

            def stop(self):
                self.stopped = True

        bc.pool = _Pool()
        bc.horizon_fallback = lambda h: False  # node can't statesync
        assert bc._check_horizon() is False
        assert bc._check_horizon() is False
        assert not bc.pool.stopped and not bc._deferred
        assert bc.below_horizon_fallbacks == 0


# -- statesync reactor: stall strikes -----------------------------------------


class TestOffererStallBan:
    def _reactor(self, tmp, ban_after=2):
        from tendermint_tpu.statesync.reactor import StateSyncReactor
        from tendermint_tpu.statesync.snapshot import SnapshotStore

        r = StateSyncReactor(SnapshotStore(os.path.join(tmp, "snaps")))
        r.stall_ban_after = ban_after

        class _Sw:
            stopped = []

            class peers:
                @staticmethod
                def get(pid):
                    return None

        r.switch = _Sw()
        return r

    def test_stall_strikes_ban_after_threshold(self, tmp_path):
        r = self._reactor(str(tmp_path), ban_after=2)
        r._note_stall("peerA", "chunk 0")
        assert r.offerer_bans_stall == 0
        r._note_stall("peerA", "chunk 1")
        assert r.offerer_bans_stall == 1
        assert r.offerers_banned == 1 and r.peers_banned == 1

    def test_answer_clears_strikes(self, tmp_path):
        r = self._reactor(str(tmp_path), ban_after=2)
        r._note_stall("peerA", "chunk 0")
        r._clear_stall("peerA")
        r._note_stall("peerA", "chunk 2")
        assert r.offerer_bans_stall == 0  # never two in a row

    def test_accomplice_answer_does_not_launder_staller_strikes(
            self, tmp_path):
        """_fetch_window attribution contract: strikes clear only for
        the peer that ACTUALLY answered — a staller whose chunks an
        accomplice keeps answering must not have its strikes cleared
        (each of its windows still burns the full timeout). Driven at
        the _note_stall/_clear_stall level the window code calls:
        clear(accomplice) between two staller strikes must not reset
        the staller."""
        r = self._reactor(str(tmp_path), ban_after=2)
        r._note_stall("staller", "chunk 0")
        r._clear_stall("accomplice")  # someone ELSE answered
        r._note_stall("staller", "chunk 1")
        assert r.offerer_bans_stall == 1

    def test_ban_kinds_counted(self, tmp_path):
        r = self._reactor(str(tmp_path))
        r._ban_peer("x", "forged manifest", kind="forged")
        r._ban_peer("y", "bad chunk", kind="corrupt")
        r._ban_peer("z", "plain ban")  # no kind: not an offerer ban
        s = r.stats()
        assert s["offerer_bans_forged"] == 1
        assert s["offerer_bans_corrupt"] == 1
        assert s["offerers_banned"] == 2
        assert s["peers_banned"] == 3


# -- WAL + store wired through the coordinator --------------------------------


class TestCoordinatorDrivesPlanes:
    def test_prune_drives_store_and_wal(self, tmp_path):
        from tendermint_tpu.consensus.wal import WAL

        chain = build_kvstore_chain(30)
        wal = WAL(
            os.path.join(str(tmp_path), "cs.wal", "wal"),
            flush_interval_s=0.02, chunk_size=600,
        )
        wal.start()
        for h in range(1, 31):
            for i in range(4):
                wal.save({"type": "msg_info", "peer_id": "",
                          "msg": {"pad": "x" * 64, "h": h, "i": i}})
            wal.write_end_height(h)
        chunks_before = len(wal.group.chunk_paths())

        cfg = PruningConfig(retain_blocks=8, interval_heights=1)
        c = RetentionCoordinator(
            cfg, chain.block_store, wal_fn=lambda: wal,
            db_dir=str(tmp_path),
            wal_dir=os.path.join(str(tmp_path), "cs.wal"),
            snapshot_dir=os.path.join(str(tmp_path), "snaps"),
        )

        class _S:
            last_block_height = 30

        pruned = c.maybe_prune(_S())
        assert pruned == 22
        assert chain.block_store.base() == 23
        assert c.wal_chunks_pruned > 0
        assert len(wal.group.chunk_paths()) < chunks_before
        assert wal.lines_after_height(30) is not None
        s = c.stats()
        assert s["runs"] == 1 and s["pruned_heights"] == 22
        assert s["disk_wal_bytes"] > 0
        wal.stop()


class TestTxIndexRetention:
    """Round 20: the kv tx index was the last per-height disk term a
    pruned node kept growing forever — it now rides the same retention
    pass as the block store and WAL."""

    @staticmethod
    def _indexer_with(heights):
        from tendermint_tpu.state.txindex import Batch, KVTxIndexer
        from tendermint_tpu.types.tx import TxResult, tx_hash

        ix = KVTxIndexer(MemDB())
        hashes = {}
        for h in heights:
            b = Batch()
            for i in range(3):
                tx = f"tx-{h}-{i}".encode()
                b.add(TxResult(height=h, index=i, tx=tx, result=None))
                hashes[(h, i)] = tx_hash(tx)
            ix.add_batch(b)
        return ix, hashes

    def test_prune_to_drops_below_and_keeps_rest(self):
        ix, hashes = self._indexer_with(range(1, 11))
        assert ix.prune_to(6) == 5 * 3  # heights 1..5, 3 txs each
        assert ix.pruned_txs == 15
        for (h, i), hsh in hashes.items():
            got = ix.get(hsh)
            if h < 6:
                assert got is None, (h, i)
            else:
                assert got is not None and got.height == h
        # idempotent: nothing left below the safe height
        assert ix.prune_to(6) == 0
        # and the height keys went with the primaries (no orphan scan
        # debt): a later deeper pass only counts the still-live txs
        assert ix.prune_to(11) == 5 * 3
        assert ix.pruned_txs == 30

    def test_pre_round_20_records_survive(self):
        """Txs indexed before the height keys existed have no secondary
        key — pruning must leave them alone (the safe failure direction
        for an index), not guess at their heights."""
        from tendermint_tpu.state.txindex import KVTxIndexer
        from tendermint_tpu.types.tx import tx_hash

        ix = KVTxIndexer(MemDB())
        old = b"pre-round-20-tx"
        ix.db.set(tx_hash(old), b'{"height": 2, "index": 0, "tx": "", "result": null}')
        assert ix.prune_to(100) == 0
        assert ix.db.get(tx_hash(old)) is not None

    def test_coordinator_drives_tx_indexer_and_stats(self, tmp_path):
        ix, _ = self._indexer_with(range(1, 21))
        chain = build_kvstore_chain(20)
        cfg = PruningConfig(retain_blocks=5, interval_heights=1)
        c = RetentionCoordinator(
            cfg, chain.block_store, tx_indexer=ix, db_dir=str(tmp_path),
        )

        class _S:
            last_block_height = 20

        assert c.maybe_prune(_S()) == 15  # safe height 16
        assert ix.pruned_txs == 15 * 3
        s = c.stats()
        assert s["tx_index_pruned"] == 45
        assert "disk_txindex_bytes" in s
        # an indexer without prune_to (the null impl) is simply skipped
        from tendermint_tpu.state.txindex import NullTxIndexer

        c2 = RetentionCoordinator(
            cfg, build_kvstore_chain(20).block_store,
            tx_indexer=NullTxIndexer(),
        )
        assert c2.maybe_prune(_S()) == 15
        assert c2.stats()["tx_index_pruned"] == 0
