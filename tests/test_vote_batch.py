"""Big-committee vote plane (round 16, docs/committee.md): the split
add API, the consensus-thread VoteBatcher's per-lane error attribution,
the batched evidence/light-client straggler routing, and the
aggregate-commit prototype (format flag + mixed-net refusal)."""

from __future__ import annotations

import threading
import time

import pytest

from consensus_common import TEST_CHAIN_ID, ValidatorStub, make_cs_and_stubs, rand_gen_state
from tendermint_tpu.consensus import messages as msgs
from tendermint_tpu.consensus.state import MsgInfo
from tendermint_tpu.consensus.vote_batcher import VoteBatcher
from tendermint_tpu.ops import gateway
from tendermint_tpu.types import (
    VOTE_TYPE_PRECOMMIT,
    VOTE_TYPE_PREVOTE,
    BlockID,
    VoteSet,
)
from tendermint_tpu.types.vote import (
    ConflictingVotesError,
    InvalidSignatureError,
    InvalidValidatorAddressError,
    InvalidValidatorIndexError,
    UnexpectedStepError,
)


def _wait_until(cond, timeout=30.0, tick=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(tick)
    return cond()


def _byz_vote(pv, index, type_, block_id, height=1, round_=0):
    """Sign bypassing the PrivValidatorFS double-sign guard — a real
    byzantine signer uses the raw key (test_evidence's convention)."""
    from tendermint_tpu.types import Vote

    vote = Vote(
        validator_address=pv.get_address(),
        validator_index=index,
        height=height,
        round_=round_,
        type_=type_,
        block_id=block_id,
    )
    return vote.with_signature(pv.priv_key.sign(vote.sign_bytes(TEST_CHAIN_ID)))


def _forge(vote):
    """Same vote, forged signature bytes (still 64B ed25519 shape)."""
    from dataclasses import replace

    from tendermint_tpu.crypto.keys import SignatureEd25519

    raw = bytearray(vote.signature.raw)
    raw[0] ^= 0xFF
    return replace(vote, signature=SignatureEd25519(bytes(raw)))


BID = BlockID(b"\x21" * 20)


class TestSplitAddParity:
    """begin_add/commit_add must be add_vote case-for-case: the split
    path is what the batcher drives, and it may never drift."""

    def _vs_and_stubs(self, n=4):
        state, pvs = rand_gen_state(n)
        vs = VoteSet(TEST_CHAIN_ID, 1, 0, VOTE_TYPE_PREVOTE, state.validators)
        return vs, [ValidatorStub(pv, i) for i, pv in enumerate(pvs)]

    def test_valid_vote_both_paths(self):
        vs, stubs = self._vs_and_stubs()
        v = stubs[0].sign_vote(VOTE_TYPE_PREVOTE, TEST_CHAIN_ID, BID)
        pending = vs.begin_add(v)
        assert pending is not None
        pk, sb, sig = pending.item()
        assert sb == v.sign_bytes(TEST_CHAIN_ID) and sig == v.signature.raw
        assert pending.commit(True) is True
        assert vs.get_by_index(0) is not None
        # exact duplicate: begin_add returns None (add_vote's False)
        assert vs.begin_add(v) is None
        assert vs.add_vote(v) is False

    def test_error_taxonomy_preserved(self):
        vs, stubs = self._vs_and_stubs()
        from dataclasses import replace

        good = stubs[1].sign_vote(VOTE_TYPE_PREVOTE, TEST_CHAIN_ID, BID)
        with pytest.raises(UnexpectedStepError):
            vs.begin_add(replace(good, height=7))
        with pytest.raises(InvalidValidatorIndexError):
            vs.begin_add(replace(good, validator_index=99))
        with pytest.raises(InvalidValidatorAddressError):
            vs.begin_add(replace(good, validator_address=b"\x01" * 20))
        with pytest.raises(InvalidSignatureError):
            vs.begin_add(replace(good, signature=None))
        # a failed verdict rejects exactly this vote
        pending = vs.begin_add(good)
        with pytest.raises(InvalidSignatureError):
            pending.commit(False)
        assert vs.get_by_index(1) is None
        # ...and the vote can still be added with a passing verdict
        assert vs.begin_add(good).commit(True)

    def test_conflict_raises_at_commit(self):
        vs, stubs = self._vs_and_stubs()
        a = _byz_vote(stubs[2].pv, 2, VOTE_TYPE_PREVOTE, BID)
        b = _byz_vote(stubs[2].pv, 2, VOTE_TYPE_PREVOTE, BlockID(b"\x42" * 20))
        assert vs.begin_add(a).commit(True)
        pending = vs.begin_add(b)
        with pytest.raises(ConflictingVotesError):
            pending.commit(True)

    def test_duplicate_between_begin_and_commit_is_false(self):
        vs, stubs = self._vs_and_stubs()
        v = stubs[0].sign_vote(VOTE_TYPE_PREVOTE, TEST_CHAIN_ID, BID)
        pending = vs.begin_add(v)
        assert vs.add_vote(v) is True  # interleaved add of the same vote
        assert pending.commit(True) is False  # degrades to duplicate

    def test_sign_bytes_memo_shared_across_quorum(self):
        vs, stubs = self._vs_and_stubs(8)
        sbs = set()
        for s in stubs:
            pending = vs.begin_add(
                s.sign_vote(VOTE_TYPE_PREVOTE, TEST_CHAIN_ID, BID)
            )
            sbs.add(id(pending.sign_bytes))
            pending.commit(True)
        # one canonical serialization object served the whole quorum
        assert len(sbs) == 1


class TestVoteBatcher:
    def _batcher(self, min_batch=2):
        verifier = gateway.Verifier(use_tpu=False)
        return VoteBatcher(lambda: verifier, min_batch=min_batch), verifier

    def test_forged_lane_rejects_exactly_that_vote(self):
        """The acceptance property: one forged signature inside a mixed
        micro-batch rejects only its own vote; every other lane lands."""
        cs, stubs, prop_idx = make_cs_and_stubs(8)
        batcher = cs.vote_batcher
        votes = [
            s.sign_vote(VOTE_TYPE_PREVOTE, TEST_CHAIN_ID, BID)
            for s in stubs
            if s.index != prop_idx
        ]
        forged_idx = votes[3].validator_index
        votes[3] = _forge(votes[3])
        cs.rs.validators = cs.state.validators  # rs seeded by constructor
        batcher.prepare(votes, cs.rs, TEST_CHAIN_ID)
        assert batcher.batches == 1 and batcher.batched_sigs == len(votes)
        results = {}
        for v in votes:
            try:
                results[v.validator_index] = cs.rs.votes.add_vote(
                    v, "peerX",
                    verifier=lambda pk, m, s: batcher.verdict((pk, m, s)),
                )
            except InvalidSignatureError:
                results[v.validator_index] = "rejected"
        assert results[forged_idx] == "rejected"
        good = [i for i in results if i != forged_idx]
        assert all(results[i] is True for i in good)
        prevotes = cs.rs.votes.prevotes(0)
        assert prevotes.get_by_index(forged_idx) is None
        for i in good:
            assert prevotes.get_by_index(i) is not None
        # only the forged lane fell back to a singleton re-verify... it
        # did NOT: its batch verdict was False and was consumed as such
        assert batcher.singletons == 0

    def test_double_sign_semantics_unchanged_through_batch(self):
        """Conflicting votes keep raising ConflictingVotesError (and feed
        evidence) when both ride the batched path."""
        cs, stubs, prop_idx = make_cs_and_stubs(4)
        s = next(x for x in stubs if x.index != prop_idx)
        a = _byz_vote(s.pv, s.index, VOTE_TYPE_PREVOTE, BID)
        b = _byz_vote(s.pv, s.index, VOTE_TYPE_PREVOTE, BlockID(b"\x55" * 20))
        cs.vote_batcher.prepare([a, b], cs.rs, TEST_CHAIN_ID)
        assert cs.rs.votes.add_vote(
            a, "p", verifier=lambda *it: cs.vote_batcher.verdict(it)
        )
        with pytest.raises(ConflictingVotesError):
            cs.rs.votes.add_vote(
                b, "p", verifier=lambda *it: cs.vote_batcher.verdict(it)
            )

    def test_floor_and_grouping(self):
        """Votes group per (height, round, type); groups below the
        min-batch floor stay singleton."""
        state, pvs = rand_gen_state(8)
        stubs = [ValidatorStub(pv, i) for i, pv in enumerate(pvs)]
        cs, _, _ = make_cs_and_stubs(1)
        batcher, _ = self._batcher(min_batch=4)

        class RS:
            pass

        from tendermint_tpu.consensus.height_vote_set import HeightVoteSet

        rs = RS()
        rs.height = 1
        rs.votes = HeightVoteSet(TEST_CHAIN_ID, 1, state.validators)
        rs.votes.set_round(1)
        rs.last_commit = None
        pre = [s.sign_vote(VOTE_TYPE_PREVOTE, TEST_CHAIN_ID, BID) for s in stubs[:5]]
        for s in stubs[5:8]:
            s.round_ = 1
        r1 = [s.sign_vote(VOTE_TYPE_PREVOTE, TEST_CHAIN_ID, BID) for s in stubs[5:8]]
        batcher.prepare(pre + r1, rs, TEST_CHAIN_ID)
        # round-0 group (5 lanes) dispatched; round-1 group (3) under floor
        assert batcher.batches == 1
        assert batcher.batched_sigs == 5
        for v in r1:
            assert batcher.verdict(
                (state.validators.get_by_index(v.validator_index)[1].pub_key.raw,
                 v.sign_bytes(TEST_CHAIN_ID), v.signature.raw)
            )
        assert batcher.singletons == 3

    def test_failed_batch_transport_falls_back_to_singletons(self):
        """A batch whose resolver dies un-primes its lanes: every vote
        re-verifies singleton — latency, never a dropped verdict."""
        state, pvs = rand_gen_state(4)
        stubs = [ValidatorStub(pv, i) for i, pv in enumerate(pvs)]

        class BoomVerifier(gateway.Verifier):
            def verify_batch_async(self, items, _attempt=0):
                def resolve():
                    raise RuntimeError("transport died")

                return resolve

        boom = BoomVerifier(use_tpu=False)
        batcher = VoteBatcher(lambda: boom, min_batch=2)

        class RS:
            pass

        from tendermint_tpu.consensus.height_vote_set import HeightVoteSet

        rs = RS()
        rs.height = 1
        rs.votes = HeightVoteSet(TEST_CHAIN_ID, 1, state.validators)
        rs.last_commit = None
        votes = [s.sign_vote(VOTE_TYPE_PREVOTE, TEST_CHAIN_ID, BID) for s in stubs]
        batcher.prepare(votes, rs, TEST_CHAIN_ID)
        for v in votes:
            _, val = state.validators.get_by_index(v.validator_index)
            assert batcher.verdict(
                (val.pub_key.raw, v.sign_bytes(TEST_CHAIN_ID), v.signature.raw)
            )
        assert batcher.singletons == len(votes)

    def test_receive_routine_batches_and_counts(self):
        """End to end through the live receive routine: a 32-validator
        prevote burst rides micro-batches (counters + histogram move)
        and every vote lands."""
        from tendermint_tpu.consensus import vote_batcher as cvb

        cs, stubs, prop_idx = make_cs_and_stubs(32)
        hist = cvb.vote_batch_hists()["batch"]
        count_before = hist._count if hasattr(hist, "_count") else None
        votes = [
            s.sign_vote(VOTE_TYPE_PREVOTE, TEST_CHAIN_ID, BID)
            for s in stubs
            if s.index != prop_idx
        ]
        for v in votes:
            cs._inputs.put(("msg", MsgInfo(msgs.VoteMessage(v), "peer-test")))
        cs.start()
        try:
            def added():
                pv = cs.rs.votes.prevotes(0)
                if pv is None:
                    return 0
                return sum(
                    1 for s in stubs
                    if s.index != prop_idx
                    and pv.get_by_index(s.index) is not None
                )

            assert _wait_until(lambda: added() == len(votes), timeout=60), (
                f"only {added()}/{len(votes)} added"
            )
            assert cs.vote_batcher.batches >= 1
            assert cs.vote_batcher.batched_sigs >= len(votes) // 2
        finally:
            cs.stop()

    def test_serial_mode_is_pure_singleton(self):
        """vote_batching=False: no batch ever dispatches; every verdict
        is a one-signature verify (the bench's A/B seam and the WAL
        replay contract)."""
        cs, stubs, prop_idx = make_cs_and_stubs(8)
        cs.vote_batching = False
        votes = [
            s.sign_vote(VOTE_TYPE_PREVOTE, TEST_CHAIN_ID, BID)
            for s in stubs
            if s.index != prop_idx
        ]
        for v in votes:
            cs._inputs.put(("msg", MsgInfo(msgs.VoteMessage(v), "peer-test")))
        cs.start()
        try:
            def added():
                pv = cs.rs.votes.prevotes(0)
                return 0 if pv is None else sum(
                    1 for s in stubs
                    if s.index != prop_idx
                    and pv.get_by_index(s.index) is not None
                )

            assert _wait_until(lambda: added() == len(votes), timeout=60)
            assert cs.vote_batcher.batches == 0
            assert cs.vote_batcher.singletons >= len(votes)
        finally:
            cs.stop()


class TestStragglerBatching:
    """The round-16 satellites: evidence and light-client turnover
    signatures route through the batch verifier."""

    def _evidence(self, n=1):
        from tendermint_tpu.types.evidence import DuplicateVoteEvidence

        state, pvs = rand_gen_state(max(n, 2))
        out = []
        for i in range(n):
            # distinct block pairs per piece: evidence hashes exclude the
            # validator identity, so identical pairs would dedupe
            a = _byz_vote(pvs[i], i, VOTE_TYPE_PREVOTE,
                          BlockID(bytes([0x10 + i]) * 20))
            b = _byz_vote(pvs[i], i, VOTE_TYPE_PREVOTE,
                          BlockID(bytes([0x60 + i]) * 20))
            out.append(DuplicateVoteEvidence.new(pvs[i].get_pub_key(), a, b))
        return out, state

    def test_evidence_validate_batches(self):
        calls = []

        def counting_batch(items):
            calls.append(list(items))
            return gateway._cpu_verify_batch(list(items))

        evs, _ = self._evidence(1)
        evs[0].validate(TEST_CHAIN_ID, batch_verifier=counting_batch)
        assert len(calls) == 1 and len(calls[0]) == 2

    def test_evidence_data_one_batch_with_attribution(self):
        from dataclasses import replace

        from tendermint_tpu.crypto.keys import SignatureEd25519
        from tendermint_tpu.types.evidence import (
            DuplicateVoteEvidence,
            EvidenceData,
            EvidenceError,
        )

        evs, state = self._evidence(3)
        calls = []

        def counting_batch(items):
            calls.append(list(items))
            return gateway._cpu_verify_batch(list(items))

        ed = EvidenceData(list(evs))
        ed.validate(TEST_CHAIN_ID, 9, None, batch_verifier=counting_batch)
        assert len(calls) == 1 and len(calls[0]) == 6  # ONE call, 2 sigs/piece

        # forge ONE piece's vote_b: attribution names exactly that piece
        bad = evs[1]
        raw = bytearray(bad.vote_b.signature.raw)
        raw[1] ^= 0x80
        forged = DuplicateVoteEvidence(
            bad.pub_key, bad.vote_a,
            replace(bad.vote_b, signature=SignatureEd25519(bytes(raw))),
        )
        ed_bad = EvidenceData([evs[0], forged, evs[2]])
        with pytest.raises(EvidenceError, match="piece 1"):
            ed_bad.validate(
                TEST_CHAIN_ID, 9, None,
                batch_verifier=lambda items: gateway._cpu_verify_batch(items),
            )
        # the good pieces alone still validate
        EvidenceData([evs[0], evs[2]]).validate(
            TEST_CHAIN_ID, 9, None,
            batch_verifier=lambda items: gateway._cpu_verify_batch(items),
        )

    def test_light_turnover_check_batches(self):
        """_check_old_set_overlap flushes its candidate signatures in one
        batch_verifier call with the tally unchanged."""
        from tendermint_tpu.rpc.light import LightClient
        from tendermint_tpu.types.block import Commit
        from tendermint_tpu.types.validator import Validator
        from tendermint_tpu.types.validator_set import ValidatorSet
        from tendermint_tpu.types.vote import Vote

        state, pvs = rand_gen_state(4)
        old_set = state.validators
        bid = BlockID(b"\x31" * 20)
        pres = []
        for i, pv in enumerate(pvs):
            v = Vote(pv.get_address(), i, 3, 0, VOTE_TYPE_PRECOMMIT, bid)
            pres.append(pv.sign_vote(TEST_CHAIN_ID, v))
        commit = Commit(bid, pres)
        calls = []

        def counting_batch(items):
            calls.append(list(items))
            return gateway._cpu_verify_batch(list(items))

        lc = LightClient(None, TEST_CHAIN_ID, old_set,
                         batch_verifier=counting_batch)
        # same-set "turnover": every old signer present -> accepted
        lc._check_old_set_overlap(3, commit, old_set)
        assert len(calls) == 1 and len(calls[0]) == 4
        # a disjoint new set leaves no creditable old power -> refused
        from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
        from tendermint_tpu.rpc.light import LightClientError

        strangers = ValidatorSet([
            Validator.new(gen_priv_key_ed25519(bytes([7, i]) * 16).pub_key(), 1)
            for i in range(4)
        ])
        with pytest.raises(LightClientError):
            lc._check_old_set_overlap(3, commit, strangers)


class TestAggregateCommit:
    """The aggregate-commit prototype: half-aggregation correctness,
    wire/JSON round trips, the size win, the genesis format flag, and
    the mixed-net refusal."""

    def _commit(self, n=8, height=5):
        from tendermint_tpu.types.block import Commit
        from tendermint_tpu.types.vote import Vote

        state, pvs = rand_gen_state(n)
        bid = BlockID(b"\x44" * 20)
        pres = []
        for i, pv in enumerate(pvs):
            v = Vote(pv.get_address(), i, height, 0, VOTE_TYPE_PRECOMMIT, bid)
            pres.append(pv.sign_vote(TEST_CHAIN_ID, v))
        return Commit(bid, pres), state.validators

    def test_roundtrip_size_and_tamper(self):
        from tendermint_tpu.types.agg_commit import AggregateCommit
        from tendermint_tpu.types.validator_set import CommitError

        commit, vals = self._commit(8)
        agg = AggregateCommit.from_commit(commit, TEST_CHAIN_ID, vals)
        agg.verify(TEST_CHAIN_ID, vals)
        # the headline: meaningfully smaller than the full commit
        assert len(agg.to_bytes()) < 0.6 * len(commit.to_bytes())
        # wire + JSON round trips verify
        AggregateCommit.from_bytes(agg.to_bytes()).verify(TEST_CHAIN_ID, vals)
        AggregateCommit.from_json(agg.to_json()).verify(TEST_CHAIN_ID, vals)
        # tamper matrix: scalar, nonce point, signer bits
        bad = AggregateCommit.from_bytes(agg.to_bytes())
        bad.s_agg = bytes(32)
        with pytest.raises(CommitError):
            bad.verify(TEST_CHAIN_ID, vals)
        bad2 = AggregateCommit.from_bytes(agg.to_bytes())
        bad2.rs[0] = bytes(32)
        with pytest.raises(CommitError):
            bad2.verify(TEST_CHAIN_ID, vals)
        bad3 = AggregateCommit.from_bytes(agg.to_bytes())
        bad3.signers.set_index(0, False)
        with pytest.raises(CommitError):
            bad3.verify(TEST_CHAIN_ID, vals)

    def test_non_ascending_signer_indices_refused_at_decode(self):
        """Strictly-ascending signer indices are the canonical wire
        order — verify() pairs rs with signers.indices() (sorted), so
        any other order would mispair lanes and reject a valid
        aggregate; decode refuses it outright."""
        from tendermint_tpu.codec.binary import Decoder, Encoder
        from tendermint_tpu.types.agg_commit import AggregateCommit

        commit, vals = self._commit(8)
        agg = AggregateCommit.from_commit(commit, TEST_CHAIN_ID, vals)
        idxs = agg.signers.indices()
        swapped = [idxs[1], idxs[0]] + idxs[2:]
        e = Encoder()
        e.write_u8(0xAC)
        agg.block_id.encode(e)
        e.write_varint(agg.height())
        e.write_varint(agg.round_())
        e.write_varint(agg.signers.size)
        e.write_list(swapped, lambda enc, i: enc.write_varint(i))
        e.write_raw(b"".join(agg.rs))
        e.write_raw(agg.s_agg)
        with pytest.raises(ValueError, match="ascending"):
            AggregateCommit.decode(Decoder(e.buf()))

    def test_sub_quorum_refused(self):
        from tendermint_tpu.types.agg_commit import AggregateCommit
        from tendermint_tpu.types.block import Commit
        from tendermint_tpu.types.validator_set import CommitError

        commit, vals = self._commit(6)
        # only 3/6 precommits: +2/3 impossible
        thin = Commit(
            commit.block_id,
            [p if i < 3 else None for i, p in enumerate(commit.precommits)],
        )
        with pytest.raises(CommitError):
            AggregateCommit.from_commit(thin, TEST_CHAIN_ID, vals)

    def test_forged_member_signature_fails_aggregate(self):
        from tendermint_tpu.types.agg_commit import AggregateCommit
        from tendermint_tpu.types.validator_set import CommitError

        commit, vals = self._commit(6)
        commit.precommits[2] = _forge(commit.precommits[2])
        agg = AggregateCommit.from_commit(commit, TEST_CHAIN_ID, vals)
        with pytest.raises(CommitError, match="aggregate signature"):
            agg.verify(TEST_CHAIN_ID, vals)

    def test_genesis_flag_and_mixed_net_refusal(self):
        from tendermint_tpu.codec.binary import Decoder
        from tendermint_tpu.types.agg_commit import AggregateCommit, decode_commit
        from tendermint_tpu.types.genesis import GenesisDoc

        commit, vals = self._commit(4)
        agg = AggregateCommit.from_commit(commit, TEST_CHAIN_ID, vals)

        # the flag rides genesis; unknown values refused at load
        state, pvs = rand_gen_state(1)
        base = GenesisDoc(
            genesis_time_ns=1, chain_id="agg-chain",
            validators=[], commit_format="full",
        )
        base.validators = []  # bypass validate for the json shape check
        doc_json = {
            "genesis_time": 1, "chain_id": "agg-chain",
            "validators": [
                {"pub_key": pvs[0].get_pub_key().to_json(), "power": 1,
                 "name": "v"}
            ],
        }
        full_doc = GenesisDoc.from_json(dict(doc_json))
        agg_doc = GenesisDoc.from_json(
            dict(doc_json, commit_format="aggregate")
        )
        assert not full_doc.aggregate_commits()
        assert agg_doc.aggregate_commits()
        # the two genesis docs differ byte-for-byte: a mixed net cannot
        # silently share a chain id story
        assert full_doc.to_json() != agg_doc.to_json()
        with pytest.raises(ValueError):
            GenesisDoc.from_json(dict(doc_json, commit_format="bls"))

        # decode-side refusal: a full-format node fed aggregate bytes
        wire = agg.to_bytes()
        with pytest.raises(ValueError, match="refused"):
            decode_commit(Decoder(wire), aggregate_commits=False)
        # the aggregate-format node decodes both forms
        got = decode_commit(Decoder(wire), aggregate_commits=True)
        got.verify(TEST_CHAIN_ID, vals)
        full_wire = commit.to_bytes()
        decoded_full = decode_commit(Decoder(full_wire), aggregate_commits=True)
        assert decoded_full.height() == commit.height()
