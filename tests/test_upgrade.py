"""Upgrade-at-height orchestration (round 22, docs/upgrade.md): the
genesis commit-format schedule, the handshake refusal that keeps
mixed-schedule nets from forking at the flip, the AggregateLastCommit
round-state stand-in, forged/sub-quorum aggregate refusal on every
ingest surface (the shared verify core gossip, fast-sync, statesync and
the light client all call), and — slow tier — a real node SIGKILLed
across the boundary whose WAL replay must re-derive the right commit
format per height."""

import json
import os
import signal

import pytest

from tendermint_tpu.codec.binary import Decoder
from tendermint_tpu.crypto import ed25519_agg
from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
from tendermint_tpu.types.agg_commit import (
    AggregateCommit,
    AggregateLastCommit,
    commit_from_json,
    commit_is_aggregate,
    decode_commit,
)
from tendermint_tpu.types.block import Commit
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.validator_set import CommitError
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT

from consensus_common import free_port, init_node_home, node_proc, rpc, wait_height
from test_types import BLOCK_ID, make_val_set, signed_vote

CHAIN = "test-chain"


def _signed_commit(n=4, height=5, drop=()):
    """A fully-signed precommit Commit over BLOCK_ID; indices in `drop`
    abstain (None precommit)."""
    vs, privs = make_val_set(n)
    pres = []
    for i, pv in enumerate(privs):
        if i in drop:
            pres.append(None)
            continue
        pres.append(signed_vote(pv, vs, height, 0, VOTE_TYPE_PRECOMMIT,
                                BLOCK_ID))
    return vs, Commit(BLOCK_ID, pres), height


# -- the genesis schedule ---------------------------------------------------


class TestGenesisSchedule:
    def _doc(self, **kw):
        pv = gen_priv_key_ed25519(b"genesis-val")
        return GenesisDoc(
            genesis_time_ns=1,
            chain_id="up-chain",
            validators=[GenesisValidator(pv.pub_key(), 10, "v0")],
            **kw,
        )

    def test_flip_below_two_refused(self):
        doc = self._doc(upgrade_height=1, upgrade_format="aggregate")
        with pytest.raises(ValueError, match="upgrade_height must be >= 2"):
            doc.validate_and_complete()

    def test_same_format_flip_refused(self):
        doc = self._doc(upgrade_height=5, upgrade_format="full")
        with pytest.raises(ValueError, match="equals commit_format"):
            doc.validate_and_complete()

    def test_format_without_height_refused(self):
        doc = self._doc(upgrade_format="aggregate")
        with pytest.raises(ValueError, match="without upgrade_height"):
            doc.validate_and_complete()

    def test_unknown_upgrade_format_refused(self):
        doc = self._doc(upgrade_height=5, upgrade_format="zip")
        with pytest.raises(ValueError, match="unknown upgrade_format"):
            doc.validate_and_complete()

    def test_format_at_height_and_schedule_string(self):
        doc = self._doc(upgrade_height=4, upgrade_format="aggregate")
        doc.validate_and_complete()
        assert doc.commit_format_at(3) == "full"
        assert doc.commit_format_at(4) == "aggregate"
        assert doc.commit_format_at(10 ** 9) == "aggregate"
        assert not doc.aggregate_commits_at(3)
        assert doc.aggregate_commits_at(4)
        assert doc.schedule_string() == "full>aggregate@4"
        # no flip scheduled: the format holds forever
        plain = self._doc()
        plain.validate_and_complete()
        assert plain.commit_format_at(10 ** 9) == "full"
        assert plain.schedule_string() == "full"

    def test_schedule_json_round_trip(self):
        doc = self._doc(upgrade_height=7, upgrade_format="aggregate")
        doc.validate_and_complete()
        obj = doc.to_json()
        assert obj["upgrade_height"] == 7
        assert obj["upgrade_format"] == "aggregate"
        back = GenesisDoc.from_json(obj)
        assert back.schedule_string() == doc.schedule_string()
        # an unscheduled doc serializes without the keys (byte-compat
        # with every pre-flag genesis)
        plain = self._doc()
        plain.validate_and_complete()
        assert "upgrade_height" not in plain.to_json()


# -- schedule-gated handshake ----------------------------------------------


def _node_info(seed: bytes, schedule: str | None, network: str = "up-net",
               legacy_format: str | None = None):
    from tendermint_tpu.p2p.node_info import NodeInfo

    other = []
    if schedule is not None:
        other.append(f"commit_schedule={schedule}")
    if legacy_format is not None:
        other.append(f"commit_format={legacy_format}")
    return NodeInfo(gen_priv_key_ed25519(seed).pub_key(), "m", network,
                    "1/test", other=other)


class TestScheduleHandshake:
    def test_same_schedule_compatible(self):
        a = _node_info(b"a", "full>aggregate@100")
        b = _node_info(b"b", "full>aggregate@100")
        assert a.compatible_with(b) is None

    def test_schedule_mismatch_named(self):
        # same format TODAY, different flip height — the disagreement
        # that forks AT the flip, so it must refuse at the handshake
        a = _node_info(b"a", "full>aggregate@100")
        b = _node_info(b"b", "full>aggregate@200")
        reason = a.compatible_with(b)
        assert reason is not None
        assert reason.startswith("commit schedule mismatch")
        assert "full>aggregate@100" in reason

    def test_legacy_format_flag_fallback(self):
        # a round-18 peer advertises only commit_format=; an unscheduled
        # round-22 node reads as schedule "full" and stays compatible
        old = _node_info(b"a", None, legacy_format="full")
        new = _node_info(b"b", "full")
        assert new.compatible_with(old) is None
        flipped = _node_info(b"c", "full>aggregate@4")
        assert flipped.compatible_with(old) is not None


class _FakeStream:
    def close(self):
        pass


class _FakePeer:
    outbound = True

    def __init__(self, info):
        self._info = info
        self.stream = _FakeStream()

    def handshake(self, _our_info):
        return self._info

    def pub_key(self):
        return self._info.pub_key


class TestScheduleRefusedCounter:
    def test_mismatch_counted_as_schedule_refused(self):
        from tendermint_tpu.p2p.switch import Switch

        sw = Switch()
        sw.node_info = _node_info(b"ours", "full>aggregate@4")
        with pytest.raises(ConnectionError, match="commit schedule mismatch"):
            sw.add_peer(_FakePeer(_node_info(b"them", "full")))
        assert sw.adversary["schedule_refused"] == 1
        # a plain network mismatch refuses too but does NOT land in the
        # schedule counter — the operator alarm stays specific
        with pytest.raises(ConnectionError, match="network mismatch"):
            sw.add_peer(_FakePeer(
                _node_info(b"other", "full>aggregate@4", network="else")))
        assert sw.adversary["schedule_refused"] == 1


# -- the AggregateLastCommit stand-in --------------------------------------


class TestAggregateLastCommit:
    def test_stand_in_contract(self):
        vs, commit, height = _signed_commit()
        agg = AggregateCommit.from_commit(commit, CHAIN, vs)
        alc = AggregateLastCommit(agg, vs)
        assert alc.has_two_thirds_majority()
        assert alc.two_thirds_majority() == BLOCK_ID
        assert alc.make_commit() is agg
        assert alc.has_all()
        # vote-gossip must find NO per-vote lane to ship (the reactor's
        # aggregate catchup branch ships the whole commit instead)
        assert alc.bit_array().num_true_bits() == 0
        # but coverage screens still see the signer lanes
        assert alc.get_by_index(0) is not None
        # and late precommits cannot be absorbed
        vote = signed_vote(make_val_set(4)[1][0], vs, height, 0,
                           VOTE_TYPE_PRECOMMIT, BLOCK_ID)
        assert alc.begin_add(vote) is None
        assert alc.add_vote(vote) is False


# -- forged / sub-quorum refusal (the shared ingest core) ------------------


class TestAggregateRefusal:
    def test_sub_quorum_aggregation_refused(self):
        vs, commit, _ = _signed_commit(drop=(2, 3))  # 2 of 4 signed
        with pytest.raises(CommitError, match="only 20/40 power"):
            AggregateCommit.from_commit(commit, CHAIN, vs)

    def test_forged_scalar_refused_everywhere(self):
        vs, commit, height = _signed_commit()
        agg = AggregateCommit.from_commit(commit, CHAIN, vs)
        agg.verify(CHAIN, vs, agg_verifier=ed25519_agg.verify_aggregate)
        forged = AggregateCommit.from_bytes(agg.to_bytes())
        forged.s_agg = bytes(32)
        # the direct verify (what gossip's _screen_agg_commit calls)
        with pytest.raises(CommitError, match="failed verification"):
            forged.verify(CHAIN, vs,
                          agg_verifier=ed25519_agg.verify_aggregate)
        # and the set-level commit verify (fast-sync / statesync /
        # store ingest all route through ValidatorSet.verify_commit)
        with pytest.raises(CommitError):
            vs.verify_commit(CHAIN, BLOCK_ID, height, forged)

    def test_dropped_signer_bit_refused(self):
        vs, commit, _ = _signed_commit()
        agg = AggregateCommit.from_commit(commit, CHAIN, vs)
        tampered = AggregateCommit.from_bytes(agg.to_bytes())
        # claim one fewer signer while keeping the same scalar: the
        # bitmap/nonce invariant trips before any curve math
        tampered.signers.set_index(0, False)
        tampered.rs = tampered.rs[1:]
        with pytest.raises(CommitError):
            tampered.verify(CHAIN, vs,
                            agg_verifier=ed25519_agg.verify_aggregate)

    def test_light_client_aggregate_overlap(self):
        from tendermint_tpu.rpc.light import LightClient, LightClientError

        vs, commit, height = _signed_commit()
        agg = AggregateCommit.from_commit(commit, CHAIN, vs)
        # trusted set IS the signing set: full old-set overlap, accepted
        LightClient(None, CHAIN, vs, height - 1) \
            ._check_old_set_overlap_aggregate(height, agg, vs)
        # a disjoint trusted set gets zero old-power from the bitmap —
        # condition (d) fails even though the aggregate itself verifies
        old_privs = [gen_priv_key_ed25519(f"old-{i}".encode())
                     for i in range(4)]
        from tendermint_tpu.types.validator import Validator
        from tendermint_tpu.types.validator_set import ValidatorSet

        old_set = ValidatorSet(
            [Validator.new(p.pub_key(), 10) for p in old_privs])
        lc = LightClient(None, CHAIN, old_set, height - 1)
        with pytest.raises(LightClientError):
            lc._check_old_set_overlap_aggregate(height, agg, vs)
        # and a forged aggregate never reaches the overlap tally
        forged = AggregateCommit.from_bytes(agg.to_bytes())
        forged.s_agg = bytes(32)
        lc_ok = LightClient(None, CHAIN, vs, height - 1)
        with pytest.raises(LightClientError, match="failed"):
            lc_ok._check_old_set_overlap_aggregate(height, forged, vs)


# -- wire / JSON dispatch ---------------------------------------------------


class TestCommitDispatch:
    def test_decode_commit_schedule_gate(self):
        vs, commit, _ = _signed_commit()
        agg = AggregateCommit.from_commit(commit, CHAIN, vs)
        with pytest.raises(ValueError, match="aggregate commit refused"):
            decode_commit(Decoder(agg.to_bytes()), aggregate_commits=False)
        got = decode_commit(Decoder(agg.to_bytes()), aggregate_commits=True)
        assert commit_is_aggregate(got)
        # full commits pass regardless of the flag (pre-flip blocks are
        # served to post-flip nodes during catchup)
        full = decode_commit(Decoder(commit.to_bytes()),
                             aggregate_commits=True)
        assert not commit_is_aggregate(full)

    def test_commit_from_json_dispatch(self):
        vs, commit, _ = _signed_commit()
        agg = AggregateCommit.from_commit(commit, CHAIN, vs)
        back = commit_from_json(agg.to_json())
        assert commit_is_aggregate(back)
        assert back.to_bytes() == agg.to_bytes()
        full = commit_from_json(commit.to_json())
        assert not commit_is_aggregate(full)
        assert full.to_bytes() == commit.to_bytes()


# -- boundary crash / WAL replay (slow tier) --------------------------------


@pytest.mark.slow
def test_upgrade_boundary_crash_replay(tmp_path):
    """SIGKILL a real node right as it crosses the flip, twice, and
    prove replay re-derives the right commit format PER HEIGHT: the WAL
    straddles #ENDHEIGHT around H, the store holds full commits below H
    and aggregates from H on, and the restarted node keeps committing
    aggregates."""
    home = str(tmp_path / "node")
    init_node_home(home, "upgrade-crash-chain")
    gpath = os.path.join(home, "genesis.json")
    with open(gpath) as f:
        g = json.load(f)
    g["upgrade_height"] = 4
    g["upgrade_format"] = "aggregate"
    with open(gpath, "w") as f:
        json.dump(g, f)

    port = free_port()
    p = node_proc(home, port)
    try:
        # cross the flip live, then die mid-era
        assert wait_height(port, 4, 120) >= 4
        p.send_signal(signal.SIGKILL)
        p.wait()
        # replay spans both formats (#ENDHEIGHT entries straddle H)
        p = node_proc(home, port)
        assert wait_height(port, 6, 120) >= 6
        p.send_signal(signal.SIGKILL)
        p.wait()
        # a second replay starts INSIDE the aggregate era
        p = node_proc(home, port)
        assert wait_height(port, 7, 120) >= 7
        below = rpc(port, "block", height=3)["block"]["last_commit"]
        assert "precommits" in below and "s_agg" not in below
        for h in (4, 6):
            lc = rpc(port, "block", height=h)["block"]["last_commit"]
            assert "s_agg" in lc, f"height {h} lost the aggregate format"
    finally:
        p.kill()
        p.wait()
