"""WAL v2 repair + group-commit unit tier (round 9, docs/crash-recovery.md).

The ALICE-style crash model for an append-only log: the on-disk image
after a power failure is SOME byte prefix of the record stream (torn
write), possibly with trailing garbage the allocator exposed, possibly
with flipped bits from a sick device. For every such image the WAL must
open, self-repair (truncate at the first bad frame, back the tail up),
and serve a clean replayable prefix. These sweeps are exhaustive per byte
offset and run in-process — the subprocess end-to-end tier is
tests/test_wal_torture.py.
"""

from __future__ import annotations

import glob
import os
import struct

import pytest

from tendermint_tpu.consensus.ticker import TimeoutInfo
from tendermint_tpu.consensus.wal import (
    MAGIC,
    WAL,
    WALMessage,
    decode_wal_line,
    scan_frames,
)


def _build_wal(path: str, n: int = 6, chunk_size: int | None = None) -> bytes:
    """A clean v2 WAL with n timeout records + ENDHEIGHT markers; returns
    the head chunk's bytes."""
    w = WAL(path, flush_interval_s=0.01, chunk_size=chunk_size)
    w.start()
    for i in range(n):
        w.save(WALMessage.timeout(TimeoutInfo(1.0 + i, 1 + i, 0, 3)))
        w.write_end_height(i + 1)
    w.stop()
    with open(path, "rb") as f:
        return f.read()


def _corrupt_backups(path: str) -> list[str]:
    return glob.glob(path + "*.corrupt-*")


class TestTornWriteSweep:
    def test_every_byte_offset_recovers(self, tmp_path):
        """Truncate the WAL image at EVERY byte offset; each opens clean,
        serves exactly the record prefix that fully survived, and backs
        up whatever was cut mid-frame."""
        base = str(tmp_path / "src" / "wal")
        raw = _build_wal(base)
        assert raw.startswith(MAGIC) and len(raw) > 200
        expected_all, bad = scan_frames(raw)
        assert bad is None and len(expected_all) >= 13  # seed marker + 12

        seen_prefix_lens = set()
        for cut in range(len(raw) + 1):
            p = str(tmp_path / f"t{cut}" / "wal")
            os.makedirs(os.path.dirname(p))
            with open(p, "wb") as f:
                f.write(raw[:cut])
            w = WAL(p)
            expected, cut_mid_frame = scan_frames(raw[:cut])
            lines = w.read_all_lines()
            assert lines == [b.decode() for b in expected], f"cut={cut}"
            for ln in lines:
                assert decode_wal_line(ln) is not None
            s = w.stats()
            if cut_mid_frame is not None:
                assert s["repairs"] == 1 and s["truncated_bytes"] == cut - cut_mid_frame
                assert _corrupt_backups(p), f"cut={cut}: no backup of the torn tail"
            else:
                assert s["repairs"] == 0
            seen_prefix_lens.add(len(expected))
            w.group.close()
        # the sweep is not vacuous: every record-prefix length occurred
        assert seen_prefix_lens == set(range(len(expected_all) + 1))

    def test_endheight_marker_never_lost_behind_tear(self, tmp_path):
        """A tear strictly after a synced #ENDHEIGHT must keep that marker
        findable — the 'never lose a height past its last synced
        ENDHEIGHT' half of the durability contract."""
        base = str(tmp_path / "src" / "wal")
        raw = _build_wal(base, n=4)
        payloads, _ = scan_frames(raw)
        # byte offset just past each ENDHEIGHT frame
        off = len(MAGIC)
        marker_ends = {}
        for pl in payloads:
            off += 8 + len(pl)
            if pl.startswith(b"#ENDHEIGHT: "):
                marker_ends[int(pl.split(b":")[1].decode())] = off
        assert set(marker_ends) == {0, 1, 2, 3, 4}
        for h, end in marker_ends.items():
            for cut in sorted({end, end + 1, min(end + 5, len(raw))}):
                p = str(tmp_path / f"h{h}c{cut}" / "wal")
                os.makedirs(os.path.dirname(p))
                with open(p, "wb") as f:
                    f.write(raw[:cut])
                w = WAL(p)
                assert w.lines_after_height(h) is not None, (h, cut)
                w.group.close()


class TestCorruptionSchedules:
    def test_bit_flip_truncates_at_flipped_record(self, tmp_path):
        """Flip one bit inside each record's payload region: repair must cut
        AT that record — everything before survives, nothing after does
        (no resync: record order is part of the safety argument)."""
        base = str(tmp_path / "src" / "wal")
        raw = _build_wal(base)
        frames = []
        off = len(MAGIC)
        while off < len(raw):
            _, length = struct.unpack_from(">II", raw, off)
            frames.append((off, 8 + length))
            off += 8 + length
        for k, (foff, flen) in enumerate(frames):
            p = str(tmp_path / f"f{k}" / "wal")
            os.makedirs(os.path.dirname(p))
            img = bytearray(raw)
            img[foff + 8 + (flen - 8) // 2] ^= 0x10  # mid-payload bit flip
            with open(p, "wb") as f:
                f.write(bytes(img))
            w = WAL(p)
            assert len(w.read_all_lines()) == k, f"record {k}"
            assert w.stats()["repairs"] == 1
            w.group.close()

    def test_garbage_suffix_cut_with_zero_record_loss(self, tmp_path):
        base = str(tmp_path / "src" / "wal")
        raw = _build_wal(base)
        n_records = len(scan_frames(raw)[0])
        for k, garbage in enumerate(
            (b"\x00" * 40, b"\xff" * 3, os.urandom(200), b"{json?")
        ):
            p = str(tmp_path / f"g{k}" / "wal")
            os.makedirs(os.path.dirname(p))
            with open(p, "wb") as f:
                f.write(raw + garbage)
            w = WAL(p)
            assert len(w.read_all_lines()) == n_records
            s = w.stats()
            assert s["repairs"] == 1 and s["truncated_bytes"] == len(garbage)
            w.group.close()

    def test_damaged_magic_drops_chunk_not_process(self, tmp_path):
        base = str(tmp_path / "src" / "wal")
        raw = _build_wal(base)
        p = str(tmp_path / "m" / "wal")
        os.makedirs(os.path.dirname(p))
        with open(p, "wb") as f:
            f.write(b"XX" + raw[2:])
        w = WAL(p)  # must not raise
        assert w.read_all_lines() == []
        assert w.stats()["repairs"] == 1
        w.group.close()


class TestRotationBoundary:
    def test_corrupt_middle_chunk_quarantines_later_chunks(
        self, tmp_path, monkeypatch
    ):
        """With a tiny chunk size the log spans several chunks; damage in a
        middle chunk truncates there AND moves every later chunk out of
        the group (ordering past a hole is unprovable).

        Bit rot in a chunk an earlier synced flush covered is outside the
        crash model the clean watermark optimizes for (round 10), so this
        runs under the forensics knob — the full-history scan whose
        quarantine semantics this test pins."""
        monkeypatch.setenv("TENDERMINT_WAL_DEEP_SCAN", "1")
        base = str(tmp_path / "rot" / "wal")
        _build_wal(base, n=12, chunk_size=256)
        from tendermint_tpu.libs.autofile import Group

        chunks = Group.list_chunks(base)
        assert len(chunks) >= 3, "chunk_size=256 must force rotation"
        victim = chunks[1]
        with open(victim, "r+b") as f:
            f.seek(len(MAGIC) + 4)
            b = f.read(1)
            f.seek(len(MAGIC) + 4)
            f.write(bytes([b[0] ^ 0xFF]))
        with open(victim, "rb") as f:
            victim_bytes = f.read()
        _, bad = scan_frames(victim_bytes)
        assert bad is not None
        with open(chunks[0], "rb") as f:
            first_chunk_records = len(scan_frames(f.read())[0])

        w = WAL(base)
        assert w.stats()["repairs"] == 1
        assert len(w.read_all_lines()) == first_chunk_records
        # later chunks left the namespace wholesale, as .corrupt backups;
        # the victim itself stays, truncated to its clean prefix, and a
        # fresh head is recreated on open. One artifact PER file: the
        # damaged tail's backup plus one per quarantined chunk — the
        # head's quarantine name must not clobber the tail backup
        # (its natural name is exactly the tail backup's)
        assert len(Group.list_chunks(base)) == 3
        backups = _corrupt_backups(base)
        assert len(backups) == len(chunks) - 1, backups
        tail_backup = min(backups, key=len)  # "<wal>.corrupt-<stamp>"
        with open(tail_backup, "rb") as f:
            assert f.read() == victim_bytes[bad:], (
                "tail backup clobbered by a quarantined chunk"
            )
        w.group.close()

    def test_torn_tail_in_final_chunk_keeps_earlier_chunks(self, tmp_path):
        base = str(tmp_path / "rot2" / "wal")
        _build_wal(base, n=12, chunk_size=256)
        from tendermint_tpu.libs.autofile import Group

        before = WAL(base)
        n_before = len(before.read_all_lines())
        before.group.close()
        with open(base, "r+b") as f:
            f.truncate(os.path.getsize(base) - 3)
        w = WAL(base)
        lines = w.read_all_lines()
        assert n_before - 1 <= len(lines) < n_before
        assert w.stats()["repairs"] == 1
        w.group.close()

    def test_zero_byte_chunk_is_clean_not_redamaged(self, tmp_path, monkeypatch):
        """A prior repair can truncate a chunk to 0 bytes (damage at its
        magic). Later opens must treat that empty chunk as clean — NOT
        re-flag it and quarantine every newer chunk (which would discard
        freshly fsynced records and #ENDHEIGHTs written since).

        Runs under the forensics knob: the in-place magic destruction is
        historical-chunk rot the clean watermark deliberately skips, and
        the zero-byte-chunk invariant it pins belongs to the full scan."""
        monkeypatch.setenv("TENDERMINT_WAL_DEEP_SCAN", "1")
        base = str(tmp_path / "z" / "wal")
        _build_wal(base, n=12, chunk_size=256)
        from tendermint_tpu.libs.autofile import Group

        chunks = Group.list_chunks(base)
        assert len(chunks) >= 3
        with open(chunks[1], "r+b") as f:  # destroy a middle chunk's magic
            f.seek(0)
            f.write(b"XX")
        w = WAL(base)  # first open: repairs (truncates chunk 1 to 0 bytes)
        assert w.stats()["repairs"] == 1
        w.group.close()
        assert os.path.getsize(chunks[1]) == 0

        # write new durable records after the repair, then reopen twice
        w = WAL(base)
        assert w.stats()["repairs"] == 0, "empty chunk re-flagged as damage"
        w.start()
        w.write_end_height(99)
        w.stop()
        r = WAL(base)
        assert r.stats()["repairs"] == 0
        assert r.lines_after_height(99) == [], "post-repair records lost"
        r.group.close()

    def test_missing_head_after_rotation_crash(self, tmp_path):
        """Crash between os.replace and reopening the head: the group has
        numbered chunks but no head file. Open must serve the chunks and
        recreate the head."""
        base = str(tmp_path / "rot3" / "wal")
        _build_wal(base, n=12, chunk_size=256)
        n_all = len(WAL(base).read_all_lines())
        with open(base, "rb") as f:
            head_records = len(scan_frames(f.read())[0])
        os.unlink(base)
        w = WAL(base)
        assert len(w.read_all_lines()) == n_all - head_records
        assert os.path.exists(base)  # head recreated (with magic)
        w.group.close()


class TestGroupCommit:
    def test_endheight_always_fsynced_others_batched(self, tmp_path):
        w = WAL(str(tmp_path / "wal"), flush_interval_s=60.0)  # no timer help
        w.start()
        for i in range(50):
            w.save(WALMessage.timeout(TimeoutInfo(1.0, 1, 0, 3)))
        s = w.stats()
        assert s["pending"] == 50, "saves must not fsync individually"
        fsyncs_before = s["fsyncs"]
        w.write_end_height(1)
        s = w.stats()
        assert s["pending"] == 0 and s["fsyncs"] == fsyncs_before + 1
        assert s["group_size"] == 51, "one fsync covered the whole group"
        w.stop()

    def test_flusher_bounds_staleness(self, tmp_path):
        import time

        w = WAL(str(tmp_path / "wal"), flush_interval_s=0.03)
        w.start()
        w.save(WALMessage.timeout(TimeoutInfo(1.0, 1, 0, 3)))
        deadline = time.monotonic() + 2.0
        while w.stats()["pending"] and time.monotonic() < deadline:
            time.sleep(0.01)
        assert w.stats()["pending"] == 0, "flusher never fsynced the tail"
        w.stop()

    def test_sync_every_write_mode(self, tmp_path):
        w = WAL(str(tmp_path / "wal"), sync_every_write=True)
        w.start()
        base = w.stats()["fsyncs"]
        for _ in range(5):
            w.save(WALMessage.timeout(TimeoutInfo(1.0, 1, 0, 3)))
        assert w.stats()["fsyncs"] == base + 5
        w.stop()

    def test_stop_never_hangs_on_stuck_flusher(self, tmp_path, monkeypatch):
        """A flusher wedged inside os.fsync (dying disk, NFS stall) holds
        _sync_mtx indefinitely; stop() must give up after its timed join
        and skip the final sync instead of blocking shutdown forever on
        the same stuck device."""
        import os as _os
        import threading
        import time

        w = WAL(str(tmp_path / "wal"), flush_interval_s=0.02)
        w.start()  # start/seed fsyncs run with the REAL fsync

        gate = threading.Event()
        entered = threading.Event()

        def stuck_fsync(fd):
            entered.set()
            gate.wait(20)  # the hung-disk image: fsync never returns

        monkeypatch.setattr(_os, "fsync", stuck_fsync)
        try:
            w.save(WALMessage.timeout(TimeoutInfo(1.0, 1, 0, 3)))
            assert entered.wait(2.0), "flusher never reached fsync"
            t0 = time.monotonic()
            w.stop()
            elapsed = time.monotonic() - t0
            # join budget is 2s; anything near gate.wait's 20s means
            # on_stop blocked on the stuck flusher's _sync_mtx
            assert elapsed < 8.0, f"stop() hung {elapsed:.1f}s on stuck flusher"
        finally:
            gate.set()


class TestLegacyCompat:
    LEGACY = (
        '{"time": 1.0, "timeout": {"duration": 1.0, "height": 1, "round": 0,'
        ' "step": 3}, "type": "timeout"}\n'
        "#ENDHEIGHT: 1\n"
        '{"time": 2.0, "timeout": {"duration": 1.0, "height": 2, "round": 0,'
        ' "step": 3}, "type": "timeout"}\n'
    )

    def test_legacy_detected_and_replayable(self, tmp_path):
        p = str(tmp_path / "wal")
        with open(p, "w") as f:
            f.write(self.LEGACY)
        w = WAL(p)
        assert w.stats()["format"] == 1
        lines = w.lines_after_height(1)
        assert lines is not None and len(lines) == 1
        assert decode_wal_line(lines[0])[0] == "timeout"
        assert w.lines_after_last_marker()[0] == 1
        w.group.close()

    def test_legacy_appends_stay_legacy_and_fsync_per_line(self, tmp_path):
        p = str(tmp_path / "wal")
        with open(p, "w") as f:
            f.write(self.LEGACY)
        w = WAL(p)
        w.start()
        base = w.stats()["fsyncs"]
        w.save(WALMessage.timeout(TimeoutInfo(9.0, 2, 0, 3)))
        w.write_end_height(2)
        assert w.stats()["fsyncs"] == base + 2
        w.stop()
        # a reread still sees one consistent legacy log
        r = WAL(p)
        assert r.stats()["format"] == 1
        assert r.lines_after_height(2) == []
        r.group.close()

    def test_fresh_wal_is_v2(self, tmp_path):
        w = WAL(str(tmp_path / "wal"))
        w.start()
        w.stop()
        with open(str(tmp_path / "wal"), "rb") as f:
            assert f.read().startswith(MAGIC)
        r = WAL(str(tmp_path / "wal"))
        assert r.stats()["format"] == 2
        assert r.lines_after_height(0) == []
        r.group.close()


class TestReadOnlyView:
    def test_read_wal_lines_never_mutates_a_damaged_home(self, tmp_path):
        """The operator tool's reader (consensus/replay_file.py) serves the
        clean prefix of a torn WAL WITHOUT repair side effects: no
        truncation, no .corrupt backups, no file creation."""
        from tendermint_tpu.consensus.wal import read_wal_lines

        base = str(tmp_path / "src" / "wal")
        raw = _build_wal(base, n=4)
        p = str(tmp_path / "damaged" / "wal")
        os.makedirs(os.path.dirname(p))
        with open(p, "wb") as f:
            f.write(raw[:-9])  # torn final frame
        dirlist = sorted(os.listdir(os.path.dirname(p)))
        lines = read_wal_lines(p)
        expect, _ = scan_frames(raw[:-9])
        assert lines == [b.decode() for b in expect]
        assert os.path.getsize(p) == len(raw) - 9, "reader truncated the file"
        assert sorted(os.listdir(os.path.dirname(p))) == dirlist, (
            "reader created/renamed files"
        )
        # legacy view too
        lp = str(tmp_path / "legacy" / "wal")
        os.makedirs(os.path.dirname(lp))
        with open(lp, "w") as f:
            f.write("#ENDHEIGHT: 0\n")
        assert read_wal_lines(lp) == ["#ENDHEIGHT: 0"]

    def test_read_wal_lines_missing_wal_raises(self, tmp_path):
        """A typo'd --home must error like the open() this replaced did —
        not read as a legitimately empty log."""
        from tendermint_tpu.consensus.wal import read_wal_lines

        with pytest.raises(FileNotFoundError):
            read_wal_lines(str(tmp_path / "nope" / "wal"))

    def test_read_wal_lines_stops_at_damaged_middle_chunk(self, tmp_path):
        """Damage in a MIDDLE chunk ends the read-only stream right there —
        the node's repair quarantines every later chunk (ordering past a
        hole is unprovable), so the operator tool must not splice later
        chunks into a stream the node itself would never replay."""
        from tendermint_tpu.consensus.wal import read_wal_lines
        from tendermint_tpu.libs.autofile import Group

        base = str(tmp_path / "mid" / "wal")
        _build_wal(base, n=12, chunk_size=256)
        chunks = Group.list_chunks(base)
        assert len(chunks) >= 3
        with open(chunks[0], "rb") as f:
            first_chunk_payloads, bad0 = scan_frames(f.read())
        assert bad0 is None
        with open(chunks[1], "r+b") as f:
            f.seek(len(MAGIC) + 4)
            b = f.read(1)
            f.seek(len(MAGIC) + 4)
            f.write(bytes([b[0] ^ 0xFF]))
        lines = read_wal_lines(base)
        assert lines == [b.decode() for b in first_chunk_payloads], (
            "reader spliced records from beyond the damaged chunk"
        )
        # and still strictly read-only: same chunks, no artifacts
        assert Group.list_chunks(base) == chunks
        assert not _corrupt_backups(base)


class TestSearchEarlyStop:
    def test_v2_marker_search_stops_at_newest_chunk(self, tmp_path):
        """The v2 record search mirrors the legacy Group search's
        newest-first early stop: a marker in the newest chunks means
        older chunk files are never opened on node start."""
        import builtins

        base = str(tmp_path / "wal")
        _build_wal(base, n=12, chunk_size=256)
        from tendermint_tpu.libs.autofile import Group

        w = WAL(base)
        chunks = Group.list_chunks(base)
        assert len(chunks) > 3
        opened = []
        real_open = builtins.open

        def spy(path, *a, **kw):
            opened.append(str(path))
            return real_open(path, *a, **kw)

        builtins.open = spy
        try:
            assert w.lines_after_height(12) == []
        finally:
            builtins.open = real_open
        read_chunks = set(p for p in opened if p in chunks)
        assert read_chunks <= set(chunks[-2:]), "older chunks were scanned"
        w.group.close()


class TestReplayFallback:
    def test_repair_that_eats_boundary_falls_back_to_last_marker(self, tmp_path):
        """Cut the WAL mid-#ENDHEIGHT-frame: the exact boundary search
        misses, but catchup must fall back to the previous surviving
        marker instead of wedging (replay.py round 9)."""
        base = str(tmp_path / "src" / "wal")
        raw = _build_wal(base, n=3)
        # find the LAST endheight frame's start
        last_marker_off = None
        payloads, _ = scan_frames(raw)
        scan_off = len(MAGIC)
        for pl in payloads:
            if pl.startswith(b"#ENDHEIGHT: 3"):
                last_marker_off = scan_off
            scan_off += 8 + len(pl)
        assert last_marker_off is not None
        p = str(tmp_path / "cut" / "wal")
        os.makedirs(os.path.dirname(p))
        with open(p, "wb") as f:
            f.write(raw[: last_marker_off + 5])  # tear inside the marker frame
        w = WAL(p)
        assert w.lines_after_height(3) is None
        h, lines = w.lines_after_last_marker()
        assert h == 2
        assert all(decode_wal_line(ln) for ln in lines)
        w.group.close()


class TestWriterInvariants:
    def test_oversize_record_rejected_at_write_not_read(self, tmp_path):
        """A record beyond MAX_RECORD_BYTES must fail LOUDLY at the
        producer: framing it would fsync fine and then read back as
        corruption on the next open, where repair would truncate there
        and quarantine everything after — retroactive data loss."""
        from tendermint_tpu.consensus.wal import MAX_RECORD_BYTES, _frame

        with pytest.raises(ValueError):
            _frame(b"x" * (MAX_RECORD_BYTES + 1))
        with pytest.raises(ValueError):
            _frame(b"")  # zero-length frames read as damage too
        base = str(tmp_path / "w" / "wal")
        w = WAL(base, flush_interval_s=0.01)
        w.start()
        with pytest.raises(ValueError):
            w.save({"type": "event", "height": 1, "round": 0,
                    "step": "x" * (MAX_RECORD_BYTES + 1)})
        # the WAL stays usable and clean after the refusal
        w.save(WALMessage.timeout(TimeoutInfo(1.0, 1, 0, 3)))
        w.write_end_height(1)
        w.stop()
        with open(base, "rb") as f:
            _, bad = scan_frames(f.read())
        assert bad is None

    def test_failed_fsync_keeps_dir_fsync_obligation(self, tmp_path, monkeypatch):
        """If the data fsync of the FIRST synced flush after head creation
        fails, the pending directory-fsync obligation must survive —
        otherwise every later flush skips the dir fsync and a power
        failure can drop the whole head file (with its fsynced records)."""
        import os as _os

        from tendermint_tpu.libs.autofile import Group

        base = str(tmp_path / "g" / "wal")
        g = Group(base, chunk_size=1 << 20)
        assert g._dir_dirty is True
        g.write_line("rec1")
        real_fsync = _os.fsync

        def boom(fd):
            raise OSError(5, "injected EIO")

        monkeypatch.setattr(_os, "fsync", boom)
        with pytest.raises(OSError):
            g.flush(sync=True)
        assert g._dir_dirty is True, (
            "failed fsync consumed the directory-fsync obligation"
        )
        monkeypatch.setattr(_os, "fsync", real_fsync)
        g.flush(sync=True)
        assert g._dir_dirty is False
        g.close()

    def test_pathological_knobs_clamped_not_fatal(self, tmp_path):
        """Range clamps share the parse's never-kill-startup contract:
        flush_interval<=0 would busy-spin the flusher, a chunk bound at or
        below the magic would rotate (file + fsync) on every record."""
        base = str(tmp_path / "k" / "wal")
        w = WAL(base, flush_interval_s=0.0, chunk_size=0)
        assert w._flush_interval_s > 0
        assert w.group._chunk_size >= 64
        w.start()
        for i in range(5):
            w.save(WALMessage.timeout(TimeoutInfo(1.0, 1 + i, 0, 3)))
        w.write_end_height(1)
        w.stop()
        w2 = WAL(base)
        assert len(w2.read_all_lines()) == 7 and w2.stats()["repairs"] == 0
        w2.group.close()

    def test_nonfinite_flush_interval_clamped(self, tmp_path):
        """inf would kill the flusher with an uncaught OverflowError in
        Event.wait (records then durable only at ENDHEIGHT, silently);
        nan passes a naive <=0 check and busy-spins."""
        for bad in (float("inf"), float("nan"), -1.0):
            w = WAL(str(tmp_path / repr(bad) / "wal"), flush_interval_s=bad)
            assert 0 < w._flush_interval_s <= 3600.0, bad
            w.group.close()


class TestLegacyDetection:
    def test_damaged_first_byte_does_not_misread_legacy_as_v2(self, tmp_path):
        """One corrupt byte at offset 0 of a legacy WAL's OLDEST chunk must
        not flip detection to v2 — the v2 repair MUTATES (truncates +
        quarantines every later chunk), wholesale-destroying an otherwise
        replayable legacy log. Any clean chunk head decides the format."""
        base = str(tmp_path / "leg" / "wal")
        os.makedirs(os.path.dirname(base))
        # multi-chunk legacy home: oldest rotated chunk + live head
        with open(base + ".000", "w") as f:
            f.write("#ENDHEIGHT: 0\n")
            f.write('{"time": 1.0, "type": "timeout", "timeout": '
                    '{"duration": 0.1, "height": 1, "round": 0, "step": 3}}\n')
        with open(base, "w") as f:
            f.write("#ENDHEIGHT: 1\n")
        with open(base + ".000", "r+b") as f:
            f.write(b"\xf3")  # damage exactly the first byte
        w = WAL(base)
        assert w.stats()["format"] == 1, "legacy log misdetected as v2"
        assert w.stats()["repairs"] == 0, "mutating repair ran on legacy"
        assert w.lines_after_height(1) == []
        w.group.close()
        # and the chunks are untouched on disk
        assert os.path.getsize(base + ".000") > 1
        assert not _corrupt_backups(base)

    def test_all_chunk_heads_damaged_defaults_to_v2_with_backup(self, tmp_path):
        """No readable signature anywhere: fall to v2, whose repair backs
        every byte up before cutting — nothing is destroyed."""
        base = str(tmp_path / "dmg" / "wal")
        os.makedirs(os.path.dirname(base))
        with open(base, "wb") as f:
            f.write(b"\xf3 unreadable")
        w = WAL(base)
        assert w.stats()["format"] == 2 and w.stats()["repairs"] == 1
        assert _corrupt_backups(base), "damaged bytes must survive as backup"
        w.group.close()


class TestCleanWatermark:
    """Round 10: the `<wal>.clean` sidecar bounds the open-time deep scan
    to bytes written since the last synced flush (ROADMAP's O(total
    history) open item). The watermark may only ever TRAIL durability —
    every test here checks either the skip or the fallback to the full
    scan when the sidecar and the files disagree."""

    def test_clean_close_skips_covered_history(self, tmp_path):
        base = str(tmp_path / "wm" / "wal")
        _build_wal(base, n=12, chunk_size=256)
        from tendermint_tpu.libs.autofile import Group

        n_rotated = len(Group.list_chunks(base)) - 1
        assert n_rotated >= 2
        assert os.path.exists(base + ".clean")
        w = WAL(base)
        s = w.stats()
        assert s["repairs"] == 0
        assert s["scan_skipped_chunks"] == n_rotated
        assert s["scan_skipped_bytes"] > 0
        # the skipped history still serves reads and the marker search
        assert w.lines_after_height(12) == []
        w.group.close()

    def test_skipped_open_counts_records_like_a_full_scan(self, tmp_path):
        base = str(tmp_path / "cnt" / "wal")
        _build_wal(base, n=9, chunk_size=256)
        fast = WAL(base)
        fast.group.close()
        os.environ["TENDERMINT_WAL_DEEP_SCAN"] = "1"
        try:
            full = WAL(base)
            full.group.close()
        finally:
            del os.environ["TENDERMINT_WAL_DEEP_SCAN"]
        assert full.stats()["scan_skipped_chunks"] == 0
        assert fast._records_at_open == full._records_at_open

    def test_tear_past_watermark_still_repaired(self, tmp_path):
        """The crash window the watermark leaves open is bytes after the
        last synced flush — a tear there must still be found and cut,
        WITHOUT rescanning the covered chunks."""
        base = str(tmp_path / "tear" / "wal")
        _build_wal(base, n=12, chunk_size=256)
        from tendermint_tpu.libs.autofile import Group

        n_rotated = len(Group.list_chunks(base)) - 1
        before = WAL(base)
        n_before = len(before.read_all_lines())
        before.group.close()
        with open(base, "ab") as f:
            f.write(b"\x00\x00\x00\x00\x00\x00\x00\x00torn post-flush bytes")
        w = WAL(base)
        s = w.stats()
        assert s["repairs"] == 1 and s["truncated_bytes"] > 0
        assert s["scan_skipped_chunks"] == n_rotated, "repair rescanned history"
        assert len(w.read_all_lines()) == n_before
        assert _corrupt_backups(base)
        # repair dropped the sidecar: the next open deep-scans until a
        # synced flush rebuilds it
        assert not os.path.exists(base + ".clean")
        w.group.close()

    def test_watermark_past_actual_size_falls_back_to_full_scan(self, tmp_path):
        """Fsynced bytes that VANISH (fs rollback, hand-edit) invalidate
        the sidecar — the open must notice and deep-scan everything."""
        base = str(tmp_path / "lost" / "wal")
        _build_wal(base, n=12, chunk_size=256)
        with open(base, "r+b") as f:
            f.truncate(max(os.path.getsize(base) - 3, 0))
        w = WAL(base)
        s = w.stats()
        assert s["scan_skipped_chunks"] == 0 and s["scan_skipped_bytes"] == 0
        assert s["repairs"] == 1  # the torn tail record was cut
        w.group.close()

    def test_garbage_sidecar_is_ignored_not_fatal(self, tmp_path):
        base = str(tmp_path / "junk" / "wal")
        _build_wal(base, n=6)
        for junk in (b"", b"not json", b'{"chunk_index": "x"}',
                     b'{"chunk_index": -1, "offset": 8, "records": 1}'):
            with open(base + ".clean", "wb") as f:
                f.write(junk)
            w = WAL(base)
            s = w.stats()
            assert s["repairs"] == 0
            assert s["scan_skipped_bytes"] == 0, junk
            w.group.close()

    def test_mid_run_crash_image_keeps_rotated_chunks_skipped(self, tmp_path):
        """Without a clean stop (the crash case) the sidecar persisted at
        the last rotation crossing still covers the rotated history; only
        the newer bytes deep-scan on restart."""
        base = str(tmp_path / "crash" / "wal")
        w = WAL(base, flush_interval_s=60.0, chunk_size=256)
        w.start()
        for i in range(12):
            w.save(WALMessage.timeout(TimeoutInfo(1.0 + i, 1 + i, 0, 3)))
            w.write_end_height(i + 1)
        n_records = len(w.read_all_lines())
        w.group.close()  # no stop(): simulates a crash
        assert os.path.exists(base + ".clean")
        r = WAL(base)
        s = r.stats()
        assert s["repairs"] == 0
        assert s["scan_skipped_chunks"] >= 1
        assert r._records_at_open == n_records
        r.group.close()
