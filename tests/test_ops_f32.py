"""Tests for the PRODUCTION fp32 radix-2^8 Ed25519 kernel
(ops/ed25519_f32.py) — the kernel the gateway actually runs
(ops/gateway.py selects it on every backend).

Mirrors the rigorous coverage test_ops.py gives the int32 reference
kernel: RFC 8032 vectors, tampered sig/msg/pub, high-s, non-canonical R,
empty/odd/bucket-padded batches — plus field-arithmetic regression tests
for the two round-2 review findings (fcanon digit canonicality, fmul
exactness at loose-bound maxima).

Reference hot paths these semantics must match: per-signature verify at
/root/reference/types/vote_set.go:175 and the VerifyCommit loop at
/root/reference/types/validator_set.go:247-250.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from tendermint_tpu.crypto import ed25519 as ed
from tendermint_tpu.ops import ed25519_f32 as f32

P = f32.P


def _limbs_value(out: np.ndarray, lane: int) -> int:
    return sum(int(out[k, lane]) << (8 * k) for k in range(32))


class TestFieldArithmetic:
    def test_fcanon_digit_canonicality_adversarial(self):
        """Round-2 review (high): a parallel-only carry chain left limb0 at
        up to 293 for values < p, so the digit-wise equality in
        _verify_impl could falsely reject a valid signature. fcanon must
        emit digits strictly in [0, 256) for any loose input."""
        x = np.zeros((32, 4), dtype=np.float32)
        x[30, :] = 256.0
        x[31, :] = 255.0
        x[0, :] = [218, 230, 240, 255]
        out = np.asarray(f32.fcanon(jnp.asarray(x)))
        assert out.max() < 256.0 and out.min() >= 0.0
        for b in range(4):
            val = sum(int(x[k, b]) << (8 * k) for k in range(32))
            assert _limbs_value(out, b) == val % P

    def test_fcanon_loose_bound_extremes(self):
        cases = [
            np.full((32, 1), 268.0),
            np.full((32, 1), 825.0),
            np.zeros((32, 1)),
        ]
        cases[0][0, 0] = 825.0
        # exact p, 2p, p-1, p+1, 2p-1 as byte limbs
        for v in (0, P, 2 * P, P - 1, P + 1, 2 * P - 1):
            d = np.frombuffer(v.to_bytes(32, "little"), dtype=np.uint8)
            cases.append(d.astype(np.float64).reshape(32, 1))
        for x in cases:
            out = np.asarray(f32.fcanon(jnp.asarray(x.astype(np.float32))))
            val = sum(int(x[k, 0]) << (8 * k) for k in range(32))
            assert out.max() < 256.0 and out.min() >= 0.0
            assert _limbs_value(out, 0) == val % P

    def test_fcanon_random_loose(self):
        rng = np.random.default_rng(7)
        x = rng.integers(0, 826, size=(32, 128)).astype(np.float32)
        out = np.asarray(f32.fcanon(jnp.asarray(x)))
        assert out.max() < 256.0 and out.min() >= 0.0
        for b in range(x.shape[1]):
            val = sum(int(x[k, b]) << (8 * k) for k in range(32))
            assert _limbs_value(out, b) == val % P

    def test_fmul_exact_at_loose_bound_maxima(self):
        """Round-2 review (low): fmul exactness rests on the active
        backend's HIGHEST-precision conv being exact for the documented
        integer ranges. Pin it: multiply limb vectors at the loose-bound
        maxima (and random loose values) and compare against python ints."""
        rng = np.random.default_rng(3)
        a = np.full((32, 8), 268.0)
        a[0, :] = 749.0
        b = np.full((32, 8), 268.0)
        b[0, :] = 825.0
        rand_a = rng.integers(0, 750, size=(32, 8)).astype(np.float64)
        rand_b = rng.integers(0, 826, size=(32, 8)).astype(np.float64)
        for lhs, rhs in [(a, b), (rand_a, rand_b)]:
            out = np.asarray(
                f32.fcanon(
                    f32.fmul(
                        jnp.asarray(lhs.astype(np.float32)),
                        jnp.asarray(rhs.astype(np.float32)),
                    )
                )
            )
            for lane in range(lhs.shape[1]):
                va = sum(int(lhs[k, lane]) << (8 * k) for k in range(32))
                vb = sum(int(rhs[k, lane]) << (8 * k) for k in range(32))
                assert _limbs_value(out, lane) == (va * vb) % P


# RFC 8032 §7.1 test vectors (secret, public, message, signature)
RFC8032_VECTORS = [
    (
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
    (
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
    ),
]


class TestVerifyF32:
    def test_rfc8032_vectors(self):
        items = []
        for _sk, pk, msg, sig in RFC8032_VECTORS:
            items.append((bytes.fromhex(pk), bytes.fromhex(msg), bytes.fromhex(sig)))
        out = f32.verify_batch(items)
        assert list(out) == [True] * len(items)

    def test_tampered_and_malformed_lanes(self):
        """Mixed batch: valid, tampered sig, tampered msg, wrong pub,
        high-s, non-canonical R.y, bad-length pub/sig, invalid point —
        lane-exact against the CPU reference verifier."""
        seeds = [bytes([i + 1]) * 32 for i in range(8)]
        pubs = [ed.public_key(s) for s in seeds]
        msg = b"vote:height=7,round=0"
        sigs = [ed.sign(s, msg) for s in seeds]

        high_s = sigs[4][:32] + (
            (int.from_bytes(sigs[4][32:], "little") + ed.L).to_bytes(32, "little")
        )
        noncanon_r = (P + 1).to_bytes(32, "little") + sigs[5][32:]
        items = [
            (pubs[0], msg, sigs[0]),                                   # valid
            (pubs[1], msg, sigs[1][:10] + b"\x00" + sigs[1][11:]),      # tampered sig
            (pubs[2], msg + b"!", sigs[2]),                             # tampered msg
            (pubs[0], msg, sigs[3]),                                    # wrong pub
            (pubs[4], msg, high_s),                                     # s >= L
            (pubs[5], msg, noncanon_r),                                 # R.y >= p
            (pubs[6][:31], msg, sigs[6]),                               # short pub
            (pubs[7], msg, sigs[7] + b"\x00"),                          # long sig
            (b"\x01" * 32, msg, sigs[0]),                               # invalid point
            (pubs[3], msg, sigs[3]),                                    # valid again
        ]
        got = list(f32.verify_batch(items))
        want = [ed.verify(p, m, s) for p, m, s in items]
        assert got == want
        assert want == [True, False, False, False, False, False, False, False, False, True]

    def test_empty_odd_and_padded_batches(self):
        assert list(f32.verify_batch([])) == []
        seeds = [bytes([i + 10]) * 32 for i in range(5)]
        items = [
            (ed.public_key(s), b"m%d" % i, ed.sign(s, b"m%d" % i))
            for i, s in enumerate(seeds)
        ]
        # odd batch (5 -> bucket 8): padding lanes must not leak into results
        assert list(f32.verify_batch(items)) == [True] * 5
        items[2] = (items[2][0], items[2][1], items[2][2][:63] + b"\x00")
        out = list(f32.verify_batch(items))
        assert out == [True, True, False, True, True] or out == [
            ed.verify(p, m, s) for p, m, s in items
        ]

    def test_identical_keys_many_messages(self):
        """The commit shape: few validators, many (H,R) messages."""
        seed = b"\x42" * 32
        pub = ed.public_key(seed)
        items = [
            (pub, b"height=%d" % i, ed.sign(seed, b"height=%d" % i))
            for i in range(16)
        ]
        items[7] = (pub, items[7][1], items[3][2])  # sig for wrong message
        got = list(f32.verify_batch(items))
        assert got == [i != 7 for i in range(16)]


def _mixed_items():
    seeds = [bytes([i + 30]) * 32 for i in range(6)]
    items = [
        (ed.public_key(s), b"native-%d" % i, ed.sign(s, b"native-%d" % i))
        for i, s in enumerate(seeds)
    ]
    items.append((b"\x07" * 32, b"badpoint", items[0][2]))       # invalid A
    items.append((items[1][0][:16], b"shortpub", items[1][2]))    # bad length
    high_s = items[2][2][:32] + (
        (int.from_bytes(items[2][2][32:], "little") + ed.L).to_bytes(32, "little")
    )
    items.append((items[2][0], b"native-2", high_s))              # s >= L
    return items


class TestMarshalNativeParity:
    """The marshal has two implementations per stage (native C / python
    fallback); their outputs must be byte-identical."""

    def test_prepare_native_vs_python(self, monkeypatch):
        from tendermint_tpu import native

        if not native.available():
            pytest.skip("native library unavailable")
        items = _mixed_items()
        f32._pubkey_cache.clear()
        nat = f32.prepare_batch8(items, 16)
        f32._pubkey_cache.clear()
        monkeypatch.setattr(native, "available", lambda: False)
        pure = f32.prepare_batch8(items, 16)
        for a, b in zip(nat, pure):
            assert np.array_equal(a, b)
        f32._pubkey_cache.clear()

    def test_cache_warm_vs_cold_identical(self):
        items = _mixed_items()
        f32._pubkey_cache.clear()
        cold = f32.prepare_batch8(items, 16)
        warm = f32.prepare_batch8(items, 16)
        for a, b in zip(cold, warm):
            assert np.array_equal(a, b)


class TestGatewayAsync:
    def test_async_matches_sync_and_order(self):
        from tendermint_tpu.ops.gateway import Verifier

        v = Verifier(min_tpu_batch=4, use_tpu=True)
        batches = []
        for salt in range(3):
            seeds = [bytes([salt * 8 + i + 1]) * 32 for i in range(6)]
            b = [
                (ed.public_key(s), b"a%d-%d" % (salt, i), ed.sign(s, b"a%d-%d" % (salt, i)))
                for i, s in enumerate(seeds)
            ]
            b[salt] = (b[salt][0], b[salt][1], b"\x00" * 64)
            batches.append(b)
        resolvers = [v.verify_batch_async(b) for b in batches]
        results = [r() for r in resolvers]
        for salt, res in enumerate(results):
            assert res == [i != salt for i in range(6)]
        assert v.stats()["tpu_batches"] == 3

    def test_async_below_threshold_resolves_cpu(self):
        from tendermint_tpu.ops.gateway import Verifier

        v = Verifier(min_tpu_batch=64, use_tpu=True)
        seed = b"\x51" * 32
        items = [(ed.public_key(seed), b"small", ed.sign(seed, b"small"))]
        resolve = v.verify_batch_async(items)
        assert resolve() == [True]
        assert v.stats()["cpu_sigs"] == 1 and v.stats()["tpu_batches"] == 0

    def test_async_resolve_device_failure_falls_back(self, monkeypatch):
        """ADVICE r2 medium: device-side failures surface at
        materialization; resolve() must keep the CPU-fallback guarantee."""
        from tendermint_tpu.ops import gateway as gw

        class Boom:
            def __array__(self, *a, **k):
                raise RuntimeError("device lost")

            def __getitem__(self, k):
                raise RuntimeError("device lost")

        v = gw.Verifier(min_tpu_batch=1, use_tpu=True)
        seed = b"\x52" * 32
        items = [(ed.public_key(seed), b"m%d" % i, ed.sign(seed, b"m%d" % i)) for i in range(4)]
        monkeypatch.setattr(f32, "_verify_jit", lambda *a: Boom())
        resolve = v.verify_batch_async(items)
        assert resolve() == [True] * 4          # CPU fallback result
        assert v._tpu_ok is False               # permanent fallback latched
        stats = v.stats()
        assert stats["cpu_sigs"] == 4 and stats["tpu_sigs"] == 0


class TestKernelRegistry:
    """TENDERMINT_TPU_KERNEL selects the verify backend (gateway.KERNELS)."""

    def test_default_is_platform_aware(self, monkeypatch):
        from tendermint_tpu.ops import gateway as gw

        monkeypatch.delenv("TENDERMINT_TPU_KERNEL", raising=False)
        want = (
            "tendermint_tpu.ops.ed25519_comb"
            if gw.on_tpu()
            else "tendermint_tpu.ops.ed25519_f32"
        )
        assert gw.kernel_module().__name__ == want

    @pytest.mark.parametrize(
        "name,module",
        [
            ("f32p", "tendermint_tpu.ops.ed25519_f32p"),
            ("f32", "tendermint_tpu.ops.ed25519_f32"),
            ("int32", "tendermint_tpu.ops.ed25519"),
            ("pallas", "tendermint_tpu.ops.ed25519_pallas"),
        ],
    )
    def test_selects_each_backend(self, monkeypatch, name, module):
        from tendermint_tpu.ops import gateway as gw

        monkeypatch.setenv("TENDERMINT_TPU_KERNEL", name)
        assert gw.kernel_module().__name__ == module

    def test_unknown_name_fails_loudly(self, monkeypatch):
        from tendermint_tpu.ops import gateway as gw

        monkeypatch.setenv("TENDERMINT_TPU_KERNEL", "cuda")
        with pytest.raises(ValueError, match="cuda"):
            gw.kernel_module()

    def test_async_without_pipelining_kernel_resolves_sync(self, monkeypatch):
        """Backends without verify_batch_async still honor the async API."""
        from tendermint_tpu.ops import gateway as gw

        v = gw.Verifier(min_tpu_batch=1, use_tpu=True)
        seed = b"\x53" * 32
        items = [
            (ed.public_key(seed), b"s%d" % i, ed.sign(seed, b"s%d" % i))
            for i in range(4)
        ]

        class SyncOnly:
            @staticmethod
            def verify_batch(its):
                return np.array([True] * len(its))

        monkeypatch.setattr(v, "_kernel_module", lambda: SyncOnly)
        resolve = v.verify_batch_async(items)
        assert resolve() == [True] * 4
        assert v.stats()["tpu_batches"] == 1

    def test_typo_fails_at_startup(self, monkeypatch):
        """A typo'd kernel name must fail at Verifier construction, not
        silently latch the CPU fallback at the first batch."""
        from tendermint_tpu.ops import gateway as gw

        monkeypatch.setenv("TENDERMINT_TPU_KERNEL", "fp32")
        with pytest.raises(ValueError, match="fp32"):
            gw.Verifier(use_tpu=True)
        # with the TPU disabled outright the env var is irrelevant
        gw.Verifier(use_tpu=False)

    def test_sharded_rejects_non_f32(self, monkeypatch):
        import jax
        from jax.sharding import Mesh

        from tendermint_tpu.ops import gateway as gw

        monkeypatch.setenv("TENDERMINT_TPU_KERNEL", "pallas")
        mesh = Mesh(np.array(jax.devices()[:1]), ("batch",))
        with pytest.raises(ValueError, match="pallas"):
            gw.ShardedVerifier(mesh)


class TestPallasF32Kernel:
    """ops/ed25519_f32p — the pallas fp32 ladder (TPU-only: interpret
    mode on CPU is impractically slow for the 127-step unrolled kernel)."""

    @pytest.mark.tpu
    @pytest.mark.skipif(
        not __import__(
            "tendermint_tpu.ops.gateway", fromlist=["on_tpu"]
        ).on_tpu(),
        reason="pallas f32 kernel needs TPU hardware",
    )
    def test_parity_with_f32_including_malformed(self):
        from tendermint_tpu.ops import ed25519_f32p as f32p

        seeds = [bytes([i + 1]) * 32 for i in range(8)]
        items = []
        expected = []
        for i in range(64):
            s = seeds[i % 8]
            pk = ed.public_key(s)
            msg = b"p%d" % i
            sig = ed.sign(s, msg)
            ok = True
            if i % 5 == 1:
                sig = sig[:20] + bytes([sig[20] ^ 1]) + sig[21:]
                ok = False
            elif i % 5 == 2:
                # high-s: add L to the scalar half
                s_int = int.from_bytes(sig[32:], "little") + ed.L
                if s_int < 1 << 256:
                    sig = sig[:32] + s_int.to_bytes(32, "little")
                    ok = False
            elif i % 5 == 3:
                pk = b"\xff" * 32  # invalid pubkey
                ok = False
            items.append((pk, msg, sig))
            expected.append(ok)
        got = f32p.verify_batch(items)
        exp = np.array(expected)
        ref = np.asarray(f32.verify_batch(items))
        assert (got == exp).all()
        assert (got == ref).all()

    def test_registry_includes_f32p(self):
        from tendermint_tpu.ops import gateway as gw

        assert gw.KERNELS["f32p"] == "tendermint_tpu.ops.ed25519_f32p"

    def test_sharded_pins_f32_for_all_paths(self, monkeypatch):
        """Platform default must never swap ShardedVerifier onto the
        unsharded pallas kernel (sync OR async paths)."""
        import jax
        from jax.sharding import Mesh

        from tendermint_tpu.ops import gateway as gw

        monkeypatch.delenv("TENDERMINT_TPU_KERNEL", raising=False)
        mesh = Mesh(np.array(jax.devices()[:1]), ("batch",))
        sv = gw.ShardedVerifier(mesh)
        assert sv._kernel_module().__name__ == "tendermint_tpu.ops.ed25519_f32"


class TestCpuFallbackNative:
    """gateway._cpu_verify_batch rides the native C++ batch verifier for
    wide ed25519 batches; semantics must be identical to the per-item
    python loop on every edge case."""

    def test_parity_with_per_item_loop(self):
        from tendermint_tpu import native
        from tendermint_tpu.crypto.keys import verify_any
        from tendermint_tpu.ops.gateway import _cpu_verify_batch

        if not native.available():
            pytest.skip("native library unavailable")
        # ONLY 32/64-shaped items: a single off-length item would push the
        # whole batch onto the per-item path and make this test vacuous
        # (code-review r3) — the interesting edges (bad point, high-s,
        # tampered) are all shape-valid
        items = [
            it for it in _mixed_items() if len(it[0]) == 32 and len(it[2]) == 64
        ]
        seeds = [bytes([i + 50]) * 32 for i in range(16)]
        items += [
            (ed.public_key(s), b"pad-%d" % i, ed.sign(s, b"pad-%d" % i))
            for i, s in enumerate(seeds)
        ]
        assert len(items) >= 16
        exp = [verify_any(p, m, s) for p, m, s in items]
        assert exp.count(False) >= 2, "edge cases must be present"
        # the gateway path (which routes this shape through native)...
        got = _cpu_verify_batch(items)
        assert got == exp
        # ...and the native verifier DIRECTLY, so the comparison cannot
        # silently degrade to python-vs-python
        direct = native.ed25519_verify_batch(items)
        assert [bool(b) for b in direct] == exp

    def test_small_and_mixed_batches_stay_per_item(self):
        from tendermint_tpu.ops.gateway import _cpu_verify_batch

        seed = b"\x41" * 32
        small = [(ed.public_key(seed), b"s", ed.sign(seed, b"s"))]
        assert _cpu_verify_batch(small) == [True]
        # a secp-length key in the batch keeps the whole batch per-item
        mixed = small * 16 + [(b"\x02" * 33, b"m", b"\x00" * 64)]
        res = _cpu_verify_batch(mixed)
        assert res[:16] == [True] * 16 and res[16] is False
