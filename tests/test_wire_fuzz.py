"""Seeded structural fuzz of the attacker-facing JSON decode surface:
msg_from_json (consensus wire), Block/Vote/Commit from_json. Contract
under test: ANY input either decodes or raises ValueError — never any
other exception type (a KeyError/TypeError/AttributeError escaping a
decode path would crash a reactor thread instead of disconnecting the
peer). The reference gets this from go-wire's typed byte decoding; our
equivalent is codec/jsonval + per-type from_json validation.

Deterministic (seeded) so failures reproduce; prints the failing value.
"""

from __future__ import annotations

import random

import pytest

from tendermint_tpu.consensus.messages import msg_from_json, msg_to_json

SEED = 20260730


def _rand_scalar(rng):
    return rng.choice([
        None, True, False, 0, 1, -1, 5, 257, 1 << 40, 1 << 70, -(1 << 70),
        0.5, float("nan"), "", "x", "5", "ff", "zz", "ab" * 20, "ab" * 200,
        [], {}, [1, 2], b"".hex(),
    ])


def _rand_json(rng, depth=0):
    if depth >= 3 or rng.random() < 0.5:
        return _rand_scalar(rng)
    if rng.random() < 0.5:
        return [_rand_json(rng, depth + 1) for _ in range(rng.randrange(3))]
    return {
        rng.choice([
            "type", "data", "height", "round", "step", "hash", "parts",
            "block_id", "signature", "validator_index", "bits", "elems",
            "total", "proof", "index", "bytes", "votes", "pub_key",
        ]): _rand_json(rng, depth + 1)
        for _ in range(rng.randrange(4))
    }


MSG_TYPES = [
    "new_round_step", "commit_step", "proposal", "proposal_pol",
    "block_part", "vote", "has_vote", "vote_set_maj23", "vote_set_bits",
    "heartbeat",
]


def test_random_structures_decode_or_valueerror():
    rng = random.Random(SEED)
    for i in range(2000):
        obj = _rand_json(rng)
        try:
            msg_from_json(obj)
        except ValueError:
            pass
        except Exception as exc:  # noqa: BLE001 — the contract violation
            pytest.fail(f"case {i}: {type(exc).__name__}: {exc!r} on {obj!r}")


def test_random_bodies_per_message_type():
    rng = random.Random(SEED + 1)
    for i in range(2000):
        obj = {"type": rng.choice(MSG_TYPES), "data": _rand_json(rng)}
        try:
            msg_from_json(obj)
        except ValueError:
            pass
        except Exception as exc:  # noqa: BLE001
            pytest.fail(f"case {i}: {type(exc).__name__}: {exc!r} on {obj!r}")


def _valid_messages():
    """Round-trippable real messages to corrupt field-by-field."""
    msgs = [
        {"type": "new_round_step",
         "data": {"height": 5, "round": 0, "step": 1,
                  "seconds_since_start_time": 0, "last_commit_round": -1}},
        {"type": "has_vote",
         "data": {"height": 2, "round": 1, "type": 1, "index": 3}},
        {"type": "proposal_pol",
         "data": {"height": 1, "proposal_pol_round": 0,
                  "proposal_pol": {"bits": 4, "elems": "f"}}},
    ]
    return msgs


def test_single_field_corruptions_of_valid_messages():
    rng = random.Random(SEED + 2)
    for base in _valid_messages():
        decoded = msg_from_json(base)
        assert msg_from_json(msg_to_json(decoded)) is not None  # round trip
        for _ in range(300):
            obj = {"type": base["type"], "data": dict(base["data"])}
            key = rng.choice(list(obj["data"].keys()))
            obj["data"][key] = _rand_json(rng)
            try:
                msg_from_json(obj)
            except ValueError:
                pass
            except Exception as exc:  # noqa: BLE001
                pytest.fail(
                    f"{type(exc).__name__}: {exc!r} corrupting "
                    f"{base['type']}.{key} with {obj['data'][key]!r}"
                )


class _FuzzSwitch:
    """Records stop_peer_for_error instead of tearing anything down."""

    def __init__(self):
        self.stopped = []

    def stop_peer_for_error(self, peer, reason):
        self.stopped.append(reason)


class _FuzzPeer:
    node_info = None
    stream = None

    def id(self):
        return "fuzz-peer"

    def try_send(self, ch, data):
        return True

    def get(self, key):
        return None


def test_reactor_receive_paths_never_leak_exceptions():
    """Drive every reactor's receive() with random wire bytes: the ONLY
    acceptable outcomes are silent handling or stop_peer_for_error —
    an exception here would kill the p2p recv routine for that peer (the
    DoS class the bounded-decode contract exists to prevent)."""
    import json as _json

    from tendermint_tpu.p2p.pex import PEXReactor

    rng = random.Random(SEED + 4)
    peer = _FuzzPeer()

    def payloads():
        for _ in range(400):
            kind = rng.random()
            if kind < 0.2:
                yield bytes(rng.randrange(256) for _ in range(rng.randrange(40)))
            elif kind < 0.4:
                yield _json.dumps(_rand_json(rng)).encode()
            else:
                yield _json.dumps({
                    "type": rng.choice([
                        "tx", "pex_request", "pex_addrs", "block_request",
                        "block_response", "status_request", "status_response",
                        "no_block_response", 7, None,
                    ]),
                    rng.choice(["tx", "height", "block", "addrs"]):
                        _rand_json(rng),
                }).encode()

    # mempool reactor
    from tendermint_tpu.abci.apps.counter import CounterApp
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.config import test_config as _cfg
    from tendermint_tpu.mempool import Mempool
    from tendermint_tpu.mempool.reactor import MempoolReactor
    from tendermint_tpu.proxy.app_conn import AppConnMempool

    mp = Mempool(_cfg().mempool, AppConnMempool(LocalClient(CounterApp())))
    mr = MempoolReactor(_cfg().mempool, mp)
    mr.switch = _FuzzSwitch()
    for data in payloads():
        mr.receive(0x30, peer, data)

    # pex reactor
    from tendermint_tpu.p2p.addrbook import AddrBook

    px = PEXReactor(AddrBook(""))
    px.switch = _FuzzSwitch()
    for data in payloads():
        px.receive(0x00, peer, data)

    # blockchain reactor (no pool started; receive must still be safe
    # for request/status shapes — block_response needs the pool, so only
    # decode-failing payloads exercise that branch here, which is the
    # point)
    from tendermint_tpu.blockchain.reactor import BlockchainReactor
    from tendermint_tpu.blockchain.store import BlockStore
    from tendermint_tpu.libs.db import MemDB
    from tendermint_tpu.state.state import State
    from tests.test_reactors import make_genesis

    doc, _pvs = make_genesis(1)
    st = State.get_state(MemDB(), doc)
    bc = BlockchainReactor(st, None, BlockStore(MemDB()), fast_sync=False)
    bc.switch = _FuzzSwitch()
    for data in payloads():
        bc.receive(0x40, peer, data)


def test_block_and_vote_from_json_fuzz():
    from tendermint_tpu.p2p.node_info import NodeInfo
    from tendermint_tpu.types.block import Block, Commit
    from tendermint_tpu.types.vote import Vote

    rng = random.Random(SEED + 3)
    for i in range(1500):
        obj = _rand_json(rng)
        for cls in (Block, Commit, Vote, NodeInfo):
            try:
                cls.from_json(obj)
            except ValueError:
                pass
            except Exception as exc:  # noqa: BLE001
                pytest.fail(
                    f"case {i}: {cls.__name__}.from_json -> "
                    f"{type(exc).__name__}: {exc!r} on {obj!r}"
                )


def test_node_info_handshake_roundtrip_and_corruptions():
    from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
    from tendermint_tpu.p2p.node_info import NodeInfo, default_version

    info = NodeInfo(
        pub_key=gen_priv_key_ed25519(b"\x44" * 32).pub_key(),
        moniker="fuzz", network="net", version=default_version("t"),
        listen_addr="1.2.3.4:46656", channels=b"\x20\x30\x40",
        other=["a=b"],
    )
    decoded = NodeInfo.from_json(info.to_json())
    assert decoded.pub_key.raw == info.pub_key.raw
    assert decoded.channels == info.channels
    rng = random.Random(SEED + 5)
    base = info.to_json()
    for _ in range(600):
        obj = dict(base)
        obj[rng.choice(list(obj.keys()))] = _rand_json(rng)
        try:
            NodeInfo.from_json(obj)
        except ValueError:
            pass
        except Exception as exc:  # noqa: BLE001
            pytest.fail(f"{type(exc).__name__}: {exc!r} on {obj!r}")
