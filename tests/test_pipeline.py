"""Pipelined execution plane (round 14, docs/execution-pipeline.md).

Proves the tentpole contract end to end on REAL ConsensusStates:

- byte-identity: a pipelined chain (deferred apply + provisional next
  state + join-at-propose) commits byte-identical blocks — block hash,
  part-set root, app hash, txs — to a fully serial chain over the same
  deterministic workload (seeded validator key, pinned genesis + block
  times, preloaded mempool);
- the sharded parallel kvstore apply folds a block's txs across keyspace
  shards and merges deterministically: responses, state map, validator
  registry/diffs, and the committed `VersionedTree` root are all
  byte-identical to the serial per-tx loop;
- executor-thread safety: the snapshot hook and event flush now run off
  the consensus thread; a hook failure never wedges consensus, events
  still arrive post-apply and in order;
- a valset-changing block reconciles rs.validators at the join (the
  provisional set is crypto-invisible by construction);
- a FAILED deferred apply poisons the joins — consensus wedges instead
  of committing on a stale app hash (the serial design's semantics);
- traces: segments still partition the wall clock within 5% with the
  pipeline on, the deferred apply is attributed to the height it
  overlaps, and the ops/trace CLI renders the idle-vs-overlap split.
"""

from __future__ import annotations

import io
import threading
import time

from consensus_common import EventCollector, new_consensus_state, wait_for_height

from tendermint_tpu.abci.apps.kvstore import KVStoreApp, PersistentKVStoreApp
from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
from tendermint_tpu.libs.db import MemDB
from tendermint_tpu.state.state import State
from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivValidatorFS
from tendermint_tpu.types import events as tev

GENESIS_NS = 1_700_000_000_000_000_000


def _det_state(seed: bytes = b"pipeline-test"):
    """Deterministic single-validator genesis: seeded key + pinned
    genesis time, so two runs build byte-identical chains."""
    pv = PrivValidatorFS(gen_priv_key_ed25519(seed), None)
    doc = GenesisDoc(
        genesis_time_ns=GENESIS_NS,
        chain_id="pipeline_chain",
        validators=[GenesisValidator(pv.get_pub_key(), 1, "v0")],
    )
    return State.get_state(MemDB(), doc), pv


def _run_chain(
    pipeline: bool,
    n_heights: int = 4,
    txs: list[bytes] | None = None,
    app=None,
    txs_per_block: int = 0,
    hook=None,
):
    """Commit `n_heights` on a real single-validator ConsensusState and
    return (per-height fingerprints, the stopped cs)."""
    state, pv = _det_state()
    app = app if app is not None else KVStoreApp()
    cs = new_consensus_state(state, pv, app=app)
    cs.pipeline_apply = pipeline
    cs.propose_time_source = lambda h: GENESIS_NS + h * 1_000_000_000
    if txs_per_block:
        cs.config.max_block_size_txs = txs_per_block
    if hook is not None:
        cs.post_apply_hook = hook
    for tx in txs or []:
        res = cs.mempool.check_tx(tx)
        assert res is None or getattr(res, "code", 0) == 0
    blocks = EventCollector(cs.evsw, tev.EVENT_NEW_BLOCK)
    cs.start()
    try:
        assert wait_for_height(cs, n_heights + 1, timeout=30), (
            f"chain stalled at {cs.rs.height} (pipeline={pipeline})"
        )
        # NEW_BLOCK fires post-apply: waiting for the event of height n
        # also guarantees the deferred applies of 1..n completed
        assert blocks.wait_for(n_heights, timeout=30)
    finally:
        cs.stop()
    fps = {}
    for h in range(1, n_heights + 1):
        meta = cs.block_store.load_block_meta(h)
        block = cs.block_store.load_block(h)
        fps[h] = (
            meta.block_id.hash.hex(),
            meta.block_id.parts_header.hash.hex(),
            block.header.app_hash.hex(),
            tuple(tx.hex() for tx in block.data.txs),
        )
    return fps, cs


def test_pipelined_chain_byte_identical_to_serial():
    txs = [f"k{i:03d}=v{i}".encode() for i in range(60)]
    serial_fps, serial_cs = _run_chain(False, n_heights=4, txs=txs,
                                       txs_per_block=20)
    piped_fps, piped_cs = _run_chain(True, n_heights=4, txs=txs,
                                     txs_per_block=20)
    assert piped_fps == serial_fps
    # the serial run never deferred; the pipelined run deferred every
    # height and actually measured joins
    assert serial_cs.pipeline_applies == 0
    assert serial_cs.pipeline_serial_commits >= 4
    assert piped_cs.pipeline_applies >= 4
    assert piped_cs.pipeline_serial_commits == 0
    # txs actually landed (saturating the 20-tx blocks first)
    assert len(piped_fps[1][3]) == 20


def test_deferred_apply_overlaps_and_traces():
    txs = [f"t{i:03d}=v".encode() for i in range(40)]
    _, cs = _run_chain(True, n_heights=4, txs=txs, txs_per_block=10)
    traces = cs.trace.last(4)
    assert traces
    for t in traces:
        total = sum(t.segments.values())
        tol = max(0.05 * t.wall_s, 0.005)
        assert abs(total - t.wall_s) <= tol, (t.height, total, t.wall_s)
        # the consensus thread never ran apply inline
        assert "apply" not in t.segments
        if t.height > 1:
            assert "overlap_apply_s" in t.aux, (t.height, t.aux)
            assert "pipeline_join_wait_s" in t.aux, (t.height, t.aux)
    # the operator CLI renders the overlap split
    from tendermint_tpu.ops.trace import render

    out = io.StringIO()
    render([t.to_json() for t in traces], out=out)
    text = out.getvalue()
    assert "apply(H-1)" in text
    assert "join wait" in text


def test_hook_failure_never_wedges_consensus():
    calls = []

    def bad_hook(state, block):
        calls.append(block.header.height)
        raise RuntimeError("snapshot producer exploded")

    fps, cs = _run_chain(True, n_heights=3, txs=[b"a=1", b"b=2"],
                         hook=bad_hook)
    assert len(fps) == 3
    assert calls, "hook never fired from the executor"
    assert cs._apply_poisoned is None


def test_events_arrive_post_apply_in_order():
    state, pv = _det_state()
    app = KVStoreApp()
    cs = new_consensus_state(state, pv, app=app)
    cs.pipeline_apply = True
    app_heights_at_event = []
    blocks = EventCollector(cs.evsw, tev.EVENT_NEW_BLOCK)

    def on_block(data):
        # NEW_BLOCK for H must observe the app already committed at H —
        # the executor fires it after apply, never before
        app_heights_at_event.append((data.block.header.height, app.height))

    cs.evsw.add_listener_for_event("pipe-test", tev.EVENT_NEW_BLOCK, on_block)
    cs.mempool.check_tx(b"x=1")
    cs.start()
    try:
        assert blocks.wait_for(3, timeout=20)
    finally:
        cs.stop()
    heights = [d.block.header.height for d in blocks.items[:3]]
    assert heights == [1, 2, 3]
    for block_h, app_h in app_heights_at_event:
        assert app_h >= block_h, (block_h, app_h)


def test_valset_change_reconciles_at_join():
    import tempfile

    state, pv = _det_state()
    app = PersistentKVStoreApp(tempfile.mkdtemp(prefix="pipe-val-"))
    cs = new_consensus_state(state, pv, app=app)
    cs.pipeline_apply = True
    pub_hex = pv.get_pub_key().raw.hex()
    cs.mempool.check_tx(f"val:{pub_hex}/5".encode())
    cs.start()
    try:
        assert wait_for_height(cs, 4, timeout=30), (
            f"chain stalled at {cs.rs.height} after the valset change"
        )
    finally:
        cs.stop()
    assert cs.pipeline_valset_reconciles >= 1
    assert cs.state.validators.validators[0].voting_power == 5


def test_failed_apply_poisons_joins_and_wedges():
    state, pv = _det_state()

    class ExplodingApp(KVStoreApp):
        def commit(self):
            if self.height >= 1:  # height 2's commit explodes
                raise RuntimeError("app commit failure")
            return super().commit()

    cs = new_consensus_state(state, pv, app=ExplodingApp())
    cs.pipeline_apply = True
    cs.start()
    try:
        # height 1 commits; apply(2) fails on the executor; the join
        # poisons — the chain must NOT advance past height 3's start
        deadline = time.monotonic() + 8
        while time.monotonic() < deadline and cs._apply_poisoned is None:
            time.sleep(0.05)
        assert cs._apply_poisoned is not None, "apply failure never surfaced"
        wedged_at = cs.rs.height
        time.sleep(0.5)
        assert cs.rs.height == wedged_at, "consensus advanced past a failed apply"
        assert cs.block_store.height() <= wedged_at
    finally:
        cs.stop()


# -- sharded parallel apply (app-level determinism) -----------------------


def _tx_workload():
    txs = []
    for i in range(200):
        txs.append(f"key{i % 37}=value{i}".encode())  # hot keys: last-wins
    txs += [b"plainkey", b"rm:key3", b"key3=resurrected", b"rm:key11",
            b"rm:missing"]
    txs += [f"wide{i}={'x' * 50}".encode() for i in range(64)]
    return txs


def test_sharded_deliver_txs_byte_identical_to_serial():
    txs = _tx_workload()
    serial, sharded = KVStoreApp(), KVStoreApp()
    sharded.shards = 3
    sharded.shard_min_txs = 4
    r1 = [serial.deliver_tx(tx) for tx in txs]
    r2 = sharded.deliver_txs(list(txs))
    assert [r.code for r in r1] == [r.code for r in r2]
    assert sharded.sharded_batches == 1
    assert serial.state == sharded.state
    h1 = serial.commit().data
    h2 = sharded.commit().data
    assert h1 == h2, "sharded apply forked the VersionedTree root"


def test_sharded_deliver_persistent_val_txs_in_order(tmp_path):
    pub_a = gen_priv_key_ed25519(b"val-a").pub_key().raw.hex()
    pub_b = gen_priv_key_ed25519(b"val-b").pub_key().raw.hex()
    txs = [b"k1=v1", f"val:{pub_a}/3".encode(), b"k2=v2",
           f"val:{pub_b}/7".encode(), b"rm:k1",
           f"val:{pub_a}/0".encode(), b"val:junk", b"k3=v3"] * 6
    serial = PersistentKVStoreApp(str(tmp_path / "serial"))
    sharded = PersistentKVStoreApp(str(tmp_path / "sharded"))
    sharded.shards = 2
    sharded.shard_min_txs = 4
    serial.begin_block(b"", None)
    sharded.begin_block(b"", None)
    r1 = [serial.deliver_tx(tx) for tx in txs]
    r2 = sharded.deliver_txs(list(txs))
    assert [r.code for r in r1] == [r.code for r in r2]
    # validator diffs keep TX order (EndBlock payload identity)
    d1 = [(v.pub_key_json, v.power) for v in serial.end_block(1).diffs]
    d2 = [(v.pub_key_json, v.power) for v in sharded.end_block(1).diffs]
    assert d1 == d2 and len(d1) == 18
    assert serial.validators == sharded.validators
    assert serial.state == sharded.state
    assert serial.commit().data == sharded.commit().data


def test_sharded_path_below_floor_stays_serial():
    app = KVStoreApp()
    app.shards = 4
    app.shard_min_txs = 32
    app.deliver_txs([b"a=1", b"b=2"])
    assert app.sharded_batches == 0
    assert app.state == {"a": b"1", "b": b"2"}


def test_pipelined_plus_sharded_chain_matches_serial():
    """The acceptance combination: pipeline + sharded apply through real
    consensus, byte-identical to the fully serial chain."""
    txs = [f"s{i:03d}=v{i}".encode() for i in range(80)]
    serial_fps, _ = _run_chain(False, n_heights=3, txs=txs, txs_per_block=40)

    app = KVStoreApp()
    app.shards = 2
    app.shard_min_txs = 8
    piped_fps, cs = _run_chain(True, n_heights=3, txs=txs,
                               txs_per_block=40, app=app)
    assert piped_fps == serial_fps
    assert app.sharded_batches >= 2, "wide blocks never took the sharded path"


def test_join_wait_telemetry_populates():
    from tendermint_tpu.consensus.pipeline import pipeline_hists

    before = pipeline_hists()["join_wait"].count
    _, cs = _run_chain(True, n_heights=3, txs=[b"m=1"])
    assert cs.pipeline_join_wait_last >= 0.0
    assert pipeline_hists()["join_wait"].count > before
