"""Mock RPC client tests (rpc/mock.py; reference rpc/client/mock/client.go)."""

from __future__ import annotations

import pytest

from tendermint_tpu.rpc.mock import Call, MockClient, MockClientError


def test_canned_values_and_recording():
    mc = MockClient().expect("status", {"latest_block_height": 7})
    assert mc.status() == {"latest_block_height": 7}
    assert mc.call("status") == {"latest_block_height": 7}
    assert len(mc.calls_for("status")) == 2
    assert mc.calls_for("status")[0].response["latest_block_height"] == 7


def test_callable_exception_and_unknown():
    boom = RuntimeError("node down")
    mc = MockClient(responses={
        "block": lambda height: {"height": height * 2},
        "tx": boom,
    })
    assert mc.block(height=21) == {"height": 42}
    assert mc.calls_for("block")[0].params == {"height": 21}
    with pytest.raises(RuntimeError, match="node down"):
        mc.tx(hash="ab")
    assert mc.calls_for("tx")[0].error is boom
    with pytest.raises(MockClientError, match="no canned response"):
        mc.genesis()


def test_passthrough_composes_with_real_client():
    class Real:
        def call(self, method, **params):
            return {"from": "real", "method": method, **params}

    mc = MockClient(responses={"status": {"from": "mock"}}, client=Real())
    assert mc.status() == {"from": "mock"}
    assert mc.validators(height=3) == {
        "from": "real", "method": "validators", "height": 3,
    }


def test_drives_the_light_client():
    """Interface-fit proof: the light client runs against MockClient with
    callable canned responses (replacing an ad-hoc stub)."""
    from tendermint_tpu.rpc.light import LightClient
    from tests.test_light import CHAIN, _chain_with_change

    stub, old_set = _chain_with_change(old_signs_transition=True)
    mc = MockClient(responses={
        "commit": lambda height: stub.commit(height),
        "validators": lambda height=0: stub.validators(height),
    })
    lc = LightClient(mc, CHAIN, old_set.copy())
    lc.advance(3)
    assert lc.height == 3
    assert [c.method for c in mc.calls][:2] == ["commit", "commit"]
