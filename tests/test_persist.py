"""Crash-restart persistence tests (reference: test/persist/
test_failure_indices.sh + consensus/replay_test.go handshake tiers).

A node subprocess runs with FAIL_TEST_INDEX=i so the i-th fail point
(consensus finalize-commit + state apply-block crash boundaries) aborts
the process mid-commit; the restart must recover via WAL replay + ABCI
handshake and keep committing on the same chain, with the persistent
kvstore app's state intact.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _write_fast_config(home: str) -> None:
    """Speed up consensus for the subprocess (config.toml is what the CLI
    node loads)."""
    from tendermint_tpu.config import load_config
    from tendermint_tpu.config.toml import config_to_toml

    cfg = load_config(home)
    c = cfg.consensus
    c.timeout_propose = 0.3
    c.timeout_prevote = 0.05
    c.timeout_precommit = 0.05
    c.timeout_commit = 0.05
    c.skip_timeout_commit = True
    cfg.base.db_backend = "filedb"
    cfg.base.proxy_app = "persistent_kvstore"
    with open(os.path.join(home, "config.toml"), "w") as f:
        f.write(config_to_toml(cfg))


def _node_proc(home: str, rpc_port: int, fail_index: int | None):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        TENDERMINT_TPU_DISABLE="1",
        PYTHONPATH=REPO,
    )
    if fail_index is not None:
        env["FAIL_TEST_INDEX"] = str(fail_index)
    else:
        env.pop("FAIL_TEST_INDEX", None)
    return subprocess.Popen(
        [
            sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "node",
            "--rpc.laddr", f"tcp://127.0.0.1:{rpc_port}",
            "--p2p.laddr", "tcp://127.0.0.1:0",
            "--log_level", "warning",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )


def _rpc(port: int, method: str, timeout=5, **params):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps(
            {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        body = json.loads(resp.read().decode())
    if body.get("error"):
        raise RuntimeError(body["error"])
    return body["result"]


def _wait_height(port: int, h: int, deadline_s: float = 60) -> int:
    deadline = time.time() + deadline_s
    last = -1
    while time.time() < deadline:
        try:
            last = _rpc(port, "status", timeout=2)["latest_block_height"]
            if last >= h:
                return last
        except Exception:
            pass
        time.sleep(0.3)
    return last


@pytest.mark.slow
def test_crash_restart_at_every_fail_point(tmp_path):
    """One crash-recover cycle per FAIL_TEST_INDEX (the 8 fail points:
    5 in consensus finalize-commit, 3 in apply-block)."""
    home = str(tmp_path / "persist")
    subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "init",
         "--chain-id", "persist-chain"],
        check=True, capture_output=True,
        env=dict(os.environ, PYTHONPATH=REPO),
    )
    _write_fast_config(home)

    committed_value = 0
    for fail_index in range(8):
        port = _free_port()
        proc = _node_proc(home, port, fail_index)
        # wait for the crash (exit 99 from the fail point)
        deadline = time.time() + 60
        while proc.poll() is None and time.time() < deadline:
            time.sleep(0.2)
        if proc.poll() is None:
            proc.kill()
            out = proc.stdout.read().decode(errors="replace")[-2000:]
            raise AssertionError(
                f"node did not hit fail point {fail_index} in 60s:\n{out}"
            )
        assert proc.returncode == 99, (
            f"fail {fail_index}: expected crash exit 99, got {proc.returncode}: "
            + proc.stdout.read().decode(errors="replace")[-2000:]
        )

        # restart WITHOUT the fail index: must recover and keep going
        port = _free_port()
        proc = _node_proc(home, port, None)
        try:
            h = _wait_height(port, 1, 60)
            assert h >= 1, f"no recovery after fail point {fail_index} (h={h})"
            # commit a tx to prove the recovered chain is live + app is sane
            committed_value += 1
            tx = f"persist-{fail_index}={committed_value}".encode()
            res = _rpc(port, "broadcast_tx_commit", timeout=30, tx=tx.hex())
            assert res["deliver_tx"]["code"] == 0, res
            q = _rpc(
                port, "abci_query", timeout=10,
                data=f"persist-{fail_index}".encode().hex(),
            )
            assert bytes.fromhex(q["response"]["value"]) == str(committed_value).encode()
        finally:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(15)
            except subprocess.TimeoutExpired:
                proc.kill()
