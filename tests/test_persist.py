"""Crash-restart persistence tests (reference: test/persist/
test_failure_indices.sh + consensus/replay_test.go handshake tiers).

A node subprocess runs with FAIL_TEST_INDEX=i so the i-th fail point
(consensus finalize-commit + state apply-block crash boundaries) aborts
the process mid-commit; the restart must recover via WAL replay + ABCI
handshake and keep committing on the same chain, with the persistent
kvstore app's state intact.

The subprocess scaffolding (fast config, node proc, RPC poll) is shared
with the round-9 WAL torture tier: tests/consensus_common.py.
"""

from __future__ import annotations

import signal
import subprocess
import time

import pytest

from consensus_common import (
    free_port,
    init_node_home,
    node_proc,
    rpc,
    wait_height,
)


@pytest.mark.slow
def test_crash_restart_at_every_fail_point(tmp_path):
    """One crash-recover cycle per FAIL_TEST_INDEX (the 8 fail points:
    5 in consensus finalize-commit, 3 in apply-block)."""
    home = str(tmp_path / "persist")
    init_node_home(home, "persist-chain")

    committed_value = 0
    for fail_index in range(8):
        port = free_port()
        proc = node_proc(home, port, fail_index)
        # wait for the crash (exit 99 from the fail point)
        deadline = time.time() + 60
        while proc.poll() is None and time.time() < deadline:
            time.sleep(0.2)
        if proc.poll() is None:
            proc.kill()
            out = proc.stdout.read().decode(errors="replace")[-2000:]
            raise AssertionError(
                f"node did not hit fail point {fail_index} in 60s:\n{out}"
            )
        assert proc.returncode == 99, (
            f"fail {fail_index}: expected crash exit 99, got {proc.returncode}: "
            + proc.stdout.read().decode(errors="replace")[-2000:]
        )

        # restart WITHOUT the fail index: must recover and keep going
        port = free_port()
        proc = node_proc(home, port, None)
        try:
            h = wait_height(port, 1, 60)
            assert h >= 1, f"no recovery after fail point {fail_index} (h={h})"
            # commit a tx to prove the recovered chain is live + app is sane
            committed_value += 1
            tx = f"persist-{fail_index}={committed_value}".encode()
            res = rpc(port, "broadcast_tx_commit", timeout=30, tx=tx.hex())
            assert res["deliver_tx"]["code"] == 0, res
            q = rpc(
                port, "abci_query", timeout=10,
                data=f"persist-{fail_index}".encode().hex(),
            )
            assert bytes.fromhex(q["response"]["value"]) == str(committed_value).encode()
        finally:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(15)
            except subprocess.TimeoutExpired:
                proc.kill()
