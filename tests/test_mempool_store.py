"""Mempool, block store, and config tests (reference test models:
mempool/mempool_test.go, blockchain/store.go usage, config/config_test.go)."""

import os
import time

import pytest

from tendermint_tpu.abci.apps.counter import CounterApp
from tendermint_tpu.abci.apps.kvstore import KVStoreApp
from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.config import default_config, reset_test_root
from tendermint_tpu.config import test_config as _test_config
from tendermint_tpu.config.toml import load_config
from tendermint_tpu.libs.db import MemDB
from tendermint_tpu.mempool import Mempool, TxInCacheError
from tendermint_tpu.proxy.app_conn import AppConnMempool
from tendermint_tpu.types import Block, BlockID, Commit, Vote, VOTE_TYPE_PRECOMMIT


def _mk_mempool(app=None):
    cfg = _test_config().mempool
    client = LocalClient(app or CounterApp(serial=False))
    return Mempool(cfg, AppConnMempool(client))


def _tx(i: int) -> bytes:
    return i.to_bytes(8, "big")


class TestMempool:
    def test_check_tx_adds_good_txs(self):
        mp = _mk_mempool()
        for i in range(10):
            mp.check_tx(_tx(i))
        assert mp.size() == 10
        assert mp.reap(-1) == [_tx(i) for i in range(10)]
        assert mp.reap(3) == [_tx(i) for i in range(3)]

    def test_cache_rejects_duplicates(self):
        mp = _mk_mempool()
        mp.check_tx(b"hello")
        with pytest.raises(TxInCacheError):
            mp.check_tx(b"hello")
        assert mp.size() == 1

    def test_bad_tx_rejected_and_cache_evicted(self):
        app = CounterApp(serial=True)
        mp = _mk_mempool(app)
        mp.check_tx(_tx(5))  # ok: 5 >= check_count 0; check_count -> 1
        mp.check_tx(_tx(0))  # rejected: 0 < check_count 1
        assert mp.size() == 1
        assert mp.reap(-1) == [_tx(5)]
        # rejection evicted the cache entry, so resubmission is allowed
        # (not TxInCacheError) and now still fails CheckTx
        mp.check_tx(_tx(0))
        assert mp.size() == 1

    def test_update_removes_committed_and_rechecks(self):
        mp = _mk_mempool(KVStoreApp())
        for i in range(5):
            mp.check_tx(_tx(i))
        mp.lock()
        mp.update(1, [_tx(0), _tx(2)])
        mp.unlock()
        assert mp.reap(-1) == [_tx(1), _tx(3), _tx(4)]

    def test_txs_available_fires_once_per_height(self):
        mp = _mk_mempool()
        fired = []
        mp.enable_txs_available(lambda: fired.append(1))
        mp.check_tx(_tx(0))
        mp.check_tx(_tx(1))
        assert len(fired) == 1
        mp.lock()
        mp.update(1, [_tx(0)])
        mp.unlock()
        # pool still non-empty after recheck → re-notifies for next height
        assert len(fired) == 2

    def test_serial_counter_recheck_evicts_stale(self):
        """After commit advances the counter, lower-nonce txs fail recheck."""
        app = CounterApp(serial=True)
        client = LocalClient(app)
        mp = Mempool(_test_config().mempool, AppConnMempool(client))
        for i in range(3):
            mp.check_tx(_tx(i))
        assert mp.size() == 3
        # commit tx 0 and 1 through the app (same app instance)
        app.deliver_tx(_tx(0))
        app.deliver_tx(_tx(1))
        app.commit()
        mp.lock()
        mp.update(1, [_tx(0), _tx(1)])
        mp.unlock()
        assert mp.reap(-1) == [_tx(2)]

    def test_wal_appends(self, tmp_path):
        cfg = _test_config().mempool
        cfg.root_dir = str(tmp_path)
        cfg.wal_path = "data/mempool.wal"
        client = LocalClient(CounterApp(serial=False))
        mp = Mempool(cfg, AppConnMempool(client))
        mp.init_wal()
        mp.check_tx(b"abc")
        mp.close_wal()
        with open(cfg.wal_dir()) as f:
            assert f.read().strip() == b"abc".hex()


class TestSigPreVerification:
    """Mempool batch signature gate (BASELINE config 5): a CheckTx
    burst's signatures verify in ONE gateway batch before app dispatch;
    bad-sig txs never reach the app (ref mempool/mempool.go:166-205
    dispatches everything and lets the app verify per tx)."""

    def _mk(self, max_wait_s=0.01):
        from tendermint_tpu.abci.apps.signedkv import SignedKVStoreApp, parse_sig_tx
        from tendermint_tpu.mempool.mempool import SigBatcher
        from tendermint_tpu.ops.gateway import Verifier

        app = SignedKVStoreApp(verify_in_app=False)
        verifier = Verifier(min_tpu_batch=4, use_tpu=True)
        # warm the kernel buckets OFF the drain clock (a cold .jax_cache
        # compile takes minutes; the batcher thread would sit inside it)
        warm = [self._sig_item(i) for i in range(12)]
        verifier.verify_batch(warm)
        self._warm_stats = verifier.stats()
        batcher = SigBatcher(verifier, parse_sig_tx, max_wait_s=max_wait_s)
        cfg = _test_config().mempool
        mp = Mempool(cfg, AppConnMempool(LocalClient(app)), sig_batcher=batcher)
        return mp, app, verifier, batcher

    @staticmethod
    def _sig_item(i: int):
        from tendermint_tpu.abci.apps.signedkv import parse_sig_tx

        return parse_sig_tx(TestSigPreVerification._signed(i))

    @staticmethod
    def _signed(i: int, forge: bool = False) -> bytes:
        from tendermint_tpu.abci.apps.signedkv import make_sig_tx

        seed = bytes([i % 7 + 1]) * 32
        tx = make_sig_tx(seed, b"k%d=v%d" % (i, i))
        if forge:
            tx = tx[:40] + bytes([tx[40] ^ 1]) + tx[41:]
        return tx

    def _drain(self, mp, expect_size, timeout=60.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            mp.flush_app_conn()
            if mp.size() == expect_size:
                return
            time.sleep(0.01)
        assert mp.size() == expect_size, mp.size()

    def test_bad_sigs_never_reach_the_app(self):
        mp, app, verifier, batcher = self._mk()
        results = {}
        for i in range(12):
            tx = self._signed(i, forge=(i % 3 == 0))
            mp.check_tx(tx, cb=lambda res, i=i: results.__setitem__(i, res.code))
        self._drain(mp, 8)  # 4 of 12 forged
        batcher.stop()
        assert app.check_tx_calls == 8  # forged txs cost no app round-trip
        assert {i for i, c in results.items() if c != 0} == {0, 3, 6, 9}
        # signatures rode the gateway in batches, not one-at-a-time
        st = verifier.stats()
        d_sigs = (st["tpu_sigs"] + st["cpu_sigs"]
                  - self._warm_stats["tpu_sigs"] - self._warm_stats["cpu_sigs"])
        d_batches = st["tpu_batches"] - self._warm_stats["tpu_batches"]
        assert d_sigs >= 12
        assert d_batches <= 4

    def test_bad_sig_tx_can_be_resubmitted(self):
        import threading

        mp, app, _, batcher = self._mk()
        bad = self._signed(1, forge=True)
        rejected = threading.Event()
        mp.check_tx(bad, cb=lambda res: rejected.set())
        assert rejected.wait(60), "batch gate never rejected the forged tx"
        # cache slot released on rejection (mempool/mempool.go:231)
        rejected2 = threading.Event()
        mp.check_tx(bad, cb=lambda res: rejected2.set())
        assert rejected2.wait(60)
        assert mp.size() == 0
        batcher.stop()

    def test_unsigned_txs_bypass_the_gate(self):
        from tendermint_tpu.abci.types import CODE_UNAUTHORIZED

        mp, app, _, batcher = self._mk()
        results = []
        mp.check_tx(b"short", cb=lambda res: results.append(res.code))
        self._drain(mp, 0)
        batcher.stop()
        assert app.check_tx_calls == 1  # the APP judged it (malformed)
        assert results == [CODE_UNAUTHORIZED]

    def test_saturated_gate_refuses_retriably(self):
        """A flood beyond the gate's bounded backlog gets retriable
        refusals (cache slot freed), never an unbounded in-memory queue —
        the same end-to-end-bound rule as the consensus peer ingress."""
        import threading

        from tendermint_tpu.abci.apps.signedkv import SignedKVStoreApp, parse_sig_tx
        from tendermint_tpu.abci.types import CODE_UNAUTHORIZED
        from tendermint_tpu.mempool.mempool import SigBatcher

        release = threading.Event()

        class SlowVerifier:
            def verify_batch(self, items):
                release.wait(30)
                return [True] * len(items)

            def verify_batch_async(self, items):
                # the real gateway contract (round-1 pipelined gate):
                # enqueue now, block in the resolver
                return lambda: self.verify_batch(items)

        batcher = SigBatcher(SlowVerifier(), parse_sig_tx,
                             max_batch=1, max_wait_s=0.001, max_backlog=2)
        app = SignedKVStoreApp(verify_in_app=False)
        cfg = _test_config().mempool
        mp = Mempool(cfg, AppConnMempool(LocalClient(app)), sig_batcher=batcher)

        results: dict = {}
        sent = []
        for i in range(8):
            tx = self._signed(i + 40)
            sent.append(tx)
            mp.check_tx(tx, cb=lambda res, i=i: results.__setitem__(i, res))
        assert batcher.dropped > 0  # the flood overflowed the bound
        saturated = [i for i, r in results.items()
                     if r.code == CODE_UNAUTHORIZED and "saturated" in r.log]
        assert saturated, results
        release.set()
        # a refused tx is retriable once the gate drains (cache slot freed)
        self._drain(mp, 8 - len(saturated))
        mp.check_tx(sent[saturated[0]])
        self._drain(mp, 8 - len(saturated) + 1)
        batcher.stop()

    def test_deliver_tx_always_verifies(self):
        """The gate is an optimization, not the security boundary: a
        forged tx arriving in a BLOCK (bypassing this node's mempool)
        dies in DeliverTx."""
        from tendermint_tpu.abci.apps.signedkv import SignedKVStoreApp

        app = SignedKVStoreApp(verify_in_app=False)
        good = self._signed(2)
        assert app.deliver_tx(good).code == 0
        assert app.deliver_tx(self._signed(3, forge=True)).code != 0
        assert app.query(b"k2").value == b"v2"


def _make_block_with_commit(height, chain_id="test-store"):
    from tendermint_tpu.types.block import empty_commit

    block, parts = Block.make_block(
        height=height,
        chain_id=chain_id,
        txs=[b"tx-%d" % i for i in range(3)],
        commit=empty_commit(),
        prev_block_id=BlockID(),
        val_hash=b"",
        app_hash=b"",
        part_size=64 * 1024,
        time_ns=time.time_ns(),
    )
    commit = Commit(BlockID(block.hash(), parts.header()), [])
    return block, parts, commit


class TestBlockStore:
    def test_save_load_roundtrip(self):
        store = BlockStore(MemDB())
        assert store.height() == 0
        block, parts, seen = _make_block_with_commit(1)
        store.save_block(block, parts, seen)
        assert store.height() == 1

        loaded = store.load_block(1)
        assert loaded is not None
        assert loaded.hash() == block.hash()
        meta = store.load_block_meta(1)
        assert meta.block_id.hash == block.hash()
        part = store.load_block_part(1, 0)
        assert part.bytes_ == parts.get_part(0).bytes_
        sc = store.load_seen_commit(1)
        assert sc.block_id.hash == block.hash()
        # canonical commit for height 0 is block 1's LastCommit
        assert store.load_block_commit(0) is not None

    def test_noncontiguous_save_rejected(self):
        store = BlockStore(MemDB())
        block, parts, seen = _make_block_with_commit(5)
        with pytest.raises(ValueError):
            store.save_block(block, parts, seen)

    def test_missing_heights_return_none(self):
        store = BlockStore(MemDB())
        assert store.load_block(1) is None
        assert store.load_block_meta(1) is None
        assert store.load_seen_commit(1) is None


class TestConfig:
    def test_timeout_schedule(self):
        c = default_config().consensus
        assert c.propose(0) == 3.0
        assert c.propose(2) == 4.0
        assert c.prevote(1) == 1.5
        assert c.commit(10.0, 9.5) == pytest.approx(0.5)
        assert c.commit(100.0, 9.5) == 0.0

    def test_reset_test_root_and_load(self, tmp_path):
        root = str(tmp_path / "node1")
        cfg = reset_test_root(root)
        assert os.path.exists(os.path.join(root, "config.toml"))
        assert os.path.exists(cfg.base.genesis_file())
        assert os.path.exists(cfg.base.priv_validator_file())

        loaded = load_config(root)
        assert loaded.base.chain_id == "tendermint_test"
        assert loaded.consensus.skip_timeout_commit is True
        assert loaded.consensus.timeout_propose == pytest.approx(0.1)

        from tendermint_tpu.types import GenesisDoc, PrivValidatorFS

        doc = GenesisDoc.from_file(cfg.base.genesis_file())
        pv = PrivValidatorFS.load(cfg.base.priv_validator_file())
        assert doc.validators[0].pub_key == pv.get_pub_key()
