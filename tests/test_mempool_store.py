"""Mempool, block store, and config tests (reference test models:
mempool/mempool_test.go, blockchain/store.go usage, config/config_test.go)."""

import os
import time

import pytest

from tendermint_tpu.abci.apps.counter import CounterApp
from tendermint_tpu.abci.apps.kvstore import KVStoreApp
from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.config import default_config, reset_test_root
from tendermint_tpu.config import test_config as _test_config
from tendermint_tpu.config.toml import load_config
from tendermint_tpu.libs.db import MemDB
from tendermint_tpu.mempool import Mempool, TxInCacheError
from tendermint_tpu.proxy.app_conn import AppConnMempool
from tendermint_tpu.types import Block, BlockID, Commit, Vote, VOTE_TYPE_PRECOMMIT


def _mk_mempool(app=None):
    cfg = _test_config().mempool
    client = LocalClient(app or CounterApp(serial=False))
    return Mempool(cfg, AppConnMempool(client))


def _tx(i: int) -> bytes:
    return i.to_bytes(8, "big")


class TestMempool:
    def test_check_tx_adds_good_txs(self):
        mp = _mk_mempool()
        for i in range(10):
            mp.check_tx(_tx(i))
        assert mp.size() == 10
        assert mp.reap(-1) == [_tx(i) for i in range(10)]
        assert mp.reap(3) == [_tx(i) for i in range(3)]

    def test_cache_rejects_duplicates(self):
        mp = _mk_mempool()
        mp.check_tx(b"hello")
        with pytest.raises(TxInCacheError):
            mp.check_tx(b"hello")
        assert mp.size() == 1

    def test_bad_tx_rejected_and_cache_evicted(self):
        app = CounterApp(serial=True)
        mp = _mk_mempool(app)
        mp.check_tx(_tx(5))  # ok: 5 >= check_count 0; check_count -> 1
        mp.check_tx(_tx(0))  # rejected: 0 < check_count 1
        assert mp.size() == 1
        assert mp.reap(-1) == [_tx(5)]
        # rejection evicted the cache entry, so resubmission is allowed
        # (not TxInCacheError) and now still fails CheckTx
        mp.check_tx(_tx(0))
        assert mp.size() == 1

    def test_update_removes_committed_and_rechecks(self):
        mp = _mk_mempool(KVStoreApp())
        for i in range(5):
            mp.check_tx(_tx(i))
        mp.lock()
        mp.update(1, [_tx(0), _tx(2)])
        mp.unlock()
        assert mp.reap(-1) == [_tx(1), _tx(3), _tx(4)]

    def test_txs_available_fires_once_per_height(self):
        mp = _mk_mempool()
        fired = []
        mp.enable_txs_available(lambda: fired.append(1))
        mp.check_tx(_tx(0))
        mp.check_tx(_tx(1))
        assert len(fired) == 1
        mp.lock()
        mp.update(1, [_tx(0)])
        mp.unlock()
        # pool still non-empty after recheck → re-notifies for next height
        assert len(fired) == 2

    def test_serial_counter_recheck_evicts_stale(self):
        """After commit advances the counter, lower-nonce txs fail recheck."""
        app = CounterApp(serial=True)
        client = LocalClient(app)
        mp = Mempool(_test_config().mempool, AppConnMempool(client))
        for i in range(3):
            mp.check_tx(_tx(i))
        assert mp.size() == 3
        # commit tx 0 and 1 through the app (same app instance)
        app.deliver_tx(_tx(0))
        app.deliver_tx(_tx(1))
        app.commit()
        mp.lock()
        mp.update(1, [_tx(0), _tx(1)])
        mp.unlock()
        assert mp.reap(-1) == [_tx(2)]

    def test_wal_appends(self, tmp_path):
        cfg = _test_config().mempool
        cfg.root_dir = str(tmp_path)
        cfg.wal_path = "data/mempool.wal"
        client = LocalClient(CounterApp(serial=False))
        mp = Mempool(cfg, AppConnMempool(client))
        mp.init_wal()
        mp.check_tx(b"abc")
        mp.close_wal()
        with open(cfg.wal_dir()) as f:
            assert f.read().strip() == b"abc".hex()


def _make_block_with_commit(height, chain_id="test-store"):
    from tendermint_tpu.types.block import empty_commit

    block, parts = Block.make_block(
        height=height,
        chain_id=chain_id,
        txs=[b"tx-%d" % i for i in range(3)],
        commit=empty_commit(),
        prev_block_id=BlockID(),
        val_hash=b"",
        app_hash=b"",
        part_size=64 * 1024,
        time_ns=time.time_ns(),
    )
    commit = Commit(BlockID(block.hash(), parts.header()), [])
    return block, parts, commit


class TestBlockStore:
    def test_save_load_roundtrip(self):
        store = BlockStore(MemDB())
        assert store.height() == 0
        block, parts, seen = _make_block_with_commit(1)
        store.save_block(block, parts, seen)
        assert store.height() == 1

        loaded = store.load_block(1)
        assert loaded is not None
        assert loaded.hash() == block.hash()
        meta = store.load_block_meta(1)
        assert meta.block_id.hash == block.hash()
        part = store.load_block_part(1, 0)
        assert part.bytes_ == parts.get_part(0).bytes_
        sc = store.load_seen_commit(1)
        assert sc.block_id.hash == block.hash()
        # canonical commit for height 0 is block 1's LastCommit
        assert store.load_block_commit(0) is not None

    def test_noncontiguous_save_rejected(self):
        store = BlockStore(MemDB())
        block, parts, seen = _make_block_with_commit(5)
        with pytest.raises(ValueError):
            store.save_block(block, parts, seen)

    def test_missing_heights_return_none(self):
        store = BlockStore(MemDB())
        assert store.load_block(1) is None
        assert store.load_block_meta(1) is None
        assert store.load_seen_commit(1) is None


class TestConfig:
    def test_timeout_schedule(self):
        c = default_config().consensus
        assert c.propose(0) == 3.0
        assert c.propose(2) == 4.0
        assert c.prevote(1) == 1.5
        assert c.commit(10.0, 9.5) == pytest.approx(0.5)
        assert c.commit(100.0, 9.5) == 0.0

    def test_reset_test_root_and_load(self, tmp_path):
        root = str(tmp_path / "node1")
        cfg = reset_test_root(root)
        assert os.path.exists(os.path.join(root, "config.toml"))
        assert os.path.exists(cfg.base.genesis_file())
        assert os.path.exists(cfg.base.priv_validator_file())

        loaded = load_config(root)
        assert loaded.base.chain_id == "tendermint_test"
        assert loaded.consensus.skip_timeout_commit is True
        assert loaded.consensus.timeout_propose == pytest.approx(0.1)

        from tendermint_tpu.types import GenesisDoc, PrivValidatorFS

        doc = GenesisDoc.from_file(cfg.base.genesis_file())
        pv = PrivValidatorFS.load(cfg.base.priv_validator_file())
        assert doc.validators[0].pub_key == pv.get_pub_key()
