"""Duplicate-vote evidence tests (beyond reference: v0.11 logs
conflicting votes and punts, consensus/state.go:1438-1447; here the
byzantine drill can assert the recorded pair — VERDICT r4 #9)."""

from __future__ import annotations

import pytest

from tendermint_tpu.types import BlockID, PartSetHeader
from tendermint_tpu.types.evidence import (
    DuplicateVoteEvidence,
    EvidencePool,
)
from tendermint_tpu.types.vote import VOTE_TYPE_PREVOTE, Vote
from tests.test_types import make_val_set

BLOCK_A = BlockID(b"\xaa" * 20, PartSetHeader(2, b"\xbb" * 20))
BLOCK_B = BlockID(b"\xcc" * 20, PartSetHeader(2, b"\xdd" * 20))


def _byz_signed_vote(priv, vs, height, round_, block_id, chain_id="test-chain"):
    """Sign bypassing the PrivValidatorFS double-sign guard (which
    correctly refuses the second conflicting vote — a real byzantine
    signer uses the raw key, like test_byzantine.ByzantinePrivValidator)."""
    idx, _ = vs.get_by_address(priv.get_address())
    vote = Vote(
        validator_address=priv.get_address(),
        validator_index=idx,
        height=height,
        round_=round_,
        type_=VOTE_TYPE_PREVOTE,
        block_id=block_id,
    )
    return vote.with_signature(priv.priv_key.sign(vote.sign_bytes(chain_id)))


def _conflicting_pair(priv, vs, height=1, round_=0, chain_id="test-chain"):
    va = _byz_signed_vote(priv, vs, height, round_, BLOCK_A, chain_id)
    vb = _byz_signed_vote(priv, vs, height, round_, BLOCK_B, chain_id)
    return va, vb


class TestDuplicateVoteEvidence:
    def test_valid_pair_validates(self):
        vs, privs = make_val_set(4)
        va, vb = _conflicting_pair(privs[0], vs)
        ev = DuplicateVoteEvidence.new(privs[0].get_pub_key(), va, vb)
        ev.validate("test-chain")  # no raise
        assert ev.address == privs[0].get_address()
        # canonical order: same hash regardless of construction order
        ev2 = DuplicateVoteEvidence.new(privs[0].get_pub_key(), vb, va)
        assert ev.hash() == ev2.hash()

    def test_agreeing_votes_rejected(self):
        vs, privs = make_val_set(4)
        va = _byz_signed_vote(privs[0], vs, 1, 0, BLOCK_A)
        ev = DuplicateVoteEvidence.new(privs[0].get_pub_key(), va, va)
        with pytest.raises(Exception, match="no conflict"):
            ev.validate("test-chain")

    def test_wrong_pubkey_rejected(self):
        vs, privs = make_val_set(4)
        va, vb = _conflicting_pair(privs[0], vs)
        ev = DuplicateVoteEvidence.new(privs[1].get_pub_key(), va, vb)
        with pytest.raises(Exception, match="does not match"):
            ev.validate("test-chain")

    def test_forged_signature_rejected(self):
        vs, privs = make_val_set(4)
        va, vb = _conflicting_pair(privs[0], vs)
        from tendermint_tpu.crypto.keys import SignatureEd25519
        from dataclasses import replace

        vb = replace(vb, signature=SignatureEd25519(b"\x01" * 64))
        ev = DuplicateVoteEvidence.new(privs[0].get_pub_key(), va, vb)
        with pytest.raises(Exception, match="invalid signature"):
            ev.validate("test-chain")

    def test_wrong_chain_id_rejected(self):
        vs, privs = make_val_set(4)
        va, vb = _conflicting_pair(privs[0], vs)
        ev = DuplicateVoteEvidence.new(privs[0].get_pub_key(), va, vb)
        with pytest.raises(Exception, match="invalid signature"):
            ev.validate("other-chain")


class TestEvidencePool:
    def test_add_dedup_and_invalid_dropped(self):
        vs, privs = make_val_set(4)
        pool = EvidencePool()
        va, vb = _conflicting_pair(privs[0], vs)
        ev = DuplicateVoteEvidence.new(privs[0].get_pub_key(), va, vb)
        assert pool.add(ev, "test-chain")
        assert not pool.add(ev, "test-chain")  # dedup
        # arrival-order-swapped pair is the SAME evidence
        ev2 = DuplicateVoteEvidence.new(privs[0].get_pub_key(), vb, va)
        assert not pool.add(ev2, "test-chain")
        # invalid evidence silently refused
        bad = DuplicateVoteEvidence.new(privs[1].get_pub_key(), va, vb)
        assert not pool.add(bad, "test-chain")
        assert pool.size() == 1

    def test_bounded(self):
        vs, privs = make_val_set(4)
        pool = EvidencePool(max_size=2)
        for r in range(3):
            va, vb = _conflicting_pair(privs[0], vs, round_=r)
            assert pool.add(
                DuplicateVoteEvidence.new(privs[0].get_pub_key(), va, vb),
                "test-chain",
            )
        assert pool.size() == 2  # oldest evicted


def test_byzantine_double_vote_recorded_and_served():
    """The byzantine drill's assertion (VERDICT r4 #9): a validator's
    conflicting prevotes arriving at a live ConsensusState are detected
    (the same ConflictingVotesError site the reference logs-and-punts
    at, state.go:1438-1447), validated against the validator's real key,
    recorded in the pool, and served by the `evidence` RPC route."""
    from tests.test_reactors import make_genesis, make_node

    doc, pvs = make_genesis(2)
    node = make_node(doc, pvs[0])
    cs = node.cs
    vs = cs.rs.validators
    # the OTHER validator double-signs height 1 prevotes
    byz = pvs[1]
    va, vb = _conflicting_pair(byz, vs, chain_id=doc.chain_id)
    fired: list = []
    if cs.evsw is not None:
        from tendermint_tpu.types import events as tev

        cs.evsw.add_listener_for_event(
            "ev-test", tev.EVENT_EVIDENCE, fired.append
        )
    cs.try_add_vote(va, "peer1")
    cs.try_add_vote(vb, "peer1")
    assert cs.evidence_pool.size() == 1
    assert fired and fired[0]["type"] == "duplicate_vote"
    ev = cs.evidence_pool.list()[0]
    assert ev.address == byz.get_address()
    assert {ev.vote_a.block_id.key(), ev.vote_b.block_id.key()} == {
        BLOCK_A.key(), BLOCK_B.key()
    }

    # the RPC route serves it
    from tendermint_tpu.rpc.core.handlers import evidence as evidence_route

    class _Ctx:
        consensus_state = cs

    rep = evidence_route(_Ctx())
    assert rep["count"] == 1
    assert rep["evidence"][0]["validator_address"] == byz.get_address().hex().upper()
    assert rep["evidence"][0]["type"] == "duplicate_vote"


# -- round 12: evidence COMMITS — the block-embedding path --------------------


class TestEvidenceData:
    def _section(self, privs, vs, rounds=(0,), chain_id="test-chain"):
        from tendermint_tpu.types.evidence import EvidenceData

        evs = []
        for r in rounds:
            va, vb = _conflicting_pair(privs[0], vs, round_=r, chain_id=chain_id)
            evs.append(DuplicateVoteEvidence.new(privs[0].get_pub_key(), va, vb))
        return EvidenceData(evs)

    def test_hash_empty_and_roundtrips(self):
        from tendermint_tpu.codec.binary import Decoder, Encoder
        from tendermint_tpu.types.evidence import EvidenceData

        vs, privs = make_val_set(4)
        assert EvidenceData().hash() == b""
        data = self._section(privs, vs, rounds=(0, 1))
        assert len(data.hash()) == 20
        e = Encoder()
        data.encode(e)
        back = EvidenceData.decode(Decoder(e.buf()))
        assert back.hash() == data.hash()
        assert EvidenceData.from_json(data.to_json()).hash() == data.hash()

    def test_validate_rejections(self):
        from tendermint_tpu.types.evidence import (
            MAX_EVIDENCE_PER_BLOCK,
            EvidenceData,
            EvidenceError,
        )

        vs, privs = make_val_set(4)
        good = self._section(privs, vs)
        good.validate("test-chain", 2, vs)  # no raise
        # same-height (or future) evidence refused
        with pytest.raises(EvidenceError, match="outside"):
            good.validate("test-chain", 1, vs)
        # duplicate piece in one block refused
        dup = EvidenceData(good.evidence * 2)
        with pytest.raises(EvidenceError, match="duplicate"):
            dup.validate("test-chain", 2, vs)
        # a signer outside the validator set refused (make_val_set is
        # seed-deterministic, so build a disjoint set explicitly)
        from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
        from tendermint_tpu.types import PrivValidatorFS
        from tendermint_tpu.types.validator import Validator
        from tendermint_tpu.types.validator_set import ValidatorSet

        other_vs = ValidatorSet(
            [
                Validator.new(
                    PrivValidatorFS(
                        gen_priv_key_ed25519(f"other-{i}".encode()), None
                    ).get_pub_key(),
                    10,
                )
                for i in range(4)
            ]
        )
        with pytest.raises(EvidenceError, match="not in the set"):
            good.validate("test-chain", 2, other_vs)
        # wrong chain (signatures don't bind) refused
        with pytest.raises(EvidenceError, match="invalid signature"):
            good.validate("other-chain", 2, vs)
        # non-canonical vote order refused (it would hash differently)
        va, vb = _conflicting_pair(privs[0], vs)
        if vb.block_id.key() < va.block_id.key():
            va, vb = vb, va
        swapped = DuplicateVoteEvidence(privs[0].get_pub_key(), vb, va)
        with pytest.raises(EvidenceError, match="canonical"):
            EvidenceData([swapped]).validate("test-chain", 2, vs)
        # oversized section refused
        big = EvidenceData(
            self._section(privs, vs, rounds=range(MAX_EVIDENCE_PER_BLOCK + 1)).evidence
        )
        with pytest.raises(EvidenceError, match="too much"):
            big.validate("test-chain", 2, vs)

    def test_block_carries_evidence_and_validates(self):
        """A devchain-committed block embeds the section; validate_block
        accepts the honest embedding and refuses a tampered one."""
        from tendermint_tpu import state as _sm  # noqa: F401
        from tendermint_tpu.abci.apps.kvstore import KVStoreApp
        from tendermint_tpu.state import execution as sm
        from tendermint_tpu.statesync.devchain import DevChain
        from tendermint_tpu.types.evidence import EvidenceData

        chain = DevChain(KVStoreApp())
        chain.build(2)
        vs = chain.state.validators
        priv = chain.pv
        va, vb = _conflicting_pair(
            priv, vs, height=1, chain_id=chain.state.chain_id
        )
        ev = DuplicateVoteEvidence.new(priv.get_pub_key(), va, vb)
        state_before = chain.state.copy()
        block = chain.commit_block(
            txs=[b"k=v"], evidence=EvidenceData([ev])
        )
        assert block.evidence.evidence and block.header.evidence_hash
        # the stored block round-trips with its evidence intact
        stored = chain.block_store.load_block(block.header.height)
        assert stored.header.evidence_hash == block.header.evidence_hash
        assert stored.evidence.evidence[0].hash() == ev.hash()
        sm.validate_block(state_before, stored)  # honest: no raise
        # tampered: strip the section but keep the header claim
        stripped = type(block)(
            stored.header, stored.data, stored.last_commit
        )
        with pytest.raises(sm.InvalidBlockError, match="evidence"):
            sm.validate_block(state_before, stripped)

    def test_header_hash_unchanged_without_evidence(self):
        """The Evidence map key only exists for non-empty sections: an
        evidence-free header hashes byte-identically to the pre-round-12
        format (cross-version fingerprint stability)."""
        from tendermint_tpu.merkle.simple import simple_hash_from_map
        from tendermint_tpu.types.block import Header
        from tendermint_tpu.codec.binary import Encoder

        h = Header(
            chain_id="c", height=3, time_ns=7, num_txs=0,
            last_commit_hash=b"\x01" * 20, data_hash=b"\x02" * 20,
            validators_hash=b"\x03" * 20, app_hash=b"\x04" * 20,
        )
        e = Encoder()
        h.last_block_id.encode(e)
        legacy = simple_hash_from_map(
            {
                "ChainID": b"c",
                "Height": Encoder().write_varint(3).buf(),
                "Time": Encoder().write_time_ns(7).buf(),
                "NumTxs": Encoder().write_varint(0).buf(),
                "LastBlockID": e.buf(),
                "LastCommit": b"\x01" * 20,
                "Data": b"\x02" * 20,
                "Validators": b"\x03" * 20,
                "App": b"\x04" * 20,
            }
        )
        assert h.hash() == legacy
        h.evidence_hash = b"\x05" * 20
        assert h.hash() != legacy


class TestEvidencePoolCommitTracking:
    def test_pending_filters_and_mark_committed(self):
        vs, privs = make_val_set(4)
        pool = EvidencePool()
        va, vb = _conflicting_pair(privs[0], vs, height=5)
        ev = DuplicateVoteEvidence.new(privs[0].get_pub_key(), va, vb)
        assert pool.add(ev, "test-chain")
        # height gating: only strictly-older evidence is proposable
        assert pool.pending(before_height=5) == []
        assert pool.pending(before_height=6) == [ev]
        pool.mark_committed([ev])
        assert pool.pending(before_height=6) == []
        assert pool.committed_count() == 1
        # committed evidence never re-enters the pending set
        assert not pool.add(ev, "test-chain")
        assert pool.size() == 1

    def test_mark_committed_adopts_unknown_pieces(self):
        vs, privs = make_val_set(4)
        pool = EvidencePool()
        va, vb = _conflicting_pair(privs[0], vs, height=2)
        ev = DuplicateVoteEvidence.new(privs[0].get_pub_key(), va, vb)
        pool.mark_committed([ev])  # this node never detected it itself
        assert pool.size() == 1 and pool.committed_count() == 1
        assert pool.pending(before_height=100) == []
