"""Duplicate-vote evidence tests (beyond reference: v0.11 logs
conflicting votes and punts, consensus/state.go:1438-1447; here the
byzantine drill can assert the recorded pair — VERDICT r4 #9)."""

from __future__ import annotations

import pytest

from tendermint_tpu.types import BlockID, PartSetHeader
from tendermint_tpu.types.evidence import (
    DuplicateVoteEvidence,
    EvidencePool,
)
from tendermint_tpu.types.vote import VOTE_TYPE_PREVOTE, Vote
from tests.test_types import make_val_set

BLOCK_A = BlockID(b"\xaa" * 20, PartSetHeader(2, b"\xbb" * 20))
BLOCK_B = BlockID(b"\xcc" * 20, PartSetHeader(2, b"\xdd" * 20))


def _byz_signed_vote(priv, vs, height, round_, block_id, chain_id="test-chain"):
    """Sign bypassing the PrivValidatorFS double-sign guard (which
    correctly refuses the second conflicting vote — a real byzantine
    signer uses the raw key, like test_byzantine.ByzantinePrivValidator)."""
    idx, _ = vs.get_by_address(priv.get_address())
    vote = Vote(
        validator_address=priv.get_address(),
        validator_index=idx,
        height=height,
        round_=round_,
        type_=VOTE_TYPE_PREVOTE,
        block_id=block_id,
    )
    return vote.with_signature(priv.priv_key.sign(vote.sign_bytes(chain_id)))


def _conflicting_pair(priv, vs, height=1, round_=0, chain_id="test-chain"):
    va = _byz_signed_vote(priv, vs, height, round_, BLOCK_A, chain_id)
    vb = _byz_signed_vote(priv, vs, height, round_, BLOCK_B, chain_id)
    return va, vb


class TestDuplicateVoteEvidence:
    def test_valid_pair_validates(self):
        vs, privs = make_val_set(4)
        va, vb = _conflicting_pair(privs[0], vs)
        ev = DuplicateVoteEvidence.new(privs[0].get_pub_key(), va, vb)
        ev.validate("test-chain")  # no raise
        assert ev.address == privs[0].get_address()
        # canonical order: same hash regardless of construction order
        ev2 = DuplicateVoteEvidence.new(privs[0].get_pub_key(), vb, va)
        assert ev.hash() == ev2.hash()

    def test_agreeing_votes_rejected(self):
        vs, privs = make_val_set(4)
        va = _byz_signed_vote(privs[0], vs, 1, 0, BLOCK_A)
        ev = DuplicateVoteEvidence.new(privs[0].get_pub_key(), va, va)
        with pytest.raises(Exception, match="no conflict"):
            ev.validate("test-chain")

    def test_wrong_pubkey_rejected(self):
        vs, privs = make_val_set(4)
        va, vb = _conflicting_pair(privs[0], vs)
        ev = DuplicateVoteEvidence.new(privs[1].get_pub_key(), va, vb)
        with pytest.raises(Exception, match="does not match"):
            ev.validate("test-chain")

    def test_forged_signature_rejected(self):
        vs, privs = make_val_set(4)
        va, vb = _conflicting_pair(privs[0], vs)
        from tendermint_tpu.crypto.keys import SignatureEd25519
        from dataclasses import replace

        vb = replace(vb, signature=SignatureEd25519(b"\x01" * 64))
        ev = DuplicateVoteEvidence.new(privs[0].get_pub_key(), va, vb)
        with pytest.raises(Exception, match="invalid signature"):
            ev.validate("test-chain")

    def test_wrong_chain_id_rejected(self):
        vs, privs = make_val_set(4)
        va, vb = _conflicting_pair(privs[0], vs)
        ev = DuplicateVoteEvidence.new(privs[0].get_pub_key(), va, vb)
        with pytest.raises(Exception, match="invalid signature"):
            ev.validate("other-chain")


class TestEvidencePool:
    def test_add_dedup_and_invalid_dropped(self):
        vs, privs = make_val_set(4)
        pool = EvidencePool()
        va, vb = _conflicting_pair(privs[0], vs)
        ev = DuplicateVoteEvidence.new(privs[0].get_pub_key(), va, vb)
        assert pool.add(ev, "test-chain")
        assert not pool.add(ev, "test-chain")  # dedup
        # arrival-order-swapped pair is the SAME evidence
        ev2 = DuplicateVoteEvidence.new(privs[0].get_pub_key(), vb, va)
        assert not pool.add(ev2, "test-chain")
        # invalid evidence silently refused
        bad = DuplicateVoteEvidence.new(privs[1].get_pub_key(), va, vb)
        assert not pool.add(bad, "test-chain")
        assert pool.size() == 1

    def test_bounded(self):
        vs, privs = make_val_set(4)
        pool = EvidencePool(max_size=2)
        for r in range(3):
            va, vb = _conflicting_pair(privs[0], vs, round_=r)
            assert pool.add(
                DuplicateVoteEvidence.new(privs[0].get_pub_key(), va, vb),
                "test-chain",
            )
        assert pool.size() == 2  # oldest evicted


def test_byzantine_double_vote_recorded_and_served():
    """The byzantine drill's assertion (VERDICT r4 #9): a validator's
    conflicting prevotes arriving at a live ConsensusState are detected
    (the same ConflictingVotesError site the reference logs-and-punts
    at, state.go:1438-1447), validated against the validator's real key,
    recorded in the pool, and served by the `evidence` RPC route."""
    from tests.test_reactors import make_genesis, make_node

    doc, pvs = make_genesis(2)
    node = make_node(doc, pvs[0])
    cs = node.cs
    vs = cs.rs.validators
    # the OTHER validator double-signs height 1 prevotes
    byz = pvs[1]
    va, vb = _conflicting_pair(byz, vs, chain_id=doc.chain_id)
    fired: list = []
    if cs.evsw is not None:
        from tendermint_tpu.types import events as tev

        cs.evsw.add_listener_for_event(
            "ev-test", tev.EVENT_EVIDENCE, fired.append
        )
    cs.try_add_vote(va, "peer1")
    cs.try_add_vote(vb, "peer1")
    assert cs.evidence_pool.size() == 1
    assert fired and fired[0]["type"] == "duplicate_vote"
    ev = cs.evidence_pool.list()[0]
    assert ev.address == byz.get_address()
    assert {ev.vote_a.block_id.key(), ev.vote_b.block_id.key()} == {
        BLOCK_A.key(), BLOCK_B.key()
    }

    # the RPC route serves it
    from tendermint_tpu.rpc.core.handlers import evidence as evidence_route

    class _Ctx:
        consensus_state = cs

    rep = evidence_route(_Ctx())
    assert rep["count"] == 1
    assert rep["evidence"][0]["validator_address"] == byz.get_address().hex().upper()
    assert rep["evidence"][0]["type"] == "duplicate_vote"
