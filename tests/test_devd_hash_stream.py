"""Streamed devd hash-plane tests (tendermint_tpu/devd.py hash_stream):
digest parity against the single-shot op and the CPU reference (both
modes, chunk-width remainders), tree-frame proofs byte-identical to the
host builders, pipelining (in-flight high-water), malformed-frame error
path, client reconnect across a daemon restart, and the gateway Hasher's
streamed routing + gauges — mirroring tests/test_devd_stream.py.

Parity runs against a real CPU-kernel daemon subprocess (the jax
RIPEMD-160 kernel serving the same IPC bytes a TPU daemon would);
behavioral tests ride the sim-device daemon (TENDERMINT_DEVD_SIM_RATE —
whose _SimHasher computes REAL digests through a rate-limited FIFO, so
parity holds there too with device time deterministic)."""

from __future__ import annotations

import os
import socket
import struct
import subprocess
import sys
import time

import pytest

from tendermint_tpu import devd
from tendermint_tpu.crypto.hashing import ripemd160
from tendermint_tpu.merkle.simple import (
    FlatTree,
    leaf_hash,
    recursive_proofs_from_hashes,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(sock: str, extra_env: dict) -> subprocess.Popen:
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "TENDERMINT_DEVD_SOCK": sock,
        "TENDERMINT_DEVD_ACCEPT_CPU": "1",
        "TENDERMINT_DEVD_EXIT_ON_TERM": "1",
        **extra_env,
    }
    return subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.devd"],
        env=env, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
    )


def _wait_held(client, proc, deadline_s: float) -> None:
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if proc.poll() is not None:
            err = proc.stderr.read() if proc.stderr else b""
            pytest.fail(f"daemon died: {err[-2000:]!r}")
        try:
            if client.ping(timeout=2.0).get("held"):
                return
        except Exception:
            pass
        time.sleep(0.3)
    proc.kill()
    pytest.fail("daemon never reached serving state")


@pytest.fixture(scope="module")
def daemon(tmp_path_factory):
    """Real jax-kernel daemon, verify warm DISABLED (the hash plane
    compiles lazily on first use; no f32 verify compile needed here)."""
    sock = str(tmp_path_factory.mktemp("devd-hash") / "devd.sock")
    proc = _spawn(sock, {"TENDERMINT_DEVD_WARM": ""})
    client = devd.DevdClient(sock)
    _wait_held(client, proc, 60.0)
    yield sock, client
    try:
        client.shutdown()
    except Exception:
        pass
    client.close()
    try:
        proc.wait(timeout=15)
    except subprocess.TimeoutExpired:
        proc.kill()


@pytest.fixture()
def sim_daemon(tmp_path):
    sock = str(tmp_path / "sim.sock")
    proc = _spawn(sock, {"TENDERMINT_DEVD_SIM_RATE": "100000"})
    client = devd.DevdClient(sock)
    _wait_held(client, proc, 30.0)
    yield sock, client, proc
    try:
        client.shutdown()
    except Exception:
        pass
    client.close()
    try:
        proc.wait(timeout=15)
    except subprocess.TimeoutExpired:
        proc.kill()


def _parts(n: int, tag: bytes = b"part") -> list[bytes]:
    # ragged sizes incl. empty and multi-block payloads
    return [tag + b"-%03d" % i + b"\xab" * ((i * 37) % 300) for i in range(n)]


def test_hash_stream_parity_with_single_shot_and_cpu(daemon):
    """Digest-for-digest: streamed == single-shot == crypto.hashing, for
    both modes, with chunk widths hitting remainder/divisor/exact/
    oversize — served by the real jax RIPEMD-160 kernel."""
    _, client = daemon
    items = _parts(23)
    items[5] = b""  # empty payload lane
    want_part = [ripemd160(it) for it in items]
    want_leaf = [leaf_hash(it) for it in items]
    assert client.hash_batch(items, mode="part") == want_part
    assert client.hash_batch(items, mode="leaf") == want_leaf
    for width in (5, 8, 23, 64):
        assert client.hash_stream(items, mode="part", chunk=width) == want_part
        assert client.hash_stream(items, mode="leaf", chunk=width) == want_leaf


def test_hash_stream_tree_frame_proofs_free(daemon):
    """tree=True: the daemon's tree kernel ships every internal node;
    FlatTree.from_nodes must reproduce the host root AND every proof
    byte-for-byte — zero host hashing."""
    _, client = daemon
    items = _parts(17, tag=b"tree")
    digests, nodes = client.hash_stream(items, mode="part", tree=True, chunk=4)
    want = [ripemd160(it) for it in items]
    assert digests == want
    root_ref, proofs_ref = recursive_proofs_from_hashes(want)
    tree = FlatTree.from_nodes(17, list(digests) + list(nodes))
    assert tree.root() == root_ref
    for i in range(17):
        assert tree.aunts_for(i) == proofs_ref[i].aunts
    # single-shot tree agrees
    d2, n2 = client.hash_batch(items, mode="part", tree=True)
    assert d2 == digests and n2 == nodes


def test_hash_stream_empty_and_single_item(sim_daemon):
    _, client, _ = sim_daemon
    assert client.hash_stream([]) == []
    assert client.hash_stream([], tree=True) == ([], [])
    one = [b"only-part"]
    assert client.hash_stream(one, chunk=16) == [ripemd160(one[0])]
    d, nodes = client.hash_stream(one, tree=True, chunk=16)
    assert d == [ripemd160(one[0])] and nodes == []
    assert FlatTree.from_nodes(1, d).root() == d[0]


def test_bad_hash_mode_rejected(sim_daemon):
    _, client, _ = sim_daemon
    with pytest.raises(devd.DevdError, match="bad hash mode"):
        client.hash_batch([b"x"], mode="nonsense")
    with pytest.raises(devd.DevdError, match="bad hash mode"):
        client.hash_stream([b"x"], mode="nonsense", chunk=1)


def test_daemon_overlaps_hash_chunks_in_flight(sim_daemon):
    """The pipelining claim: with sim device time 10 ms/chunk the daemon
    holds multiple dispatched-unresolved hash chunks at once."""
    _, client, _ = sim_daemon
    items = [b"lap-%05d" % i * 4 for i in range(8000)]
    assert client.hash_stream(items, chunk=1000) == [ripemd160(b) for b in items]
    hs = client.status()["hash_stream"]
    assert hs["inflight_max"] >= 2, hs
    assert hs["inflight"] == 0, hs
    assert hs["chunks"] == 8 and hs["lanes"] == 8000
    assert hs["chunk_device_ms_last"] > 0 and hs["chunk_device_ms_avg"] > 0
    # the verify-plane gauges did not move
    assert client.status()["stream"]["chunks"] == 0


def test_malformed_mid_stream_frame_gets_error_frame(sim_daemon):
    """Raw protocol: one good hash chunk, then garbage. The daemon must
    answer the good chunk, send an ERROR frame, and close the stream."""
    sock, _, _ = sim_daemon
    conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    conn.settimeout(10.0)
    conn.connect(sock)
    try:
        devd._send_frame(conn, {
            "op": "hash_stream", "chunks": 3, "total": 8, "mode": "part",
        })
        good = devd._pack_hash_chunk([b"mal-%d" % i for i in range(4)])
        conn.sendall(struct.pack(">I", len(good)) + good)
        garbage = b"\xde\xad\xbe\xef" * 5  # claims 0xefbeadde items
        conn.sendall(struct.pack(">I", len(garbage)) + garbage)

        first = devd._recv_raw_frame(conn)
        status, idx = struct.unpack_from("<BI", first, 0)
        assert (status, idx) == (devd.STREAM_OK, 0)
        (n,) = struct.unpack_from("<I", first, 5)
        assert n == 4 and len(first) == 9 + 20 * 4
        second = devd._recv_raw_frame(conn)
        status, idx = struct.unpack_from("<BI", second, 0)
        assert status == devd.STREAM_ERR and idx == 1
        assert b"malformed" in second[5:]
        conn.settimeout(5.0)
        assert conn.recv(1) == b""
    finally:
        conn.close()


def test_malformed_stream_leaves_daemon_serving(sim_daemon):
    sock, client, _ = sim_daemon
    bad = devd.DevdClient(sock)
    with pytest.raises(devd.DevdError, match="malformed|mismatch"):
        conn, _ = bad._acquire()
        devd._send_frame(conn, {
            "op": "hash_stream", "chunks": 1, "total": 4, "mode": "part",
        })
        conn.sendall(struct.pack(">I", 2) + b"\x01\x02")
        bad._collect_hash_stream(conn, _NopThread(), [], 1, False)
    bad.close()
    # poll: the daemon's error accounting can land after the client's
    # exception under a loaded suite (same race as test_devd_stream)
    deadline = time.monotonic() + 5.0
    while client.status()["hash_stream"]["errors"] < 1 and \
            time.monotonic() < deadline:
        time.sleep(0.05)
    rep = client.status()
    assert rep["hash_stream"]["errors"] >= 1
    items = [b"after-%d" % i for i in range(6)]
    assert client.hash_stream(items, chunk=4) == [ripemd160(b) for b in items]


class _NopThread:
    def join(self, timeout=None):
        pass


def test_client_reconnects_after_daemon_restart(tmp_path):
    """Pooled connections go stale across a daemon restart; the next
    hash request (single-shot AND streamed) retries on a fresh socket."""
    sock = str(tmp_path / "restart.sock")
    proc = _spawn(sock, {"TENDERMINT_DEVD_SIM_RATE": "100000"})
    client = devd.DevdClient(sock)
    _wait_held(client, proc, 30.0)
    items = [b"rc-%d" % i * 10 for i in range(32)]
    want = [ripemd160(b) for b in items]
    assert client.hash_stream(items, chunk=8) == want
    assert client.hash_batch(items) == want

    client.shutdown()
    proc.wait(timeout=15)
    proc2 = _spawn(sock, {"TENDERMINT_DEVD_SIM_RATE": "100000"})
    try:
        _wait_held(devd.DevdClient(sock), proc2, 30.0)
        assert client.hash_stream(items, chunk=8) == want
        assert client.hash_batch(items) == want
        assert client.hash_stream_stats()["reconnects"] >= 1
    finally:
        try:
            client.shutdown()
        except Exception:
            pass
        client.close()
        try:
            proc2.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc2.kill()


def test_gateway_hasher_routes_over_stream(sim_daemon, monkeypatch):
    """A Hasher with offload on next to a serving daemon resolves the
    devd route: wide part batches stream (daemon hash counters move),
    stats() carries the flat stream_* gauges, and part_set_tree rides
    the tree frame — proofs byte-identical to the host path."""
    from tendermint_tpu.ops import gateway as _gw

    # hermetic vs suite order (same discipline as test_devd.py's breaker
    # tests): earlier transport-failure tests leave the SHARED breaker
    # with accumulated failures/backoff, and a half-open breaker lets
    # the leaf-hash probes through but can reject the later tree batch —
    # trees stays 0 and this test reads as a routing regression
    _gw.reset_devd_breaker()
    sock, client, _ = sim_daemon
    monkeypatch.setenv("TENDERMINT_DEVD_SOCK", sock)
    monkeypatch.setenv("TENDERMINT_DEVD_STREAM_MIN", "8")
    monkeypatch.setenv("TENDERMINT_DEVD_HASH_CHUNK", "16")
    import tendermint_tpu.ops.devd_backend as backend
    from tendermint_tpu.ops import gateway
    from tendermint_tpu.types.part_set import PartSet

    monkeypatch.setattr(backend, "_client", None)
    monkeypatch.setattr(backend, "_stream_ok", True)
    monkeypatch.setattr(backend, "_hash_stream_ok", True)
    devd.bust_avail_cache()
    h = gateway.Hasher(min_tpu_batch=1, use_tpu=True)
    assert h._route == "devd"

    before = client.status()["hash_stream"]
    chunks = [b"c-%02d" % i * 50 for i in range(40)]
    assert h.part_leaf_hashes(chunks) == [ripemd160(c) for c in chunks]
    after = client.status()["hash_stream"]
    assert after["chunks"] - before["chunks"] == 3  # 40 items / width 16
    assert after["lanes"] - before["lanes"] == 40
    assert after["bytes_framed"] > before["bytes_framed"]
    stats = h.stats()
    assert stats["tpu_leaves"] == 40 and stats["stream_lanes"] >= 40
    assert all(isinstance(v, (int, float)) for v in stats.values()), stats

    # proof-free part set through the tree frame
    data = bytes(range(256)) * 512  # 128 KB
    ps = PartSet.from_data(data, 4096, tree_hasher=h.part_set_tree)
    ref = PartSet.from_data(data, 4096)
    assert ps.header() == ref.header()
    for i in range(ps.total):
        part, rpart = ps.get_part(i), ref.get_part(i)
        assert part.proof == rpart.proof
        assert part.proof.verify(i, ps.total, part.hash(), ps.hash())
    # poll: the daemon counts `trees` AFTER sending the tree frame, so a
    # status read issued right after the client's stream completes can
    # land before the serving thread's increment (loaded-suite race)
    deadline = time.monotonic() + 5.0
    while client.status()["hash_stream"]["trees"] < 1 and \
            time.monotonic() < deadline:
        time.sleep(0.05)
    assert client.status()["hash_stream"]["trees"] >= 1
    assert h.stats()["stream_trees"] >= 1

    # tx roots over the leaf mode + memoization
    txs = [b"tx-%04d" % i for i in range(32)]
    from tendermint_tpu.merkle.simple import simple_hash_from_byteslices

    assert h.tx_merkle_root(txs) == simple_hash_from_byteslices(txs)
    assert h.tx_merkle_root(list(txs)) == simple_hash_from_byteslices(txs)
    assert h.stats()["tx_root_cache_hits"] == 1


def test_status_and_stats_expose_hash_stream_section(sim_daemon):
    _, client, _ = sim_daemon
    rep = client.status()
    assert {"streams", "chunks", "lanes", "bytes_framed", "inflight",
            "inflight_max", "errors", "trees", "single_batches",
            "chunk_device_ms_last"} <= set(rep["hash_stream"])
    full = client.request({"op": "stats"})
    assert full["ok"] and "hash_stream" in full
