"""Consensus test harness (reference: consensus/common_test.go).

Builds real ConsensusStates over in-memory DBs with validator stubs —
fake peers whose votes are signed locally and injected into the peer
message queue (addVotes, common_test.go:131-140) — and event-subscription
helpers for asserting progress.
"""

from __future__ import annotations

import threading
import time

from tendermint_tpu.abci.apps.counter import CounterApp
from tendermint_tpu.abci.apps.kvstore import KVStoreApp
from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.config import test_config
from tendermint_tpu.consensus.state import ConsensusState, MsgInfo
from tendermint_tpu.consensus import messages as msgs
from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
from tendermint_tpu.libs.db import MemDB
from tendermint_tpu.libs.events import EventSwitch
from tendermint_tpu.mempool import Mempool
from tendermint_tpu.proxy.app_conn import AppConnConsensus, AppConnMempool
from tendermint_tpu.state.state import State
from tendermint_tpu.types import (
    BlockID,
    GenesisDoc,
    GenesisValidator,
    PrivValidatorFS,
    Vote,
)

TEST_CHAIN_ID = "test_chain"


class ValidatorStub:
    """A fake validator: signs votes locally for injection
    (common_test.go:49-105)."""

    def __init__(self, pv: PrivValidatorFS, index: int):
        self.pv = pv
        self.index = index
        self.height = 1
        self.round_ = 0

    def sign_vote(self, type_: int, chain_id: str, block_id: BlockID) -> Vote:
        vote = Vote(
            validator_address=self.pv.get_address(),
            validator_index=self.index,
            height=self.height,
            round_=self.round_,
            type_=type_,
            block_id=block_id,
        )
        return self.pv.sign_vote(chain_id, vote)


def rand_gen_state(n_validators: int, power: int = 1):
    """N deterministic-ish validators + genesis state over MemDB
    (common_test.go:292-322)."""
    pvs = []
    gen_vals = []
    for i in range(n_validators):
        pv = PrivValidatorFS(gen_priv_key_ed25519(), None)
        pvs.append(pv)
        gen_vals.append(GenesisValidator(pv.get_pub_key(), power, f"val{i}"))
    # sort stubs in validator-set order (by address) so indices line up
    order = sorted(range(n_validators), key=lambda i: pvs[i].get_address())
    pvs = [pvs[i] for i in order]
    doc = GenesisDoc(
        genesis_time_ns=time.time_ns(),
        chain_id=TEST_CHAIN_ID,
        validators=[gen_vals[i] for i in order],
    )
    state = State.get_state(MemDB(), doc)
    return state, pvs


def new_consensus_state(state, pv, app=None, config=None):
    """Real ConsensusState over in-proc app (common_test.go:474-481)."""
    if config is None:
        # each state machine gets its own root so WALs never leak across
        # tests (a shared relative wal path replays a stale WAL!)
        import tempfile

        config = test_config().consensus
        config.root_dir = tempfile.mkdtemp(prefix="cs-test-")
    app = app if app is not None else CounterApp()
    mtx = threading.RLock()
    mp = Mempool(test_config().mempool, AppConnMempool(LocalClient(app, mtx)))
    store = BlockStore(MemDB())
    evsw = EventSwitch()
    evsw.start()
    cs = ConsensusState(
        config, state, AppConnConsensus(LocalClient(app, mtx)), store, mp
    )
    cs.set_event_switch(evsw)
    if pv is not None:
        cs.set_priv_validator(pv)
    return cs


def make_cs_and_stubs(n_validators: int, app=None, config=None):
    state, pvs = rand_gen_state(n_validators)
    # cs's own validator is whichever sorted validator is round-0 proposer,
    # so proposer-driven tests work for any n (common_test uses vss[0])
    proposer = state.validators.get_proposer()
    prop_idx = next(
        i for i, pv in enumerate(pvs) if pv.get_address() == proposer.address
    )
    cs = new_consensus_state(state, pvs[prop_idx], app=app, config=config)
    stubs = [ValidatorStub(pv, i) for i, pv in enumerate(pvs)]
    return cs, stubs, prop_idx


def add_votes(cs: ConsensusState, *votes: Vote) -> None:
    """Inject peer votes (common_test.go:131-140)."""
    for v in votes:
        cs.peer_msg_queue.put(MsgInfo(msgs.VoteMessage(v), "peer-test"))


def sign_add_votes(cs, stubs, type_, block_id: BlockID, skip_index: int) -> None:
    votes = [
        s.sign_vote(type_, TEST_CHAIN_ID, block_id)
        for s in stubs
        if s.index != skip_index
    ]
    add_votes(cs, *votes)


class EventCollector:
    """Subscribe to events and wait on them (consensus/common.go:11-19)."""

    def __init__(self, evsw: EventSwitch, event: str, listener_id: str = "collector"):
        self.items: list = []
        self._cond = threading.Condition()
        evsw.add_listener_for_event(listener_id + event, event, self._on)

    def _on(self, data):
        with self._cond:
            self.items.append(data)
            self._cond.notify_all()

    def wait_for(self, n: int, timeout: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout
        with self._cond:
            while len(self.items) < n:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True


def wait_for_height(cs: ConsensusState, height: int, timeout: float = 10.0) -> bool:
    deadline = time.monotonic() + timeout
    while cs.rs.height < height:
        if time.monotonic() > deadline:
            return False
        time.sleep(0.005)
    return True


# -- subprocess node harness (reference: test/persist/test.sh) ---------------
#
# The crash tiers (tests/test_persist.py FAIL_TEST_INDEX cycles, round 9's
# tests/test_wal_torture.py torn-write sweeps) all drive the SAME node
# shape: a real `python -m tendermint_tpu.cli node` subprocess over a
# fast-consensus config with the persistent kvstore app, crashed by env-armed
# fail points and restarted to prove recovery. One copy of that scaffolding
# lives here.

import json as _json
import os as _os
import subprocess as _subprocess
import sys as _sys
import urllib.request as _urllib_request

REPO = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))


def free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def init_node_home(home: str, chain_id: str) -> None:
    """`cli init` + the fast-consensus subprocess config."""
    _subprocess.run(
        [_sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "init",
         "--chain-id", chain_id],
        check=True, capture_output=True,
        env=dict(_os.environ, PYTHONPATH=REPO),
    )
    write_fast_config(home)


def write_fast_config(home: str) -> None:
    """Speed up consensus for the subprocess (config.toml is what the CLI
    node loads)."""
    from tendermint_tpu.config import load_config
    from tendermint_tpu.config.toml import config_to_toml

    cfg = load_config(home)
    c = cfg.consensus
    c.timeout_propose = 0.3
    c.timeout_prevote = 0.05
    c.timeout_precommit = 0.05
    c.timeout_commit = 0.05
    c.skip_timeout_commit = True
    cfg.base.db_backend = "filedb"
    cfg.base.proxy_app = "persistent_kvstore"
    with open(_os.path.join(home, "config.toml"), "w") as f:
        f.write(config_to_toml(cfg))


def node_proc(home: str, rpc_port: int, fail_index: int | None = None,
              extra_env: dict | None = None):
    """A real node subprocess; fail_index arms FAIL_TEST_INDEX, extra_env
    arms anything else (e.g. the FAIL_TEST_MODE=torn_write torture tier)."""
    env = dict(
        _os.environ,
        JAX_PLATFORMS="cpu",
        TENDERMINT_TPU_DISABLE="1",
        PYTHONPATH=REPO,
    )
    for k in ("FAIL_TEST_INDEX", "FAIL_TEST_MODE", "FAIL_TEST_WAL_BYTES",
              "FAIL_TEST_ROTATE_INDEX", "FAIL_TEST_ROTATE_PHASE"):
        env.pop(k, None)
    if fail_index is not None:
        env["FAIL_TEST_INDEX"] = str(fail_index)
    if extra_env:
        env.update({k: str(v) for k, v in extra_env.items()})
    return _subprocess.Popen(
        [
            _sys.executable, "-m", "tendermint_tpu.cli", "--home", home, "node",
            "--rpc.laddr", f"tcp://127.0.0.1:{rpc_port}",
            "--p2p.laddr", "tcp://127.0.0.1:0",
            "--log_level", "warning",
        ],
        env=env,
        stdout=_subprocess.PIPE,
        stderr=_subprocess.STDOUT,
    )


def rpc(port: int, method: str, timeout=5, **params):
    req = _urllib_request.Request(
        f"http://127.0.0.1:{port}/",
        data=_json.dumps(
            {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with _urllib_request.urlopen(req, timeout=timeout) as resp:
        body = _json.loads(resp.read().decode())
    if body.get("error"):
        raise RuntimeError(body["error"])
    return body["result"]


def wait_height(port: int, h: int, deadline_s: float = 60) -> int:
    deadline = time.time() + deadline_s
    last = -1
    while time.time() < deadline:
        try:
            last = rpc(port, "status", timeout=2)["latest_block_height"]
            if last >= h:
                return last
        except Exception:
            pass
        time.sleep(0.3)
    return last
