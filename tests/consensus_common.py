"""Consensus test harness (reference: consensus/common_test.go).

Builds real ConsensusStates over in-memory DBs with validator stubs —
fake peers whose votes are signed locally and injected into the peer
message queue (addVotes, common_test.go:131-140) — and event-subscription
helpers for asserting progress.
"""

from __future__ import annotations

import threading
import time

from tendermint_tpu.abci.apps.counter import CounterApp
from tendermint_tpu.abci.apps.kvstore import KVStoreApp
from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.config import test_config
from tendermint_tpu.consensus.state import ConsensusState, MsgInfo
from tendermint_tpu.consensus import messages as msgs
from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
from tendermint_tpu.libs.db import MemDB
from tendermint_tpu.libs.events import EventSwitch
from tendermint_tpu.mempool import Mempool
from tendermint_tpu.proxy.app_conn import AppConnConsensus, AppConnMempool
from tendermint_tpu.state.state import State
from tendermint_tpu.types import (
    BlockID,
    GenesisDoc,
    GenesisValidator,
    PrivValidatorFS,
    Vote,
)

TEST_CHAIN_ID = "test_chain"


class ValidatorStub:
    """A fake validator: signs votes locally for injection
    (common_test.go:49-105)."""

    def __init__(self, pv: PrivValidatorFS, index: int):
        self.pv = pv
        self.index = index
        self.height = 1
        self.round_ = 0

    def sign_vote(self, type_: int, chain_id: str, block_id: BlockID) -> Vote:
        vote = Vote(
            validator_address=self.pv.get_address(),
            validator_index=self.index,
            height=self.height,
            round_=self.round_,
            type_=type_,
            block_id=block_id,
        )
        return self.pv.sign_vote(chain_id, vote)


def rand_gen_state(n_validators: int, power: int = 1):
    """N deterministic-ish validators + genesis state over MemDB
    (common_test.go:292-322)."""
    pvs = []
    gen_vals = []
    for i in range(n_validators):
        pv = PrivValidatorFS(gen_priv_key_ed25519(), None)
        pvs.append(pv)
        gen_vals.append(GenesisValidator(pv.get_pub_key(), power, f"val{i}"))
    # sort stubs in validator-set order (by address) so indices line up
    order = sorted(range(n_validators), key=lambda i: pvs[i].get_address())
    pvs = [pvs[i] for i in order]
    doc = GenesisDoc(
        genesis_time_ns=time.time_ns(),
        chain_id=TEST_CHAIN_ID,
        validators=[gen_vals[i] for i in order],
    )
    state = State.get_state(MemDB(), doc)
    return state, pvs


def new_consensus_state(state, pv, app=None, config=None):
    """Real ConsensusState over in-proc app (common_test.go:474-481)."""
    if config is None:
        # each state machine gets its own root so WALs never leak across
        # tests (a shared relative wal path replays a stale WAL!)
        import tempfile

        config = test_config().consensus
        config.root_dir = tempfile.mkdtemp(prefix="cs-test-")
    app = app if app is not None else CounterApp()
    mtx = threading.RLock()
    mp = Mempool(test_config().mempool, AppConnMempool(LocalClient(app, mtx)))
    store = BlockStore(MemDB())
    evsw = EventSwitch()
    evsw.start()
    cs = ConsensusState(
        config, state, AppConnConsensus(LocalClient(app, mtx)), store, mp
    )
    cs.set_event_switch(evsw)
    if pv is not None:
        cs.set_priv_validator(pv)
    return cs


def make_cs_and_stubs(n_validators: int, app=None, config=None):
    state, pvs = rand_gen_state(n_validators)
    # cs's own validator is whichever sorted validator is round-0 proposer,
    # so proposer-driven tests work for any n (common_test uses vss[0])
    proposer = state.validators.get_proposer()
    prop_idx = next(
        i for i, pv in enumerate(pvs) if pv.get_address() == proposer.address
    )
    cs = new_consensus_state(state, pvs[prop_idx], app=app, config=config)
    stubs = [ValidatorStub(pv, i) for i, pv in enumerate(pvs)]
    return cs, stubs, prop_idx


def add_votes(cs: ConsensusState, *votes: Vote) -> None:
    """Inject peer votes (common_test.go:131-140)."""
    for v in votes:
        cs.peer_msg_queue.put(MsgInfo(msgs.VoteMessage(v), "peer-test"))


def sign_add_votes(cs, stubs, type_, block_id: BlockID, skip_index: int) -> None:
    votes = [
        s.sign_vote(type_, TEST_CHAIN_ID, block_id)
        for s in stubs
        if s.index != skip_index
    ]
    add_votes(cs, *votes)


class EventCollector:
    """Subscribe to events and wait on them (consensus/common.go:11-19)."""

    def __init__(self, evsw: EventSwitch, event: str, listener_id: str = "collector"):
        self.items: list = []
        self._cond = threading.Condition()
        evsw.add_listener_for_event(listener_id + event, event, self._on)

    def _on(self, data):
        with self._cond:
            self.items.append(data)
            self._cond.notify_all()

    def wait_for(self, n: int, timeout: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout
        with self._cond:
            while len(self.items) < n:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True


def wait_for_height(cs: ConsensusState, height: int, timeout: float = 10.0) -> bool:
    deadline = time.monotonic() + timeout
    while cs.rs.height < height:
        if time.monotonic() > deadline:
            return False
        time.sleep(0.005)
    return True
