"""Consensus state machine tests (reference: consensus/state_test.go,
wal/replay tests)."""

import tempfile
import threading
import time

import pytest

from consensus_common import (
    EventCollector,
    TEST_CHAIN_ID,
    add_votes,
    make_cs_and_stubs,
    new_consensus_state,
    rand_gen_state,
    sign_add_votes,
    wait_for_height,
)
from tendermint_tpu.abci.apps.kvstore import KVStoreApp
from tendermint_tpu.config import test_config as _test_config
from tendermint_tpu.consensus.height_vote_set import HeightVoteSet
from tendermint_tpu.consensus.round_state import RoundStep
from tendermint_tpu.consensus.ticker import MockTicker, TimeoutInfo, TimeoutTicker
from tendermint_tpu.consensus.wal import WAL, WALMessage, decode_wal_line
from tendermint_tpu.consensus import messages as msgs
from tendermint_tpu.types import (
    VOTE_TYPE_PRECOMMIT,
    VOTE_TYPE_PREVOTE,
    BlockID,
)
from tendermint_tpu.types import events as tev


class TestSingleValidator:
    def test_makes_blocks(self):
        """One validator, counter app: the chain advances on its own."""
        cs, stubs, _ = make_cs_and_stubs(1)
        blocks = EventCollector(cs.evsw, tev.EVENT_NEW_BLOCK)
        cs.start()
        try:
            assert blocks.wait_for(3, timeout=15), "expected 3 blocks"
        finally:
            cs.stop()
        heights = [d.block.header.height for d in blocks.items[:3]]
        assert heights == [1, 2, 3]

    def test_commits_txs_and_app_hash_advances(self):
        cs, stubs, _ = make_cs_and_stubs(1, app=KVStoreApp())
        blocks = EventCollector(cs.evsw, tev.EVENT_NEW_BLOCK)
        cs.mempool.check_tx(b"x=1")
        cs.start()
        try:
            assert blocks.wait_for(3, timeout=15)
        finally:
            cs.stop()
        # the tx landed in an early block and the app hash is bound into a
        # later header
        all_txs = [tx for d in blocks.items for tx in d.block.data.txs]
        assert b"x=1" in all_txs
        assert blocks.items[2].block.header.app_hash != b""

    def test_new_round_event_sequence(self):
        cs, stubs, _ = make_cs_and_stubs(1)
        rounds = EventCollector(cs.evsw, tev.EVENT_NEW_ROUND)
        cs.start()
        try:
            assert rounds.wait_for(2, timeout=15)
        finally:
            cs.stop()
        assert rounds.items[0].height == 1
        assert rounds.items[1].height == 2


class TestMultiValidatorQuorum:
    def test_full_round_with_stub_votes(self):
        """cs is the round-0 proposer of a 4-validator set; the other 3
        validators' votes are injected (state_test.go FullRound2 analog)."""
        cs, stubs, prop_idx = make_cs_and_stubs(4)
        votes = EventCollector(cs.evsw, tev.EVENT_VOTE)
        blocks = EventCollector(cs.evsw, tev.EVENT_NEW_BLOCK)
        cs.start()
        try:
            # proposer signs its own prevote
            assert votes.wait_for(1, timeout=10)
            own_prevote = votes.items[0].vote
            assert own_prevote.type_ == VOTE_TYPE_PREVOTE
            block_id = own_prevote.block_id
            assert block_id.hash, "proposer should prevote its own proposal"

            sign_add_votes(cs, stubs, VOTE_TYPE_PREVOTE, block_id, prop_idx)
            # +2/3 prevotes -> cs precommits
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                pcs = [v for v in votes.items if v.vote.type_ == VOTE_TYPE_PRECOMMIT]
                if pcs:
                    break
                time.sleep(0.01)
            assert pcs and pcs[0].vote.block_id.hash == block_id.hash

            for s in stubs:
                s.height, s.round_ = 1, 0
            sign_add_votes(cs, stubs, VOTE_TYPE_PRECOMMIT, block_id, prop_idx)
            assert blocks.wait_for(1, timeout=10), "block should commit"
            assert blocks.items[0].block.header.height == 1
        finally:
            cs.stop()

    def test_no_quorum_no_commit(self):
        """With only 1/4 voting, nothing commits."""
        cs, stubs, _ = make_cs_and_stubs(4)
        blocks = EventCollector(cs.evsw, tev.EVENT_NEW_BLOCK)
        cs.start()
        try:
            assert not blocks.wait_for(1, timeout=1.0)
            assert cs.rs.height == 1
        finally:
            cs.stop()

    def test_nil_prevotes_precommit_nil_and_new_round(self):
        """+2/3 nil prevotes -> cs precommits nil; +2/3 nil precommits ->
        next round, same height."""
        cs, stubs, prop_idx = make_cs_and_stubs(4)
        votes = EventCollector(cs.evsw, tev.EVENT_VOTE)
        rounds = EventCollector(cs.evsw, tev.EVENT_NEW_ROUND)
        cs.start()
        try:
            assert votes.wait_for(1, timeout=10)
            sign_add_votes(cs, stubs, VOTE_TYPE_PREVOTE, BlockID(), prop_idx)
            deadline = time.monotonic() + 10
            nil_pc = None
            while time.monotonic() < deadline and nil_pc is None:
                for v in votes.items:
                    if (
                        v.vote.type_ == VOTE_TYPE_PRECOMMIT
                        and v.vote.validator_index != prop_idx  # ours comes via event too
                        or (v.vote.type_ == VOTE_TYPE_PRECOMMIT)
                    ):
                        nil_pc = v.vote
                        break
                time.sleep(0.01)
            assert nil_pc is not None
            assert not nil_pc.block_id.hash, "precommit should be nil"

            sign_add_votes(cs, stubs, VOTE_TYPE_PRECOMMIT, BlockID(), prop_idx)
            # +2/3 nil precommits → precommit-wait timeout → round 1
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and cs.rs.round_ == 0:
                time.sleep(0.01)
            assert cs.rs.height == 1
            assert cs.rs.round_ >= 1
        finally:
            cs.stop()


class TestHeightVoteSet:
    def test_catchup_round_budget(self):
        state, pvs = rand_gen_state(2)
        hvs = HeightVoteSet(TEST_CHAIN_ID, 1, state.validators)
        from consensus_common import ValidatorStub

        stub = ValidatorStub(pvs[0], 0)
        added_rounds = []
        for r in (5, 6, 7):
            stub.round_ = r
            v = stub.sign_vote(VOTE_TYPE_PREVOTE, TEST_CHAIN_ID, BlockID())
            added_rounds.append(hvs.add_vote(v, peer_id="peerX"))
        # two catchup rounds allowed, third dropped
        assert added_rounds == [True, True, False]

    def test_pol_info(self):
        state, pvs = rand_gen_state(1)
        hvs = HeightVoteSet(TEST_CHAIN_ID, 1, state.validators)
        from consensus_common import ValidatorStub

        stub = ValidatorStub(pvs[0], 0)
        assert hvs.pol_info() == (-1, None)
        bid = BlockID(b"\x01" * 20)
        v = stub.sign_vote(VOTE_TYPE_PREVOTE, TEST_CHAIN_ID, bid)
        hvs.add_vote(v, peer_id="")
        r, pol = hvs.pol_info()
        assert r == 0 and pol.hash == bid.hash


class TestTicker:
    def test_fires_after_duration(self):
        t = TimeoutTicker()
        t.start()
        t.schedule_timeout(TimeoutInfo(0.05, 1, 0, RoundStep.PROPOSE))
        ti = t.chan.get(timeout=2)
        assert ti.height == 1 and ti.step == RoundStep.PROPOSE
        t.stop()

    def test_newer_replaces_older(self):
        t = TimeoutTicker()
        t.start()
        t.schedule_timeout(TimeoutInfo(0.5, 1, 0, RoundStep.PROPOSE))
        t.schedule_timeout(TimeoutInfo(0.05, 1, 0, RoundStep.PREVOTE_WAIT))
        ti = t.chan.get(timeout=2)
        assert ti.step == RoundStep.PREVOTE_WAIT
        t.stop()

    def test_stale_ignored(self):
        t = TimeoutTicker()
        t.start()
        t.schedule_timeout(TimeoutInfo(0.05, 5, 0, RoundStep.PROPOSE))
        t.schedule_timeout(TimeoutInfo(0.01, 1, 0, RoundStep.PROPOSE))  # stale
        ti = t.chan.get(timeout=2)
        assert ti.height == 5
        t.stop()


class TestWAL:
    def test_roundtrip_and_endheight_search(self, tmp_path):
        wal = WAL(str(tmp_path / "wal"))
        wal.start()
        wal.save(WALMessage.timeout(TimeoutInfo(1.0, 1, 0, RoundStep.PROPOSE)))
        wal.write_end_height(1)
        vote_msg = None
        state, pvs = rand_gen_state(1)
        from consensus_common import ValidatorStub

        stub = ValidatorStub(pvs[0], 0)
        v = stub.sign_vote(VOTE_TYPE_PREVOTE, TEST_CHAIN_ID, BlockID())
        wal.save(WALMessage.msg_info(msgs.VoteMessage(v), "peerA"))
        wal.stop()

        wal2 = WAL(str(tmp_path / "wal"))
        lines = wal2.lines_after_height(1)
        assert lines is not None
        entries = [decode_wal_line(ln) for ln in lines if ln.strip()]
        kinds = [e[0] for e in entries if e]
        assert "msg_info" in kinds
        decoded = next(e for e in entries if e[0] == "msg_info")
        assert decoded[1].vote.signature == v.signature
        assert decoded[2] == "peerA"
        # marker for an uncommitted height: not found
        assert wal2.lines_after_height(7) is None

    def test_fresh_wal_has_height0_marker(self, tmp_path):
        wal = WAL(str(tmp_path / "wal"))
        wal.start()
        wal.stop()
        wal2 = WAL(str(tmp_path / "wal"))
        assert wal2.lines_after_height(0) == []


class TestCrashRecovery:
    def _run_node(self, root, app, state_db, store_db, n_blocks, chain_db_doc):
        """Run a 1-validator node until n_blocks commit; leave WAL+dbs."""
        from tendermint_tpu.abci.client import LocalClient
        from tendermint_tpu.blockchain.store import BlockStore
        from tendermint_tpu.consensus.state import ConsensusState
        from tendermint_tpu.libs.events import EventSwitch
        from tendermint_tpu.mempool import Mempool
        from tendermint_tpu.proxy.app_conn import AppConnConsensus, AppConnMempool
        from tendermint_tpu.state.state import State
        from tendermint_tpu.types import PrivValidatorFS

        cfg = _test_config()
        cfg.set_root(root)
        doc = chain_db_doc
        state = State.get_state(state_db, doc)
        pv = PrivValidatorFS.load(root + "/priv_validator.json")
        mtx = threading.RLock()
        mp = Mempool(cfg.mempool, AppConnMempool(LocalClient(app, mtx)))
        store = BlockStore(store_db)
        evsw = EventSwitch()
        evsw.start()
        cs = ConsensusState(
            cfg.consensus, state, AppConnConsensus(LocalClient(app, mtx)), store, mp
        )
        cs.set_event_switch(evsw)
        cs.set_priv_validator(pv)
        blocks = EventCollector(evsw, tev.EVENT_NEW_BLOCK)
        cs.start()
        ok = blocks.wait_for(n_blocks, timeout=20)
        cs.stop()
        assert ok
        return cs

    def test_restart_continues_chain(self, tmp_path):
        """Stop after 2 blocks; restart with fresh app; handshake replays
        the chain into the app and consensus continues from height 3."""
        from tendermint_tpu.config import reset_test_root
        from tendermint_tpu.consensus.replay import Handshaker
        from tendermint_tpu.libs.db import MemDB
        from tendermint_tpu.proxy.multi_app_conn import AppConns
        from tendermint_tpu.proxy.client_creator import LocalClientCreator
        from tendermint_tpu.state.state import State
        from tendermint_tpu.types import GenesisDoc

        root = str(tmp_path / "node")
        reset_test_root(root, chain_id="crash-test")
        doc = GenesisDoc.from_file(root + "/genesis.json")
        state_db, store_db = MemDB(), MemDB()
        app = KVStoreApp()

        cs1 = self._run_node(root, app, state_db, store_db, 2, doc)
        committed_height = cs1.state.last_block_height
        assert committed_height >= 2
        committed_app_hash = cs1.state.app_hash

        # "crash": new app instance knows nothing; handshake replays it
        app2 = KVStoreApp()
        state2 = State.get_state(state_db, doc)
        from tendermint_tpu.blockchain.store import BlockStore

        store2 = BlockStore(store_db)
        hs = Handshaker(state2, store2)
        conns = AppConns(LocalClientCreator(app2), hs)
        conns.start()
        assert hs.n_blocks >= 1 or app2.height > 0
        assert app2.app_hash == committed_app_hash

        # consensus resumes and extends the chain
        cs2 = self._run_node(root, app2, state_db, store_db, 1, doc)
        assert cs2.state.last_block_height > committed_height


class TestWALTruncation:
    """WAL recovery from arbitrary truncation (consensus/replay_test.go:61-66
    replays fixtures cut at every line; TestWALCrash* in replay_test.go cover
    the crash-mid-write residues). A crash can leave the WAL cut anywhere;
    catchup must treat a torn TAIL line as the expected residue and replay
    everything before it (replay.py catchup_replay)."""

    def _record(self, tmp_path, n_blocks=2):
        """Run a 1-validator node for n_blocks; return everything needed to
        restart from arbitrary WAL prefixes."""
        from tendermint_tpu.config import reset_test_root
        from tendermint_tpu.libs.db import MemDB
        from tendermint_tpu.types import GenesisDoc

        root = str(tmp_path / "rec")
        reset_test_root(root, chain_id="wal-trunc")
        with open(root + "/priv_validator.json", "rb") as f:
            pre_pv = f.read()  # privval BEFORE it ever signed
        doc = GenesisDoc.from_file(root + "/genesis.json")
        state_db, store_db = MemDB(), MemDB()
        app = KVStoreApp()
        TestCrashRecovery()._run_node(root, app, state_db, store_db, n_blocks, doc)
        cfg = _test_config()
        cfg.set_root(root)
        wal_file = cfg.consensus.wal_file()
        with open(wal_file, "rb") as f:
            wal_bytes = f.read()
        return root, doc, state_db, store_db, wal_file, wal_bytes, pre_pv

    def _fresh_cs(self, root, doc, pre_pv, wal_trunc: bytes):
        """A brand-new node (fresh dbs/app, pre-run privval) whose WAL file
        holds `wal_trunc`."""
        import os

        from tendermint_tpu.abci.client import LocalClient
        from tendermint_tpu.blockchain.store import BlockStore
        from tendermint_tpu.consensus.state import ConsensusState
        from tendermint_tpu.libs.db import MemDB
        from tendermint_tpu.libs.events import EventSwitch
        from tendermint_tpu.mempool import Mempool
        from tendermint_tpu.proxy.app_conn import AppConnConsensus, AppConnMempool
        from tendermint_tpu.state.state import State
        from tendermint_tpu.types import PrivValidatorFS

        os.makedirs(root, exist_ok=True)
        with open(root + "/priv_validator.json", "wb") as f:
            f.write(pre_pv)
        cfg = _test_config()
        cfg.set_root(root)
        wal_file = cfg.consensus.wal_file()
        os.makedirs(os.path.dirname(wal_file), exist_ok=True)
        with open(wal_file, "wb") as f:
            f.write(wal_trunc)
        state = State.get_state(MemDB(), doc)
        app = KVStoreApp()
        mtx = threading.RLock()
        mp = Mempool(cfg.mempool, AppConnMempool(LocalClient(app, mtx)))
        evsw = EventSwitch()
        evsw.start()
        cs = ConsensusState(
            cfg.consensus,
            state,
            AppConnConsensus(LocalClient(app, mtx)),
            BlockStore(MemDB()),
            mp,
        )
        cs.set_event_switch(evsw)
        cs.set_priv_validator(PrivValidatorFS.load(root + "/priv_validator.json"))
        return cs, wal_file

    def test_replay_from_every_truncation_point(self, tmp_path):
        """Cut the recorded WAL at every line boundary plus mid-line tears;
        a fresh node must replay the surviving prefix without an exception
        and land on a sane height every time."""
        from tendermint_tpu.consensus.replay import catchup_replay

        _, doc, _, _, _, wal_bytes, pre_pv = self._record(tmp_path)
        points = set()
        off = 0
        # the v2 WAL is binary (newline bytes appear only inside JSON
        # payloads), so "line" boundaries are arbitrary cut points — keep
        # them, and add an even byte stride so the sweep density never
        # depends on how many 0x0A bytes this run's frames happened to hold;
        # the stride stays coarse because each point replays a full
        # consensus state machine (~0.5 s) and the per-byte-exhaustive
        # sweep already runs at the WAL layer in tests/test_wal_repair.py
        for ln in wal_bytes.splitlines(keepends=True):
            if len(ln) > 8:
                points.add(off + len(ln) // 2)  # torn mid-line tail
                points.add(off + len(ln) - 1)  # complete line, newline lost
            off += len(ln)
            points.add(off)  # clean cut after this line
        for cut in range(8, len(wal_bytes), max(1, len(wal_bytes) // 16)):
            points.add(cut)
        assert len(points) > 20, "recording produced a suspiciously short WAL"
        heights = {}
        for i, cut in enumerate(sorted(points)):
            cs, wal_file = self._fresh_cs(
                str(tmp_path / f"t{i}"), doc, pre_pv, wal_bytes[:cut]
            )
            cs.open_wal(wal_file)
            try:
                catchup_replay(cs, cs.rs.height)
                # height 1 fully replayed iff its commit survived the cut
                assert cs.rs.height in (1, 2), f"cut={cut}: height {cs.rs.height}"
                heights[cut] = cs.rs.height
            finally:
                cs.wal.stop()
                cs.evsw.stop()
        # the sweep must not be vacuous: a full prefix commits height 1,
        # and some earlier cut leaves it uncommitted
        assert heights[max(heights)] == 2
        assert 1 in heights.values()

    def test_crash_residue_restart_extends_chain(self, tmp_path):
        """The realistic crash residues — WAL intact, final line torn
        mid-write, final line never written — against the PERSISTED node
        state: restart must replay and commit a further block."""
        residues = {
            "intact": lambda b: b,
            "torn-tail": lambda b: b[: len(b) - len(b.splitlines(keepends=True)[-1]) // 2],
            "missing-tail": lambda b: b[: len(b) - len(b.splitlines(keepends=True)[-1])],
        }
        for name, cut in residues.items():
            root, doc, state_db, store_db, wal_file, wal_bytes, _ = self._record(
                tmp_path / name
            )
            with open(wal_file, "wb") as f:
                f.write(cut(wal_bytes))
            app2 = KVStoreApp()
            from tendermint_tpu.blockchain.store import BlockStore
            from tendermint_tpu.consensus.replay import Handshaker
            from tendermint_tpu.proxy.client_creator import LocalClientCreator
            from tendermint_tpu.proxy.multi_app_conn import AppConns
            from tendermint_tpu.state.state import State

            hs = Handshaker(State.get_state(state_db, doc), BlockStore(store_db))
            AppConns(LocalClientCreator(app2), hs).start()
            before = State.get_state(state_db, doc).last_block_height
            cs = TestCrashRecovery()._run_node(
                root, app2, state_db, store_db, 1, doc
            )
            assert cs.state.last_block_height > before, f"residue {name!r} stalled"


# -- adversarial robustness (peer-facing surfaces) ---------------------------


class TestPeerStateRobustness:
    """The reactor's peer mirror is driven by attacker-controlled
    messages; stale or replayed ones must never move it backwards
    (reactor.go:1050-1053)."""

    def _ps(self):
        from tendermint_tpu.consensus.reactor import PeerState

        return PeerState(peer=object())

    def _nrs(self, h, r, s, last_commit_round=0):
        from tendermint_tpu.consensus import messages as msgs

        return msgs.NewRoundStepMessage(
            height=h, round_=r, step=s,
            seconds_since_start_time=0, last_commit_round=last_commit_round,
        )

    def test_stale_new_round_step_ignored(self):
        from tendermint_tpu.libs.bitarray import BitArray

        ps = self._ps()
        ps.apply_new_round_step(self._nrs(5, 2, 3))
        ps.ensure_vote_bit_arrays(5, 4)
        ps.prs.prevotes.set_index(1, True)

        # replayed earlier round: bit arrays must survive
        ps.apply_new_round_step(self._nrs(5, 1, 6))
        assert ps.prs.round_ == 2
        assert ps.prs.prevotes is not None and ps.prs.prevotes.get_index(1)

        # exact duplicate: also a no-op
        ps.apply_new_round_step(self._nrs(5, 2, 3))
        assert ps.prs.prevotes is not None

        # genuine progress still applies and resets
        ps.apply_new_round_step(self._nrs(5, 3, 1))
        assert ps.prs.round_ == 3
        assert ps.prs.prevotes is None

    def test_last_commit_bit_array_uses_last_commit_size(self):
        ps = self._ps()
        ps.apply_new_round_step(self._nrs(7, 0, 1))
        # current set has 10 validators, height-6 commit had 4
        ps.ensure_vote_bit_arrays(7, 10)
        ps.ensure_vote_bit_arrays(6, 4)
        assert ps.prs.prevotes.size == 10
        assert ps.prs.last_commit.size == 4


class TestMessageDecodeRobustness:
    """msg_from_json handles raw attacker JSON: anything off-contract
    must raise ValueError (-> peer error), never propagate garbage."""

    def test_malformed_envelopes(self):
        import pytest as _pytest

        from tendermint_tpu.consensus.messages import msg_from_json

        for bad in (
            None, [], 42, "x",
            {"type": 7, "data": {}},
            {"type": "nope", "data": {}},
            {"type": "vote", "data": []},
            {"type": "new_round_step"},
        ):
            with _pytest.raises(ValueError):
                msg_from_json(bad)

    def test_scalar_field_bounds(self):
        import pytest as _pytest

        from tendermint_tpu.consensus.messages import msg_from_json

        good = {
            "height": 5, "round": 0, "step": 1,
            "seconds_since_start_time": 0, "last_commit_round": -1,
        }
        assert msg_from_json({"type": "new_round_step", "data": good}).height == 5
        for key, bad in (
            ("height", -1), ("height", 1 << 70), ("height", "5"),
            ("height", True), ("round", -2), ("step", 99),
        ):
            data = dict(good, **{key: bad})
            with _pytest.raises(ValueError):
                msg_from_json({"type": "new_round_step", "data": data})

    def test_bitarray_bounds(self):
        import pytest as _pytest

        from tendermint_tpu.consensus.messages import msg_from_json

        ok = {
            "type": "proposal_pol",
            "data": {"height": 1, "proposal_pol_round": 0,
                     "proposal_pol": {"bits": 4, "elems": "f"}},
        }
        assert msg_from_json(ok).proposal_pol.size == 4
        for bits in (-1, 1 << 30, "4", None):
            bad = {
                "type": "proposal_pol",
                "data": {"height": 1, "proposal_pol_round": 0,
                         "proposal_pol": {"bits": bits, "elems": "f"}},
            }
            with _pytest.raises(ValueError):
                msg_from_json(bad)

    def test_nested_vote_garbage_rejected(self):
        """Off-contract scalars nested inside a Vote must fail at decode
        (-> peer disconnect), not deep in the consensus loop."""
        import pytest as _pytest

        from tendermint_tpu.consensus.messages import msg_from_json

        def vote(**over):
            v = {
                "validator_address": "aa" * 20, "validator_index": 0,
                "height": 7, "round": 0, "type": 1,
                "block_id": {"hash": "", "parts": {"total": 0, "hash": ""}},
                "signature": None,
            }
            v.update(over)
            return {"type": "vote", "data": {"vote": v}}

        assert msg_from_json(vote()).vote.height == 7
        for bad in (
            vote(height="7"), vote(height=True), vote(round=-1),
            vote(validator_index=1 << 30), vote(validator_address="zz"),
            vote(block_id={"hash": "x" * 200, "parts": {"total": 0, "hash": ""}}),
            vote(signature=[1, "ab"]), vote(signature="junk"),
        ):
            with _pytest.raises(ValueError):
                msg_from_json(bad)


def test_live_vote_path_batches_on_gateway():
    """SURVEY §7 deferred vote verification: a burst of gossiped votes
    from 100 validators must ride the batched kernel (verifier tpu_sigs
    moves) while VoteSet keeps per-vote accept/reject semantics."""
    from tendermint_tpu.consensus.state import MsgInfo
    from tendermint_tpu.ops import gateway
    from tendermint_tpu.types import BlockID
    from tendermint_tpu.types.vote import VOTE_TYPE_PREVOTE
    from consensus_common import TEST_CHAIN_ID, make_cs_and_stubs

    def wait_until(cond, timeout=60.0, tick=0.1):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return True
            time.sleep(tick)
        return cond()

    cs, stubs, prop_idx = make_cs_and_stubs(100)
    verifier = gateway.Verifier(min_tpu_batch=8, use_tpu=True)
    cs.verifier = verifier
    fake_block = BlockID(hash=b"\x17" * 20)
    votes = [
        s.sign_vote(VOTE_TYPE_PREVOTE, TEST_CHAIN_ID, fake_block)
        for s in stubs
        if s.index != prop_idx
    ]
    # pre-load the routine's input queue directly: deterministic burst —
    # via the forwarder threads, GIL scheduling could drip votes in below
    # the batch threshold and make the batch assertion flaky
    for v in votes:
        cs._inputs.put(("msg", MsgInfo(msgs.VoteMessage(v), "peer-test")))
    cs.start()
    try:
        n = len(votes)

        def added():
            prevotes = cs.rs.votes.prevotes(0)
            if prevotes is None:
                return 0
            return sum(
                1 for s in stubs
                if s.index != prop_idx and prevotes.get_by_index(s.index) is not None
            )

        # wait for APPLICATION, not just verification: priming counts the
        # stats before the receive routine has tallied every vote
        assert wait_until(lambda: added() == n, timeout=120), (
            f"only {added()}/{n} votes added; stats {verifier.stats()}"
        )
        st = verifier.stats()
        # the burst must have landed on the batched path, not vote-by-vote
        assert st["tpu_batches"] >= 1 and st["tpu_sigs"] >= 32, st
    finally:
        cs.stop()


def test_add_peer_message_never_blocks_when_full():
    """The peer recv routine calls add_peer_message; a full queue (state
    machine behind or stopped) must DROP, not block — a blocking put
    wedges the whole multiplexed connection and hands a flooding peer a
    DoS lever (found via the fast-sync stall flake)."""
    import time as _time

    from tests.test_reactors import make_genesis, make_node

    doc, pvs = make_genesis(1)
    node = make_node(doc, pvs[0])  # cs constructed, NOT started: no drain
    cs = node.cs

    class _Msg:
        pass

    # fill the queue instantly, then verify overflow waits are BOUNDED:
    # each excess put may wait up to PEER_PUT_TIMEOUT, never forever
    for _ in range(cs.peer_msg_queue.maxsize):
        cs.add_peer_message(_Msg(), "peerX")
    assert cs.peer_msg_queue.full()
    t0 = _time.monotonic()
    for _ in range(3):
        cs.add_peer_message(_Msg(), "peerX")
    dt = _time.monotonic() - t0
    assert dt < 3 * cs.PEER_PUT_TIMEOUT + 1.0, f"wedged for {dt:.1f}s"
    assert cs._peer_msg_drops == 3
    # the sibling peer-originated entry points share the bounded helper
    from tests.test_types import BLOCK_ID
    from tendermint_tpu.types import Vote
    from tendermint_tpu.types.vote import VOTE_TYPE_PREVOTE

    v = Vote(b"\x00" * 20, 0, 1, 0, VOTE_TYPE_PREVOTE, BLOCK_ID)
    t0 = _time.monotonic()
    cs.add_vote_msg(v, "peerX")
    assert _time.monotonic() - t0 < cs.PEER_PUT_TIMEOUT + 1.0
    assert cs._peer_msg_drops == 4
