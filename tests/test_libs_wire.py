"""Direct tests for the small wire/runtime helpers that were previously
covered only transitively: codec/jsonval (the attacker-facing JSON bounds
contract), libs/flowrate, p2p/peer_set, types/protobuf (TM2PB).
Reference models: go-wire's size-capped decoding, tmlibs/flowrate,
p2p/peer_set.go, types/protobuf.go.
"""

from __future__ import annotations

import threading
import time

import pytest

from tendermint_tpu.codec import jsonval as jv


class TestJsonval:
    """Every violation must raise ValueError (the p2p receive paths turn
    that into a peer disconnect) — never crash, never allocate unbounded."""

    def test_int_field_accepts_range(self):
        assert jv.int_field({"h": 5}, "h", 0, 10) == 5
        assert jv.int_field({"h": 0}, "h", 0, jv.MAX_HEIGHT) == 0
        assert jv.int_field({"h": jv.MAX_HEIGHT}, "h", 0, jv.MAX_HEIGHT) == jv.MAX_HEIGHT

    @pytest.mark.parametrize("bad", [
        {"h": -1}, {"h": 11}, {"h": "5"}, {"h": 5.0}, {"h": None},
        {"h": True},  # bool is an int subclass; must still be rejected
        {"h": [5]}, {}, None, "not-a-dict", 7,
    ])
    def test_int_field_rejects(self, bad):
        with pytest.raises(ValueError):
            jv.int_field(bad, "h", 0, 10)

    def test_hex_field_roundtrip_and_caps(self):
        assert jv.hex_field({"x": "00ff"}, "x") == b"\x00\xff"
        assert jv.hex_field({"x": ""}, "x") == b""
        # exactly at the cap is fine; one byte over is rejected BEFORE
        # decoding (no attacker-sized allocation)
        assert jv.hex_field({"x": "ab" * 64}, "x") == b"\xab" * 64
        with pytest.raises(ValueError):
            jv.hex_field({"x": "ab" * 65}, "x")

    @pytest.mark.parametrize("bad", [
        {"x": "zz"}, {"x": "abc"}, {"x": 5}, {"x": None}, {"x": b"ab"},
        {}, None,
    ])
    def test_hex_field_rejects(self, bad):
        with pytest.raises(ValueError):
            jv.hex_field(bad, "x")

    def test_dict_field(self):
        assert jv.dict_field({"d": {"k": 1}}, "d") == {"k": 1}
        for bad in ({"d": []}, {"d": None}, {"d": "x"}, {}, None):
            with pytest.raises(ValueError):
                jv.dict_field(bad, "d")


class TestFlowrate:
    def test_status_tracks_totals_and_avg(self):
        from tendermint_tpu.libs.flowrate import Monitor

        m = Monitor(sample_period=0.01)
        for _ in range(10):
            m.update(1000)
            time.sleep(0.002)
        st = m.status()
        assert st.bytes == 10_000
        assert st.avg_rate > 0
        m.update(1000)
        assert m.status().bytes == 11_000

    def test_limit_paces_average_rate(self):
        from tendermint_tpu.libs.flowrate import Monitor

        m = Monitor()
        t0 = time.monotonic()
        sent = 0
        while sent < 3000:
            n = m.limit(1000, rate_limit=10_000)  # 10 KB/s cap
            m.update(n)
            sent += n
        elapsed = time.monotonic() - t0
        # 3 KB at 10 KB/s floor: >= ~0.2s (pacing happened); uncapped this
        # loop finishes in microseconds
        assert elapsed >= 0.15, elapsed
        assert m.limit(500, rate_limit=0) == 500  # 0 = unlimited, no sleep


class _P:
    def __init__(self, pid):
        self._pid = pid

    def id(self):
        return self._pid


class TestPeerSet:
    def test_add_get_remove(self):
        from tendermint_tpu.p2p.peer_set import PeerSet

        ps = PeerSet()
        a, b = _P("aa"), _P("bb")
        assert ps.add(a) and ps.add(b)
        assert not ps.add(_P("aa"))  # duplicate id refused
        assert ps.has("aa") and ps.get("bb") is b
        assert ps.size() == 2 and set(p.id() for p in ps.list()) == {"aa", "bb"}
        ps.remove(a)
        assert not ps.has("aa") and ps.size() == 1
        ps.remove(a)  # idempotent

    def test_cap_enforced_under_concurrent_adds(self):
        """The cap check shares the registration lock: a 32-thread dial
        burst against cap=8 admits exactly 8 (p2p/peer_set.go's
        goroutine-safety contract; wired to max_num_peers in the switch)."""
        from tendermint_tpu.p2p.peer_set import PeerSet

        ps = PeerSet()
        admitted = []
        barrier = threading.Barrier(32)

        def dial(i):
            barrier.wait()
            if ps.add(_P("p%02d" % i), cap=8):
                admitted.append(i)

        threads = [threading.Thread(target=dial, args=(i,)) for i in range(32)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(admitted) == 8 and ps.size() == 8


class TestTM2PB:
    def test_header_conversion(self):
        from tendermint_tpu.types.block import Header
        from tendermint_tpu.types.block_id import BlockID
        from tendermint_tpu.types.protobuf import tm2pb_header

        h = Header(
            chain_id="pbchain", height=9, time_ns=123, num_txs=4,
            last_block_id=BlockID(), last_commit_hash=b"", data_hash=b"",
            validators_hash=b"", app_hash=b"\x0a" * 20,
        )
        ah = tm2pb_header(h)
        assert (ah.chain_id, ah.height, ah.time_ns, ah.num_txs, ah.app_hash) == (
            "pbchain", 9, 123, 4, b"\x0a" * 20,
        )

    def test_validator_conversions(self):
        from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
        from tendermint_tpu.types.protobuf import tm2pb_validator, tm2pb_validators
        from tendermint_tpu.types.validator import Validator

        pv = gen_priv_key_ed25519(b"\x3c" * 32)
        val = Validator.new(pv.pub_key(), 7)
        av = tm2pb_validator(val)
        assert av.power == 7 and av.pub_key_json == val.pub_key.to_json()

        class GV:  # genesis-doc validator shape
            def __init__(self, pk, power):
                self.pub_key = pk
                self.power = power

        out = tm2pb_validators([GV(pv.pub_key(), 3)])
        assert len(out) == 1 and out[0].power == 3
