"""Verified read-replica tier (round 24, tendermint_tpu/replica/).

The upstream here is a REAL RPCServer over a DevChain — the replica
follows it through the same WS subscription + HTTP fetch path it uses
against a live node, so reconnect/replay, tamper detection, and the
serve-window semantics are exercised end to end in-process."""

from __future__ import annotations

import json
import queue
import time
import urllib.request

import pytest

from tendermint_tpu.config.toml import ensure_root
from tendermint_tpu.libs.events import EventSwitch
from tendermint_tpu.node.light_anchor import load_anchor
from tendermint_tpu.replica import ProofCache, ReplicaDaemon
from tendermint_tpu.rpc.client import HTTPClient, RPCClientError, WSClient
from tendermint_tpu.rpc.core.handlers import RPCError
from tendermint_tpu.rpc.core.pipe import RPCContext
from tendermint_tpu.rpc.light import LightClient, LightClientError
from tendermint_tpu.rpc.server import RPCServer
from tendermint_tpu.statesync.devchain import build_kvstore_chain
from tendermint_tpu.types import events as tev

INITIAL_HEIGHT = 6

# Completeness contract for the replica's flat metric surface — the
# replica-side twin of METRICS_REQUIRED_KEYS in tests/test_node_rpc.py
# (a separate daemon, a separate tuple). Adding a replica_* family?
# Extend this so the test below guards the new name; catalog rows live
# in docs/observability.md.
REPLICA_METRICS_REQUIRED_KEYS = (
    # follower plane
    "replica_height",
    "replica_lag_heights",
    "replica_upstream_height",
    "replica_upstream_connected",
    "replica_upstream_reconnects",
    # proof-carrying cache
    "replica_cache_hits",
    "replica_cache_misses",
    "replica_cache_entries",
    "replica_cache_invalidations",
    "replica_proof_verify_failures",
    # serving plane
    "replica_served_reads_total",
    "replica_relayed_events_total",
)


def _wait(cond, timeout: float = 15.0, every: float = 0.02, what: str = ""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(every)
    raise AssertionError(f"timed out waiting for {what or cond}")


def _drain_heights(ws, want: set[int], timeout: float = 10.0) -> set[int]:
    """Collect NewBlock heights off a WS client until `want` is covered
    (or the timeout lapses)."""
    heights: set[int] = set()
    deadline = time.monotonic() + timeout
    while (want - heights) and time.monotonic() < deadline:
        try:
            ev = ws.next_event(timeout=0.5)
        except queue.Empty:
            continue
        hdr = ((ev.get("data") or {}).get("block") or {}).get("header") or {}
        h = hdr.get("height")
        if isinstance(h, int) and not isinstance(h, bool):
            heights.add(h)
    return heights


class UpstreamSim:
    """A DevChain behind a real RPCServer: the subset of a node's
    surface a replica consumes (status/genesis/commit/validators/block/
    abci_query over HTTP, NewBlock announcements over WS). `stop()` +
    `start()` on the same port models an upstream restart."""

    def __init__(self, chain, port: int = 0):
        self.chain = chain
        self._port = port
        self.evsw: EventSwitch | None = None
        self.srv: RPCServer | None = None
        self.start()

    def _routes(self) -> dict:
        chain = self.chain
        stub = chain.rpc_stub()

        def status(ctx):
            return {
                "latest_block_height": chain.block_store.height(),
                "earliest_block_height": 1,
            }

        def genesis(ctx):
            return {"genesis": chain.genesis_doc.to_json()}

        def commit(ctx, height=0):
            return stub.commit(height)

        def validators(ctx, height=0):
            return stub.validators(height)

        def block(ctx, height=0):
            h = int(height)
            meta = chain.block_store.load_block_meta(h)
            blk = chain.block_store.load_block(h)
            return {
                "block_meta": meta.to_json() if meta else None,
                "block": blk.to_json() if blk else None,
            }

        def abci_query(ctx, data="", path="", height=0, prove=False):
            return stub.abci_query(data, path, height, prove)

        return {
            "status": (status, []),
            "genesis": (genesis, []),
            "commit": (commit, ["height"]),
            "validators": (validators, ["height"]),
            "block": (block, ["height"]),
            "abci_query": (abci_query, ["data", "path", "height", "prove"]),
        }

    def start(self) -> None:
        self.evsw = EventSwitch()
        self.evsw.start()
        ctx = RPCContext(event_switch=self.evsw)
        self.srv = RPCServer(
            f"tcp://127.0.0.1:{self._port}", ctx, routes=self._routes()
        )
        self.srv.start()
        self._port = self.srv.port

    @property
    def port(self) -> int:
        return self._port

    def announce(self, height: int) -> None:
        """What a node's consensus fires on commit — just enough of the
        NewBlock event for a follower to learn the height."""
        self.evsw.fire_event(
            tev.EVENT_NEW_BLOCK, {"block": {"header": {"height": int(height)}}}
        )

    def commit_and_announce(self, txs: list[bytes]) -> int:
        self.chain.commit_block(txs)
        h = self.chain.block_store.height()
        self.announce(h)
        return h

    def stop(self) -> None:
        srv, self.srv = self.srv, None
        if srv is None:
            return
        srv.stop()
        # in-process stop() leaves live WS sessions parked in their
        # handler threads: force-teardown so followers see EOF — the
        # in-process analogue of the upstream process dying
        for conn in list(srv.admission._ws):
            conn._teardown()
        self.evsw.stop()


@pytest.fixture(scope="module")
def sim():
    s = UpstreamSim(build_kvstore_chain(INITIAL_HEIGHT))
    yield s
    s.stop()


@pytest.fixture(scope="module")
def replica(sim, tmp_path_factory):
    home = tmp_path_factory.mktemp("replica-home")
    cfg = ensure_root(str(home))
    cfg.replica.upstream = f"127.0.0.1:{sim.port}"
    cfg.replica.laddr = "tcp://127.0.0.1:0"
    rep = ReplicaDaemon(cfg)
    rep.start()
    try:
        _wait(lambda: rep._ingested >= INITIAL_HEIGHT, what="initial catch-up")
    except BaseException:
        rep.stop()
        raise
    yield rep
    rep.stop()


def _addr(rep) -> str:
    return f"127.0.0.1:{rep.rpc_port}"


# -- proof cache units -------------------------------------------------------


class TestProofCache:
    def test_exact_get_and_latest_floor(self):
        c = ProofCache(8)
        ent = {"response": {"value": "AA"}}
        c.put("", "6b31", 5, ent)
        assert c.get("", "6B31", 5) is ent  # key hex is case-insensitive
        assert c.get_latest("", "6b31", 1) is ent
        # proven below the staleness floor -> must refetch
        assert c.get_latest("", "6b31", 6) is None
        st = c.stats()
        assert st["hits"] == 2 and st["misses"] == 1

    def test_key_invalidation_spares_pinned_reads(self):
        c = ProofCache(8)
        kh = b"k".hex()
        c.put("", kh, 5, {"v": 1})
        c.note_block(6, [b"k=new", b"other"])
        # "latest" must refetch (the key changed at 6)...
        assert c.get_latest("", kh, 1) is None
        # ...but the height-pinned proof is still a valid answer for 5
        assert c.get("", kh, 5) == {"v": 1}
        # an untouched key keeps serving latest
        c.put("", b"z".hex(), 5, {"v": 2})
        assert c.get_latest("", b"z".hex(), 1) == {"v": 2}

    def test_all_mode_invalidates_every_key(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_REPLICA_INVALIDATE", "all")
        c = ProofCache(8)
        c.put("", b"a".hex(), 5, {"v": 1})
        c.note_block(6, [b"unrelated-opaque-tx"])
        assert c.get_latest("", b"a".hex(), 1) is None
        assert c.get("", b"a".hex(), 5) == {"v": 1}

    def test_lru_eviction_clears_latest_pointer(self):
        c = ProofCache(2)
        c.put("", "aa", 1, {"n": 1})
        c.put("", "bb", 2, {"n": 2})
        c.put("", "cc", 3, {"n": 3})  # evicts ("", "aa", 1)
        assert c.stats()["entries"] == 2
        assert c.get("", "aa", 1) is None
        assert c.get_latest("", "aa", 1) is None  # no dangling pointer

    def test_prune_drops_stale_touch_rows(self):
        c = ProofCache(8)
        c.note_block(3, [b"a=1"])
        c.note_block(9, [b"b=2"])
        c.prune(5)
        assert b"a".hex() not in c._touched
        assert c._touched[b"b".hex()] == 9


# -- daemon construction guards ---------------------------------------------


def test_upstream_is_required(tmp_path):
    cfg = ensure_root(str(tmp_path))
    with pytest.raises(ValueError, match="upstream"):
        ReplicaDaemon(cfg)


def test_query_before_bootstrap_is_typed_warming(tmp_path):
    cfg = ensure_root(str(tmp_path))
    cfg.replica.upstream = "127.0.0.1:1"
    rep = ReplicaDaemon(cfg)  # never started: no verified state
    with pytest.raises(RPCError, match="replica_warming"):
        rep.query(data=b"k".hex(), height=0)


# -- follow + verified read path ---------------------------------------------


def test_follows_upstream_and_serves_verified_reads(replica):
    """A client light pointed at the replica verifies end to end —
    trust bootstraps from the replica's /genesis, advances through its
    re-served commits, and the proof checks against the walk."""
    lc = LightClient.from_genesis(HTTPClient(_addr(replica)))
    res = lc.verified_query(b"k3-0")
    assert res["value"] == b"v3"
    assert not res["absent"]

    hits0 = replica.cache.stats()["hits"]
    reads0 = replica.served_reads_total
    res2 = lc.verified_query(b"k3-0")
    assert res2["value"] == b"v3"
    assert res2["height"] == res["height"]
    assert replica.cache.stats()["hits"] >= hits0 + 1
    assert replica.served_reads_total >= reads0 + 1
    assert replica.proof_verify_failures == 0


def test_status_carries_replica_identity_and_lag(replica):
    st = HTTPClient(_addr(replica)).status()
    assert st["node_info"]["replica"] is True
    assert st["node_info"]["upstream"] == replica.upstream
    assert st["latest_block_height"] >= INITIAL_HEIGHT
    assert st["earliest_block_height"] >= 1
    assert st["replica_lag_heights"] == 0
    assert st["replica"]["connected"] is True
    assert st["replica"]["max_lag_heights"] == replica.max_lag()


def test_block_and_blockchain_windows(replica):
    c = HTTPClient(_addr(replica))
    h = replica._ingested
    blk = c.block(height=h)
    assert blk["block"]["header"]["height"] == h
    info = c.blockchain(min_height=1, max_height=h)
    assert info["last_height"] >= h
    metas = info["block_metas"]
    assert metas, "replica served an empty recent window"
    got = [m["header"]["height"] for m in metas]
    assert got == sorted(got, reverse=True)  # newest first
    # outside the verified window: typed error naming the window start
    with pytest.raises(RPCClientError, match="no commit"):
        c.commit(height=10_000)


def test_metrics_on_both_surfaces(replica):
    flat = HTTPClient(_addr(replica)).metrics()
    for key in REPLICA_METRICS_REQUIRED_KEYS:
        assert key in flat, f"missing {key} in replica metrics"
    assert flat["replica_height"] >= INITIAL_HEIGHT
    # the round-23 ingress plane runs on the replica's own listener
    assert "rpc_inflight" in flat
    body = urllib.request.urlopen(
        f"http://{_addr(replica)}/metrics", timeout=10
    ).read().decode()
    assert "replica_height" in body
    assert "replica_served_reads_total" in body


def test_follower_absorbs_upstream_sheds_as_pacing(replica):
    # a rate-limited upstream answers `shed:<reason>` (HTTP 429/503);
    # the follower must retry through it, not raise into the
    # reconnect path — and anything non-shed must still propagate
    calls = []

    def shed_twice():
        calls.append(1)
        if len(calls) < 3:
            raise RPCClientError("shed:rate_limited")
        return "through"

    assert replica._shed_paced(shed_twice) == "through"
    assert len(calls) == 3

    def hard_fail():
        raise RPCClientError("HTTP 500")

    with pytest.raises(RPCClientError, match="HTTP 500"):
        replica._shed_paced(hard_fail)


def test_health_probe(replica):
    with urllib.request.urlopen(
        f"http://{_addr(replica)}/health", timeout=10
    ) as resp:
        assert resp.status == 200
        report = json.loads(resp.read().decode())
    assert report["status"] == "ok"
    assert report["checks"]["upstream_connected"]["ok"] is True


def test_stale_replica_refuses_latest_reads(replica, monkeypatch):
    monkeypatch.setenv("TENDERMINT_REPLICA_MAX_LAG_HEIGHTS", "2")
    old = replica.upstream_height
    replica.upstream_height = replica._ingested + 5
    try:
        with pytest.raises(RPCClientError, match="replica_stale"):
            HTTPClient(_addr(replica)).abci_query(
                data=b"k1-0".hex(), path="", height=0, prove=True
            )
    finally:
        replica.upstream_height = old


# -- tamper: a lying replica is detected, never trusted ----------------------


def test_tampered_responses_rejected_client_side(replica, monkeypatch):
    """ISSUE acceptance: flipping one byte in a cached value or proof is
    rejected by EVERY verifying client — 100%, both tamper modes."""
    keys = [b"k1-0", b"k2-1", b"k3-0", b"k4-1", b"k5-0"]
    for mode in ("value", "proof"):
        monkeypatch.setenv("TENDERMINT_REPLICA_TAMPER", mode)
        lc = LightClient.from_genesis(HTTPClient(_addr(replica)))
        rejected = 0
        for key in keys:
            with pytest.raises(LightClientError):
                lc.verified_query(key)
            rejected += 1
        assert rejected == len(keys)
    # the knob corrupts at serve time only: clean env serves clean bytes
    monkeypatch.delenv("TENDERMINT_REPLICA_TAMPER")
    lc = LightClient.from_genesis(HTTPClient(_addr(replica)))
    assert lc.verified_query(b"k3-0")["value"] == b"v3"


# -- WS relay lifecycle ------------------------------------------------------


def test_event_relay_one_upstream_many_clients(replica, sim):
    subs = [WSClient(_addr(replica)) for _ in range(3)]
    try:
        for ws in subs:
            ws.subscribe(tev.EVENT_NEW_BLOCK)
        h = sim.commit_and_announce([b"relay-1=r1"])
        _wait(lambda: replica._ingested >= h, what=f"ingest of {h}")
        for ws in subs:
            assert h in _drain_heights(ws, {h})
    finally:
        for ws in subs:
            ws.close()


def test_client_eviction_never_tears_down_upstream_sub(replica, sim):
    ws = WSClient(_addr(replica))
    ws.subscribe(tev.EVENT_NEW_BLOCK)
    _wait(lambda: len(replica._rpc.admission._ws) >= 1, what="ws register")
    # force-evict EVERY downstream subscriber (what queue-overflow
    # eviction does) — the shared upstream subscription must survive
    for conn in list(replica._rpc.admission._ws):
        conn._teardown()
    reconnects0 = replica.upstream_reconnects
    h = sim.commit_and_announce([b"evict-1=e1"])
    _wait(lambda: replica._ingested >= h, what=f"ingest of {h}")
    assert replica.upstream_reconnects == reconnects0
    # and a fresh subscriber picks up the stream
    ws2 = WSClient(_addr(replica))
    try:
        ws2.subscribe(tev.EVENT_NEW_BLOCK)
        h2 = sim.commit_and_announce([b"evict-2=e2"])
        assert h2 in _drain_heights(ws2, {h2})
    finally:
        ws2.close()
        ws.close()


def test_upstream_drop_reconnects_and_replays(replica, sim):
    """Upstream restart: the follower re-subscribes with backoff and
    replays the heights committed while it was dark — downstream WS
    clients see every replayed block, none skipped."""
    ws = WSClient(_addr(replica))
    try:
        ws.subscribe(tev.EVENT_NEW_BLOCK)
        reconnects0 = replica.upstream_reconnects
        sim.stop()
        _wait(lambda: not replica.connected, what="drop detection")
        # two blocks commit while the replica is dark
        sim.chain.build(2, tx_fn=lambda h: [b"dark-%d=d%d" % (h, h)])
        sim.start()
        target = sim.chain.block_store.height()
        _wait(lambda: replica._ingested >= target, timeout=30,
              what=f"replay to {target}")
        assert replica.upstream_reconnects > reconnects0
        assert replica.connected
        # both missed heights were relayed to the surviving client
        missed = {target - 1, target}
        assert _drain_heights(ws, missed) >= missed
    finally:
        ws.close()
    # the replayed state serves verified reads immediately (a proof at
    # the newest provable height: header H commits block H-1's state)
    lc = LightClient.from_genesis(HTTPClient(_addr(replica)))
    h = replica._ingested - 1
    assert lc.verified_query(b"dark-%d" % h)["value"] == b"d%d" % h


# -- tiering: a replica follows a replica ------------------------------------


def test_replica_chains_behind_replica(replica, tmp_path_factory):
    home = tmp_path_factory.mktemp("replica-b")
    cfg = ensure_root(str(home))
    cfg.replica.upstream = _addr(replica)
    cfg.replica.laddr = "tcp://127.0.0.1:0"
    b = ReplicaDaemon(cfg)
    b.start()
    try:
        head = replica._ingested
        _wait(lambda: b._ingested >= head, what="tier-2 catch-up")
        lc = LightClient.from_genesis(HTTPClient(_addr(b)))
        res = lc.verified_query(b"k2-0")
        assert res["value"] == b"v2"
        assert b.proof_verify_failures == 0
    finally:
        b.stop()
    # stop persisted the trust anchor: a restart resumes, not re-walks
    anchor = load_anchor(cfg.replica.root_dir, b.genesis_doc.chain_id)
    assert anchor is not None
    assert anchor[0] >= 2
