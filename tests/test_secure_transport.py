"""In-repo secure-transport primitives (round 12): RFC 7748 X25519,
RFC 8439 ChaCha20-Poly1305, pure secp256k1 ECDSA, and the
SecretConnection failure semantics built on them (docs/secure-p2p.md).

Every implementation is pinned to the published RFC test vectors, and
whenever an alternative backend is importable (the `cryptography`
package or the ctypes libcrypto bindings) the pure path is cross-checked
against it byte-for-byte — the parity-oracle contract that lets `auto`
pick the fastest backend without ever changing wire bytes."""

from __future__ import annotations

import socket
import threading
import time

import pytest

from tendermint_tpu.crypto import _openssl
from tendermint_tpu.crypto import chacha20poly1305 as aead
from tendermint_tpu.crypto import secp256k1
from tendermint_tpu.crypto import x25519 as x
from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
from tendermint_tpu.p2p.secret_connection import (
    HandshakeTimeout,
    SecretConnection,
    SecretConnectionError,
)
from tendermint_tpu.p2p.stream import SocketStream, pipe_pair

# -- RFC 7748 X25519 ----------------------------------------------------------


class TestX25519Vectors:
    def test_rfc7748_section_5_2_vector_1(self):
        k = bytes.fromhex(
            "a546e36bf0527c9d3b16154b82465edd62144c0ac1fc5a18506a2244ba449ac4"
        )
        u = bytes.fromhex(
            "e6db6867583030db3594c1a424b15f7c726624ec26b3353b10a903a6d0ab1c4c"
        )
        assert x.x25519(k, u) == bytes.fromhex(
            "c3da55379de9c6908e94ea4df28d084f32eccf03491c71f754b4075577a28552"
        )

    def test_rfc7748_section_5_2_vector_2(self):
        k = bytes.fromhex(
            "4b66e9d4d1b4673c5ad22691957d6af5c11b6421e0ea01d42ca4169e7918ba0d"
        )
        u = bytes.fromhex(
            "e5210f12786811d3f4b7959d0538ae2c31dbe7106fc03c3efc4cd549c715a493"
        )
        assert x.x25519(k, u) == bytes.fromhex(
            "95cbde9476e8907d7aade45cb4b873f88b595a68799fa152e6f8f7647aac7957"
        )

    def test_rfc7748_iterated_ladder_one(self):
        k = u = x.BASE_POINT
        k = x.scalar_mult(k, u)
        assert k == bytes.fromhex(
            "422c8e7a6227d7bca1350b3e2bb7279f7897b87bb6854b783c60e80311ae3079"
        )

    @pytest.mark.slow
    def test_rfc7748_iterated_ladder_1000(self):
        # ~2.5 s of bigint ladder: slow tier by budget, not fragility
        k, u = x.BASE_POINT, x.BASE_POINT
        for _ in range(1000):
            k, u = x.scalar_mult(k, u), k
        assert k == bytes.fromhex(
            "684cf59ba83309552800ef566f2f4d3c1c3887c49360e3875f2eb94d99532c51"
        )

    def test_rfc7748_section_6_1_diffie_hellman(self):
        a = bytes.fromhex(
            "77076d0a7318a57d3c16c17251b26645df4c2f87ebc0992ab177fba51db92c2a"
        )
        b = bytes.fromhex(
            "5dab087e624a8a4b79e17f8b83800ee66f3bb1292618b6fd1c2f8b27ff88e0eb"
        )
        a_pub = x.public_from_private(a)
        b_pub = x.public_from_private(b)
        assert a_pub == bytes.fromhex(
            "8520f0098930a754748b7ddcb43ef75a0dbf3a0d26381af4eba4a98eaa9b4e6a"
        )
        assert b_pub == bytes.fromhex(
            "de9edb7d7b7dc1b4d35b61c2ece435373f8343c85b78674dadfc7e146f882b4f"
        )
        shared = bytes.fromhex(
            "4a5d9d5ba4ce2de1728e3bf480350f25e07e21c947d19e3376f09b3c1e161742"
        )
        assert x.x25519(a, b_pub) == shared
        assert x.x25519(b, a_pub) == shared

    def test_low_order_point_rejected(self):
        k = bytes.fromhex(
            "77076d0a7318a57d3c16c17251b26645df4c2f87ebc0992ab177fba51db92c2a"
        )
        with pytest.raises(x.X25519Error):
            x.x25519(k, b"\x00" * 32)  # order-1 point -> all-zero secret

    def test_length_validation(self):
        with pytest.raises(x.X25519Error):
            x.scalar_mult(b"\x01" * 31, x.BASE_POINT)
        with pytest.raises(x.X25519Error):
            x.scalar_mult(b"\x01" * 32, b"\x02" * 33)

    def test_key_objects_roundtrip_any_backend(self):
        # whatever `auto` resolves to on this host, two fresh keys agree
        a = x.X25519PrivateKey.generate()
        b = x.X25519PrivateKey.generate(backend="pure")
        s1 = a.exchange(b.public_key())
        s2 = b.exchange(a.public_key())
        assert s1 == s2 and len(s1) == 32


# -- RFC 8439 ChaCha20-Poly1305 -----------------------------------------------

_SUNSCREEN = (
    b"Ladies and Gentlemen of the class of '99: If I could offer you "
    b"only one tip for the future, sunscreen would be it."
)


class TestChaCha20Poly1305Vectors:
    def test_rfc8439_2_3_2_block(self):
        key = bytes(range(32))
        nonce = bytes.fromhex("000000090000004a00000000")
        assert aead.chacha20_block(key, 1, nonce) == bytes.fromhex(
            "10f1e7e4d13b5915500fdd1fa32071c4c7d1f4c733c068030422aa9a"
            "c3d46c4ed2826446079faa0914c2d705d98b02a2b5129cd1de164eb9"
            "cbd083e8a2503c4e"
        )

    def test_rfc8439_2_4_2_encryption(self):
        key = bytes(range(32))
        nonce = bytes.fromhex("000000000000004a00000000")
        ct = aead.chacha20_xor(key, 1, nonce, _SUNSCREEN)
        assert ct == bytes.fromhex(
            "6e2e359a2568f98041ba0728dd0d6981e97e7aec1d4360c20a27afcc"
            "fd9fae0bf91b65c5524733ab8f593dabcd62b3571639d624e65152ab"
            "8f530c359f0861d807ca0dbf500d6a6156a38e088a22b65e52bc514d"
            "16ccf806818ce91ab77937365af90bbf74a35be6b40b8eedf2785e42"
            "874d"
        )
        # xor is its own inverse
        assert aead.chacha20_xor(key, 1, nonce, ct) == _SUNSCREEN

    def test_rfc8439_2_5_2_poly1305(self):
        key = bytes.fromhex(
            "85d6be7857556d337f4452fe42d506a80103808afb0db2fd4abff6af4149f51b"
        )
        tag = aead.poly1305_mac(key, b"Cryptographic Forum Research Group")
        assert tag == bytes.fromhex("a8061dc1305136c6c22b8baf0c0127a9")

    def test_rfc8439_2_8_2_aead_seal_open(self):
        key = bytes(range(0x80, 0xA0))
        nonce = bytes.fromhex("070000004041424344454647")
        aad = bytes.fromhex("50515253c0c1c2c3c4c5c6c7")
        boxed = aead.seal(key, nonce, _SUNSCREEN, aad)
        assert boxed[-16:] == bytes.fromhex("1ae10b594f09e26a7e902ecbd0600691")
        assert boxed[:-16] == bytes.fromhex(
            "d31a8d34648e60db7b86afbc53ef7ec2a4aded51296e08fea9e2b5a7"
            "36ee62d63dbea45e8ca9671282fafb69da92728b1a71de0a9e060b29"
            "05d6a5b67ecd3b3692ddbd7f2d778b8c9803aee328091b58fab324e4"
            "fad675945585808b4831d7bc3ff4def08e4b7a9de576d26586cec64b"
            "6116"
        )
        assert aead.open_(key, nonce, boxed, aad) == _SUNSCREEN

    def test_tamper_and_truncation_rejected(self):
        key, nonce = bytes(32), bytes(12)
        boxed = aead.seal(key, nonce, b"payload", b"aad")
        for bad in (
            boxed[:-1] + bytes([boxed[-1] ^ 1]),  # flipped tag bit
            bytes([boxed[0] ^ 1]) + boxed[1:],  # flipped ciphertext bit
            boxed[:-1],  # truncated tag
            boxed[:15],  # shorter than a tag
            b"",
        ):
            with pytest.raises(aead.InvalidTag):
                aead.open_(key, nonce, bad, b"aad")
        # wrong aad / wrong nonce / wrong key all fail the tag
        with pytest.raises(aead.InvalidTag):
            aead.open_(key, nonce, boxed, b"other")
        with pytest.raises(aead.InvalidTag):
            aead.open_(key, bytes(11) + b"\x01", boxed, b"aad")
        with pytest.raises(aead.InvalidTag):
            aead.open_(b"\x01" + key[1:], nonce, boxed, b"aad")

    def test_empty_plaintext(self):
        key, nonce = bytes(32), bytes(12)
        boxed = aead.seal(key, nonce, b"", b"")
        assert len(boxed) == 16
        assert aead.open_(key, nonce, boxed, b"") == b""

    def test_counter_wraps_modulo_2_32(self):
        # RFC 8439 2.3: the 32-bit counter wraps; block(2^32) == block(0)
        key, nonce = bytes(range(32)), bytes(12)
        assert aead.chacha20_block(key, 1 << 32, nonce) == aead.chacha20_block(
            key, 0, nonce
        )


# -- cross-backend parity (the oracle contract) -------------------------------


class TestBackendParity:
    def _pairs(self):
        import os

        rnd = os.urandom
        for n in (0, 1, 15, 16, 17, 64, 1024, 4096):
            yield rnd(32), rnd(12), rnd(n), rnd(7)

    @pytest.mark.skipif(not _openssl.available(), reason="parity oracle: no libcrypto")
    def test_openssl_aead_matches_pure(self):
        for key, nonce, pt, aad in self._pairs():
            boxed = aead.seal(key, nonce, pt, aad)
            assert _openssl.aead_seal(key, nonce, pt, aad) == boxed
            assert _openssl.aead_open(key, nonce, boxed, aad) == pt
            assert aead.open_(key, nonce, boxed, aad) == pt
            tampered = boxed[:-1] + bytes([boxed[-1] ^ 0x80])
            assert _openssl.aead_open(key, nonce, tampered, aad) is None

    @pytest.mark.skipif(not _openssl.available(), reason="parity oracle: no libcrypto")
    def test_openssl_x25519_matches_pure(self):
        import os

        for _ in range(4):
            a, b = os.urandom(32), os.urandom(32)
            a_pub = x.public_from_private(a)
            b_pub = x.public_from_private(b)
            assert _openssl.x25519_public(a) == a_pub
            assert _openssl.x25519_derive(a, b_pub) == x.x25519(a, b_pub)
        assert _openssl.x25519_derive(a, b"\x00" * 32) is None

    @pytest.mark.skipif(not aead.have_native(), reason="parity oracle: cryptography absent")
    def test_native_aead_matches_pure(self):
        for key, nonce, pt, aad in self._pairs():
            nat = aead.ChaCha20Poly1305(key, backend="native")
            pure = aead.ChaCha20Poly1305(key, backend="pure")
            boxed = nat.encrypt(nonce, pt, aad)
            assert boxed == pure.encrypt(nonce, pt, aad)
            assert nat.decrypt(nonce, boxed, aad) == pt
            assert pure.decrypt(nonce, boxed, aad) == pt

    @pytest.mark.skipif(not x.have_native(), reason="parity oracle: cryptography absent")
    def test_native_x25519_matches_pure(self):
        import os

        a = x.X25519PrivateKey.from_private_bytes(os.urandom(32), backend="native")
        b = x.X25519PrivateKey.from_private_bytes(os.urandom(32), backend="pure")
        assert (
            x.public_from_private(a.private_bytes_raw())
            == a.public_key().public_bytes_raw()
        )
        assert a.exchange(b.public_key()) == b.exchange(a.public_key())

    def test_pinned_unavailable_backend_raises(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_SECRETCONN_BACKEND", "native")
        if x.have_native():
            assert x.resolve_backend() == "native"
        else:
            with pytest.raises(RuntimeError):
                x.resolve_backend()

    def test_unknown_backend_value_falls_back(self, monkeypatch):
        # envknob contract: a typo warns and uses the default, never dies
        monkeypatch.setenv("TENDERMINT_SECRETCONN_BACKEND", "quantum")
        assert x.resolve_backend() in ("pure", "native", "openssl")


# -- pure secp256k1 -----------------------------------------------------------


class TestSecp256k1Pure:
    def test_rfc6979_known_vector(self):
        # key = 1, msg "Satoshi Nakamoto" — the classic deterministic-
        # nonce vector; proves the RFC 6979 k-derivation, not just
        # roundtrip consistency
        sig = secp256k1.sign_py((1).to_bytes(32, "big"), b"Satoshi Nakamoto")
        r, s = secp256k1.decode_der(sig)
        assert r == 0x934B1EA10A4B3C1757E2B0C017D0B6143CE3C9A7E6A4A49860D7A6AB210EE3D8
        assert s == 0x2442CE9D2B916064108014783E923EC36B49743E2FFA1C4496F01A512AAFD9E5

    def test_pure_sign_verify_and_determinism(self):
        sec = secp256k1.secret_from_seed(b"pure-secp")
        pub = secp256k1.public_key_py(sec)
        assert len(pub) == 33 and pub[0] in (2, 3)
        sig = secp256k1.sign_py(sec, b"msg")
        assert sig == secp256k1.sign_py(sec, b"msg")  # RFC 6979
        assert secp256k1.verify_py(pub, b"msg", sig)
        assert not secp256k1.verify_py(pub, b"other", sig)

    def test_der_strictness(self):
        sec = secp256k1.secret_from_seed(b"der")
        pub = secp256k1.public_key_py(sec)
        sig = secp256k1.sign_py(sec, b"m")
        r, s = secp256k1.decode_der(sig)
        # trailing garbage, padded int, high-s: all refused
        assert not secp256k1.verify_py(pub, b"m", sig + b"\x00")
        with pytest.raises(ValueError):
            secp256k1.decode_der(sig + b"\x00")
        padded = (
            b"\x30"
            + bytes([len(sig)])
            + b"\x02"
            + bytes([(sig[3] + 1)])
            + b"\x00"
            + sig[4 : 4 + sig[3]]
        )
        with pytest.raises(ValueError):
            secp256k1.decode_der(padded + sig[4 + sig[3] :])
        assert not secp256k1.verify_py(
            pub, b"m", secp256k1.encode_der(r, secp256k1._N - s)
        )

    def test_garbage_pubkey_and_sig(self):
        sec = secp256k1.secret_from_seed(b"g")
        sig = secp256k1.sign_py(sec, b"m")
        assert not secp256k1.verify_py(b"\x02" + b"\xff" * 32, b"m", sig)  # off-curve
        assert not secp256k1.verify_py(b"\x05" + b"\x01" * 32, b"m", sig)  # bad prefix
        assert not secp256k1.verify_py(
            secp256k1.public_key_py(sec), b"m", b"\x30\x02\x02\x00"
        )

    @pytest.mark.skipif(not secp256k1._HAVE_OPENSSL, reason="parity oracle: cryptography absent")
    def test_cross_backend(self):
        sec = secp256k1.secret_from_seed(b"cross")
        assert secp256k1.public_key(sec) == secp256k1.public_key_py(sec)
        # native signature (random nonce) verifies under the pure
        # verifier and vice versa (deterministic nonce)
        assert secp256k1.verify_py(
            secp256k1.public_key(sec), b"m", secp256k1.sign(sec, b"m")
        )
        assert secp256k1.verify(
            secp256k1.public_key(sec), b"m", secp256k1.sign_py(sec, b"m")
        )


# -- SecretConnection failure semantics ---------------------------------------


def _handshake_pair(stream_a, stream_b, ka=None, kb=None, **kw):
    ka = ka or gen_priv_key_ed25519()
    kb = kb or gen_priv_key_ed25519()
    out, err = {}, []

    def srv():
        try:
            out["conn"] = SecretConnection(stream_b, kb, **kw)
        except Exception as exc:  # noqa: BLE001 — surfaced by the test
            err.append(exc)

    t = threading.Thread(target=srv, daemon=True)
    t.start()
    ca = SecretConnection(stream_a, ka, **kw)
    t.join(10)
    assert not err, err
    return ca, out["conn"]


class TestSecretConnectionSemantics:
    def test_cross_backend_wire_parity(self, monkeypatch):
        # one side pure, the other side auto (openssl/native when
        # present): the wire protocol must not care
        a, b = pipe_pair()
        kb = gen_priv_key_ed25519()
        out = {}
        t = threading.Thread(
            target=lambda: out.update(conn=SecretConnection(b, kb)), daemon=True
        )
        t.start()
        monkeypatch.setenv("TENDERMINT_SECRETCONN_BACKEND", "pure")
        ca = SecretConnection(a, gen_priv_key_ed25519())
        t.join(10)
        assert ca.backend == "pure"
        ca.write(b"hello across backends")
        got = bytearray()
        while len(got) < 21:
            got += out["conn"].read(64)
        assert bytes(got) == b"hello across backends"
        out["conn"].write(b"pong")
        assert ca.read(16) == b"pong"

    def test_bit_flipped_frame_raises_not_eof(self):
        s1, s2 = socket.socketpair()
        ca, cb = _handshake_pair(SocketStream(s1), SocketStream(s2))
        # capture a REAL frame a would send, flip one payload bit, and
        # deliver the damaged bytes (regression: this used to read b"")
        frames = []
        real_write = ca.stream.write
        ca.stream.write = lambda data: frames.append(bytes(data))
        ca.write(b"legitimate payload")
        ca.stream.write = real_write
        (frame,) = frames
        bad = bytearray(frame)
        bad[4] ^= 0x01  # inside the ciphertext, framing intact
        real_write(bytes(bad))
        with pytest.raises(SecretConnectionError):
            cb.read(64)
        with pytest.raises(SecretConnectionError):  # poisoned
            cb.read(1)
        ca.close()

    def test_clean_eof_still_reads_empty(self):
        s1, s2 = socket.socketpair()
        ca, cb = _handshake_pair(SocketStream(s1), SocketStream(s2))
        ca.close()
        assert cb.read(16) == b""

    def test_handshake_deadline_on_silent_peer(self):
        s1, s2 = socket.socketpair()
        t0 = time.monotonic()
        with pytest.raises(HandshakeTimeout):
            SecretConnection(SocketStream(s1), gen_priv_key_ed25519(),
                             handshake_timeout_s=0.4)
        assert time.monotonic() - t0 < 5.0
        s1.close()
        s2.close()

    def test_handshake_deadline_on_dribbling_peer(self):
        # a peer leaking one byte at a time must hit the ABSOLUTE
        # deadline, not reset a per-read timer forever
        s1, s2 = socket.socketpair()

        def dribble():
            try:
                for i in range(64):
                    s2.sendall(bytes([i]))
                    time.sleep(0.05)
            except OSError:
                pass

        threading.Thread(target=dribble, daemon=True).start()
        t0 = time.monotonic()
        with pytest.raises(HandshakeTimeout):
            SecretConnection(SocketStream(s1), gen_priv_key_ed25519(),
                             handshake_timeout_s=0.5)
        assert time.monotonic() - t0 < 5.0
        s1.close()
        s2.close()

    def test_telemetry_counters_move(self):
        from tendermint_tpu.libs import telemetry

        reg = telemetry.default_registry()
        ok0 = reg.counter("p2p_secretconn_handshakes_total").value
        to0 = reg.counter("p2p_secretconn_handshake_timeouts_total").value
        af0 = reg.counter("p2p_secretconn_auth_failures_total").value
        a, b = pipe_pair()
        ca, cb = _handshake_pair(a, b)
        assert reg.counter("p2p_secretconn_handshakes_total").value >= ok0 + 2
        s1, s2 = socket.socketpair()
        with pytest.raises(HandshakeTimeout):
            SecretConnection(SocketStream(s1), gen_priv_key_ed25519(),
                             handshake_timeout_s=0.2)
        assert (
            reg.counter("p2p_secretconn_handshake_timeouts_total").value
            == to0 + 1
        )
        ca.stream.write(b"\x00\x20" + b"\x00" * 32)
        with pytest.raises(SecretConnectionError):
            cb.read(8)
        assert (
            reg.counter("p2p_secretconn_auth_failures_total").value == af0 + 1
        )
        s1.close()
        s2.close()
        ca.close()
