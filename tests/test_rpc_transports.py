"""RPC framework transport matrix — echo calls over HTTP(TCP),
HTTP(unix socket), URI-GET, and WebSocket on both listeners (reference:
rpc/lib/rpc_test.go:40-75 runs the same echo handler over HTTP, WS and
unix transports; server side rpc/lib/server/http_server.go:20-40)."""

from __future__ import annotations

import json
import os
import tempfile
import urllib.request

import pytest

from tendermint_tpu.rpc.client import HTTPClient, WSClient, _UnixHTTPConnection
from tendermint_tpu.rpc.server import RPCServer, is_unix_laddr


def _echo(ctx, value=None):
    return {"value": value}


class _Ctx:
    event_switch = None


def _make_server(laddr: str) -> RPCServer:
    srv = RPCServer(laddr, _Ctx())
    # the framework test exercises transports, not the core route table:
    # swap in the reference test's echo handler (rpc_test.go:24-38)
    srv.routes = {"echo": (_echo, ["value"])}
    srv.start()
    return srv


@pytest.fixture(scope="module")
def tcp_server():
    srv = _make_server("127.0.0.1:0")
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def unix_server():
    path = os.path.join(tempfile.mkdtemp(prefix="rpc-unix-"), "rpc.sock")
    srv = _make_server(f"unix://{path}")
    yield srv
    srv.stop()


def test_is_unix_laddr():
    assert is_unix_laddr("unix:///tmp/x.sock")
    assert is_unix_laddr("/tmp/x.sock")
    assert not is_unix_laddr("tcp://0.0.0.0:46657".split("://", 1)[-1])
    assert not is_unix_laddr("127.0.0.1:0")


def test_http_echo_over_tcp(tcp_server):
    c = HTTPClient(f"127.0.0.1:{tcp_server.port}")
    assert c.echo(value="hello")["value"] == "hello"


def test_http_echo_over_unix(unix_server):
    c = HTTPClient(f"unix://{unix_server.unix_path}")
    assert c.echo(value="hello-unix")["value"] == "hello-unix"
    # round-trip non-ASCII and structured params like the reference's
    # random-string echo loop (rpc_test.go:118-130)
    assert c.echo(value=["a", 1, {"b": None}])["value"] == ["a", 1, {"b": None}]


def test_uri_get_over_tcp(tcp_server):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{tcp_server.port}/echo?value=%22x%22"
    ) as resp:
        body = json.loads(resp.read().decode())
    assert body["result"]["value"] == "x"


def test_uri_get_over_unix(unix_server):
    conn = _UnixHTTPConnection(unix_server.unix_path, timeout=10.0)
    try:
        conn.request("GET", '/echo?value="y"')
        body = json.loads(conn.getresponse().read().decode())
    finally:
        conn.close()
    assert body["result"]["value"] == "y"


def test_ws_echo_over_tcp(tcp_server):
    ws = WSClient(f"127.0.0.1:{tcp_server.port}")
    try:
        assert ws.call("echo", value="ws")["value"] == "ws"
    finally:
        ws.close()


def test_ws_echo_over_unix(unix_server):
    ws = WSClient(f"unix://{unix_server.unix_path}")
    try:
        assert ws.call("echo", value="ws-unix")["value"] == "ws-unix"
    finally:
        ws.close()


def test_tcp_scheme_accepted():
    """The documented \"tcp://host:port\" form must construct (the scheme
    is stripped), matching the unix:// branch's behavior."""
    srv = _make_server("tcp://127.0.0.1:0")
    try:
        c = HTTPClient(f"127.0.0.1:{srv.port}")
        assert c.echo(value=1)["value"] == 1
    finally:
        srv.stop()


def test_http_client_keepalive_reuses_connection(tcp_server):
    """Round-24 satellite: the replica's upstream fetch path must NOT
    pay a TCP handshake per request — one thread keeps one connection."""
    c = HTTPClient(f"127.0.0.1:{tcp_server.port}")
    assert c.echo(value=1)["value"] == 1
    conn1 = c._local.conn
    assert conn1 is not None
    assert c.echo(value=2)["value"] == 2
    assert c._local.conn is conn1
    assert c.reconnects == 0
    c.close()
    assert c._local.conn is None


def test_http_client_reconnects_on_eof(tcp_server):
    """Regression: EOF on the persistent connection (server restart,
    idle timeout) heals with ONE transparent rebuild + resend."""
    c = HTTPClient(f"127.0.0.1:{tcp_server.port}")
    assert c.echo(value="a")["value"] == "a"
    # sever the kept-alive connection out from under the client — what
    # the far end going away looks like to the next request
    c._local.conn.sock.close()
    assert c.echo(value="b")["value"] == "b"
    assert c.reconnects == 1
    # healed connection persists again
    assert c.echo(value="c")["value"] == "c"
    assert c.reconnects == 1
    c.close()


def test_http_client_fresh_connection_failure_raises():
    """A server that is genuinely down raises to the caller — the
    retry-once path is only for connections that died while parked."""
    srv = _make_server("127.0.0.1:0")
    port = srv.port
    srv.stop()
    c = HTTPClient(f"127.0.0.1:{port}")
    with pytest.raises(OSError):
        c.echo(value=1)
    assert c.reconnects == 0


def test_http_client_keepalive_over_unix(unix_server):
    c = HTTPClient(f"unix://{unix_server.unix_path}")
    assert c.echo(value="u1")["value"] == "u1"
    conn1 = c._local.conn
    assert conn1 is not None
    assert c.echo(value="u2")["value"] == "u2"
    assert c._local.conn is conn1
    c.close()


def test_unix_bind_refuses_to_delete_regular_file():
    """A mistyped laddr pointing at an existing regular file must fail at
    bind WITHOUT deleting the file."""
    path = os.path.join(tempfile.mkdtemp(prefix="rpc-unix-"), "precious.txt")
    with open(path, "w") as f:
        f.write("do not delete")
    with pytest.raises(OSError):
        RPCServer(f"unix://{path}", _Ctx())
    assert open(path).read() == "do not delete"


def test_unix_socket_removed_on_stop():
    path = os.path.join(tempfile.mkdtemp(prefix="rpc-unix-"), "gone.sock")
    srv = _make_server(f"unix://{path}")
    assert os.path.exists(path)
    srv.stop()
    assert not os.path.exists(path)
