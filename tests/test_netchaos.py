"""Real-TCP chaos scenario matrix (round 12, docs/secure-p2p.md).

Every test here runs FULL nodes (node/node.py — consensus, mempool,
fast sync, statesync, RPC) over real TCP listeners with the in-repo
SecretConnection encrypting every byte, all traffic relayed through
`ops/netfaults.LinkProxy` fault proxies. No loopback fabric anywhere.
The convergence assertion is the same byte-identity the existing soaks
use: (block hash, part-set root, app hash, evidence hash) per height,
identical across every node.

The whole matrix is slow-marked (the ISSUE-8 tiering: tier-1's
network-chaos gate is `make net-chaos-smoke`, the bench's reduced
partition-heal pass — full nodes booting N-at-a-time are too
scheduler-sensitive for the strict tier-1 budget on a 2-core box):
partition-heal and the byzantine double-signer are the two acceptance
pillars, then asymmetric delay, peer churn, frame reorder
(AEAD-detected), statesync join mid-chaos, and the 5-node
everything-at-once matrix soak.
"""

from __future__ import annotations

import os
import time

import pytest

from tendermint_tpu.libs import telemetry
from tests.netchaos_common import (
    ChaosNet,
    VoteInjector,
    make_conflicting_votes,
    wait_until,
)


@pytest.fixture
def net4(tmp_path):
    net = ChaosNet(4, str(tmp_path / "net4"))
    net.start()
    try:
        assert net.wait_height(2, timeout=150), net.heights()
        yield net
    finally:
        net.stop()


# -- the two acceptance pillars ----------------------------------------------


@pytest.mark.slow
def test_partition_heal_converges(net4):
    """{0,1} | {2,3}: neither side holds +2/3, so the chain HALTS (the
    safety half); healing re-peers via the persistent-dial loop and the
    chain resumes to byte-identical state everywhere (the liveness
    half)."""
    net4.partition({0, 1})
    h_stall = max(net4.heights())
    time.sleep(2.5)
    assert max(net4.heights()) <= h_stall + 1  # at most one in-flight commit
    stalled = max(net4.heights())
    net4.heal()
    assert net4.wait_height(stalled + 3, timeout=90), net4.heights()
    net4.assert_converged(stalled + 3)
    stats = net4.fabric.stats()
    assert stats["netfaults_partitions"] >= 4  # every crossing link severed
    assert stats["netfaults_heals"] >= 4
    # the scrape surface shows the same chaos (ops/faults convention)
    from tendermint_tpu.ops import netfaults

    scraped = netfaults.telemetry_counters()
    assert scraped["netfaults_partitions"] >= 4


@pytest.mark.slow
def test_byzantine_double_signer_commits_evidence(net4):
    """A double-signer (validator 0's key, wielded by a hostile peer
    speaking the real encrypted transport) sends conflicting prevotes to
    node 1. Node 1 must detect (types/evidence.py), pool, and PROPOSE the
    evidence; every node must commit the block carrying it and land on
    identical bytes — proof-on-chain, not just proof-in-RAM."""
    target = net4.nodes[1]  # NOT the signer: a node refuses self-evidence
    inj = VoteInjector(
        "127.0.0.1", target.listener.internal_address().port, "netchaos"
    )
    try:
        cs = target.consensus_state
        for _ in range(10):
            h, r = cs.rs.height, cs.rs.round_ + 1
            va, vb = make_conflicting_votes(
                net4.pvs[0], cs.rs.validators, h, r, "netchaos"
            )
            assert va.block_id.key() != vb.block_id.key()
            inj.send_vote(va)
            inj.send_vote(vb)
            if wait_until(lambda: cs.evidence_pool.size() > 0, timeout=2):
                break
        assert cs.evidence_pool.size() > 0, "double-sign never detected"
        # ... and COMMITS: every node marks the piece committed
        assert wait_until(
            lambda: all(
                n.consensus_state.evidence_pool.committed_count() >= 1
                for n in net4.nodes
            ),
            timeout=90,
        ), [n.consensus_state.evidence_pool.committed_count() for n in net4.nodes]
        top = max(net4.heights())
        assert net4.wait_height(top, timeout=30)
        ev_heights = [
            hh
            for hh in range(1, top + 1)
            if net4.nodes[2].block_store.load_block(hh).evidence.evidence
        ]
        assert ev_heights, "no committed block carries the evidence"
        block = net4.nodes[2].block_store.load_block(ev_heights[0])
        assert block.header.evidence_hash == block.evidence.hash()
        assert (
            block.evidence.evidence[0].address == net4.pvs[0].get_address()
        )
        net4.assert_converged(ev_heights[-1])
    finally:
        inj.close()


@pytest.mark.slow
def test_partition_fleet_signals_scrape_only(net4, monkeypatch):
    """Round 15 acceptance: the partition scenario must be VISIBLE in the
    scraped signals and the heal must recover them — every assertion
    here reads GET /metrics, GET /health, or the consensus_trace RPC;
    none reaches into harness objects.

    Partition {3} (minority): the majority keeps committing while node
    3's scrape shows the stall — peers gone, vote-gossip send counters
    frozen, /health flipped degraded on height age + peer loss. Heal:
    /health recovers to ok, and the outage lands in node 3's
    quorum-formation surface (consensus_quorum_seconds spike / a traced
    height whose precommit quorum took the whole outage)."""
    from tendermint_tpu.ops import fleet

    monkeypatch.setenv("TENDERMINT_HEALTH_HEIGHT_AGE_DEGRADED_S", "3.0")
    monkeypatch.setenv("TENDERMINT_HEALTH_HEIGHT_AGE_FAILING_S", "1e9")
    monkeypatch.setenv("TENDERMINT_HEALTH_MIN_PEERS", "1")
    urls = [f"127.0.0.1:{n.rpc_port()}" for n in net4.nodes]

    def status(url):
        return fleet.fetch_health(url)["status"]

    # -- pre-partition: fleet healthy, timeline reconstructs 4-wide ----
    assert wait_until(
        lambda: all(status(u) == "ok" for u in urls), timeout=60
    ), [status(u) for u in urls]
    snapshot = fleet.collect(urls, last=8)
    rows = fleet.build_timeline(
        {u: e["traces"] for u, e in snapshot.items()}, last=8
    )
    full = [r for r in rows if r["nodes_reporting"] == 4]
    assert full, f"no height traced on all 4 nodes: {rows}"
    assert any(r["commit_skew_s"] is not None for r in full)
    assert any(r["precommit_quorum_s_max"] is not None for r in full)

    m3 = fleet.fetch_metrics(urls[3])
    q_sum0 = fleet.metric_value(
        m3, "consensus_quorum_seconds_sum", {"phase": "precommit"},
        default=0.0,
    )

    # -- partition: the stall is scrape-visible ------------------------
    net4.partition({3})
    assert wait_until(lambda: status(urls[3]) == "degraded", timeout=45)
    health3 = fleet.fetch_health(urls[3])
    assert health3["checks"]["peers"]["status"] == "degraded", health3
    m3 = fleet.fetch_metrics(urls[3])
    peers3 = (
        fleet.metric_value(m3, "p2p_peers_outbound", default=0)
        + fleet.metric_value(m3, "p2p_peers_inbound", default=0)
    )
    assert peers3 == 0, "severed links must be visible in the peer gauges"
    sends_stalled = fleet.metric_value(
        m3, "p2p_peer_vote_gossip_sends_total", default=0.0
    )
    h_major0 = fleet.metric_value(
        fleet.fetch_metrics(urls[0]), "consensus_height"
    )
    time.sleep(1.5)
    m3b = fleet.fetch_metrics(urls[3])
    assert fleet.metric_value(
        m3b, "p2p_peer_vote_gossip_sends_total", default=0.0
    ) == sends_stalled, "gossip sends must freeze on a partitioned node"
    # hold the partition until the liveness signal engages too (the
    # peers check flips instantly; the quorum-spike assertion below
    # needs the stall to actually span the height-age budget)
    assert wait_until(
        lambda: fleet.fetch_health(urls[3])["checks"]["height_age"][
            "status"] == "degraded",
        timeout=45,
    )
    # the majority side kept committing (scraped height moved)
    assert wait_until(
        lambda: fleet.metric_value(
            fleet.fetch_metrics(urls[0]), "consensus_height"
        ) > h_major0,
        timeout=60,
    )

    # -- heal: recovery is scrape-visible ------------------------------
    net4.heal()
    assert wait_until(lambda: status(urls[3]) == "ok", timeout=90), (
        fleet.fetch_health(urls[3])
    )
    m3c = fleet.fetch_metrics(urls[3])
    peers3 = (
        fleet.metric_value(m3c, "p2p_peers_outbound", default=0)
        + fleet.metric_value(m3c, "p2p_peers_inbound", default=0)
    )
    assert peers3 >= 1, "healed links must re-appear in the peer gauges"
    assert fleet.metric_value(
        m3c, "p2p_peer_vote_gossip_sends_total", default=0.0
    ) >= sends_stalled
    # the outage shows in the quorum-formation surface: either the
    # histogram sum jumped by ~the outage, or a freshly traced height
    # carries it in its arrival marks (both pure scrape reads; the
    # histogram can miss it only if quorum formed in the instant before
    # the links dropped)
    q_sum1 = fleet.metric_value(
        m3c, "consensus_quorum_seconds_sum", {"phase": "precommit"},
        default=0.0,
    )
    traces3 = fleet.fetch_traces(urls[3], last=10)
    spiked_trace = any(
        t["arrivals"].get("precommit_quorum", t["started_at"])
        - t["started_at"] > 2.0
        or t["wall_s"] > 2.5
        for t in traces3
    )
    assert (q_sum1 - q_sum0 > 2.0) or spiked_trace, (
        q_sum0, q_sum1, [t["wall_s"] for t in traces3]
    )


# -- the rest of the matrix ---------------------------------------------------


@pytest.mark.slow
def test_asymmetric_delay_converges(net4):
    """One slow validator (250 ms one-way toward it, instant return):
    consensus rides through the induced timeout/round churn and all
    nodes stay byte-identical."""
    net4.delay_node(3, 0.25)
    h = max(net4.heights())
    assert net4.wait_height(h + 4, timeout=120), net4.heights()
    net4.clear_delays()
    net4.assert_converged(h + 4)
    assert net4.fabric.stats()["netfaults_delays_injected"] > 0


@pytest.mark.slow
def test_rolling_peer_churn_converges(net4):
    """Listener kill/restart rolling over every node: each churned node
    loses all its connections, re-binds the SAME port, and the
    persistent-dial mesh re-forms — while blocks keep committing."""
    for idx in (2, 1, 3):
        net4.churn_listener(idx, down_s=0.5)
        # first the mesh must heal (re-peering is the churn arm's own
        # assertion), THEN the chain must move — conflating the two made
        # a slow re-peer read as a consensus stall
        assert wait_until(
            lambda: all(n.sw.peers.size() >= 3 for n in net4.nodes),
            timeout=90,
        ), (idx, [n.sw.peers.size() for n in net4.nodes])
        h = max(net4.heights())
        assert net4.wait_height(h + 2, timeout=120), (
            idx,
            net4.heights(),
            [n.sw.peers.size() for n in net4.nodes],
            [
                (r.height, r.round_, int(r.step))
                for r in (n.consensus_state.rs for n in net4.nodes)
            ],
        )
    net4.assert_converged(max(min(net4.heights()) - 1, 1))


@pytest.mark.slow
def test_reorder_is_detected_as_tamper(net4):
    """Frame reorder on a live link: the counter-nonce AEAD must flag it
    (p2p_secretconn_auth_failures_total moves), the poisoned connection
    dies loudly, and the chain converges through the reconnect."""
    reg = telemetry.default_registry()
    af0 = reg.counter("p2p_secretconn_auth_failures_total").value
    link = net4.fabric.link(1, 0)
    link.set_reorder(2)
    h = max(net4.heights())
    assert net4.wait_height(h + 3, timeout=120), net4.heights()
    net4.assert_converged(h + 3)
    if link.stats()["netfaults_reorders_injected"]:
        assert reg.counter("p2p_secretconn_auth_failures_total").value > af0


@pytest.mark.slow
def test_statesync_node_joins_mid_chaos(tmp_path):
    """A fresh node statesync-restores from a live net WHILE a link is
    delayed, then fast-syncs the tail and lands on the same fingerprints
    — the cold-start path exercised over the real encrypted wire."""
    net = ChaosNet(4, str(tmp_path / "ssnet"), snapshot_interval=5)
    net.start()
    try:
        assert net.wait_height(12, timeout=180), net.heights()
        net.delay_node(3, 0.15)
        joiner = net.start_node(4, pv=None, statesync_from=[0, 1])
        assert wait_until(
            lambda: joiner.block_store.height() >= 13, timeout=180
        ), (joiner.block_store.height(), joiner.block_store.base())
        net.clear_delays()
        # statesync actually restored (store starts at a snapshot base,
        # not genesis) and the joiner's bytes match node 0's
        base = joiner.block_store.base()
        assert base > 1, "joiner fast-synced from genesis instead of restoring"
        top = min(n.block_store.height() for n in net.nodes)
        for hh in range(base, top + 1):
            want = net.nodes[0].block_store.load_block_meta(hh)
            got = joiner.block_store.load_block_meta(hh)
            assert got.block_id.key() == want.block_id.key(), hh
            assert (
                joiner.block_store.load_block(hh).header.app_hash
                == net.nodes[0].block_store.load_block(hh).header.app_hash
            ), hh
        # round 13, deterministic snapshot roots: every snapshot height
        # shared across replicas must carry the SAME manifest root —
        # the seen commit (which legitimately differs per node, 3-of-4
        # vs 4-of-4 precommits) now rides the manifest sidecar, outside
        # the digested payload. Pre-r13 this diverged at height 5.
        height_sets = [set(n.snapshot_store.heights()) for n in net.nodes[:4]]
        common = set.intersection(*height_sets)
        assert common, f"no shared snapshot heights: {height_sets}"
        for sh in common:
            roots = {
                n.snapshot_store.load_manifest(sh).root for n in net.nodes[:4]
            }
            assert len(roots) == 1, (
                f"snapshot roots diverged at height {sh}: "
                f"{[r.hex()[:12] for r in roots]}"
            )
    finally:
        net.stop()


@pytest.mark.slow
def test_five_node_matrix_soak(tmp_path):
    """Everything at once on a 5-node net: partition that heals, an
    asymmetrically slow validator, listener churn, a byzantine
    double-signer whose evidence must commit, txs flowing throughout —
    and byte-identical convergence at the end."""
    net = ChaosNet(5, str(tmp_path / "matrix"), snapshot_interval=0)
    net.start()
    try:
        assert net.wait_height(2, timeout=90), net.heights()
        for i in range(10):
            net.broadcast_tx(f"soak-{i}=v{i}".encode(), via=i % 5)

        # phase 1: minority partition {4} — majority keeps committing
        net.partition({4})
        h = max(net.heights())
        assert net.wait_height(h + 2, timeout=90, nodes=[0, 1, 2, 3])
        net.heal()

        # phase 2: slow link + churn + byzantine injection
        net.delay_node(2, 0.2)
        net.churn_listener(1, down_s=0.5)
        target = net.nodes[3]
        inj = VoteInjector(
            "127.0.0.1", target.listener.internal_address().port, "netchaos"
        )
        cs = target.consensus_state
        for _ in range(10):
            hh, rr = cs.rs.height, cs.rs.round_ + 1
            va, vb = make_conflicting_votes(
                net.pvs[0], cs.rs.validators, hh, rr, "netchaos"
            )
            inj.send_vote(va)
            inj.send_vote(vb)
            if wait_until(lambda: cs.evidence_pool.size() > 0, timeout=2):
                break
        inj.close()
        assert cs.evidence_pool.size() > 0
        for i in range(10):
            net.broadcast_tx(f"soak2-{i}=w{i}".encode(), via=i % 5)
        net.clear_delays()

        # phase 3: quiesce — evidence committed everywhere, all caught up
        assert wait_until(
            lambda: all(
                n.consensus_state.evidence_pool.committed_count() >= 1
                for n in net.nodes
            ),
            timeout=180,
        ), (
            net.heights(),
            [n.consensus_state.evidence_pool.committed_count() for n in net.nodes],
            [n.consensus_state.evidence_pool.size() for n in net.nodes],
        )
        top = max(net.heights())
        assert net.wait_height(top, timeout=120), net.heights()
        net.assert_converged(top)
        # the soak's txs actually committed
        total_txs = sum(
            net.nodes[0].block_store.load_block(hh).header.num_txs
            for hh in range(1, top + 1)
        )
        assert total_txs >= 20, total_txs
    finally:
        net.stop()


@pytest.mark.slow
def test_partition_wedge_diagnosable_from_artifacts_alone(net4, monkeypatch):
    """Round-17 acceptance: the partition wedge must be identified from
    the AUTO-DUMPED flight record + the cross-node tx timeline with
    zero re-runs. Partition {3}; a tx submitted to the partitioned node
    parks before proposal; the health watchdog flips node 3 to failing
    and auto-dumps its flight ring. Every assertion below reads the
    dump FILE or a tx_trace scrape — never a live harness object's
    internal state (the operator's position after the incident)."""
    import glob as _glob
    import json as _json

    from tendermint_tpu.ops import txtrace as ops_txtrace

    # tight budgets so the wedge becomes a FAILING verdict within the
    # test's patience (the watchdog evaluates health every ~2 s)
    monkeypatch.setenv("TENDERMINT_HEALTH_HEIGHT_AGE_DEGRADED_S", "2.0")
    monkeypatch.setenv("TENDERMINT_HEALTH_HEIGHT_AGE_FAILING_S", "6.0")
    node3 = net4.nodes[3]
    url3 = f"127.0.0.1:{node3.rpc_port()}"
    dump_glob = os.path.join(node3.flightrec.dump_dir or "", "dump-*.json")
    pre_dumps = set(_glob.glob(dump_glob))

    # -- partition, then submit a tx to the cut-off node ----------------
    net4.partition({3})
    time.sleep(0.5)
    parked_tx = b"wedge-probe=never-commits"
    net4.broadcast_tx(parked_tx, via=3)

    # -- artifact 1: the auto-dumped flight record ----------------------
    assert wait_until(
        lambda: set(_glob.glob(dump_glob)) - pre_dumps, timeout=60
    ), "health->failing never auto-dumped the flight record"
    dump_path = sorted(set(_glob.glob(dump_glob)) - pre_dumps)[-1]
    with open(dump_path) as f:
        dump = _json.load(f)  # valid JSON or this raises
    assert dump["reason"] == "health_failing"
    events = dump["events"]
    ts = [e["t"] for e in events]
    assert ts == sorted(ts), "dump timestamps not monotonic"
    # the gossip-stall signature: the links died (peer_drop events) and
    # the step spine FROZE — every trailing step event sits at one
    # height while the majority side kept committing
    assert any(e["kind"] == "peer_drop" for e in events), (
        "no peer_drop events in the wedge dump"
    )
    steps = [e for e in events if e["kind"] == "step"]
    assert steps, "no step events in the wedge dump"
    trailing = [e["height"] for e in steps[-8:]]
    assert len(set(trailing)) <= 2, (
        f"step spine not frozen in the dump: {trailing}"
    )
    # picks without sends: the dump's counter snapshot carries the
    # gossip totals — nothing sent since the cut means picks >= sends
    # and zero live peers' worth of progress
    counters = dump["counters"]
    assert counters["peer_vote_gossip_picks"] >= counters[
        "peer_vote_gossip_sends"
    ], counters
    assert counters["height"] <= max(net4.heights()), counters

    # -- artifact 2: the cross-node tx timeline -------------------------
    snapshot = ops_txtrace.collect_txtraces([url3], last=50)
    assert "error" not in snapshot[url3], snapshot[url3]
    rows = ops_txtrace.join_tx_timelines(snapshot)
    from tendermint_tpu.types.tx import tx_hash

    want = tx_hash(parked_tx).hex().upper()
    parked = [r for r in rows if r["hash"] == want]
    assert parked, (
        f"partitioned tx not traced (first-K window consumed?): {rows}"
    )
    [row] = parked
    assert not row["committed"], row
    # parked in the broadcast phase: admitted to the pool, never made a
    # proposal — the partition cut it off before dissemination
    from tendermint_tpu.libs.txtrace import STAGES

    assert row["last_stage"] in (
        "rpc_ingress", "sig_gate", "mempool_admit", "p2p_broadcast"
    ), row
    assert STAGES.index(row["last_stage"]) < STAGES.index("proposal")

    # -- heal: the net converges and the probe tx finally commits -------
    net4.heal()
    stalled = max(net4.heights())
    assert net4.wait_height(stalled + 2, timeout=90), net4.heights()
