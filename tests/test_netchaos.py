"""Real-TCP chaos scenario matrix (round 12, docs/secure-p2p.md).

Every test here runs FULL nodes (node/node.py — consensus, mempool,
fast sync, statesync, RPC) over real TCP listeners with the in-repo
SecretConnection encrypting every byte, all traffic relayed through
`ops/netfaults.LinkProxy` fault proxies. No loopback fabric anywhere.
The convergence assertion is the same byte-identity the existing soaks
use: (block hash, part-set root, app hash, evidence hash) per height,
identical across every node.

The whole matrix is slow-marked (the ISSUE-8 tiering: tier-1's
network-chaos gate is `make net-chaos-smoke`, the bench's reduced
partition-heal pass — full nodes booting N-at-a-time are too
scheduler-sensitive for the strict tier-1 budget on a 2-core box):
partition-heal and the byzantine double-signer are the two acceptance
pillars, then asymmetric delay, peer churn, frame reorder
(AEAD-detected), statesync join mid-chaos, and the 5-node
everything-at-once matrix soak.
"""

from __future__ import annotations

import os
import time

import pytest

from tendermint_tpu.libs import telemetry
from tests.netchaos_common import (
    ChaosNet,
    VoteInjector,
    make_conflicting_votes,
    wait_until,
)


@pytest.fixture
def net4(tmp_path):
    net = ChaosNet(4, str(tmp_path / "net4"))
    net.start()
    try:
        assert net.wait_height(2, timeout=150), net.heights()
        yield net
    finally:
        net.stop()


# -- the two acceptance pillars ----------------------------------------------


@pytest.mark.slow
def test_partition_heal_converges(net4):
    """{0,1} | {2,3}: neither side holds +2/3, so the chain HALTS (the
    safety half); healing re-peers via the persistent-dial loop and the
    chain resumes to byte-identical state everywhere (the liveness
    half)."""
    net4.partition({0, 1})
    h_stall = max(net4.heights())
    time.sleep(2.5)
    assert max(net4.heights()) <= h_stall + 1  # at most one in-flight commit
    stalled = max(net4.heights())
    net4.heal()
    assert net4.wait_height(stalled + 3, timeout=90), net4.heights()
    net4.assert_converged(stalled + 3)
    stats = net4.fabric.stats()
    assert stats["netfaults_partitions"] >= 4  # every crossing link severed
    assert stats["netfaults_heals"] >= 4
    # the scrape surface shows the same chaos (ops/faults convention)
    from tendermint_tpu.ops import netfaults

    scraped = netfaults.telemetry_counters()
    assert scraped["netfaults_partitions"] >= 4


@pytest.mark.slow
def test_byzantine_double_signer_commits_evidence(net4):
    """A double-signer (validator 0's key, wielded by a hostile peer
    speaking the real encrypted transport) sends conflicting prevotes to
    node 1. Node 1 must detect (types/evidence.py), pool, and PROPOSE the
    evidence; every node must commit the block carrying it and land on
    identical bytes — proof-on-chain, not just proof-in-RAM."""
    target = net4.nodes[1]  # NOT the signer: a node refuses self-evidence
    inj = VoteInjector(
        "127.0.0.1", target.listener.internal_address().port, "netchaos"
    )
    try:
        cs = target.consensus_state
        for _ in range(10):
            h, r = cs.rs.height, cs.rs.round_ + 1
            va, vb = make_conflicting_votes(
                net4.pvs[0], cs.rs.validators, h, r, "netchaos"
            )
            assert va.block_id.key() != vb.block_id.key()
            inj.send_vote(va)
            inj.send_vote(vb)
            if wait_until(lambda: cs.evidence_pool.size() > 0, timeout=2):
                break
        assert cs.evidence_pool.size() > 0, "double-sign never detected"
        # ... and COMMITS: every node marks the piece committed
        assert wait_until(
            lambda: all(
                n.consensus_state.evidence_pool.committed_count() >= 1
                for n in net4.nodes
            ),
            timeout=90,
        ), [n.consensus_state.evidence_pool.committed_count() for n in net4.nodes]
        top = max(net4.heights())
        assert net4.wait_height(top, timeout=30)
        ev_heights = [
            hh
            for hh in range(1, top + 1)
            if net4.nodes[2].block_store.load_block(hh).evidence.evidence
        ]
        assert ev_heights, "no committed block carries the evidence"
        block = net4.nodes[2].block_store.load_block(ev_heights[0])
        assert block.header.evidence_hash == block.evidence.hash()
        assert (
            block.evidence.evidence[0].address == net4.pvs[0].get_address()
        )
        net4.assert_converged(ev_heights[-1])
    finally:
        inj.close()


@pytest.mark.slow
def test_partition_fleet_signals_scrape_only(net4, monkeypatch):
    """Round 15 acceptance: the partition scenario must be VISIBLE in the
    scraped signals and the heal must recover them — every assertion
    here reads GET /metrics, GET /health, or the consensus_trace RPC;
    none reaches into harness objects.

    Partition {3} (minority): the majority keeps committing while node
    3's scrape shows the stall — peers gone, vote-gossip send counters
    frozen, /health flipped degraded on height age + peer loss. Heal:
    /health recovers to ok, and the outage lands in node 3's
    quorum-formation surface (consensus_quorum_seconds spike / a traced
    height whose precommit quorum took the whole outage)."""
    from tendermint_tpu.ops import fleet

    monkeypatch.setenv("TENDERMINT_HEALTH_HEIGHT_AGE_DEGRADED_S", "3.0")
    monkeypatch.setenv("TENDERMINT_HEALTH_HEIGHT_AGE_FAILING_S", "1e9")
    monkeypatch.setenv("TENDERMINT_HEALTH_MIN_PEERS", "1")
    urls = [f"127.0.0.1:{n.rpc_port()}" for n in net4.nodes]

    def status(url):
        return fleet.fetch_health(url)["status"]

    # -- pre-partition: fleet healthy, timeline reconstructs 4-wide ----
    assert wait_until(
        lambda: all(status(u) == "ok" for u in urls), timeout=60
    ), [status(u) for u in urls]
    snapshot = fleet.collect(urls, last=8)
    rows = fleet.build_timeline(
        {u: e["traces"] for u, e in snapshot.items()}, last=8
    )
    full = [r for r in rows if r["nodes_reporting"] == 4]
    assert full, f"no height traced on all 4 nodes: {rows}"
    assert any(r["commit_skew_s"] is not None for r in full)
    assert any(r["precommit_quorum_s_max"] is not None for r in full)

    m3 = fleet.fetch_metrics(urls[3])
    q_sum0 = fleet.metric_value(
        m3, "consensus_quorum_seconds_sum", {"phase": "precommit"},
        default=0.0,
    )

    # -- partition: the stall is scrape-visible ------------------------
    net4.partition({3})
    assert wait_until(lambda: status(urls[3]) == "degraded", timeout=45)
    health3 = fleet.fetch_health(urls[3])
    assert health3["checks"]["peers"]["status"] == "degraded", health3
    m3 = fleet.fetch_metrics(urls[3])
    peers3 = (
        fleet.metric_value(m3, "p2p_peers_outbound", default=0)
        + fleet.metric_value(m3, "p2p_peers_inbound", default=0)
    )
    assert peers3 == 0, "severed links must be visible in the peer gauges"
    sends_stalled = fleet.metric_value(
        m3, "p2p_peer_vote_gossip_sends_total", default=0.0
    )
    h_major0 = fleet.metric_value(
        fleet.fetch_metrics(urls[0]), "consensus_height"
    )
    time.sleep(1.5)
    m3b = fleet.fetch_metrics(urls[3])
    assert fleet.metric_value(
        m3b, "p2p_peer_vote_gossip_sends_total", default=0.0
    ) == sends_stalled, "gossip sends must freeze on a partitioned node"
    # hold the partition until the liveness signal engages too (the
    # peers check flips instantly; the quorum-spike assertion below
    # needs the stall to actually span the height-age budget)
    assert wait_until(
        lambda: fleet.fetch_health(urls[3])["checks"]["height_age"][
            "status"] == "degraded",
        timeout=45,
    )
    # the majority side kept committing (scraped height moved)
    assert wait_until(
        lambda: fleet.metric_value(
            fleet.fetch_metrics(urls[0]), "consensus_height"
        ) > h_major0,
        timeout=60,
    )

    # -- heal: recovery is scrape-visible ------------------------------
    net4.heal()
    assert wait_until(lambda: status(urls[3]) == "ok", timeout=90), (
        fleet.fetch_health(urls[3])
    )
    m3c = fleet.fetch_metrics(urls[3])
    peers3 = (
        fleet.metric_value(m3c, "p2p_peers_outbound", default=0)
        + fleet.metric_value(m3c, "p2p_peers_inbound", default=0)
    )
    assert peers3 >= 1, "healed links must re-appear in the peer gauges"
    assert fleet.metric_value(
        m3c, "p2p_peer_vote_gossip_sends_total", default=0.0
    ) >= sends_stalled
    # the outage shows in the quorum-formation surface: either the
    # histogram sum jumped by ~the outage, or a freshly traced height
    # carries it in its arrival marks (both pure scrape reads; the
    # histogram can miss it only if quorum formed in the instant before
    # the links dropped)
    q_sum1 = fleet.metric_value(
        m3c, "consensus_quorum_seconds_sum", {"phase": "precommit"},
        default=0.0,
    )
    traces3 = fleet.fetch_traces(urls[3], last=10)
    spiked_trace = any(
        t["arrivals"].get("precommit_quorum", t["started_at"])
        - t["started_at"] > 2.0
        or t["wall_s"] > 2.5
        for t in traces3
    )
    assert (q_sum1 - q_sum0 > 2.0) or spiked_trace, (
        q_sum0, q_sum1, [t["wall_s"] for t in traces3]
    )


# -- the rest of the matrix ---------------------------------------------------


@pytest.mark.slow
def test_asymmetric_delay_converges(net4):
    """One slow validator (250 ms one-way toward it, instant return):
    consensus rides through the induced timeout/round churn and all
    nodes stay byte-identical."""
    net4.delay_node(3, 0.25)
    h = max(net4.heights())
    assert net4.wait_height(h + 4, timeout=120), net4.heights()
    net4.clear_delays()
    net4.assert_converged(h + 4)
    assert net4.fabric.stats()["netfaults_delays_injected"] > 0


@pytest.mark.slow
def test_rolling_peer_churn_converges(net4):
    """Listener kill/restart rolling over every node: each churned node
    loses all its connections, re-binds the SAME port, and the
    persistent-dial mesh re-forms — while blocks keep committing."""
    for idx in (2, 1, 3):
        net4.churn_listener(idx, down_s=0.5)
        # first the mesh must heal (re-peering is the churn arm's own
        # assertion), THEN the chain must move — conflating the two made
        # a slow re-peer read as a consensus stall
        assert wait_until(
            lambda: all(n.sw.peers.size() >= 3 for n in net4.nodes),
            timeout=90,
        ), (idx, [n.sw.peers.size() for n in net4.nodes])
        h = max(net4.heights())
        assert net4.wait_height(h + 2, timeout=120), (
            idx,
            net4.heights(),
            [n.sw.peers.size() for n in net4.nodes],
            [
                (r.height, r.round_, int(r.step))
                for r in (n.consensus_state.rs for n in net4.nodes)
            ],
        )
    net4.assert_converged(max(min(net4.heights()) - 1, 1))


@pytest.mark.slow
def test_reorder_is_detected_as_tamper(net4):
    """Frame reorder on a live link: the counter-nonce AEAD must flag it
    (p2p_secretconn_auth_failures_total moves), the poisoned connection
    dies loudly, and the chain converges through the reconnect."""
    reg = telemetry.default_registry()
    af0 = reg.counter("p2p_secretconn_auth_failures_total").value
    link = net4.fabric.link(1, 0)
    link.set_reorder(2)
    h = max(net4.heights())
    assert net4.wait_height(h + 3, timeout=120), net4.heights()
    net4.assert_converged(h + 3)
    if link.stats()["netfaults_reorders_injected"]:
        assert reg.counter("p2p_secretconn_auth_failures_total").value > af0


@pytest.mark.slow
def test_statesync_node_joins_mid_chaos(tmp_path):
    """A fresh node statesync-restores from a live net WHILE a link is
    delayed, then fast-syncs the tail and lands on the same fingerprints
    — the cold-start path exercised over the real encrypted wire."""
    net = ChaosNet(4, str(tmp_path / "ssnet"), snapshot_interval=5)
    net.start()
    try:
        assert net.wait_height(12, timeout=180), net.heights()
        net.delay_node(3, 0.15)
        joiner = net.start_node(4, pv=None, statesync_from=[0, 1])
        assert wait_until(
            lambda: joiner.block_store.height() >= 13, timeout=180
        ), (joiner.block_store.height(), joiner.block_store.base())
        net.clear_delays()
        # statesync actually restored (store starts at a snapshot base,
        # not genesis) and the joiner's bytes match node 0's
        base = joiner.block_store.base()
        assert base > 1, "joiner fast-synced from genesis instead of restoring"
        top = min(n.block_store.height() for n in net.nodes)
        for hh in range(base, top + 1):
            want = net.nodes[0].block_store.load_block_meta(hh)
            got = joiner.block_store.load_block_meta(hh)
            assert got.block_id.key() == want.block_id.key(), hh
            assert (
                joiner.block_store.load_block(hh).header.app_hash
                == net.nodes[0].block_store.load_block(hh).header.app_hash
            ), hh
        # round 13, deterministic snapshot roots: every snapshot height
        # shared across replicas must carry the SAME manifest root —
        # the seen commit (which legitimately differs per node, 3-of-4
        # vs 4-of-4 precommits) now rides the manifest sidecar, outside
        # the digested payload. Pre-r13 this diverged at height 5.
        height_sets = [set(n.snapshot_store.heights()) for n in net.nodes[:4]]
        common = set.intersection(*height_sets)
        assert common, f"no shared snapshot heights: {height_sets}"
        for sh in common:
            roots = {
                n.snapshot_store.load_manifest(sh).root for n in net.nodes[:4]
            }
            assert len(roots) == 1, (
                f"snapshot roots diverged at height {sh}: "
                f"{[r.hex()[:12] for r in roots]}"
            )
    finally:
        net.stop()


@pytest.mark.slow
def test_five_node_matrix_soak(tmp_path):
    """Everything at once on a 5-node net: partition that heals, an
    asymmetrically slow validator, listener churn, a byzantine
    double-signer whose evidence must commit, txs flowing throughout —
    and byte-identical convergence at the end."""
    net = ChaosNet(5, str(tmp_path / "matrix"), snapshot_interval=0)
    net.start()
    try:
        assert net.wait_height(2, timeout=90), net.heights()
        for i in range(10):
            net.broadcast_tx(f"soak-{i}=v{i}".encode(), via=i % 5)

        # phase 1: minority partition {4} — majority keeps committing
        net.partition({4})
        h = max(net.heights())
        assert net.wait_height(h + 2, timeout=90, nodes=[0, 1, 2, 3])
        net.heal()

        # phase 2: slow link + churn + byzantine injection
        net.delay_node(2, 0.2)
        net.churn_listener(1, down_s=0.5)
        target = net.nodes[3]
        inj = VoteInjector(
            "127.0.0.1", target.listener.internal_address().port, "netchaos"
        )
        cs = target.consensus_state
        for _ in range(10):
            hh, rr = cs.rs.height, cs.rs.round_ + 1
            va, vb = make_conflicting_votes(
                net.pvs[0], cs.rs.validators, hh, rr, "netchaos"
            )
            inj.send_vote(va)
            inj.send_vote(vb)
            if wait_until(lambda: cs.evidence_pool.size() > 0, timeout=2):
                break
        inj.close()
        assert cs.evidence_pool.size() > 0
        for i in range(10):
            net.broadcast_tx(f"soak2-{i}=w{i}".encode(), via=i % 5)
        net.clear_delays()

        # phase 3: quiesce — evidence committed everywhere, all caught up
        assert wait_until(
            lambda: all(
                n.consensus_state.evidence_pool.committed_count() >= 1
                for n in net.nodes
            ),
            timeout=180,
        ), (
            net.heights(),
            [n.consensus_state.evidence_pool.committed_count() for n in net.nodes],
            [n.consensus_state.evidence_pool.size() for n in net.nodes],
        )
        top = max(net.heights())
        assert net.wait_height(top, timeout=120), net.heights()
        net.assert_converged(top)
        # the soak's txs actually committed
        total_txs = sum(
            net.nodes[0].block_store.load_block(hh).header.num_txs
            for hh in range(1, top + 1)
        )
        assert total_txs >= 20, total_txs
    finally:
        net.stop()


@pytest.mark.slow
def test_partition_wedge_diagnosable_from_artifacts_alone(net4, monkeypatch):
    """Round-17 acceptance: the partition wedge must be identified from
    the AUTO-DUMPED flight record + the cross-node tx timeline with
    zero re-runs. Partition {3}; a tx submitted to the partitioned node
    parks before proposal; the health watchdog flips node 3 to failing
    and auto-dumps its flight ring. Every assertion below reads the
    dump FILE or a tx_trace scrape — never a live harness object's
    internal state (the operator's position after the incident)."""
    import glob as _glob
    import json as _json

    from tendermint_tpu.ops import txtrace as ops_txtrace

    # tight budgets so the wedge becomes a FAILING verdict within the
    # test's patience (the watchdog evaluates health every ~2 s)
    monkeypatch.setenv("TENDERMINT_HEALTH_HEIGHT_AGE_DEGRADED_S", "2.0")
    monkeypatch.setenv("TENDERMINT_HEALTH_HEIGHT_AGE_FAILING_S", "6.0")
    node3 = net4.nodes[3]
    url3 = f"127.0.0.1:{node3.rpc_port()}"
    dump_glob = os.path.join(node3.flightrec.dump_dir or "", "dump-*.json")
    pre_dumps = set(_glob.glob(dump_glob))

    # -- partition, then submit a tx to the cut-off node ----------------
    net4.partition({3})
    time.sleep(0.5)
    parked_tx = b"wedge-probe=never-commits"
    net4.broadcast_tx(parked_tx, via=3)

    # -- artifact 1: the auto-dumped flight record ----------------------
    assert wait_until(
        lambda: set(_glob.glob(dump_glob)) - pre_dumps, timeout=60
    ), "health->failing never auto-dumped the flight record"
    dump_path = sorted(set(_glob.glob(dump_glob)) - pre_dumps)[-1]
    with open(dump_path) as f:
        dump = _json.load(f)  # valid JSON or this raises
    assert dump["reason"] == "health_failing"
    events = dump["events"]
    ts = [e["t"] for e in events]
    assert ts == sorted(ts), "dump timestamps not monotonic"
    # the gossip-stall signature: the links died (peer_drop events) and
    # the step spine FROZE — every trailing step event sits at one
    # height while the majority side kept committing
    assert any(e["kind"] == "peer_drop" for e in events), (
        "no peer_drop events in the wedge dump"
    )
    steps = [e for e in events if e["kind"] == "step"]
    assert steps, "no step events in the wedge dump"
    trailing = [e["height"] for e in steps[-8:]]
    assert len(set(trailing)) <= 2, (
        f"step spine not frozen in the dump: {trailing}"
    )
    # picks without sends: the dump's counter snapshot carries the
    # gossip totals — nothing sent since the cut means picks >= sends
    # and zero live peers' worth of progress
    counters = dump["counters"]
    assert counters["peer_vote_gossip_picks"] >= counters[
        "peer_vote_gossip_sends"
    ], counters
    assert counters["height"] <= max(net4.heights()), counters

    # -- artifact 2: the cross-node tx timeline -------------------------
    snapshot = ops_txtrace.collect_txtraces([url3], last=50)
    assert "error" not in snapshot[url3], snapshot[url3]
    rows = ops_txtrace.join_tx_timelines(snapshot)
    from tendermint_tpu.types.tx import tx_hash

    want = tx_hash(parked_tx).hex().upper()
    parked = [r for r in rows if r["hash"] == want]
    assert parked, (
        f"partitioned tx not traced (first-K window consumed?): {rows}"
    )
    [row] = parked
    assert not row["committed"], row
    # parked in the broadcast phase: admitted to the pool, never made a
    # proposal — the partition cut it off before dissemination
    from tendermint_tpu.libs.txtrace import STAGES

    assert row["last_stage"] in (
        "rpc_ingress", "sig_gate", "mempool_admit", "p2p_broadcast"
    ), row
    assert STAGES.index(row["last_stage"]) < STAGES.index("proposal")

    # -- heal: the net converges and the probe tx finally commits -------
    net4.heal()
    stalled = max(net4.heights())
    assert net4.wait_height(stalled + 2, timeout=90), net4.heights()


# -- round 18: the internet-scale adversarial tier ----------------------------
#
# WAN profiles / geo clusters over the same fault fabric, the
# hostile-peer family (protocol-fluent adversaries, not socket faults),
# mixed-version nets, and the rolling-restart + soak discipline. Every
# scenario keeps the per-height byte-identity assert; every attack must
# be SHED (honest net keeps committing within the stated bound) and
# VISIBLE (p2p_adversary_* / netfaults_wan_* telemetry moves). Full
# catalog: docs/netchaos.md.


def _heights_per_s(net, window_s: float) -> float:
    h0 = min(net.heights())
    time.sleep(window_s)
    return (min(net.heights()) - h0) / window_s


@pytest.mark.slow
def test_geo_cluster_wan_converges(net4):
    """2 clusters x 2 nodes: lan latency inside a cluster, a sampled
    continental distribution between them (seeded per link — no
    hand-set delays). Consensus rides the WAN-shaped quorum path and
    every node stays byte-identical; the shaping is scrape-visible in
    netfaults_wan_*."""
    clusters = net4.apply_geo_clusters(k=2, intra="lan",
                                       inter="continental", seed=7)
    assert clusters == [[0, 1], [2, 3]]
    h = max(net4.heights())
    assert net4.wait_height(h + 4, timeout=150), net4.heights()
    net4.clear_wan()
    net4.assert_converged(h + 4)
    from tendermint_tpu.ops import netfaults

    scraped = netfaults.telemetry_counters()
    assert scraped["netfaults_wan_delays_applied"] > 0
    assert scraped["netfaults_wan_delay_seconds"] > 0
    # inter-cluster links carry the heavy profile, intra stay lan
    assert net4.fabric.link(2, 0).wan_profile_name() is None  # cleared
    net4.apply_geo_clusters(k=2, seed=7)
    assert net4.fabric.link(1, 0).wan_profile_name() == "lan"
    assert net4.fabric.link(2, 0).wan_profile_name() == "intercontinental"
    net4.clear_wan()


@pytest.mark.slow
def test_mempool_flood_is_shed_liveness_flat(tmp_path):
    """The mempool-flood adversary against the batched sig gate: a
    hostile peer pushes garbage-signature txs (structurally valid
    envelopes, junk signatures) plus a duplicate storm at a signedkv
    net. The garbage must be shed at the gate (never admitted, never
    app-dispatched) and counted in p2p_adversary_flood_txs_rejected;
    the duplicates shed at the dedup cache and counted in
    mempool_cache_dups — while consensus liveness stays flat within
    the stated bound (flood-window heights/s >= 1/3 of the pre-flood
    rate) and an honest tx still commits."""
    from tendermint_tpu.abci.apps.signedkv import make_sig_tx
    from tendermint_tpu.ops import fleet
    from tests.netchaos_common import MempoolFlooder

    net = ChaosNet(4, str(tmp_path / "flood"), app="signedkv")
    net.start()
    try:
        assert net.wait_height(2, timeout=150), net.heights()
        url1 = f"127.0.0.1:{net.nodes[1].rpc_port()}"

        base_hps = _heights_per_s(net, 6.0)
        m1_pre = fleet.fetch_metrics(url1)
        rejected0 = fleet.metric_value(
            m1_pre, "p2p_adversary_flood_txs_rejected", default=0.0,
        )
        dups0 = fleet.metric_value(
            m1_pre, "mempool_cache_dups", default=0.0,
        )

        target = net.nodes[1]
        flooder = MempoolFlooder(
            "127.0.0.1", target.listener.internal_address().port, "netchaos"
        )
        dup_tx = make_sig_tx(b"\x11" * 32, b"dupkey=dupval")
        try:
            h0 = min(net.heights())
            t0 = time.monotonic()
            sent_garbage = flooder.flood_garbage(2000, seed=5)
            sent_dups = flooder.flood_duplicates(dup_tx, 400)
            # keep the flood window honest: measure until the shed shows
            assert wait_until(
                lambda: fleet.metric_value(
                    fleet.fetch_metrics(url1),
                    "p2p_adversary_flood_txs_rejected", default=0.0,
                ) - rejected0 >= 0.8 * sent_garbage,
                timeout=60,
            ), "flood not shed/visible in p2p_adversary_flood_txs_rejected"
            flood_wall = time.monotonic() - t0
            flood_hps = (min(net.heights()) - h0) / flood_wall
        finally:
            flooder.close()
        assert sent_garbage >= 1900 and sent_dups >= 390
        # the duplicate storm shed at the dedup cache (first copy
        # admits; gossip redundancy adds a little on top — hence >=)
        assert wait_until(
            lambda: fleet.metric_value(
                fleet.fetch_metrics(url1), "mempool_cache_dups",
                default=0.0,
            ) - dups0 >= sent_dups - 10,
            timeout=30,
        ), "duplicate storm not visible in mempool_cache_dups"

        # liveness flat within the stated bound
        if base_hps > 0.3:
            assert flood_hps >= base_hps / 3.0, (base_hps, flood_hps)
        else:
            assert min(net.heights()) - h0 >= 1, net.heights()
        m1 = fleet.fetch_metrics(url1)
        # the commit cadence never degenerated (scraped liveness gauge)
        assert fleet.metric_value(
            m1, "consensus_height_seconds_last", default=0.0
        ) < 30.0
        # nothing hostile reached the pool: garbage died at the gate,
        # dups at the cache (pool only ever holds honest traffic)
        assert fleet.metric_value(m1, "mempool_size", default=0.0) < 100
        assert fleet.metric_value(
            m1, "mempool_sig_gate_dropped", default=0.0
        ) + fleet.metric_value(
            m1, "p2p_adversary_flood_txs_rejected", default=0.0
        ) - rejected0 >= sent_garbage * 0.8

        # an honest tx still commits through the flooded node
        probe = make_sig_tx(b"\x22" * 32, b"honest=survives")
        net.broadcast_tx(probe, via=1)
        top0 = max(net.heights())
        assert net.wait_height(top0 + 2, timeout=90), net.heights()
        committed = []
        store = net.nodes[0].block_store
        for hh in range(1, max(net.heights()) + 1):
            committed += store.load_block(hh).data.txs
        assert probe in committed, "honest tx starved by the flood"
        net.assert_converged(min(net.heights()))
    finally:
        net.stop()


@pytest.mark.slow
def test_slow_loris_oversized_and_corrupting_peers_dropped(net4, monkeypatch):
    """Three framing-layer adversaries against one live net:

    - slow-loris: dribbles the secret handshake one byte at a beat —
      the ABSOLUTE handshake deadline (not per-read) must cut it off;
    - oversized-frame: a fluent admitted peer streams 128 KiB at the
      vote channel's 64 KiB reassembly ceiling — dropped for cause;
    - frame corruptor: a fluent peer whose encrypted frames tamper in
      flight — the AEAD flags every one loudly.

    Each is shed (counted in handshake timeouts / frame violations /
    auth failures), none moves consensus off its cadence, and the net
    stays byte-identical."""
    from tendermint_tpu.libs import telemetry
    from tests.netchaos_common import (
        HostilePeer,
        OversizedFramePeer,
        slow_loris_handshake,
    )

    monkeypatch.setenv("TENDERMINT_SECRETCONN_HANDSHAKE_S", "2")
    target = net4.nodes[2]
    port = target.listener.internal_address().port
    reg = telemetry.default_registry()

    # -- slow loris ----------------------------------------------------
    hs_timeouts0 = reg.counter("p2p_secretconn_handshake_timeouts_total").value
    took = slow_loris_handshake("127.0.0.1", port, byte_interval_s=0.3,
                                max_s=20.0)
    assert took is not None, "target tolerated the loris for 20 s"
    assert took < 10.0, f"loris held the handshake {took:.1f}s"
    assert wait_until(
        lambda: reg.counter(
            "p2p_secretconn_handshake_timeouts_total"
        ).value > hs_timeouts0,
        timeout=10,
    )
    assert wait_until(
        lambda: target.sw.adversary_stats()["handshake_rejects"] >= 1,
        timeout=10,
    )

    # -- oversized frame ----------------------------------------------
    ofp = OversizedFramePeer("127.0.0.1", port, "netchaos")
    try:
        assert ofp.send_oversized(1 << 17)
        assert wait_until(ofp.dropped, timeout=15), (
            "target never dropped the oversized framer"
        )
        assert wait_until(
            lambda: target.sw.adversary_stats()["frame_violations"] >= 1,
            timeout=10,
        ), target.sw.adversary_stats()
    finally:
        ofp.close()

    # -- frame corruptor (the round-18 home for p2p/fuzz.py) -----------
    af0 = reg.counter("p2p_secretconn_auth_failures_total").value
    cp = HostilePeer("127.0.0.1", port, "netchaos", corrupt_prob=1.0)
    try:
        cp.send_msg(cp.vote_channel, b"this frame tampers in flight")
        assert wait_until(
            lambda: reg.counter(
                "p2p_secretconn_auth_failures_total"
            ).value > af0,
            timeout=15,
        ), "corrupted frame never flagged by the AEAD"
        assert wait_until(cp.dropped, timeout=15)
        assert cp.fuzz.corrupted_writes >= 1
    finally:
        cp.close()

    # the honest net rode through all three
    h = max(net4.heights())
    assert net4.wait_height(h + 2, timeout=90), net4.heights()
    net4.assert_converged(h + 2)


@pytest.mark.slow
def test_eclipse_pressure_honest_minority_keeps_node_live(net4):
    """The eclipse adversary: 30 distinct identities dialed from ONE
    address range at node 0 (whose honest links also ride that range —
    loopback is exactly the worst case). The IP-range counter must shed
    the surplus (scrape-visible), the honest minority of links stays
    connected, the node keeps committing, and when the attacker leaves
    the range counts drain back (the round-12 leak would have bricked
    inbound forever)."""
    from tendermint_tpu.ops import fleet
    from tests.netchaos_common import eclipse_dials

    target = net4.nodes[0]
    port = target.listener.internal_address().port
    url0 = f"127.0.0.1:{target.rpc_port()}"
    honest_range = target.sw.ip_ranges.count("127.0.0")
    assert honest_range >= 1  # the honest inbound links ride the range

    peers, refused = eclipse_dials("127.0.0.1", port, "netchaos", 30)
    try:
        # limits (64,32,16): the /24 budget caps total admissions; with
        # the honest links inside it, >= 14 of 30 dials must be shed
        assert refused >= 10, (len(peers), refused)
        assert len(peers) + honest_range <= 16
        assert wait_until(
            lambda: fleet.metric_value(
                fleet.fetch_metrics(url0),
                "p2p_adversary_eclipse_dials_refused", default=0.0,
            ) >= refused,
            timeout=30,
        ), fleet.fetch_metrics(url0).get("p2p_adversary_eclipse_dials_refused")

        # honest links survived the pressure: the eclipsed-at node still
        # commits with the rest of the net while the attacker holds its
        # admitted connections
        h = max(net4.heights())
        assert net4.wait_height(h + 2, timeout=90), net4.heights()
    finally:
        for p in peers:
            p.close()
    # the attacker leaves: its range counts DRAIN (wrapper-chain
    # uncount), so the node's inbound budget recovers for honest churn
    assert wait_until(
        lambda: target.sw.ip_ranges.count("127.0.0") <= honest_range + 1,
        timeout=60,
    ), target.sw.ip_ranges.count("127.0.0")
    net4.assert_converged(min(net4.heights()))


@pytest.mark.slow
def test_mixed_commit_format_net_refuses_loudly(tmp_path, monkeypatch):
    """Mixed-version net: node 3 boots under genesis
    commit_format="aggregate" while {0,1,2} run "full". The refusal is
    LOUD and at the handshake (NodeInfo.compatible_with names the flag;
    p2p_adversary_handshake_rejects moves on the majority; the odd node
    reads degraded on /health with zero peers) and the homogeneous
    majority keeps committing byte-identical blocks — no wedge, no
    silent mixed net."""
    from tendermint_tpu.ops import fleet

    monkeypatch.setenv("TENDERMINT_HEALTH_MIN_PEERS", "1")
    net = ChaosNet(4, str(tmp_path / "mixed"),
                   commit_format_of={3: "aggregate"})
    net.start()
    try:
        # the majority forms and commits without node 3
        assert net.wait_height(3, timeout=150, nodes=[0, 1, 2]), net.heights()
        # the mismatch names the flag, both directions
        reason = net.nodes[0].sw.node_info.compatible_with(
            net.nodes[3].sw.node_info
        )
        assert reason is not None and "commit format mismatch" in reason
        # node 3 never peers: every dial refused at the handshake
        assert net.nodes[3].sw.peers.size() == 0
        assert net.nodes[3].block_store.height() == 0
        rejects = sum(
            net.nodes[i].sw.adversary_stats()["handshake_rejects"]
            for i in range(3)
        )
        assert rejects >= 1, "refusals not counted on the majority side"
        # ... and scrape-visible on the majority
        assert any(
            fleet.metric_value(
                fleet.fetch_metrics(f"127.0.0.1:{net.nodes[i].rpc_port()}"),
                "p2p_adversary_handshake_rejects", default=0.0,
            ) >= 1
            for i in range(3)
        )
        # the odd node's own surface says it is cut off
        health3 = fleet.fetch_health(
            f"127.0.0.1:{net.nodes[3].rpc_port()}"
        )
        assert health3["status"] != "ok", health3
        assert health3["checks"]["peers"]["status"] != "ok", health3
        # majority byte-identity
        net.assert_converged(3, nodes=[0, 1, 2])
    finally:
        net.stop()


@pytest.mark.slow
def test_rolling_restart_statesync_rejoin_under_wan(tmp_path):
    """The rolling-upgrade arm under WAN latency: node 3 stops, its
    home is wiped (a cold replace), and it restarts with statesync
    while every link rides the continental profile. The majority keeps
    committing through the restart; the replacement restores at a
    snapshot base (never replays from genesis), tails the chain, and
    lands byte-identical."""
    net = ChaosNet(4, str(tmp_path / "rolling"), snapshot_interval=5)
    net.start()
    try:
        assert net.wait_height(8, timeout=180), net.heights()
        net.apply_wan("continental", seed=3)
        h_before = max(net.heights())
        node3 = net.restart_node(3, statesync_from=[0, 1], wipe=True)
        # the majority never stalled behind the restart
        assert net.wait_height(h_before + 2, timeout=120, nodes=[0, 1, 2])
        assert wait_until(
            lambda: node3.block_store.height() >= h_before + 2, timeout=240
        ), (node3.block_store.height(), node3.block_store.base())
        base = node3.block_store.base()
        assert base > 1, "replacement replayed from genesis, not statesync"
        net.clear_wan()
        top = min(n.block_store.height() for n in net.nodes)
        for hh in range(base, top + 1):
            want = net.nodes[0].block_store.load_block_meta(hh)
            got = node3.block_store.load_block_meta(hh)
            assert got.block_id.key() == want.block_id.key(), hh
            assert (
                node3.block_store.load_block(hh).header.app_hash
                == net.nodes[0].block_store.load_block(hh).header.app_hash
            ), hh
        # the restarted validator is signing again (the net includes it
        # in fresh commits): heights keep advancing with all 4 live
        h = max(net.heights())
        assert net.wait_height(h + 2, timeout=90), net.heights()
    finally:
        net.stop()


@pytest.mark.slow
def test_wan_soak_rss_flat_disk_bounded(tmp_path):
    """The soak discipline under a WAN profile (the pre-seed sqlite
    soak, now network-shaped): a 4-node net under continental latency
    commits NETCHAOS_SOAK_HEIGHTS (default 200) heights with light tx
    traffic. Asserts: RSS flat after warmup (< 30% / 64 MiB growth),
    disk growth bounded per height, the flight recorder QUIET on every
    healthy node (zero auto-dump episodes — round 17's recorder is the
    black box; a healthy soak must not trip it), and byte-identical
    convergence at the end."""
    target_heights = int(os.environ.get("NETCHAOS_SOAK_HEIGHTS", "200"))
    warmup = min(30, target_heights // 4)
    net = ChaosNet(4, str(tmp_path / "soak"), snapshot_interval=25)
    net.start()
    try:
        net.apply_wan("continental", seed=11)
        assert net.wait_height(warmup, timeout=300), net.heights()
        rss0_kb = net.rss_kb()
        disk0 = net.disk_bytes()
        h0 = min(net.heights())

        i = 0
        while min(net.heights()) < target_heights:
            net.broadcast_tx(f"soak-{i}=v{i}".encode(), via=i % 4)
            i += 1
            assert net.wait_height(
                min(net.heights()) + 1, timeout=120
            ), net.heights()

        rss1_kb = net.rss_kb()
        disk1 = net.disk_bytes()
        grew_kb = rss1_kb - rss0_kb
        assert grew_kb < max(65536, rss0_kb * 0.30), (
            f"RSS not flat: {rss0_kb} -> {rss1_kb} KiB over "
            f"{target_heights - h0} heights"
        )
        per_height = (disk1 - disk0) / max(1, min(net.heights()) - h0)
        assert per_height < 200 * 1024, (
            f"disk unbounded: {per_height:.0f} B/height "
            f"({disk0} -> {disk1})"
        )
        # the black box stayed quiet: no health-failing / wedge / crash
        # auto-dump episodes on any node through the whole soak
        assert net.flight_dump_counts() == [0, 0, 0, 0], (
            net.flight_dump_counts()
        )
        # the WAN shaping really ran the whole time
        from tendermint_tpu.ops import netfaults

        scraped = netfaults.telemetry_counters()
        assert scraped["netfaults_wan_delays_applied"] > 1000
        net.clear_wan()
        net.assert_converged(min(net.heights()))
    finally:
        net.stop()


# -- bounded-retention lifecycle (round 19, docs/state-sync.md § Retention) --


@pytest.mark.slow
def test_adversarial_statesync_offerers_under_wan(tmp_path, monkeypatch):
    """The adversarial offerer matrix (round 19): a joining node's
    restore faces a FORGED-manifest offerer (internally consistent
    manifest whose header/app hashes contradict the verified chain), a
    CORRUPT-chunk offerer (real manifest, flipped chunk bytes), and a
    STALLING offerer (answers discovery + manifest, then goes silent on
    chunks) — all under continental WAN shaping. The reactor must ban
    each kind (scrape-visible statesync_offerer_bans_*) and complete
    the restore from the honest offerers, landing byte-identical."""
    from tests.netchaos_common import CHAIN_ID, hostile_offerer_matrix

    # snapshot_interval LARGE and the idle cadence throttled so the
    # honest offers stay pinned at one height for the whole restore
    # (the picker takes max offered height; a producer racing new
    # snapshots past the forged one would bypass the attack instead of
    # defeating it — real networks snapshot hourly, the test preset
    # commits 10+ heights/s)
    net = ChaosNet(3, str(tmp_path / "advoff"), snapshot_interval=40,
                   snapshot_chunk_size=1024, height_throttle_s=0.25)
    net.start()
    try:
        # snapshot at 40 published; head comfortably past the forged
        # height 41 so its light walk to 42 SUCCEEDS and the binding
        # check (not a transient walk failure) is what kills it
        assert net.wait_height(44, timeout=300), net.heights()
        src = net.nodes[0]
        h_s = max(src.snapshot_store.heights())
        assert h_s == 40
        honest = src.snapshot_store.load_manifest(h_s)
        chunks = [
            src.snapshot_store.load_chunk(h_s, i)
            for i in range(honest.chunks)
        ]
        assert len(chunks) >= 4, "fixture needs several chunks to spread"

        # restore knobs: small windows + short timeouts so the stalled
        # windows cost seconds, and a 2-strike stall ban
        monkeypatch.setenv("TENDERMINT_STATESYNC_WINDOW", "4")
        monkeypatch.setenv("TENDERMINT_STATESYNC_CHUNK_TIMEOUT_S", "2")
        monkeypatch.setenv("TENDERMINT_STATESYNC_STALL_BAN", "2")
        monkeypatch.setenv("TENDERMINT_STATESYNC_DISCOVERY_S", "4")

        net.apply_wan("continental", seed=19)
        # dial ONE honest source: the hostile offerers then outnumber
        # the honest side 3-to-1 (the acceptance bar's "restore
        # completes from the honest MINORITY"), and every offerer of
        # the honest height fits one request window so the staller is
        # deterministically exercised
        joiner = net.start_node(3, pv=None, statesync_from=[0], dial=[0])
        # shape the joiner's fresh links too
        net.apply_wan("continental", seed=19)
        jport = joiner.listener.internal_address().port
        offerers = hostile_offerer_matrix(
            "127.0.0.1", jport, CHAIN_ID, honest, chunks
        )
        try:
            assert wait_until(
                lambda: joiner.block_store.base() > 1, timeout=240
            ), (joiner.block_store.height(), joiner.block_store.base(),
                joiner.statesync_reactor.stats())
            assert wait_until(
                lambda: joiner.block_store.height() >= 44, timeout=240
            ), joiner.block_store.height()

            # every adversary kind banned, visible on the flat scrape
            m = joiner.telemetry.flatten()
            assert m["statesync_offerer_bans_forged"] >= 1, m
            assert m["statesync_offerer_bans_corrupt"] >= 1, m
            assert m["statesync_offerer_bans_stall"] >= 1, m
            assert m["statesync_offerers_banned"] >= 3, m
            # ... and each hostile link was actually cut by the target
            assert wait_until(
                lambda: all(o.dropped() for o in offerers.values()),
                timeout=30,
            ), {k: o.dropped() for k, o in offerers.items()}

            # the restore used the honest snapshot, not the forged height
            assert joiner.block_store.base() == h_s
            net.clear_wan()
            top = min(
                [n.block_store.height() for n in net.nodes[:3]]
                + [joiner.block_store.height()]
            )
            for hh in range(h_s, top + 1):
                want = src.block_store.load_block_meta(hh)
                got = joiner.block_store.load_block_meta(hh)
                assert got.block_id.key() == want.block_id.key(), hh
        finally:
            for o in offerers.values():
                o.close()
    finally:
        net.stop()


@pytest.mark.slow
def test_laggard_below_horizon_auto_switches_to_statesync(tmp_path,
                                                          monkeypatch):
    """Horizon-aware catchup (round 19): a fresh node fast-syncing into
    a PRUNED network — every peer's store base is above height 1 — has
    no path back via block gossip. The pool detects that every serving
    peer pruned its next height and the node auto-falls-back to
    statesync (statesync.enable was FALSE; only rpc_servers were
    configured), restores at a snapshot base, fast-syncs the tail, and
    converges byte-identically instead of spinning on
    no_block_response."""
    # a small tree-version window so the statetree floor doesn't pin
    # retention far above the operator target (kvstore keeps 64 by
    # default; tree construction reads the knob at node boot)
    monkeypatch.setenv("TENDERMINT_STATETREE_KEEP_VERSIONS", "8")
    # snapshot lifetime engineering (netchaos_common.ChaosNet): keep 8
    # snapshots and throttle the idle cadence, or the producers rotate
    # snapshots out faster than any restore can fetch them
    net = ChaosNet(3, str(tmp_path / "horizon"),
                   snapshot_interval=8, snapshot_full_every=1,
                   snapshot_chunk_size=2048, snapshot_keep=8,
                   height_throttle_s=0.25,
                   retain_blocks=10, prune_interval=5)
    net.start()
    try:
        # run until every source PRUNED genesis away
        assert net.wait_height(60, timeout=400), net.heights()
        assert wait_until(
            lambda: all(n.block_store.base() > 1 for n in net.nodes),
            timeout=120,
        ), [n.block_store.base() for n in net.nodes]

        joiner = net.start_node(
            3, pv=None, statesync_from=[0, 1], statesync_enable=False
        )
        # boot-time restore must NOT be armed: this is the runtime path
        assert joiner.statesync_reactor.enabled is False

        target = max(net.heights()) + 2
        assert wait_until(
            lambda: joiner.block_store.height() >= target, timeout=300
        ), (joiner.block_store.height(), joiner.block_store.base(),
            joiner.blockchain_reactor.below_horizon_fallbacks)

        m = joiner.telemetry.flatten()
        assert m["fastsync_below_horizon_fallbacks"] >= 1, m
        assert joiner.block_store.base() > 1, (
            "joiner fast-synced from genesis through a pruned net?!"
        )
        # byte identity over the range the joiner holds
        top = min(n.block_store.height() for n in net.nodes[:3] + [joiner])
        base = joiner.block_store.base()
        for hh in range(base, top + 1):
            want = net.nodes[0].block_store.load_block_meta(hh)
            got = joiner.block_store.load_block_meta(hh)
            assert got.block_id.key() == want.block_id.key(), hh
            assert (
                joiner.block_store.load_block(hh).header.app_hash
                == net.nodes[0].block_store.load_block(hh).header.app_hash
            ), hh
    finally:
        net.stop()


@pytest.mark.slow
def test_retention_soak_disk_bounded_and_rejoin(tmp_path, monkeypatch):
    """The retention soak (round 19): a 4-node sqlite-backed net with
    [pruning] armed and the statesync producer live commits
    RETENTION_SOAK_HEIGHTS (default 300; the ROADMAP's full soak sets
    10000) heights. Asserts per-node disk BOUNDED BY RETENTION rather
    than chain length (steady-state bytes/height a small constant after
    the pruning horizon engages), every store base advancing with the
    head, prune + WAL-chunk accounting scrape-visible, a freshly WIPED
    node re-joining via snapshot and tailing to byte-identical hashes,
    and byte-identity across the fleet at the end. The SIGKILL-mid-prune
    recovery claim is held by tests/test_retention.py's subprocess kill
    test."""
    target_heights = int(os.environ.get("RETENTION_SOAK_HEIGHTS", "300"))
    monkeypatch.setenv("TENDERMINT_STATETREE_KEEP_VERSIONS", "24")
    # small WAL chunks so rotation (and therefore WAL retention) is
    # actually exercised at soak scale
    monkeypatch.setenv("TENDERMINT_WAL_CHUNK_BYTES", "65536")
    retain = 40
    net = ChaosNet(4, str(tmp_path / "retsoak"), db_backend="sqlite",
                   snapshot_interval=15, snapshot_full_every=1,
                   snapshot_chunk_size=4096, snapshot_keep=6,
                   height_throttle_s=0.1,
                   retain_blocks=retain, prune_interval=10)
    net.start()
    try:
        # warm up past the EQUILIBRIUM point, not merely first-prune:
        # the deepest retention floor here is the snapshot window (6 x
        # 15 = 90 heights), so the block stores keep absorbing new
        # heights until the head is ~retention past it and sqlite's
        # freed pages start recycling — measuring earlier reads archive-
        # rate growth and calls it a retention failure
        measure_from = max(2 * retain + 90, target_heights // 2)
        assert net.wait_height(min(measure_from, target_heights),
                               timeout=600), net.heights()
        assert wait_until(
            lambda: all(n.block_store.base() > 1 for n in net.nodes),
            timeout=300,
        ), [n.block_store.base() for n in net.nodes]
        h1 = min(net.heights())
        d1 = net.disk_bytes()

        i = 0
        while min(net.heights()) < target_heights:
            net.broadcast_tx(f"ret-{i}=v{i}".encode(), via=i % 4)
            i += 1
            assert net.wait_height(
                min(net.heights()) + 1, timeout=120
            ), net.heights()
        h2 = min(net.heights())
        d2 = net.disk_bytes()

        # disk bounded by retention: steady-state growth per height per
        # NODE must be a small constant (sqlite reuses freed pages,
        # snapshots rotate, WAL chunks prune) — NOT proportional to
        # chain length (the pre-retention WAN soak budgeted 200 KiB per
        # height per process and still grew linearly forever)
        per_height_per_node = (d2 - d1) / max(1, h2 - h1) / len(net.nodes)
        assert per_height_per_node < 30 * 1024, (
            f"disk grows {per_height_per_node:.0f} B/height/node under "
            f"pruning ({d1} -> {d2} over {h2 - h1} heights)"
        )
        for n in net.nodes:
            m = n.telemetry.flatten()
            head, base = n.block_store.height(), n.block_store.base()
            assert m["blockstore_pruned_heights_total"] > 0, m
            assert m["pruning_runs"] > 0, m
            assert base > 1, (head, base)
            # the base TRACKS the head: the deepest floor here is the
            # snapshot window (keep 6 x interval 15 = 90 heights), plus
            # interval granularity + prune-interval slack
            assert head - base <= 90 + 15 + 10 + 15, (head, base)
            assert m["wal_chunks_pruned"] > 0, {
                k: v for k, v in m.items() if k.startswith("wal_")
            }

        # a wiped node re-joins via snapshot and tails byte-identically
        h_before = max(net.heights())
        node3 = net.restart_node(3, statesync_from=[0, 1], wipe=True)
        assert net.wait_height(h_before + 2, timeout=120, nodes=[0, 1, 2])
        assert wait_until(
            lambda: node3.block_store.height() >= h_before + 2, timeout=300
        ), (node3.block_store.height(), node3.block_store.base())
        assert node3.block_store.base() > 1, (
            "wiped node replayed from genesis instead of statesync"
        )
        top = min(n.block_store.height() for n in net.nodes)
        net.assert_converged(top)  # from the highest base across nodes
    finally:
        net.stop()
