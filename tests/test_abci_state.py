"""ABCI apps/clients/proxy and state execution pipeline tests."""

import json
import threading

import pytest

from tendermint_tpu.abci.apps import CounterApp, KVStoreApp, NilApp, PersistentKVStoreApp
from tendermint_tpu.abci.client import ABCIServer, LocalClient, SocketClient
from tendermint_tpu.abci.types import ABCIValidator, Header as ABCIHeader
from tendermint_tpu.crypto.keys import TYPE_ED25519, gen_priv_key_ed25519
from tendermint_tpu.libs.db import MemDB
from tendermint_tpu.libs.events import EventCache, EventSwitch
from tendermint_tpu.proxy import AppConns, LocalClientCreator, default_client_creator
from tendermint_tpu.state import State, apply_block, exec_commit_block, validate_block
from tendermint_tpu.state.execution import InvalidBlockError, update_validators
from tendermint_tpu.state.txindex import KVTxIndexer
from tendermint_tpu.types import (
    Block,
    BlockID,
    GenesisDoc,
    GenesisValidator,
    VoteSet,
    VOTE_TYPE_PRECOMMIT,
)
from tendermint_tpu.types.block import empty_commit
from tendermint_tpu.types.priv_validator import PrivValidatorFS
from tendermint_tpu.types.services import MockMempool

from tests.test_types import make_val_set, signed_vote


class TestKVStoreApp:
    def test_deliver_query_commit(self):
        app = KVStoreApp()
        assert app.deliver_tx(b"name=satoshi").is_ok
        res = app.commit()
        assert res.is_ok and len(res.data) == 20
        q = app.query(b"name")
        assert q.value == b"satoshi"
        assert app.query(b"missing").value == b""
        # app hash deterministic across instances
        app2 = KVStoreApp()
        app2.deliver_tx(b"name=satoshi")
        assert app2.commit().data == res.data

    def test_info_tracks_height(self):
        app = KVStoreApp()
        assert app.info().last_block_height == 0
        app.deliver_tx(b"a=1")
        app.commit()
        info = app.info()
        assert info.last_block_height == 1
        assert info.last_block_app_hash == app.app_hash


class TestPersistentKVStore:
    def test_persistence(self, tmp_path):
        app = PersistentKVStoreApp(str(tmp_path))
        app.deliver_tx(b"k=v")
        h = app.commit()
        app2 = PersistentKVStoreApp(str(tmp_path))
        assert app2.height == 1
        assert app2.app_hash == h.data
        assert app2.query(b"k").value == b"v"

    def test_val_tx_diffs(self, tmp_path):
        app = PersistentKVStoreApp(str(tmp_path))
        pub = gen_priv_key_ed25519(b"val-seed").pub_key()
        app.begin_block(b"", ABCIHeader())
        assert app.deliver_tx(b"val:" + pub.raw.hex().encode() + b"/10").is_ok
        diffs = app.end_block(1).diffs
        assert len(diffs) == 1 and diffs[0].power == 10
        assert not app.deliver_tx(b"val:nothex/10").is_ok


class TestCounterApp:
    def test_serial_ordering(self):
        app = CounterApp(serial=True)
        assert app.deliver_tx(b"\x00").is_ok
        assert app.deliver_tx(b"\x01").is_ok
        assert not app.deliver_tx(b"\x05").is_ok  # gap
        assert app.check_tx(b"\x02").is_ok
        assert not app.check_tx(b"\x00").is_ok  # below check count

    def test_commit_hash(self):
        app = CounterApp()
        assert app.commit().data == b""
        app.deliver_tx(b"\x00")
        assert app.commit().data.endswith(b"\x01")


class TestSocketClient:
    def test_roundtrip_over_tcp(self, tmp_path):
        app = KVStoreApp()
        server = ABCIServer(app, "127.0.0.1:0")
        server.start()
        try:
            cli = SocketClient(server.addr)
            cli.start()
            assert cli.echo_sync("hello") == "hello"
            assert cli.info_sync().last_block_height == 0
            assert cli.deliver_tx_sync(b"x=42").is_ok
            res = cli.commit_sync()
            assert res.is_ok and len(res.data) == 20
            assert cli.query_sync(b"x").value == b"42"
            # async pipeline
            rrs = [cli.deliver_tx_async(b"k%d=%d" % (i, i)) for i in range(10)]
            for rr in rrs:
                assert rr.wait(5).is_ok
            cli.stop()
        finally:
            server.stop()


class TestAppConns:
    def test_three_connections(self):
        creator = LocalClientCreator(CounterApp(serial=True))
        conns = AppConns(creator)
        conns.start()
        assert conns.query().info_sync() is not None
        assert conns.mempool().check_tx_async(b"\x00").wait(1).is_ok
        conns.consensus().begin_block_sync(b"", ABCIHeader())
        assert conns.consensus().deliver_tx_async(b"\x00").wait(1).is_ok
        assert conns.consensus().commit_sync().is_ok

    def test_default_creator_names(self, tmp_path):
        for name in ("kvstore", "dummy", "counter", "nilapp"):
            c = default_client_creator(name, str(tmp_path))
            assert isinstance(c, LocalClientCreator)


def make_genesis(n=4, power=10, chain_id="exec-chain"):
    vs, privs = make_val_set(n, power)
    doc = GenesisDoc(
        genesis_time_ns=0,
        chain_id=chain_id,
        validators=[
            GenesisValidator(v.pub_key, v.voting_power) for v in vs.validators
        ],
    )
    return doc, vs, privs


def make_next_block(state: State, txs, privs, part_size=4096):
    """Build a valid next block with a proper commit for the last block."""
    height = state.last_block_height + 1
    if height == 1:
        commit = empty_commit()
    else:
        voteset = VoteSet(
            state.chain_id, height - 1, 0, VOTE_TYPE_PRECOMMIT, state.last_validators
        )
        for p in privs:
            voteset.add_vote(
                signed_vote(
                    p, state.last_validators, height - 1, 0, VOTE_TYPE_PRECOMMIT,
                    state.last_block_id, chain_id=state.chain_id,
                )
            )
        commit = voteset.make_commit()
    block, ps = Block.make_block(
        height, state.chain_id, txs, commit,
        state.last_block_id, state.validators.hash(), state.app_hash, part_size,
        time_ns=height * 10**9,
    )
    return block, ps


class TestStatePersistence:
    def test_genesis_and_reload(self):
        doc, vs, _ = make_genesis()
        db = MemDB()
        s = State.get_state(db, doc)
        assert s.last_block_height == 0
        assert s.validators.hash() == vs.hash()
        s2 = State.get_state(db, doc)
        assert s2.equals(s)

    def test_validators_history(self):
        doc, vs, privs = make_genesis()
        db = MemDB()
        s = State.get_state(db, doc)
        # heights 1..3 without changes: pointer chain resolves to genesis set
        app = KVStoreApp()
        conns = AppConns(LocalClientCreator(app))
        conns.start()
        for h in range(1, 4):
            block, ps = make_next_block(s, [b"tx%d" % h], privs)
            apply_block(s, None, conns.consensus(), block, ps.header(), MockMempool())
        for h in range(1, 4):
            assert s.load_validators(h).hash() == vs.hash()


class TestExecution:
    def _setup(self, app=None):
        doc, vs, privs = make_genesis()
        db = MemDB()
        s = State.get_state(db, doc)
        s.tx_indexer = KVTxIndexer(MemDB())
        conns = AppConns(LocalClientCreator(app or KVStoreApp()))
        conns.start()
        return s, conns, privs

    def test_apply_blocks_advances_state(self):
        s, conns, privs = self._setup()
        for h in range(1, 4):
            block, ps = make_next_block(s, [b"key%d=val%d" % (h, h)], privs)
            apply_block(s, None, conns.consensus(), block, ps.header(), MockMempool())
            assert s.last_block_height == h
            assert s.last_block_id.hash == block.hash()
        # app hash binds app state
        q = conns.query().query_sync(b"key1")
        assert q.value == b"val1"
        # tx indexed
        from tendermint_tpu.types.tx import tx_hash

        r = s.tx_indexer.get(tx_hash(b"key1=val1"))
        assert r is not None and r.height == 1

    def test_validate_block_rejects(self):
        s, conns, privs = self._setup()
        block, ps = make_next_block(s, [b"a=1"], privs)
        apply_block(s, None, conns.consensus(), block, ps.header(), MockMempool())
        # wrong height
        bad, _ = make_next_block(s, [b"b=2"], privs)
        bad.header.height = 99
        with pytest.raises(InvalidBlockError):
            validate_block(s, bad)
        # tampered commit (drop one sig -> below quorum)
        bad2, _ = make_next_block(s, [b"b=2"], privs)
        signed = [i for i, p in enumerate(bad2.last_commit.precommits) if p]
        for i in signed[:2]:
            bad2.last_commit.precommits[i] = None
        bad2.header.last_commit_hash = bad2.last_commit.hash()
        bad2.header.data_hash = b""
        bad2.fill_header()
        with pytest.raises(InvalidBlockError):
            validate_block(s, bad2)

    def test_events_fired_on_flush(self):
        s, conns, privs = self._setup()
        evsw = EventSwitch()
        got = []
        from tendermint_tpu.types.events import event_string_tx
        from tendermint_tpu.types.tx import tx_hash

        tx = b"watched=1"
        evsw.add_listener_for_event("t", event_string_tx(tx_hash(tx)), got.append)
        cache = EventCache(evsw)
        block, ps = make_next_block(s, [tx], privs)
        apply_block(s, cache, conns.consensus(), block, ps.header(), MockMempool())
        assert got == []  # not yet flushed
        cache.flush()
        assert len(got) == 1 and got[0].height == 1

    def test_valset_change_via_endblock(self, tmp_path):
        app = PersistentKVStoreApp(str(tmp_path))
        s, conns, privs = self._setup(app)
        new_pub = gen_priv_key_ed25519(b"newval").pub_key()
        val_tx = b"val:" + new_pub.raw.hex().encode() + b"/7"
        block, ps = make_next_block(s, [val_tx], privs)
        apply_block(s, None, conns.consensus(), block, ps.header(), MockMempool())
        assert s.validators.size() == 5
        assert s.last_height_validators_changed == 2
        _, v = s.validators.get_by_address(new_pub.address())
        assert v is not None and v.voting_power == 7
        # removal
        rm_tx = b"val:" + new_pub.raw.hex().encode() + b"/0"
        block2, ps2 = make_next_block(s, [rm_tx], privs)
        apply_block(s, None, conns.consensus(), block2, ps2.header(), MockMempool())
        assert s.validators.size() == 4

    def test_exec_commit_block(self):
        s, conns, privs = self._setup()
        block, ps = make_next_block(s, [b"z=9"], privs)
        app_hash = exec_commit_block(conns.consensus(), block)
        assert len(app_hash) == 20

    def test_update_validators_errors(self):
        _, vs, _ = make_genesis()
        missing = gen_priv_key_ed25519(b"missing").pub_key()
        with pytest.raises(ValueError):
            update_validators(
                vs, [ABCIValidator([TYPE_ED25519, missing.raw.hex().upper()], -5)]
            )
