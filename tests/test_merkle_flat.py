"""Flat Merkle builder parity (round 7): the level-order FlatTree +
shared-aunt SimpleProof views must be byte-identical — roots AND every
per-leaf proof — to the pre-r7 recursive reference
(merkle.simple.recursive_proofs_from_hashes), across odd/even/prime leaf
counts from 1 to ~300. Plus the satellite hardening: SimpleProof.from_json
rejects aunts that aren't exactly one RIPEMD-160 digest wide, and the
gateway tx-root cache returns memoized roots without rehashing."""

from __future__ import annotations

import pytest

from tendermint_tpu.merkle.simple import (
    FlatTree,
    SharedProof,
    SimpleProof,
    flat_tree_from_leaf_digests,
    leaf_hash,
    recursive_proofs_from_hashes,
    simple_hash_from_hashes,
    simple_proofs_from_hashes,
)

# every count 1..40 (all small shapes incl. each odd/even boundary), then
# powers of two, their neighbors, and primes out to ~300
PARITY_COUNTS = list(range(1, 41)) + [
    63, 64, 65, 97, 101, 127, 128, 129, 151, 199, 200, 256, 257, 283, 300,
]


def _digests(n: int) -> list[bytes]:
    return [leaf_hash(b"leaf-%d" % i) for i in range(n)]


class TestFlatParity:
    @pytest.mark.parametrize("n", PARITY_COUNTS)
    def test_roots_and_proofs_byte_identical(self, n):
        ds = _digests(n)
        root_ref, proofs_ref = recursive_proofs_from_hashes(ds)
        root_flat, proofs_flat = simple_proofs_from_hashes(ds)
        assert root_flat == root_ref
        assert root_flat == simple_hash_from_hashes(ds)
        assert len(proofs_flat) == n
        for i in range(n):
            assert proofs_flat[i].aunts == proofs_ref[i].aunts, (n, i)
            assert proofs_flat[i].verify(i, n, ds[i], root_ref)

    @pytest.mark.parametrize("n", [1, 2, 3, 5, 16, 33, 100])
    def test_from_nodes_rehydration(self, n):
        """A FlatTree rebuilt from its own node buffer (what the devd
        tree frame ships) yields the same root and proofs."""
        ds = _digests(n)
        built = flat_tree_from_leaf_digests(ds)
        tree = FlatTree.from_nodes(n, ds + built.internal_nodes())
        root_ref, proofs_ref = recursive_proofs_from_hashes(ds)
        assert tree.root() == root_ref
        for i in range(n):
            assert tree.aunts_for(i) == proofs_ref[i].aunts

    def test_from_nodes_validates_count(self):
        ds = _digests(4)
        with pytest.raises(ValueError, match="needs 7 nodes"):
            FlatTree.from_nodes(4, ds)

    def test_empty(self):
        root, proofs = simple_proofs_from_hashes([])
        assert root == b"" and proofs == []
        assert simple_hash_from_hashes([]) == b""
        assert flat_tree_from_leaf_digests([]).root() == b""

    @pytest.mark.parametrize("n", [2, 3, 7, 16])
    def test_non_digest_leaf_widths_still_match_recursive(self, n):
        """simple_hash_from_hashes is a public API: non-20-byte operands
        must hash with their REAL varint length prefixes (the recursive
        builder's semantics), not the fast path's fixed 20-byte prefix."""
        from tendermint_tpu.merkle.simple import inner_hash

        leaves = [b"x" * (8 + i) for i in range(n)]  # 8..8+n-1 bytes

        def recursive(hs):
            if len(hs) == 1:
                return hs[0]
            mid = (len(hs) + 1) // 2
            return inner_hash(recursive(hs[:mid]), recursive(hs[mid:]))

        assert simple_hash_from_hashes(leaves) == recursive(leaves)

    def test_shared_proof_is_a_simple_proof(self):
        """SharedProof views serialize, compare, and verify exactly like
        eager SimpleProofs (wire compatibility)."""
        ds = _digests(7)
        root, proofs = simple_proofs_from_hashes(ds)
        _, proofs_ref = recursive_proofs_from_hashes(ds)
        p = proofs[3]
        assert isinstance(p, SharedProof) and isinstance(p, SimpleProof)
        # eq across representations, both directions
        assert p == proofs_ref[3] and proofs_ref[3] == p
        assert p != proofs_ref[2]
        rt = SimpleProof.from_json(p.to_json())
        assert rt == p
        assert rt.verify(3, 7, ds[3], root)

    def test_aunts_materialize_lazily_and_once(self):
        ds = _digests(9)
        tree = flat_tree_from_leaf_digests(ds)
        p = tree.proofs()[4]
        assert p._aunts is None  # view only until first access
        first = p.aunts
        assert p.aunts is first  # memoized


class TestProofJsonValidation:
    def test_roundtrip_ok(self):
        _, proofs = simple_proofs_from_hashes(_digests(5))
        for i, p in enumerate(proofs):
            assert SimpleProof.from_json(p.to_json()).aunts == p.aunts

    @pytest.mark.parametrize("width", [0, 2, 38, 42, 64, 128])
    def test_wrong_width_aunt_rejected(self, width):
        """Satellite: every decoded aunt must be exactly 20 bytes (40 hex
        chars) — the pre-r7 decoder accepted anything up to 64 bytes and
        only failed later at compare time."""
        with pytest.raises(ValueError, match="bad merkle proof aunts"):
            SimpleProof.from_json({"aunts": ["ab" * 20, "c" * width]})

    def test_exact_width_accepted(self):
        p = SimpleProof.from_json({"aunts": ["AB" * 20, "cd" * 20]})
        assert [len(a) for a in p.aunts] == [20, 20]

    def test_depth_and_type_still_rejected(self):
        with pytest.raises(ValueError):
            SimpleProof.from_json({"aunts": ["ab" * 20] * 65})
        with pytest.raises(ValueError):
            SimpleProof.from_json({"aunts": [42]})
        with pytest.raises(ValueError):
            SimpleProof.from_json({"aunts": "ab" * 20})


class TestTxRootCache:
    def test_cache_hits_skip_rehash(self, monkeypatch):
        from tendermint_tpu.ops.gateway import Hasher

        h = Hasher(use_tpu=False)
        txs = [b"tx-%d" % i for i in range(20)]
        root = h.tx_merkle_root(txs)
        assert h.stats()["tx_root_cache_hits"] == 0
        # unchanged set: memoized root, no second hash pass
        calls = []
        monkeypatch.setattr(
            h, "_tx_merkle_root_uncached",
            lambda t: calls.append(1) or b"\x00" * 20,
        )
        assert h.tx_merkle_root(list(txs)) == root
        assert calls == [] and h.stats()["tx_root_cache_hits"] == 1

    def test_distinct_sets_distinct_roots(self):
        from tendermint_tpu.merkle.simple import simple_hash_from_byteslices
        from tendermint_tpu.ops.gateway import Hasher

        h = Hasher(use_tpu=False)
        a = [b"a-%d" % i for i in range(17)]
        b = [b"b-%d" % i for i in range(17)]
        assert h.tx_merkle_root(a) == simple_hash_from_byteslices(a)
        assert h.tx_merkle_root(b) == simple_hash_from_byteslices(b)
        assert h.tx_merkle_root(a) != h.tx_merkle_root(b)

    def test_cache_evicts_fifo(self):
        from tendermint_tpu.ops.gateway import Hasher

        h = Hasher(use_tpu=False)
        h._tx_roots_cap = 4
        for i in range(6):
            h.tx_merkle_root([b"set-%d" % i])
        assert len(h._tx_roots) == 4


class TestPartSetTreePath:
    def test_from_data_tree_hasher_used(self):
        """A tree_hasher that returns (digests, FlatTree) short-circuits
        host proof building; headers and proofs stay byte-identical."""
        from tendermint_tpu.crypto.hashing import ripemd160
        from tendermint_tpu.types.part_set import PartSet

        data = bytes(range(256)) * 160  # 40 KB -> 10 parts of 4 KB
        calls = []

        def tree_hasher(chunks):
            calls.append(len(chunks))
            digests = [ripemd160(c) for c in chunks]
            return digests, flat_tree_from_leaf_digests(digests)

        ps = PartSet.from_data(data, 4096, tree_hasher=tree_hasher)
        ref = PartSet.from_data(data, 4096)
        assert calls == [10]
        assert ps.header() == ref.header()
        for i in range(ps.total):
            part, rpart = ps.get_part(i), ref.get_part(i)
            assert part.proof == rpart.proof
            assert part.proof.verify(i, ps.total, part.hash(), ps.hash())

    def test_from_data_tree_hasher_none_falls_back(self):
        from tendermint_tpu.types.part_set import PartSet

        data = b"z" * 30000
        ps = PartSet.from_data(data, 4096, tree_hasher=lambda chunks: None)
        assert ps.header() == PartSet.from_data(data, 4096).header()

    def test_gateway_part_set_tree_local_route(self):
        """Hasher.part_set_tree on the in-process route returns the
        kernel node buffer; parity against the host reference."""
        from tendermint_tpu.crypto.hashing import ripemd160
        from tendermint_tpu.ops.gateway import Hasher

        h = Hasher(min_tpu_batch=1, use_tpu=True)
        h._route = "local"
        chunks = [bytes([i]) * (2000 + i) for i in range(11)]
        built = h.part_set_tree(chunks)
        assert built is not None
        digests, tree = built
        assert digests == [ripemd160(c) for c in chunks]
        root_ref, proofs_ref = recursive_proofs_from_hashes(digests)
        assert tree.root() == root_ref
        for i in range(11):
            assert tree.aunts_for(i) == proofs_ref[i].aunts
