"""Crypto tests: RFC 8032 vectors, pure-py vs OpenSSL parity, RIPEMD-160
known-answer tests, codec determinism, merkle tree + proofs."""

import hashlib

import pytest

from tendermint_tpu.codec.binary import (
    Decoder,
    Encoder,
    encode_bytes,
    encode_uvarint,
    encode_varint,
)
from tendermint_tpu.codec.canonical import canonical_dumps, sign_bytes
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.crypto.hashing import _ripemd160_py, ripemd160, sha256
from tendermint_tpu.crypto.keys import (
    PrivKeyEd25519,
    PubKeyEd25519,
    SignatureEd25519,
    gen_priv_key_ed25519,
)
from tendermint_tpu.merkle.simple import (
    SimpleProof,
    inner_hash,
    leaf_hash,
    simple_hash_from_byteslices,
    simple_hash_from_hashes,
    simple_hash_from_map,
    simple_proofs_from_byteslices,
)

# RFC 8032 section 7.1 test vectors (secret, public, message, signature)
RFC8032_VECTORS = [
    (
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
    (
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
    ),
]


class TestEd25519PurePython:
    @pytest.mark.parametrize("sk,pk,msg,sig", RFC8032_VECTORS)
    def test_rfc8032_keygen(self, sk, pk, msg, sig):
        assert ed25519.public_key_py(bytes.fromhex(sk)).hex() == pk

    @pytest.mark.parametrize("sk,pk,msg,sig", RFC8032_VECTORS)
    def test_rfc8032_sign(self, sk, pk, msg, sig):
        assert ed25519.sign_py(bytes.fromhex(sk), bytes.fromhex(msg)).hex() == sig

    @pytest.mark.parametrize("sk,pk,msg,sig", RFC8032_VECTORS)
    def test_rfc8032_verify(self, sk, pk, msg, sig):
        assert ed25519.verify_py(
            bytes.fromhex(pk), bytes.fromhex(msg), bytes.fromhex(sig)
        )

    def test_verify_rejects_bad_sig(self):
        sk, pk, msg, sig = RFC8032_VECTORS[2]
        bad = bytearray(bytes.fromhex(sig))
        bad[0] ^= 1
        assert not ed25519.verify_py(bytes.fromhex(pk), bytes.fromhex(msg), bytes(bad))
        assert not ed25519.verify_py(
            bytes.fromhex(pk), b"wrong message", bytes.fromhex(sig)
        )

    def test_verify_rejects_high_s(self):
        sk, pk, msg, sig = RFC8032_VECTORS[0]
        raw = bytearray(bytes.fromhex(sig))
        s = int.from_bytes(raw[32:], "little") + ed25519.L
        raw[32:] = s.to_bytes(32, "little")
        assert not ed25519.verify_py(bytes.fromhex(pk), bytes.fromhex(msg), bytes(raw))


class TestEd25519Backends:
    def test_backend_parity(self):
        """OpenSSL fast path and pure python agree on keygen/sign/verify."""
        seed = hashlib.sha256(b"parity-seed").digest()
        msg = b"the quick brown fox"
        assert ed25519.public_key(seed) == ed25519.public_key_py(seed)
        sig_fast = ed25519.sign(seed, msg)
        sig_py = ed25519.sign_py(seed, msg)
        assert sig_fast == sig_py  # ed25519 signing is deterministic
        assert ed25519.verify(ed25519.public_key(seed), msg, sig_fast)
        assert ed25519.verify_py(ed25519.public_key(seed), msg, sig_fast)

    def test_keys_api(self):
        priv = gen_priv_key_ed25519(b"some-seed-material")
        pub = priv.pub_key()
        sig = priv.sign(b"hello")
        assert pub.verify_bytes(b"hello", sig)
        assert not pub.verify_bytes(b"goodbye", sig)
        assert len(pub.address()) == 20
        # deterministic address
        assert gen_priv_key_ed25519(b"some-seed-material").pub_key().address() == pub.address()

    def test_key_json_roundtrip(self):
        priv = gen_priv_key_ed25519(b"json-seed")
        assert PrivKeyEd25519.from_json(priv.to_json()) == priv
        pub = priv.pub_key()
        assert PubKeyEd25519.from_json(pub.to_json()) == pub
        sig = priv.sign(b"m")
        assert SignatureEd25519.from_json(sig.to_json()) == sig


class TestHashing:
    # Known-answer tests from the RIPEMD-160 paper (Bosselaers & Preneel)
    KATS = [
        (b"", "9c1185a5c5e9fc54612808977ee8f548b2258d31"),
        (b"a", "0bdc9d2d256b3ee9daae347be6f4dc835a467ffe"),
        (b"abc", "8eb208f7e05d987a9b044a8e98c6b087f15a0bfc"),
        (b"message digest", "5d0689ef49d2fae572b881b123a85ffa21595f36"),
        (
            b"abcdefghijklmnopqrstuvwxyz",
            "f71c27109c692c1b56bbdceb5b9d2865b3708dbc",
        ),
        (
            b"1234567890" * 8,
            "9b752e45573d4b39f4dbd3323cab82bf63326bfb",
        ),
    ]

    @pytest.mark.parametrize("msg,digest", KATS)
    def test_ripemd160_pure(self, msg, digest):
        assert _ripemd160_py(msg).hex() == digest

    @pytest.mark.parametrize("msg,digest", KATS)
    def test_ripemd160_dispatch(self, msg, digest):
        assert ripemd160(msg).hex() == digest

    def test_ripemd160_long_input(self):
        data = bytes(range(256)) * 300
        assert _ripemd160_py(data) == ripemd160(data)

    def test_ripemd160_native_batch_parity(self):
        """The native batch (16-lane SIMD groups + scalar remainder —
        the PartSet leaf-hash path) must be bit-identical to the scalar
        reference at every padding shape and across mixed-length
        grouping boundaries."""
        import random

        from tendermint_tpu import native

        if not native.available():
            pytest.skip("native library unavailable")
        rng = random.Random(71)
        msgs = []
        for ln in (0, 1, 55, 56, 63, 64, 65, 119, 120, 127, 128, 4096):
            # 17 per length: one full 16-lane group plus a scalar leftover
            msgs.extend(rng.randbytes(ln) for _ in range(17))
        rng.shuffle(msgs)
        got = native.ripemd160_batch(msgs)
        assert got == [ripemd160(m) for m in msgs]

    def test_sha256(self):
        assert (
            sha256(b"abc").hex()
            == "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad"
        )


class TestBinaryCodec:
    def test_uvarint_spec_examples(self):
        # from docs/specification/wire-protocol.rst
        assert encode_uvarint(0) == bytes.fromhex("00")
        assert encode_uvarint(1) == bytes.fromhex("0101")
        assert encode_uvarint(2) == bytes.fromhex("0102")
        assert encode_uvarint(256) == bytes.fromhex("020100")

    def test_varint_spec_examples(self):
        assert encode_varint(0) == bytes.fromhex("00")
        assert encode_varint(1) == bytes.fromhex("0101")
        assert encode_varint(-1) == bytes.fromhex("8101")
        assert encode_varint(-2) == bytes.fromhex("8102")
        assert encode_varint(-256) == bytes.fromhex("820100")

    def test_struct_spec_example(self):
        # Foo{"626172" (i.e. "bar"), MaxUint32} -> 0103626172FFFFFFFF
        e = Encoder().write_string("bar").write_u32(0xFFFFFFFF)
        assert e.buf().hex().upper() == "0103626172FFFFFFFF"

    def test_roundtrip(self):
        e = (
            Encoder()
            .write_varint(-12345)
            .write_uvarint(98765)
            .write_bytes(b"payload")
            .write_string("hello")
            .write_u64(2**63)
            .write_i64(-42)
            .write_time_ns(1500000000 * 10**9)
            .write_list([1, 2, 3], lambda enc, x: enc.write_varint(x))
        )
        d = Decoder(e.buf())
        assert d.read_varint() == -12345
        assert d.read_uvarint() == 98765
        assert d.read_bytes() == b"payload"
        assert d.read_string() == "hello"
        assert d.read_u64() == 2**63
        assert d.read_i64() == -42
        assert d.read_time_ns() == 1500000000 * 10**9
        assert d.read_list(lambda dec: dec.read_varint()) == [1, 2, 3]
        assert d.done()

    def test_decode_truncated_raises(self):
        with pytest.raises(ValueError):
            Decoder(b"\x05ab").read_bytes()

    def test_decode_rejects_non_canonical(self):
        # negative zero
        with pytest.raises(ValueError):
            Decoder(b"\x80").read_varint()
        # leading zero bodies
        with pytest.raises(ValueError):
            Decoder(b"\x02\x00\x01").read_varint()
        with pytest.raises(ValueError):
            Decoder(b"\x02\x00\x01").read_uvarint()


class TestCanonicalJSON:
    def test_deterministic_sorted_compact(self):
        out = canonical_dumps({"b": 1, "a": {"d": 2, "c": b"\xab\xcd"}})
        assert out == b'{"a":{"c":"ABCD","d":2},"b":1}'

    def test_sign_bytes_shape(self):
        # mirrors the docs' vote sign-bytes example shape
        payload = {
            "block_id": {
                "hash": bytes.fromhex("611801F57B4CE378DF1A3FFF1216656E89209A99"),
                "parts": {
                    "hash": bytes.fromhex("B46697379DBE0774CC2C3B656083F07CA7E0F9CE"),
                    "total": 123,
                },
            },
            "height": 1234,
            "round": 1,
            "type": 2,
        }
        out = sign_bytes("my_chain", "vote", payload)
        assert out.startswith(b'{"chain_id":"my_chain","vote":{"block_id"')
        assert b'"height":1234' in out
        assert out.index(b'"chain_id"') < out.index(b'"vote"')

    def test_floats_rejected(self):
        with pytest.raises(TypeError):
            canonical_dumps({"x": 1.5})


class TestMerkle:
    def test_empty_and_single(self):
        assert simple_hash_from_hashes([]) == b""
        h = leaf_hash(b"item")
        assert simple_hash_from_hashes([h]) == h

    def test_left_heavy_split(self):
        """With 3 leaves the split is 2|1 per the spec diagrams."""
        hs = [leaf_hash(bytes([i])) for i in range(3)]
        expected = inner_hash(inner_hash(hs[0], hs[1]), hs[2])
        assert simple_hash_from_hashes(hs) == expected

    def test_five_leaves_shape(self):
        hs = [leaf_hash(bytes([i])) for i in range(5)]
        # split 3|2; left splits 2|1; right splits 1|1
        left = inner_hash(inner_hash(hs[0], hs[1]), hs[2])
        right = inner_hash(hs[3], hs[4])
        assert simple_hash_from_hashes(hs) == inner_hash(left, right)

    @pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 6, 7, 8, 9, 16, 17, 100])
    def test_proofs_verify(self, n):
        items = [b"item-%d" % i for i in range(n)]
        root, proofs = simple_proofs_from_byteslices(items)
        assert root == simple_hash_from_byteslices(items)
        for i, item in enumerate(items):
            assert proofs[i].verify(i, n, leaf_hash(item), root)
            # wrong index / wrong leaf fail
            assert not proofs[i].verify((i + 1) % n, n, leaf_hash(item), root) or n == 1
            assert not proofs[i].verify(i, n, leaf_hash(b"evil"), root)

    def test_proof_json_roundtrip(self):
        _, proofs = simple_proofs_from_byteslices([b"a", b"b", b"c"])
        p = proofs[1]
        assert SimpleProof.from_json(p.to_json()).aunts == p.aunts

    def test_map_hash_order_independent(self):
        a = simple_hash_from_map({"x": b"1", "y": b"2", "z": b"3"})
        b = simple_hash_from_map({"z": b"3", "x": b"1", "y": b"2"})
        assert a == b and len(a) == 20


class TestSecp256k1:
    """go-crypto's second key type (ref types/validator.go:75-86 consumes
    any crypto.PubKey): compressed points, DER low-s ECDSA, bitcoin-shaped
    addresses, CPU-verified via the gateway's key-type partition."""

    def test_sign_verify_roundtrip(self):
        from tendermint_tpu.crypto.keys import gen_priv_key_secp256k1

        pk = gen_priv_key_secp256k1(b"secp-test-seed")
        pub = pk.pub_key()
        assert len(pub.raw) == 33 and pub.raw[0] in (2, 3)
        assert len(pub.address()) == 20
        sig = pk.sign(b"hello")
        assert pub.verify_bytes(b"hello", sig)
        assert not pub.verify_bytes(b"hell0", sig)
        # deterministic key from seed
        assert gen_priv_key_secp256k1(b"secp-test-seed").raw == pk.raw

    def test_low_s_and_tamper_rejection(self):
        from tendermint_tpu.crypto import secp256k1
        from tendermint_tpu.crypto.keys import gen_priv_key_secp256k1

        pk = gen_priv_key_secp256k1(b"low-s")
        sig = pk.sign(b"msg")
        r, s = secp256k1.decode_der(sig.raw)
        assert s <= secp256k1._N // 2
        # the high-s twin verifies under naive ECDSA but must be rejected
        high = secp256k1.encode_der(r, secp256k1._N - s)
        assert not secp256k1.verify(pk.pub_key().raw, b"msg", high)

    def test_json_roundtrip_and_dispatch(self):
        from tendermint_tpu.crypto.keys import (
            gen_priv_key_secp256k1,
            priv_key_from_json,
            pub_key_from_json,
            signature_from_json,
        )

        pk = gen_priv_key_secp256k1(b"json")
        assert priv_key_from_json(pk.to_json()) == pk
        assert pub_key_from_json(pk.pub_key().to_json()) == pk.pub_key()
        sig = pk.sign(b"x")
        assert signature_from_json(sig.to_json()) == sig

    def test_gateway_mixed_batch(self):
        from tendermint_tpu.crypto.keys import (
            gen_priv_key_ed25519,
            gen_priv_key_secp256k1,
        )
        from tendermint_tpu.ops.gateway import Verifier

        eds = [gen_priv_key_ed25519(b"me%d" % i) for i in range(6)]
        secs = [gen_priv_key_secp256k1(b"ms%d" % i) for i in range(3)]
        items, want = [], []
        for i, k in enumerate(eds):
            msg = b"edmsg%d" % i
            sig = k.sign(msg).raw
            if i == 2:
                sig = sig[:5] + bytes([sig[5] ^ 1]) + sig[6:]
            items.append((k.pub_key().raw, msg, sig))
            want.append(i != 2)
        for i, k in enumerate(secs):
            msg = b"smsg%d" % i
            sig = k.sign(msg).raw
            ok = i != 1
            if not ok:
                msg = b"tampered"
                items.append((k.pub_key().raw, b"smsg1", k.sign(msg).raw))
            else:
                items.append((k.pub_key().raw, msg, sig))
            want.append(ok)
        # interleave deterministically
        order = [0, 6, 1, 7, 2, 8, 3, 4, 5]
        mixed = [items[i] for i in order]
        expect = [want[i] for i in order]
        v = Verifier(min_tpu_batch=1, use_tpu=True)
        assert v.verify_batch(mixed) == expect
        assert v.verify_batch_async(mixed)() == expect
        st = v.stats()
        assert st["tpu_sigs"] > 0 and st["cpu_sigs"] > 0

    def test_secp_validator_in_commit(self):
        """A mixed ed25519/secp256k1 validator set verifies a commit
        through the batch path with identical semantics."""
        from tendermint_tpu.crypto.keys import (
            gen_priv_key_ed25519,
            gen_priv_key_secp256k1,
        )
        from tendermint_tpu.ops.gateway import Verifier
        from tendermint_tpu.types import BlockID, PrivValidatorFS, Vote
        from tendermint_tpu.types.block_id import PartSetHeader
        from tendermint_tpu.types.validator_set import Validator, ValidatorSet
        from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT
        from tendermint_tpu.types.vote_set import VoteSet

        privs = [gen_priv_key_ed25519(b"mixed%d" % i) for i in range(3)] + [
            gen_priv_key_secp256k1(b"mixed3")
        ]
        vs = ValidatorSet([Validator.new(p.pub_key(), 1) for p in privs])
        by_addr = {p.pub_key().address(): p for p in privs}
        block_id = BlockID(b"\x42" * 20, PartSetHeader(1, b"\x43" * 20))
        voteset = VoteSet("test-chain", 5, 0, VOTE_TYPE_PRECOMMIT, vs)
        for i, val in enumerate(vs.validators):
            p = by_addr[val.address]
            vote = Vote(val.address, i, 5, 0, VOTE_TYPE_PRECOMMIT, block_id)
            voteset.add_vote(vote.with_signature(p.sign(vote.sign_bytes("test-chain"))))
        commit = voteset.make_commit()
        v = Verifier(min_tpu_batch=1, use_tpu=True)
        vs.verify_commit(
            "test-chain", block_id, 5, commit, batch_verifier=v.commit_batch_verifier()
        )  # no raise
        assert v.stats()["cpu_sigs"] >= 1  # the secp lane went to CPU


class TestNativeRLCBatchVerify:
    """Random-linear-combination batch verification (native/src/ed25519.cc
    ed25519_verify_batch_rlc): the combined-equation fast path must be
    indistinguishable from the strict per-item loop on every adversarial
    shape — any divergence is a consensus-safety bug."""

    @staticmethod
    def _items(n, mutate=None):
        from tendermint_tpu.crypto import ed25519 as ed

        seeds = [bytes([i % 48 + 1]) * 32 for i in range(n)]
        items = []
        for i, s in enumerate(seeds):
            msg = b"rlc-t-%d" % i
            items.append((ed.public_key(s), msg, ed.sign(s, msg)))
        if mutate:
            items = mutate(items)
        return items

    def _check_parity(self, items):
        from tendermint_tpu import native
        from tendermint_tpu.crypto import ed25519 as ed

        if not native.available():
            pytest.skip("native library unavailable")
        got = native.ed25519_verify_batch(items)
        want = [
            len(p) == 32 and len(s) == 64 and ed.verify(p, m, s)
            for p, m, s in items
        ]
        assert got == want
        return got

    def test_all_valid_wide_batch(self):
        out = self._check_parity(self._items(128))
        assert out == [True] * 128

    def test_rlc_fast_path_accepts_directly(self):
        """The combined equation itself must ACCEPT all-valid batches.
        Verdict-parity tests can't see a silently-broken MSM: a wrong
        combined point just rejects, and the per-item fallback hides it
        behind correct (but slow) verdicts. Sizes straddle the
        vectorized path's group boundaries and its m>=128 gate."""
        import ctypes

        import numpy as np

        from tendermint_tpu import native

        if not native.available():
            pytest.skip("native library unavailable")
        lib = native.get_lib()

        def rlc(items):
            pubs = np.frombuffer(b"".join(p for p, _, _ in items), np.uint8)
            sigs = np.frombuffer(b"".join(s for _, _, s in items), np.uint8)
            data, offsets = native._concat([m for _, m, _ in items])
            return lib.tm_ed25519_verify_batch_rlc(
                native._as_u8p(pubs), native._as_u8p(sigs),
                native._as_u8p(data),
                offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                len(items),
            )

        try:
            # 1 = scalar MSM, 2 = vectorized; both must accept every size,
            # including m < 128 shapes the auto gate never vectorizes
            for path in (1, 2, 0):
                lib.tm_ed25519_msm_path(path)
                for n in (16, 63, 64, 65, 200, 512):
                    assert rlc(self._items(n)) == 1, (
                        f"RLC fast path rejected a valid batch "
                        f"(n={n}, msm_path={path})"
                    )
                # soundness through the same forced path: one forged lane
                # must reject the combined equation. Catches a degenerate
                # MSM (e.g. buckets never accumulating -> identity), which
                # the acceptance assertions above cannot see.
                for n in (64, 200):
                    items = self._items(n)
                    sig = bytearray(items[n // 2][2])
                    sig[5] ^= 0x20
                    items[n // 2] = (
                        items[n // 2][0], items[n // 2][1], bytes(sig)
                    )
                    assert rlc(items) == 0, (
                        f"RLC accepted a forged lane (n={n}, msm_path={path})"
                    )
        finally:
            lib.tm_ed25519_msm_path(0)

    def test_every_adversarial_lane_shape(self):
        from tendermint_tpu.crypto import ed25519 as ed

        def mutate(items):
            P = 2**255 - 19
            p0, m0, s0 = items[0]
            items[1] = (p0, m0 + b"!", items[1][2])          # wrong msg
            items[2] = (items[3][0], m0, s0)                 # wrong pub
            sig = items[4][2]
            items[4] = (items[4][0], items[4][1],
                        sig[:10] + bytes([sig[10] ^ 1]) + sig[11:])  # tampered
            # s >= L: s' = s + L verifies mod L — the strict check (and
            # the RLC pre-check) must reject it
            p5, m5, s5 = items[5]
            s_plus_l = (int.from_bytes(s5[32:], "little") + ed.L).to_bytes(32, "little")
            items[5] = (p5, m5, s5[:32] + s_plus_l)
            # non-canonical R.y >= p
            p6, m6, s6 = items[6]
            items[6] = (p6, m6, (P + 1).to_bytes(32, "little") + s6[32:])
            # invalid A point
            items[7] = (b"\x01" * 32, items[7][1], items[7][2])
            return items

        out = self._check_parity(self._items(64, mutate))
        # lanes 1,2,4,5,6,7 mutated bad; 0,3 and the rest stay valid
        assert out == [
            i not in (1, 2, 4, 5, 6, 7) for i in range(64)
        ]

    def test_rfc8032_vectors_through_the_batch(self):
        from tests.test_ops_f32 import RFC8032_VECTORS

        base = self._items(40)
        for _sk, pk, msg, sig in RFC8032_VECTORS:
            base.append((bytes.fromhex(pk), bytes.fromhex(msg), bytes.fromhex(sig)))
        out = self._check_parity(base)
        assert all(out)

    def test_repeated_and_distinct_keys(self):
        # one signer for the whole batch (max A-cache hits) and all
        # distinct signers (no hits) must both verify
        from tendermint_tpu.crypto import ed25519 as ed

        seed = b"\x51" * 32
        pub = ed.public_key(seed)
        same = [(pub, b"m%d" % i, ed.sign(seed, b"m%d" % i)) for i in range(64)]
        assert self._check_parity(same) == [True] * 64
        distinct = self._items(64)
        assert self._check_parity(distinct) == [True] * 64

    def test_small_batches_take_the_exact_path(self):
        # below RLC_MIN_BATCH nothing changes at all
        out = self._check_parity(self._items(8))
        assert out == [True] * 8

    def test_bisection_finds_random_forged_subsets(self):
        """On rejection the batch bisects (k bad lanes cost O(k log n)
        RLC work, not a full per-item rerun) — verdicts must stay exact
        for any forged-subset shape, including subsets straddling the
        bisection midpoints."""
        import random as _random

        rng = _random.Random(77)
        # forge by mutating the MESSAGE: the signature stays canonical
        # (s < L, valid R), so rejection happens at the RLC combined
        # EQUATION, not the cheap strict pre-checks — the mathematically
        # interesting path
        for n, k in ((64, 1), (64, 2), (96, 5), (128, 33), (128, 128)):
            items = self._items(n)
            bad = set(rng.sample(range(n), k))
            for b in bad:
                items[b] = (items[b][0], items[b][1] + b"!", items[b][2])
            out = self._check_parity(items)
            assert out == [i not in bad for i in range(n)], (n, k)
        # and the exact midpoint-straddle shape
        items = self._items(64)
        for b in (31, 32):
            items[b] = (items[b][0], items[b][1] + b"!", items[b][2])
        assert self._check_parity(items) == [i not in (31, 32) for i in range(64)]


class TestItems8Ladder:
    """Differential tests of the 8-wide IFMA per-item ladder
    (native verify8_with_neg_a) against the scalar ladder via the
    tm_ed25519_items8_path seam — the exact-verdict floor every failed
    RLC batch now runs once (native.py ed25519_verify_batch)."""

    @staticmethod
    def _run_items(items, path):
        import ctypes

        import numpy as np

        from tendermint_tpu import native

        lib = native.get_lib()
        lib.tm_ed25519_items8_path(path)
        try:
            pubs = np.frombuffer(
                b"".join(
                    p if len(p) == 32 else b"\x00" * 32 for p, _, _ in items
                ),
                np.uint8,
            )
            sigs = np.frombuffer(
                b"".join(
                    s if len(s) == 64 else b"\x00" * 64 for _, _, s in items
                ),
                np.uint8,
            )
            data, offsets = native._concat([m for _, m, _ in items])
            out = np.zeros(len(items), dtype=np.uint8)
            lib.tm_ed25519_verify_batch(
                native._as_u8p(pubs), native._as_u8p(sigs),
                native._as_u8p(data),
                offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                len(items), native._as_u8p(out),
            )
            return [bool(b) for b in out]
        finally:
            lib.tm_ed25519_items8_path(0)

    def _parity(self, items):
        import pytest as _pytest

        from tendermint_tpu import native

        if not native.available():
            _pytest.skip("native library unavailable")
        scalar = self._run_items(items, 1)
        wide = self._run_items(items, 2)
        assert scalar == wide, [
            i for i, (a, b) in enumerate(zip(scalar, wide)) if a != b
        ]
        return wide

    def test_clean_batches_every_group_shape(self):
        # sizes straddle the 8-lane grouping: full groups, ragged tails,
        # and sub-group batches that run entirely scalar
        for n in (3, 8, 9, 15, 16, 17, 64):
            items = TestNativeRLCBatchVerify._items(n)
            assert all(self._parity(items)), n

    def test_adversarial_lane_shapes(self):
        from tendermint_tpu.crypto import ed25519 as ed

        items = TestNativeRLCBatchVerify._items(32)
        p, m, s = items[0]
        items[0] = (p, m, s[:32] + bytes([s[32] ^ 1]) + s[33:])  # forged S
        p, m, s = items[9]
        items[9] = (p, m + b"!", s)  # wrong message
        p, m, s = items[17]
        items[17] = (bytes([p[0] ^ 1]) + p[1:], m, s)  # wrong key
        p, m, s = items[18]
        items[18] = (b"\xff" * 32, m, s)  # undecodable A
        p, m, s = items[25]
        items[25] = (p, m, s[:63] + b"\xff")  # s >= L (cheap reject)
        out = self._parity(items)
        assert out == [i not in (0, 9, 17, 18, 25) for i in range(32)]

    def test_repeated_keys_share_decompression(self):
        # one signer across groups: the A-cache dedups decompression;
        # verdicts must be unaffected
        from tendermint_tpu.crypto import ed25519 as ed

        seed = b"\x52" * 32
        pub = ed.public_key(seed)
        items = [(pub, b"k%d" % i, ed.sign(seed, b"k%d" % i)) for i in range(24)]
        items[11] = (pub, b"k11", b"\x01" * 64)
        out = self._parity(items)
        assert out == [i != 11 for i in range(24)]
