"""Device-daemon tests (tendermint_tpu/devd.py): protocol, verify parity,
async pipelining, and the gateway's automatic devd routing — all against
a real daemon subprocess serving the CPU backend, so the IPC path CI
exercises is byte-for-byte the one the TPU daemon serves in production.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

import pytest

from tendermint_tpu import devd
from tendermint_tpu.crypto import ed25519 as ed

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def daemon(tmp_path_factory):
    sock = str(tmp_path_factory.mktemp("devd") / "devd.sock")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "TENDERMINT_DEVD_SOCK": sock,
        "TENDERMINT_DEVD_ACCEPT_CPU": "1",
        "TENDERMINT_DEVD_WARM": "16",
        "TENDERMINT_DEVD_EXIT_ON_TERM": "1",
    }
    proc = subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.devd"],
        env=env, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
    )
    client = devd.DevdClient(sock)
    deadline = time.time() + 240  # cold .jax_cache: one f32 ladder compile
    held = False
    while time.time() < deadline:
        if proc.poll() is not None:
            break
        try:
            rep = client.ping(timeout=2.0)
            if rep.get("held"):
                held = True
                break
        except Exception:
            pass
        time.sleep(1.0)
    if not held:
        err = b""
        if proc.poll() is not None:
            err = proc.stderr.read() if proc.stderr else b""
        proc.kill()
        pytest.fail(f"daemon never reached serving state: {err[-2000:]!r}")
    yield sock, client
    try:
        client.shutdown()
    except Exception:
        pass
    client.close()
    try:
        proc.wait(timeout=15)
    except subprocess.TimeoutExpired:
        proc.kill()


def _items(n: int, tag: bytes = b"devd"):
    seed = b"\x21" * 32
    pub = ed.public_key(seed)
    return [
        (pub, tag + b"-%d" % i, ed.sign(seed, tag + b"-%d" % i))
        for i in range(n)
    ]


def test_ping_reports_serving(daemon):
    _, client = daemon
    rep = client.ping()
    assert rep["held"] and rep["status"] == "serving"
    assert rep["platform"] == "cpu"
    assert rep["warmed"] == [16]
    assert rep["pid"] > 0


def test_verify_parity_with_cpu(daemon):
    _, client = daemon
    items = _items(6)
    items[2] = (items[2][0], items[2][1], b"\x13" * 64)  # forged
    items[4] = (items[4][0], items[4][1] + b"x", items[4][2])  # tampered msg
    got = client.verify_batch(items)
    want = [ed.verify(p, m, s) for p, m, s in items]
    assert got == want == [True, True, False, True, False, True]


def test_async_pipelining_preserves_order(daemon):
    _, client = daemon
    batches = [_items(5, tag=b"pipe%d" % k) for k in range(4)]
    for k in range(4):
        p, m, _ = batches[k][k]
        batches[k][k] = (p, m, b"\x31" * 64)
    resolvers = [client.verify_batch_async(b) for b in batches]
    for k, resolve in enumerate(resolvers):
        assert resolve() == [i != k for i in range(5)], k


def test_gateway_default_routes_through_daemon(daemon, monkeypatch):
    """With a daemon serving, a default-constructed Verifier picks the
    devd backend automatically: this process does no device (or kernel)
    work at all, and the daemon's counters move."""
    sock, client = daemon
    monkeypatch.setenv("TENDERMINT_DEVD_SOCK", sock)
    monkeypatch.delenv("TENDERMINT_TPU_KERNEL", raising=False)
    import tendermint_tpu.ops.devd_backend as backend
    from tendermint_tpu.ops import gateway

    monkeypatch.setattr(backend, "_client", None)
    devd.bust_avail_cache()  # bust the TTL cache for the new path
    assert gateway.kernel_name() == "devd"

    before = client.stats().get("tpu_sigs", 0) + client.stats().get("cpu_sigs", 0)
    v = gateway.Verifier(min_tpu_batch=1)
    items = _items(8, tag=b"gw")
    items[3] = (items[3][0], items[3][1], b"\x55" * 64)
    assert v.verify_batch(items) == [i != 3 for i in range(8)]
    assert v.stats()["tpu_sigs"] == 8  # routed, not CPU-fallback
    after = client.stats().get("tpu_sigs", 0) + client.stats().get("cpu_sigs", 0)
    assert after - before == 8


class _DeadClient:
    def verify_batch(self, items):
        raise ConnectionError("daemon transport died")

    def verify_batch_async(self, items):
        raise ConnectionError("daemon transport died")


def test_transport_failure_with_live_daemon_opens_breaker(daemon, monkeypatch):
    """Requests failing while the daemon still serves: after the breaker
    threshold (3 consecutive failures) the shared breaker OPENS and
    batches ride the CPU fallback — never an in-process dial at the chip
    the live daemon exclusively holds, and (round 8) never the old
    permanent CPU latch: once the transport heals, a half-open probe
    re-closes the breaker and devd routing resumes."""
    sock, _ = daemon
    monkeypatch.setenv("TENDERMINT_DEVD_SOCK", sock)
    monkeypatch.delenv("TENDERMINT_TPU_KERNEL", raising=False)
    monkeypatch.setenv("TENDERMINT_TPU_BREAKER_BACKOFF_S", "0.05")
    monkeypatch.setenv("TENDERMINT_TPU_BREAKER_BACKOFF_CAP_S", "0.2")
    devd.bust_avail_cache()
    import tendermint_tpu.ops.devd_backend as backend
    from tendermint_tpu.ops import gateway

    gateway.reset_devd_breaker()
    try:
        v = gateway.Verifier(min_tpu_batch=1)
        assert v._kernel == "devd"
        monkeypatch.setattr(backend, "_client", _DeadClient())
        items = _items(4, tag=b"demote")
        items[1] = (items[1][0], items[1][1], b"\x99" * 64)
        # correct results throughout (retries then the CPU fallback)
        assert v.verify_batch(items) == [True, False, True, True]
        assert v._kernel == "devd"  # never stole the daemon's device
        assert v._tpu_ok  # NOT latched: the breaker owns the fallback
        br = gateway.devd_breaker()
        assert br.state == br.OPEN
        resolve = v.verify_batch_async(items)
        assert resolve() == [True, False, True, True]

        # transport heals (the daemon was serving all along): the next
        # due probe re-closes the breaker and devd routing resumes
        backend._client = None  # next _get_client dials the real daemon
        deadline = time.time() + 5.0
        while br.state != br.CLOSED and time.time() < deadline:
            time.sleep(0.05)
            assert v.verify_batch(items) == [True, False, True, True]
        assert br.state == br.CLOSED
        before = v.stats()["tpu_sigs"]
        assert v.verify_batch(items) == [True, False, True, True]
        assert v.stats()["tpu_sigs"] == before + 4  # devd-routed again
    finally:
        gateway.reset_devd_breaker()


def test_daemon_death_opens_breaker_and_recovery_restores_devd(
        daemon, monkeypatch):
    """The daemon actually gone: the breaker opens (probes fail), every
    batch verifies correctly on the CPU fallback, and when the daemon
    returns a probe re-closes the breaker — devd routing restored with
    no process restart. (Round 8 replaces the old one-way devd ->
    direct-kernel demotion: re-dialing the chip in-process raced the
    daemon's own re-claim, the exact one-owner violation devd exists to
    prevent.)"""
    sock, _ = daemon
    monkeypatch.setenv("TENDERMINT_DEVD_SOCK", sock)
    monkeypatch.delenv("TENDERMINT_TPU_KERNEL", raising=False)
    monkeypatch.setenv("TENDERMINT_TPU_BREAKER_BACKOFF_S", "0.05")
    monkeypatch.setenv("TENDERMINT_TPU_BREAKER_BACKOFF_CAP_S", "0.2")
    devd.bust_avail_cache()
    import tendermint_tpu.ops.devd_backend as backend
    from tendermint_tpu.ops import gateway

    gateway.reset_devd_breaker()
    real_available = devd.available
    try:
        v = gateway.Verifier(min_tpu_batch=1)
        assert v._kernel == "devd"
        # simulate death: transport raises AND the fresh re-ping (the
        # breaker's probe) finds nothing
        monkeypatch.setattr(backend, "_client", _DeadClient())
        monkeypatch.setattr(devd, "available", lambda *a, **k: None)
        items = _items(4, tag=b"demote2")
        items[2] = (items[2][0], items[2][1], b"\x77" * 64)
        assert v.verify_batch(items) == [True, True, False, True]
        assert v._kernel == "devd", v._kernel  # no direct-kernel steal
        assert v._tpu_ok
        br = gateway.devd_breaker()
        assert br.state == br.OPEN
        # while dead, probes keep failing and the fallback keeps serving
        time.sleep(0.1)
        assert v.verify_batch(items) == [True, True, False, True]
        assert br.state == br.OPEN
        assert br.stats()["breaker_probe_failures"] >= 1

        # daemon comes back: probe succeeds, breaker closes, devd routes
        monkeypatch.setattr(devd, "available", real_available)
        backend._client = None
        devd.bust_avail_cache()
        deadline = time.time() + 5.0
        while br.state != br.CLOSED and time.time() < deadline:
            time.sleep(0.05)
            assert v.verify_batch(items) == [True, True, False, True]
        assert br.state == br.CLOSED
        before = v.stats()["tpu_sigs"]
        assert v.verify_batch(items) == [True, True, False, True]
        assert v.stats()["tpu_sigs"] == before + 4
        st = v.stats()
        assert st["breaker_opens"] >= 1 and st["breaker_closes"] >= 1
        assert st["breaker_fallback_s"] > 0
    finally:
        gateway.reset_devd_breaker()


def test_fast_sync_rides_the_daemon(daemon, monkeypatch):
    """End to end, the production topology in miniature: a fast-syncing
    node's commit-signature batches — including concurrent speculative
    dispatches — route over IPC to the device daemon; the synced chain is
    byte-identical and the node process did no kernel work itself."""
    sock, client = daemon
    monkeypatch.setenv("TENDERMINT_DEVD_SOCK", sock)
    monkeypatch.delenv("TENDERMINT_TPU_KERNEL", raising=False)
    devd.bust_avail_cache()
    from tendermint_tpu.blockchain.reactor import BlockchainReactor
    from tendermint_tpu.consensus.reactor import ConsensusReactor
    from tendermint_tpu.ops import gateway
    from tendermint_tpu.p2p import Switch, connect2_switches
    from tendermint_tpu.p2p.node_info import NodeInfo, default_version
    from tests.test_reactors import (
        TEST_CHAIN_ID,
        make_genesis,
        make_node,
        stop_net,
        wait_until,
    )

    verifier = gateway.Verifier(min_tpu_batch=1)
    assert verifier._kernel == "devd"
    daemon_sigs_before = client.stats().get("tpu_sigs", 0) + client.stats().get(
        "cpu_sigs", 0
    )

    doc, pvs = make_genesis(1)
    node_a = make_node(doc, pvs[0])
    node_b = make_node(doc, None)

    def init(i, sw):
        node = (node_a, node_b)[i]
        fast_sync = i == 1
        con_r = ConsensusReactor(node.cs, fast_sync=fast_sync)
        con_r.set_event_switch(node.evsw)
        sw.add_reactor("CONSENSUS", con_r)
        sw.add_reactor("BLOCKCHAIN", BlockchainReactor(
            node.state.copy(),
            node.cs.proxy_app_conn,
            node.store,
            fast_sync=fast_sync,
            batch_verifier=verifier.commit_batch_verifier() if fast_sync else None,
            async_batch_verifier=verifier.verify_batch_async if fast_sync else None,
            status_update_interval=0.5,
        ))
        sw.set_node_info(NodeInfo(
            pub_key=sw.node_priv_key.pub_key(),
            moniker=f"devd-node{i}",
            network=TEST_CHAIN_ID,
            version=default_version("test"),
        ))
        return sw

    switches = [init(i, Switch()) for i in range(2)]
    for sw in switches:
        sw.start()
    try:
        assert wait_until(lambda: node_a.store.height() >= 3, timeout=120)
        node_a.cs.stop()
        target = node_a.store.height()
        connect2_switches(switches, 0, 1)
        assert wait_until(
            lambda: node_b.store.height() >= target, timeout=120
        ), f"B at {node_b.store.height()}, A at {target}"
        for h in range(1, target + 1):
            assert node_b.store.load_block(h).hash() == node_a.store.load_block(h).hash()
        # the signature work landed in the DAEMON, and the node-side
        # verifier recorded those batches as accelerated (devd)
        vstats = verifier.stats()
        assert vstats["tpu_sigs"] > 0 and vstats["tpu_batches"] > 0, vstats
        daemon_sigs_after = client.stats().get("tpu_sigs", 0) + client.stats().get(
            "cpu_sigs", 0
        )
        assert daemon_sigs_after - daemon_sigs_before >= vstats["tpu_sigs"]
    finally:
        stop_net([node_a, node_b], switches)


def test_second_daemon_refuses_live_socket(daemon):
    sock, _ = daemon
    env = {
        **os.environ,
        "TENDERMINT_DEVD_SOCK": sock,
        "TENDERMINT_DEVD_ACCEPT_CPU": "1",
        "TENDERMINT_DEVD_EXIT_ON_TERM": "1",
    }
    proc = subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.devd"],
        env=env, cwd=REPO, capture_output=True, timeout=60,
    )
    assert proc.returncode != 0
    assert b"already serving" in proc.stderr


def test_available_requires_held_device(daemon, monkeypatch, tmp_path):
    sock, _ = daemon
    monkeypatch.setenv("TENDERMINT_DEVD_SOCK", sock)
    devd.bust_avail_cache()
    rep = devd.available()
    assert rep is not None and rep["held"]
    # no socket -> unavailable (and the gateway default falls back)
    monkeypatch.setenv("TENDERMINT_DEVD_SOCK", str(tmp_path / "absent.sock"))
    devd.bust_avail_cache()
    assert devd.available() is None


def test_resolve_platform_waits_out_claiming_daemon(monkeypatch, tmp_path):
    """A devd socket whose daemon is mid-claim/warm means the chip is
    (about to be) owned: resolve_platform must WAIT for it to serve —
    never launch a contending probe, never latch the CPU path minutes
    before the daemon comes up (VERDICT r4 #2's anti-goal)."""
    import pickle
    import socket as socketlib
    import struct
    import threading

    from tendermint_tpu.ops import gateway

    path = str(tmp_path / "fake-devd.sock")
    state = {"pings": 0}

    def handle(c):
        try:
            while True:
                (n,) = struct.unpack(">I", c.recv(4))
                pickle.loads(c.recv(n))
                state["pings"] += 1
                if state["pings"] < 3:
                    rep = {"ok": True, "held": False, "status": "warming",
                           "platform": None}
                else:
                    rep = {"ok": True, "held": True, "status": "serving",
                           "platform": "tpu"}
                payload = pickle.dumps(rep)
                c.sendall(struct.pack(">I", len(payload)) + payload)
        except Exception:  # noqa: BLE001 — client closed
            pass

    def serve():
        srv = socketlib.socket(socketlib.AF_UNIX, socketlib.SOCK_STREAM)
        srv.bind(path)
        srv.listen(8)
        while True:
            c, _ = srv.accept()
            threading.Thread(target=handle, args=(c,), daemon=True).start()

    threading.Thread(target=serve, daemon=True).start()
    time.sleep(0.2)
    monkeypatch.setenv("TENDERMINT_DEVD_SOCK", path)
    monkeypatch.setenv("TENDERMINT_DEVD_RESOLVE_WAIT_S", "30")
    monkeypatch.delenv("TENDERMINT_TPU_PLATFORM", raising=False)
    monkeypatch.setitem(gateway._platform_cache, "v", None)
    gateway._platform_cache.pop("v")
    devd.bust_avail_cache()
    assert gateway.resolve_platform() == "tpu"
    assert state["pings"] >= 3  # it actually polled through "warming"
    gateway._platform_cache.pop("v", None)
