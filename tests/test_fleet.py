"""Fleet observability plane unit suite (round 15): per-peer p2p
instrumentation (p2p/telemetry.py wired through MConnection and the
gossip reactor), trace gossip-arrival marks, the ops/fleet cross-node
timeline math, and the node/health verdict — everything chip-free and
harness-local (the live-node surfaces are covered in tests/test_node_rpc.py,
the scrape-only chaos scenario in tests/test_netchaos.py)."""

from __future__ import annotations

import io
import time

import pytest

from tendermint_tpu.libs.telemetry import Registry
from tendermint_tpu.p2p.conn import ChannelDescriptor, MConnConfig, MConnection
from tendermint_tpu.p2p.stream import pipe_pair
from tendermint_tpu.p2p.telemetry import PeerConnMetrics, peer_metrics


def wait_until(cond, timeout=10.0, tick=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(tick)
    return cond()


# -- p2p/telemetry through a real MConnection ----------------------------------


def _labeled_value(counter, **labels):
    return counter.labels(**labels).value


def test_mconn_per_peer_channel_accounting():
    """Messages over a real mconn pair land in the labeled send/recv
    families of the registry each side was armed with — per channel,
    bytes and whole messages both."""
    reg_a, reg_b = Registry(), Registry()
    descs = [ChannelDescriptor(id=0x01, priority=1, send_queue_capacity=4)]
    a, b = pipe_pair()
    recv_b, err = [], []
    ma = MConnection(a, descs, lambda ch, m: None, err.append, MConnConfig())
    mb = MConnection(b, descs, lambda ch, m: recv_b.append((ch, m)),
                     err.append, MConnConfig())
    ma.set_peer_label("peerB", reg_a)
    mb.set_peer_label("peerA", reg_b)
    ma.start()
    mb.start()
    try:
        msg = b"x" * 3000  # 3 packets
        assert ma.send(0x01, msg)
        assert wait_until(lambda: recv_b and recv_b[0] == (0x01, msg))
        fams_a, fams_b = peer_metrics(reg_a), peer_metrics(reg_b)
        lbl = {"peer": "peerB", "channel": "0x1"}
        assert wait_until(
            lambda: _labeled_value(fams_a["send_msgs"], **lbl) == 1
        )
        assert _labeled_value(fams_a["send_bytes"], **lbl) >= len(msg)
        lbl_b = {"peer": "peerA", "channel": "0x1"}
        assert _labeled_value(fams_b["recv_msgs"], **lbl_b) == 1
        assert _labeled_value(fams_b["recv_bytes"], **lbl_b) >= len(msg)
        # queue gauges sampled at enqueue
        assert fams_a["send_queue_high_water"].labels(**lbl).value >= 1
        # registries are independent: a's families never saw b's side
        assert _labeled_value(fams_a["recv_msgs"], **lbl) == 0
        assert not err
    finally:
        ma.stop()
        mb.stop()


def test_mconn_full_queue_send_failures_counted():
    """try_send against a full channel queue is counted on the per-peer
    send-failure series — the burst-load moment the PR-13 wedge hid in."""
    reg = Registry()
    descs = [ChannelDescriptor(id=0x01, priority=1, send_queue_capacity=1)]
    a, _b = pipe_pair()
    mconn = MConnection(a, descs, lambda ch, m: None, lambda e: None,
                        MConnConfig())
    mconn.set_peer_label("victim", reg)
    # not started: nothing drains the queue, so the second try_send hits
    # a full queue deterministically — but try_send requires running
    mconn._started = True
    assert mconn.try_send(0x01, b"first")
    assert not mconn.try_send(0x01, b"second")
    assert not mconn.try_send(0x01, b"third")
    fams = peer_metrics(reg)
    child = fams["send_failures"].labels(peer="victim", channel="0x1")
    assert child.value == 2
    mconn._started = False


def test_peer_conn_metrics_ping_rtt():
    pm = PeerConnMetrics("p1", [0x01], Registry())
    pm.ping_sent()
    time.sleep(0.01)
    pm.pong_received()
    assert pm._ping_rtt.count == 1
    assert pm._ping_rtt.sum >= 0.009
    pm.pong_received()  # unsolicited pong: no double observation
    assert pm._ping_rtt.count == 1


# -- trace arrival marks -------------------------------------------------------


def test_trace_recorder_arrival_marks_first_wins_and_feed_hists():
    from tendermint_tpu.consensus.trace import TraceRecorder

    reg = Registry()
    rec = TraceRecorder(device_probe=None, ring=4)
    rec.metrics_registry = reg
    rec.begin(7, now=100.0)
    rec._started_wall = 1000.0  # pin the wall clock for the math below
    rec.mark_arrival("first_block_part", at=1000.2)
    rec.mark_arrival("first_block_part", at=1000.9)  # duplicate: first wins
    rec.mark_arrival("prevote_quorum", at=1000.5)
    rec.mark_arrival("precommit_quorum", at=1000.8)
    rec.mark_arrival("commit", at=1000.9)
    tr = rec.finish(7, wall_s=1.0, now=101.0)
    assert tr.arrivals["first_block_part"] == 1000.2
    assert tr.started_at == 1000.0
    assert tr.to_json()["arrivals"]["prevote_quorum"] == 1000.5
    # the scrape-side distributions got exactly one observation each
    from tendermint_tpu.consensus.trace import arrival_hists

    hists = arrival_hists(reg)
    assert hists["quorum"].labels(phase="prevote").count == 1
    assert hists["quorum"].labels(phase="prevote").sum == pytest.approx(0.5)
    assert hists["quorum"].labels(phase="precommit").sum == pytest.approx(0.8)
    assert hists["first_part"].count == 1
    # the next height starts with a clean slate
    rec.begin(8, now=101.0)
    tr2 = rec.finish(8, wall_s=0.5, now=101.5)
    assert tr2.arrivals == {}
    assert hists["quorum"].labels(phase="prevote").count == 1


# -- ops/fleet: scrape parsing + timeline math ---------------------------------


def test_parse_prometheus_and_metric_value():
    from tendermint_tpu.ops.fleet import metric_value, parse_prometheus

    text = "\n".join([
        "# HELP consensus_height position",
        "# TYPE consensus_height gauge",
        "consensus_height 42",
        'p2p_peer_vote_gossip_sends_total{peer="aa"} 3',
        'p2p_peer_vote_gossip_sends_total{peer="bb"} 4',
        'consensus_quorum_seconds_bucket{phase="precommit",le="+Inf"} 9',
        'consensus_quorum_seconds_sum{phase="precommit"} 1.25',
        "weird_line_that_should_be_ignored{",
    ])
    m = parse_prometheus(text)
    assert metric_value(m, "consensus_height") == 42
    # several series, no label filter: the sum
    assert metric_value(m, "p2p_peer_vote_gossip_sends_total") == 7
    assert metric_value(m, "p2p_peer_vote_gossip_sends_total",
                        {"peer": "bb"}) == 4
    assert metric_value(m, "consensus_quorum_seconds_sum",
                        {"phase": "precommit"}) == 1.25
    assert metric_value(
        m, "consensus_quorum_seconds_bucket",
        {"phase": "precommit", "le": "+Inf"},
    ) == 9
    assert metric_value(m, "missing", default=-1) == -1


def _trace(height, start, first_part=None, prevote=None, precommit=None,
           commit=None):
    arr = {}
    if first_part is not None:
        arr["first_block_part"] = start + first_part
    if prevote is not None:
        arr["prevote_quorum"] = start + prevote
    if precommit is not None:
        arr["precommit_quorum"] = start + precommit
    if commit is not None:
        arr["commit"] = start + commit
    return {
        "height": height, "started_at": start, "arrivals": arr,
        "wall_s": (commit or 1.0), "rounds": 1,
        "completed_at": start + (commit or 1.0),
    }


def test_build_timeline_cross_node_math():
    """Three nodes' traces join into per-height rows: propagation lag is
    the first-part spread, quorum time the per-node max, commit skew the
    finalize spread — and absent marks degrade to None, not crashes."""
    from tendermint_tpu.ops.fleet import build_timeline

    per_node = {
        "n0": [_trace(10, 1000.0, first_part=0.00, prevote=0.10,
                      precommit=0.20, commit=0.25),
               _trace(11, 1001.0, first_part=0.00, precommit=0.30,
                      commit=0.40)],
        "n1": [_trace(10, 1000.0, first_part=0.05, prevote=0.12,
                      precommit=0.22, commit=0.30)],
        "n2": [_trace(10, 1000.0, first_part=0.15, prevote=0.18,
                      precommit=0.28, commit=0.45),
               # catchup height: no quorum marks at all
               _trace(11, 1001.0, commit=0.60)],
    }
    rows = build_timeline(per_node, last=10)
    assert [r["height"] for r in rows] == [11, 10]
    r10 = rows[1]
    assert r10["nodes_reporting"] == 3
    assert r10["propagation_lag_s"] == pytest.approx(0.15)
    assert r10["prevote_quorum_s_max"] == pytest.approx(0.18)
    assert r10["precommit_quorum_s_max"] == pytest.approx(0.28)
    assert r10["precommit_quorum_s_min"] == pytest.approx(0.20)
    assert r10["commit_skew_s"] == pytest.approx(0.20)
    r11 = rows[0]
    assert r11["nodes_reporting"] == 2
    assert r11["propagation_lag_s"] is None  # one first-part mark only
    assert r11["prevote_quorum_s_max"] is None
    assert r11["commit_skew_s"] == pytest.approx(0.20)
    # per-node detail survives for the renderer
    assert r10["per_node"]["n2"]["precommit_quorum_s"] == pytest.approx(0.28)

    # the `last` window keeps the newest heights
    assert [r["height"] for r in build_timeline(per_node, last=1)] == [11]


def test_fleet_render_handles_partial_fleet():
    from tendermint_tpu.ops.fleet import build_timeline, render

    snapshot = {
        "up:46657": {
            "metrics": {"consensus_height": [({}, 5.0)],
                        "p2p_peers_outbound": [({}, 2.0)],
                        "p2p_peers_inbound": [({}, 1.0)]},
            "health": {"status": "ok"},
            "traces": [_trace(5, 1000.0, first_part=0.0, commit=0.2)],
        },
        "down:46657": {"error": "URLError: refused"},
    }
    rows = build_timeline(
        {u: e.get("traces", []) for u, e in snapshot.items()}
    )
    buf = io.StringIO()
    render(snapshot, rows, out=buf)
    out = buf.getvalue()
    assert "UNREACHABLE" in out
    assert "health ok" in out
    assert "5" in out


# -- node/health verdict -------------------------------------------------------


class _FakeWal:
    def __init__(self, pending=0, age=0.0):
        self._pending, self._age = pending, age

    def stats(self):
        return {"pending": self._pending, "sync_age_s": self._age}


class _FakeCS:
    def __init__(self, age=0.5, poisoned=False, wal=None):
        self._age, self._poisoned = age, poisoned
        self.wal = wal if wal is not None else _FakeWal()

    def height_age_s(self):
        return self._age

    def pipeline_poisoned(self):
        return self._poisoned

    def get_round_state(self):
        class _RS:
            height = 9

        return _RS()


class _FakeSwitch:
    def __init__(self, peers=3):
        self._peers = peers

    def num_peers(self):
        return self._peers, 0, 0


class _FakeMempool:
    def __init__(self, n=1):
        self._n = n

    def size(self):
        return self._n


class _FakeBC:
    fast_sync = False


class _FakeNode:
    def __init__(self, **kw):
        self.consensus_state = kw.get("cs", _FakeCS())
        self.sw = kw.get("sw", _FakeSwitch())
        self.mempool = kw.get("mempool", _FakeMempool())
        self.blockchain_reactor = kw.get("bc", _FakeBC())


def test_health_verdict_ok_degraded_failing(monkeypatch):
    from tendermint_tpu.node.health import health_gauges, health_report

    report = health_report(_FakeNode())
    assert report["status"] == "ok" and report["code"] == 0

    # stalled height -> degraded, then failing at the bigger budget
    monkeypatch.setenv("TENDERMINT_HEALTH_HEIGHT_AGE_DEGRADED_S", "10")
    monkeypatch.setenv("TENDERMINT_HEALTH_HEIGHT_AGE_FAILING_S", "100")
    assert health_report(_FakeNode(cs=_FakeCS(age=11)))["status"] == "degraded"
    assert health_report(_FakeNode(cs=_FakeCS(age=101)))["status"] == "failing"
    # ... unless fast sync is active (catching up, not stalled)
    class _Syncing(_FakeBC):
        fast_sync = True

    assert health_report(
        _FakeNode(cs=_FakeCS(age=101), bc=_Syncing())
    )["checks"]["height_age"]["status"] == "ok"

    # a poisoned pipeline is FAILING no matter what else says
    report = health_report(_FakeNode(cs=_FakeCS(poisoned=True)))
    assert report["status"] == "failing"
    assert report["checks"]["pipeline"]["status"] == "failing"

    # the peers gate only engages when the knob says so
    assert health_report(_FakeNode(sw=_FakeSwitch(0)))["status"] == "ok"
    monkeypatch.setenv("TENDERMINT_HEALTH_MIN_PEERS", "2")
    report = health_report(_FakeNode(sw=_FakeSwitch(0)))
    assert report["status"] == "degraded"
    assert report["checks"]["peers"]["status"] == "degraded"

    # stuck WAL flusher: pending records with a growing sync age
    monkeypatch.setenv("TENDERMINT_HEALTH_WAL_SYNC_AGE_S", "5")
    report = health_report(
        _FakeNode(cs=_FakeCS(wal=_FakeWal(pending=3, age=9.0)))
    )
    assert report["checks"]["wal"]["status"] == "degraded"

    # mempool backlog
    monkeypatch.setenv("TENDERMINT_HEALTH_MEMPOOL_DEGRADED", "10")
    report = health_report(_FakeNode(mempool=_FakeMempool(50)))
    assert report["checks"]["mempool"]["status"] == "degraded"

    # the flat gauge view mirrors the verdict
    monkeypatch.delenv("TENDERMINT_HEALTH_MIN_PEERS")
    monkeypatch.delenv("TENDERMINT_HEALTH_MEMPOOL_DEGRADED")
    g = health_gauges(_FakeNode(cs=_FakeCS(age=11)))
    assert g["status"] == 1 and g["checks_degraded"] == 1
    assert g["checks_failing"] == 0
