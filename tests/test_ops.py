"""TPU ops tests (run on CPU backend; conftest forces an 8-device CPU mesh).

Parity contract: every kernel must reproduce the CPU implementation
byte-for-byte / verdict-for-verdict. These tests are the enforcement."""

import hashlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tendermint_tpu.crypto import ed25519 as ref
from tendermint_tpu.crypto.hashing import ripemd160
from tendermint_tpu.merkle.simple import (
    leaf_hash,
    simple_hash_from_byteslices,
    simple_proofs_from_hashes,
)
from tendermint_tpu.ops import ed25519 as ops_ed
from tendermint_tpu.ops import gateway
from tendermint_tpu.ops.hashing import ripemd160_batch, sha256_batch
from tendermint_tpu.ops.merkle import (
    leaf_hashes,
    part_leaf_hashes,
    tree_hash_from_leaf_digests,
)


class TestHashKernels:
    def test_ripemd160_parity(self):
        msgs = [b"", b"a", b"abc", b"x" * 200, bytes(range(256)) * 3, b"q" * 64]
        assert ripemd160_batch(msgs) == [ripemd160(m) for m in msgs]

    def test_sha256_parity(self):
        msgs = [b"", b"abc", b"z" * 1000]
        assert sha256_batch(msgs) == [hashlib.sha256(m).digest() for m in msgs]

    def test_empty_batch(self):
        assert ripemd160_batch([]) == []
        assert sha256_batch([]) == []


class TestMerkleKernel:
    @pytest.mark.parametrize("n", [1, 2, 3, 5, 7, 16, 33, 100])
    def test_tree_and_proofs_parity(self, n):
        digests = [leaf_hash(b"item-%d" % i) for i in range(n)]
        root_cpu, proofs_cpu = simple_proofs_from_hashes(digests)
        root_tpu, aunts_tpu = tree_hash_from_leaf_digests(digests)
        assert root_tpu == root_cpu
        for i in range(n):
            assert aunts_tpu[i] == proofs_cpu[i].aunts

    def test_part_leaves(self):
        chunks = [bytes([i]) * (100 + i) for i in range(20)]
        assert part_leaf_hashes(chunks) == [ripemd160(c) for c in chunks]

    def test_leaf_hashes(self):
        items = [b"tx-%d" % i for i in range(9)]
        assert leaf_hashes(items) == [leaf_hash(i) for i in items]


@pytest.mark.slow
class TestFieldArithmetic:
    """int32 radix-2^15 reference-kernel math (dormant in production —
    the gateway runs ops/ed25519_f32; see tests/test_ops_f32.py). Marked
    slow: compiles the big ladder graphs."""

    def test_mul_inv_canon(self):
        import random

        random.seed(7)
        vals = [random.randrange(ref.P) for _ in range(8)]
        bv = [random.randrange(ref.P) for _ in range(8)]
        aj = jnp.asarray(ops_ed.int_to_limbs_np(vals))
        bj = jnp.asarray(ops_ed.int_to_limbs_np(bv))
        mres = np.asarray(jax.jit(lambda a, b: ops_ed.fcanon(ops_ed.fmul(a, b)))(aj, bj))
        for i in range(8):
            assert ops_ed.limbs_to_int(mres[:, i]) == (vals[i] * bv[i]) % ref.P

    def test_edge_values(self):
        edge = [0, 1, ref.P - 1, ref.P - 19, 2**255 - 20, (1 << 255) - 1]
        aj = jnp.asarray(ops_ed.int_to_limbs_np(edge))
        out = np.asarray(jax.jit(lambda a: ops_ed.fcanon(ops_ed.fmul(a, a)))(aj))
        for i, v in enumerate(edge):
            assert ops_ed.limbs_to_int(out[:, i]) == (v * v) % ref.P


def _mk_items(n, corrupt=()):
    items = []
    for i in range(n):
        sk = hashlib.sha256(b"t%d" % i).digest()
        pub = ref.public_key(sk)
        msg = b"msg-%d" % i
        sig = ref.sign(sk, msg)
        items.append((pub, msg, sig))
    for i, kind in corrupt:
        pub, msg, sig = items[i]
        if kind == "sig":
            b = bytearray(sig)
            b[0] ^= 1
            items[i] = (pub, msg, bytes(b))
        elif kind == "msg":
            items[i] = (pub, b"evil", sig)
        elif kind == "pub":
            b = bytearray(pub)
            b[0] ^= 1
            items[i] = (bytes(b), msg, sig)
        elif kind == "high_s":
            s = int.from_bytes(sig[32:], "little") + ref.L
            items[i] = (pub, msg, sig[:32] + s.to_bytes(32, "little"))
    return items


@pytest.mark.slow
class TestVerifyKernel:
    """Compiles the full jnp verify program once (slow on CPU backend) and
    reuses it; the pallas variant shares all math helpers. Slow: the
    int32 kernel is the dormant math reference — the production f32
    kernel has its own always-on suite in tests/test_ops_f32.py."""

    def test_verify_and_reject(self):
        items = _mk_items(
            8, corrupt=[(1, "sig"), (2, "msg"), (3, "high_s"), (4, "pub")]
        )
        ok = ops_ed.verify_batch(items)
        expected = [ref.verify(p, m, s) for p, m, s in items]
        assert list(ok) == expected
        assert expected == [True, False, False, False, False, True, True, True]

    def test_rfc8032_vectors(self):
        from tests.test_crypto import RFC8032_VECTORS

        items = [
            (bytes.fromhex(pk), bytes.fromhex(msg), bytes.fromhex(sig))
            for _, pk, msg, sig in RFC8032_VECTORS
        ]
        assert ops_ed.verify_batch(items).all()

    def test_decompress_batch(self):
        pubs = [ref.public_key(hashlib.sha256(b"d%d" % i).digest()) for i in range(6)]
        x, y, valid = ops_ed.decompress_batch(pubs + [b"\xff" * 32])
        assert valid[:6].all() and not valid[6]
        for i, p in enumerate(pubs):
            pt = ref.point_decompress(p)
            assert ops_ed.limbs_to_int(x[:, i]) == pt[0]
            assert ops_ed.limbs_to_int(y[:, i]) == pt[1]


@pytest.mark.slow
class TestPallasKernelMath:
    """The Pallas kernel's row-based limb arithmetic is plain jnp outside
    the pallas_call plumbing — test it directly against the reference so
    the production-TPU math has CPU coverage. The pallas_call plumbing
    itself (block specs, lane reshape) runs under the real-TPU bench and
    the TPU-gated test below."""

    def _to_rows(self, vals):
        import jax.numpy as jnp

        from tendermint_tpu.ops import ed25519_pallas as pk

        arr = ops_ed.int_to_limbs_np(vals)  # (17, B)
        return [jnp.asarray(arr[k]) for k in range(pk.NLIMB)]

    def _to_int(self, rows, i):
        import numpy as np

        stacked = np.stack([np.asarray(r) for r in rows])
        return ops_ed.limbs_to_int(stacked[:, i])

    def test_fmul_fsq_rows(self):
        import random

        from tendermint_tpu.ops import ed25519_pallas as pk

        random.seed(11)
        vals = [random.randrange(ref.P) for _ in range(8)]
        bv = [random.randrange(ref.P) for _ in range(8)]
        a = self._to_rows(vals)
        b = self._to_rows(bv)
        m = pk._fcanon_rows(pk._fmul_rows(a, b))
        s = pk._fcanon_rows(pk._fsq_rows(a))
        for i in range(8):
            assert self._to_int(m, i) == (vals[i] * bv[i]) % ref.P
            assert self._to_int(s, i) == (vals[i] * vals[i]) % ref.P

    def test_point_ladder_rows(self):
        """One double+add in row form matches the reference group law."""
        import jax.numpy as jnp

        from tendermint_tpu.ops import ed25519_pallas as pk

        B_pt = ref.B
        dbl = ref.point_double(B_pt)
        tripled = ref.point_add(dbl, B_pt)

        def const_rows(v):
            arr = ops_ed.int_to_limbs_np([v] * 4)
            return [jnp.asarray(arr[k]) for k in range(pk.NLIMB)]

        zeros = const_rows(0)
        one = const_rows(1)
        bx, by = const_rows(B_pt[0]), const_rows(B_pt[1])
        bt = const_rows((B_pt[0] * B_pt[1]) % ref.P)
        d2 = const_rows((2 * ref.D) % ref.P)
        p = (bx, by, one, bt)
        d = pk._point_double_rows(p)
        t = pk._point_add_rows(d, p, d2)
        # compare affine
        zinv = pk._finv_rows(t[2])
        x = pk._fcanon_rows(pk._fmul_rows(t[0], zinv))
        y = pk._fcanon_rows(pk._fmul_rows(t[1], zinv))
        zexp = pow(tripled[2], ref.P - 2, ref.P)
        assert self._to_int(x, 0) == tripled[0] * zexp % ref.P
        assert self._to_int(y, 0) == tripled[1] * zexp % ref.P

    @pytest.mark.skipif(
        jax.devices()[0].platform != "tpu", reason="full pallas kernel needs TPU"
    )
    def test_pallas_verify_on_tpu(self):
        from tendermint_tpu.ops import ed25519_pallas as pk

        items = _mk_items(8, corrupt=[(2, "sig")])
        ok = pk.verify_batch(items)
        assert list(ok) == [True, True, False] + [True] * 5


class TestGateway:
    def test_tx_root_hook_parity(self):
        """The node-assembly hook (types/tx.set_batch_tx_root) must route
        Txs.Hash through the batched kernel with a byte-identical root
        (ref types/tx.go:33-46) and move the hasher stats."""
        from tendermint_tpu.merkle.simple import simple_hash_from_hashes
        from tendermint_tpu.types import tx as tx_types

        txs = [bytes([i]) * (i + 1) for i in range(20)]
        # explicit CPU reference — independent of any hook a previously
        # constructed Node may have left installed in this process
        cpu_root = simple_hash_from_hashes([tx_types.tx_hash(t) for t in txs])
        hasher = gateway.Hasher(min_tpu_batch=1, use_tpu=True)
        prev = tx_types._batch_tx_root
        tx_types.set_batch_tx_root(hasher.tx_merkle_root)
        try:
            tpu_root = tx_types.txs_hash(txs)
        finally:
            tx_types.set_batch_tx_root(prev)
        assert tpu_root == cpu_root
        st = hasher.stats()
        assert st["tpu_tx_roots"] == 1 and st["tpu_leaves"] == 20

    def test_cpu_small_batch(self):
        v = gateway.Verifier(min_tpu_batch=1000)
        items = _mk_items(4, corrupt=[(2, "sig")])
        assert v.verify_batch(items) == [True, True, False, True]
        assert v.stats()["cpu_sigs"] == 4

    def test_tpu_path_parity(self):
        v = gateway.Verifier(min_tpu_batch=1)
        items = _mk_items(8, corrupt=[(0, "sig")])
        assert v.verify_batch(items) == [False] + [True] * 7

    def test_verify_one(self):
        v = gateway.Verifier()
        (pub, msg, sig) = _mk_items(1)[0]
        assert v.verify_one(pub, msg, sig)
        assert not v.verify_one(pub, b"other", sig)

    def test_hasher_transport_keyed_policy(self, monkeypatch):
        """Hasher default offloads iff the measured device round trip is
        local-chip scale (VERDICT r4 #3: the r4 CPU-default closure was
        tunnel-biased; the policy now keys on transport)."""
        monkeypatch.delenv("TENDERMINT_TPU_HASHES", raising=False)
        monkeypatch.delenv("TENDERMINT_TPU_DISABLE", raising=False)
        monkeypatch.setitem(gateway._platform_cache, "rtt", 2.0)
        assert gateway.Hasher()._tpu_ok  # local-chip rtt -> offload
        monkeypatch.setitem(gateway._platform_cache, "rtt", 90.0)
        assert not gateway.Hasher()._tpu_ok  # tunnel rtt -> CPU
        monkeypatch.setitem(gateway._platform_cache, "rtt", None)
        assert not gateway.Hasher()._tpu_ok  # no device -> CPU
        monkeypatch.setenv("TENDERMINT_TPU_HASHES", "1")
        assert gateway.Hasher()._tpu_ok  # forced on beats transport
        monkeypatch.setenv("TENDERMINT_TPU_HASHES", "0")
        monkeypatch.setitem(gateway._platform_cache, "rtt", 2.0)
        assert not gateway.Hasher()._tpu_ok  # forced off beats transport

    def test_hasher_fallback_parity(self):
        # use_tpu=True explicitly: the Hasher default is transport-keyed
        # (CPU on this boxed test env), which would make this
        # kernel-parity check compare CPU to CPU
        h_tpu = gateway.Hasher(min_tpu_batch=1, use_tpu=True)
        h_cpu = gateway.Hasher(min_tpu_batch=10**9)
        chunks = [b"c%d" % i * 50 for i in range(8)]
        assert h_tpu.part_leaf_hashes(chunks) == h_cpu.part_leaf_hashes(chunks)
        txs = [b"tx%d" % i for i in range(8)]
        assert h_tpu.tx_merkle_root(txs) == h_cpu.tx_merkle_root(txs)
        assert h_cpu.tx_merkle_root(txs) == simple_hash_from_byteslices(txs)


class TestShardedVerifier:
    def test_mesh_sharded_batch(self):
        """Multi-chip path: batch axis sharded over the 8-device CPU mesh."""
        from jax.sharding import Mesh

        devs = np.array(jax.devices())
        assert devs.size == 8, "conftest should force 8 cpu devices"
        mesh = Mesh(devs, ("batch",))
        v = gateway.ShardedVerifier(mesh, min_tpu_batch=1)
        items = _mk_items(16, corrupt=[(5, "sig")])
        out = v.verify_batch(items)
        assert out == [True] * 5 + [False] + [True] * 10
        assert v.stats()["tpu_sigs"] == 16

    def test_mesh_sharded_f32p_parity(self, monkeypatch):
        """The f32p ladder sharded 8 ways (ed25519_f32p.make_sharded_verify):
        on this CPU mesh the per-shard body is the plain-XLA _ladder — the
        exact math the pallas kernel runs per chip on a TPU mesh — so this
        is a real parity check of the sharded f32p path (VERDICT r3 #3)."""
        from jax.sharding import Mesh

        monkeypatch.setenv("TENDERMINT_TPU_KERNEL", "f32p")
        devs = np.array(jax.devices())
        mesh = Mesh(devs, ("batch",))
        v = gateway.ShardedVerifier(mesh, min_tpu_batch=1)
        assert v._kernel == "f32p"
        items = _mk_items(16, corrupt=[(3, "sig"), (11, "msg")])
        out = v.verify_batch(items)
        assert out == [i not in (3, 11) for i in range(16)]
        assert v.stats()["tpu_sigs"] == 16
        assert v._kernel == "f32p"  # did not silently demote to f32

    def test_sharded_async_uses_the_sharded_path(self):
        """verify_batch_async on a ShardedVerifier must ride the sharded
        dispatch (regression: the inherited base implementation silently
        ran the UNSHARDED kernel)."""
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()), ("batch",))
        v = gateway.ShardedVerifier(mesh, min_tpu_batch=1)
        items = _mk_items(16, corrupt=[(9, "msg")])
        resolve = v.verify_batch_async(items)
        assert resolve() == [i != 9 for i in range(16)]
        assert v.stats()["tpu_batches"] == 1
        assert v.stats()["tpu_sigs"] == 16

    def test_sharded_rejects_bakeoff_kernels(self, monkeypatch):
        from jax.sharding import Mesh

        monkeypatch.setenv("TENDERMINT_TPU_KERNEL", "int32")
        mesh = Mesh(np.array(jax.devices()), ("batch",))
        with pytest.raises(ValueError, match="shards the f32/f32p"):
            gateway.ShardedVerifier(mesh)

    def test_sharded_fast_sync_commit(self):
        """Fast sync's VerifyCommit driven end-to-end through the sharded
        verifier: real ValidatorSet commits (the quorum math of
        types/validator_set.go:220-264) grouped exactly as the blockchain
        reactor groups them (validator_set.verify_commits_async — the
        call blockchain/reactor._dispatch_speculative makes, replacing
        the reference's per-block loop at blockchain/reactor.go:235-236),
        with the signature batch sharded over the 8-device CPU mesh.
        Asserts verdicts AND the measured per-device shard layout."""
        from jax.sharding import Mesh

        from tendermint_tpu.types.validator_set import CommitError
        from tests.test_types import BLOCK_ID, make_val_set, signed_vote
        from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT
        from tendermint_tpu.types.vote_set import VoteSet

        vs, privs = make_val_set(8, power=1)
        entries = []
        for height in (1, 2, 3):
            voteset = VoteSet(
                "test-chain", height, 0, VOTE_TYPE_PRECOMMIT, vs
            )
            for p in privs:
                voteset.add_vote(
                    signed_vote(p, vs, height, 0, VOTE_TYPE_PRECOMMIT, BLOCK_ID)
                )
            entries.append((BLOCK_ID, height, voteset.make_commit()))
        # tamper height 2's first signature: its finisher (and ONLY its
        # finisher) must raise, as the reactor's bad-block path expects
        from tendermint_tpu.crypto.keys import SignatureEd25519

        bad = entries[1][2]
        bad.precommits[0] = bad.precommits[0].with_signature(
            SignatureEd25519(b"\x07" * 64)
        )

        mesh = Mesh(np.array(jax.devices()), ("batch",))
        v = gateway.ShardedVerifier(mesh, min_tpu_batch=1)
        finishers = vs.verify_commits_async(
            "test-chain", entries, v.verify_batch_async
        )
        assert len(finishers) == 3
        finishers[0]()
        with pytest.raises(CommitError):
            finishers[1]()
        finishers[2]()
        # one grouped dispatch, 24 signatures, sharded over all 8 devices
        assert v.stats()["tpu_batches"] == 1
        assert v.stats()["tpu_sigs"] == 24
        layout = v.last_shard_layout
        assert layout is not None and len(layout) == 8, layout
        assert len({d for d, _ in layout}) == 8, layout
        assert len({sz for _, sz in layout}) == 1, layout
