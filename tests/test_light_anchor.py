"""Persisted light-client trust anchor (round 20, node/light_anchor.py).

A statesync restore walks light-client trust to the restored height but
kept the result only in memory: a wipe-and-restore restart re-anchored
at the operator's configured pin and re-trusted the whole range this
home had already verified. The anchor file closes that window. These
tests cover the round-trip, every strict-load rejection, and the node
wiring (`_make_restorer` resumes from the anchor; an operator pin ABOVE
the anchor still wins)."""

from __future__ import annotations

import json
import os
from types import SimpleNamespace

from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
from tendermint_tpu.libs.db import MemDB
from tendermint_tpu.node.light_anchor import anchor_path, load_anchor, save_anchor
from tendermint_tpu.rpc.light import LightClient
from tendermint_tpu.types.block import Header
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import ValidatorSet

CHAIN = "anchor-test-chain"


def _vset(n=2, tag="a"):
    return ValidatorSet(
        [
            Validator.new(
                gen_priv_key_ed25519(f"{CHAIN}-{tag}-{i}".encode()).pub_key(),
                10,
            )
            for i in range(n)
        ]
    )


def _header(height: int, vset: ValidatorSet, chain=CHAIN) -> Header:
    return Header(
        chain_id=chain,
        height=height,
        time_ns=height * 1000,
        num_txs=0,
        last_block_id=BlockID(),
        last_commit_hash=b"\x02" * 20,
        data_hash=b"\x03" * 20,
        validators_hash=vset.hash(),
        app_hash=b"",
    )


def _lc(height: int, vset: ValidatorSet, header: Header | None):
    lc = LightClient(None, CHAIN, vset, trusted_height=height)
    lc._trusted_header = header
    return lc


def test_round_trip_with_header(tmp_path):
    vset = _vset()
    header = _header(7, vset)
    assert save_anchor(str(tmp_path), _lc(7, vset, header))

    got = load_anchor(str(tmp_path), CHAIN)
    assert got is not None
    height, vs, hdr = got
    assert height == 7
    assert vs.hash() == vset.hash()
    assert hdr is not None and hdr.hash() == header.hash()


def test_round_trip_without_header(tmp_path):
    """A restore that never crossed a validator-set change has no
    trusted header yet — the anchor still carries height + set."""
    vset = _vset()
    assert save_anchor(str(tmp_path), _lc(5, vset, None))
    got = load_anchor(str(tmp_path), CHAIN)
    assert got == (5, got[1], None)
    assert got[1].hash() == vset.hash()


def test_save_refuses_unanchored_state(tmp_path):
    vset = _vset()
    assert not save_anchor("", _lc(5, vset, None))  # no home
    assert not save_anchor(str(tmp_path), None)  # no light client
    assert not save_anchor(str(tmp_path), _lc(0, vset, None))  # nothing walked
    assert not os.path.exists(anchor_path(str(tmp_path)))


def test_load_absent_or_corrupt_is_none(tmp_path):
    root = str(tmp_path)
    assert load_anchor(root, CHAIN) is None  # absent
    os.makedirs(os.path.dirname(anchor_path(root)), exist_ok=True)
    with open(anchor_path(root), "w") as f:
        f.write('{"chain_id": "anchor-test-chain", "height":')  # torn write
    assert load_anchor(root, CHAIN) is None


def test_load_rejects_wrong_chain(tmp_path):
    vset = _vset()
    assert save_anchor(str(tmp_path), _lc(7, vset, _header(7, vset)))
    assert load_anchor(str(tmp_path), "some-other-chain") is None


def _mutate(root, **changes):
    with open(anchor_path(root)) as f:
        doc = json.load(f)
    doc.update(changes)
    with open(anchor_path(root), "w") as f:
        json.dump(doc, f)


def test_load_rejects_inconsistent_fields(tmp_path):
    root = str(tmp_path)
    vset = _vset()
    save_anchor(root, _lc(7, vset, _header(7, vset)))
    base = json.load(open(anchor_path(root)))

    # non-positive / non-int heights
    for bad in (0, -3, True, "7", None):
        _mutate(root, height=bad)
        assert load_anchor(root, CHAIN) is None, bad

    # header height disagrees with the anchor height
    _mutate(root, height=base["height"] + 1, header=base["header"])
    assert load_anchor(root, CHAIN) is None

    # header signed by a DIFFERENT set than the persisted one: the
    # file's parts disagree — corrupt, not trustworthy
    other = _vset(tag="b")
    _mutate(root, height=7, header=_header(7, other).to_json())
    assert load_anchor(root, CHAIN) is None

    # garbage validators shape
    _mutate(root, header=base["header"], validators={"nope": 1})
    assert load_anchor(root, CHAIN) is None


# -- node wiring --------------------------------------------------------------


def _stub_node(root: str):
    from tendermint_tpu.blockchain.store import BlockStore

    return SimpleNamespace(
        config=SimpleNamespace(base=SimpleNamespace(root_dir=root)),
        verifier=SimpleNamespace(commit_batch_verifier=lambda: None),
        block_store=BlockStore(MemDB()),
        hasher=None,
    )


def _restorer_for(root: str, vset: ValidatorSet, trust_height: int):
    from tendermint_tpu.node.node import Node

    genesis_doc = SimpleNamespace(
        chain_id=CHAIN,
        validators=[
            SimpleNamespace(pub_key=v.pub_key, power=v.voting_power)
            for _, v in ((vset.get_by_index(i)) for i in range(vset.size()))
        ],
    )
    sc = SimpleNamespace(trust_height=trust_height, rpc_servers="127.0.0.1:1")
    return Node._make_restorer(
        _stub_node(root), sc, object(), genesis_doc, MemDB()
    )


def test_make_restorer_resumes_from_anchor(tmp_path):
    """The restart half of the story: a home whose prior restore
    persisted an anchor at 42 boots its next light client AT 42 with
    the anchored set and header — not at the configured pin below it."""
    genesis_vset = _vset()
    anchored_vset = _vset(tag="later")
    header = _header(42, anchored_vset)
    assert save_anchor(str(tmp_path), _lc(42, anchored_vset, header))

    restorer = _restorer_for(str(tmp_path), genesis_vset, trust_height=3)
    lc = restorer.light_client
    assert lc.height == 42
    assert lc.validators.hash() == anchored_vset.hash()
    assert lc.trusted_header() is not None
    assert lc.trusted_header().hash() == header.hash()


def test_make_restorer_operator_pin_above_anchor_wins(tmp_path):
    """An operator who pins trust ABOVE the anchor means it: the deeper
    (staler) anchor must not drag trust back down."""
    genesis_vset = _vset()
    anchored_vset = _vset(tag="later")
    assert save_anchor(str(tmp_path), _lc(10, anchored_vset, None))

    restorer = _restorer_for(str(tmp_path), genesis_vset, trust_height=50)
    lc = restorer.light_client
    assert lc.height == 50
    assert lc.validators.hash() == genesis_vset.hash()
    assert lc.trusted_header() is None


def test_make_restorer_without_anchor_uses_configured_trust(tmp_path):
    genesis_vset = _vset()
    restorer = _restorer_for(str(tmp_path), genesis_vset, trust_height=3)
    lc = restorer.light_client
    assert lc.height == 3
    assert lc.validators.hash() == genesis_vset.hash()


def test_statesync_complete_persists_anchor(tmp_path):
    """_on_statesync_complete writes the anchor from the restorer's
    walked light client before handing the tail to fast sync."""
    from tendermint_tpu.node.node import Node

    vset = _vset()
    lc = _lc(13, vset, _header(13, vset))
    calls = []
    stub = SimpleNamespace(
        config=SimpleNamespace(base=SimpleNamespace(root_dir=str(tmp_path))),
        statesync_reactor=SimpleNamespace(
            restorer=SimpleNamespace(light_client=lc)
        ),
        blockchain_reactor=SimpleNamespace(
            start_after_statesync=lambda s: calls.append(s)
        ),
    )
    restored = SimpleNamespace(last_block_height=13)
    Node._on_statesync_complete(stub, restored)
    assert calls == [restored]
    assert stub.state is restored
    got = load_anchor(str(tmp_path), CHAIN)
    assert got is not None and got[0] == 13

    # the fallback path (restore failed -> None) must not touch the disk
    os.remove(anchor_path(str(tmp_path)))
    Node._on_statesync_complete(stub, None)
    assert calls[-1] is None
    assert not os.path.exists(anchor_path(str(tmp_path)))
