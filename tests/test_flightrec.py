"""Black-box flight recorder tests (round 17, node/flightrec.py).

The ISSUE's contracts: ring overflow keeps NEWEST events, the auto-dump
fires exactly once per failing transition (re-arming when the verdict
recovers), dumps are valid JSON with monotonic timestamps and a counter
snapshot, the kill switch makes the step path free, and the consensus
receive routine's crash hook records + dumps before re-raising."""

from __future__ import annotations

import glob
import json
import os

import pytest

from tendermint_tpu.node.flightrec import FlightRecorder


class TestRing:
    def test_overflow_keeps_newest(self):
        rec = FlightRecorder(ring=16)
        for i in range(50):
            rec.record("step", height=i)
        evs = rec.events()
        assert len(evs) == 16
        assert [e["height"] for e in evs] == list(range(34, 50))
        assert rec.recorded == 50

    def test_events_last_slice(self):
        rec = FlightRecorder(ring=64)
        for i in range(10):
            rec.record("step", height=i)
        assert [e["height"] for e in rec.events(last=3)] == [7, 8, 9]

    def test_timestamps_monotonic(self):
        rec = FlightRecorder(ring=64)
        for i in range(100):
            rec.record("step", height=i)
        ts = [e["t"] for e in rec.events()]
        assert ts == sorted(ts)

    def test_kill_switch_costs_nothing_on_the_step_path(self):
        rec = FlightRecorder(ring=64)
        rec.set_enabled(False)
        for i in range(100):
            rec.record("step", height=i)
        rec.note_health("failing")
        rec.note_vote_dup("peer")
        rec.note_height_age(999.0, 1.0)
        rec.note_exception("consensus", RuntimeError("boom"))
        assert rec.recorded == 0
        assert rec.events() == []
        assert rec.dumps == 0, "a disabled recorder must write NOTHING"
        # and env-knob construction honors the same switch
        os.environ["TENDERMINT_FLIGHTREC_DISABLE"] = "1"
        try:
            assert FlightRecorder().enabled is False
        finally:
            del os.environ["TENDERMINT_FLIGHTREC_DISABLE"]


class TestAutoDump:
    def test_failing_transition_dumps_exactly_once_per_episode(self, tmp_path):
        rec = FlightRecorder(home=str(tmp_path), ring=32)
        rec.record("step", height=1)
        rec.note_health("ok")
        assert rec.dumps == 0
        rec.note_health("failing")
        rec.note_health("failing")   # repeated observation: same episode
        assert rec.dumps == 1
        rec.note_health("degraded")  # episode cleared: latch re-arms
        rec.note_health("failing")
        assert rec.dumps == 2
        files = glob.glob(str(tmp_path / "flightrec" / "dump-*health_failing*"))
        assert len(files) == 2

    def test_wedge_dump_once_per_episode_and_waived_in_fastsync(self, tmp_path):
        rec = FlightRecorder(home=str(tmp_path), ring=8)
        rec.note_height_age(120.0, 60.0, waived=True)   # fast sync: no dump
        assert rec.dumps == 0
        rec.note_height_age(120.0, 60.0)
        rec.note_height_age(130.0, 60.0)
        assert rec.dumps == 1
        rec.note_height_age(1.0, 60.0)                  # a commit re-arms
        rec.note_height_age(80.0, 60.0)
        assert rec.dumps == 2

    def test_dump_is_valid_json_with_monotonic_times_and_counters(
        self, tmp_path
    ):
        rec = FlightRecorder(home=str(tmp_path), ring=32)
        rec.counters_fn = lambda: {
            "peer_vote_gossip_picks": 10, "peer_vote_gossip_sends": 4,
        }
        for i in range(20):
            rec.record("step", height=5, round=0, step=i % 8)
        path = rec.dump("unit")
        assert path is not None and os.path.exists(path)
        with open(path) as f:
            payload = json.load(f)
        assert payload["reason"] == "unit"
        assert payload["counters"]["peer_vote_gossip_picks"] == 10
        ts = [e["t"] for e in payload["events"]]
        assert ts == sorted(ts) and len(ts) == 20
        assert payload["recorded_total"] == 20

    def test_two_dumps_in_one_second_get_distinct_files(self, tmp_path):
        rec = FlightRecorder(home=str(tmp_path), ring=8)
        p1 = rec.dump("same")
        p2 = rec.dump("same")
        assert p1 != p2 and os.path.exists(p1) and os.path.exists(p2)

    def test_dump_without_home_counts_but_never_raises(self):
        rec = FlightRecorder(ring=8)
        rec.record("step", height=1)
        assert rec.dump("nohome") is None
        assert rec.dumps == 1 and rec.dump_failures == 0

    def test_counter_provider_failure_costs_the_section_not_the_dump(
        self, tmp_path
    ):
        rec = FlightRecorder(home=str(tmp_path), ring=8)

        def boom():
            raise RuntimeError("mid-teardown")

        rec.counters_fn = boom
        path = rec.dump("provider_down")
        with open(path) as f:
            assert json.load(f)["counters"] == {}

    def test_exception_note_records_and_dumps(self, tmp_path):
        rec = FlightRecorder(home=str(tmp_path), ring=8)
        rec.note_exception("consensus", RuntimeError("boom"))
        assert rec.dumps == 1
        [ev] = [e for e in rec.events() if e["kind"] == "exception"]
        assert ev["thread"] == "consensus"
        assert "RuntimeError: boom" in ev["err"]


class TestConsensusCrashHook:
    def test_receive_routine_escape_dumps_then_reraises(self, tmp_path):
        """An exception ESCAPING the receive routine (not the per-item
        catch) must land in the ring + a dump before the thread dies."""
        from tendermint_tpu.consensus.state import ConsensusState

        rec = FlightRecorder(home=str(tmp_path), ring=8)

        class _CS:
            flightrec = rec

            def _receive_routine(self, max_steps):
                raise RuntimeError("wedged interpreter state")

        with pytest.raises(RuntimeError, match="wedged"):
            ConsensusState.receive_routine(_CS(), 0)
        assert rec.dumps == 1
        assert any(e["kind"] == "exception" for e in rec.events())
        files = glob.glob(
            str(tmp_path / "flightrec" / "dump-*exception_consensus*")
        )
        assert len(files) == 1


class TestHealthIntegration:
    def test_health_report_feeds_the_recorder(self, tmp_path, monkeypatch):
        """node/health.health_report routes its verdict through
        note_health — the scrape path IS a dump trigger."""
        from tendermint_tpu.node.health import health_report

        rec = FlightRecorder(home=str(tmp_path), ring=8)

        class _RS:
            height = 4

        class _CS:
            wal = None

            def height_age_s(self):
                return 0.1

            def pipeline_poisoned(self):
                return True  # -> failing

            def get_round_state(self):
                return _RS()

        class _BC:
            fast_sync = False

        class _SW:
            def num_peers(self):
                return (1, 1, 0)

        class _MP:
            def size(self):
                return 0

        class _Node:
            consensus_state = _CS()
            blockchain_reactor = _BC()
            sw = _SW()
            mempool = _MP()
            flightrec = rec

        report = health_report(_Node())
        assert report["status"] == "failing"
        assert rec.dumps == 1
        assert [e for e in rec.events() if e["kind"] == "health"]
        # second evaluation: same episode, no second dump
        health_report(_Node())
        assert rec.dumps == 1
