"""UPnP client against a fake in-process IGD, the per-IP-range inbound
counter, and the profiler RPC routes (ref p2p/upnp/*, p2p/ip_range_counter.go,
rpc/core/routes.go:42-45)."""

from __future__ import annotations

import http.server
import socket
import threading

import pytest

from tendermint_tpu.p2p import upnp
from tendermint_tpu.p2p.ip_range_counter import IPRangeCounter

DESC_XML = b"""<?xml version="1.0"?>
<root xmlns="urn:schemas-upnp-org:device-1-0">
 <device><deviceList><device>
  <serviceList><service>
   <serviceType>urn:schemas-upnp-org:service:WANIPConnection:1</serviceType>
   <controlURL>/ctl</controlURL>
  </service></serviceList>
 </device></deviceList></device>
</root>"""

SOAP_EXT_IP = b"""<?xml version="1.0"?>
<s:Envelope xmlns:s="http://schemas.xmlsoap.org/soap/envelope/"><s:Body>
 <u:GetExternalIPAddressResponse xmlns:u="urn:schemas-upnp-org:service:WANIPConnection:1">
  <NewExternalIPAddress>203.0.113.7</NewExternalIPAddress>
 </u:GetExternalIPAddressResponse>
</s:Body></s:Envelope>"""

SOAP_OK = b"""<?xml version="1.0"?>
<s:Envelope xmlns:s="http://schemas.xmlsoap.org/soap/envelope/"><s:Body>
 <u:AnyResponse xmlns:u="urn:schemas-upnp-org:service:WANIPConnection:1"/>
</s:Body></s:Envelope>"""


class _FakeIGD:
    """SSDP responder + description/SOAP HTTP server."""

    def __init__(self):
        self.mapped: list[tuple[str, int]] = []
        self.deleted: list[tuple[str, int]] = []
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                self.send_response(200)
                self.end_headers()
                self.wfile.write(DESC_XML)

            def do_POST(self):
                body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
                action = self.headers.get("SOAPAction", "")
                if "GetExternalIPAddress" in action:
                    payload = SOAP_EXT_IP
                else:
                    import re

                    port = re.search(rb"<NewExternalPort>(\d+)<", body)
                    port = int(port.group(1)) if port else 0
                    if "AddPortMapping" in action:
                        outer.mapped.append(("tcp", port))
                    elif "DeletePortMapping" in action:
                        outer.deleted.append(("tcp", port))
                    payload = SOAP_OK
                self.send_response(200)
                self.end_headers()
                self.wfile.write(payload)

        self.http = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.http.serve_forever, daemon=True).start()
        self.location = f"http://127.0.0.1:{self.http.server_address[1]}/desc.xml"
        # SSDP responder on a plain UDP socket
        self.udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.udp.bind(("127.0.0.1", 0))
        self.ssdp_addr = self.udp.getsockname()

        def responder():
            while True:
                try:
                    data, addr = self.udp.recvfrom(2048)
                except OSError:
                    return
                if b"M-SEARCH" in data:
                    resp = (
                        "HTTP/1.1 200 OK\r\n"
                        f"LOCATION: {self.location}\r\n"
                        "ST: urn:schemas-upnp-org:device:InternetGatewayDevice:1\r\n\r\n"
                    )
                    self.udp.sendto(resp.encode(), addr)

        threading.Thread(target=responder, daemon=True).start()

    def close(self):
        self.http.shutdown()
        self.udp.close()


class TestUPnP:
    @pytest.fixture()
    def igd(self):
        g = _FakeIGD()
        yield g
        g.close()

    def test_discover_and_map(self, igd):
        nat = upnp.discover(timeout=3.0, ssdp_addr=igd.ssdp_addr)
        assert nat.service_type.endswith("WANIPConnection:1")
        assert nat.get_external_address() == "203.0.113.7"
        assert nat.add_port_mapping("tcp", 46656, 46656, "test") == 46656
        nat.delete_port_mapping("tcp", 46656)
        assert igd.mapped == [("tcp", 46656)]
        assert igd.deleted == [("tcp", 46656)]

    def test_discovery_timeout_is_an_error(self):
        sink = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sink.bind(("127.0.0.1", 0))
        try:
            with pytest.raises(upnp.UPnPError):
                upnp.discover(timeout=0.2, ssdp_addr=sink.getsockname())
        finally:
            sink.close()


class TestIPRangeCounter:
    def test_limits_per_depth(self):
        c = IPRangeCounter(limits=(4, 3, 2))
        assert c.try_add("10.0.0.1")
        assert c.try_add("10.0.0.2")
        assert not c.try_add("10.0.0.3")  # /24 at 2
        assert c.try_add("10.0.1.1")  # same /16, different /24
        assert not c.try_add("10.0.2.1")  # /16 at 3
        assert c.try_add("10.9.0.1")  # same /8
        assert not c.try_add("10.8.0.1")  # /8 at 4
        c.remove("10.0.0.1")
        assert c.try_add("10.0.0.9")  # freed

    def test_remove_unknown_is_noop(self):
        c = IPRangeCounter()
        c.remove("192.168.1.1")
        assert c.count("192") == 0


class TestProfilerRoutes:
    def test_cpu_and_heap_profile(self, tmp_path):
        from tendermint_tpu.rpc.core import handlers

        cpu_out = tmp_path / "cpu.prof"
        heap_out = tmp_path / "heap.txt"
        handlers.unsafe_start_cpu_profiler(None, str(cpu_out))
        with pytest.raises(handlers.RPCError):
            handlers.unsafe_start_cpu_profiler(None, str(cpu_out))  # already on
        sum(i * i for i in range(10000))  # some work to profile
        res = handlers.unsafe_stop_cpu_profiler(None)
        assert "profile written" in res["log"]
        assert cpu_out.stat().st_size > 0
        import pstats

        pstats.Stats(str(cpu_out))  # parses as a valid profile
        with pytest.raises(handlers.RPCError):
            handlers.unsafe_stop_cpu_profiler(None)  # already off
        handlers.unsafe_write_heap_profile(None, str(heap_out))
        # second call captures live tracing
        handlers.unsafe_write_heap_profile(None, str(heap_out))
        assert heap_out.exists()

    def test_routes_registered_as_unsafe(self):
        from tendermint_tpu.rpc.core.handlers import UNSAFE_ROUTES_TABLE

        for r in (
            "unsafe_start_cpu_profiler",
            "unsafe_stop_cpu_profiler",
            "unsafe_write_heap_profile",
        ):
            assert r in UNSAFE_ROUTES_TABLE
