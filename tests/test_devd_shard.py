"""Sharded device plane (round 21 — ISSUE 17): N devd daemons behind one
gateway, work-stealing dispatch, per-endpoint circuit breakers.

Unit rows cover the pure pieces (endpoint parsing, slice planning, the
keyed breaker registry's single-socket back-compat); the process rows
run REAL sim-rate daemons (ops/faults.DaemonFleet — separate processes,
real sockets) and assert the tentpole's contracts: per-lane verdict
attribution survives slicing AND re-dispatch, a slow endpoint's residue
is stolen by fast ones, digests stay byte-identical to host hashing,
and the gateway's prime/pop plane rides sharded dispatch unchanged.

Sim daemons verify STRUCTURALLY (len(pk)==32 and len(sig)==64 —
devd._SimVerifier), so forged lanes here are wrong-LENGTH lanes: the
CPU ed25519 fallback agrees they are invalid, making every assertion
fallback-proof. Sim hashing is REAL digests, so hash parity is real.
"""

from __future__ import annotations

import time

import pytest

from tendermint_tpu import devd
from tendermint_tpu.crypto import ed25519 as ed
from tendermint_tpu.ops.faults import DaemonFleet

SIM_ENV = {"TENDERMINT_DEVD_SIM_RATE": "200000"}


@pytest.fixture()
def shard_env(monkeypatch, tmp_path):
    """Clean sharded-plane state: fast breaker windows, low slice floor,
    no inherited endpoint config; resets the endpoint table + keyed
    breaker registry around the test."""
    monkeypatch.delenv("TENDERMINT_DEVD_SOCKS", raising=False)
    monkeypatch.delenv("TENDERMINT_DEVD_SOCK", raising=False)
    monkeypatch.setenv("TENDERMINT_TPU_KERNEL", "devd")
    monkeypatch.setenv("TENDERMINT_TPU_MIN_BATCH", "8")
    monkeypatch.setenv("TENDERMINT_TPU_BREAKER_BACKOFF_S", "0.05")
    monkeypatch.setenv("TENDERMINT_TPU_BREAKER_BACKOFF_CAP_S", "0.25")
    # leave TENDERMINT_DEVD_STREAM_MIN at its 256 default: slices here
    # are narrower, so they ride the single-shot op — whose sim verdicts
    # are structural, letting wrong-LENGTH lanes mark forgeries (the
    # streamed protocol's fixed-width frames reject those lanes outright)
    monkeypatch.delenv("TENDERMINT_DEVD_STREAM_MIN", raising=False)
    import tendermint_tpu.ops.devd_backend as backend
    from tendermint_tpu.ops import devd_shard, gateway

    monkeypatch.setattr(backend, "_client", None)
    monkeypatch.setattr(gateway, "_default_verifier", None)
    monkeypatch.setattr(gateway, "_default_hasher", None)
    backend.reset_stream_latches()
    gateway.reset_devd_breaker()
    devd_shard.reset()
    devd.bust_avail_cache()
    yield monkeypatch
    gateway.reset_devd_breaker()
    devd_shard.reset()
    backend.reset_stream_latches()
    devd.bust_avail_cache()


def _items(n: int, tag: bytes = b"shard"):
    seed = b"\x2a" * 32
    pub = ed.public_key(seed)
    return [
        (pub, tag + b"-%d" % i, ed.sign(seed, tag + b"-%d" % i))
        for i in range(n)
    ]


def _forge(items, idx):
    """Wrong-length signature: structurally invalid to the sim verifier
    AND cryptographically invalid to the CPU fallback."""
    for i in idx:
        p, m, s = items[i]
        items[i] = (p, m, s[:10])
    return items


# -- pure units ---------------------------------------------------------------


def test_endpoint_paths_parsing(shard_env):
    from tendermint_tpu.ops import devd_shard

    mp = shard_env
    mp.setenv("TENDERMINT_DEVD_SOCKS", " /a.sock , /b.sock,/a.sock,, ")
    assert devd_shard.endpoint_paths() == ["/a.sock", "/b.sock"]
    assert devd_shard.enabled()
    # one entry: byte-for-byte the single-socket plane — not enabled
    mp.setenv("TENDERMINT_DEVD_SOCKS", "/only.sock")
    assert devd_shard.endpoint_paths() == ["/only.sock"]
    assert not devd_shard.enabled()
    # and sock_path() itself resolves the single SOCKS entry
    mp.delenv("TENDERMINT_DEVD_SOCK", raising=False)
    assert devd.sock_path() == "/only.sock"
    # explicit SOCK wins over the fleet list
    mp.setenv("TENDERMINT_DEVD_SOCK", "/pinned.sock")
    assert devd.sock_path() == "/pinned.sock"
    # unset: the default fallback
    mp.delenv("TENDERMINT_DEVD_SOCKS", raising=False)
    mp.delenv("TENDERMINT_DEVD_SOCK", raising=False)
    assert devd_shard.endpoint_paths() == [devd.DEFAULT_SOCK]
    assert not devd_shard.enabled()


def test_plan_slices_respects_floor_and_balance():
    from tendermint_tpu.ops.devd_shard import _plan_slices

    # wide batch, 2 workers: ~2 slices each
    assert _plan_slices(64, 2, 8) == [(0, 16), (16, 32), (32, 48), (48, 64)]
    # the floor caps slice count: 20 lanes / floor 8 -> 2 slices, not 4
    assert _plan_slices(20, 2, 8) == [(0, 10), (10, 20)]
    # narrower than the floor: one slice, never zero
    assert _plan_slices(5, 4, 8) == [(0, 5)]
    # uneven remainder spreads one lane at a time, coverage exact
    slices = _plan_slices(67, 3, 4)
    assert slices[0] == (0, 12) and slices[-1][1] == 67
    assert all(b == c for (_, b), (c, _) in zip(slices, slices[1:]))
    assert all(stop - start >= 4 for start, stop in slices)


def test_breaker_registry_keyed_and_backcompat(shard_env):
    from tendermint_tpu.ops import gateway

    # no-arg call == primary-socket call: the five legacy import sites
    # keep observing the same breaker object
    assert gateway.devd_breaker() is gateway.devd_breaker(devd.sock_path())
    a = gateway.devd_breaker("/a.sock")
    b = gateway.devd_breaker("/b.sock")
    assert a is not b and a is gateway.devd_breaker("/a.sock")
    a.record_failure()
    states = gateway.devd_breaker_states()
    assert set(states) >= {"/a.sock", "/b.sock"}
    assert states["/b.sock"] == 0
    gateway.reset_devd_breaker()
    assert gateway.devd_breaker_states() == {}


# -- real fleet rows ----------------------------------------------------------


@pytest.fixture()
def fleet2(shard_env, tmp_path):
    fleet = DaemonFleet(2, sock_dir=str(tmp_path), extra_env=SIM_ENV)
    fleet.start()
    shard_env.setenv("TENDERMINT_DEVD_SOCKS", fleet.socks_env)
    yield fleet
    fleet.stop()


def test_sharded_verify_per_lane_attribution(fleet2):
    from tendermint_tpu.ops import devd_shard

    assert devd_shard.enabled()
    items = _forge(_items(64), [5, 17, 40, 63])
    got = devd_shard.verify_batch(items)
    assert [i for i, ok in enumerate(got) if not ok] == [5, 17, 40, 63]
    st = devd_shard.endpoint_stats()
    assert len(st) == 2
    assert sum(d["dispatched_slices"] for d in st.values()) >= 2
    assert sum(d["sigs"] for d in st.values()) == 64
    # both endpoints actually participated
    assert all(d["dispatched_slices"] >= 1 for d in st.values())


def test_work_stealing_from_slow_endpoint(shard_env, tmp_path):
    """Asymmetric fleet — one endpoint 4000x slower than the other. The
    fast endpoint must finish its own slices and STEAL the slow one's
    residue; the batch completes at fleet speed and the stolen-slice
    counter moves on the fast endpoint."""
    from tendermint_tpu.ops import devd_shard

    slow = DaemonFleet(1, sock_dir=str(tmp_path),
                       extra_env={"TENDERMINT_DEVD_SIM_RATE": "50"})
    fast = DaemonFleet(1, sock_dir=str(tmp_path),
                       extra_env={"TENDERMINT_DEVD_SIM_RATE": "200000"})
    slow.start()
    fast.start()
    try:
        shard_env.setenv(
            "TENDERMINT_DEVD_SOCKS",
            ",".join([slow.sock_paths[0], fast.sock_paths[0]]),
        )
        # floor 8, 64 lanes, 2 workers -> 4 slices of 16: the slow
        # endpoint's first slice alone takes 16/50 = 0.32 s, so the fast
        # one drains its own two and steals at least one
        items = _forge(_items(64), [9])
        t0 = time.monotonic()
        got = devd_shard.verify_batch(items)
        dt = time.monotonic() - t0
        assert [i for i, ok in enumerate(got) if not ok] == [9]
        st = devd_shard.endpoint_stats()
        assert st[fast.sock_paths[0]]["stolen_slices"] >= 1, st
        # fleet speed, not slowest-member speed: 64 lanes at rate 50
        # would be 1.28 s on the slow chip alone
        assert dt < 1.2, f"batch gated on the slow endpoint ({dt:.2f}s)"
        assert devd_shard.plane_stats()["stolen_slices"] >= 1
    finally:
        slow.stop()
        fast.stop()


def test_sharded_hash_parity_and_tree(fleet2):
    from tendermint_tpu.crypto.hashing import ripemd160
    from tendermint_tpu.merkle.simple import flat_tree_from_leaf_digests
    from tendermint_tpu.ops import devd_shard

    parts = [bytes([i % 251]) * 700 for i in range(32)]
    assert devd_shard.hash_batch(parts) == [ripemd160(p) for p in parts]
    digests, internal = devd_shard.hash_tree(parts)
    want = flat_tree_from_leaf_digests([ripemd160(p) for p in parts])
    assert digests == [ripemd160(p) for p in parts]
    assert internal == want.internal_nodes()


def test_gateway_verifier_rides_sharded_plane(fleet2):
    """The production entry point: a devd-routed Verifier's batches shard
    across the fleet (both endpoints' counters move), verdict order is
    preserved, and the prime/pop pipeline works unchanged."""
    from tendermint_tpu.ops import devd_shard, gateway

    v = gateway.Verifier(min_tpu_batch=1)
    assert v._kernel == "devd"
    items = _forge(_items(48, tag=b"gw"), [7, 33])
    assert v.verify_batch(items) == [i not in (7, 33) for i in range(48)]
    st = devd_shard.endpoint_stats()
    # the gateway screens the 2 wrong-length lanes to its CPU path
    # (non-ed25519 shape); the 46 well-formed lanes sharded
    assert sum(d["sigs"] for d in st.values()) == 46
    assert all(d["dispatched_slices"] >= 1 for d in st.values()), st

    # prime plane: dispatch async, pop per-item verdicts
    primed = _forge(_items(32, tag=b"prime"), [3])
    v.prime_cache_async(primed)
    assert v.pop_primed(primed[3]) is False
    assert v.pop_primed(primed[4]) is True
    assert v.pop_primed(primed[4]) is None  # single-use

    # devd-routed counters moved; the fleet-summed transport stats fold
    # into the same flat surface the single-socket plane exports
    vs = v.stats()
    assert vs["tpu_sigs"] >= 48
    assert any(k.startswith("stream") for k in vs), sorted(vs)


def test_kill_one_endpoint_mid_batch_redispatches(fleet2):
    """The tentpole's failure contract at the dispatcher level: SIGKILL
    one daemon, dispatch — the failed slices re-dispatch to the healthy
    endpoint, every lane still gets the CORRECT verdict, and the dead
    endpoint's breaker took the failure accounting."""
    from tendermint_tpu.ops import devd_shard, gateway

    items = _forge(_items(64, tag=b"kill"), [11, 50])
    assert devd_shard.verify_batch(items) == [
        i not in (11, 50) for i in range(64)
    ]
    fleet2.kill(0)
    dead = fleet2.sock_paths[0]
    got = devd_shard.verify_batch(items)
    assert got == [i not in (11, 50) for i in range(64)]
    st = devd_shard.endpoint_stats()
    assert st[dead]["redispatches"] >= 1, st
    assert gateway.devd_breaker(dead).stats()[
        "breaker_consecutive_failures"] >= 1
    # plane still allows: one healthy endpoint is capacity, not death
    assert gateway.devd_plane_allow()


def test_all_endpoints_dead_raises_to_cpu_floor(shard_env, tmp_path):
    """Every breaker open -> the dispatcher refuses (DevdShardError) and
    the gateway Verifier serves correct verdicts on the CPU floor —
    the whole plane degrades only when the entire fleet is gone."""
    from tendermint_tpu.ops import devd_shard, gateway

    socks = [str(tmp_path / "gone-0.sock"), str(tmp_path / "gone-1.sock")]
    shard_env.setenv("TENDERMINT_DEVD_SOCKS", ",".join(socks))
    shard_env.setenv("TENDERMINT_TPU_BREAKER_FAILURES", "1")
    items = _forge(_items(24, tag=b"floor"), [2])
    v = gateway.Verifier(min_tpu_batch=1, use_tpu=True)
    # first batch eats the endpoint failures (opening both breakers) and
    # falls back; verdicts are correct throughout
    assert v.verify_batch(items) == [i != 2 for i in range(24)]
    states = gateway.devd_breaker_states()
    assert all(states[s] == 2 for s in socks), states
    assert not gateway.devd_plane_allow()
    with pytest.raises(devd_shard.DevdShardError):
        devd_shard.verify_batch(items)
    # still serving on the floor
    assert v.verify_batch(items) == [i != 2 for i in range(24)]
    assert v.stats()["cpu_sigs"] >= 24
