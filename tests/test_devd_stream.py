"""Streamed devd transport tests (tendermint_tpu/devd.py verify_stream):
verdict parity against the single-shot op and the CPU reference, protocol
edges (empty batch, 1 item, chunk-width remainders, malformed mid-stream
frames), pipelining (the daemon accepts chunk N+1 while chunk N is in the
kernel — proven by the in-flight high-water counter), and client
reconnect across a daemon restart.

Parity runs against a real CPU-kernel daemon subprocess (the same IPC
bytes a TPU daemon serves); behavioral tests ride the sim-device daemon
(TENDERMINT_DEVD_SIM_RATE — no jax, instant startup, deterministic
device time).
"""

from __future__ import annotations

import os
import socket
import struct
import subprocess
import sys
import time

import pytest

from tendermint_tpu import devd
from tendermint_tpu.crypto import ed25519 as ed

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(sock: str, extra_env: dict) -> subprocess.Popen:
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "TENDERMINT_DEVD_SOCK": sock,
        "TENDERMINT_DEVD_ACCEPT_CPU": "1",
        "TENDERMINT_DEVD_EXIT_ON_TERM": "1",
        **extra_env,
    }
    return subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.devd"],
        env=env, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
    )


def _wait_held(client, proc, deadline_s: float) -> None:
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if proc.poll() is not None:
            err = proc.stderr.read() if proc.stderr else b""
            pytest.fail(f"daemon died: {err[-2000:]!r}")
        try:
            if client.ping(timeout=2.0).get("held"):
                return
        except Exception:
            pass
        time.sleep(0.3)
    proc.kill()
    pytest.fail("daemon never reached serving state")


@pytest.fixture(scope="module")
def daemon(tmp_path_factory):
    """Real CPU-kernel daemon (f32 ladder) — the parity oracle's peer."""
    sock = str(tmp_path_factory.mktemp("devd-stream") / "devd.sock")
    proc = _spawn(sock, {"TENDERMINT_DEVD_WARM": "16"})
    client = devd.DevdClient(sock)
    _wait_held(client, proc, 240.0)  # cold .jax_cache: one f32 compile
    yield sock, client
    try:
        client.shutdown()
    except Exception:
        pass
    client.close()
    try:
        proc.wait(timeout=15)
    except subprocess.TimeoutExpired:
        proc.kill()


@pytest.fixture()
def sim_daemon(tmp_path):
    """Sim-device daemon: pure-python, holds immediately, device time is
    deterministic (1 ms per 100 lanes at the rate below)."""
    sock = str(tmp_path / "sim.sock")
    proc = _spawn(sock, {"TENDERMINT_DEVD_SIM_RATE": "100000"})
    client = devd.DevdClient(sock)
    _wait_held(client, proc, 30.0)
    yield sock, client, proc
    try:
        client.shutdown()
    except Exception:
        pass
    client.close()
    try:
        proc.wait(timeout=15)
    except subprocess.TimeoutExpired:
        proc.kill()


def _items(n: int, tag: bytes = b"stream"):
    seeds = [bytes([9, k]) + b"\x09" * 30 for k in range(8)]
    out = []
    for i in range(n):
        seed = seeds[i % 8]
        msg = tag + b"-%d" % i
        out.append((ed.public_key(seed), msg, ed.sign(seed, msg)))
    return out


def test_streamed_parity_with_single_shot_and_cpu(daemon):
    """Lane-for-lane: streamed == single-shot == _cpu_verify_batch,
    on a batch mixing valid lanes, forged sigs, tampered msgs, and
    msg lengths from 0 to 300 bytes."""
    from tendermint_tpu.ops.gateway import _cpu_verify_batch

    _, client = daemon
    items = _items(37)
    items[3] = (items[3][0], items[3][1], b"\x44" * 64)           # forged
    items[11] = (items[11][0], items[11][1] + b"x", items[11][2])  # tampered
    seed = bytes([9, 0]) + b"\x09" * 30
    items[20] = (ed.public_key(seed), b"", ed.sign(seed, b""))     # empty msg
    long = b"L" * 300
    items[30] = (ed.public_key(seed), long, ed.sign(seed, long))
    items[31] = (items[31][0][::-1], items[31][1], items[31][2])   # wrong key

    want = _cpu_verify_batch(items)
    single = client.verify_batch(items)
    for width in (5, 16, 37, 64):  # remainder, divisor, exact, oversize
        streamed = client.verify_stream(items, chunk=width)
        assert streamed == single == want, f"chunk width {width}"
    assert not all(want)  # the forged lanes actually exercised rejection


def test_streamed_empty_and_single_item(daemon):
    _, client = daemon
    assert client.verify_stream([]) == []
    one = _items(1, tag=b"one")
    assert client.verify_stream(one, chunk=16) == [True]
    forged = [(one[0][0], one[0][1], b"\x21" * 64)]
    assert client.verify_stream(forged, chunk=16) == [False]


def test_gateway_devd_backend_streams_wide_batches(daemon, monkeypatch):
    """A default-constructed Verifier against a serving daemon routes
    wide batches over the STREAMED transport: daemon-side stream
    counters move and the verifier's stats() carries the client-side
    stream section."""
    sock, client = daemon
    monkeypatch.setenv("TENDERMINT_DEVD_SOCK", sock)
    monkeypatch.delenv("TENDERMINT_TPU_KERNEL", raising=False)
    monkeypatch.setenv("TENDERMINT_DEVD_STREAM_MIN", "8")
    monkeypatch.setenv("TENDERMINT_DEVD_CHUNK", "16")
    import tendermint_tpu.ops.devd_backend as backend
    from tendermint_tpu.ops import gateway

    monkeypatch.setattr(backend, "_client", None)
    monkeypatch.setattr(backend, "_stream_ok", True)
    devd.bust_avail_cache()
    v = gateway.Verifier(min_tpu_batch=1)
    assert v._kernel == "devd"

    before = client.status()["stream"]
    items = _items(40, tag=b"gw-stream")
    items[7] = (items[7][0], items[7][1], b"\x66" * 64)
    assert v.verify_batch(items) == [i != 7 for i in range(40)]
    after = client.status()["stream"]
    assert after["chunks"] - before["chunks"] == 3  # 40 lanes / width 16
    assert after["lanes"] - before["lanes"] == 40
    assert after["bytes_framed"] > before["bytes_framed"]
    vstats = v.stats()
    assert vstats["tpu_sigs"] == 40
    # flat numeric keys: the metrics RPC exports these as scalar gauges
    assert vstats["stream_lanes"] >= 40
    assert all(isinstance(val, (int, float)) for val in vstats.values())

    # async form too: resolver contract preserved over the stream
    resolve = v.verify_batch_async(items)
    assert resolve() == [i != 7 for i in range(40)]


def test_daemon_overlaps_chunks_in_flight(sim_daemon):
    """The pipelining claim itself: with device time 10 ms/chunk, the
    daemon must be holding multiple dispatched-unresolved chunks at once
    — inflight_max >= 2 — and per-chunk device latency must be
    recorded."""
    _, client, _ = sim_daemon
    items = [(b"\x05" * 32, b"lap-%04d" % i, b"\x06" * 64) for i in range(8000)]
    assert all(client.verify_stream(items, chunk=1000))
    stream = client.status()["stream"]
    assert stream["inflight_max"] >= 2, stream
    assert stream["inflight"] == 0, stream  # all resolved at stream end
    assert stream["chunks"] == 8
    assert stream["chunk_device_ms_last"] > 0
    assert stream["chunk_device_ms_avg"] > 0


def test_malformed_mid_stream_frame_gets_error_frame(sim_daemon):
    """Speak the raw protocol: one good chunk, then garbage. The daemon
    must answer the good chunk, send an ERROR frame for the bad one
    (never hang), and close the stream."""
    sock, _, _ = sim_daemon
    conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    conn.settimeout(10.0)
    conn.connect(sock)
    try:
        devd._send_frame(conn, {"op": "verify_stream", "chunks": 3, "total": 8})
        good = devd._pack_chunk(
            [(b"\x07" * 32, b"mal-%d" % i, b"\x08" * 64) for i in range(4)]
        )
        conn.sendall(struct.pack(">I", len(good)) + good)
        garbage = b"\xde\xad\xbe\xef" * 5  # claims 0xefbeadde lanes
        conn.sendall(struct.pack(">I", len(garbage)) + garbage)

        first = devd._recv_raw_frame(conn)
        status, idx = struct.unpack_from("<BI", first, 0)
        assert (status, idx) == (devd.STREAM_OK, 0)
        second = devd._recv_raw_frame(conn)
        status, idx = struct.unpack_from("<BI", second, 0)
        assert status == devd.STREAM_ERR and idx == 1
        assert b"malformed" in second[5:]
        # stream aborted: the daemon closes rather than guess at framing
        conn.settimeout(5.0)
        assert conn.recv(1) == b""
    finally:
        conn.close()


def test_malformed_stream_leaves_daemon_serving(sim_daemon):
    """After an aborted stream the daemon still serves new connections,
    and the error counter moved."""
    sock, client, _ = sim_daemon
    bad = devd.DevdClient(sock)
    with pytest.raises(devd.DevdError, match="malformed|mismatch"):
        # undersized chunk: daemon's size validation rejects it
        conn, _ = bad._acquire()
        devd._send_frame(conn, {"op": "verify_stream", "chunks": 1, "total": 4})
        conn.sendall(struct.pack(">I", 2) + b"\x01\x02")
        bad._collect_stream(conn, _NopThread(), [], 1)
    bad.close()
    # the daemon counts the abort on ITS side of the torn stream — poll
    # briefly: under a loaded suite the error handling can land after
    # the client's exception (the status read raced it)
    deadline = time.monotonic() + 5.0
    while client.status()["stream"]["errors"] < 1 and \
            time.monotonic() < deadline:
        time.sleep(0.05)
    rep = client.status()
    assert rep["stream"]["errors"] >= 1
    assert all(client.verify_stream(
        [(b"\x05" * 32, b"after-%d" % i, b"\x06" * 64) for i in range(6)],
        chunk=4,
    ))


class _NopThread:
    def join(self, timeout=None):
        pass


def test_bad_lane_fails_fast_without_hanging(sim_daemon):
    """A malformed lane kills the writer mid-stream; the client must
    surface the ValueError promptly (no io_timeout hang, no retry of a
    deterministic failure) and the daemon must keep serving."""
    _, client, _ = sim_daemon
    items = [(b"\x05" * 32, b"bl-%d" % i, b"\x06" * 64) for i in range(10)]
    items[7] = (b"short", items[7][1], items[7][2])
    t0 = time.time()
    with pytest.raises(ValueError, match="route non-ed25519"):
        client.verify_stream(items, chunk=4)
    assert time.time() - t0 < 10.0  # failed fast, not at io_timeout
    good = [(b"\x05" * 32, b"ok-%d" % i, b"\x06" * 64) for i in range(6)]
    assert all(client.verify_stream(good, chunk=4))


def test_client_reconnects_after_daemon_restart(tmp_path):
    """Pooled connections go stale when the daemon restarts; the next
    request (single-shot AND streamed) must retry on a fresh socket with
    no caller-visible flap."""
    sock = str(tmp_path / "restart.sock")
    proc = _spawn(sock, {"TENDERMINT_DEVD_SIM_RATE": "100000"})
    client = devd.DevdClient(sock)
    _wait_held(client, proc, 30.0)
    items = [(b"\x05" * 32, b"rc-%d" % i, b"\x06" * 64) for i in range(32)]
    assert all(client.verify_stream(items, chunk=8))
    assert all(client.verify_batch(items))

    client.shutdown()
    proc.wait(timeout=15)
    proc2 = _spawn(sock, {"TENDERMINT_DEVD_SIM_RATE": "100000"})
    try:
        _wait_held(devd.DevdClient(sock), proc2, 30.0)
        # same client object, pool full of dead sockets from daemon #1
        assert all(client.verify_stream(items, chunk=8))
        assert all(client.verify_batch(items))
        assert client.stream_stats()["reconnects"] >= 1
    finally:
        try:
            client.shutdown()
        except Exception:
            pass
        client.close()
        try:
            proc2.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc2.kill()


def test_status_op_exposes_stream_counters(sim_daemon):
    _, client, _ = sim_daemon
    rep = client.status()
    assert rep["ok"] and rep["held"]
    assert {"chunks", "lanes", "bytes_framed", "inflight", "inflight_max",
            "errors", "chunk_device_ms_last"} <= set(rep["stream"])
    assert rep["stream_chunk"] >= 1
    assert rep["stream_depth"] >= 2
    # plain stats op carries the same section
    full = client.request({"op": "stats"})
    assert full["ok"] and "stream" in full
