"""Overload-control plane tests (round 23, docs/serving.md): RPC ingress
admission, priority mempool lanes + per-source limits, WS fan-out
backpressure, and the load-shed ladder — units first, then a live node
for the wire contracts (typed sheds, Retry-After, dead-subscriber
teardown)."""

from __future__ import annotations

import json
import socket
import tempfile
import threading
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import pytest

from tendermint_tpu.abci.apps.kvstore import KVStoreApp
from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.abci.types import CODE_MEMPOOL_FULL
from tendermint_tpu.config import reset_test_root
from tendermint_tpu.config import test_config as _test_config
from tendermint_tpu.mempool import (
    Mempool,
    MempoolFullError,
    MempoolSourceLimitError,
    TxInCacheError,
)
from tendermint_tpu.node import default_new_node
from tendermint_tpu.proxy.app_conn import AppConnMempool
from tendermint_tpu.rpc import admission
from tendermint_tpu.rpc.admission import AdmissionController, retry_after_header
from tendermint_tpu.rpc.core import handlers


def wait_until(cond, timeout=30.0, tick=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(tick)
    return cond()


@pytest.fixture(autouse=True)
def _clean_request_tls():
    """Admission state rides a thread-local; tests must not leak a
    deadline or source into each other (or into other test files)."""
    yield
    admission.clear_deadline()
    admission._tls.source_ip = ""


# -- admission unit matrix ---------------------------------------------------


class TestAdmission:
    def test_token_bucket_burst_edge(self, monkeypatch):
        """Exactly `burst` requests admit back-to-back; the next one is a
        429 with a positive Retry-After derived from the refill rate."""
        monkeypatch.setenv("TENDERMINT_RPC_RATE_LIMIT", "5")
        monkeypatch.setenv("TENDERMINT_RPC_RATE_BURST", "2")
        ctl = AdmissionController()
        for _ in range(2):
            a = ctl.admit_request("9.9.9.9", "write")
            assert a
            ctl.request_done()
        a = ctl.admit_request("9.9.9.9", "write")
        assert not a
        assert a.status == 429
        assert a.reason == admission.SHED_RATE_LIMITED
        assert 0 < a.retry_after <= 0.2 + 0.01  # (1 token) / (5/s)
        assert ctl.sheds[admission.SHED_RATE_LIMITED] == 1
        # a different source has its own bucket
        assert ctl.admit_request("8.8.8.8", "write")
        ctl.request_done()
        # waiting one refill interval restores exactly one token
        time.sleep(0.21)
        assert ctl.admit_request("9.9.9.9", "write")
        ctl.request_done()
        assert not ctl.admit_request("9.9.9.9", "write")

    def test_unix_peers_exempt_from_rate_limit(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_RPC_RATE_LIMIT", "1")
        monkeypatch.setenv("TENDERMINT_RPC_RATE_BURST", "1")
        ctl = AdmissionController()
        for _ in range(10):
            assert ctl.admit_request("unix", "write")
            ctl.request_done()
        assert ctl.sheds_total == 0

    def test_inflight_cap(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_RPC_MAX_INFLIGHT", "2")
        ctl = AdmissionController()
        assert ctl.admit_request("1.1.1.1", "read")
        assert ctl.admit_request("1.1.1.1", "read")
        a = ctl.admit_request("1.1.1.1", "read")
        assert not a and a.status == 503
        assert a.reason == admission.SHED_INFLIGHT
        ctl.request_done()
        assert ctl.admit_request("1.1.1.1", "read")
        ctl.request_done()
        ctl.request_done()
        assert ctl.inflight == 0

    def test_connection_cap(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_RPC_MAX_CONNECTIONS", "1")
        ctl = AdmissionController()
        assert ctl.conn_acquire()
        a = ctl.conn_acquire()
        assert not a and a.reason == admission.SHED_CONN_CAP
        ctl.conn_release()
        assert ctl.conn_acquire()

    def test_ladder_sheds_reads_never_writes(self):
        ctl = AdmissionController()
        ctl.pressure_fn = lambda: admission.PRESSURE_SHED_READS
        for kind in ("read", "ws"):
            a = ctl.admit_request("1.1.1.1", kind)
            assert not a and a.reason == admission.SHED_READS
        # writes pass the edge even at shed-writes: the MEMPOOL decides
        # by lane, so the priority lane stays reachable
        ctl.pressure_fn = lambda: admission.PRESSURE_SHED_WRITES
        assert ctl.admit_request("1.1.1.1", "write")
        ctl.request_done()
        # ops stays observable at any ladder level, uncounted
        assert ctl.admit_request("1.1.1.1", "ops")
        assert ctl.inflight == 0

    def test_deadline_armed_and_cleared(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_RPC_DEADLINE_S", "0.5")
        ctl = AdmissionController()
        assert ctl.admit_request("1.1.1.1", "write")
        left = admission.deadline_remaining()
        assert left is not None and 0 < left <= 0.5
        assert admission.request_source() == "1.1.1.1"
        ctl.request_done()
        assert admission.deadline_remaining() is None
        assert admission.request_source() == ""

    def test_deadline_expiry_mid_handler(self):
        """A handler wait that outlives the request budget fails typed
        (deadline_exceeded) and lands on the deadline shed counter —
        never the generic timed-out 500."""
        ctl = AdmissionController()
        ctx = SimpleNamespace(node=SimpleNamespace(rpc_admission=ctl))
        admission.set_deadline(0.02)
        time.sleep(0.03)
        with pytest.raises(handlers.RPCError, match="deadline_exceeded"):
            handlers._wait_or_deadline(ctx, threading.Event(), 10.0, "CheckTx")
        assert ctl.sheds[admission.SHED_DEADLINE] == 1
        # without a deadline the handler's own timeout still fires
        admission.clear_deadline()
        with pytest.raises(handlers.RPCError, match="timed out"):
            handlers._wait_or_deadline(ctx, threading.Event(), 0.01, "CheckTx")
        assert ctl.sheds[admission.SHED_DEADLINE] == 1

    def test_retry_after_header_contract(self):
        # RFC 7231: whole seconds, and never "0" (clients would hot-loop)
        assert retry_after_header(0.05) == "1"
        assert retry_after_header(1.0) == "1"
        assert retry_after_header(3.2) == "4"

    def test_snapshot_keys(self):
        snap = AdmissionController().snapshot()
        for key in ("inflight", "connections", "sheds", "deadline_rejects",
                    "ws_clients", "ws_evictions", "ws_dropped_events"):
            assert key in snap, key


# -- WS fan-out backpressure (unit: no sockets on the event-bus side) --------


class _FakeWSServer:
    def __init__(self, ctl):
        self.admission = ctl
        self.ctx = SimpleNamespace(event_switch=None)
        import logging

        self.logger = logging.getLogger("test.ws")


class TestWSBackpressure:
    def _conn(self, ctl):
        from tendermint_tpu.rpc.server import WSConnection

        a, b = socket.socketpair()
        self._peer = b
        return WSConnection(_FakeWSServer(ctl), a)

    def test_queue_overflow_drops_oldest_then_evicts(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_RPC_WS_QUEUE", "4")
        monkeypatch.setenv("TENDERMINT_RPC_WS_MAX_OVERFLOWS", "2")
        ctl = AdmissionController()
        conn = self._conn(ctl)
        assert ctl.ws_register(conn)
        assert ctl.ws_clients() == 1
        # writer thread deliberately NOT started: the consumer is stuck
        for i in range(4):
            conn.send_json({"i": i})
        assert conn.sendq_depth() == 4
        conn.send_json({"i": 4})  # overflow 1: drop-oldest, stay connected
        assert ctl.ws_dropped_events == 1
        assert conn.sendq_depth() == 4
        assert not conn._torn
        conn.send_json({"i": 5})  # overflow 2: evicted
        assert ctl.ws_evictions == 1
        assert conn._torn
        assert ctl.ws_clients() == 0
        # post-eviction sends are no-ops, not errors (event bus safety)
        conn.send_json({"i": 6})
        self._peer.close()

    def test_ws_client_cap(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_RPC_WS_MAX_CLIENTS", "1")
        ctl = AdmissionController()
        c1, c2 = self._conn(ctl), self._conn(ctl)
        assert ctl.ws_register(c1)
        assert not ctl.ws_register(c2)
        assert ctl.sheds[admission.SHED_WS_CAP] == 1
        ctl.ws_unregister(c1)
        assert ctl.ws_register(c2)

    def test_queue_frac_feeds_pressure(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_RPC_WS_QUEUE", "8")
        ctl = AdmissionController()
        conn = self._conn(ctl)
        ctl.ws_register(conn)
        assert ctl.ws_queue_frac() == 0.0
        for i in range(4):
            conn.send_json({"i": i})
        assert ctl.ws_queue_frac() == pytest.approx(0.5)


# -- mempool lanes + per-source limits ---------------------------------------


def _mk_lane_mempool():
    cfg = _test_config().mempool
    return Mempool(cfg, AppConnMempool(LocalClient(KVStoreApp())))


def _sync_check(mp, tx, **kw):
    """LocalClient is synchronous: the response callback fires inside
    check_tx, so box holds the (possibly mutated) ResponseCheckTx."""
    box = {}
    mp.check_tx(tx, lambda res: box.__setitem__("res", res), **kw)
    return box["res"]


class TestMempoolLanes:
    def test_reap_drains_lanes_in_priority_order(self):
        mp = _mk_lane_mempool()
        mp.check_tx(b"bulk:a=1")
        mp.check_tx(b"plain-a=1")
        mp.check_tx(b"pri:a=1")
        mp.check_tx(b"bulk:b=1")
        mp.check_tx(b"pri:b=1")
        assert mp.reap(-1) == [
            b"pri:a=1", b"pri:b=1",      # priority lane, FIFO within
            b"plain-a=1",                 # default lane
            b"bulk:a=1", b"bulk:b=1",     # bulk lane last
        ]
        assert mp.reap(3) == [b"pri:a=1", b"pri:b=1", b"plain-a=1"]
        assert mp.lane_counts == {"priority": 2, "default": 1, "bulk": 2}

    def test_lane_full_mutates_response_typed(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_MEMPOOL_LANE_BULK_MAX_TXS", "2")
        mp = _mk_lane_mempool()
        assert _sync_check(mp, b"bulk:a=1").code == 0
        assert _sync_check(mp, b"bulk:b=1").code == 0
        res = _sync_check(mp, b"bulk:c=1")
        assert res.code == CODE_MEMPOOL_FULL
        assert res.log == "mempool_lane_full:bulk"
        assert mp.lane_full["bulk"] == 1
        assert mp.size() == 2
        # other lanes unaffected, and the rejected tx left the dedup
        # cache so it can resubmit once the lane drains
        assert _sync_check(mp, b"pri:c=1").code == 0
        mp.lock()
        try:
            mp.update(1, [b"bulk:a=1"])
        finally:
            mp.unlock()
        assert _sync_check(mp, b"bulk:c=1").code == 0

    def test_lane_byte_cap(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_MEMPOOL_LANE_DEFAULT_MAX_BYTES", "16")
        mp = _mk_lane_mempool()
        assert _sync_check(mp, b"k1=0123456789").code == 0  # 12 bytes
        res = _sync_check(mp, b"k2=0123456789")
        assert res.code == CODE_MEMPOOL_FULL
        assert res.log == "mempool_lane_full:default"

    def test_pool_cap_fails_fast_at_intake(self, monkeypatch):
        for lane in ("PRIORITY", "DEFAULT", "BULK"):
            monkeypatch.setenv(f"TENDERMINT_MEMPOOL_LANE_{lane}_MAX_TXS", "1")
        mp = _mk_lane_mempool()
        assert mp.pool_cap == 3
        mp.check_tx(b"pri:a=1")
        mp.check_tx(b"plain=1")
        mp.check_tx(b"bulk:a=1")
        with pytest.raises(MempoolFullError, match="^mempool_full:"):
            mp.check_tx(b"plain=2")
        assert mp.pool_full_rejects == 1
        # fail-fast dropped the cache entry: resubmission after drain works
        mp.lock()
        try:
            mp.update(1, [b"plain=1"])
        finally:
            mp.unlock()
        mp.check_tx(b"plain=2")
        assert mp.size() == 3

    def test_per_source_limit(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_MEMPOOL_SOURCE_MAX_TXS", "2")
        mp = _mk_lane_mempool()
        mp.check_tx(b"a=1", source_id="1.2.3.4")
        mp.check_tx(b"b=1", source_id="1.2.3.4")
        with pytest.raises(MempoolSourceLimitError,
                           match="^mempool_source_limit: rpc:1.2.3.4"):
            mp.check_tx(b"c=1", source_id="1.2.3.4")
        assert mp.source_limited == 1
        # another source (and the peer plane) is unaffected
        mp.check_tx(b"c=1", source_id="5.6.7.8")
        mp.check_tx(b"d=1", source="peer", source_id="peerX")
        assert mp.source_counts == {"rpc:1.2.3.4": 2, "rpc:5.6.7.8": 1,
                                    "peer:peerX": 1}
        # committing a tx releases its slot
        mp.lock()
        try:
            mp.update(1, [b"a=1"])
        finally:
            mp.unlock()
        mp.check_tx(b"e=1", source_id="1.2.3.4")

    def test_shed_writes_spares_priority_lane(self):
        mp = _mk_lane_mempool()
        mp.pressure_fn = lambda: 2  # PRESSURE_SHED_WRITES
        res = _sync_check(mp, b"plain=1")
        assert res.code == CODE_MEMPOOL_FULL
        assert res.log == "mempool_shed_writes:default"
        res = _sync_check(mp, b"bulk:a=1")
        assert res.log == "mempool_shed_writes:bulk"
        assert mp.shed_writes == 2
        # the whole point of the ladder: priority writes still land
        assert _sync_check(mp, b"pri:a=1").code == 0
        assert mp.size() == 1

    def test_gossip_stays_lane_blind(self):
        """The CList the reactor walks keeps ARRIVAL order — lanes bias
        reap (block building), never gossip, so blocks stay
        byte-identical across nodes that disagree about lane config."""
        mp = _mk_lane_mempool()
        order = [b"bulk:a=1", b"pri:a=1", b"plain=1"]
        for tx in order:
            mp.check_tx(tx)
        walked, el = [], mp.txs_front()
        while el is not None:
            walked.append(el.value.tx)
            el = el.next()
        assert walked == order


class TestLaneHammer:
    def test_concurrent_mixed_source_checktx_vs_update_reap(self):
        """4 submitter threads (rpc + peer sources, all three lanes, some
        deliberate duplicates) race a churn thread doing reap + update.
        Afterwards every accounting plane must agree with the pool."""
        mp = _mk_lane_mempool()
        stop = threading.Event()
        dups_hit = []
        errors = []

        def submitter(t):
            prefixes = [b"pri:", b"", b"bulk:"]
            kw = ({"source": "rpc", "source_id": f"10.0.0.{t}"}
                  if t % 2 == 0 else
                  {"source": "peer", "source_id": f"peer{t}"})
            for i in range(150):
                tx = prefixes[i % 3] + f"k{t}-{i}=v".encode()
                try:
                    mp.check_tx(tx, **kw)
                except TxInCacheError:
                    dups_hit.append(tx)
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)
                if i % 25 == 0:
                    # the same tx from every thread: dedup-cache hammer
                    try:
                        mp.check_tx(b"dup=1", **kw)
                    except TxInCacheError:
                        dups_hit.append(b"dup=1")

        def churner():
            height = 0
            while not stop.is_set():
                txs = mp.reap(20)
                height += 1
                mp.lock()
                try:
                    mp.update(height, txs[:10])
                finally:
                    mp.unlock()
                time.sleep(0.001)

        threads = [threading.Thread(target=submitter, args=(t,))
                   for t in range(4)]
        churn = threading.Thread(target=churner)
        churn.start()
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60)
        stop.set()
        churn.join(timeout=60)
        assert not errors, errors

        # -- invariants: lanes, bytes, and sources all agree with the pool
        by_lane = {"priority": 0, "default": 0, "bulk": 0}
        by_lane_bytes = dict.fromkeys(by_lane, 0)
        by_source: dict[str, int] = {}
        el = mp.txs_front()
        while el is not None:
            memtx = el.value
            by_lane[memtx.lane] += 1
            by_lane_bytes[memtx.lane] += len(memtx.tx)
            by_source[memtx.source] = by_source.get(memtx.source, 0) + 1
            el = el.next()
        assert mp.lane_counts == by_lane
        assert mp.lane_bytes == by_lane_bytes
        assert mp.source_counts == by_source
        assert sum(by_lane.values()) == mp.size()
        # the shared dup tx collided at the cache and was counted
        assert mp.cache_dups >= len(dups_hit) > 0
        assert not mp._pending_source, "pending-source map leaked entries"


# -- live node: wire contracts -----------------------------------------------


@pytest.fixture(scope="module")
def node():
    tmp = tempfile.mkdtemp(prefix="overload-test-")
    cfg = reset_test_root(tmp)
    cfg.base.proxy_app = "kvstore"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    n = default_new_node(cfg)
    n.start()
    assert wait_until(lambda: n.block_store.height() >= 1, timeout=30)
    yield n
    n.stop()


@pytest.fixture(scope="module")
def client(node):
    from tendermint_tpu.rpc.client import HTTPClient

    return HTTPClient(f"127.0.0.1:{node.rpc_port()}")


def test_duplicate_tx_is_typed_not_500(node, client):
    from tendermint_tpu.rpc.client import RPCClientError

    tx = b"overload-dup=1".hex()
    assert client.broadcast_tx_sync(tx=tx)["code"] == 0
    with pytest.raises(RPCClientError, match="^tx_in_cache:"):
        client.broadcast_tx_sync(tx=tx)


def test_rate_limit_429_retry_after_on_the_wire(node, client, monkeypatch):
    monkeypatch.setenv("TENDERMINT_RPC_RATE_LIMIT", "1")
    monkeypatch.setenv("TENDERMINT_RPC_RATE_BURST", "1")
    url = f"http://127.0.0.1:{node.rpc_port()}/"
    payload = json.dumps({"jsonrpc": "2.0", "id": 1, "method": "status",
                          "params": {}}).encode()

    def post():
        req = urllib.request.Request(
            url, data=payload, headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status, dict(resp.headers), resp.read()
        except urllib.error.HTTPError as exc:
            return exc.code, dict(exc.headers), exc.read()

    before = node.rpc_admission.sheds[admission.SHED_RATE_LIMITED]
    results = [post() for _ in range(5)]
    limited = [r for r in results if r[0] == 429]
    assert limited, [r[0] for r in results]
    status, headers, body = limited[0]
    assert int(headers["Retry-After"]) >= 1
    assert json.loads(body)["error"] == "shed:rate_limited"
    assert node.rpc_admission.sheds[admission.SHED_RATE_LIMITED] > before
    # ops endpoints stay reachable while the same IP is throttled
    with urllib.request.urlopen(
        f"http://127.0.0.1:{node.rpc_port()}/metrics", timeout=10
    ) as resp:
        assert resp.status == 200


def test_ws_dead_socket_between_subscribe_and_event(node):
    """Regression (satellite 1): a subscriber whose socket dies between
    subscribe and the next event must be torn down on the server —
    listener deregistered, registry slot freed — not leak a callback on
    the event delivery path."""
    from tendermint_tpu.rpc.client import WSClient

    ws = WSClient(f"127.0.0.1:{node.rpc_port()}")
    ws.subscribe("NewBlock")
    assert wait_until(
        lambda: any(l.startswith("ws-") for l in node.evsw._listeners),
        timeout=10)
    assert node.rpc_admission.ws_clients() == 1
    # kill the socket abruptly — no close frame, no unsubscribe
    ws.sock.close()
    assert wait_until(
        lambda: not any(l.startswith("ws-") for l in node.evsw._listeners),
        timeout=15), "dead subscriber left its event listener registered"
    assert wait_until(lambda: node.rpc_admission.ws_clients() == 0, timeout=10)


def test_overload_monitor_level_and_snapshot(node):
    mon = node.overload
    snap = mon.snapshot()
    assert snap["level"] == 0
    assert 0.0 <= snap["score"] <= 1.0
    for key in ("frac_mempool", "frac_rpc_inflight", "frac_ws_queue",
                "frac_apply_backlog"):
        assert key in snap, key
    # the ladder level is what both ingress layers consult (bound-method
    # equality: same function, same monitor)
    assert node.rpc_admission.pressure_fn == mon.level
    assert node.mempool.pressure_fn == mon.level
