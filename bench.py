"""Headline benchmark: VerifyCommit throughput (BASELINE.md north star).

Measures batched Ed25519 commit verification — the reference's hottest
path (types/validator_set.go:220-264: N sequential verifies per block) —
on the available accelerator, against our own CPU reference loop (the
Go-equivalent baseline; upstream publishes no numbers, BASELINE.md).

The accelerator measurement is SUSTAINED pipelined throughput: host
marshaling of batch i+1 overlaps device execution of batch i (jax async
dispatch), exactly how a fast-syncing node streams commits through the
verifier.

Prints ONE JSON line:
  {"metric": "verify_commit_sigs_per_sec", "value": N, "unit": "sigs/s",
   "vs_baseline": N / cpu_sigs_per_sec}
"""

from __future__ import annotations

import json
import os
import sys
import time

from tendermint_tpu.jitcache import enable as _enable_jit_cache

_enable_jit_cache()

BATCH = int(os.environ.get("BENCH_BATCH", "8192"))
N_BATCHES = int(os.environ.get("BENCH_N_BATCHES", "6"))
CPU_SAMPLE = int(os.environ.get("BENCH_CPU_SAMPLE", "512"))


def _make_items(n: int):
    from tendermint_tpu.crypto import ed25519 as ed

    # 64 distinct validators signing vote-like canonical messages, cycled
    # to n — matches a real commit (few keys, many (H,R) messages).
    seeds = [bytes([i]) * 32 for i in range(64)]
    pubs = [ed.public_key(s) for s in seeds]
    items = []
    for i in range(n):
        k = i % 64
        msg = (
            b'{"chain_id":"bench","vote":{"block_id":{},"height":%d,'
            b'"round":0,"type":2,"validator_index":%d}}' % (1 + i // 64, k)
        )
        items.append((pubs[k], msg, ed.sign(seeds[k], msg)))
    return items


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tendermint_tpu.crypto import ed25519 as ed_cpu
    from tendermint_tpu.ops import ed25519 as ops_ed

    chunks = [_make_items(BATCH) for _ in range(N_BATCHES)]

    # --- CPU baseline: the reference-faithful sequential loop ------------
    t0 = time.perf_counter()
    for pub, msg, sig in chunks[0][:CPU_SAMPLE]:
        assert ed_cpu.verify(pub, msg, sig)
    cpu_rate = CPU_SAMPLE / (time.perf_counter() - t0)

    def dispatch(prep):
        args = tuple(jnp.asarray(a) for a in prep[:6])
        return ops_ed._verify_jit(*args), prep[6]

    # warmup (compile)
    ok, valid = dispatch(ops_ed.prepare_batch_limbs(chunks[0], BATCH))
    assert bool(np.asarray(ok).all()), "warmup verify failed"

    # --- sustained pipelined throughput: a prep thread feeds marshaled
    # batches while the device runs the previous kernel ------------------
    import queue as _q
    import threading as _t

    fed: _q.Queue = _q.Queue(maxsize=2)

    def prep_worker():
        # host marshaling only: device transfers stay on the dispatch
        # thread (off-thread device_put serializes with kernel execution
        # on this backend and measured slower)
        for chunk in chunks:
            fed.put(ops_ed.prepare_batch_limbs(chunk, BATCH))
        fed.put(None)

    t0 = time.perf_counter()
    _t.Thread(target=prep_worker, daemon=True).start()
    in_flight, valids = [], []
    while True:
        prep = fed.get()
        if prep is None:
            break
        ok, valid = dispatch(prep)
        in_flight.append(ok)
        valids.append(valid)
    results = [np.asarray(ok) for ok in in_flight]
    elapsed = time.perf_counter() - t0
    assert all(r.all() and v.all() for r, v in zip(results, valids))
    total = BATCH * N_BATCHES
    rate = total / elapsed

    print(
        json.dumps(
            {
                "metric": "verify_commit_sigs_per_sec",
                "value": round(rate, 1),
                "unit": "sigs/s",
                "vs_baseline": round(rate / cpu_rate, 2),
                "detail": {
                    "batch": BATCH,
                    "n_batches": N_BATCHES,
                    "elapsed_s": round(elapsed, 3),
                    "cpu_sigs_per_sec": round(cpu_rate, 1),
                    "platform": jax.devices()[0].platform,
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
