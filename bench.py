"""Headline benchmark: VerifyCommit throughput (BASELINE.md north star).

Measures batched Ed25519 commit verification — the reference's hottest
path (types/validator_set.go:220-264: N sequential verifies per block) —
through the PRODUCTION gateway path (ops/gateway.py Verifier, which
selects the platform-default verify kernel — the pallas fp32 ladder
ops/ed25519_f32p.py on TPU; see gateway.KERNELS), against our own CPU
reference loop (the Go-equivalent baseline; upstream publishes no
numbers, BASELINE.md).

The accelerator measurement is SUSTAINED pipelined throughput, shaped
like a fast-syncing node streaming commits through the verifier:
- prep threads marshal batches and enqueue the device kernel
  (gateway.verify_batch_async — host marshal overlaps device execution);
- resolver threads block on results CONCURRENTLY, which matters when the
  chip sits behind a network tunnel: each result fetch pays the tunnel
  round trip, so overlapping fetches is the difference between the
  kernel's rate and half of it.
Results are order-preserved and parity-checked against the CPU verifier
on a mixed valid/tampered sample.

CPU baseline methodology (pinned; round-2 review flagged run-to-run
wobble): fixed 512-signature sample, best-of-3 passes (max rate =
min time), same process, measured before any device work starts.

Prints ONE JSON line:
  {"metric": "verify_commit_sigs_per_sec", "value": N, "unit": "sigs/s",
   "vs_baseline": N / cpu_sigs_per_sec}
"""

from __future__ import annotations

import json
import os
import sys
import time

from tendermint_tpu.jitcache import enable as _enable_jit_cache

_enable_jit_cache()

BATCH = int(os.environ.get("BENCH_BATCH", "4096"))
N_BATCHES = int(os.environ.get("BENCH_N_BATCHES", "32"))
CPU_SAMPLE = int(os.environ.get("BENCH_CPU_SAMPLE", "512"))
CPU_PASSES = int(os.environ.get("BENCH_CPU_PASSES", "3"))
PREP_THREADS = int(os.environ.get("BENCH_PREP_THREADS", "2"))
RESOLVE_THREADS = int(os.environ.get("BENCH_RESOLVE_THREADS", "4"))


def _make_items(n: int, salt: int = 0):
    from tendermint_tpu.crypto import ed25519 as ed

    # 64 distinct validators signing vote-like canonical messages, cycled
    # to n — matches a real commit (few keys, many (H,R) messages).
    seeds = [bytes([i]) * 32 for i in range(64)]
    pubs = [ed.public_key(s) for s in seeds]
    items = []
    for i in range(n):
        k = i % 64
        msg = (
            b'{"chain_id":"bench","vote":{"block_id":{},"height":%d,'
            b'"round":%d,"type":2,"validator_index":%d}}'
            % (1 + i // 64, salt, k)
        )
        items.append((pubs[k], msg, ed.sign(seeds[k], msg)))
    return items


def main() -> None:
    import queue as _q
    import threading as _t

    from tendermint_tpu.crypto import ed25519 as ed_cpu
    from tendermint_tpu.ops.gateway import Verifier

    stale_device = False
    if os.environ.get("TENDERMINT_TPU_DISABLE", "") == "1":
        platform = "cpu (TENDERMINT_TPU_DISABLE)"  # don't dial the device
    else:
        # Device-access discipline (round-3 postmortem: a wedged tunnel
        # silently turned the round's headline number into a CPU number).
        # Preference order:
        # 1. a serving device daemon (devd) — it holds the chip with
        #    warmed kernels, and this process stays off the tunnel;
        # 2. a direct bounded dial;
        # 3. the CPU fallback, loudly marked stale_device so a fallback
        #    number can never read as a TPU regression.
        from tendermint_tpu import devd

        explicit_kernel = os.environ.get("TENDERMINT_TPU_KERNEL", "")
        daemon = devd.available(timeout=3.0)
        if daemon is None:
            # a daemon mid-claim/warm holds the chip already — dialing it
            # directly now would time out and publish a stale CPU number
            # minutes before the daemon starts serving. Wait it out.
            wait_s = float(os.environ.get("BENCH_DEVD_WAIT_S", "900"))
            deadline = time.time() + wait_s
            try:
                client = devd.DevdClient(devd.sock_path())
                while time.time() < deadline:
                    rep = client.ping(timeout=3.0)
                    if rep.get("held"):
                        devd.bust_avail_cache()
                        daemon = devd.available(timeout=3.0)
                        break
                    if rep.get("status") == "waiting-for-device":
                        break  # tunnel is down for the daemon too
                    print(
                        f"bench: daemon {rep.get('status')!r} "
                        f"(warmed={rep.get('warmed')}); waiting...",
                        file=sys.stderr,
                    )
                    time.sleep(15.0)
                client.close()
            except Exception:  # noqa: BLE001 — no daemon at all
                pass
        if explicit_kernel == "devd" and daemon is None:
            print("bench: TENDERMINT_TPU_KERNEL=devd but no daemon is "
                  "serving a device", file=sys.stderr)
            raise SystemExit(3)
        daemon_is_accel = daemon is not None and daemon.get("platform") in (
            "tpu", "axon",
        )
        if explicit_kernel == "devd" or (not explicit_kernel and daemon_is_accel):
            # route through the daemon only when it holds REAL hardware
            # (or the operator explicitly asked): an ACCEPT_CPU daemon
            # must not produce an unmarked CPU-over-IPC headline number
            os.environ["TENDERMINT_TPU_KERNEL"] = "devd"
            platform = f"{daemon.get('platform')} (via devd)"
            print(
                f"bench: device daemon serving (platform="
                f"{daemon.get('platform')}, warmed={daemon.get('warmed')})",
                file=sys.stderr,
            )
        else:
            from tendermint_tpu.jitcache import probe_device

            platform = probe_device()
            if platform is None:
                # the gateway would dial the same dead tunnel; pin CPU so
                # the run below measures the honest fallback, not a hang
                os.environ["TENDERMINT_TPU_DISABLE"] = "1"
                stale_device = True
                print(
                    "bench: STALE DEVICE — no daemon serving and the direct "
                    "dial timed out; the number below is the CPU fallback "
                    "path, NOT an accelerator measurement",
                    file=sys.stderr,
                )

    # the CPU fallback path rides the native batch verifier; build it NOW
    # (fresh clone: ~1 min) so a missing .so can't silently demote the
    # fallback measurement to the per-item python loop
    from tendermint_tpu import native as _native

    _native.available()

    chunks = [_make_items(BATCH, salt) for salt in range(N_BATCHES)]
    verifier = Verifier(min_tpu_batch=1)

    # --- CPU baseline: the reference-faithful sequential loop ------------
    # (best-of-k over a fixed sample pins the methodology across rounds)
    cpu_rate = 0.0
    for _ in range(CPU_PASSES):
        t0 = time.perf_counter()
        for pub, msg, sig in chunks[0][:CPU_SAMPLE]:
            assert ed_cpu.verify(pub, msg, sig)
        cpu_rate = max(cpu_rate, CPU_SAMPLE / (time.perf_counter() - t0))

    # warmup (compile) through the production path
    ok = verifier.verify_batch(chunks[0])
    assert all(ok), "warmup verify failed"

    # --- sustained pipelined throughput (best-of-k: the chip sits behind
    # a shared tunnel, so single passes can catch contention noise) ------
    PASSES = int(os.environ.get("BENCH_PASSES", "2"))
    elapsed = float("inf")
    for _ in range(PASSES):
        results: list = [None] * N_BATCHES
        next_idx = {"v": 0}
        idx_mtx = _t.Lock()
        dispatched: _q.Queue = _q.Queue(maxsize=PREP_THREADS + RESOLVE_THREADS)

        def prep_worker():
            while True:
                with idx_mtx:
                    i = next_idx["v"]
                    if i >= N_BATCHES:
                        return
                    next_idx["v"] = i + 1
                dispatched.put((i, verifier.verify_batch_async(chunks[i])))

        def resolve_worker():
            while True:
                item = dispatched.get()
                if item is None:
                    return
                i, resolve = item
                results[i] = resolve()

        t0 = time.perf_counter()
        preps = [_t.Thread(target=prep_worker, daemon=True) for _ in range(PREP_THREADS)]
        resolvers = [
            _t.Thread(target=resolve_worker, daemon=True) for _ in range(RESOLVE_THREADS)
        ]
        for th in preps + resolvers:
            th.start()
        for th in preps:
            th.join()
        for _ in resolvers:
            dispatched.put(None)
        for th in resolvers:
            th.join()
        elapsed = min(elapsed, time.perf_counter() - t0)
        assert all(r is not None and all(r) for r in results), "sustained verify failed"
    total = BATCH * N_BATCHES
    rate = total / elapsed

    # --- parity check: TPU verdicts == CPU verdicts on a mixed sample ----
    sample = chunks[0][:64]
    tampered = [
        (p, m, sig[:10] + bytes([sig[10] ^ 1]) + sig[11:])
        for p, m, sig in chunks[1][:64]
    ]
    mixed = sample + tampered
    tpu_verdicts = verifier.verify_batch(mixed)
    cpu_verdicts = [ed_cpu.verify(p, m, s) for p, m, s in mixed]
    assert tpu_verdicts == cpu_verdicts, "TPU/CPU parity failure"

    stats = verifier.stats()
    print(
        json.dumps(
            {
                "metric": "verify_commit_sigs_per_sec",
                "value": round(rate, 1),
                "unit": "sigs/s",
                "vs_baseline": round(rate / cpu_rate, 2),
                "detail": {
                    "batch": BATCH,
                    "n_batches": N_BATCHES,
                    "elapsed_s": round(elapsed, 3),
                    "cpu_sigs_per_sec": round(cpu_rate, 1),
                    "cpu_methodology": f"best-of-{CPU_PASSES} over {CPU_SAMPLE} fixed sigs",
                    "platform": platform or "cpu-fallback (device unreachable)",
                    "gateway_stats": stats,
                    "parity": "ok",
                    **(
                        {
                            "stale_device": True,
                            "note": (
                                "TPU tunnel unreachable at bench time — this "
                                "measures the host fallback backend (native "
                                "RLC batch verify, AVX-512 IFMA), which meets "
                                "the >=10x north star on its own. See "
                                "BENCHES.json for the recorded TPU rate and "
                                "BENCHES.cpu-fallback.json for the full host "
                                "set."
                            ),
                        }
                        if stale_device
                        else {}
                    ),
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
