"""Headline benchmark: VerifyCommit throughput (BASELINE.md north star).

Measures batched Ed25519 commit verification — the reference's hottest
path (types/validator_set.go:220-264: N sequential verifies per block) —
on the available accelerator, against our own CPU reference loop (the
Go-equivalent baseline; upstream publishes no numbers, BASELINE.md).

Prints ONE JSON line:
  {"metric": "verify_commit_sigs_per_sec", "value": N, "unit": "sigs/s",
   "vs_baseline": N / cpu_sigs_per_sec}
"""

from __future__ import annotations

import json
import os
import sys
import time

from tendermint_tpu.jitcache import enable as _enable_jit_cache

_enable_jit_cache()

BATCH = int(os.environ.get("BENCH_BATCH", "1024"))
CPU_SAMPLE = int(os.environ.get("BENCH_CPU_SAMPLE", "256"))
REPS = int(os.environ.get("BENCH_REPS", "5"))


def _make_items(n: int):
    from tendermint_tpu.crypto import ed25519 as ed

    # 64 distinct validators signing vote-like canonical messages, cycled
    # to n — matches a real commit (few keys, many (H,R) messages).
    seeds = [bytes([i]) * 32 for i in range(64)]
    pubs = [ed.public_key(s) for s in seeds]
    items = []
    for i in range(n):
        k = i % 64
        msg = (
            b'{"chain_id":"bench","vote":{"block_id":{},"height":%d,'
            b'"round":0,"type":2,"validator_index":%d}}' % (1 + i // 64, k)
        )
        items.append((pubs[k], msg, ed.sign(seeds[k], msg)))
    return items


def main() -> None:
    import jax
    import numpy as np

    from tendermint_tpu.crypto import ed25519 as ed_cpu
    from tendermint_tpu.ops import ed25519 as ops_ed

    items = _make_items(BATCH)

    # --- CPU baseline: the reference-faithful sequential loop ------------
    t0 = time.perf_counter()
    for pub, msg, sig in items[:CPU_SAMPLE]:
        assert ed_cpu.verify(pub, msg, sig)
    cpu_rate = CPU_SAMPLE / (time.perf_counter() - t0)

    # --- accelerator: one warmup (compile) then timed reps ---------------
    ok = ops_ed.verify_batch(items)
    assert bool(np.all(ok)), "warmup verify failed"
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        ok = ops_ed.verify_batch(items)
        dt = time.perf_counter() - t0
        assert bool(np.all(ok))
        best = min(best, dt)
    rate = BATCH / best

    print(
        json.dumps(
            {
                "metric": "verify_commit_sigs_per_sec",
                "value": round(rate, 1),
                "unit": "sigs/s",
                "vs_baseline": round(rate / cpu_rate, 2),
                "detail": {
                    "batch": BATCH,
                    "best_batch_ms": round(best * 1e3, 2),
                    "cpu_sigs_per_sec": round(cpu_rate, 1),
                    "platform": jax.devices()[0].platform,
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
